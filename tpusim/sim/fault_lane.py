"""The in-scan fault plane (ISSUE 10): fault schedules as sweep operands.

PR 2's fault injection splits the base trace host-side and replays the
segments between host-applied fault transitions — a shape-changing Python
loop that cannot vmap, so every fault what-if costs one full replay
(ROADMAP: "the last named config scalar" keeping robustness off the
one-compile sweep axis). This module moves the whole fault vocabulary
INSIDE the compiled scan:

  1. `compile_fault_plan` merges a fault schedule into the base event
     stream host-side: EV_NODE_FAIL / EV_NODE_RECOVER / EV_EVICT become
     ordinary scan steps at their trace positions, and fixed blocks of
     EV_RETRY slots are inserted at every position a queued retry could
     possibly become due (the backoff chains are a pure function of the
     schedule — attempt k of an eviction at e fires at e + Σ backoff(1..k)
     — so the slot positions are computable without knowing outcomes; a
     slot with nothing due is an inert skip). The merged stream plus the
     pre-drawn eviction tables are fixed-shape per-lane OPERANDS, so a
     B-lane disruption frontier vmaps onto ONE compiled scan.

  2. `FaultCarry` holds the retry queue as i32 carry arrays with the
     exact `queues.RetryQueue` semantics: capped exponential backoff,
     FIFO ties ((ready, seq) lexicographic pops), and a dead list
     (attempt > max_retries, or queue overflow at the static capacity —
     both terminal "max-retries-exceeded"). Because it is carry state it
     survives chunked scans and checkpoint round-trips bit-identically.

  3. Random eviction victims stay bit-identical to the host path's
     numpy PCG64 draw: `pick_eviction_victim` draws
     default_rng(seed + pos*K).integers(0, size) where size is the
     placed-pod count AT REPLAY TIME — unknowable host-side — but the
     draw for EVERY possible size is precomputable, so each EV_EVICT
     event ships a [P+1] draw row and the scan gathers draws[row, size].

Equivalence contract: under a deterministic config (no RandomScore /
gpu_sel random — the PRNG chain differs from the segmented path by
construction) and sufficient queue capacity, the in-scan lane reproduces
the segmented PR 2 path's placements, DisruptionMetrics, and final state
exactly; `Simulator.run_with_faults` dispatches here by default and
tests/test_fault_lane.py pins the equality per engine.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.constants import MAX_GPUS_PER_NODE, MILLI
from tpusim.sim.engine import (
    EV_CREATE,
    EV_EVICT,
    EV_NODE_FAIL,
    EV_NODE_RECOVER,
    EV_RETRY,
    EV_SKIP,
)

_INT_MAX = jnp.int32(jnp.iinfo(jnp.int32).max)
_VICTIM_MIX = 2654435761  # pick_eviction_victim's Knuth multiplier

# dctr layout (i32[7] disruption counters carried in-scan)
D_EVICTED = 0
D_RETRIES_ENQ = 1
D_RESCHEDULED = 2
D_FAILURES = 3
D_RECOVERIES = 4
D_FN_GPU_EVENTS = 5
D_DEAD = 6
NUM_DCTR = 7


class FaultOps(NamedTuple):
    """Per-lane fault operands of one fault-enabled replay. The first
    three ride the scan as xs beside (ev_kind, ev_pod); draws/params are
    gathered constants. Everything is data — two lanes with different
    schedules share one jaxpr as long as the padded shapes match."""

    pos: jnp.ndarray  # i32[E_m] base-trace position of each merged step
    arg: jnp.ndarray  # i32[E_m] node (fail/recover) | explicit pod
    #                   (evict, -1 = drawn) | flush round (retry slots)
    aux: jnp.ndarray  # i32[E_m] eviction draw-table row (-1 otherwise)
    draws: jnp.ndarray  # i32[n_rows(>=1), P+1] pre-drawn victim ranks
    params: jnp.ndarray  # i32[4]: backoff base, cap, max_retries, E
    gcnt: jnp.ndarray  # i32[N] global per-node GPU counts (broadcast in
    #                    sweeps; the dark-capacity clock needs the global
    #                    row even on the sharded engine)


class FaultPlan(NamedTuple):
    """Host-side compilation of one fault schedule (numpy arrays — the
    driver uploads/stacks them into FaultOps)."""

    kind: np.ndarray  # i32[E_m] merged stream kinds (0..6)
    idx: np.ndarray  # i32[E_m] base pod index (0 on non-base steps)
    pos: np.ndarray  # i32[E_m]
    arg: np.ndarray  # i32[E_m]
    aux: np.ndarray  # i32[E_m]
    draws: np.ndarray  # i32[n_rows, P+1]
    params: np.ndarray  # i32[4]
    capacity: int  # static retry-queue capacity R
    num_events: int  # base trace length E
    has_recover: bool  # static: arm the frag-delta capture


class FaultCarry(NamedTuple):
    """Retry queue + disruption bookkeeping as exact-dtype carry arrays
    (the queues.RetryQueue semantics; checkpoint/resume transparent like
    every other carry leaf). Invalid queue slots carry pod == -1 and
    ready == seq == INT_MAX so lexicographic pops never see them."""

    q_ready: jnp.ndarray  # i32[R]
    q_seq: jnp.ndarray  # i32[R]
    q_pod: jnp.ndarray  # i32[R]
    q_att: jnp.ndarray  # i32[R]
    q_era: jnp.ndarray  # i32[R] flush round the entry was pushed in (0 =
    #                     during the trace); round r pops only era < r
    seq: jnp.ndarray  # i32 next insertion sequence number
    attempts: jnp.ndarray  # i32[Pp] consecutive failed attempts so far
    evicted_at: jnp.ndarray  # i32[Pp] eviction position (-1 = not evicted)
    dead: jnp.ndarray  # bool[Pp] terminal max-retries-exceeded
    down_at: jnp.ndarray  # i32[N] failure position per node (-1 = up)
    dctr: jnp.ndarray  # i32[NUM_DCTR] disruption counters


class FaultY(NamedTuple):
    """Per-merged-event fault telemetry (scan ys): enough for the host
    to reconstruct every DisruptionMetrics list, the [Fault] log lines,
    creation ranks, and the true event count."""

    rpod: jnp.ndarray  # i32 popped retry pod (-1 = no pop this step)
    lat: jnp.ndarray  # i32 reschedule latency on retry success (-1 else)
    vpod: jnp.ndarray  # i32 EV_EVICT victim (-1 none)
    vnode: jnp.ndarray  # i32 the evict victim's node (-1 none)
    nvict: jnp.ndarray  # i32 pods evicted at this step (fail/evict)
    rec: jnp.ndarray  # i32 1 = recover applied this step
    fb: jnp.ndarray  # f32 cluster frag before a recover (frag flag only)
    fa: jnp.ndarray  # f32 cluster frag after a recover


def no_fault_y():
    z = jnp.int32(-1)
    return FaultY(z, z, z, z, jnp.int32(0), jnp.int32(0),
                  jnp.float32(0), jnp.float32(0))


# ---------------------------------------------------------------------------
# Host-side plan compilation
# ---------------------------------------------------------------------------


def resolve_capacity(fcfg, num_pods: int) -> int:
    """Static retry-queue capacity R: the explicit knob, else
    min(num_pods, 256) — enough that the host RetryQueue (unbounded)
    and the in-carry queue never diverge on realistic schedules; an
    overflowing eviction wave goes terminal instead of corrupting."""
    cap = int(getattr(fcfg, "queue_capacity", 0) or 0)
    if cap > 0:
        return cap
    return max(1, min(int(num_pods), 256))


def _backoffs(fcfg) -> List[int]:
    return [
        min(fcfg.backoff_base * (1 << max(k - 1, 0)), fcfg.backoff_cap)
        for k in range(1, max(fcfg.max_retries, 0) + 1)
    ]


def _victim_draw_row(seed: int, pos: int, num_pods: int) -> np.ndarray:
    """draws[size] = the host path's PCG64 pick for every possible
    placed-count `size` (pick_eviction_victim: a FRESH generator per
    (seed, pos), first draw). Row 0 is -1 (nothing placed)."""
    row = np.full(num_pods + 1, -1, np.int32)
    base = np.uint64(seed) + np.uint64(pos) * np.uint64(_VICTIM_MIX)
    for s in range(1, num_pods + 1):
        row[s] = int(np.random.default_rng(base).integers(0, s))
    return row


def compile_fault_plan(
    ev_kind: np.ndarray,
    ev_pod: np.ndarray,
    faults: Sequence,
    fcfg,
    num_nodes: int,
    num_pods: int,
    capacity: int = 0,
) -> FaultPlan:
    """Merge a fault schedule into the base stream (module docstring).

    The merged order reproduces the segmented host loop exactly: base
    events run to each boundary position, faults clamped to that
    position fire first (schedule order), then one block of EV_RETRY
    slots pops the retries due there (FIFO (ready, seq) order); after
    the trace and fault stream drain, max_retries flush rounds pop the
    queue regardless of backoff, era-gated so each round only sees
    entries pushed before it — the host loop's thresh=inf semantics."""
    from tpusim.sim.faults import validate_fault_schedule

    ev_kind = np.asarray(ev_kind, np.int32)
    ev_pod = np.asarray(ev_pod, np.int32)
    e = int(ev_kind.shape[0])
    faults = sorted(faults, key=lambda f: f.pos)  # stable like the host
    validate_fault_schedule(faults, num_nodes, num_pods)
    if fcfg.backoff_cap > (1 << 20):
        raise ValueError(
            f"backoff_cap {fcfg.backoff_cap} > 2^20: the in-scan backoff "
            "is computed in f32-exact integer range"
        )
    cap_r = capacity or resolve_capacity(fcfg, num_pods)
    bos = _backoffs(fcfg)

    # potential retry boundaries: attempt k of an eviction at source e0
    # fires at e0 + Σ backoff(1..k); chains past the trace end land in
    # the flush rounds. Slot multiplicity per position: 1 per reaching
    # EVICT chain, capacity per reaching FAIL chain (victim counts are
    # outcome-dependent), capped at capacity (<= queue occupancy).
    slot_need: dict = {}
    any_evict_src = False
    for f in faults:
        if f.kind not in (EV_NODE_FAIL, EV_EVICT):
            continue
        any_evict_src = True
        mult = cap_r if f.kind == EV_NODE_FAIL else 1
        t = min(f.pos, e)
        for b in bos:
            t = t + b
            if t >= e:
                break
            slot_need[t] = min(cap_r, slot_need.get(t, 0) + mult)

    boundaries = sorted(
        set(min(f.pos, e) for f in faults) | set(slot_need)
    )

    kinds: List[int] = []
    idxs: List[int] = []
    poss: List[int] = []
    args: List[int] = []
    auxs: List[int] = []
    draw_rows: List[np.ndarray] = []

    def emit(kind, idx=0, pos=0, arg=0, aux=-1):
        kinds.append(kind)
        idxs.append(idx)
        poss.append(pos)
        args.append(arg)
        auxs.append(aux)

    fi = 0
    cursor = 0
    for p in boundaries:
        p = min(p, e)
        # base events up to the boundary
        for i in range(cursor, p):
            emit(int(ev_kind[i]), int(ev_pod[i]), pos=i)
        cursor = max(cursor, p)
        # faults clamped to this boundary, in schedule order
        while fi < len(faults) and min(faults[fi].pos, e) <= p:
            f = faults[fi]
            fi += 1
            if f.kind == EV_EVICT:
                row = -1
                if f.pod < 0:
                    row = len(draw_rows)
                    draw_rows.append(
                        _victim_draw_row(fcfg.seed, p, num_pods)
                    )
                emit(EV_EVICT, pos=p, arg=int(f.pod), aux=row)
            else:
                emit(int(f.kind), pos=p, arg=int(f.node))
        # due-retry slots (normal mode: ready <= pos gate)
        for _ in range(slot_need.get(p, 0)):
            emit(EV_RETRY, pos=p, arg=0)
    # trace tail + faults clamped past the end
    for i in range(cursor, e):
        emit(int(ev_kind[i]), int(ev_pod[i]), pos=i)
    while fi < len(faults):
        f = faults[fi]
        fi += 1
        if f.kind == EV_EVICT:
            row = -1
            if f.pod < 0:
                row = len(draw_rows)
                draw_rows.append(_victim_draw_row(fcfg.seed, e, num_pods))
            emit(EV_EVICT, pos=e, arg=int(f.pod), aux=row)
        else:
            emit(int(f.kind), pos=e, arg=int(f.node))
    # flush rounds: pop everything queued before the round, regardless
    # of backoff (the host loop's end-of-trace thresh=inf drain)
    if any_evict_src:
        for r in range(1, max(fcfg.max_retries, 1) + 1):
            for _ in range(cap_r):
                emit(EV_RETRY, pos=e, arg=r)

    draws = (
        np.stack(draw_rows)
        if draw_rows else np.full((1, num_pods + 1), -1, np.int32)
    )
    has_rec = any(f.kind == EV_NODE_RECOVER for f in faults)
    return FaultPlan(
        kind=np.asarray(kinds, np.int32),
        idx=np.asarray(idxs, np.int32),
        pos=np.asarray(poss, np.int32),
        arg=np.asarray(args, np.int32),
        aux=np.asarray(auxs, np.int32),
        draws=draws.astype(np.int32),
        params=np.asarray(
            [fcfg.backoff_base, fcfg.backoff_cap, fcfg.max_retries, e],
            np.int32,
        ),
        capacity=cap_r,
        num_events=e,
        has_recover=has_rec,
    )


def pad_fault_plans(
    plans: Sequence[FaultPlan], bucket: int = 256, min_stream: int = 0,
    min_rows: int = 0,
) -> Tuple[np.ndarray, ...]:
    """Pad B per-lane plans to common shapes for the vmapped chaos sweep:
    streams to a shared bucketed length (EV_SKIP padding — inert steps),
    draw tables to a shared row count. Returns stacked
    (kind, idx, pos, arg, aux, draws, params) arrays plus the unified
    static (capacity, has_recover). Capacities must already agree (the
    driver resolves one capacity for the whole sweep)."""
    caps = {p.capacity for p in plans}
    if len(caps) != 1:
        raise ValueError(
            f"chaos-sweep lanes must share one queue capacity, got {caps}"
        )
    # power-of-two shape classes above the base bucket: merged-stream
    # lengths and draw-table rows vary with every schedule, and a shape
    # change IS a recompile — rounding up to the next power of two keeps
    # consecutive waves of similar-size schedules on one executable
    # (padding is inert EV_SKIP steps / unused draw rows). min_stream /
    # min_rows are the caller's sticky high-water floors (the svc
    # worker's min_pods/min_events discipline): a later smaller wave on
    # the same Simulator must not land on a smaller shape and recompile.
    em = max(
        max(int(p.kind.shape[0]) for p in plans), int(min_stream)
    )
    em = bucket if em <= bucket else (1 << (em - 1).bit_length())
    rows = max(
        max(int(p.draws.shape[0]) for p in plans), int(min_rows)
    )
    # 64-row floor: random-evict counts jitter wave to wave (they follow
    # the schedule's geometric draws), and a [64, P+1] i32 table is
    # noise-sized — a generous floor keeps typical waves in ONE class
    rows = max(64, 1 << max(rows - 1, 0).bit_length())
    pp = max(int(p.draws.shape[1]) for p in plans)

    def pad_stream(a, fill):
        out = np.full(em, fill, np.int32)
        out[: a.shape[0]] = a
        return out

    kinds, idxs, poss, args, auxs, draws, params = [], [], [], [], [], [], []
    for p in plans:
        kinds.append(pad_stream(p.kind, EV_SKIP))
        idxs.append(pad_stream(p.idx, 0))
        poss.append(pad_stream(p.pos, p.num_events))
        args.append(pad_stream(p.arg, 0))
        auxs.append(pad_stream(p.aux, -1))
        d = np.full((rows, pp), -1, np.int32)
        d[: p.draws.shape[0], : p.draws.shape[1]] = p.draws
        draws.append(d)
        params.append(p.params)
    return (
        np.stack(kinds), np.stack(idxs), np.stack(poss), np.stack(args),
        np.stack(auxs), np.stack(draws), np.stack(params),
        plans[0].capacity, any(p.has_recover for p in plans),
    )


# ---------------------------------------------------------------------------
# In-scan carry + queue ops
# ---------------------------------------------------------------------------


def init_fault_carry(num_pods: int, num_nodes: int, capacity: int) -> FaultCarry:
    r = int(capacity)
    return FaultCarry(
        q_ready=jnp.full(r, _INT_MAX, jnp.int32),
        q_seq=jnp.full(r, _INT_MAX, jnp.int32),
        q_pod=jnp.full(r, -1, jnp.int32),
        q_att=jnp.zeros(r, jnp.int32),
        q_era=jnp.zeros(r, jnp.int32),
        seq=jnp.int32(0),
        attempts=jnp.zeros(num_pods, jnp.int32),
        evicted_at=jnp.full(num_pods, -1, jnp.int32),
        dead=jnp.zeros(num_pods, jnp.bool_),
        down_at=jnp.full(num_nodes, -1, jnp.int32),
        dctr=jnp.zeros(NUM_DCTR, jnp.int32),
    )


def pad_fault_carry(fc0: FaultCarry) -> FaultCarry:
    """Size the FaultCarry's pod axis to the engines' P+1 bookkeeping
    rows (the dummy row absorbing the pipelined commit's skip writes can
    never be evicted — placed[P] stays -1 — so the pad rows are inert).
    Shared by the table and shard_map fault builds; trim_fault_carry is
    the inverse the ReplayResult applies."""
    return fc0._replace(
        attempts=jnp.pad(fc0.attempts, (0, 1)),
        evicted_at=jnp.pad(fc0.evicted_at, (0, 1), constant_values=-1),
        dead=jnp.pad(fc0.dead, (0, 1)),
    )


def trim_fault_carry(fc: FaultCarry) -> FaultCarry:
    return fc._replace(
        attempts=fc.attempts[:-1],
        evicted_at=fc.evicted_at[:-1],
        dead=fc.dead[:-1],
    )


def backoff_of(att, base, cap):
    """min(base * 2^(att-1), cap) with traced operands, exact: the shift
    is clamped so base << s stays in i32 (and once it exceeds cap — which
    compile_fault_plan bounds at 2^20 — the min snaps to cap anyway)."""
    s = jnp.maximum(att - 1, 0)
    lb = jnp.floor(
        jnp.log2(jnp.maximum(base, 1).astype(jnp.float32))
    ).astype(jnp.int32)
    s = jnp.minimum(s, jnp.maximum(29 - lb, 0))
    return jnp.minimum(base << s, cap)


def pop_retry(fc: FaultCarry, is_slot, pos, flush_round):
    """One EV_RETRY slot's pop: the earliest (ready, seq) entry that is
    due (normal slots: ready <= pos) or era-eligible (flush round r:
    pushed before round r). Returns (fc', has, pod). Inert when nothing
    qualifies — extra slots are skips by construction."""
    eligible = (fc.q_pod >= 0) & jnp.where(
        flush_round > 0, fc.q_era < flush_round, fc.q_ready <= pos
    )
    any_e = eligible.any()
    rmin = jnp.min(jnp.where(eligible, fc.q_ready, _INT_MAX))
    cand = eligible & (fc.q_ready == rmin)
    slot = jnp.argmin(jnp.where(cand, fc.q_seq, _INT_MAX)).astype(jnp.int32)
    has = is_slot & any_e
    pod = jnp.where(has, fc.q_pod[slot], 0).astype(jnp.int32)
    fc = fc._replace(
        q_pod=fc.q_pod.at[slot].set(jnp.where(has, -1, fc.q_pod[slot])),
        q_ready=fc.q_ready.at[slot].set(
            jnp.where(has, _INT_MAX, fc.q_ready[slot])
        ),
        q_seq=fc.q_seq.at[slot].set(
            jnp.where(has, _INT_MAX, fc.q_seq[slot])
        ),
    )
    return fc, has, pod


def _queue_push_mask(fc: FaultCarry, vm, att, pos, era, params):
    """Push every pod in mask `vm` (ascending pod order = FIFO seq
    order, the host's flatnonzero discipline) for attempt vector `att`.
    Entries with att > max_retries go dead instead (RetryQueue.push ->
    None); overflow past the static capacity also goes dead (the
    documented divergence from the unbounded host heap). Returns
    (fc', pushed bool[Pp], dead_now bool[Pp])."""
    r = fc.q_pod.shape[0]
    base, cap, maxr = params[0], params[1], params[2]
    dead_now = vm & (att > maxr)
    want = vm & ~dead_now
    rank = jnp.cumsum(want.astype(jnp.int32)) - 1
    free = fc.q_pod < 0
    nfree = free.sum()
    free_order = jnp.argsort(~free)  # free slots first, index order
    fits = want & (rank < nfree)
    tgt = jnp.where(fits, free_order[jnp.clip(rank, 0, r - 1)], r)
    pods_iota = jnp.arange(vm.shape[0], dtype=jnp.int32)
    ready = pos + backoff_of(att, base, cap)
    fc = fc._replace(
        q_pod=fc.q_pod.at[tgt].set(pods_iota, mode="drop"),
        q_att=fc.q_att.at[tgt].set(att, mode="drop"),
        q_ready=fc.q_ready.at[tgt].set(ready, mode="drop"),
        q_seq=fc.q_seq.at[tgt].set(fc.seq + rank, mode="drop"),
        q_era=fc.q_era.at[tgt].set(
            jnp.broadcast_to(era, pods_iota.shape).astype(jnp.int32),
            mode="drop",
        ),
        seq=fc.seq + fits.sum(),
    )
    return fc, fits, dead_now | (want & ~fits)


def _evict_into_queue(fc: FaultCarry, vm, pos, era, params):
    """evict_bookkeep for a victim mask: attempts += 1, eviction clock
    stamped, push-or-dead, disruption counters. Returns
    (fc', newly_dead bool[Pp])."""
    att = jnp.where(vm, fc.attempts + 1, 0)
    fc, pushed, dead_now = _queue_push_mask(fc, vm, att, pos, era, params)
    nd = vm & dead_now
    fc = fc._replace(
        attempts=jnp.where(vm, att, fc.attempts),
        evicted_at=jnp.where(vm, pos, fc.evicted_at),
        dead=fc.dead | nd,
        dctr=fc.dctr.at[D_EVICTED].add(vm.sum().astype(jnp.int32))
        .at[D_RETRIES_ENQ].add(pushed.sum().astype(jnp.int32))
        .at[D_DEAD].add(nd.sum().astype(jnp.int32)),
    )
    return fc, nd


# ---------------------------------------------------------------------------
# Masked fault-step application (shared by all engines)
# ---------------------------------------------------------------------------


def _frag_scalar(state, tp):
    from tpusim.ops.frag import cluster_frag_amounts, frag_sum_except_q3

    return frag_sum_except_q3(cluster_frag_amounts(state, tp).sum(0))


def _fault_decisions(placed, fc: FaultCarry, kind, arg, aux, ops: FaultOps):
    """The decision half of one fault step, shared by the in-line apply
    (apply_fault_step) and the pipelined plan (plan_fault_step): which
    transition fires and on what — (do_fail, do_rec, do_evict, node,
    victim, vnode). Reads only committed bookkeeping; writes nothing."""
    is_fail = kind == EV_NODE_FAIL
    is_rec = kind == EV_NODE_RECOVER
    is_evict = kind == EV_EVICT
    node = jnp.clip(arg, 0, fc.down_at.shape[0] - 1)
    node_down = fc.down_at[node] >= 0
    do_fail = is_fail & ~node_down
    do_rec = is_rec & node_down

    # ---- EV_EVICT victim selection (host pick_eviction_victim, exact:
    # the PCG64 draw per placed-count is pre-tabulated in ops.draws)
    placed_ok = placed >= 0
    size = placed_ok.sum().astype(jnp.int32)
    row = jnp.clip(aux, 0, ops.draws.shape[0] - 1)
    j = ops.draws[row, jnp.clip(size, 0, ops.draws.shape[1] - 1)]
    ranks = jnp.cumsum(placed_ok.astype(jnp.int32)) - 1
    vsel = placed_ok & (ranks == j)
    drawn = jnp.argmax(vsel).astype(jnp.int32)
    use_explicit = is_evict & (arg >= 0)
    exp_c = jnp.clip(arg, 0, placed.shape[0] - 1)
    victim = jnp.where(use_explicit, exp_c, drawn)
    found = jnp.where(
        use_explicit, placed_ok[exp_c], (aux >= 0) & (j >= 0)
    )
    do_evict = is_evict & found
    vnode = jnp.where(do_evict, placed[victim], -1)
    return do_fail, do_rec, do_evict, node, victim, vnode


def _fault_bookkeep(fc: FaultCarry, placed, node, victim, do_fail, do_rec,
                    do_evict, pos, ops: FaultOps):
    """The FaultCarry half of one fault step (victim requeue, down clock,
    disruption counters) — shared by apply_fault_step and
    plan_fault_step so the queue trajectory cannot depend on whether the
    state writes were in-line or deferred. `placed` must be the
    PRE-clearing bookkeeping (vm derives from it). Returns
    (fc', vm victim mask, newly_dead mask)."""
    params = ops.params
    # node-fail evicts every pod on the node, evict exactly one; both
    # requeue through the carry queue in ascending pod order (the host's
    # flatnonzero discipline)
    vm = (do_fail & (placed == node)) | (
        do_evict & (jnp.arange(placed.shape[0]) == victim)
    )
    fc, newly_dead = _evict_into_queue(fc, vm, pos, jnp.int32(0), params)

    # ---- down clock + recover accounting
    fc = fc._replace(
        down_at=fc.down_at.at[node].set(
            jnp.where(do_fail, pos,
                      jnp.where(do_rec, -1, fc.down_at[node]))
        ),
        dctr=fc.dctr.at[D_FAILURES].add(do_fail.astype(jnp.int32))
        .at[D_RECOVERIES].add(do_rec.astype(jnp.int32))
        .at[D_FN_GPU_EVENTS].add(
            jnp.where(
                do_rec,
                ops.gcnt[node] * (pos - fc.down_at[node]),
                0,
            )
        ),
    )
    return fc, vm, newly_dead


def apply_fault_step(
    state,
    placed,
    masks,
    failed,
    fc: FaultCarry,
    specs,
    kind,
    arg,
    aux,
    pos,
    ops: FaultOps,
    tp,
    node_ids,
    frag_delta: bool,
):
    """Apply one EV_NODE_FAIL / EV_NODE_RECOVER / EV_EVICT step as masked
    whole-array updates (at most one kind fires; non-fault steps are
    exact no-ops). `state` may be a LOCAL node shard: `node_ids` carries
    each local row's global id (arange(N) on one device), and the
    replicated bookkeeping (placed/masks/failed/fc) updates identically
    on every shard. Returns (state, placed, masks, failed, fc, touched
    global node id (-1 none), FaultY minus the retry fields)."""
    do_fail, do_rec, do_evict, node, victim, vnode = _fault_decisions(
        placed, fc, kind, arg, aux, ops
    )

    # ---- frag-before capture (recover events; static flag)
    if frag_delta:
        fb = jax.lax.cond(
            do_rec, lambda: _frag_scalar(state, tp),
            lambda: jnp.float32(0),
        )
    else:
        fb = jnp.float32(0)

    # ---- node row reset (fail -> DOWN sentinel, recover -> empty):
    # the faults._reset_node encoding as a masked row op
    do_reset = do_fail | do_rec
    rowm = (node_ids == node) & do_reset
    gpu_full = (
        jnp.arange(MAX_GPUS_PER_NODE, dtype=jnp.int32)[None, :]
        < state.gpu_cnt[:, None]
    ).astype(jnp.int32) * MILLI
    new_mem = jnp.where(do_fail, jnp.full_like(state.mem_cap, -1),
                        state.mem_cap)
    state = state._replace(
        cpu_left=jnp.where(rowm, state.cpu_cap, state.cpu_left),
        mem_left=jnp.where(rowm, new_mem, state.mem_left),
        gpu_left=jnp.where(rowm[:, None], gpu_full, state.gpu_left),
        aff_cnt=jnp.where(rowm[:, None], 0, state.aff_cnt),
    )

    # ---- EV_EVICT resource return (deschedule.evict semantics) at the
    # victim's node, owner-masked via node_ids
    vpod_spec = jax.tree.map(lambda a: a[victim], specs)
    from tpusim.policies.clustering import pod_affinity_class

    cls = pod_affinity_class(vpod_spec)
    vrow = (node_ids == vnode) & do_evict
    colm = (
        jnp.arange(state.aff_cnt.shape[1], dtype=jnp.int32)
        == jnp.maximum(cls, 0)
    ) & (cls >= 0)
    state = state._replace(
        cpu_left=state.cpu_left + jnp.where(vrow, vpod_spec.cpu, 0),
        mem_left=state.mem_left + jnp.where(vrow, vpod_spec.mem, 0),
        gpu_left=state.gpu_left + jnp.where(
            vrow[:, None],
            masks[victim].astype(jnp.int32) * vpod_spec.gpu_milli,
            0,
        ),
        aff_cnt=state.aff_cnt - jnp.where(
            vrow[:, None] & colm[None, :], 1, 0
        ),
    )

    if frag_delta:
        fa = jax.lax.cond(
            do_rec, lambda s=state: _frag_scalar(s, tp),
            lambda: jnp.float32(0),
        )
    else:
        fa = jnp.float32(0)

    # ---- victim bookkeeping (shared _fault_bookkeep: requeue through
    # the carry queue in ascending pod order, down clock, counters)
    fc, vm, newly_dead = _fault_bookkeep(
        fc, placed, node, victim, do_fail, do_rec, do_evict, pos, ops
    )
    placed = jnp.where(vm, -1, placed)
    masks = jnp.where(vm[:, None], False, masks)
    # a pod out of retries AT EVICTION marks ever-failed explicitly (the
    # host's evict_bookkeep; retry failures mark it via the create path)
    failed = failed | newly_dead

    touched = jnp.where(
        do_reset, node, jnp.where(do_evict, vnode, -1)
    ).astype(jnp.int32)
    y = FaultY(
        rpod=jnp.int32(-1),
        lat=jnp.int32(-1),
        vpod=jnp.where(do_evict, victim, -1).astype(jnp.int32),
        vnode=jnp.where(do_evict, vnode, -1).astype(jnp.int32),
        nvict=vm.sum().astype(jnp.int32),
        rec=do_rec.astype(jnp.int32),
        fb=fb,
        fa=fa,
    )
    return state, placed, masks, failed, fc, touched, y


class FaultPending(NamedTuple):
    """One fault step's deferred write set — the fault half of the
    shard engine's software pipeline (ISSUE 11): the DECISION (victim
    draw, row targets, queue bookkeeping) happens in-line at the event —
    it only reads committed bookkeeping — while every state/placed/
    masks/failed WRITE is encoded here and applied at the top of the
    NEXT scan iteration by apply_fault_pending, keeping the body
    strictly write-then-read. All node ids are GLOBAL; fields are inert
    (-1 / zeros) on non-fault steps."""

    reset_node: jnp.ndarray  # i32 node to reset (-1 none)
    reset_fail: jnp.ndarray  # bool: True -> DOWN sentinel, False -> empty
    evict_node: jnp.ndarray  # i32 node returning an evicted pod's
    #                          resources (-1 none)
    evict_cpu: jnp.ndarray  # i32
    evict_mem: jnp.ndarray  # i32
    evict_milli: jnp.ndarray  # i32 per-GPU milli of the victim
    evict_mask: jnp.ndarray  # bool[8] the victim's recorded device mask
    evict_cls: jnp.ndarray  # i32 affinity class (-1 none)
    clear: jnp.ndarray  # bool[Pp] rows cleared in placed/masks
    dead_or: jnp.ndarray  # bool[Pp] OR-ed into ever-failed


def no_fault_pending(num_rows: int) -> FaultPending:
    z = jnp.int32(0)
    return FaultPending(
        reset_node=jnp.int32(-1), reset_fail=jnp.bool_(False),
        evict_node=jnp.int32(-1), evict_cpu=z, evict_mem=z, evict_milli=z,
        evict_mask=jnp.zeros(MAX_GPUS_PER_NODE, jnp.bool_),
        evict_cls=jnp.int32(-1),
        clear=jnp.zeros(num_rows, jnp.bool_),
        dead_or=jnp.zeros(num_rows, jnp.bool_),
    )


def plan_fault_step(
    placed,
    masks,
    fc: FaultCarry,
    specs,
    kind,
    arg,
    aux,
    pos,
    ops: FaultOps,
):
    """apply_fault_step with the state/bookkeeping WRITES deferred: runs
    the same decision + queue bookkeeping (shared _fault_decisions /
    _fault_bookkeep, so the trajectory is bit-identical by construction)
    but returns the write set as a FaultPending instead of mutating the
    buffers. The recover frag-delta capture is unsupported here (the
    post-reset state is never materialized at the event) — the shard
    engine, the only pipelined-fault consumer, never captures it anyway
    (ENGINES.md Round 14). Returns (FaultPending, fc', touched global
    node id, FaultY minus the retry fields)."""
    do_fail, do_rec, do_evict, node, victim, vnode = _fault_decisions(
        placed, fc, kind, arg, aux, ops
    )
    vpod_spec = jax.tree.map(lambda a: a[victim], specs)
    from tpusim.policies.clustering import pod_affinity_class

    cls = pod_affinity_class(vpod_spec)
    fc, vm, newly_dead = _fault_bookkeep(
        fc, placed, node, victim, do_fail, do_rec, do_evict, pos, ops
    )
    do_reset = do_fail | do_rec
    fp = FaultPending(
        reset_node=jnp.where(do_reset, node, -1).astype(jnp.int32),
        reset_fail=do_fail,
        evict_node=jnp.where(do_evict, vnode, -1).astype(jnp.int32),
        evict_cpu=vpod_spec.cpu,
        evict_mem=vpod_spec.mem,
        evict_milli=vpod_spec.gpu_milli,
        evict_mask=masks[victim],
        evict_cls=cls,
        clear=vm,
        dead_or=newly_dead,
    )
    touched = jnp.where(
        do_reset, node, jnp.where(do_evict, vnode, -1)
    ).astype(jnp.int32)
    y = FaultY(
        rpod=jnp.int32(-1),
        lat=jnp.int32(-1),
        vpod=jnp.where(do_evict, victim, -1).astype(jnp.int32),
        vnode=jnp.where(do_evict, vnode, -1).astype(jnp.int32),
        nvict=vm.sum().astype(jnp.int32),
        rec=do_rec.astype(jnp.int32),
        fb=jnp.float32(0),
        fa=jnp.float32(0),
    )
    return fp, fc, touched, y


def apply_fault_pending(state, placed, masks, failed, fp: FaultPending,
                        offset, nloc: int):
    """Apply one FaultPending's deferred writes — strictly write-only on
    every touched buffer: the node-row effects land as one-row scatters
    with out-of-range-drop owner masking (`offset`/`nloc` select this
    shard's local window; 0/N on a gathered global view), the [Pp]
    bookkeeping as masked whole-row selects. The value reads touch only
    the never-written capacity leaves (cpu_cap/mem_cap/gpu_cnt), so the
    scatters alias in place under scan exactly like apply_commit's."""
    # ---- node row reset (fail -> DOWN sentinel, recover -> empty)
    lres = fp.reset_node - offset
    owns_r = (fp.reset_node >= 0) & (lres >= 0) & (lres < nloc)
    ri = jnp.clip(lres, 0, nloc - 1)
    tgt_r = jnp.where(owns_r, ri, nloc)  # nloc = out of range -> dropped
    gpu_full = (
        jnp.arange(MAX_GPUS_PER_NODE, dtype=jnp.int32) < state.gpu_cnt[ri]
    ).astype(jnp.int32) * MILLI
    state = state._replace(
        cpu_left=state.cpu_left.at[tgt_r].set(
            state.cpu_cap[ri], mode="drop"
        ),
        mem_left=state.mem_left.at[tgt_r].set(
            jnp.where(fp.reset_fail, jnp.int32(-1), state.mem_cap[ri]),
            mode="drop",
        ),
        gpu_left=state.gpu_left.at[tgt_r].set(gpu_full, mode="drop"),
        aff_cnt=state.aff_cnt.at[tgt_r].set(0, mode="drop"),
    )

    # ---- EV_EVICT resource return at the victim's node
    lev = fp.evict_node - offset
    owns_e = (fp.evict_node >= 0) & (lev >= 0) & (lev < nloc)
    ei = jnp.clip(lev, 0, nloc - 1)
    tgt_e = jnp.where(owns_e, ei, nloc)
    state = state._replace(
        cpu_left=state.cpu_left.at[tgt_e].add(fp.evict_cpu, mode="drop"),
        mem_left=state.mem_left.at[tgt_e].add(fp.evict_mem, mode="drop"),
        gpu_left=state.gpu_left.at[tgt_e].add(
            fp.evict_mask.astype(jnp.int32) * fp.evict_milli, mode="drop"
        ),
        aff_cnt=state.aff_cnt.at[
            tgt_e, jnp.maximum(fp.evict_cls, 0)
        ].add(jnp.where(fp.evict_cls >= 0, -1, 0), mode="drop"),
    )

    placed = jnp.where(fp.clear, -1, placed)
    masks = jnp.where(fp.clear[:, None], False, masks)
    failed = failed | fp.dead_or
    return state, placed, masks, failed


def commit_retry(fc: FaultCarry, has, pod, node, pos, era, params):
    """Post-create bookkeeping of one popped retry: success resets the
    consecutive-failure budget and records the reschedule latency;
    failure burns an attempt and re-enqueues (or goes dead). Returns
    (fc', lat i32 — the latency on success, -1 otherwise, dead_mask)."""
    success = has & (node >= 0)
    failn = has & (node < 0)
    v = jnp.clip(pod, 0, fc.attempts.shape[0] - 1)
    lat = jnp.where(success, pos - fc.evicted_at[v], -1).astype(jnp.int32)
    att_v = fc.attempts[v] + 1
    vm = failn & (jnp.arange(fc.attempts.shape[0]) == v)
    att_vec = jnp.where(vm, att_v, 0)
    fc, pushed, dead_now = _queue_push_mask(
        fc, vm, att_vec, pos, era, params
    )
    nd = vm & dead_now
    fc = fc._replace(
        attempts=jnp.where(
            vm, att_v,
            jnp.where(
                success & (jnp.arange(fc.attempts.shape[0]) == v),
                0, fc.attempts,
            ),
        ),
        evicted_at=jnp.where(
            success & (jnp.arange(fc.evicted_at.shape[0]) == v),
            -1, fc.evicted_at,
        ),
        dead=fc.dead | nd,
        dctr=fc.dctr.at[D_RESCHEDULED].add(success.astype(jnp.int32))
        .at[D_RETRIES_ENQ].add(pushed.sum().astype(jnp.int32))
        .at[D_DEAD].add(nd.sum().astype(jnp.int32)),
    )
    return fc, lat, nd


# ---------------------------------------------------------------------------
# Host-side result assembly
# ---------------------------------------------------------------------------


def assemble_disruption(plan: FaultPlan, ys: FaultY, final_fc,
                        gpu_cnt: np.ndarray, frag_delta: bool = True):
    """(DisruptionMetrics, dead_pods bool[Pp], retry attempt count) from
    the scan's fault telemetry — the exact numbers the segmented host
    loop accumulates, including the end-of-trace dark-capacity clock for
    nodes still down when the trace ends. frag_delta=False (the shard
    engine, whose replay cannot capture it) leaves
    post_recovery_frag_delta EMPTY instead of reporting the ys' zero
    placeholders as if they were measured deltas (ISSUE 11 satellite —
    the driver pairs this with a [Degrade] warning + obs counter)."""
    from tpusim.sim.metrics import DisruptionMetrics

    dctr = np.asarray(final_fc.dctr, np.int64)
    dm = DisruptionMetrics(
        node_failures=int(dctr[D_FAILURES]),
        node_recoveries=int(dctr[D_RECOVERIES]),
        evicted_pods=int(dctr[D_EVICTED]),
        retries_enqueued=int(dctr[D_RETRIES_ENQ]),
        rescheduled_pods=int(dctr[D_RESCHEDULED]),
        unscheduled_after_retries=int(dctr[D_DEAD]),
        failed_node_gpu_events=int(dctr[D_FN_GPU_EVENTS]),
    )
    down = np.asarray(final_fc.down_at, np.int64)
    gpu_cnt = np.asarray(gpu_cnt, np.int64)
    # the shard path's down_at spans the mesh-PADDED node axis while the
    # caller's gpu_cnt may be the real cluster's — pad rows can never be
    # down (fault targets are validated < num_nodes), so trimming to the
    # common prefix is exact
    n = min(down.shape[0], gpu_cnt.shape[0])
    down = down[:n]
    still = down >= 0
    dm.failed_node_gpu_events += int(
        (gpu_cnt[:n][still]
         * np.maximum(plan.num_events - down[still], 0)).sum()
    )
    lat = np.asarray(ys.lat, np.int64)
    dm.reschedule_latency_events = [int(x) for x in lat[lat >= 0]]
    if frag_delta:
        rec = np.asarray(ys.rec) > 0
        fb = np.asarray(ys.fb, np.float64)
        fa = np.asarray(ys.fa, np.float64)
        dm.post_recovery_frag_delta = [
            float(fa[i]) - float(fb[i]) for i in np.flatnonzero(rec)
        ]
    else:
        dm.post_recovery_frag_delta = []
    dead = np.asarray(final_fc.dead, bool)
    attempts_run = int((np.asarray(ys.rpod) >= 0).sum())
    return dm, dead, attempts_run


def fault_creation_rank(plan: FaultPlan, ys: FaultY,
                        num_pods: int) -> np.ndarray:
    """Per-pod creation rank over the merged stream: base creations and
    actual retry attempts rank in replay order, later attempts
    overwrite — the segmented path's state_box['rank'] bookkeeping."""
    kind = plan.kind
    rpod = np.asarray(ys.rpod)[: kind.shape[0]]
    cand = np.where(
        kind == EV_CREATE, plan.idx,
        np.where((kind == EV_RETRY) & (rpod >= 0), rpod, -1),
    )
    rank = np.full(num_pods, -1, np.int64)
    hits = np.flatnonzero(cand >= 0)
    for r, i in enumerate(hits):
        rank[cand[i]] = r
    return rank
