"""Fused whole-replay Pallas engine — one kernel for the entire event loop.

Round-3 profiling (ENGINES.md) showed the incremental table replay is
KERNEL-LAUNCH-BOUND: ~40 small fused kernels per event plus a ~15 us/iteration
`lax.scan` floor put a hard ceiling of ~16.6k events/s on one chip, while the
per-event math itself is only ~1-2 us of VPU work. This engine removes both
overheads at once: the WHOLE replay is a single `pl.pallas_call` with
`grid=(E,)` and sequential ("arbitrary") dimension semantics. The score /
feasibility / device tables, the cluster state, and the placement bookkeeping
all live in VMEM across grid steps (~6 MB total); one grid step = one event =
the same filter -> score-column refresh -> selectHost -> Reserve -> Bind cycle
the table engine runs (mirroring the reference's per-pod cycle,
vendor .../scheduler/scheduler.go:441 scheduleOne + the simon plugin set),
executed as straight-line VPU code with zero kernel launches per event.

Mosaic constraints shape the implementation (probed on the target chip):
scalars cannot be stored to VMEM and dynamic lane-dim slicing is not
lowerable, so every "pointer chase" is a masked vector op instead --
  row gather  score_tbl[t_id]      -> sum(where(sublane_iota == t_id, tbl, 0))
  col update  tbl[:, node] = col   -> where(lane_iota == node, col, tbl)
  scalar read placed[idx]          -> sum(where(lane_iota == idx, placed, 0))
Each masked rewrite touches the full [K, N] table (~0.7 us of i32 VPU work),
noise next to the launch overhead it replaces.

Exactness: the kernel computes the same integer scores from the same integer
state as the table engine; the only divergence channel is f32 reduction order
inside the FGD frag sums (floor(sigmoid(.)*100) can flip an integer score when
a sum lands exactly on a truncation boundary). Placements are asserted
identical to the table engine on the full openb trace in the TPU lane
(tests/test_tpu.py); the CPU lane pins interpreter-mode equality on
randomized small traces (tests/test_pallas_engine.py).

Scope: single-policy configurations (the reference's own experiment protocol
enables one Score plugin at weight 1000, SURVEY.md §5.6) whose policy has a
column kernel in PALLAS_COLUMNS, gpu_sel in {best, worst, policy self-select},
report_per_event=False. driver.run_events picks this engine automatically on
TPU backends and falls back to the table/sequential engines otherwise.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpusim.constants import MAX_GPUS_PER_NODE, MAX_NODE_SCORE
from tpusim.sim.engine import ReplayResult
from tpusim.sim.step import SELF_SELECT_POLICIES
from tpusim.sim.table_engine import PodTypes, reject_randomized
from tpusim.types import NodeState, PodSpec

_INT_MAX = np.int32(np.iinfo(np.int32).max)

_EV_FIELDS = 12  # packed per-event row size (see _pack_events)


def _iota(shape, dim):
    return jax.lax.broadcasted_iota(jnp.int32, shape, dim)


def _node_bit(gtyp):
    """GPU-model bit of a node's gpu_type id (-1 = no GPU -> no bit).
    ref: utils.go:957-1005 IsNodeAccessibleToPod."""
    return jnp.where(gtyp >= 0, jax.lax.shift_left(1, jnp.maximum(gtyp, 0)), 0)


def _sigmoid_score_f32(cur, new):
    """trunc(sigmoid((cur-new)/1000) * MaxNodeScore) — fgd_score.go:124."""
    s = jax.nn.sigmoid((cur - new) / 1000.0)
    return jnp.floor(s * MAX_NODE_SCORE).astype(jnp.int32)


def _cumsum8_lanes(u):
    """Inclusive prefix sum of a (1,8) lane vector (no cumsum in Mosaic)."""
    sub = _iota((8, 8), 0)
    lane = _iota((8, 8), 1)
    a = jnp.where(lane <= sub, u, 0)  # (8,8): row d = prefix of u
    return a.sum(axis=1, keepdims=True).T  # (1,8)


# ---------------------------------------------------------------------------
# Policy column kernels: score ONE node (scalars + (8,1) device vector)
# against every pod type at once. Signature:
#   col_fn(node: _NodeScalars, types: _TypeCols, tp: _TpRows)
#     -> (score_col i32[K,1], sdev_col i32[K,1])
# Registered per policy name; policies without an entry fall back to the
# table engine.
# ---------------------------------------------------------------------------


class _NodeScalars(NamedTuple):
    cpu: jnp.ndarray  # scalar i32 cpu_left
    mem: jnp.ndarray  # scalar i32 mem_left
    gcnt: jnp.ndarray  # scalar i32 gpu count
    gtyp: jnp.ndarray  # scalar i32 gpu model id (-1 none)
    g8: jnp.ndarray  # (8,1) i32 per-device milli left


class _TypeCols(NamedTuple):
    """Pod-type spec columns, share-group rows [0,Ks) then whole [Ks,K)."""

    cpu: jnp.ndarray  # (K,1) i32
    mem: jnp.ndarray  # (K,1) i32
    milli: jnp.ndarray  # (K,1) i32
    num: jnp.ndarray  # (K,1) i32
    mask: jnp.ndarray  # (K,1) i32
    ks: int  # static share-group size


class _TpRows(NamedTuple):
    """Typical-pod distribution as (1,T) rows (ref: frag.go:285-380)."""

    cpu: jnp.ndarray  # (1,T) i32
    milli: jnp.ndarray  # (1,T) i32
    numf: jnp.ndarray  # (1,T) f32
    mask: jnp.ndarray  # (1,T) i32
    freq: jnp.ndarray  # (1,T) f32


def _frag_terms(node: _NodeScalars, tp: _TpRows):
    """Shared frag ingredients for one node: the fit/fitcnt/fitsum
    decomposition of NodeGpuShareFragAmountScore (frag.go:148-203) that
    policies/fgd.py uses, here against (8,T)-shaped broadcasts."""
    gf = node.g8.astype(jnp.float32)  # (8,1)
    fit = (node.g8 >= tp.milli) & (tp.milli > 0)  # (8,T)
    fitf = fit.astype(jnp.float32)
    fitcnt = fitf.sum(axis=0, keepdims=True)  # (1,T)
    fitsum = jnp.where(fit, gf, 0.0).sum(axis=0, keepdims=True)  # (1,T)
    total = gf.sum()
    acc = (tp.mask == 0) | ((tp.mask & _node_bit(node.gtyp)) != 0)  # (1,T)
    gpu_pod = tp.milli > 0
    return fit, fitf, fitcnt, fitsum, total, acc, gpu_pod


def _fgd_column(node: _NodeScalars, types: _TypeCols, tp: _TpRows):
    """FGD score + Reserve-device column for one node across all pod types
    (ref: plugin/fgd_score.go:99-156; the same fit/fitsum decomposition as
    policies/fgd.py, vectorized over the type axis)."""
    ks = types.ks
    k = types.cpu.shape[0]
    kw = k - ks
    fit, fitf, fitcnt, fitsum, total, acc, gpu_pod = _frag_terms(node, tp)
    isq3 = gpu_pod & acc & (fitcnt >= tp.numf) & (node.cpu >= tp.cpu)
    cur = (tp.freq * jnp.where(isq3, total - fitsum, total)).sum()
    gf = node.g8.astype(jnp.float32)  # (8,1)
    gT = node.g8.T  # (1,8)
    t = tp.cpu.shape[1]

    outs = []
    # --- share branch: best per-device hypothetical (fgd_score.go:111-134)
    if ks:
        p = types.milli[:ks]  # (Ks,1)
        p3 = p.astype(jnp.float32).reshape(ks, 1, 1)
        g3 = gf.reshape(1, 8, 1)
        m3i = tp.milli.reshape(1, 1, t)
        fitp = ((g3 - p3) >= m3i.astype(jnp.float32)) & (m3i > 0)  # (Ks,8,T)
        fit3 = fitf.reshape(1, 8, t)
        fitcnt_h = fitcnt.reshape(1, 1, t) - fit3 + fitp.astype(jnp.float32)
        fitsum_h = (
            fitsum.reshape(1, 1, t)
            - jnp.where(fit.reshape(1, 8, t), g3, 0.0)
            + jnp.where(fitp, g3 - p3, 0.0)
        )
        cpu_ok = (node.cpu - types.cpu[:ks]) >= tp.cpu  # (Ks,T)
        isq3_h = (
            gpu_pod.reshape(1, 1, t)
            & acc.reshape(1, 1, t)
            & (fitcnt_h >= tp.numf.reshape(1, 1, t))
            & cpu_ok.reshape(ks, 1, t)
        )
        total_h = total - p3  # (Ks,1,1)
        new = (
            tp.freq.reshape(1, 1, t)
            * jnp.where(isq3_h, total_h - fitsum_h, total_h)
        ).sum(axis=2)  # (Ks,8)
        fits = gT >= p  # (Ks,8)
        dev_scores = jnp.where(fits, _sigmoid_score_f32(cur, new), -1)
        best_score = jnp.max(dev_scores, axis=1, keepdims=True)  # (Ks,1)
        lane8 = _iota((ks, 8), 1)
        best_dev = jnp.min(
            jnp.where(dev_scores == best_score, lane8, 8), axis=1, keepdims=True
        )
        ok = best_score >= 0  # == fits.any(): fitting devices score >= 0
        outs.append((jnp.where(ok, best_score, 0), jnp.where(ok, best_dev, -1)))

    # --- whole/CPU branch: Sub hypothetical (fgd_score.go:137-148)
    if kw:
        wm = types.milli[ks:]  # (Kw,1)
        wn = types.num[ks:]
        wc = types.cpu[ks:]
        # select_devices_packed (resource.go:454-480): stable ascending
        # rank of each device by milli-left, ties by device index
        sub8 = _iota((8, 8), 0)  # d
        lane8b = _iota((8, 8), 1)  # e
        lt = (gT < node.g8) | ((gT == node.g8) & (lane8b < sub8))  # [d,e]
        rank8 = lt.astype(jnp.int32).sum(axis=1, keepdims=True)  # (8,1)
        fit_w = (gT >= wm) & (wm > 0)  # (Kw,8)
        # devices taken = fitting, with < num fitting devices ahead in order
        earlier = fit_w.reshape(kw, 1, 8) & (
            rank8.T.reshape(1, 1, 8) < rank8.reshape(1, 8, 1)
        )  # [k,d,e]
        cnt = earlier.astype(jnp.int32).sum(axis=2)  # (Kw,8)
        take = fit_w & (cnt < wn)
        g2 = jnp.where(wn > 0, gT - take * wm, gT)  # (Kw,8)
        g2f = g2.astype(jnp.float32)
        m3i = tp.milli.reshape(1, 1, t)
        fit2 = (g2.reshape(kw, 8, 1) >= m3i) & (m3i > 0)  # (Kw,8,T)
        fitcnt2 = fit2.astype(jnp.float32).sum(axis=1)  # (Kw,T)
        fitsum2 = jnp.where(fit2, g2f.reshape(kw, 8, 1), 0.0).sum(axis=1)
        total2 = g2f.sum(axis=1, keepdims=True)  # (Kw,1)
        isq3_2 = gpu_pod & acc & (fitcnt2 >= tp.numf) & ((node.cpu - wc) >= tp.cpu)
        new_w = (tp.freq * jnp.where(isq3_2, total2 - fitsum2, total2)).sum(
            axis=1, keepdims=True
        )
        outs.append(
            (_sigmoid_score_f32(cur, new_w), jnp.full((kw, 1), -1, jnp.int32))
        )

    if len(outs) == 2:
        return (
            jnp.concatenate([outs[0][0], outs[1][0]], axis=0),
            jnp.concatenate([outs[0][1], outs[1][1]], axis=0),
        )
    return outs[0]


PALLAS_COLUMNS = {"FGDScore": _fgd_column}

_SUPPORTED_GPU_SEL = {"best", "worst"} | SELF_SELECT_POLICIES


def supports(policies, gpu_sel: str, report: bool) -> bool:
    """Whether make_pallas_replay can run this configuration."""
    if report or len(policies) != 1:
        return False
    fn, _ = policies[0]
    if fn.policy_name not in PALLAS_COLUMNS:
        return False
    if gpu_sel not in _SUPPORTED_GPU_SEL:
        return False
    # a self-select gpuSelMethod must name the enabled policy (otherwise
    # there is no sdev source; the reference would fail plugin lookup too)
    if gpu_sel in SELF_SELECT_POLICIES and gpu_sel != fn.policy_name:
        return False
    return True


def _feas_column(node: _NodeScalars, types: _TypeCols):
    """Filter-phase feasibility for one node x all types (mirrors
    step.filter_nodes minus the per-event pinned-node mask)."""
    gT = node.g8.T  # (1,8)
    fit = (node.cpu >= types.cpu) & (node.mem >= types.mem)  # (K,1)
    units = jnp.where(types.milli > 0, gT // jnp.maximum(types.milli, 1), 0)
    can_alloc = units.sum(axis=1, keepdims=True) >= types.num
    acc = (types.mask == 0) | ((types.mask & _node_bit(node.gtyp)) != 0)
    gpu_ok = (node.gcnt > 0) & acc & can_alloc
    needs_gpu = (types.milli * types.num) > 0
    return (fit & (~needs_gpu | gpu_ok)).astype(jnp.int32)


def _pack_events(specs: PodSpec, type_id, ev_kind, ev_pod):
    """[_EV_FIELDS, E] i32 per-event rows: every pod scalar the kernel
    needs, pre-gathered host/XLA-side so the kernel only does masked lane
    extraction (Mosaic cannot dynamically index the pod axis)."""
    from tpusim.policies.clustering import pod_affinity_class

    pod = jax.tree.map(lambda a: a[ev_pod], specs)
    return jnp.stack(
        [
            ev_kind.astype(jnp.int32),
            ev_pod.astype(jnp.int32),
            type_id[ev_pod].astype(jnp.int32),
            pod.cpu,
            pod.mem,
            pod.gpu_milli,
            pod.gpu_num,
            pod.gpu_mask,
            pod.pinned,
            pod_affinity_class(pod),
            pod.is_gpu_share().astype(jnp.int32),
            pod.total_gpu_milli(),
        ]
    )


def _make_kernel(column_fn, ks, normalize, gpu_sel, weight):
    """The fused replay kernel for a static (column_fn, Ks, normalize,
    gpu_sel, weight) configuration. See module docstring for the masked-op
    calculus; every step mirrors a line of sim/step.py or table_engine.py."""
    self_select = gpu_sel in SELF_SELECT_POLICIES

    def kernel(
        ev_ref,  # [F, E] i32
        tcpu_ref, tmem_ref, tmilli_ref, tnum_ref, tmask_ref,  # [K,1] i32
        tpcpu_ref, tpmilli_ref, tpnumf_ref, tpmask_ref, tpfreq_ref,  # [1,T]
        gcnt_ref, gtyp_ref, rank_ref,  # [1,N] i32 (read-only)
        cpu0_ref, mem0_ref, gpu0_ref, aff0_ref,  # initial state
        score_ref, sdev_ref, feas_ref,  # [K,N] i32
        cpu_ref, mem_ref,  # [1,N] i32
        gpul_ref,  # [8,N] i32
        aff_ref,  # [9,N] i32
        placed_ref, maskb_ref, failed_ref,  # [1,P] i32
        evnode_ref, evdevb_ref,  # [1,E] i32
        dirty,  # SMEM (1,) i32
    ):
        i = pl.program_id(0)
        kdim, n = score_ref.shape
        e = evnode_ref.shape[1]
        p = placed_ref.shape[1]

        lane_n = _iota((1, n), 1)
        lane_e = _iota((1, e), 1)
        lane_p = _iota((1, p), 1)
        lane_kn = _iota((kdim, n), 1)
        sub_kn = _iota((kdim, n), 0)

        types = _TypeCols(
            tcpu_ref[:, :], tmem_ref[:, :], tmilli_ref[:, :],
            tnum_ref[:, :], tmask_ref[:, :], ks,
        )
        tp = _TpRows(
            tpcpu_ref[:, :], tpmilli_ref[:, :], tpnumf_ref[:, :],
            tpmask_ref[:, :], tpfreq_ref[:, :],
        )

        def node_scalars(d):
            seln = lane_n == d
            return _NodeScalars(
                cpu=jnp.sum(jnp.where(seln, cpu_ref[:, :], 0)),
                mem=jnp.sum(jnp.where(seln, mem_ref[:, :], 0)),
                gcnt=jnp.sum(jnp.where(seln, gcnt_ref[:, :], 0)),
                gtyp=jnp.sum(jnp.where(seln, gtyp_ref[:, :], 0)),
                g8=jnp.sum(
                    jnp.where(seln, gpul_ref[:, :], 0), axis=1, keepdims=True
                ),
            )

        def refresh_column(d):
            node = node_scalars(d)
            col_score, col_sdev = column_fn(node, types, tp)
            col_feas = _feas_column(node, types)
            hit = lane_kn == d
            score_ref[:, :] = jnp.where(hit, col_score, score_ref[:, :])
            sdev_ref[:, :] = jnp.where(hit, col_sdev, sdev_ref[:, :])
            feas_ref[:, :] = jnp.where(hit, col_feas, feas_ref[:, :])

        @pl.when(i == 0)
        def _():
            cpu_ref[:, :] = cpu0_ref[:, :]
            mem_ref[:, :] = mem0_ref[:, :]
            gpul_ref[:, :] = gpu0_ref[:, :]
            aff_ref[:, :] = aff0_ref[:, :]
            placed_ref[:, :] = jnp.full((1, p), -1, jnp.int32)
            maskb_ref[:, :] = jnp.zeros((1, p), jnp.int32)
            failed_ref[:, :] = jnp.zeros((1, p), jnp.int32)
            evnode_ref[:, :] = jnp.full((1, e), -1, jnp.int32)
            evdevb_ref[:, :] = jnp.zeros((1, e), jnp.int32)
            dirty[0] = 0

            # build the score/sdev/feas tables column by column from the
            # initial state — the table engine's init_tables, but through
            # the SAME column code path the per-event refresh uses
            def body(d, _):
                refresh_column(d)
                return 0

            jax.lax.fori_loop(0, n, body, 0)

        # refresh the one column whose node changed last event
        # (table_engine.py's per-event column refresh; at i == 0 the tables
        # were just built, so the refresh is subsumed by the init loop)
        @pl.when(i != 0)
        def _():
            refresh_column(dirty[0])

        # ---- this event's packed scalars (masked lane extraction)
        ev = ev_ref[:, :]

        def f(j):
            return jnp.sum(jnp.where(lane_e == i, ev[j : j + 1, :], 0))

        kind = f(0)
        idx = f(1)
        tid = f(2)
        pcpu, pmem, pmilli, pnum = f(3), f(4), f(5), f(6)
        ppin, pcls, pshare, ptgm = f(8), f(9), f(10), f(11)
        sel_p = lane_p == idx
        sel_e = lane_e == i
        sub8c = _iota((8, 1), 0)
        sub9c = _iota((9, 1), 0)

        # ---- creation: Filter -> Score row -> selectHost -> Reserve -> Bind
        @pl.when(kind == 0)
        def _():
            hit_t = sub_kn == tid
            raw = jnp.sum(
                jnp.where(hit_t, score_ref[:, :], 0), axis=0, keepdims=True
            )  # (1,N)
            feas_row = (
                jnp.sum(jnp.where(hit_t, feas_ref[:, :], 0), axis=0, keepdims=True)
                != 0
            )
            # nodeSelector pinning is a per-event mask, not a table column
            feasible = feas_row & ((ppin < 0) | (lane_n == ppin))
            if normalize in ("minmax", "pwr"):
                lo = jnp.min(jnp.where(feasible, raw, _INT_MAX))
                hi = jnp.max(jnp.where(feasible, raw, -_INT_MAX))
                rngv = hi - lo
                degen = 0 if normalize == "minmax" else MAX_NODE_SCORE
                scaled = jnp.where(
                    rngv == 0,
                    degen,
                    (raw - lo) * MAX_NODE_SCORE // jnp.maximum(rngv, 1),
                )
                raw = jnp.where(feasible, scaled, raw)
            total = weight * raw
            # selectHost: max weighted score, smallest tie-break rank wins
            best = jnp.max(jnp.where(feasible, total, -_INT_MAX))
            wkey = jnp.where(
                feasible & (total == best), -rank_ref[:, :], -_INT_MAX
            )
            m = jnp.max(wkey)
            ok = m != -_INT_MAX
            node = jnp.where(ok, jnp.min(jnp.where(wkey == m, lane_n, n)), 0)

            # Reserve: device pick on the winner (step.choose_devices)
            seln = lane_n == node
            g8w = jnp.sum(
                jnp.where(seln, gpul_ref[:, :], 0), axis=1, keepdims=True
            )  # (8,1)
            gT = g8w.T  # (1,8)
            lane8 = _iota((1, 8), 1)
            fits = gT >= pmilli
            any_fit = jnp.sum(fits.astype(jnp.int32)) > 0
            # allocate_share_best: min milli-left among fitting, first index
            bkey = jnp.where(fits, gT, _INT_MAX)
            bdev = jnp.min(jnp.where(bkey == jnp.min(bkey), lane8, 8))
            bdev = jnp.where(any_fit, bdev, -1)
            if gpu_sel == "worst":
                wkey8 = jnp.where(fits, gT, -_INT_MAX)
                wdev = jnp.min(jnp.where(wkey8 == jnp.max(wkey8), lane8, 8))
                share_dev = jnp.where(any_fit, wdev, -1)
            elif self_select:
                sdev = jnp.sum(jnp.where(hit_t & seln, sdev_ref[:, :], 0))
                share_dev = jnp.where(sdev >= 0, sdev, bdev)
            else:  # "best"
                share_dev = bdev
            share_bits = jnp.where(
                share_dev >= 0,
                jax.lax.shift_left(1, jnp.maximum(share_dev, 0)),
                0,
            )
            # allocate_two_pointer for whole/multi-GPU pods
            units = jnp.where(pmilli > 0, gT // jnp.maximum(pmilli, 1), 0)
            prev = _cumsum8_lanes(units) - units
            take_units = jnp.clip(pnum - prev, 0, units)
            whole_bits = jnp.sum(
                jnp.where(take_units > 0, jax.lax.shift_left(1, lane8), 0)
            )
            bits = jnp.where(
                ptgm > 0, jnp.where(pshare != 0, share_bits, whole_bits), 0
            )
            bits = jnp.where(ok, bits, 0)

            # Bind: masked scatter-commit (step.select_and_bind)
            okn = seln & ok
            cpu_ref[:, :] = jnp.where(okn, cpu_ref[:, :] - pcpu, cpu_ref[:, :])
            mem_ref[:, :] = jnp.where(okn, mem_ref[:, :] - pmem, mem_ref[:, :])
            mask8 = (jax.lax.shift_right_logical(bits, sub8c) & 1) != 0  # (8,1)
            gpul_ref[:, :] = jnp.where(
                okn & mask8, gpul_ref[:, :] - pmilli, gpul_ref[:, :]
            )
            aff_hit = okn & (sub9c == jnp.maximum(pcls, 0)) & (pcls >= 0)
            aff_ref[:, :] = jnp.where(aff_hit, aff_ref[:, :] + 1, aff_ref[:, :])

            placed_ref[:, :] = jnp.where(
                sel_p, jnp.where(ok, node, -1), placed_ref[:, :]
            )
            maskb_ref[:, :] = jnp.where(sel_p, bits, maskb_ref[:, :])
            failed_ref[:, :] = jnp.where(
                sel_p, jnp.where(ok, 0, 1), failed_ref[:, :]
            )
            evnode_ref[:, :] = jnp.where(
                sel_e, jnp.where(ok, node, -1), evnode_ref[:, :]
            )
            evdevb_ref[:, :] = jnp.where(sel_e, bits, evdevb_ref[:, :])
            dirty[0] = jnp.where(ok, node, 0)

        # ---- deletion: return resources to the recorded devices
        # (step.unschedule; simulator.go:334-357)
        @pl.when(kind == 1)
        def _():
            node = jnp.sum(jnp.where(sel_p, placed_ref[:, :], 0))
            bits = jnp.sum(jnp.where(sel_p, maskb_ref[:, :], 0))
            was = node >= 0
            nodee = jnp.maximum(node, 0)
            seln = (lane_n == nodee) & was
            cpu_ref[:, :] = jnp.where(seln, cpu_ref[:, :] + pcpu, cpu_ref[:, :])
            mem_ref[:, :] = jnp.where(seln, mem_ref[:, :] + pmem, mem_ref[:, :])
            mask8 = (jax.lax.shift_right_logical(bits, sub8c) & 1) != 0
            gpul_ref[:, :] = jnp.where(
                seln & mask8, gpul_ref[:, :] + pmilli, gpul_ref[:, :]
            )
            aff_hit = seln & (sub9c == jnp.maximum(pcls, 0)) & (pcls >= 0)
            aff_ref[:, :] = jnp.where(aff_hit, aff_ref[:, :] - 1, aff_ref[:, :])
            placed_ref[:, :] = jnp.where(sel_p, -1, placed_ref[:, :])
            maskb_ref[:, :] = jnp.where(sel_p, 0, maskb_ref[:, :])
            evnode_ref[:, :] = jnp.where(sel_e, node, evnode_ref[:, :])
            evdevb_ref[:, :] = jnp.where(sel_e, bits, evdevb_ref[:, :])
            dirty[0] = nodee

        # kind == 2 (EV_SKIP / padding): dirty, outputs unchanged

    return kernel


_PALLAS_REPLAY_CACHE = {}


def make_pallas_replay(
    policies, gpu_sel: str = "best", report: bool = False, interpret: bool = False
):
    """Build the fused single-kernel replayer. Same call signature as the
    table engine's replay (state, pods, types, ev_kind, ev_pod, tp, key,
    tiebreak_rank); raises for configurations supports() rejects. `key` is
    accepted but unused — every supported configuration is deterministic
    (reject_randomized guarantees it)."""
    reject_randomized(policies, gpu_sel)
    if not supports(policies, gpu_sel, report):
        raise ValueError(
            "pallas engine supports single-policy no-report configs with a "
            f"registered column kernel; got {[f.policy_name for f, _ in policies]}"
            f" / gpu_sel={gpu_sel} / report={report}"
        )
    cache_key = (tuple((fn, w) for fn, w in policies), gpu_sel, interpret)
    if cache_key in _PALLAS_REPLAY_CACHE:
        return _PALLAS_REPLAY_CACHE[cache_key]

    fn, weight = policies[0]
    column_fn = PALLAS_COLUMNS[fn.policy_name]
    normalize = fn.normalize
    weight = int(weight)

    @jax.jit
    def replay(
        state: NodeState,
        pods: PodSpec,
        types: PodTypes,
        ev_kind,
        ev_pod,
        tp,
        key,
        tiebreak_rank=None,
    ) -> ReplayResult:
        from tpusim.parallel.sharding import pad_nodes

        n0 = state.num_nodes
        if tiebreak_rank is None:
            tiebreak_rank = jnp.arange(n0, dtype=jnp.int32)
        state_p, rank_p = pad_nodes(state, tiebreak_rank, 128)
        n = state_p.num_nodes

        ks = int(types.share.cpu.shape[0])
        kw = int(types.whole.cpu.shape[0])
        kdim = ks + kw

        def col(field):
            return jnp.concatenate(
                [getattr(types.share, field), getattr(types.whole, field)]
            ).reshape(kdim, 1)

        tcols = [col(f) for f in ("cpu", "mem", "gpu_milli", "gpu_num", "gpu_mask")]
        t = int(tp.cpu.shape[0])
        tprows = [
            tp.cpu.reshape(1, t),
            tp.gpu_milli.reshape(1, t),
            tp.gpu_num.astype(jnp.float32).reshape(1, t),
            tp.gpu_mask.reshape(1, t),
            tp.freq.reshape(1, t),
        ]
        ev = _pack_events(pods, types.type_id, ev_kind, ev_pod)
        e = int(ev.shape[1])
        p = int(pods.cpu.shape[0])

        kernel = _make_kernel(column_fn, ks, normalize, gpu_sel, weight)
        out_shape = (
            jax.ShapeDtypeStruct((kdim, n), jnp.int32),  # score
            jax.ShapeDtypeStruct((kdim, n), jnp.int32),  # sdev
            jax.ShapeDtypeStruct((kdim, n), jnp.int32),  # feas
            jax.ShapeDtypeStruct((1, n), jnp.int32),  # cpu_left
            jax.ShapeDtypeStruct((1, n), jnp.int32),  # mem_left
            jax.ShapeDtypeStruct((8, n), jnp.int32),  # gpu_left (dev-major)
            jax.ShapeDtypeStruct((9, n), jnp.int32),  # aff_cnt (class-major)
            jax.ShapeDtypeStruct((1, p), jnp.int32),  # placed
            jax.ShapeDtypeStruct((1, p), jnp.int32),  # device mask bits
            jax.ShapeDtypeStruct((1, p), jnp.int32),  # failed
            jax.ShapeDtypeStruct((1, e), jnp.int32),  # event node
            jax.ShapeDtypeStruct((1, e), jnp.int32),  # event dev bits
        )
        (
            _score, _sdev, _feas, cpu_l, mem_l, gpul, aff,
            placed, maskb, failed, evnode, evdevb,
        ) = pl.pallas_call(
            kernel,
            grid=(e,),
            out_shape=out_shape,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 18,
            out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)] * 12),
            scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",),
            ),
            interpret=interpret,
        )(
            ev,
            *tcols,
            *tprows,
            state_p.gpu_cnt.reshape(1, n),
            state_p.gpu_type.reshape(1, n),
            rank_p.reshape(1, n),
            state_p.cpu_left.reshape(1, n),
            state_p.mem_left.reshape(1, n),
            state_p.gpu_left.T,
            state_p.aff_cnt.T,
        )

        bit8 = jnp.arange(MAX_GPUS_PER_NODE, dtype=jnp.int32)
        new_state = state._replace(
            cpu_left=cpu_l[0, :n0],
            mem_left=mem_l[0, :n0],
            gpu_left=gpul[:, :n0].T,
            aff_cnt=aff[:, :n0].T,
        )
        masks = ((maskb[0, :, None] >> bit8) & 1) != 0  # [P,8] bool
        devs = ((evdevb[0, :, None] >> bit8) & 1) != 0  # [E,8] bool
        return ReplayResult(
            new_state, placed[0], masks, failed[0] != 0, None, evnode[0], devs
        )

    _PALLAS_REPLAY_CACHE[cache_key] = replay
    return replay
