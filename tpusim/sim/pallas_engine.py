"""Fused whole-replay Pallas engine — one kernel for the entire event loop.

Round-3 profiling (ENGINES.md) showed the incremental table replay is
KERNEL-LAUNCH-BOUND: ~40 small fused kernels per event plus a ~15 us/iteration
`lax.scan` floor put a hard ceiling of ~16.6k events/s on one chip, while the
per-event math itself is only ~1-2 us of VPU work. This engine removes both
overheads at once: the WHOLE replay is a single `pl.pallas_call` with
`grid=(E,)` and sequential ("arbitrary") dimension semantics. The score /
feasibility / device tables, the cluster state, and the placement bookkeeping
all live in VMEM across grid steps (~6 MB total); one grid step = one event =
the same filter -> score-column refresh -> selectHost -> Reserve -> Bind cycle
the table engine runs (mirroring the reference's per-pod cycle,
vendor .../scheduler/scheduler.go:441 scheduleOne + the simon plugin set),
executed as straight-line VPU code with zero kernel launches per event.

Mosaic constraints shape the implementation (probed on the target chip):
scalars cannot be stored to VMEM and dynamic lane-dim slicing is not
lowerable — but dynamic slicing on LEADING and SUBLANE dims is. So the node
and event axes are chunked as (C, 128) and the tables as [K, C, 128]:
  row gather   score_tbl[t_id]     -> score[pl.ds(tid,1), :, :]   (free)
  col update   tbl[:, node] = col  -> rmw of tbl[:, pl.ds(c,1), :]
                                      masked on lane == node % 128
  scalar read  placed[idx]         -> sum(where(lane_iota == idx, placed, 0))
                                      (pod-axis arrays stay flat [1, P] —
                                      the masked full-row op is ~45 KB)
Each update touches one (.., 1, 128) chunk instead of a whole [K, N] table
(~12x less masked-write traffic than the round-4 v1 flat layout).

Exactness: the kernel computes the same integer scores from the same integer
state as the table engine; the only divergence channel is f32 reduction order
inside the FGD frag sums (floor(sigmoid(.)*100) can flip an integer score when
a sum lands exactly on a truncation boundary). Placements are asserted
identical to the table engine on the full openb trace in the TPU lane
(tests/test_tpu.py); the CPU lane pins interpreter-mode equality on
randomized small traces (tests/test_pallas_engine.py).

Scope: configurations where EVERY enabled Score plugin has a column kernel
in PALLAS_COLUMNS — FGD, BestFit, GpuPacking, GpuClustering, PWR, and
DotProduct (all 4 dim-extension methods) — with gpu_sel in {best, worst,
enabled self-select policy}. That covers the reference's full experiment
protocol: the single-plugin-at-weight-1000 rows (SURVEY.md §5.6) AND the
PWR+FGD weighted mixes (generate_run_scripts.py rows 08/11/12), whose
Σ wᵢ·normalizeᵢ(colᵢ) accumulation runs fused since round 5. Per-event
reporting configs run here too: the kernel replays metric-free and the
shared post-pass (tpusim.sim.metrics) reconstructs the report series from
the emitted (event_node, event_dev) telemetry. driver.run_events picks
this engine automatically on TPU backends and falls back to the
table/sequential engines otherwise.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpusim.constants import (
    CPU_FULL_W,
    CPU_IDLE_W,
    CPU_NCORES,
    GPU_FULL_W,
    GPU_IDLE_W,
    MAX_GPUS_PER_NODE,
    MAX_NODE_SCORE,
    MAX_SPEC_CPU,
    MAX_SPEC_GPU,
    MILLI,
)
from tpusim.sim.engine import ReplayResult
from tpusim.sim.step import SELF_SELECT_POLICIES
from tpusim.sim.table_engine import PodTypes, reject_randomized
from tpusim.types import NodeState, PodSpec

_INT_MAX = np.int32(np.iinfo(np.int32).max)

_EV_FIELDS = 12  # packed per-event row size (see _pack_events)

# Per-core VMEM budget the fused kernel's resident set must fit in. Real
# TPU cores carry ~16 MiB; the default leaves headroom for Mosaic's own
# scratch. Exceeding it used to surface as an opaque Mosaic allocation
# failure mid-compile (or a wedged device) — driver.run_events now probes
# fits_vmem() first and degrades to the blocked table engine instead
# (ISSUE 2 graceful degradation). Override with TPUSIM_PALLAS_VMEM_BYTES.
DEFAULT_VMEM_BUDGET = 14 * 2**20


def vmem_resident_bytes(
    n_nodes: int, k_types: int, num_pol: int, num_pods: int, num_events: int
) -> int:
    """Estimated VMEM-resident footprint of the fused kernel: the
    score/sdev/feas tables ([K, N] i32 per policy + 2), the node state
    (~14 i32 lanes per node), the packed event rows ([_EV_FIELDS, E] i32),
    and the pod-axis bookkeeping ([1, P] rows). The node axis is padded to
    a 128 multiple like make_pallas_replay does."""
    n = -(-n_nodes // 128) * 128
    tables = (num_pol + 2) * k_types * n * 4
    state = 14 * n * 4
    events = _EV_FIELDS * num_events * 4
    pods = 12 * num_pods * 4
    return tables + state + events + pods


def _compiler_params_cls():
    """pltpu compiler-params class across the 0.5.x rename; a clear error
    beats `None(...)` when a future jax drops both spellings."""
    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    if cls is None:
        raise RuntimeError(
            "jax.experimental.pallas.tpu exposes neither CompilerParams "
            "nor TPUCompilerParams; this jax version is unsupported by "
            "the fused pallas engine (use engine: table)"
        )
    return cls


def vmem_budget() -> int:
    """The per-core VMEM budget the residency probes test against:
    TPUSIM_PALLAS_VMEM_BYTES or DEFAULT_VMEM_BUDGET. A malformed value
    fails LOUDLY naming the variable (ISSUE 15 satellite, the shared
    tpusim.envutil helper): it used to fall back silently, which could
    re-open the degradation path — or un-gate a kernel that then dies
    with an opaque Mosaic allocation failure — without the operator
    ever learning their override was ignored."""
    from tpusim.envutil import int_env

    return int_env("TPUSIM_PALLAS_VMEM_BYTES", DEFAULT_VMEM_BUDGET,
                   minimum=1)


def fits_vmem(
    n_nodes: int, k_types: int, num_pol: int, num_pods: int, num_events: int
) -> bool:
    """Whether the fused kernel's FULLY-VMEM-RESIDENT set fits the
    budget — tier 1 of the driver's pre-dispatch residency probe
    (ENGINES.md spill list: the measured ceiling is N ≤ 4096 at K = 151
    on a 16 MiB core). Tier 2 is fits_hbm: the HBM-resident-table
    layout whose VMEM footprint drops to O(K·B + row scratch)."""
    return vmem_resident_bytes(
        n_nodes, k_types, num_pol, num_pods, num_events
    ) <= vmem_budget()


def vmem_resident_bytes_hbm(
    n_nodes: int, k_types: int, num_pol: int, num_pods: int,
    num_events: int, num_norm: int = 1,
) -> int:
    """Estimated VMEM-resident footprint of the HBM-residency kernel
    (ENGINES.md Round 19). The [K, N] score/sdev/feas tables and the
    mutable node state live in HBM (`TPUMemorySpace.ANY`); what stays
    VMEM-resident is

      blocked summaries   bt/br/bn [N/B, K] + brmin/brmax
                          [N/B, nn·K] + slo/shi — (3 + 2·nn)·K·4 bytes
                          per 128-node block (nn = max(num_norm, 1))
      tie-break rank      [N/B, 128] i32 (the drift rebuild reduces it)
      row scratch         the event type's double-buffered score rows +
                          feas row: (2·num_pol + 2)·N·4 bytes
      column scratch      the dirty node's double-buffered table column
                          chunks: (num_pol + 2)·K·2·128·4 bytes
      state/chunk scratch one retained state chunk + read-only chunk +
                          the winner's sdev chunk (~24 rows of 128 i32)
      events + pods       the packed event rows, per-event telemetry,
                          and pod bookkeeping — unchanged from the
                          VMEM-resident layout

    so the per-node cost falls from (num_pol + 2)·K·4 + ~56 bytes to
    (3 + 2·nn)·K/32 + (2·num_pol + 2 + 1)·4 bytes and the ceiling moves
    from N ≤ 4096 to ≥ 256k at K = 151 (see hbm_ceiling_nodes)."""
    n = -(-n_nodes // 128) * 128
    nc = n // 128
    nn = max(int(num_norm), 1)
    summaries = (3 + 2 * nn) * k_types * nc * 4 + 2 * nn * k_types * 4
    rank = n * 4
    rows = (2 * num_pol + 2) * n * 4
    cols = (num_pol + 2) * k_types * 2 * 128 * 4
    state_scratch = 24 * 128 * 4
    events = (_EV_FIELDS + 2) * num_events * 4
    pods = 12 * num_pods * 4
    return summaries + rank + rows + cols + state_scratch + events + pods


def fits_hbm(
    n_nodes: int, k_types: int, num_pol: int, num_pods: int,
    num_events: int, num_norm: int = 1,
) -> bool:
    """Tier 2 of the residency probe: whether the HBM-residency
    kernel's VMEM-resident set (vmem_resident_bytes_hbm) fits the
    budget. The tables themselves are HBM-bounded, so this is the only
    VMEM constraint left."""
    return vmem_resident_bytes_hbm(
        n_nodes, k_types, num_pol, num_pods, num_events, num_norm
    ) <= vmem_budget()


def select_residency(
    n_nodes: int, k_types: int, num_pol: int, num_pods: int,
    num_events: int, num_norm: int = 1,
):
    """The two-tier residency auto-select the driver dispatches on:
    'vmem' when the whole table set fits on-core (the original fused
    kernel — fastest, zero DMA), else 'hbm' when the HBM-resident
    layout's VMEM working set fits, else None (degrade to the blocked
    table engine — the [Degrade] path, now narrowed to genuinely
    VMEM-impossible shapes)."""
    if fits_vmem(n_nodes, k_types, num_pol, num_pods, num_events):
        return "vmem"
    if fits_hbm(n_nodes, k_types, num_pol, num_pods, num_events, num_norm):
        return "hbm"
    return None


def hbm_ceiling_nodes(
    k_types: int, num_pol: int, num_norm: int = 1, num_pods: int = 2048,
    num_events: int = 4096, budget: int = None,
) -> int:
    """Largest node count (128-multiple) whose HBM-residency VMEM
    working set fits the budget at this (K, num_pol, num_norm) shape and
    a reference workload size — the documented ceiling
    `bench_scale --pallas-ceiling` sweeps and the gate pins ≥ 256k at
    K = 151 (ENGINES.md Round 19 footprint math)."""
    if budget is None:
        budget = vmem_budget()

    def fits(blocks: int) -> bool:
        return vmem_resident_bytes_hbm(
            blocks * 128, k_types, num_pol, num_pods, num_events, num_norm
        ) <= budget

    lo, hi = 0, 1
    while fits(hi) and hi < 2 ** 24:
        lo, hi = hi, hi * 2
    while lo + 1 < hi:
        mid = (lo + hi) // 2
        if fits(mid):
            lo = mid
        else:
            hi = mid
    return lo * 128


def _iota(shape, dim):
    return jax.lax.broadcasted_iota(jnp.int32, shape, dim)


def _node_bit(gtyp):
    """GPU-model bit of a node's gpu_type id (-1 = no GPU -> no bit).
    ref: utils.go:957-1005 IsNodeAccessibleToPod."""
    return jnp.where(gtyp >= 0, jax.lax.shift_left(1, jnp.maximum(gtyp, 0)), 0)


def _sigmoid_score_f32(cur, new):
    """trunc(sigmoid((cur-new)/1000) * MaxNodeScore) — fgd_score.go:124."""
    s = jax.nn.sigmoid((cur - new) / 1000.0)
    return jnp.floor(s * MAX_NODE_SCORE).astype(jnp.int32)


def _cumsum8_lanes(u):
    """Inclusive prefix sum of a (1,8) lane vector (no cumsum in Mosaic)."""
    sub = _iota((8, 8), 0)
    lane = _iota((8, 8), 1)
    a = jnp.where(lane <= sub, u, 0)  # (8,8): row d = prefix of u
    return a.sum(axis=1, keepdims=True).T  # (1,8)


# ---------------------------------------------------------------------------
# Policy column kernels: score ONE node (scalars + (8,1) device vector)
# against every pod type at once. Signature:
#   col_fn(node: _NodeScalars, types: _TypeCols, tp: _TpRows)
#     -> (score_col i32[K,1], sdev_col i32[K,1])
# Registered per policy name; policies without an entry fall back to the
# table engine.
# ---------------------------------------------------------------------------


class _NodeScalars(NamedTuple):
    cpu: jnp.ndarray  # scalar i32 cpu_left
    mem: jnp.ndarray  # scalar i32 mem_left
    cap: jnp.ndarray  # scalar i32 cpu_cap
    gcnt: jnp.ndarray  # scalar i32 gpu count
    gtyp: jnp.ndarray  # scalar i32 gpu model id (-1 none)
    ctyp: jnp.ndarray  # scalar i32 cpu model id
    g8: jnp.ndarray  # (8,1) i32 per-device milli left
    aff9: jnp.ndarray  # (9,1) i32 pods per GPU-affinity class


class _EnergyRows(NamedTuple):
    """Energy model tables as (1,M) rows (ref: open-gpu-share/utils/
    const.go:48-121; tpusim.constants CPU_*/GPU_* arrays)."""

    gidle: jnp.ndarray  # (1,Mg) f32 idle watts per GPU model
    gfull: jnp.ndarray  # (1,Mg) f32 full watts per GPU model
    cidle: jnp.ndarray  # (1,Mc) f32 idle watts per CPU package
    cfull: jnp.ndarray  # (1,Mc) f32 full watts per CPU package
    ncores: jnp.ndarray  # (1,Mc) f32 physical cores per CPU package


class _TypeCols(NamedTuple):
    """Pod-type spec columns, share-group rows [0,Ks) then whole [Ks,K)."""

    cpu: jnp.ndarray  # (K,1) i32
    mem: jnp.ndarray  # (K,1) i32
    milli: jnp.ndarray  # (K,1) i32
    num: jnp.ndarray  # (K,1) i32
    mask: jnp.ndarray  # (K,1) i32
    ks: int  # static share-group size


class _TpRows(NamedTuple):
    """Typical-pod distribution as (1,T) rows (ref: frag.go:285-380)."""

    cpu: jnp.ndarray  # (1,T) i32
    milli: jnp.ndarray  # (1,T) i32
    numf: jnp.ndarray  # (1,T) f32
    mask: jnp.ndarray  # (1,T) i32
    freq: jnp.ndarray  # (1,T) f32


def _packed_take(node: _NodeScalars, milli, num):
    """select_devices_packed for (K,1) type columns on one node: fitting
    devices taken least-free-first, stable by index, until `num` are found
    (ref: resource.go:454-480). Returns (take (K,8) bool, ok (K,1) bool)."""
    gT = node.g8.T  # (1,8)
    kdim = milli.shape[0]
    sub8 = _iota((8, 8), 0)  # d
    lane8b = _iota((8, 8), 1)  # e
    lt = (gT < node.g8) | ((gT == node.g8) & (lane8b < sub8))  # [d,e]
    rank8 = lt.astype(jnp.int32).sum(axis=1, keepdims=True)  # (8,1)
    fit = (gT >= milli) & (milli > 0)  # (K,8)
    # taken = fitting, with < num fitting devices ahead in sorted order
    earlier = fit.reshape(kdim, 1, 8) & (
        rank8.T.reshape(1, 1, 8) < rank8.reshape(1, 8, 1)
    )  # [k,d,e]
    cnt = earlier.astype(jnp.int32).sum(axis=2)  # (K,8)
    take = fit & (cnt < num)
    ok = take.astype(jnp.int32).sum(axis=1, keepdims=True) >= num
    return take, ok


def _frag_terms(node: _NodeScalars, tp: _TpRows):
    """Shared frag ingredients for one node: the fit/fitcnt/fitsum
    decomposition of NodeGpuShareFragAmountScore (frag.go:148-203) that
    policies/fgd.py uses, here against (8,T)-shaped broadcasts."""
    gf = node.g8.astype(jnp.float32)  # (8,1)
    fit = (node.g8 >= tp.milli) & (tp.milli > 0)  # (8,T)
    fitf = fit.astype(jnp.float32)
    fitcnt = fitf.sum(axis=0, keepdims=True)  # (1,T)
    fitsum = jnp.where(fit, gf, 0.0).sum(axis=0, keepdims=True)  # (1,T)
    total = gf.sum()
    acc = (tp.mask == 0) | ((tp.mask & _node_bit(node.gtyp)) != 0)  # (1,T)
    gpu_pod = tp.milli > 0
    return fit, fitf, fitcnt, fitsum, total, acc, gpu_pod


def _fgd_column(node: _NodeScalars, types: _TypeCols, tp: _TpRows, aux):
    """FGD score + Reserve-device column for one node across all pod types
    (ref: plugin/fgd_score.go:99-156; the same fit/fitsum decomposition as
    policies/fgd.py, vectorized over the type axis)."""
    ks = types.ks
    k = types.cpu.shape[0]
    kw = k - ks
    fit, fitf, fitcnt, fitsum, total, acc, gpu_pod = _frag_terms(node, tp)
    isq3 = gpu_pod & acc & (fitcnt >= tp.numf) & (node.cpu >= tp.cpu)
    cur = (tp.freq * jnp.where(isq3, total - fitsum, total)).sum()
    gf = node.g8.astype(jnp.float32)  # (8,1)
    gT = node.g8.T  # (1,8)
    t = tp.cpu.shape[1]

    outs = []
    # --- share branch: best per-device hypothetical (fgd_score.go:111-134)
    if ks:
        p = types.milli[:ks]  # (Ks,1)
        p3 = p.astype(jnp.float32).reshape(ks, 1, 1)
        g3 = gf.reshape(1, 8, 1)
        m3i = tp.milli.reshape(1, 1, t)
        fitp = ((g3 - p3) >= m3i.astype(jnp.float32)) & (m3i > 0)  # (Ks,8,T)
        fit3 = fitf.reshape(1, 8, t)
        fitcnt_h = fitcnt.reshape(1, 1, t) - fit3 + fitp.astype(jnp.float32)
        fitsum_h = (
            fitsum.reshape(1, 1, t)
            - jnp.where(fit.reshape(1, 8, t), g3, 0.0)
            + jnp.where(fitp, g3 - p3, 0.0)
        )
        cpu_ok = (node.cpu - types.cpu[:ks]) >= tp.cpu  # (Ks,T)
        isq3_h = (
            gpu_pod.reshape(1, 1, t)
            & acc.reshape(1, 1, t)
            & (fitcnt_h >= tp.numf.reshape(1, 1, t))
            & cpu_ok.reshape(ks, 1, t)
        )
        total_h = total - p3  # (Ks,1,1)
        new = (
            tp.freq.reshape(1, 1, t)
            * jnp.where(isq3_h, total_h - fitsum_h, total_h)
        ).sum(axis=2)  # (Ks,8)
        fits = gT >= p  # (Ks,8)
        dev_scores = jnp.where(fits, _sigmoid_score_f32(cur, new), -1)
        best_score = jnp.max(dev_scores, axis=1, keepdims=True)  # (Ks,1)
        lane8 = _iota((ks, 8), 1)
        best_dev = jnp.min(
            jnp.where(dev_scores == best_score, lane8, 8), axis=1, keepdims=True
        )
        ok = best_score >= 0  # == fits.any(): fitting devices score >= 0
        outs.append((jnp.where(ok, best_score, 0), jnp.where(ok, best_dev, -1)))

    # --- whole/CPU branch: Sub hypothetical (fgd_score.go:137-148)
    if kw:
        wm = types.milli[ks:]  # (Kw,1)
        wn = types.num[ks:]
        wc = types.cpu[ks:]
        take, _ = _packed_take(node, wm, wn)  # (Kw,8)
        g2 = jnp.where(wn > 0, gT - take * wm, gT)  # (Kw,8)
        g2f = g2.astype(jnp.float32)
        m3i = tp.milli.reshape(1, 1, t)
        fit2 = (g2.reshape(kw, 8, 1) >= m3i) & (m3i > 0)  # (Kw,8,T)
        fitcnt2 = fit2.astype(jnp.float32).sum(axis=1)  # (Kw,T)
        fitsum2 = jnp.where(fit2, g2f.reshape(kw, 8, 1), 0.0).sum(axis=1)
        total2 = g2f.sum(axis=1, keepdims=True)  # (Kw,1)
        isq3_2 = gpu_pod & acc & (fitcnt2 >= tp.numf) & ((node.cpu - wc) >= tp.cpu)
        new_w = (tp.freq * jnp.where(isq3_2, total2 - fitsum2, total2)).sum(
            axis=1, keepdims=True
        )
        outs.append(
            (_sigmoid_score_f32(cur, new_w), jnp.full((kw, 1), -1, jnp.int32))
        )

    if len(outs) == 2:
        return (
            jnp.concatenate([outs[0][0], outs[1][0]], axis=0),
            jnp.concatenate([outs[0][1], outs[1][1]], axis=0),
        )
    return outs[0]


def _first_max_dev(scores, neg):
    """(value, device) of the first maximum over the device lane axis —
    jnp.argmax's first-on-ties semantics via max + min-index."""
    kdim = scores.shape[0]
    best = jnp.max(scores, axis=1, keepdims=True)  # (K,1)
    lane8 = _iota((kdim, 8), 1)
    dev = jnp.min(jnp.where(scores == best, lane8, 8), axis=1, keepdims=True)
    ok = best > neg
    return jnp.where(ok, best, neg), jnp.where(ok, dev, -1)


def _bestfit_column(node: _NodeScalars, types: _TypeCols, tp, aux):
    """BestFit (ref: best_fit_score.go:66-97): weighted free-minus-request
    over {cpu, gpu} dims against max machine specs."""
    gtot = node.g8.sum().astype(jnp.float32)
    s = (
        (node.cpu - types.cpu).astype(jnp.float32) / MAX_SPEC_CPU * 0.5
        + (gtot - (types.milli * types.num).astype(jnp.float32))
        / MAX_SPEC_GPU * 0.5
    )
    score = jnp.floor((1.0 - s) * MAX_NODE_SCORE).astype(jnp.int32)
    return score, jnp.full_like(score, -1)


def _packing_column(node: _NodeScalars, types: _TypeCols, tp, aux):
    """GpuPacking 3-tier scoring (ref: gpu_packing_score.go:67-117;
    mirrors policies/packing.py over the type axis)."""
    gT = node.g8.T  # (1,8)
    fully_free = (node.g8 == MILLI).astype(jnp.int32).sum()
    t3, t2 = MAX_NODE_SCORE // 3, MAX_NODE_SCORE // 2
    case3 = jnp.maximum(t3 - fully_free, fully_free)
    take, ok = _packed_take(node, types.milli, types.num)  # (K,8)
    free_used = (take & (gT == MILLI)).astype(jnp.int32).sum(
        axis=1, keepdims=True
    )
    ratio = jnp.where(take, gT * 100 // MILLI, 0).sum(axis=1, keepdims=True)
    case1 = jnp.maximum(MAX_NODE_SCORE - ratio // 10, t2)
    case2 = jnp.maximum(t2 - free_used, t3)
    score = jnp.where(
        fully_free == node.gcnt,
        case3,
        jnp.where(~ok, 0, jnp.where(free_used > 0, case2, case1)),
    )
    score = jnp.where((types.milli * types.num) > 0, score, 0)
    return score.astype(jnp.int32), jnp.full_like(score, -1)


def _type_affinity_class(types: _TypeCols):
    """pod_affinity_class per type column (ref: pod.go:111-123)."""
    share = (types.num == 1) & (types.milli < MILLI)
    cls = jnp.where(share, 0, types.num)
    return jnp.where(types.num == 0, -1, cls)


def _clustering_column(node: _NodeScalars, types: _TypeCols, tp, aux):
    """GpuClustering quartile scoring (ref: gpu_clustering_score.go:32-56;
    mirrors policies/clustering.py)."""
    q = MAX_NODE_SCORE // 4  # 25
    counts = node.aff9.T  # (1,9)
    n_classes = (counts > 0).astype(jnp.int32).sum()
    cls = _type_affinity_class(types)  # (K,1)
    kdim = cls.shape[0]
    lane9 = _iota((kdim, 9), 1)
    has_cls = jnp.sum(
        jnp.where(lane9 == jnp.maximum(cls, 0), counts, 0),
        axis=1, keepdims=True,
    ) > 0
    gtot = node.g8.sum()
    pack = q * (MAX_SPEC_GPU - gtot) // MAX_SPEC_GPU
    base = jnp.where(
        has_cls,
        jnp.where(n_classes == 1, 3 * q, 2 * q),
        jnp.where(n_classes == 0, q, 0),
    )
    score = jnp.where(cls < 0, 0, base + pack).astype(jnp.int32)
    return score, jnp.full_like(score, -1)


_PWR_NEG = np.int32(-(2**31) + 1)  # policies/pwr.py _NEG_INF


def _pwr_column(node: _NodeScalars, types: _TypeCols, tp, aux: _EnergyRows):
    """PWR watts-delta scoring (ref: pwr_score.go:150-218; mirrors
    policies/pwr.py's two-channel decomposition: the CPU package count and
    devices flipping idle->working)."""
    ks = types.ks
    kdim = types.cpu.shape[0]

    def look(row, idx):
        lane = _iota((1, row.shape[1]), 1)
        return jnp.sum(jnp.where(lane == idx, row, 0.0))

    gidle = jnp.where(node.gtyp >= 0, look(aux.gidle, jnp.maximum(node.gtyp, 0)), 0.0)
    gfull = jnp.where(node.gtyp >= 0, look(aux.gfull, jnp.maximum(node.gtyp, 0)), 0.0)
    busy_delta = gfull - gidle
    cidle = look(aux.cidle, node.ctyp)
    cfull = look(aux.cfull, node.ctyp)
    ncores = look(aux.ncores, node.ctyp)

    real_cores = jnp.ceil(node.cap.astype(jnp.float32) / MILLI / 2)
    num_cpus = jnp.ceil(real_cores / ncores)

    def cpu_watts(cpu_left):
        idle_cores = jnp.floor(cpu_left.astype(jnp.float32) / MILLI / 2)
        active = jnp.ceil((real_cores - idle_cores) / ncores)
        return cidle * (num_cpus - active) + cfull * active

    was_idle = node.g8.T == MILLI  # (1,8)
    n_idle = was_idle.astype(jnp.float32).sum()
    gpu_old = gidle * n_idle + gfull * (node.gcnt.astype(jnp.float32) - n_idle)
    old = cpu_watts(node.cpu) + gpu_old
    cpu_new = cpu_watts(node.cpu - types.cpu)  # (K,1)

    score = jnp.zeros((kdim, 1), jnp.int32)
    sdev = jnp.full((kdim, 1), -1, jnp.int32)
    sub_k = _iota((kdim, 1), 0)
    if ks:
        # share branch: device flips iff fully idle and the pod takes milli
        new_dev = cpu_new + gpu_old + jnp.where(
            was_idle & (types.milli > 0), busy_delta, 0.0
        )  # (K,8)
        fits = node.g8.T >= types.milli
        dev_scores = jnp.where(fits, (old - new_dev).astype(jnp.int32), _PWR_NEG)
        s_val, s_dev = _first_max_dev(dev_scores, _PWR_NEG)
        in_share = sub_k < ks
        score = jnp.where(in_share, s_val, score)
        sdev = jnp.where(in_share, s_dev, sdev)
    if kdim - ks:
        # whole/CPU branch: Sub's taken devices flip iff previously idle
        take, _ = _packed_take(node, types.milli, types.num)  # (K,8)
        flips = (take & was_idle).astype(jnp.float32).sum(axis=1, keepdims=True)
        w_val = (old - (cpu_new + gpu_old + flips * busy_delta)).astype(jnp.int32)
        in_whole = sub_k >= ks
        score = jnp.where(in_whole, w_val, score)
        sdev = jnp.where(in_whole, -1, sdev)
    return score, sdev


def _make_dotprod_column(dim_ext: str, norm: str):
    """DotProduct column for a (dimExtMethod, normMethod) config (ref:
    dot_product_score.go + the virtual expansion resource.go:246-381;
    mirrors policies/dotprod.py's fixed-slot masked kernels)."""

    def safe_div(v, n):
        return jnp.where(n > 0, v / jnp.where(n > 0, n, 1.0), 0.0)

    def column(node: _NodeScalars, types: _TypeCols, tp, aux):
        kdim = types.cpu.shape[0]
        gT = node.g8.T.astype(jnp.float32)  # (1,8)
        gtot = node.g8.sum().astype(jnp.float32)
        idle_cnt = (node.g8 == MILLI).astype(jnp.int32).sum()
        cpu_f = node.cpu.astype(jnp.float32)
        treq = (types.milli * types.num).astype(jnp.float32)  # (K,1)
        tcpu = types.cpu.astype(jnp.float32)
        cap_f = node.cap.astype(jnp.float32)
        gcap = (node.gcnt * MILLI).astype(jnp.float32)
        neg = jnp.float32(-(2.0**30))

        if norm == "node":
            div_cpu, div_gpu = cap_f, gcap
        elif norm == "pod":
            div_cpu, div_gpu = tcpu, treq
        else:  # max
            div_cpu = jnp.float32(MAX_SPEC_CPU)
            div_gpu = jnp.float32(MAX_SPEC_GPU)

        if dim_ext == "merge":
            dot = (
                safe_div(cpu_f, div_cpu) * safe_div(tcpu, div_cpu)
                + safe_div(gtot, div_gpu) * safe_div(treq, div_gpu)
            ) / 2.0
            if norm == "pod":
                dot = jnp.tanh(dot / 10.0)
            s = jnp.where(node.cpu >= types.cpu, 1.0 - dot, neg)  # (K,1)
            best = s
            dev = jnp.full((kdim, 1), -1, jnp.int32)
        else:
            slot_real = _iota((1, 8), 1) < node.gcnt
            pool_gpu = (idle_cnt * MILLI).astype(jnp.float32)
            first_free = jnp.min(
                jnp.where((node.g8.T == MILLI), _iota((1, 8), 1), 8)
            )
            first_free = jnp.where(idle_cnt > 0, first_free, -1)
            if dim_ext in ("share", "divide"):
                # 8 per-device slots (partially-used fitting devices, share
                # pods only) + the idle pool (resource.go:315-365)
                dev_active = (
                    (treq < MILLI) & slot_real & (gT < MILLI) & (gT >= treq)
                )  # (K,8)
                pool_active = treq <= (idle_cnt * MILLI).astype(jnp.float32)
                slot_gpu9 = jnp.concatenate(
                    [jnp.broadcast_to(gT, (kdim, 8)),
                     jnp.broadcast_to(pool_gpu, (kdim, 1))], axis=1
                )  # (K,9)
                active9 = jnp.concatenate([dev_active, pool_active], axis=1)
                if dim_ext == "divide":
                    slot_cpu9 = safe_div(cpu_f * slot_gpu9, gtot)
                else:
                    slot_cpu9 = jnp.broadcast_to(cpu_f, (kdim, 9))
                dots = (
                    safe_div(slot_cpu9, div_cpu) * safe_div(tcpu, div_cpu)
                    + safe_div(slot_gpu9, div_gpu) * safe_div(treq, div_gpu)
                ) / 2.0
            else:  # extend: formalized groups (resource.go:217-287)
                dev_group = slot_real & (gT > 0) & (gT < MILLI)  # (1,8)
                pool_group = idle_cnt > 0
                group9 = jnp.concatenate(
                    [jnp.broadcast_to(dev_group, (kdim, 8)),
                     jnp.broadcast_to(pool_group, (kdim, 1))], axis=1
                )
                left9 = jnp.concatenate(
                    [jnp.broadcast_to(gT, (kdim, 8)),
                     jnp.broadcast_to(pool_gpu, (kdim, 1))], axis=1
                )
                n_groups = dev_group.astype(jnp.float32).sum() + jnp.where(
                    pool_group, 1.0, 0.0
                )
                active9 = group9 & (left9 >= treq)
                slot_gpu9 = left9
                cpu_term = safe_div(cpu_f, div_cpu) * safe_div(tcpu, div_cpu)
                gpu_terms = safe_div(left9, div_gpu) * safe_div(treq, div_gpu)
                dots = (cpu_term + gpu_terms) / jnp.maximum(1.0 + n_groups, 1.0)
            if norm == "pod":
                dots = jnp.tanh(dots / 10.0)
            s9 = jnp.where((node.cpu >= types.cpu) & active9, 1.0 - dots, neg)
            best = jnp.max(s9, axis=1, keepdims=True)  # (K,1)
            lane9 = _iota((kdim, 9), 1)
            slot = jnp.min(
                jnp.where(s9 == best, lane9, 9), axis=1, keepdims=True
            )
            dev = jnp.where(slot < 8, slot, first_free).astype(jnp.int32)
            dev = jnp.where(best == neg, -1, dev)
        raw = jnp.where(
            best == neg, 0, (MAX_NODE_SCORE * best).astype(jnp.int32)
        )
        return raw, dev

    return column


def _resolve_column(fn):
    """Column kernel for a policy fn, or None if this policy/config has no
    Pallas form (the driver then falls back to the table engine)."""
    name = fn.policy_name
    if name == "FGDScore":
        return _fgd_column
    if name == "BestFitScore":
        return _bestfit_column
    if name == "GpuPackingScore":
        return _packing_column
    if name == "GpuClusteringScore":
        return _clustering_column
    if name == "PWRScore":
        return _pwr_column
    if name == "DotProductScore":
        dim_ext = getattr(fn, "dim_ext", None)
        norm = getattr(fn, "norm", None)
        # a wrapped policy object (e.g. jit_policy) may not carry the
        # config attrs — answer the predicate with "no column" rather
        # than crash
        if dim_ext is None or norm is None:
            return None
        return _make_dotprod_column(dim_ext, norm)
    return None


# policy names with a Pallas column implementation (config resolved by
# _resolve_column; kept as a set for quick membership tests/docs)
PALLAS_COLUMNS = {
    "FGDScore", "BestFitScore", "GpuPackingScore", "GpuClusteringScore",
    "PWRScore", "DotProductScore",
}

_SUPPORTED_GPU_SEL = {"best", "worst"} | SELF_SELECT_POLICIES


def supports(policies, gpu_sel: str) -> bool:
    """Whether make_pallas_replay can run this configuration. Per-event
    reporting is no longer gated here: engines replay metric-free and the
    shared post-pass (tpusim.sim.metrics) reconstructs the report series
    from the telemetry this kernel already emits. Weighted multi-policy
    configs (the reference's PWR+FGD mixes,
    generate_run_scripts.py:39-41) run fused since round 5 — every
    enabled policy needs a column kernel."""
    if not policies:
        return False
    if any(_resolve_column(fn) is None for fn, _ in policies):
        return False
    if gpu_sel not in _SUPPORTED_GPU_SEL:
        return False
    # a self-select gpuSelMethod must name an enabled policy (otherwise
    # there is no sdev source; the reference would fail plugin lookup too)
    if gpu_sel in SELF_SELECT_POLICIES and gpu_sel not in {
        fn.policy_name for fn, _ in policies
    }:
        return False
    return True


def _feas_column(node: _NodeScalars, types: _TypeCols):
    """Filter-phase feasibility for one node x all types (mirrors
    step.filter_nodes minus the per-event pinned-node mask)."""
    gT = node.g8.T  # (1,8)
    fit = (node.cpu >= types.cpu) & (node.mem >= types.mem)  # (K,1)
    units = jnp.where(types.milli > 0, gT // jnp.maximum(types.milli, 1), 0)
    can_alloc = units.sum(axis=1, keepdims=True) >= types.num
    acc = (types.mask == 0) | ((types.mask & _node_bit(node.gtyp)) != 0)
    gpu_ok = (node.gcnt > 0) & acc & can_alloc
    needs_gpu = (types.milli * types.num) > 0
    return (fit & (~needs_gpu | gpu_ok)).astype(jnp.int32)


def _pack_events(specs: PodSpec, type_id, ev_kind, ev_pod):
    """[_EV_FIELDS, E] i32 per-event rows: every pod scalar the kernel
    needs, pre-gathered host/XLA-side so the kernel only does masked lane
    extraction (Mosaic cannot dynamically index the pod axis)."""
    from tpusim.policies.clustering import pod_affinity_class

    pod = jax.tree.map(lambda a: a[ev_pod], specs)
    return jnp.stack(
        [
            ev_kind.astype(jnp.int32),
            ev_pod.astype(jnp.int32),
            type_id[ev_pod].astype(jnp.int32),
            pod.cpu,
            pod.mem,
            pod.gpu_milli,
            pod.gpu_num,
            pod.gpu_mask,
            pod.pinned,
            pod_affinity_class(pod),
            pod.is_gpu_share().astype(jnp.int32),
            pod.total_gpu_milli(),
        ]
    )


_CH = 128  # lane-chunk width: the node/event axes are laid out [*, C, 128]


def _make_kernel(columns, ks, gpu_sel):
    """The fused replay kernel for a static configuration. `columns` is a
    tuple of (column_fn, normalize, weight, is_selector) — one per enabled
    Score plugin; multi-policy rows accumulate Σ wᵢ · normalizeᵢ(colᵢ) in
    i32 exactly like the table engine's do_create (and the vendored
    RunScorePlugins weighted sum). The score table stacks per-policy
    blocks as [n_pol·K, C, 128]; the sdev table carries only the
    gpuSelMethod selector's Reserve picks. See module docstring for the
    masked-op calculus; every step mirrors a line of sim/step.py or
    table_engine.py.

    Layout (round-4 v2): the node axis is chunked as (C, 128) and the
    tables as [K, C, 128], because Mosaic supports dynamic slicing on
    leading and sublane dims (probed) but not the lane dim. Row gathers
    become free leading-dim slices, and column/state updates touch one
    (.., 1, 128) chunk instead of rewriting whole [K, N] tables — ~12x
    less masked-write traffic per event than the v1 flat layout."""
    self_select = gpu_sel in SELF_SELECT_POLICIES
    n_pol = len(columns)

    def kernel(
        ev_ref,  # [F, Ec, 128] i32
        tcpu_ref, tmem_ref, tmilli_ref, tnum_ref, tmask_ref,  # [K,1] i32
        tpcpu_ref, tpmilli_ref, tpnumf_ref, tpmask_ref, tpfreq_ref,  # [1,T]
        gcnt_ref, gtyp_ref, rank_ref,  # (C,128) i32 (read-only)
        cpucap_ref, ctyp_ref,  # (C,128) i32 (read-only; PWR dims)
        gidle_ref, gfull_ref, cidle_ref, cfull_ref, ncores_ref,  # (1,M) f32
        cpu0_ref, mem0_ref, gpu0_ref, aff0_ref,  # initial state (chunked)
        score_ref, sdev_ref, feas_ref,  # [K, C, 128] i32
        cpu_ref, mem_ref,  # (C,128) i32
        gpul_ref,  # [8, C, 128] i32
        aff_ref,  # [9, C, 128] i32
        placed_ref, maskb_ref, failed_ref,  # [1,P] i32
        evnode_ref, evdevb_ref,  # [Ec, 128] i32
        dirty,  # SMEM (1,) i32
    ):
        i = pl.program_id(0)
        kdim, nc, _ = feas_ref.shape  # K types; score_ref is [n_pol*K,..]
        n = nc * _CH
        p = placed_ref.shape[1]

        lane_p = _iota((1, p), 1)
        # node id grid over the chunked layout
        nid = _iota((nc, _CH), 0) * _CH + _iota((nc, _CH), 1)
        lane1 = _iota((1, _CH), 1)

        types = _TypeCols(
            tcpu_ref[:, :], tmem_ref[:, :], tmilli_ref[:, :],
            tnum_ref[:, :], tmask_ref[:, :], ks,
        )
        tp = _TpRows(
            tpcpu_ref[:, :], tpmilli_ref[:, :], tpnumf_ref[:, :],
            tpmask_ref[:, :], tpfreq_ref[:, :],
        )
        aux = _EnergyRows(
            gidle_ref[:, :], gfull_ref[:, :], cidle_ref[:, :],
            cfull_ref[:, :], ncores_ref[:, :],
        )

        def chunk_scalar(ref, c, sel):
            """ref (C,128): ref[c, l] via a one-chunk masked reduce."""
            return jnp.sum(jnp.where(sel, ref[pl.ds(c, 1), :], 0))

        def node_scalars(d):
            c, l = d // _CH, d % _CH
            sel = lane1 == l
            # 3D chunk slices reshape to 2D before reducing — Mosaic's
            # reduction lowering rejects the layout a 3D-sliced operand
            # carries (observed on-chip), while the 2D pattern is the one
            # the v1 layout already proved out
            g8c = gpul_ref[:, pl.ds(c, 1), :].reshape(8, _CH)
            a9c = aff_ref[:, pl.ds(c, 1), :].reshape(9, _CH)
            return _NodeScalars(
                cpu=chunk_scalar(cpu_ref, c, sel),
                mem=chunk_scalar(mem_ref, c, sel),
                cap=chunk_scalar(cpucap_ref, c, sel),
                gcnt=chunk_scalar(gcnt_ref, c, sel),
                gtyp=chunk_scalar(gtyp_ref, c, sel),
                ctyp=chunk_scalar(ctyp_ref, c, sel),
                g8=jnp.sum(jnp.where(sel, g8c, 0), axis=1, keepdims=True),
                aff9=jnp.sum(jnp.where(sel, a9c, 0), axis=1, keepdims=True),
            )

        def refresh_column(d):
            node = node_scalars(d)
            col_scores = []
            col_sdev = jnp.full((kdim, 1), -1, jnp.int32)
            for column_fn, _, _, is_sel in columns:
                cs, cd = column_fn(node, types, tp, aux)
                col_scores.append(cs)
                if is_sel:
                    col_sdev = cd
            col_score = (
                col_scores[0]
                if n_pol == 1
                else jnp.concatenate(col_scores, axis=0)
            )  # (n_pol*K, 1)
            col_feas = _feas_column(node, types)
            c, l = d // _CH, d % _CH
            hit = (lane1 == l).reshape(1, 1, _CH)
            for ref, col in (
                (score_ref, col_score),
                (sdev_ref, col_sdev),
                (feas_ref, col_feas),
            ):
                blk = ref[:, pl.ds(c, 1), :]  # (rows,1,128)
                ref[:, pl.ds(c, 1), :] = jnp.where(
                    hit, col.reshape(col.shape[0], 1, 1), blk
                )

        @pl.when(i == 0)
        def _():
            cpu_ref[:, :] = cpu0_ref[:, :]
            mem_ref[:, :] = mem0_ref[:, :]
            gpul_ref[:, :, :] = gpu0_ref[:, :, :]
            aff_ref[:, :, :] = aff0_ref[:, :, :]
            placed_ref[:, :] = jnp.full(placed_ref.shape, -1, jnp.int32)
            maskb_ref[:, :] = jnp.zeros(placed_ref.shape, jnp.int32)
            failed_ref[:, :] = jnp.zeros(placed_ref.shape, jnp.int32)
            evnode_ref[:, :] = jnp.full(evnode_ref.shape, -1, jnp.int32)
            evdevb_ref[:, :] = jnp.zeros(evnode_ref.shape, jnp.int32)
            dirty[0] = 0

            # build the score/sdev/feas tables column by column from the
            # initial state — the table engine's init_tables, but through
            # the SAME column code path the per-event refresh uses
            def body(d, _):
                refresh_column(d)
                return 0

            jax.lax.fori_loop(0, n, body, 0)

        # refresh the one column whose node changed last event
        # (table_engine.py's per-event column refresh; at i == 0 the tables
        # were just built, so the refresh is subsumed by the init loop)
        @pl.when(i != 0)
        def _():
            refresh_column(dirty[0])

        # ---- this event's packed scalars (one-chunk masked extraction)
        ec, el = i // _CH, i % _CH
        evblk = ev_ref[:, pl.ds(ec, 1), :]  # (F,1,128)
        sel_ev = (lane1 == el).reshape(1, 1, _CH)

        def f(j):
            return jnp.sum(jnp.where(sel_ev, evblk[j : j + 1, :, :], 0))

        kind = f(0)
        idx = f(1)
        tid = f(2)
        pcpu, pmem, pmilli, pnum = f(3), f(4), f(5), f(6)
        ppin, pcls, pshare, ptgm = f(8), f(9), f(10), f(11)
        sel_p = lane_p == idx
        sel_e1 = lane1 == el
        sub8c = _iota((8, 1), 0)

        def state_update(c, delta_fns):
            """Apply masked one-chunk updates: [(ref, hit_mask, delta)] —
            (C,128) refs take a (1,128) mask; [R,C,128] refs take an
            (R,1,128)-broadcastable mask; delta is scalar (or (R,1,1))."""
            for ref, hit, delta in delta_fns:
                if ref.ndim == 2:
                    blk = ref[pl.ds(c, 1), :]
                    ref[pl.ds(c, 1), :] = jnp.where(hit, blk + delta, blk)
                else:
                    blk = ref[:, pl.ds(c, 1), :]
                    ref[:, pl.ds(c, 1), :] = jnp.where(hit, blk + delta, blk)

        # ---- creation: Filter -> Score row -> selectHost -> Reserve -> Bind
        @pl.when(kind == 0)
        def _():
            feas_row = feas_ref[pl.ds(tid, 1), :, :].reshape(nc, _CH) != 0
            # nodeSelector pinning is a per-event mask, not a table column
            feasible = feas_row & ((ppin < 0) | (nid == ppin))
            total = jnp.zeros((nc, _CH), jnp.int32)
            for pi, (_, normalize, weight, _) in enumerate(columns):
                raw = score_ref[pl.ds(tid + pi * kdim, 1), :, :].reshape(
                    nc, _CH
                )
                if normalize in ("minmax", "pwr"):
                    lo = jnp.min(jnp.where(feasible, raw, _INT_MAX))
                    hi = jnp.max(jnp.where(feasible, raw, -_INT_MAX))
                    rngv = hi - lo
                    degen = 0 if normalize == "minmax" else MAX_NODE_SCORE
                    scaled = jnp.where(
                        rngv == 0,
                        degen,
                        (raw - lo) * MAX_NODE_SCORE // jnp.maximum(rngv, 1),
                    )
                    raw = jnp.where(feasible, scaled, raw)
                total = total + weight * raw
            # selectHost: max weighted score, smallest tie-break rank wins
            best = jnp.max(jnp.where(feasible, total, -_INT_MAX))
            wkey = jnp.where(
                feasible & (total == best), -rank_ref[:, :], -_INT_MAX
            )
            m = jnp.max(wkey)
            ok = m != -_INT_MAX
            node = jnp.where(ok, jnp.min(jnp.where(wkey == m, nid, n)), 0)
            c, l = node // _CH, node % _CH
            sel_l = lane1 == l

            # Reserve: device pick on the winner (step.choose_devices)
            g8w = jnp.sum(
                jnp.where(sel_l, gpul_ref[:, pl.ds(c, 1), :].reshape(8, _CH), 0),
                axis=1, keepdims=True,
            )  # (8,1)
            gT = g8w.T  # (1,8)
            lane8 = _iota((1, 8), 1)
            fits = gT >= pmilli
            any_fit = jnp.sum(fits.astype(jnp.int32)) > 0
            # allocate_share_best: min milli-left among fitting, first index
            bkey = jnp.where(fits, gT, _INT_MAX)
            bdev = jnp.min(jnp.where(bkey == jnp.min(bkey), lane8, 8))
            bdev = jnp.where(any_fit, bdev, -1)
            if gpu_sel == "worst":
                wkey8 = jnp.where(fits, gT, -_INT_MAX)
                wdev = jnp.min(jnp.where(wkey8 == jnp.max(wkey8), lane8, 8))
                share_dev = jnp.where(any_fit, wdev, -1)
            elif self_select:
                sdev = jnp.sum(
                    jnp.where(
                        sel_l,
                        sdev_ref[pl.ds(tid, 1), pl.ds(c, 1), :].reshape(1, _CH),
                        0,
                    )
                )
                share_dev = jnp.where(sdev >= 0, sdev, bdev)
            else:  # "best"
                share_dev = bdev
            share_bits = jnp.where(
                share_dev >= 0,
                jax.lax.shift_left(1, jnp.maximum(share_dev, 0)),
                0,
            )
            # allocate_two_pointer for whole/multi-GPU pods
            units = jnp.where(pmilli > 0, gT // jnp.maximum(pmilli, 1), 0)
            prev = _cumsum8_lanes(units) - units
            take_units = jnp.clip(pnum - prev, 0, units)
            whole_bits = jnp.sum(
                jnp.where(take_units > 0, jax.lax.shift_left(1, lane8), 0)
            )
            bits = jnp.where(
                ptgm > 0, jnp.where(pshare != 0, share_bits, whole_bits), 0
            )
            bits = jnp.where(ok, bits, 0)

            # Bind: masked one-chunk scatter-commit (step.select_and_bind)
            okl = sel_l & ok
            mask8 = (jax.lax.shift_right_logical(bits, sub8c) & 1) != 0
            aff_sub = _iota((9, 1), 0) == jnp.maximum(pcls, 0)
            state_update(
                c,
                [
                    (cpu_ref, okl, -pcpu),
                    (mem_ref, okl, -pmem),
                    (
                        gpul_ref,
                        okl.reshape(1, 1, _CH) & mask8.reshape(8, 1, 1),
                        -pmilli,
                    ),
                    (
                        aff_ref,
                        okl.reshape(1, 1, _CH)
                        & aff_sub.reshape(9, 1, 1)
                        & (pcls >= 0),
                        1,
                    ),
                ],
            )

            placed_ref[:, :] = jnp.where(
                sel_p, jnp.where(ok, node, -1), placed_ref[:, :]
            )
            maskb_ref[:, :] = jnp.where(sel_p, bits, maskb_ref[:, :])
            failed_ref[:, :] = jnp.where(
                sel_p, jnp.where(ok, 0, 1), failed_ref[:, :]
            )
            eblk = evnode_ref[pl.ds(ec, 1), :]
            evnode_ref[pl.ds(ec, 1), :] = jnp.where(
                sel_e1, jnp.where(ok, node, -1), eblk
            )
            dblk = evdevb_ref[pl.ds(ec, 1), :]
            evdevb_ref[pl.ds(ec, 1), :] = jnp.where(sel_e1, bits, dblk)
            dirty[0] = jnp.where(ok, node, 0)

        # ---- deletion: return resources to the recorded devices
        # (step.unschedule; simulator.go:334-357)
        @pl.when(kind == 1)
        def _():
            node = jnp.sum(jnp.where(sel_p, placed_ref[:, :], 0))
            bits = jnp.sum(jnp.where(sel_p, maskb_ref[:, :], 0))
            was = node >= 0
            nodee = jnp.maximum(node, 0)
            c, l = nodee // _CH, nodee % _CH
            sel_l = (lane1 == l) & was
            mask8 = (jax.lax.shift_right_logical(bits, sub8c) & 1) != 0
            aff_sub = _iota((9, 1), 0) == jnp.maximum(pcls, 0)
            state_update(
                c,
                [
                    (cpu_ref, sel_l, pcpu),
                    (mem_ref, sel_l, pmem),
                    (
                        gpul_ref,
                        sel_l.reshape(1, 1, _CH) & mask8.reshape(8, 1, 1),
                        pmilli,
                    ),
                    (
                        aff_ref,
                        sel_l.reshape(1, 1, _CH)
                        & aff_sub.reshape(9, 1, 1)
                        & (pcls >= 0),
                        -1,
                    ),
                ],
            )
            placed_ref[:, :] = jnp.where(sel_p, -1, placed_ref[:, :])
            maskb_ref[:, :] = jnp.where(sel_p, 0, maskb_ref[:, :])
            eblk = evnode_ref[pl.ds(ec, 1), :]
            evnode_ref[pl.ds(ec, 1), :] = jnp.where(sel_e1, node, eblk)
            dblk = evdevb_ref[pl.ds(ec, 1), :]
            evdevb_ref[pl.ds(ec, 1), :] = jnp.where(sel_e1, bits, dblk)
            dirty[0] = nodee

        # kind == 2 (EV_SKIP / padding): dirty, outputs unchanged

    return kernel


_PALLAS_REPLAY_CACHE = {}


def num_normalized(policies) -> int:
    """How many enabled policies carry a minmax/pwr NormalizeScore pass —
    the `num_norm` the HBM-residency footprint math sizes its
    brmin/brmax summaries with."""
    return sum(
        1 for fn, _ in policies if fn.normalize in ("minmax", "pwr")
    )


def make_pallas_replay(
    policies, gpu_sel: str = "best", interpret: bool = False,
    residency: str = "vmem",
):
    """Build the fused single-kernel replayer. Same call signature as the
    table engine's replay (state, pods, types, ev_kind, ev_pod, tp, key,
    tiebreak_rank); raises for configurations supports() rejects. `key` is
    accepted but unused — every supported configuration is deterministic
    (reject_randomized guarantees it).

    residency='vmem' is the original layout: every table VMEM-resident
    across grid steps (N ≤ 4096 at K = 151). residency='hbm' is the
    Round-19 layout (ENGINES.md): the [K, N] score/sdev/feas tables and
    the mutable node state live in HBM (`TPUMemorySpace.ANY`) and only
    the event's active working set crosses into VMEM by per-event
    double-buffered async DMA; its replay returns
    `(ReplayResult, dma_stats i32[3])` where dma_stats counts the
    kernel's (semaphore waits, DMA starts, extrema-drift summary
    rebuilds) — exact in-kernel counters the driver surfaces in the
    obs run record."""
    if residency not in ("vmem", "hbm"):
        raise ValueError(
            f"residency must be 'vmem' or 'hbm' (got {residency!r})"
        )
    reject_randomized(policies, gpu_sel)
    if not supports(policies, gpu_sel):
        raise ValueError(
            "pallas engine needs a registered column kernel for EVERY "
            "enabled policy and gpu_sel in {best, worst, an enabled "
            "self-select policy}; got "
            f"{[f.policy_name for f, _ in policies]} / gpu_sel={gpu_sel}"
        )
    cache_key = (
        tuple((fn, w) for fn, w in policies), gpu_sel, interpret, residency
    )
    if cache_key in _PALLAS_REPLAY_CACHE:
        return _PALLAS_REPLAY_CACHE[cache_key]
    if residency == "hbm":
        replay = _make_hbm_replay(policies, gpu_sel, interpret)
        _PALLAS_REPLAY_CACHE[cache_key] = replay
        return replay

    # (column_fn, normalize, weight, is_selector) per enabled plugin; the
    # selector is the policy the gpuSelMethod delegates Reserve picks to
    # (the allocateGpuIdFunc registry, plugin/open_gpu_share.go:39)
    columns = tuple(
        (
            _resolve_column(fn),
            fn.normalize,
            int(w),
            gpu_sel == fn.policy_name and fn.policy_name in SELF_SELECT_POLICIES,
        )
        for fn, w in policies
    )
    n_pol = len(columns)

    @jax.jit
    def replay(
        state: NodeState,
        pods: PodSpec,
        types: PodTypes,
        ev_kind,
        ev_pod,
        tp,
        key,
        tiebreak_rank=None,
    ) -> ReplayResult:
        from tpusim.parallel.sharding import pad_nodes

        n0 = state.num_nodes
        if tiebreak_rank is None:
            tiebreak_rank = jnp.arange(n0, dtype=jnp.int32)
        state_p, rank_p = pad_nodes(state, tiebreak_rank, 128)
        n = state_p.num_nodes

        ks = int(types.share.cpu.shape[0])
        kw = int(types.whole.cpu.shape[0])
        kdim = ks + kw

        def col(field):
            return jnp.concatenate(
                [getattr(types.share, field), getattr(types.whole, field)]
            ).reshape(kdim, 1)

        tcols = [col(f) for f in ("cpu", "mem", "gpu_milli", "gpu_num", "gpu_mask")]
        t = int(tp.cpu.shape[0])
        tprows = [
            tp.cpu.reshape(1, t),
            tp.gpu_milli.reshape(1, t),
            tp.gpu_num.astype(jnp.float32).reshape(1, t),
            tp.gpu_mask.reshape(1, t),
            tp.freq.reshape(1, t),
        ]
        ev = _pack_events(pods, types.type_id, ev_kind, ev_pod)
        e = int(ev.shape[1])
        p = int(pods.cpu.shape[0])
        nc = n // _CH
        # event axis chunked like the node axis; pad with EV_SKIP rows the
        # grid (over the TRUE e) never reads
        epad = (-e) % _CH
        if epad:
            ev = jnp.concatenate(
                [ev, jnp.zeros((ev.shape[0], epad), jnp.int32)
                 .at[0, :].set(2)],
                axis=1,
            )
        ec = (e + epad) // _CH
        ev3 = ev.reshape(ev.shape[0], ec, _CH)

        kernel = _make_kernel(columns, ks, gpu_sel)
        out_shape = (
            jax.ShapeDtypeStruct((n_pol * kdim, nc, _CH), jnp.int32),  # score
            jax.ShapeDtypeStruct((kdim, nc, _CH), jnp.int32),  # sdev
            jax.ShapeDtypeStruct((kdim, nc, _CH), jnp.int32),  # feas
            jax.ShapeDtypeStruct((nc, _CH), jnp.int32),  # cpu_left
            jax.ShapeDtypeStruct((nc, _CH), jnp.int32),  # mem_left
            jax.ShapeDtypeStruct((8, nc, _CH), jnp.int32),  # gpu_left
            jax.ShapeDtypeStruct((9, nc, _CH), jnp.int32),  # aff_cnt
            jax.ShapeDtypeStruct((1, p), jnp.int32),  # placed
            jax.ShapeDtypeStruct((1, p), jnp.int32),  # device mask bits
            jax.ShapeDtypeStruct((1, p), jnp.int32),  # failed
            jax.ShapeDtypeStruct((ec, _CH), jnp.int32),  # event node
            jax.ShapeDtypeStruct((ec, _CH), jnp.int32),  # event dev bits
        )
        energy_rows = [
            jnp.asarray(GPU_IDLE_W).reshape(1, -1),
            jnp.asarray(GPU_FULL_W).reshape(1, -1),
            jnp.asarray(CPU_IDLE_W).reshape(1, -1),
            jnp.asarray(CPU_FULL_W).reshape(1, -1),
            jnp.asarray(CPU_NCORES).reshape(1, -1),
        ]

        def chunk(a):
            return a.reshape(nc, _CH)

        (
            _score, _sdev, _feas, cpu_l, mem_l, gpul, aff,
            placed, maskb, failed, evnode, evdevb,
        ) = pl.pallas_call(
            kernel,
            grid=(e,),
            out_shape=out_shape,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 25,
            out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)] * 12),
            scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
            # jax renamed TPUCompilerParams -> CompilerParams in 0.5.x;
            # accept either so the engine survives both sides of the rename
            compiler_params=_compiler_params_cls()(
                dimension_semantics=("arbitrary",),
            ),
            interpret=interpret,
        )(
            ev3,
            *tcols,
            *tprows,
            chunk(state_p.gpu_cnt),
            chunk(state_p.gpu_type),
            chunk(rank_p),
            chunk(state_p.cpu_cap),
            chunk(state_p.cpu_type),
            *energy_rows,
            chunk(state_p.cpu_left),
            chunk(state_p.mem_left),
            state_p.gpu_left.T.reshape(8, nc, _CH),
            state_p.aff_cnt.T.reshape(9, nc, _CH),
        )

        bit8 = jnp.arange(MAX_GPUS_PER_NODE, dtype=jnp.int32)
        new_state = state._replace(
            cpu_left=cpu_l.reshape(n)[:n0],
            mem_left=mem_l.reshape(n)[:n0],
            gpu_left=gpul.reshape(8, n)[:, :n0].T,
            aff_cnt=aff.reshape(9, n)[:, :n0].T,
        )
        masks = ((maskb[0, :, None] >> bit8) & 1) != 0  # [P,8] bool
        evnode_f = evnode.reshape(ec * _CH)[:e]
        evdevb_f = evdevb.reshape(ec * _CH)[:e]
        devs = ((evdevb_f[:, None] >> bit8) & 1) != 0  # [E,8] bool
        return ReplayResult(
            new_state, placed[0], masks, failed[0] != 0, None, evnode_f, devs
        )

    _PALLAS_REPLAY_CACHE[cache_key] = replay
    return replay


# ---------------------------------------------------------------------------
# HBM residency (ENGINES.md Round 19): the [K, N] score/sdev/feas tables and
# the mutable node state live in HBM (`pl.BlockSpec(memory_space=
# pltpu.TPUMemorySpace.ANY)`); only the event's ACTIVE working set crosses
# into VMEM, by per-event async DMA (`pltpu.make_async_copy` + DMA
# semaphores — the SNIPPETS.md [2] primitive):
#
#   row slice    the event type's score rows + feas row, double-buffered:
#                event e+1's slice (its type comes from the scalar-
#                prefetched event stream) starts right after event e's
#                dirty-column writeback completes and is waited at the top
#                of body e+1 — DMA overlaps the grid turn-around + the
#                next event's refresh.
#   column chunk the dirty node's (.., 1, 128) table chunks, prefetched the
#                same way (the dirty node is known at the END of the
#                previous body — it IS that body's winner/freed node), and
#                written BACK by a second async copy after the refresh.
#   state chunk  the touched chunk of cpu/mem/gpu/aff, read-modify-written
#                around the Bind; the retained scratch copy doubles as the
#                next event's refresh input (dirty chunk == bound chunk).
#
# selectHost no longer touches the full row: it reduces the VMEM-RESIDENT
# blocked summaries bt/br/bn ([N/B, K]: per 128-node block the max weighted
# total, min tie-break rank among the maxima, and that winner's node id)
# maintained exactly like the blocked table engine's (ENGINES.md Round 6
# math): the dirty block's summary row refreshes each event from the column
# chunk under STORED per-type extrema (slo/shi), brmin/brmax track the
# per-block feasible raw extrema, and an extrema-drift check rebuilds one
# type's summary column (inside pl.when, from the row slice already in
# VMEM) before the select consumes it. Bit-identity with the flat select is
# inherited from the blocked engine's proof; the oracle tests pin it.
#
# Resident VMEM becomes O(K·B + row scratch) instead of O(K·N)
# (vmem_resident_bytes_hbm), moving the ceiling from N <= 4096 to
# HBM-bounded (>= 256k at K = 151 — hbm_ceiling_nodes).
# ---------------------------------------------------------------------------


def _make_hbm_kernel(columns, ks, gpu_sel):
    """The HBM-residency replay kernel for a static configuration. Same
    per-event math as _make_kernel (every line mirrors the blocked table
    engine or the VMEM-resident kernel); what changes is WHERE the tables
    live and the DMA choreography above. Control flow is uniform across
    event kinds — every body runs the same DMA skeleton with masked
    no-op updates — so the in-kernel DMA counters (dctr: waits, starts,
    drift rebuilds) are exact and static per event."""
    self_select = gpu_sel in SELF_SELECT_POLICIES
    n_pol = len(columns)
    norm_idx = [
        i for i, (_, nrm, _, _) in enumerate(columns)
        if nrm in ("minmax", "pwr")
    ]
    n_norm = len(norm_idx)
    nn = max(n_norm, 1)

    def kernel(
        kref, tref,  # scalar-prefetched event kind / type-id streams
        ev_ref,  # [F, Ec, 128] i32 packed event rows
        tcpu_ref, tmem_ref, tmilli_ref, tnum_ref, tmask_ref,  # [K,1] i32
        tpcpu_ref, tpmilli_ref, tpnumf_ref, tpmask_ref, tpfreq_ref,  # [1,T]
        gidle_ref, gfull_ref, cidle_ref, cfull_ref, ncores_ref,  # [1,M] f32
        rank_ref,  # (C,128) i32 VMEM (the drift rebuild reduces it whole)
        gcnt_any, gtyp_any, cap_any, ctyp_any,  # (C,128) i32 HBM read-only
        cpu0_any, mem0_any, gpu0_any, aff0_any,  # initial state, HBM
        # ---- outputs
        score_any, sdev_any, feas_any,  # [*, C, 128] i32 HBM tables
        cpu_any, mem_any, gpu_any, aff_any,  # mutable state, HBM
        bt_ref, br_ref, bn_ref,  # (C, K) i32 VMEM blocked summaries
        brmin_ref, brmax_ref,  # (C, nn*K) i32 block feasible raw extrema
        slo_ref, shi_ref,  # (1, nn*K) i32 stored per-type extrema
        placed_ref, maskb_ref, failed_ref,  # [1,P] i32
        evnode_ref, evdevb_ref,  # [Ec, 128] i32
        dma_ref,  # (1,128) i32: [waits, starts, rebuilds] at lanes 0..2
        # ---- scratch
        rowS,  # (2*n_pol, C, 128) double-buffered event-type score rows
        rowF,  # (2, C, 128) double-buffered event-type feas row
        colS,  # (n_pol*K, 2, 128) double-buffered dirty column chunk
        colD,  # (K, 2, 128)
        colF,  # (K, 2, 128)
        stC, stM,  # (1,128) retained state chunk (cpu / mem)
        stG,  # (8,1,128)
        stA,  # (9,1,128)
        roB,  # (4,128) read-only chunk rows: gcnt/gtyp/cap/ctyp
        sdW,  # (1,1,128) the winner's sdev chunk (self-select Reserve)
        dirty, dctr,  # SMEM (1,) / (4,) i32
        row_sem, colin_sem, colwb_sem,  # DMA sems
        stin_sem, stwb_sem, ro_sem, sd_sem, init_sem,
    ):
        i = pl.program_id(0)
        e = pl.num_programs(0)
        kdim, nc, _ = feas_any.shape
        n = nc * _CH
        p = placed_ref.shape[1]
        slot = jax.lax.rem(i, 2)
        nslot = jax.lax.rem(i + 1, 2)

        lane_p = _iota((1, p), 1)
        nid = _iota((nc, _CH), 0) * _CH + _iota((nc, _CH), 1)
        lane1 = _iota((1, _CH), 1)
        laneK = _iota((nc, kdim), 1)
        lane_nn = _iota((nc, nn * kdim), 1)
        lane_s = _iota((1, nn * kdim), 1)
        blki = _iota((nc, 1), 0)

        types = _TypeCols(
            tcpu_ref[:, :], tmem_ref[:, :], tmilli_ref[:, :],
            tnum_ref[:, :], tmask_ref[:, :], ks,
        )
        tp = _TpRows(
            tpcpu_ref[:, :], tpmilli_ref[:, :], tpnumf_ref[:, :],
            tpmask_ref[:, :], tpfreq_ref[:, :],
        )
        aux = _EnergyRows(
            gidle_ref[:, :], gfull_ref[:, :], cidle_ref[:, :],
            cfull_ref[:, :], ncores_ref[:, :],
        )

        # ---- DMA descriptors (constructed identically at start and wait
        # sites — the make_async_copy contract) + exact counters
        def start(cps):
            for cp in cps:
                dctr[1] = dctr[1] + 1
                cp.start()

        def wait(cps):
            for cp in cps:
                dctr[0] = dctr[0] + 1
                cp.wait()

        def row_dmas(s, t):
            cps = [
                pltpu.make_async_copy(
                    score_any.at[pl.ds(t + pi * kdim, 1), :, :],
                    rowS.at[pl.ds(s * n_pol + pi, 1), :, :],
                    row_sem.at[pi],
                )
                for pi in range(n_pol)
            ]
            cps.append(pltpu.make_async_copy(
                feas_any.at[pl.ds(t, 1), :, :],
                rowF.at[pl.ds(s, 1), :, :],
                row_sem.at[n_pol],
            ))
            return cps

        def colin_dmas(s, c):
            return [
                pltpu.make_async_copy(
                    score_any.at[:, pl.ds(c, 1), :],
                    colS.at[:, pl.ds(s, 1), :], colin_sem.at[0],
                ),
                pltpu.make_async_copy(
                    sdev_any.at[:, pl.ds(c, 1), :],
                    colD.at[:, pl.ds(s, 1), :], colin_sem.at[1],
                ),
                pltpu.make_async_copy(
                    feas_any.at[:, pl.ds(c, 1), :],
                    colF.at[:, pl.ds(s, 1), :], colin_sem.at[2],
                ),
            ]

        def colwb_dmas(s, c):
            return [
                pltpu.make_async_copy(
                    colS.at[:, pl.ds(s, 1), :],
                    score_any.at[:, pl.ds(c, 1), :], colwb_sem.at[0],
                ),
                pltpu.make_async_copy(
                    colD.at[:, pl.ds(s, 1), :],
                    sdev_any.at[:, pl.ds(c, 1), :], colwb_sem.at[1],
                ),
                pltpu.make_async_copy(
                    colF.at[:, pl.ds(s, 1), :],
                    feas_any.at[:, pl.ds(c, 1), :], colwb_sem.at[2],
                ),
            ]

        def state_dmas(c, srcs, inward, sems):
            cpu_r, mem_r, gpu_r, aff_r = srcs
            pairs = [
                (cpu_r.at[pl.ds(c, 1), :], stC),
                (mem_r.at[pl.ds(c, 1), :], stM),
                (gpu_r.at[:, pl.ds(c, 1), :], stG),
                (aff_r.at[:, pl.ds(c, 1), :], stA),
            ]
            return [
                pltpu.make_async_copy(
                    a if inward else b, b if inward else a, sems.at[j]
                )
                for j, (a, b) in enumerate(pairs)
            ]

        def ro_dmas(c):
            return [
                pltpu.make_async_copy(
                    r.at[pl.ds(c, 1), :], roB.at[pl.ds(j, 1), :],
                    ro_sem.at[j],
                )
                for j, r in enumerate(
                    (gcnt_any, gtyp_any, cap_any, ctyp_any)
                )
            ]

        def sd_dmas(t, c):
            return [pltpu.make_async_copy(
                sdev_any.at[pl.ds(t, 1), pl.ds(c, 1), :], sdW,
                sd_sem.at[0],
            )]

        # ---- shared compute helpers (mirror _make_kernel / the blocked
        # table engine line by line)
        def node_scalars_chunk(l):
            """_NodeScalars of lane `l` of the retained state chunk."""
            sel = lane1 == l
            g8c = stG[:, :, :].reshape(8, _CH)
            a9c = stA[:, :, :].reshape(9, _CH)

            def ro(j):
                return jnp.sum(jnp.where(sel, roB[pl.ds(j, 1), :], 0))

            return _NodeScalars(
                cpu=jnp.sum(jnp.where(sel, stC[:, :], 0)),
                mem=jnp.sum(jnp.where(sel, stM[:, :], 0)),
                cap=ro(2),
                gcnt=ro(0),
                gtyp=ro(1),
                ctyp=ro(3),
                g8=jnp.sum(jnp.where(sel, g8c, 0), axis=1, keepdims=True),
                aff9=jnp.sum(jnp.where(sel, a9c, 0), axis=1, keepdims=True),
            )

        def column_for(node):
            col_scores = []
            col_sdev = jnp.full((kdim, 1), -1, jnp.int32)
            for column_fn, _, _, is_sel in columns:
                cs, cd = column_fn(node, types, tp, aux)
                col_scores.append(cs)
                if is_sel:
                    col_sdev = cd
            col_score = (
                col_scores[0]
                if n_pol == 1
                else jnp.concatenate(col_scores, axis=0)
            )
            return col_score, col_sdev, _feas_column(node, types)

        def chunk_totals(score3, feas_b):
            """Weighted normalized totals over one (K, 128) chunk under
            the STORED extrema — the blocked engine's _totals with the
            -INT_MAX infeasible sentinel."""
            tot = jnp.zeros(feas_b.shape, jnp.int32)
            slo_k = slo_ref[:, :].reshape(nn, kdim)
            shi_k = shi_ref[:, :].reshape(nn, kdim)
            for pi, (_, nrm, w, _) in enumerate(columns):
                raw = score3[pi]
                if nrm in ("minmax", "pwr"):
                    j = norm_idx.index(pi)
                    lo = slo_k[j].reshape(kdim, 1)
                    hi = shi_k[j].reshape(kdim, 1)
                    rngv = hi - lo
                    degen = 0 if nrm == "minmax" else MAX_NODE_SCORE
                    scaled = jnp.where(
                        rngv == 0, degen,
                        (raw - lo) * MAX_NODE_SCORE // jnp.maximum(rngv, 1),
                    )
                    raw = jnp.where(feas_b, scaled, raw)
                tot = tot + w * raw
            return jnp.where(feas_b, tot, -_INT_MAX)

        def chunk_block_reduce(tot, rank_row, c):
            """block_reduce over one chunk's lane axis: (max total, min
            tie-break rank among the maxima, winner node id) per type."""
            m = jnp.max(tot, axis=1, keepdims=True)  # (K,1)
            wkey = jnp.where(tot == m, -rank_row, -_INT_MAX)
            mw = jnp.max(wkey, axis=1, keepdims=True)
            lane8k = _iota(tot.shape, 1)
            a = jnp.min(
                jnp.where(wkey == mw, lane8k, _CH), axis=1, keepdims=True
            )
            r = jnp.sum(
                jnp.where(lane8k == a, jnp.broadcast_to(rank_row, tot.shape),
                          0),
                axis=1, keepdims=True,
            )
            return m, r, c * _CH + a

        def col_chunk_views(s):
            score3 = colS[:, pl.ds(s, 1), :].reshape(n_pol, kdim, _CH)
            feas_b = colF[:, pl.ds(s, 1), :].reshape(kdim, _CH) != 0
            return score3, feas_b

        def block_extrema_row(score3, feas_b):
            """(1, nn*K) brmin/brmax rows of one chunk: per normalized
            policy the feasible raw extrema over the 128 lanes."""
            mns, mxs = [], []
            for j in range(nn):
                raw = score3[norm_idx[j]] if n_norm else score3[0]
                mns.append(jnp.min(
                    jnp.where(feas_b, raw, _INT_MAX), axis=1, keepdims=True
                ))
                mxs.append(jnp.max(
                    jnp.where(feas_b, raw, -_INT_MAX), axis=1, keepdims=True
                ))
            mn = jnp.concatenate(mns, axis=0).reshape(1, nn * kdim)
            mx = jnp.concatenate(mxs, axis=0).reshape(1, nn * kdim)
            return mn, mx

        def summary_rows_at(c, s):
            """Refresh brmin/brmax + bt/br/bn row `c` from the column
            chunk in slot `s` (stored extrema — the incremental half of
            the blocked engine's per-event aggregate refresh)."""
            score3, feas_b = col_chunk_views(s)
            if n_norm:
                mn, mx = block_extrema_row(score3, feas_b)
                brmin_ref[pl.ds(c, 1), :] = mn
                brmax_ref[pl.ds(c, 1), :] = mx
            rank_row = rank_ref[pl.ds(c, 1), :]
            tot = chunk_totals(score3, feas_b)
            bm, brk, bar = chunk_block_reduce(tot, rank_row, c)
            bt_ref[pl.ds(c, 1), :] = bm.reshape(1, kdim)
            br_ref[pl.ds(c, 1), :] = brk.reshape(1, kdim)
            bn_ref[pl.ds(c, 1), :] = bar.reshape(1, kdim)

        # dirty[0] is only written from i == 0 onward; mask the SMEM
        # read so a first-event EV_SKIP (t_node falls back to d_prev)
        # cannot derive a garbage chunk index from uninitialized scratch
        # on hardware (interpreter zero-fills and would hide it)
        d_prev = jnp.where(i == 0, 0, dirty[0])
        cd_prev = d_prev // _CH
        ld_prev = jax.lax.rem(d_prev, _CH)
        kind = kref[i]
        tid = tref[i]
        inext = jnp.minimum(i + 1, e - 1)
        tid_next = tref[inext]

        # ================= init (event 0): build everything =============
        @pl.when(i == 0)
        def _():
            dctr[0] = 0
            dctr[1] = 0
            dctr[2] = 0
            dirty[0] = 0
            init_cps = [
                pltpu.make_async_copy(a, b, init_sem.at[j])
                for j, (a, b) in enumerate((
                    (cpu0_any, cpu_any), (mem0_any, mem_any),
                    (gpu0_any, gpu_any), (aff0_any, aff_any),
                ))
            ]
            start(init_cps)
            wait(init_cps)
            placed_ref[:, :] = jnp.full(placed_ref.shape, -1, jnp.int32)
            maskb_ref[:, :] = jnp.zeros(placed_ref.shape, jnp.int32)
            failed_ref[:, :] = jnp.zeros(placed_ref.shape, jnp.int32)
            evnode_ref[:, :] = jnp.full(evnode_ref.shape, -1, jnp.int32)
            evdevb_ref[:, :] = jnp.zeros(evnode_ref.shape, jnp.int32)
            brmin_ref[:, :] = jnp.full(brmin_ref.shape, _INT_MAX, jnp.int32)
            brmax_ref[:, :] = jnp.full(brmax_ref.shape, -_INT_MAX, jnp.int32)
            slo_ref[:, :] = jnp.zeros(slo_ref.shape, jnp.int32)
            shi_ref[:, :] = jnp.zeros(shi_ref.shape, jnp.int32)

            # pass 1: table columns chunk by chunk (through the SAME
            # column code path the per-event refresh uses) + block extrema
            def pass1(c, _c):
                sd = state_dmas(c, (cpu0_any, mem0_any, gpu0_any, aff0_any),
                                True, stin_sem)
                rd = ro_dmas(c)
                start(sd + rd)
                wait(sd + rd)

                def lane_body(l, _l):
                    cs, cdv, cf = column_for(node_scalars_chunk(l))
                    hit = (lane1 == l).reshape(1, 1, _CH)
                    for ref, col in (
                        (colS, cs), (colD, cdv), (colF, cf)
                    ):
                        blk = ref[:, pl.ds(0, 1), :]
                        ref[:, pl.ds(0, 1), :] = jnp.where(
                            hit, col.reshape(col.shape[0], 1, 1), blk
                        )
                    return 0

                jax.lax.fori_loop(0, _CH, lane_body, 0)
                wb = colwb_dmas(0, c)
                start(wb)
                wait(wb)
                if n_norm:
                    score3, feas_b = col_chunk_views(0)
                    mn, mx = block_extrema_row(score3, feas_b)
                    brmin_ref[pl.ds(c, 1), :] = mn
                    brmax_ref[pl.ds(c, 1), :] = mx
                return 0

            jax.lax.fori_loop(0, nc, pass1, 0)
            if n_norm:
                slo_ref[:, :] = jnp.min(brmin_ref[:, :], axis=0,
                                        keepdims=True)
                shi_ref[:, :] = jnp.max(brmax_ref[:, :], axis=0,
                                        keepdims=True)

            # pass 2: bt/br/bn under the just-stored extrema
            def pass2(c, _c):
                cin = colin_dmas(0, c)
                start(cin)
                wait(cin)
                summary_rows_at(c, 0)
                return 0

            jax.lax.fori_loop(0, nc, pass2, 0)
            # event 0's row slice, synchronously, into slot 0
            r0 = row_dmas(0, tid)
            start(r0)
            wait(r0)

        # ============ steady state: wait prefetches, refresh ============
        @pl.when(i != 0)
        def _():
            wait(row_dmas(slot, tid))
            wait(colin_dmas(slot, cd_prev))
            wait(ro_dmas(cd_prev))
            # dirty-column refresh (the table engine's per-event column
            # refresh) on the retained state chunk, into this slot's
            # column scratch, then write back + patch the row slice the
            # prefetch could not have seen (it left HBM before this
            # refresh — the same-block-twice correctness case)
            cs, cdv, cf = column_for(node_scalars_chunk(ld_prev))
            hit = (lane1 == ld_prev).reshape(1, 1, _CH)
            for ref, col in ((colS, cs), (colD, cdv), (colF, cf)):
                blk = ref[:, pl.ds(slot, 1), :]
                ref[:, pl.ds(slot, 1), :] = jnp.where(
                    hit, col.reshape(col.shape[0], 1, 1), blk
                )
            start(colwb_dmas(slot, cd_prev))
            sub_np = _iota((n_pol * kdim, 1), 0)
            for pi in range(n_pol):
                v = jnp.sum(
                    jnp.where(sub_np == tid + pi * kdim, cs, 0)
                )
                old = rowS[pl.ds(slot * n_pol + pi, 1), pl.ds(cd_prev, 1), :]
                rowS[pl.ds(slot * n_pol + pi, 1), pl.ds(cd_prev, 1), :] = (
                    jnp.where(hit, v, old)
                )
            sub_k = _iota((kdim, 1), 0)
            vf = jnp.sum(jnp.where(sub_k == tid, cf, 0))
            oldf = rowF[pl.ds(slot, 1), pl.ds(cd_prev, 1), :]
            rowF[pl.ds(slot, 1), pl.ds(cd_prev, 1), :] = jnp.where(
                hit, vf, oldf
            )
            # dirty-block aggregate refresh for ALL K types (stored
            # extrema — consistent with every other block by construction)
            summary_rows_at(cd_prev, slot)

        # ---- extrema drift check + conditional summary-column rebuild
        # for THIS event's type (the blocked engine's cond, from the row
        # slice already in VMEM)
        if n_norm:
            lo_cur, hi_cur, slo_v, shi_v = [], [], [], []
            for j in range(n_norm):
                msk = lane_nn == (j * kdim + tid)
                lo_cur.append(jnp.min(
                    jnp.where(msk, brmin_ref[:, :], _INT_MAX)
                ))
                hi_cur.append(jnp.max(
                    jnp.where(msk, brmax_ref[:, :], -_INT_MAX)
                ))
                msk_s = lane_s == (j * kdim + tid)
                slo_v.append(jnp.sum(jnp.where(msk_s, slo_ref[:, :], 0)))
                shi_v.append(jnp.sum(jnp.where(msk_s, shi_ref[:, :], 0)))
            changed = jnp.zeros((), jnp.bool_)
            for j in range(n_norm):
                changed = changed | (lo_cur[j] != slo_v[j]) | (
                    hi_cur[j] != shi_v[j]
                )

            @pl.when(changed)
            def _():
                dctr[2] = dctr[2] + 1
                feas_row = rowF[pl.ds(slot, 1), :, :].reshape(nc, _CH) != 0
                tot = jnp.zeros((nc, _CH), jnp.int32)
                for pi, (_, nrm, w, _) in enumerate(columns):
                    raw = rowS[pl.ds(slot * n_pol + pi, 1), :, :].reshape(
                        nc, _CH
                    )
                    if nrm in ("minmax", "pwr"):
                        j = norm_idx.index(pi)
                        rngv = hi_cur[j] - lo_cur[j]
                        degen = 0 if nrm == "minmax" else MAX_NODE_SCORE
                        scaled = jnp.where(
                            rngv == 0, degen,
                            (raw - lo_cur[j]) * MAX_NODE_SCORE
                            // jnp.maximum(rngv, 1),
                        )
                        raw = jnp.where(feas_row, scaled, raw)
                    tot = tot + w * raw
                tot = jnp.where(feas_row, tot, -_INT_MAX)
                rank2 = rank_ref[:, :]
                m = jnp.max(tot, axis=1, keepdims=True)
                wkey = jnp.where(tot == m, -rank2, -_INT_MAX)
                mw = jnp.max(wkey, axis=1, keepdims=True)
                lane2 = _iota((nc, _CH), 1)
                a = jnp.min(
                    jnp.where(wkey == mw, lane2, _CH), axis=1, keepdims=True
                )
                r = jnp.sum(
                    jnp.where(lane2 == a, rank2, 0), axis=1, keepdims=True
                )
                nid_b = blki * _CH + a
                mT = laneK == tid
                bt_ref[:, :] = jnp.where(mT, m, bt_ref[:, :])
                br_ref[:, :] = jnp.where(mT, r, br_ref[:, :])
                bn_ref[:, :] = jnp.where(mT, nid_b, bn_ref[:, :])
                for j in range(n_norm):
                    msk_s = lane_s == (j * kdim + tid)
                    slo_ref[:, :] = jnp.where(msk_s, lo_cur[j],
                                              slo_ref[:, :])
                    shi_ref[:, :] = jnp.where(msk_s, hi_cur[j],
                                              shi_ref[:, :])

        # ---- this event's packed scalars (one-chunk masked extraction)
        ec_i = i // _CH
        el = jax.lax.rem(i, _CH)
        evblk = ev_ref[:, pl.ds(ec_i, 1), :]
        sel_ev = (lane1 == el).reshape(1, 1, _CH)

        def f(j):
            return jnp.sum(jnp.where(sel_ev, evblk[j:j + 1, :, :], 0))

        idx = f(1)
        pcpu, pmem, pmilli, pnum = f(3), f(4), f(5), f(6)
        ppin, pcls, pshare, ptgm = f(8), f(9), f(10), f(11)
        sel_p = lane_p == idx
        sel_e1 = lane1 == el
        sub8c = _iota((8, 1), 0)
        is_c = kind == 0
        is_d = kind == 1

        # ---- create: selectHost over the N/B block summaries (the
        # blocked two-level select; pinned pods bypass it — exactly one
        # candidate, its Filter bit decides)
        mT2 = laneK == tid
        bt_t = jnp.sum(jnp.where(mT2, bt_ref[:, :], 0), axis=1,
                       keepdims=True)
        br_t = jnp.sum(jnp.where(mT2, br_ref[:, :], 0), axis=1,
                       keepdims=True)
        bn_t = jnp.sum(jnp.where(mT2, bn_ref[:, :], 0), axis=1,
                       keepdims=True)
        vld = bt_t != -_INT_MAX
        best = jnp.max(jnp.where(vld, bt_t, -_INT_MAX))
        wkeyb = jnp.where(vld & (bt_t == best), -br_t, -_INT_MAX)
        mwb = jnp.max(wkeyb)
        okb = mwb != -_INT_MAX
        blk_w = jnp.min(jnp.where(wkeyb == mwb, blki, nc))
        cand = jnp.sum(jnp.where(blki == blk_w, bn_t, 0))
        pinc = jnp.clip(ppin, 0, n - 1)
        feas_rowv = rowF[pl.ds(slot, 1), :, :].reshape(nc, _CH)
        pin_feas = (jnp.sum(jnp.where(nid == pinc, feas_rowv, 0)) != 0) & (
            ppin < n
        )
        node_c = jnp.where(
            ppin >= 0,
            jnp.where(pin_feas, pinc, -1),
            jnp.where(okb, cand, -1),
        ).astype(jnp.int32)
        ok_c = node_c >= 0
        sel_c = jnp.maximum(node_c, 0)

        # ---- delete: the recorded placement
        node_d = jnp.sum(jnp.where(sel_p, placed_ref[:, :], 0))
        bits_d = jnp.sum(jnp.where(sel_p, maskb_ref[:, :], 0))
        was_d = node_d >= 0

        # unified touched node -> the state chunk every kind DMAs
        t_node = jnp.where(
            is_c, sel_c, jnp.where(is_d, jnp.maximum(node_d, 0), d_prev)
        )
        ct = t_node // _CH
        lt = jax.lax.rem(t_node, _CH)
        sel_l = lane1 == lt

        # previous event's state writeback must land before this read —
        # and THIS event's dirty-column writeback (started in the
        # refresh above) before the sdev-chunk read below: when the
        # winner lands in the chunk the refresh just wrote (ct ==
        # cd_prev), an unordered read could return the pre-refresh sdev
        # lane on hardware (interpreter DMAs complete at start() and
        # would hide it). The wait also precedes the e+1 prefetches, so
        # the original row/column read-after-writeback ordering holds.
        @pl.when(i != 0)
        def _():
            wait(state_dmas(cd_prev, (cpu_any, mem_any, gpu_any, aff_any),
                            False, stwb_sem))
            wait(colwb_dmas(slot, cd_prev))
        st_in = state_dmas(ct, (cpu_any, mem_any, gpu_any, aff_any),
                           True, stin_sem)
        start(st_in)
        wait(st_in)
        sd_in = sd_dmas(tid, ct)
        start(sd_in)
        wait(sd_in)

        # ---- Reserve: device pick on the winner (step.choose_devices)
        g8w = jnp.sum(
            jnp.where(sel_l, stG[:, :, :].reshape(8, _CH), 0),
            axis=1, keepdims=True,
        )
        gT = g8w.T
        lane8 = _iota((1, 8), 1)
        fits = gT >= pmilli
        any_fit = jnp.sum(fits.astype(jnp.int32)) > 0
        bkey = jnp.where(fits, gT, _INT_MAX)
        bdev = jnp.min(jnp.where(bkey == jnp.min(bkey), lane8, 8))
        bdev = jnp.where(any_fit, bdev, -1)
        if gpu_sel == "worst":
            wkey8 = jnp.where(fits, gT, -_INT_MAX)
            wdev = jnp.min(jnp.where(wkey8 == jnp.max(wkey8), lane8, 8))
            share_dev = jnp.where(any_fit, wdev, -1)
        elif self_select:
            sdev = jnp.sum(
                jnp.where(sel_l, sdW[:, :, :].reshape(1, _CH), 0)
            )
            share_dev = jnp.where(sdev >= 0, sdev, bdev)
        else:  # "best"
            share_dev = bdev
        share_bits = jnp.where(
            share_dev >= 0,
            jax.lax.shift_left(1, jnp.maximum(share_dev, 0)),
            0,
        )
        units = jnp.where(pmilli > 0, gT // jnp.maximum(pmilli, 1), 0)
        prev = _cumsum8_lanes(units) - units
        take_units = jnp.clip(pnum - prev, 0, units)
        whole_bits = jnp.sum(
            jnp.where(take_units > 0, jax.lax.shift_left(1, lane8), 0)
        )
        bits_c = jnp.where(
            ptgm > 0, jnp.where(pshare != 0, share_bits, whole_bits), 0
        )
        bits_c = jnp.where(ok_c, bits_c, 0)

        # ---- Bind: masked read-modify-write of the retained state chunk
        # (one scatter-commit per kind, no-op for skips/failed creates)
        act = jnp.where(
            is_c & ok_c, -1, jnp.where(is_d & was_d, 1, 0)
        ).astype(jnp.int32)
        bits_eff = jnp.where(is_c, bits_c, jnp.where(is_d, bits_d, 0))
        mask8 = (jax.lax.shift_right_logical(bits_eff, sub8c) & 1) != 0
        aff_sub = _iota((9, 1), 0) == jnp.maximum(pcls, 0)
        stC[:, :] = stC[:, :] + jnp.where(sel_l, act * pcpu, 0)
        stM[:, :] = stM[:, :] + jnp.where(sel_l, act * pmem, 0)
        stG[:, :, :] = stG[:, :, :] + jnp.where(
            sel_l.reshape(1, 1, _CH) & mask8.reshape(8, 1, 1),
            act * pmilli, 0,
        )
        stA[:, :, :] = stA[:, :, :] + jnp.where(
            sel_l.reshape(1, 1, _CH) & aff_sub.reshape(9, 1, 1)
            & (pcls >= 0),
            -act, 0,
        )
        start(state_dmas(ct, (cpu_any, mem_any, gpu_any, aff_any),
                         False, stwb_sem))

        # ---- bookkeeping (mirrors _make_kernel's create/delete writes)
        placed_ref[:, :] = jnp.where(
            sel_p & is_c, jnp.where(ok_c, node_c, -1),
            jnp.where(sel_p & is_d, -1, placed_ref[:, :]),
        )
        maskb_ref[:, :] = jnp.where(
            sel_p & is_c, bits_c,
            jnp.where(sel_p & is_d, 0, maskb_ref[:, :]),
        )
        failed_ref[:, :] = jnp.where(
            sel_p & is_c, jnp.where(ok_c, 0, 1), failed_ref[:, :]
        )
        eblk = evnode_ref[pl.ds(ec_i, 1), :]
        evnode_ref[pl.ds(ec_i, 1), :] = jnp.where(
            sel_e1 & is_c, jnp.where(ok_c, node_c, -1),
            jnp.where(sel_e1 & is_d, node_d, eblk),
        )
        dblk = evdevb_ref[pl.ds(ec_i, 1), :]
        evdevb_ref[pl.ds(ec_i, 1), :] = jnp.where(
            sel_e1 & is_c, bits_c,
            jnp.where(sel_e1 & is_d, bits_d, dblk),
        )
        dirty[0] = t_node

        # ---- prefetch event e+1's working set (the double buffer):
        # the column writeback already landed (waited before the
        # state/sdev chunk reads above), so the next row/column reads
        # cannot cover a chunk still being written
        @pl.when(i + 1 < e)
        def _():
            start(colin_dmas(nslot, ct))
            start(row_dmas(nslot, tid_next))
            start(ro_dmas(ct))

        @pl.when(i + 1 == e)
        def _():
            wait(state_dmas(ct, (cpu_any, mem_any, gpu_any, aff_any),
                            False, stwb_sem))

        dma_ref[:, :] = jnp.where(
            lane1 == 0, dctr[0],
            jnp.where(lane1 == 1, dctr[1],
                      jnp.where(lane1 == 2, dctr[2], 0)),
        )

    return kernel


def _make_hbm_replay(policies, gpu_sel: str, interpret: bool):
    """Build the HBM-residency replayer (make_pallas_replay's
    residency='hbm' arm). Returns a jitted `replay(...)` with the table
    engine's call signature that yields `(ReplayResult, dma_stats)` —
    dma_stats = i32[3] (semaphore waits, DMA starts, drift rebuilds)
    counted exactly inside the kernel."""
    columns = tuple(
        (
            _resolve_column(fn),
            fn.normalize,
            int(w),
            gpu_sel == fn.policy_name
            and fn.policy_name in SELF_SELECT_POLICIES,
        )
        for fn, w in policies
    )
    n_pol = len(columns)
    n_norm = sum(1 for _, nrm, _, _ in columns if nrm in ("minmax", "pwr"))
    nn = max(n_norm, 1)

    @jax.jit
    def replay(
        state: NodeState,
        pods: PodSpec,
        types: PodTypes,
        ev_kind,
        ev_pod,
        tp,
        key,
        tiebreak_rank=None,
    ):
        from tpusim.parallel.sharding import pad_nodes

        n0 = state.num_nodes
        if tiebreak_rank is None:
            tiebreak_rank = jnp.arange(n0, dtype=jnp.int32)
        state_p, rank_p = pad_nodes(state, tiebreak_rank, 128)
        n = state_p.num_nodes

        ks = int(types.share.cpu.shape[0])
        kw = int(types.whole.cpu.shape[0])
        kdim = ks + kw

        def col(field):
            return jnp.concatenate(
                [getattr(types.share, field), getattr(types.whole, field)]
            ).reshape(kdim, 1)

        tcols = [col(f) for f in ("cpu", "mem", "gpu_milli", "gpu_num",
                                  "gpu_mask")]
        t = int(tp.cpu.shape[0])
        tprows = [
            tp.cpu.reshape(1, t),
            tp.gpu_milli.reshape(1, t),
            tp.gpu_num.astype(jnp.float32).reshape(1, t),
            tp.gpu_mask.reshape(1, t),
            tp.freq.reshape(1, t),
        ]
        ev = _pack_events(pods, types.type_id, ev_kind, ev_pod)
        e = int(ev.shape[1])
        p = int(pods.cpu.shape[0])
        nc = n // _CH
        epad = (-e) % _CH
        if epad:
            ev = jnp.concatenate(
                [ev, jnp.zeros((ev.shape[0], epad), jnp.int32)
                 .at[0, :].set(2)],
                axis=1,
            )
        ec = (e + epad) // _CH
        ev3 = ev.reshape(ev.shape[0], ec, _CH)
        kinds = jnp.asarray(ev_kind, jnp.int32)
        tids = types.type_id[ev_pod].astype(jnp.int32)

        kernel = _make_hbm_kernel(columns, ks, gpu_sel)
        any_spec = pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)
        vmem_spec = pl.BlockSpec(memory_space=pltpu.VMEM)
        out_shape = (
            jax.ShapeDtypeStruct((n_pol * kdim, nc, _CH), jnp.int32),
            jax.ShapeDtypeStruct((kdim, nc, _CH), jnp.int32),  # sdev
            jax.ShapeDtypeStruct((kdim, nc, _CH), jnp.int32),  # feas
            jax.ShapeDtypeStruct((nc, _CH), jnp.int32),  # cpu_left
            jax.ShapeDtypeStruct((nc, _CH), jnp.int32),  # mem_left
            jax.ShapeDtypeStruct((8, nc, _CH), jnp.int32),  # gpu_left
            jax.ShapeDtypeStruct((9, nc, _CH), jnp.int32),  # aff_cnt
            jax.ShapeDtypeStruct((nc, kdim), jnp.int32),  # bt
            jax.ShapeDtypeStruct((nc, kdim), jnp.int32),  # br
            jax.ShapeDtypeStruct((nc, kdim), jnp.int32),  # bn
            jax.ShapeDtypeStruct((nc, nn * kdim), jnp.int32),  # brmin
            jax.ShapeDtypeStruct((nc, nn * kdim), jnp.int32),  # brmax
            jax.ShapeDtypeStruct((1, nn * kdim), jnp.int32),  # slo
            jax.ShapeDtypeStruct((1, nn * kdim), jnp.int32),  # shi
            jax.ShapeDtypeStruct((1, p), jnp.int32),  # placed
            jax.ShapeDtypeStruct((1, p), jnp.int32),  # device mask bits
            jax.ShapeDtypeStruct((1, p), jnp.int32),  # failed
            jax.ShapeDtypeStruct((ec, _CH), jnp.int32),  # event node
            jax.ShapeDtypeStruct((ec, _CH), jnp.int32),  # event dev bits
            jax.ShapeDtypeStruct((1, _CH), jnp.int32),  # dma stats
        )
        energy_rows = [
            jnp.asarray(GPU_IDLE_W).reshape(1, -1),
            jnp.asarray(GPU_FULL_W).reshape(1, -1),
            jnp.asarray(CPU_IDLE_W).reshape(1, -1),
            jnp.asarray(CPU_FULL_W).reshape(1, -1),
            jnp.asarray(CPU_NCORES).reshape(1, -1),
        ]

        def chunk(a):
            return a.reshape(nc, _CH)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(e,),
            in_specs=[vmem_spec] * 17 + [any_spec] * 8,
            out_specs=tuple([any_spec] * 7 + [vmem_spec] * 13),
            scratch_shapes=[
                pltpu.VMEM((2 * n_pol, nc, _CH), jnp.int32),  # rowS
                pltpu.VMEM((2, nc, _CH), jnp.int32),  # rowF
                pltpu.VMEM((n_pol * kdim, 2, _CH), jnp.int32),  # colS
                pltpu.VMEM((kdim, 2, _CH), jnp.int32),  # colD
                pltpu.VMEM((kdim, 2, _CH), jnp.int32),  # colF
                pltpu.VMEM((1, _CH), jnp.int32),  # stC
                pltpu.VMEM((1, _CH), jnp.int32),  # stM
                pltpu.VMEM((8, 1, _CH), jnp.int32),  # stG
                pltpu.VMEM((9, 1, _CH), jnp.int32),  # stA
                pltpu.VMEM((4, _CH), jnp.int32),  # roB
                pltpu.VMEM((1, 1, _CH), jnp.int32),  # sdW
                pltpu.SMEM((1,), jnp.int32),  # dirty
                pltpu.SMEM((4,), jnp.int32),  # dctr
                pltpu.SemaphoreType.DMA((n_pol + 1,)),  # row_sem
                pltpu.SemaphoreType.DMA((3,)),  # colin_sem
                pltpu.SemaphoreType.DMA((3,)),  # colwb_sem
                pltpu.SemaphoreType.DMA((4,)),  # stin_sem
                pltpu.SemaphoreType.DMA((4,)),  # stwb_sem
                pltpu.SemaphoreType.DMA((4,)),  # ro_sem
                pltpu.SemaphoreType.DMA((1,)),  # sd_sem
                pltpu.SemaphoreType.DMA((4,)),  # init_sem
            ],
        )
        (
            _score, _sdev, _feas, cpu_l, mem_l, gpul, aff,
            _bt, _br, _bn, _bmin, _bmax, _slo, _shi,
            placed, maskb, failed, evnode, evdevb, dma,
        ) = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=out_shape,
            compiler_params=_compiler_params_cls()(
                dimension_semantics=("arbitrary",),
            ),
            interpret=interpret,
        )(
            kinds,
            tids,
            ev3,
            *tcols,
            *tprows,
            *energy_rows,
            chunk(rank_p),
            chunk(state_p.gpu_cnt),
            chunk(state_p.gpu_type),
            chunk(state_p.cpu_cap),
            chunk(state_p.cpu_type),
            chunk(state_p.cpu_left),
            chunk(state_p.mem_left),
            state_p.gpu_left.T.reshape(8, nc, _CH),
            state_p.aff_cnt.T.reshape(9, nc, _CH),
        )

        bit8 = jnp.arange(MAX_GPUS_PER_NODE, dtype=jnp.int32)
        new_state = state._replace(
            cpu_left=cpu_l.reshape(n)[:n0],
            mem_left=mem_l.reshape(n)[:n0],
            gpu_left=gpul.reshape(8, n)[:, :n0].T,
            aff_cnt=aff.reshape(9, n)[:, :n0].T,
        )
        masks = ((maskb[0, :, None] >> bit8) & 1) != 0
        evnode_f = evnode.reshape(ec * _CH)[:e]
        evdevb_f = evdevb.reshape(ec * _CH)[:e]
        devs = ((evdevb_f[:, None] >> bit8) & 1) != 0
        result = ReplayResult(
            new_state, placed[0], masks, failed[0] != 0, None, evnode_f,
            devs,
        )
        return result, dma[0, :3]

    replay.residency = "hbm"
    return replay
