"""Wave-batched replay engine — exact intra-wave conflict repair.

The table engine (tpusim.sim.table_engine) made each event cheap by keeping
incremental score tables, but its lax.scan still runs one iteration per event
— and on TPU the per-iteration floor of a small-bodied scan (~15 us) plus the
per-event column refresh dominates. This engine dispatches a WAVE of W
consecutive events per scan iteration:

  1. refresh the score-table columns of every node touched in the previous
     wave in ONE batched (vmapped) sweep instead of W serial refreshes;
  2. gather the wave's W stale score/feasibility/device rows in one go;
  3. commit the W events in a statically-unrolled mini-loop where each
     event's row is PATCHED with freshly-computed values for only the <= W
     nodes already touched within this wave.

Because every deterministic policy scores a node as a pure function of (that
node's state, the pod's spec) — the same premise the table engine rests on —
the patched row is exactly the row the strictly-serial oracle would compute:
stale entries cover nodes whose state is unchanged since wave start, patched
entries are recomputed from live state. Placements, device masks, and final
state are therefore BIT-IDENTICAL to the sequential engine (and the table
engine); there is no conflict/retry divergence policy to document because
intra-wave conflicts are repaired exactly. tests/test_wave_engine.py pins
equality on the openb trace prefix and randomized create/delete mixes across
wave sizes.

What a wave buys: the W column refreshes (the per-event dominant cost,
K pod types x policy kernels) leave the serial dependency chain and run as
one [W, K] batch, and the scan has E/W iterations instead of E. SURVEY §7.2
step 3 names this batched-wave mode as the step past the serial scheduleOne
loop (vendor .../scheduler/scheduler.go:441).

Measured reality (TPU v5e, openb FGD replay): the wave engine matches the
table engine (~60 us/event, speedup ~1.0x at W=8) rather than beating it.
Profiling shows the replay is KERNEL-LAUNCH-BOUND — ~40+ small fused
kernels per event with no single hotspot — and the intra-wave fresh
scoring (policy kernel + filter on <= W rows, ~18 us) costs about what the
batched refresh saves. The wave structure is still what a sharded replay
wants (one batched refresh per wave instead of W serial ones), and the
engine is the exactness-preserving skeleton for any future divergent fast
mode. For raw single-chip throughput, the winning axis is batching
INDEPENDENT replays instead: jax.vmap over the seed axis amortizes every
kernel launch R-fold with zero divergence (~4x aggregate throughput at
R=16 on one chip, bit-identical per seed).

Same restrictions as the table engine (RandomScore / gpu_sel='random' draw
per-event randomness and must use the sequential oracle), plus report mode
is out of scope — per-event metric rows belong to the table engine
(report=True there).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusim.constants import MAX_GPUS_PER_NODE
from tpusim.policies import ScoreContext, minmax_normalize_i32, pwr_normalize_i32
from tpusim.sim.engine import EV_SKIP, ReplayResult
from tpusim.sim.step import (
    Placement,
    filter_nodes,
    select_and_bind,
    unschedule,
)
from tpusim.sim.table_engine import (
    PodTypes,
    _row_state,
    make_table_builders,
    reject_randomized,
    selector_index,
)
from tpusim.types import NodeState, PodSpec

_WAVE_REPLAY_CACHE = {}


def make_wave_replay(policies, gpu_sel: str = "best", wave: int = 16):
    """Build the jitted wave-batched replayer for a static policy config.

    policies: [(policy_fn, weight)] — all must be table-izable (see module
    docstring). wave: events per scan iteration (W); placements are
    bit-identical to the sequential oracle for EVERY W, so W only tunes
    throughput/compile-time.
    """
    reject_randomized(policies, gpu_sel)
    if wave < 1:
        raise ValueError(f"wave must be >= 1, got {wave}")
    cache_key = (tuple((fn, w) for fn, w in policies), gpu_sel, wave)
    if cache_key in _WAVE_REPLAY_CACHE:
        return _WAVE_REPLAY_CACHE[cache_key]
    sel_idx = selector_index(policies, gpu_sel)
    _columns, _init_tables = make_table_builders(policies, sel_idx)

    def _patch(row, touched, fresh, n):
        """row[touched[i]] = fresh[i] for non-empty slots. Empty slots
        (touched == -1) are routed out of bounds and dropped — clamping them
        to 0 instead would let a stale row[0] scatter alias over a genuine
        patch of node 0. Duplicate valid indices carry identical fresh
        values (same node, same live state), so write order is immaterial."""
        at = jnp.where(touched >= 0, touched, n)
        return row.at[at].set(fresh, mode="drop")

    @jax.jit
    def replay(
        state: NodeState,
        pods: PodSpec,  # [P]
        types: PodTypes,  # host-side build_pod_types(pods)
        ev_kind: jnp.ndarray,  # i32[E]
        ev_pod: jnp.ndarray,  # i32[E]
        tp,
        key,
        tiebreak_rank=None,
    ) -> ReplayResult:
        n = state.num_nodes
        num_pods = pods.cpu.shape[0]
        if tiebreak_rank is None:
            tiebreak_rank = jnp.arange(n, dtype=jnp.int32)
        type_id = types.type_id
        node_ids = jnp.arange(n, dtype=jnp.int32)
        npol = len(policies)  # packed-table channels: npol scores, sdev, feas

        e = ev_kind.shape[0]
        e2 = -(-e // wave) * wave
        if e2 != e:
            ev_kind = jnp.concatenate(
                [ev_kind, jnp.full(e2 - e, EV_SKIP, ev_kind.dtype)]
            )
            ev_pod = jnp.concatenate([ev_pod, jnp.zeros(e2 - e, ev_pod.dtype)])

        # RNG is only drawn by RandomScore / gpu_sel='random', both rejected
        # here — `key` seeds table init and then threads through unused, so
        # the scan body carries no splitting ops.
        key, k_init = jax.random.split(key)
        s0, d0, f0 = _init_tables(state, types, tp, k_init)
        # one packed [K, N, C] table: a single gather per row / scatter per
        # refresh instead of three (each gather/scatter is its own kernel
        # launch inside the scan body)
        packed_tbl = jnp.concatenate(
            [
                jnp.moveaxis(s0, 0, -1),  # [K, N, npol]
                d0[..., None],
                f0.astype(jnp.int32)[..., None],
            ],
            axis=-1,
        )
        # pods packed the same way: one [P, 6] row gather per event
        pods_packed = jnp.stack(
            [pods.cpu, pods.mem, pods.gpu_milli, pods.gpu_num,
             pods.gpu_mask, pods.pinned],
            axis=1,
        )

        placed = jnp.full(num_pods, -1, jnp.int32)
        masks = jnp.zeros((num_pods, MAX_GPUS_PER_NODE), jnp.bool_)
        failed = jnp.zeros(num_pods, jnp.bool_)

        def wave_body(carry, ev):
            (state, packed_tbl, dirty, placed, masks, failed) = carry
            kinds, idxs = ev  # i32[W] each

            # 1. batched refresh of last wave's touched columns. dirty == -1
            # slots clamp to node 0: its state is unchanged, so the rewrite
            # is value-identical (same trick as the table engine's initial
            # dirty = 0).
            dirty_c = jnp.maximum(dirty, 0)  # i32[W]
            col_scores, col_sdev, col_feas = jax.vmap(
                lambda d: _columns(_row_state(state, d), types, tp, key)
            )(dirty_c)  # [W, npol, K], [W, K], [W, K]
            packed_cols = jnp.concatenate(
                [
                    jnp.transpose(col_scores, (0, 2, 1)),  # [W, K, npol]
                    col_sdev[..., None],
                    col_feas.astype(jnp.int32)[..., None],
                ],
                axis=-1,
            )  # [W, K, C]
            packed_tbl = packed_tbl.at[:, dirty_c, :].set(
                jnp.transpose(packed_cols, (1, 0, 2))
            )

            # 2. gather the wave's stale rows (exact for every node whose
            # state is unchanged since wave start) and pod rows
            t_ids = type_id[idxs]  # [W]
            stale_rows = packed_tbl[t_ids]  # [W, N, C]
            pod_rows = pods_packed[idxs]  # [W, 6]

            # 3. statically-unrolled commit loop; `touched` records this
            # wave's mutated nodes (-1 = slot committed nothing)
            touched = jnp.full(wave, -1, jnp.int32)
            ev_nodes, ev_devs = [], []
            for j in range(wave):
                kind = kinds[j]
                idx = idxs[j]
                pr = pod_rows[j]
                pod = PodSpec(pr[0], pr[1], pr[2], pr[3], pr[4], pr[5])

                def do_create(state=state, touched=touched, placed=placed,
                              masks=masks, failed=failed, pod=pod, idx=idx,
                              j=j, row_j=stale_rows[j]):
                    touched_c = jnp.maximum(touched, 0)
                    # fresh values for intra-wave touched nodes, from live
                    # state, via the same kernels that build the tables
                    # (empty slots gather node 0; their values are dropped
                    # by _patch)
                    tstate = jax.tree.map(lambda a: a[touched_c], state)
                    pod_un = pod._replace(pinned=jnp.int32(-1))
                    ctx = ScoreContext(
                        tp=tp, feasible=jnp.ones(wave, jnp.bool_), rng=key
                    )
                    row_feas = _patch(
                        row_j[:, npol + 1] != 0, touched,
                        filter_nodes(tstate, pod_un), n,
                    )
                    feasible = row_feas & (
                        (pod.pinned < 0) | (node_ids == pod.pinned)
                    )
                    sdev_row = row_j[:, npol]
                    total = jnp.zeros(n, jnp.int32)
                    for i, (fn, weight) in enumerate(policies):
                        res = fn(tstate, pod_un, ctx)
                        raw = _patch(row_j[:, i], touched, res.raw_scores, n)
                        if i == sel_idx:
                            sdev_row = _patch(
                                sdev_row, touched, res.share_dev, n
                            )
                        if fn.normalize == "minmax":
                            raw = minmax_normalize_i32(raw, feasible)
                        elif fn.normalize == "pwr":
                            raw = pwr_normalize_i32(raw, feasible)
                        total = total + jnp.int32(weight) * raw
                    new_state, pl = select_and_bind(
                        state, pod, feasible, total, sdev_row, gpu_sel,
                        key, tiebreak_rank,
                    )
                    return (
                        new_state,
                        touched.at[j].set(pl.node),
                        placed.at[idx].set(pl.node),
                        masks.at[idx].set(pl.dev_mask),
                        failed.at[idx].set(pl.node < 0),
                        pl.node,
                        pl.dev_mask,
                    )

                def do_delete(state=state, touched=touched, placed=placed,
                              masks=masks, failed=failed, pod=pod, idx=idx,
                              j=j):
                    pl = Placement(placed[idx], masks[idx])
                    new_state = unschedule(state, pod, pl)
                    return (
                        new_state,
                        touched.at[j].set(pl.node),
                        placed.at[idx].set(-1),
                        masks.at[idx].set(False),
                        failed,
                        pl.node,
                        pl.dev_mask,
                    )

                def do_skip(state=state, touched=touched, placed=placed,
                            masks=masks, failed=failed):
                    return (
                        state, touched, placed, masks, failed,
                        jnp.int32(-1), jnp.zeros(MAX_GPUS_PER_NODE, jnp.bool_),
                    )

                (state, touched, placed, masks, failed,
                 node, dev) = jax.lax.switch(
                    jnp.clip(kind, 0, 2), [do_create, do_delete, do_skip]
                )
                ev_nodes.append(node)
                ev_devs.append(dev)

            return (
                state, packed_tbl, touched, placed, masks, failed,
            ), (jnp.stack(ev_nodes), jnp.stack(ev_devs))

        init = (state, packed_tbl, jnp.zeros(wave, jnp.int32),
                placed, masks, failed)
        waves = e2 // wave
        (state, _, _, placed, masks, failed), (
            nodes, devs
        ) = jax.lax.scan(
            wave_body, init,
            (ev_kind.reshape(waves, wave), ev_pod.reshape(waves, wave)),
        )
        nodes = nodes.reshape(e2)[:e]
        devs = devs.reshape(e2, MAX_GPUS_PER_NODE)[:e]
        return ReplayResult(state, placed, masks, failed, None, nodes, devs)

    _WAVE_REPLAY_CACHE[cache_key] = replay
    return replay
