"""Scheduling-queue sort orders for app pods (ref: pkg/algo/).

The reference sorts app pods with sort.Sort over boolean Less predicates —
with a constant-per-element key this is a partition; we implement each
queue as a stable partition/sort so the intent (strict-requirement pods
first) is preserved deterministically.

Used by ScheduleApp (pkg/simulator/simulator.go:224-237): affinity sort,
then toleration sort; `--use-greed` additionally pre-sorts by dominant
resource share (pkg/apply + algo/greed.go).
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Sequence, Tuple

from tpusim.io.trace import NodeRow, PodRow


def affinity_sort(pods: Sequence[PodRow]) -> List[PodRow]:
    """Node-selector pods first (ref: algo/affinity.go:8-32)."""
    return sorted(
        pods,
        key=lambda p: 0 if (p.node_selector or p.pinned_node) else 1,
    )


def toleration_sort(pods: Sequence[PodRow]) -> List[PodRow]:
    """Toleration-bearing pods first (ref: algo/toleration.go:7-22)."""
    return sorted(pods, key=lambda p: 0 if p.tolerations else 1)


def _share(alloc: float, total: float) -> float:
    """ref: algo/greed.go Share."""
    if total == 0:
        return 0.0 if alloc == 0 else 1.0
    return alloc / total


def greed_sort(pods: Sequence[PodRow], nodes: Sequence[NodeRow]) -> List[PodRow]:
    """Dominant-resource-share descending, pinned pods first
    (ref: algo/greed.go:12-91: NodeName-assigned pods lead; otherwise the
    larger max(cpu-share, memory-share) schedules earlier)."""
    total_cpu = float(sum(n.cpu_milli for n in nodes))
    total_mem = float(sum(n.memory_mib for n in nodes))

    def key(p: PodRow):
        pinned = 0 if p.pinned_node else 1
        share = max(_share(p.cpu_milli, total_cpu), _share(p.memory_mib, total_mem))
        return (pinned, -share)

    return sorted(pods, key=key)


def app_queue(
    pods: Sequence[PodRow],
    nodes: Sequence[NodeRow],
    use_greed: bool = False,
) -> List[PodRow]:
    """ScheduleApp's composite order (simulator.go:230-233): greed
    (optional) → affinity → toleration; later sorts are stable, so earlier
    keys act as tie-breaks."""
    out = list(pods)
    if use_greed:
        out = greed_sort(out, nodes)
    out = affinity_sort(out)
    return toleration_sort(out)


class RetryQueue:
    """Backoff requeue for fault-evicted pods (tpusim.sim.faults; the
    kube-scheduler backoff-queue shape: per-attempt exponential delay with
    a cap, then a terminal state).

    Attempt k re-enters the event stream min(base * 2^(k-1), cap) events
    after its eviction; a pod that has already failed max_retries attempts
    goes to `dead` instead (the driver reports it as an UnscheduledPod
    with reason "max-retries-exceeded"). Ordering is a (ready_position,
    insertion_seq) heap — deterministic FIFO among same-position retries,
    which the fault-replay determinism tests pin."""

    def __init__(self, base: int = 8, cap: int = 256, max_retries: int = 3):
        if base < 1 or cap < base or max_retries < 0:
            raise ValueError(
                f"RetryQueue(base={base}, cap={cap}, max_retries="
                f"{max_retries}): want base >= 1 <= cap and retries >= 0"
            )
        self.base = int(base)
        self.cap = int(cap)
        self.max_retries = int(max_retries)
        self._heap: List[Tuple[int, int, int, int]] = []
        self._seq = 0
        self.dead: List[Tuple[int, int]] = []  # (pod, attempts burned)

    def backoff(self, attempt: int) -> int:
        """Events to wait before attempt `attempt` (1-based)."""
        return min(self.base * (1 << max(attempt - 1, 0)), self.cap)

    def push(self, pod: int, evicted_at: int, attempt: int) -> Optional[int]:
        """Enqueue retry `attempt` for `pod`; returns its ready position,
        or None when the pod is out of retries (terminal)."""
        if attempt > self.max_retries:
            self.dead.append((pod, attempt - 1))
            return None
        ready = evicted_at + self.backoff(attempt)
        heapq.heappush(self._heap, (ready, self._seq, pod, attempt))
        self._seq += 1
        return ready

    def next_ready(self) -> Optional[int]:
        """Position of the earliest queued retry (None when empty)."""
        return self._heap[0][0] if self._heap else None

    def pop_due(self, pos: int) -> List[Tuple[int, int]]:
        """All (pod, attempt) retries due at or before `pos`, FIFO."""
        due = []
        while self._heap and self._heap[0][0] <= pos:
            _, _, pod, attempt = heapq.heappop(self._heap)
            due.append((pod, attempt))
        return due

    def __len__(self) -> int:
        return len(self._heap)
