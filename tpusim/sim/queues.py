"""Scheduling-queue sort orders for app pods (ref: pkg/algo/).

The reference sorts app pods with sort.Sort over boolean Less predicates —
with a constant-per-element key this is a partition; we implement each
queue as a stable partition/sort so the intent (strict-requirement pods
first) is preserved deterministically.

Used by ScheduleApp (pkg/simulator/simulator.go:224-237): affinity sort,
then toleration sort; `--use-greed` additionally pre-sorts by dominant
resource share (pkg/apply + algo/greed.go).
"""

from __future__ import annotations

from typing import List, Sequence

from tpusim.io.trace import NodeRow, PodRow


def affinity_sort(pods: Sequence[PodRow]) -> List[PodRow]:
    """Node-selector pods first (ref: algo/affinity.go:8-32)."""
    return sorted(
        pods,
        key=lambda p: 0 if (p.node_selector or p.pinned_node) else 1,
    )


def toleration_sort(pods: Sequence[PodRow]) -> List[PodRow]:
    """Toleration-bearing pods first (ref: algo/toleration.go:7-22)."""
    return sorted(pods, key=lambda p: 0 if p.tolerations else 1)


def _share(alloc: float, total: float) -> float:
    """ref: algo/greed.go Share."""
    if total == 0:
        return 0.0 if alloc == 0 else 1.0
    return alloc / total


def greed_sort(pods: Sequence[PodRow], nodes: Sequence[NodeRow]) -> List[PodRow]:
    """Dominant-resource-share descending, pinned pods first
    (ref: algo/greed.go:12-91: NodeName-assigned pods lead; otherwise the
    larger max(cpu-share, memory-share) schedules earlier)."""
    total_cpu = float(sum(n.cpu_milli for n in nodes))
    total_mem = float(sum(n.memory_mib for n in nodes))

    def key(p: PodRow):
        pinned = 0 if p.pinned_node else 1
        share = max(_share(p.cpu_milli, total_cpu), _share(p.memory_mib, total_mem))
        return (pinned, -share)

    return sorted(pods, key=key)


def app_queue(
    pods: Sequence[PodRow],
    nodes: Sequence[NodeRow],
    use_greed: bool = False,
) -> List[PodRow]:
    """ScheduleApp's composite order (simulator.go:230-233): greed
    (optional) → affinity → toleration; later sorts are stable, so earlier
    keys act as tie-breaks."""
    out = list(pods)
    if use_greed:
        out = greed_sort(out, nodes)
    out = affinity_sort(out)
    return toleration_sort(out)
