"""Applier: Simon-CR-driven experiment orchestration.

The reference's pkg/apply/apply.go Run() + pkg/simulator/core.go Simulate()
pipeline, driving the array-state Simulator:

  load CR → load cluster YAML dir (+ apps / Helm charts) → daemonset pods →
  typical pods → sort/tune workload → replay → ClusterAnalysis(InitSchedule)
  → snapshot export → inflation eval → new-workload swap → deschedule +
  reschedule → per-app scheduling → success/failure verdict.

Env caps MaxCPU/MaxMemory/MaxVG (apply.go:550-631 satisfyResourceSetting)
are honored for the final verdict; MaxVG reads the open-local VG totals
from the node storage annotations (see _satisfy_resource_setting).
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np

from tpusim.config.scheduler import SchedulerConfig, load_scheduler_config
from tpusim.config.simon import SimonCR, load_simon_cr
from tpusim.io.k8s_yaml import ClusterResource, load_cluster_from_dir
from tpusim.io.trace import PodRow
from tpusim.sim.driver import SimulateResult, Simulator, SimulatorConfig

COLOR_RED = "\033[31m"
COLOR_GREEN = "\033[32m"
COLOR_RESET = "\033[0m"


@dataclass
class ApplyOptions:
    """CLI surface (ref: cmd/apply/apply.go:26-40)."""

    simon_config: str = ""
    default_scheduler_config: str = ""
    use_greed: bool = False
    interactive: bool = False
    extended_resources: List[str] = field(default_factory=lambda: ["gpu"])
    base_dir: str = "."
    report_tables: bool = False
    # exact checkpoint/resume of the main replay (ISSUE 2; README
    # "Checkpoint/resume"): segment length in events, 0 = off
    checkpoint_every: int = 0
    checkpoint_dir: str = ""
    # retention (ISSUE 16): 0 = prune behind the run (resume-only),
    # -1 = keep every segment carry (the warm-fork ladder), N>0 = newest N
    checkpoint_keep: int = 0
    # fault injection (README "Fault injection"): MTBF-style schedule
    # knobs, all in EVENTS; mtbf 0 = no node failures, evict 0 = no
    # preemptions. Any non-zero rate routes the main schedule through
    # Simulator.run_with_faults.
    fault_mtbf: float = 0.0
    fault_mttr: float = 0.0
    fault_evict_every: float = 0.0
    fault_seed: int = 0
    fault_max_retries: int = 3
    # observability (README "Profiling & telemetry"; tpusim.obs): any
    # non-empty output path switches the run into profiling mode (phase
    # spans get the compile/execute split) and emits the corresponding
    # artifact after the run.
    profile_out: str = ""  # JSONL run record (appended)
    metrics_out: str = ""  # Prometheus textfile (atomic rewrite)
    trace_out: str = ""  # Chrome-trace timeline
    heartbeat_every: int = 0  # in-scan progress ticks (0 = off)
    # decision-provenance flight recorder (ISSUE 4; README "Explain a
    # placement"): a non-empty path turns record_decisions on and writes
    # the run's decision JSONL there — the input of `tpusim explain` /
    # `tpusim diff`.
    decisions_out: str = ""
    # in-scan cluster time-series plane (ISSUE 5; README "Live
    # monitoring"): > 0 samples utilization/frag/score distributions
    # every N processed events from inside the scan
    # (SimulatorConfig.series_every); the series lands in the JSONL run
    # record, the Chrome counter tracks, and `tpusim report`.
    series_every: int = 0
    # live monitoring endpoint: "HOST:PORT" / ":PORT" / "PORT" starts a
    # threaded HTTP server (tpusim.obs.server.MonitorServer) for the
    # run's lifetime — /metrics (Prometheus text; the final publish is
    # byte-equal to --metrics-out), /healthz, /progress (heartbeat-fed
    # phase/ev-per-s/ETA). Empty = off; bare ":PORT" binds loopback.
    listen: str = ""
    # config-axis sweep (ISSUE 6; README "Sweep many configs in one
    # compile"): a weights JSON here replaces the main schedule with ONE
    # vmapped replay over its [B, num_pol] weight grid (+ optional
    # per-config seeds) and prints the per-config summary table. The
    # file is either a bare [[w...], ...] list or
    # {"weights": [[...]], "seeds": [...]}.
    sweep_weights: str = ""
    # chaos sweep (ISSUE 10; README "Chaos sweep"): a faults JSON here
    # replaces the main schedule with ONE vmapped fault-lane replay —
    # same trace, B fault schedules (seed/MTBF/evict cadence/backoff as
    # per-lane operands) — and prints the per-lane disruption frontier.
    # The file is a bare [{...FaultConfig fields...}, ...] list or
    # {"faults": [...], "weights": [[...]], "seeds": [...]}.
    sweep_faults: str = ""
    # JAX persistent compilation cache dir (ISSUE 6 satellite;
    # SimulatorConfig.compile_cache_dir / $TPUSIM_COMPILE_CACHE_DIR):
    # wired before the first dispatch so re-runs skip the scan compile;
    # the obs record notes the probable hit/miss.
    compile_cache_dir: str = ""
    # score-plugin override (ISSUE 14): 'LearnedScore:FILE.json' replays
    # a signed learned-policy artifact as the (only) scoring family,
    # 'learned'/'learned-bucketed' the default-parameter families, or a
    # built-in name at weight 1000. Empty = the scheduler config's
    # plugins. A gpuSelMethod delegating to a policy the override
    # removed falls back to 'best' (the learned family carries no
    # Reserve-phase device pick of its own).
    policy: str = ""


class Applier:
    def __init__(self, options: ApplyOptions):
        if not options.simon_config:
            raise ValueError("--simon-config is required")
        self.options = options
        self.cr: SimonCR = load_simon_cr(options.simon_config, options.base_dir)
        self.sched_cfg: SchedulerConfig = load_scheduler_config(
            options.default_scheduler_config
        )
        # kubeConfig mode: the reference connects a kube-client and lists
        # the cluster's objects (CreateClusterResourceFromClient,
        # simulator.go:746-830). Here the kubeConfig path accepts BOTH a
        # kubeconfig credential file (live API server, thin HTTP client in
        # tpusim.io.kube_client) and a `kubectl get ... -o yaml` dump
        # (offline fallback); run() routes on the file's shape.

    def _simulator_config(self) -> SimulatorConfig:
        cc = self.cr.custom_config
        policies = self.sched_cfg.policy_tuple()
        gpu_sel = self.sched_cfg.gpu_sel_method
        if self.options.policy:
            # --policy override (ISSUE 14): replace the scheduler
            # config's plugin family wholesale; a policy-delegated
            # gpuSelMethod whose plugin is no longer enabled would
            # silently degrade inside the step, so resolve it to 'best'
            # loudly here
            from tpusim.learn.policy import parse_policy_spec

            policies = tuple(parse_policy_spec(self.options.policy))
            if gpu_sel not in ("best", "worst", "random") and gpu_sel not in {
                n for n, _ in policies
            }:
                print(
                    f"[policy] gpuSelMethod {gpu_sel!r} delegates to a "
                    "plugin the --policy override removed; using 'best'",
                    file=sys.stderr,
                )
                gpu_sel = "best"
        return SimulatorConfig(
            policies=policies,
            gpu_sel_method=gpu_sel,
            dim_ext_method=self.sched_cfg.dim_ext_method,
            norm_method=self.sched_cfg.norm_method,
            shuffle_pod=cc.shuffle_pod,
            tuning_ratio=cc.tuning.ratio,
            tuning_seed=cc.tuning.seed,
            inflation_ratio=cc.inflation.ratio,
            inflation_seed=cc.inflation.seed,
            typical_pods=cc.typical_pods,
            deschedule_ratio=cc.deschedule.ratio,
            deschedule_policy=cc.deschedule.policy,
            use_timestamps=cc.use_timestamps,
            engine=cc.engine,
            mesh=cc.mesh,
            extenders=self.sched_cfg.extenders,
            checkpoint_every=self.options.checkpoint_every,
            checkpoint_dir=self.options.checkpoint_dir,
            checkpoint_keep=self.options.checkpoint_keep,
            profile=bool(
                self.options.profile_out or self.options.metrics_out
                or self.options.trace_out
            ),
            heartbeat_every=self.options.heartbeat_every,
            record_decisions=bool(self.options.decisions_out),
            series_every=self.options.series_every,
            compile_cache_dir=self.options.compile_cache_dir,
        )

    def _fault_config(self):
        """FaultConfig from the --fault-* flags, or None when fault
        injection is off (no failure/eviction rate configured)."""
        o = self.options
        if o.fault_mtbf <= 0 and o.fault_evict_every <= 0:
            return None
        from tpusim.sim.faults import FaultConfig

        return FaultConfig(
            mtbf_events=o.fault_mtbf,
            mttr_events=o.fault_mttr,
            evict_every_events=o.fault_evict_every,
            seed=o.fault_seed,
            max_retries=o.fault_max_retries,
        )

    def _load_apps(self, node_names: Sequence[str]) -> List[tuple]:
        """appList → [(name, pods)] (apply.go:118-141; Helm charts render
        through tpusim.io.chart). App DaemonSets expand over the CLUSTER's
        nodes, which an app-only ClusterResource does not know about."""
        from tpusim.io.chart import chart_objects
        from tpusim.io.k8s_yaml import (
            daemonset_pods,
            load_cluster_from_objects,
            load_objects,
            yaml_files_in_dir,
        )

        apps = []
        for app in self.cr.app_list:
            if app.chart:
                objs = chart_objects(app.name, app.path)
            else:
                objs = load_objects(yaml_files_in_dir(app.path))
            res = load_cluster_from_objects(objs)
            pods = list(res.workload_pods())
            for ds in res.daemonsets:
                pods.extend(daemonset_pods(ds, node_names))
            apps.append((app.name, pods))
        if self.options.interactive and apps:
            apps = _interactive_select(apps)
        return apps

    def run(self, out=sys.stdout) -> SimulateResult:
        # persistent compilation cache (ISSUE 6 satellite): wired BEFORE
        # any jitted dispatch so the scan compile itself lands in / loads
        # from the cache; the post-run telemetry notes the probable
        # hit/miss via the dispatch-wall heuristic
        from tpusim.sim.driver import enable_compile_cache

        self._compile_cache_dir = enable_compile_cache(
            self.options.compile_cache_dir
        )
        if self._compile_cache_dir:
            print(
                f"[obs] compile cache at {self._compile_cache_dir}",
                file=out,
            )
        if self.cr.kube_config:
            from tpusim.io.k8s_yaml import load_cluster_from_dump
            from tpusim.io.kube_client import (
                is_kubeconfig_file,
                load_cluster_from_client,
            )

            if is_kubeconfig_file(self.cr.kube_config):
                cluster = load_cluster_from_client(self.cr.kube_config)
            else:
                cluster = load_cluster_from_dump(self.cr.kube_config)
            if not cluster.nodes:
                raise ValueError(
                    f"no Node objects from kubeConfig {self.cr.kube_config}"
                )
        else:
            cluster = load_cluster_from_dir(self.cr.custom_cluster)
        if not cluster.nodes:
            raise ValueError(f"no Node manifests under {self.cr.custom_cluster}")
        cc = self.cr.custom_config

        # live monitoring endpoint (--listen): up BEFORE the replay so a
        # scraper sees the run from its first phase; lives for the
        # process (a daemon thread — `tpusim serve` covers post-hoc
        # watching of checkpoint/record directories)
        self.monitor = None
        if self.options.listen:
            from tpusim.obs.server import MonitorServer

            self.monitor = MonitorServer(self.options.listen).start()
            self.monitor.attach_heartbeat()
            self.monitor.publish_progress(phase="loading")
            print(
                f"[obs] monitoring at {self.monitor.url} "
                "(/metrics /healthz /progress)", file=out,
            )

        sim = Simulator(cluster.nodes, self._simulator_config())
        sim.log.stream = out
        self.sim = sim

        # workload = trace pods + per-node daemonset pods (core.go:103-123)
        workload = cluster.workload_pods()
        ds_pods = cluster.daemonset_pods()
        sim.set_workload_pods(workload + ds_pods)
        fault_cfg = self._fault_config()
        if self.options.sweep_faults:
            # chaos sweep replaces the main schedule: one vmapped scan
            # over B fault schedules, the disruption frontier table
            if self.options.sweep_weights:
                raise ValueError(
                    "--sweep-faults and --sweep-weights are separate "
                    "sweep axes; pass per-lane weights inside the faults "
                    "JSON instead"
                )
            if fault_cfg is not None:
                raise ValueError(
                    "--sweep-faults replaces the --fault-* flags (each "
                    "lane carries its own schedule)"
                )
            return self._run_chaos(sim, out)
        if self.options.sweep_weights:
            # config-axis sweep replaces the main schedule: one vmapped
            # replay over the weight grid, a summary table, telemetry —
            # no snapshot/inflation/deschedule stages (they describe one
            # placement run, not B of them)
            if fault_cfg is not None:
                raise ValueError(
                    "--sweep-weights cannot combine with fault injection "
                    "(the vmapped sweep replays a single uninterrupted "
                    "event stream per config)"
                )
            return self._run_sweep(sim, out)
        if self.monitor is not None:
            self.monitor.publish_progress(
                phase="scheduling", nodes=len(cluster.nodes),
                pods=len(workload) + len(ds_pods),
            )
        if fault_cfg is not None:
            sim.run_with_faults(fault_cfg)
        else:
            sim.run()

        # snapshot export at InitSchedule (core.go:160-185)
        self._export_snapshots(sim, "init_schedule")

        # workload inflation eval (core.go:189-192)
        if cc.inflation.ratio > 1:
            sim.run_workload_inflation_evaluation("ScheduleInflation")

        # new-workload swap (core.go:195-209): replace the typical-pod
        # distribution with the new workload's, then schedule it on top
        if cc.new_workload_config:
            nw_dir = cc.new_workload_config
            if not os.path.isabs(nw_dir):
                nw_dir = os.path.join(self.options.base_dir, nw_dir)
            nw = load_cluster_from_dir(nw_dir)
            nw_pods = nw.workload_pods()
            sim.set_workload_pods(nw_pods)
            sim.set_typical_pods()
            sim.schedule_additional(nw_pods)
            sim.cluster_analysis("InitSchedule")

        # deschedule + reschedule (core.go:213-246)
        if cc.deschedule.ratio > 0 and cc.deschedule.policy:
            sim.deschedule_cluster()
            sim.cluster_analysis("PostDeschedule")
            self._export_snapshots(sim, "post_deschedule")
            if cc.inflation.ratio > 1:
                sim.run_workload_inflation_evaluation("DescheduleInflation")

        # per-app scheduling (core.go:255-261)
        for name, pods in self._load_apps(cluster.node_names):
            sim.schedule_app(name, pods, self.options.use_greed)

        result = sim.last_result
        sim.finish()
        self._note_compile_cache(sim)
        self._emit_telemetry(sim, out)
        if self.monitor is not None:
            self.monitor.publish_progress(
                phase="done", events_done=result.events,
                events_total=result.events,
            )
        self._emit_decisions(sim, out)
        self._verdict(result, out)
        if self.options.report_tables:
            from tpusim.sim.report_tables import full_report

            print(
                full_report(
                    result.pods,
                    result.placed_node,
                    result.dev_mask,
                    cluster.nodes,
                    self.options.extended_resources,
                ),
                file=out,
            )
        return result

    def _note_compile_cache(self, sim: Simulator):
        """Record the persistent-compilation-cache outcome on the run's
        telemetry (the `timing.compile_cache` block of the JSONL record;
        dispatch-wall heuristic, obs.spans.note_compile_cache)."""
        from tpusim.obs import note_compile_cache

        note_compile_cache(
            sim.obs, enabled=bool(self._compile_cache_dir),
            cache_dir=self._compile_cache_dir or "",
        )

    def _run_sweep(self, sim: Simulator, out):
        """`apply --sweep-weights`: load the weight grid, run the
        config-axis sweep (one compiled scan for all B configs; per-lane
        `tunes` ride the multi-trace sweep, ISSUE 7), print the
        per-config summary table (README "Sweep many configs in one
        compile")."""
        from tpusim.sim.driver import format_sweep_table

        weights, seeds, tunes = load_weights_payload(
            self.options.sweep_weights
        )
        lanes = sim.run_sweep(weights, seeds=seeds, tunes=tunes)
        print(
            f"[Sweep] {len(lanes)} configs x {lanes[0].events} events "
            f"in one compiled scan ({sim._last_engine})",
            file=out,
        )
        print(format_sweep_table(lanes, sim.cfg.policies), file=out)
        self._note_compile_cache(sim)
        self._emit_telemetry(sim, out)
        if self.monitor is not None:
            self.monitor.publish_progress(
                phase="done", events_done=lanes[0].events * len(lanes),
                events_total=lanes[0].events * len(lanes),
            )
        return None

    def _run_chaos(self, sim: Simulator, out):
        """`apply --sweep-faults`: load the per-lane fault documents, run
        the chaos sweep (one compiled vmapped scan for all B disruption
        what-ifs), print the per-lane disruption frontier (README "Chaos
        sweep")."""
        from tpusim.sim.driver import format_chaos_table

        specs, weights, seeds = load_faults_payload(
            self.options.sweep_faults, sim.cfg.policies
        )
        lanes = sim.run_sweep(weights, seeds=seeds, faults=specs)
        print(
            f"[Chaos] {len(lanes)} fault lanes x {lanes[0].events} events "
            f"in one compiled scan ({sim._last_engine})",
            file=out,
        )
        print(format_chaos_table(lanes, sim.cfg.policies), file=out)
        self._note_compile_cache(sim)
        self._emit_telemetry(sim, out)
        if self.monitor is not None:
            self.monitor.publish_progress(
                phase="done", events_done=sum(l.events for l in lanes),
                events_total=sum(l.events for l in lanes),
            )
        return None

    def _series_block(self, sim: Simulator):
        """The run's in-scan series as a JSONL record block, or None when
        series sampling was off (no key then — old records stay
        byte-identical)."""
        res = getattr(sim, "last_result", None)
        if res is None or res.series is None:
            return None
        from tpusim.obs.series import series_to_record

        return series_to_record(
            res.series, sim.cfg.series_every,
            [name for name, _ in sim.cfg.policies],
        )

    def _emit_telemetry(self, sim: Simulator, out):
        """Write the requested obs artifacts (--profile / --metrics-out /
        --trace-out) from the full experiment's telemetry — every stage
        (main schedule, inflation, deschedule, apps) contributed spans
        and counters to the one recorder. The record is built ONCE and
        shared with the live /metrics endpoint, so the final scrape of a
        --listen run is byte-equal to the --metrics-out textfile."""
        o = self.options
        if not (o.profile_out or o.metrics_out or o.trace_out
                or self.monitor is not None):
            return
        from tpusim.obs import emitters

        telemetry = sim.run_telemetry()
        record = emitters.build_record(
            telemetry, series=self._series_block(sim)
        )
        counter_series = None
        if o.trace_out:
            # only the Chrome-trace emitter consumes the counter series;
            # building it walks every per-event report row (O(E)). The
            # in-scan series adds its own counter tracks (per sample, not
            # per event — each track is laid across the wall window
            # independently).
            counter_series = sim.event_counter_series()
            last = getattr(sim, "last_result", None)
            if last is not None and last.series is not None:
                from tpusim.obs.series import series_tracks

                counter_series.update(series_tracks(last.series))
        paths = emitters.emit_record(
            record, telemetry.spans,
            jsonl=o.profile_out, metrics=o.metrics_out, trace=o.trace_out,
            counter_series=counter_series,
        )
        if self.monitor is not None:
            self.monitor.publish_record(record)
        for p in paths:
            print(f"[obs] wrote {p}", file=out)

    def _emit_decisions(self, sim: Simulator, out):
        """Persist the run's decision-provenance stream (--decisions-out)
        — the `tpusim explain` / `tpusim diff` input (ISSUE 4)."""
        path = self.options.decisions_out
        if not path:
            return
        from tpusim.obs import decisions as obs_decisions

        res = sim.last_result
        if res.decisions is None:
            print(
                "[obs] no decision stream recorded (engine without "
                "provenance support?)", file=out,
            )
            return
        written = obs_decisions.write_decisions(
            path, res.decisions,
            policies=list(sim.cfg.policies),
            meta=sim._telemetry_meta(),
            pod_names=[p.name for p in res.pods],
        )
        print(f"[obs] wrote {written}", file=out)

    def _export_snapshots(self, sim: Simulator, tag: str):
        exp = self.cr.custom_config.export
        if exp.pod_snapshot_yaml_file_prefix:
            path = f"{exp.pod_snapshot_yaml_file_prefix}_{tag}.yaml"
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            sim.export_pod_snapshot_yaml(path)
        if exp.node_snapshot_csv_file_prefix:
            path = f"{exp.node_snapshot_csv_file_prefix}_{tag}.csv"
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            sim.export_node_snapshot_csv(path)
            sim.export_pod_snapshot_csv(
                f"{exp.node_snapshot_csv_file_prefix}_{tag}_pod.csv"
            )

    def _verdict(self, result: SimulateResult, out):
        """Success print + env resource caps (apply.go:219-246, 550-631)."""
        if result.unscheduled_pods:
            print(
                f"{COLOR_RED}there are {len(result.unscheduled_pods)} "
                f"unscheduled pods{COLOR_RESET}",
                file=out,
            )
            print(f"{COLOR_RED}Failed!{COLOR_RESET}", file=out)
            return
        ok, reason = self._satisfy_resource_setting(result)
        if not ok:
            print(f"{COLOR_RED}{reason}{COLOR_RESET}", file=out)
            print(f"{COLOR_RED}Failed!{COLOR_RESET}", file=out)
        else:
            print(f"{COLOR_GREEN}Success!{COLOR_RESET}", file=out)

    def _satisfy_resource_setting(self, result: SimulateResult):
        """Env caps MaxCPU / MaxMemory / MaxVG as PERCENT occupancy-rate
        ceilings over cluster totals (ref: satisfyResourceSetting,
        apply.go:550-631: defaults 100, out-of-range values clamp to 100;
        VG totals come from the open-local node storage annotations)."""

        def _cap(env: str) -> int:
            raw = os.environ.get(env, "")
            if not raw:
                return 100
            v = int(raw)  # non-integers are an error in the reference too
            return 100 if v > 100 or v < 0 else v

        max_cpu, max_mem, max_vg = _cap("MaxCPU"), _cap("MaxMemory"), _cap("MaxVG")
        s = result.state
        cpu_rate = int(
            100.0 * (np.asarray(s.cpu_cap) - np.asarray(s.cpu_left)).sum()
            / max(1, np.asarray(s.cpu_cap, np.int64).sum())
        )
        mem_rate = int(
            100.0 * (np.asarray(s.mem_cap) - np.asarray(s.mem_left)).sum()
            / max(1, np.asarray(s.mem_cap, np.int64).sum())
        )
        if cpu_rate > max_cpu:
            return False, (
                f"the average occupancy rate({cpu_rate}%) of cpu goes beyond "
                f"the env setting({max_cpu}%)\n"
            )
        if mem_rate > max_mem:
            return False, (
                f"the average occupancy rate({mem_rate}%) of memory goes "
                f"beyond the env setting({max_mem}%)\n"
            )
        from tpusim.io.storage import cluster_vg_totals, parse_node_storage

        vg_req, vg_cap = cluster_vg_totals(
            parse_node_storage(n.local_storage) for n in self.sim.nodes
        )
        if vg_cap:
            vg_rate = int(100.0 * vg_req / vg_cap)
            if vg_rate > max_vg:
                return False, (
                    f"the average occupancy rate({vg_rate}%) of vg goes "
                    f"beyond the env setting({max_vg}%)\n"
                )
        return True, ""


def load_weights_payload(path: str):
    """Weights-grid JSON -> (weights, seeds, tunes): a bare
    [[w, ...], ...] list of rows, or {"weights": [[...]], "seeds":
    [...], "tunes": [...]} with the optional per-row seed/tune vectors.
    Shared vocabulary of `apply --sweep-weights` and the `tpusim submit`
    grid form (tpusim.svc.jobs.jobs_from_grid expands the same shape
    into job documents)."""
    import json

    with open(path) as f:
        payload = json.load(f)
    if isinstance(payload, dict):
        weights = payload.get("weights")
        seeds = payload.get("seeds")
        tunes = payload.get("tunes")
    else:
        weights, seeds, tunes = payload, None, None
    if not weights:
        raise ValueError(
            f"{path}: no weight rows (want [[w, ...], ...] or "
            '{"weights": [[...]], "seeds": [...], "tunes": [...]})'
        )
    return weights, seeds, tunes


# every key a chaos-lane fault document may carry — FaultConfig's field
# names exactly; unknown keys are rejected loudly (a typo'd "mtbf" must
# not silently run a fault-free lane)
FAULT_PAYLOAD_KEYS = frozenset((
    "mtbf_events", "mttr_events", "evict_every_events", "seed",
    "max_retries", "backoff_base", "backoff_cap", "queue_capacity",
))


def load_faults_payload(path: str, policies):
    """Chaos-sweep JSON -> (fault_specs, weights, seeds) for
    `Simulator.run_sweep(faults=...)`: a bare [{...FaultConfig
    fields...}, ...] list of per-lane fault documents, or
    {"faults": [...], "weights": [[...]], "seeds": [...]} with optional
    per-lane weight rows / seeds (defaults: the scheduler config's
    weights and cfg.seed for every lane)."""
    import json

    from tpusim.sim.faults import FaultConfig

    with open(path) as f:
        payload = json.load(f)
    if isinstance(payload, dict):
        docs = payload.get("faults")
        weights = payload.get("weights")
        seeds = payload.get("seeds")
        unknown = set(payload) - {"faults", "weights", "seeds"}
        if unknown:
            raise ValueError(
                f"{path}: unknown key(s) {sorted(unknown)} (known: "
                "faults, weights, seeds)"
            )
    else:
        docs, weights, seeds = payload, None, None
    if not isinstance(docs, list) or not docs:
        raise ValueError(
            f"{path}: no fault lanes (want [{{...FaultConfig fields...}}, "
            '...] or {"faults": [...], "weights": [[...]], "seeds": [...]})'
        )
    specs = []
    for i, doc in enumerate(docs):
        if not isinstance(doc, dict):
            raise ValueError(f"{path}: fault lane {i} must be an object")
        unknown = set(doc) - FAULT_PAYLOAD_KEYS
        if unknown:
            raise ValueError(
                f"{path}: fault lane {i} has unknown key(s) "
                f"{sorted(unknown)} (known: {sorted(FAULT_PAYLOAD_KEYS)})"
            )
        specs.append(FaultConfig(**doc))
    if weights is None:
        weights = [[w for _, w in policies]] * len(specs)
    if len(weights) != len(specs):
        raise ValueError(
            f"{path}: {len(weights)} weight rows for {len(specs)} fault "
            "lanes"
        )
    return specs, weights, seeds


def save_weights_payload(path: str, weights, seeds=None, tunes=None,
                         policies=None) -> str:
    """Write a weights-grid JSON in the exact shape load_weights_payload /
    `tpusim submit` read back — the shared weights-payload I/O (ISSUE 9):
    `tpusim tune --best-out` persists its tuned vector here so the next
    `apply --sweep-weights` or `submit` run replays it unchanged. Rows
    are coerced to plain ints (the engines' i32 operand space); the
    optional `policies` key names the columns for submit's grid form.
    Atomic (tmp + rename) like every other artifact writer."""
    import json

    doc = {"weights": [[int(w) for w in row] for row in weights]}
    if seeds is not None:
        doc["seeds"] = [int(s) for s in seeds]
    if tunes is not None:
        doc["tunes"] = [float(t) for t in tunes]
    if policies is not None:
        doc["policies"] = [[str(n), int(w)] for n, w in policies]
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True, separators=(",", ":"))
        f.write("\n")
    os.replace(tmp, path)
    return path


def _interactive_select(apps):
    """Multi-select confirmation (apply.go:172-189, survey lib)."""
    print("Confirm your apps (comma-separated indices, empty = all):")
    for i, (name, pods) in enumerate(apps):
        print(f"  [{i}] {name} ({len(pods)} pods)")
    line = input("> ").strip()
    if not line:
        return apps
    picked = {int(x) for x in line.split(",") if x.strip().isdigit()}
    return [a for i, a in enumerate(apps) if i in picked]
