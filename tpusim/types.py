"""Array-of-structs → struct-of-arrays domain model.

The reference keeps cluster state as a graph of k8s objects + annotations
(pkg/type/resource.go:51-72 NodeResource/PodResource; the fake API server).
Here the whole cluster is a handful of dense integer arrays, padded to
MAX_GPUS_PER_NODE devices per node, so that every policy/frag kernel is a
shape-static vmap over the node axis and the event loop is a lax.scan.

All resource quantities are int32 milli-units (CPU milli, GPU milli, MiB for
memory) — feasibility tests are exact integer comparisons, matching the
reference's int64 semantics (SURVEY.md §7.3 "Exact integer semantics").
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from tpusim.constants import MAX_GPUS_PER_NODE, MILLI


class NodeState(NamedTuple):
    """Cluster node state, one row per node (ref: NodeResource, resource.go:61-72).

    gpu_left rows are padded with 0 beyond gpu_cnt devices; 0-milli pads are
    inert in every kernel (a pod's per-GPU request is >0 whenever GPU math
    runs, so pads never fit, never count as fully-free capacity, and add 0 to
    totals).
    """

    cpu_left: jnp.ndarray  # i32[N] milli-CPU free
    cpu_cap: jnp.ndarray  # i32[N] milli-CPU allocatable
    mem_left: jnp.ndarray  # i32[N] MiB free
    mem_cap: jnp.ndarray  # i32[N] MiB allocatable
    gpu_left: jnp.ndarray  # i32[N, 8] milli-GPU free per device
    gpu_cnt: jnp.ndarray  # i32[N] number of physical GPUs
    gpu_type: jnp.ndarray  # i32[N] GPU model id, -1 = no GPU
    cpu_type: jnp.ndarray  # i32[N] CPU model id (0 = unknown profile)
    aff_cnt: jnp.ndarray  # i32[N, 9] pods per GPU-affinity class (GpuClustering)

    @property
    def num_nodes(self) -> int:
        return self.cpu_left.shape[0]

    def total_gpu_left(self) -> jnp.ndarray:
        """Per-node total idle milli-GPU (ref: resource.go:163-168)."""
        return self.gpu_left.sum(axis=-1)

    def fully_free_gpus(self) -> jnp.ndarray:
        """Per-node count of completely idle devices (ref: resource.go:170-177)."""
        return (self.gpu_left == MILLI).sum(axis=-1)


def make_node_state(
    cpu_cap,
    mem_cap,
    gpu_cnt,
    gpu_type,
    cpu_type=None,
) -> NodeState:
    """Build an all-idle NodeState from per-node capacity arrays."""
    cpu_cap = np.asarray(cpu_cap, np.int32)
    n = cpu_cap.shape[0]
    mem_cap = np.asarray(mem_cap, np.int32)
    gpu_cnt = np.asarray(gpu_cnt, np.int32)
    gpu_type = np.asarray(gpu_type, np.int32)
    cpu_type = (
        np.zeros(n, np.int32) if cpu_type is None else np.asarray(cpu_type, np.int32)
    )
    gpu_left = (np.arange(MAX_GPUS_PER_NODE)[None, :] < gpu_cnt[:, None]).astype(
        np.int32
    ) * MILLI
    return NodeState(
        cpu_left=jnp.asarray(cpu_cap),
        cpu_cap=jnp.asarray(cpu_cap),
        mem_left=jnp.asarray(mem_cap),
        mem_cap=jnp.asarray(mem_cap),
        gpu_left=jnp.asarray(gpu_left),
        gpu_cnt=jnp.asarray(gpu_cnt),
        gpu_type=jnp.asarray(gpu_type),
        cpu_type=jnp.asarray(cpu_type),
        aff_cnt=jnp.zeros((n, 9), jnp.int32),
    )


class PodSpec(NamedTuple):
    """Pod resource request (ref: PodResource, resource.go:51-58).

    Scalar fields for a single pod, or [P] arrays for a batch. gpu_milli is
    the per-device request (0-1000); gpu_mask is the allowed-GPU-model bitmask
    (0 = no constraint, ref: data/README.md gpu_spec).
    """

    cpu: jnp.ndarray  # i32 milli-CPU request
    mem: jnp.ndarray  # i32 MiB request
    gpu_milli: jnp.ndarray  # i32 per-GPU milli request
    gpu_num: jnp.ndarray  # i32 number of GPUs
    gpu_mask: jnp.ndarray  # i32 allowed GPU model bitmask
    pinned: jnp.ndarray  # i32 nodeSelector-pinned node index, -1 = free

    def total_gpu_milli(self):
        """ref: resource.go:129-131 TotalMilliGpu."""
        return self.gpu_milli * self.gpu_num

    def is_gpu_share(self):
        """ref: resource.go:405-411 IsGpuShare."""
        return (self.gpu_num == 1) & (self.gpu_milli < MILLI)


def make_pod(cpu=0, mem=0, gpu_milli=0, gpu_num=0, gpu_mask=0, pinned=-1) -> PodSpec:
    return PodSpec(
        cpu=jnp.int32(cpu),
        mem=jnp.int32(mem),
        gpu_milli=jnp.int32(gpu_milli),
        gpu_num=jnp.int32(gpu_num),
        gpu_mask=jnp.int32(gpu_mask),
        pinned=jnp.int32(pinned),
    )


class TypicalPods(NamedTuple):
    """Target-workload distribution for the frag math (ref: frag.go:285-380).

    Fixed-size [T] arrays, padded with freq == 0 rows (pads contribute nothing
    to any weighted sum).
    """

    cpu: jnp.ndarray  # i32[T]
    gpu_milli: jnp.ndarray  # i32[T]
    gpu_num: jnp.ndarray  # i32[T]
    gpu_mask: jnp.ndarray  # i32[T]
    freq: jnp.ndarray  # f32[T], sums to 1

    @property
    def size(self) -> int:
        return self.cpu.shape[0]


def make_typical_pods(rows) -> TypicalPods:
    """rows: iterable of (cpu_milli, gpu_milli, gpu_num, gpu_mask, freq)."""
    rows = list(rows)
    cpu, milli, num, mask, freq = (
        zip(*rows) if rows else ((), (), (), (), ())
    )
    return TypicalPods(
        cpu=jnp.asarray(np.array(cpu, np.int32)),
        gpu_milli=jnp.asarray(np.array(milli, np.int32)),
        gpu_num=jnp.asarray(np.array(num, np.int32)),
        gpu_mask=jnp.asarray(np.array(mask, np.int32)),
        freq=jnp.asarray(np.array(freq, np.float32)),
    )


def node_row(state: NodeState, i) -> NodeState:
    """View of one node as a NodeState of scalars (for single-node kernels)."""
    return NodeState(*(x[i] for x in state))
