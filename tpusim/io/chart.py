"""Helm-chart rendering for app manifests (ref: pkg/chart/chart.go
ProcessChart, which loads a chart and renders it through the real Helm
engine to YAML docs, dropping NOTES.txt and empty manifests).

This module implements the Go-template subset that real-world charts (and
`helm create` scaffolding) use, without a Go toolchain:

  actions     {{ pipeline }}, {{- ... -}} whitespace trimming, {{/* */}}
  control     if / else if / else, range (with $i, $v := assignment),
              with, define / template / include, end
  data        .Values.x.y field chains, $ (root), $var variables,
              string/number/bool literals, parenthesized sub-pipelines
  functions   default quote squote upper lower title trunc trimSuffix
              trimPrefix replace indent nindent toYaml printf eq ne lt le
              gt ge and or not empty coalesce required len
  helpers     templates/_*.tpl files are parsed for their define blocks

Files named NOTES.txt are skipped like the reference's renderResources
(chart.go:116-130); empty rendered manifests are dropped. Anything
genuinely outside the subset raises ChartError naming the directive, with
`helm template` pre-rendering as the escape hatch.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Tuple

import yaml


class ChartError(ValueError):
    pass


# ---------------------------------------------------------------------------
# tokenizer: text / {{ action }} stream with {{- / -}} trimming
# ---------------------------------------------------------------------------

_COMMENT = re.compile(r"/\*.*?\*/", re.S)


def _scan_action(src: str, i: int) -> int:
    """`i` points just past '{{'; return the index of the closing '}}',
    skipping over quoted string literals (a '}}' inside "..."/'...'/`...`
    is data, not a delimiter). -1 when unterminated."""
    n = len(src)
    while i < n:
        ch = src[i]
        if ch in "\"'`":
            i += 1
            while i < n and src[i] != ch:
                i += 2 if ch == '"' and src[i] == "\\" else 1
            i += 1
        elif ch == "}" and src.startswith("}}", i):
            return i
        else:
            i += 1
    return -1


def _tokenize(src: str) -> List[Tuple[str, str]]:
    """→ [(kind, payload)]: kind 'text' or 'action'. Handles `{{- `/` -}}`
    whitespace trimming (Go spec: the minus must be flanked by whitespace
    or the delimiter to count as a trim marker, not a negative number)."""
    out: List[Tuple[str, str]] = []
    pos = 0
    while True:
        start = src.find("{{", pos)
        if start < 0:
            break
        body_start = start + 2
        ltrim = src.startswith("-", body_start) and (
            body_start + 1 >= len(src) or src[body_start + 1] in " \t\n\r"
        )
        if ltrim:
            body_start += 1
        end = _scan_action(src, body_start)
        if end < 0:
            raise ChartError(f"unterminated template action: {src[start:start+40]!r}")
        body = src[body_start:end]
        rtrim = body.rstrip().endswith("-") and (
            len(body.rstrip()) == 1 or body.rstrip()[-2] in " \t\n\r"
        )
        if rtrim:
            body = body.rstrip()[:-1]
        text = src[pos:start]
        if ltrim:
            text = text.rstrip(" \t\n\r")
        if text:
            out.append(("text", text))
        expr = _COMMENT.sub("", body).strip()
        if expr:
            out.append(("action", expr))
        pos = end + 2
        if rtrim:
            while pos < len(src) and src[pos] in " \t\n\r":
                pos += 1
    if pos < len(src):
        out.append(("text", src[pos:]))
    return out


# ---------------------------------------------------------------------------
# parser: token stream → node tree
# ---------------------------------------------------------------------------


class _Text:
    def __init__(self, s):
        self.s = s


class _Pipe:
    def __init__(self, expr):
        self.expr = expr


class _If:
    def __init__(self):
        self.branches: List[Tuple[str, list]] = []  # (cond expr, body)
        self.else_body: list = []


class _Range:
    def __init__(self, decl, expr):
        self.decl = decl  # [] | [$v] | [$k, $v]
        self.expr = expr
        self.body: list = []
        self.else_body: list = []


class _With:
    def __init__(self, expr):
        self.expr = expr
        self.body: list = []
        self.else_body: list = []


class _Template:
    def __init__(self, expr):
        self.expr = expr  # '"name" pipeline?'


class _Var:
    def __init__(self, name, expr):
        self.name = name
        self.expr = expr


_KEYWORD = re.compile(r"^(if|range|with|define|template|else|end|block)\b")
_ASSIGN = re.compile(r"^(\$[\w]*)\s*:?=\s*(.*)$", re.S)
_RANGE_DECL = re.compile(
    r"^(\$[\w]+)\s*(?:,\s*(\$[\w]+)\s*)?:=\s*(.*)$", re.S
)


def _parse(tokens, i, templates, stop=("end",)):
    """Parse until a stop keyword; returns (nodes, stop_word, stop_expr, i)."""
    nodes: list = []
    while i < len(tokens):
        kind, payload = tokens[i]
        i += 1
        if kind == "text":
            nodes.append(_Text(payload))
            continue
        m = _KEYWORD.match(payload)
        word = m.group(1) if m else None
        rest = payload[m.end() :].strip() if m else payload
        if word in stop:
            return nodes, word, rest, i
        if word == "if":
            node = _If()
            cond = rest
            while True:
                body, stop_word, stop_expr, i = _parse(
                    tokens, i, templates, stop=("end", "else")
                )
                node.branches.append((cond, body))
                if stop_word == "end":
                    break
                if stop_expr.startswith("if"):
                    cond = stop_expr[2:].strip()
                    continue
                node.else_body, stop_word, _, i = _parse(
                    tokens, i, templates, stop=("end",)
                )
                break
            nodes.append(node)
        elif word == "range":
            dm = _RANGE_DECL.match(rest)
            if dm:
                decl = [v for v in (dm.group(1), dm.group(2)) if v]
                expr = dm.group(3)
            else:
                decl, expr = [], rest
            node = _Range(decl, expr)
            node.body, stop_word, _, i = _parse(
                tokens, i, templates, stop=("end", "else")
            )
            if stop_word == "else":
                node.else_body, _, _, i = _parse(tokens, i, templates)
            nodes.append(node)
        elif word == "with":
            node = _With(rest)
            node.body, stop_word, _, i = _parse(
                tokens, i, templates, stop=("end", "else")
            )
            if stop_word == "else":
                node.else_body, _, _, i = _parse(tokens, i, templates)
            nodes.append(node)
        elif word in ("define", "block"):
            name = rest.strip().strip("\"'")
            body, _, _, i = _parse(tokens, i, templates)
            templates[name] = body
            if word == "block":  # block also renders in place
                nodes.append(_Template(rest))
        elif word == "template":
            nodes.append(_Template(rest))
        else:
            am = _ASSIGN.match(payload)
            if am:
                nodes.append(_Var(am.group(1), am.group(2)))
            else:
                nodes.append(_Pipe(payload))
    return nodes, None, None, i


# ---------------------------------------------------------------------------
# pipeline evaluation
# ---------------------------------------------------------------------------


def _truthy(v) -> bool:
    """Go-template truth: false/0/""/nil/empty collection are false."""
    if v is None or v is False:
        return False
    if isinstance(v, (int, float)) and v == 0:
        return False
    if isinstance(v, (str, list, tuple, dict)) and len(v) == 0:
        return False
    return True


def _split_top(s: str, sep: str) -> List[str]:
    """Split on `sep` outside quotes/parens."""
    parts, depth, quote, cur = [], 0, "", []
    for ch in s:
        if quote:
            cur.append(ch)
            if ch == quote:
                quote = ""
            continue
        if ch in "\"'`":
            quote = ch
            cur.append(ch)
        elif ch == "(":
            depth += 1
            cur.append(ch)
        elif ch == ")":
            depth -= 1
            cur.append(ch)
        elif ch == sep and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


_TOKEN = re.compile(
    r"""\(|\)|"(?:[^"\\]|\\.)*"|'[^']*'|`[^`]*`|[^\s()]+""", re.S
)


class _Ctx:
    def __init__(self, root, dot, vars, templates):
        self.root = root
        self.dot = dot
        self.vars = vars
        self.templates = templates

    def child(self, dot=None, vars=None):
        return _Ctx(
            self.root,
            self.dot if dot is None else dot,
            dict(self.vars if vars is None else vars),
            self.templates,
        )


def _field_chain(base, path: str, expr: str):
    cur = base
    for part in path.split("."):
        if not part:
            continue
        if isinstance(cur, dict):
            cur = cur.get(part)
        elif cur is None:
            return None
        else:
            raise ChartError(f"cannot access .{part} in {{{{ {expr} }}}}")
    return cur


def _eval_atom(tok: str, ctx: _Ctx, expr: str):
    if tok == ".":
        return ctx.dot
    if tok == "$":
        return ctx.root
    if tok.startswith("$."):
        return _field_chain(ctx.root, tok[2:], expr)
    if tok.startswith("$"):
        name = tok.split(".", 1)
        if name[0] not in ctx.vars:
            raise ChartError(f"undefined variable {name[0]} in {{{{ {expr} }}}}")
        v = ctx.vars[name[0]]
        return _field_chain(v, name[1], expr) if len(name) > 1 else v
    if tok.startswith("."):
        return _field_chain(ctx.dot, tok[1:], expr)
    if tok[0] in "\"'`":
        s = tok[1:-1]
        return s.replace('\\"', '"').replace("\\n", "\n").replace("\\t", "\t") if tok[0] == '"' else s
    if tok in ("true", "false"):
        return tok == "true"
    if tok in ("nil", "null"):
        return None
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    raise ChartError(f"unsupported token {tok!r} in {{{{ {expr} }}}}")


def _to_yaml(v) -> str:
    return yaml.safe_dump(v, default_flow_style=False, sort_keys=False).rstrip("\n")


def _indent(n: int, s: str) -> str:
    pad = " " * n
    return "\n".join(pad + line if line else line for line in s.splitlines())


def _go_str(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    return str(v)


def _printf(fmt: str, *args) -> str:
    # Go verbs used by charts: %s %d %v %q (+ %% escape)
    out, ai = [], 0
    i = 0
    while i < len(fmt):
        ch = fmt[i]
        if ch == "%" and i + 1 < len(fmt):
            verb = fmt[i + 1]
            i += 2
            if verb == "%":
                out.append("%")
                continue
            arg = args[ai] if ai < len(args) else None
            ai += 1
            if verb == "q":
                out.append(f'"{_go_str(arg)}"')
            elif verb == "d":
                out.append(str(int(arg)))
            else:  # s, v
                out.append(_go_str(arg))
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def _semver_compare(constraint, version):
    """Minimal semverCompare: one `[op]x.y.z` constraint against a version.
    Helm's range/caret/tilde/wildcard syntax is outside the subset → ChartError."""
    # only the ubiquitous "-0" prerelease-inclusive idiom (">=1.19.0-0") is
    # accepted on the constraint side; other prerelease constraints have
    # ordering semantics the subset doesn't model and raise below
    m = re.match(
        r"^\s*(>=|<=|!=|>|<|=)?\s*v?(\d+(?:\.\d+){0,2})(-0)?\s*$",
        str(constraint),
    )
    # build metadata (+...) is ignored like Helm does. A prerelease version
    # (1.27.3-gke.100) compares by its numeric core ONLY when the constraint
    # opted into prereleases via "-0"; against a plain constraint Helm
    # EXCLUDES prereleases, which the subset doesn't model — raise.
    vm = re.match(
        r"^\s*v?(\d+(?:\.\d+){0,2})(-[\w.-]+)?(?:\+[\w.-]+)?\s*$",
        str(version),
    )
    if not m or not vm or (vm.group(2) and not m.group(3)):
        raise ChartError(
            f"semverCompare: unsupported constraint {constraint!r} vs {version!r} "
            "(the subset models single [>=|<=|>|<|=|!=]x.y.z constraints; "
            "prerelease versions only against a '-0'-suffixed constraint)"
        )
    op = m.group(1) or "="
    # the numeric-core comparison is only sound for a '-0' (minimal
    # prerelease) constraint under >= and < — under =, !=, > and <= the
    # version's own prerelease ordering would decide, which the subset
    # doesn't model
    if m.group(3) and op not in (">=", "<"):
        raise ChartError(
            f"semverCompare: unsupported constraint {constraint!r} "
            "('-0' prerelease constraints are only modeled under >= and <)"
        )
    want = tuple(int(x) for x in m.group(2).split("."))
    have = tuple(int(x) for x in vm.group(1).split("."))[: len(want)]
    have = have + (0,) * (len(want) - len(have))
    return {
        "=": have == want, "!=": have != want,
        ">": have > want, ">=": have >= want,
        "<": have < want, "<=": have <= want,
    }[op]


def _make_funcs(render_template, render_string):
    def required(msg, v):
        if v is None or v == "":
            raise ChartError(f"required value missing: {msg}")
        return v

    return {
        "default": lambda d, v=None: v if _truthy(v) else d,
        "quote": lambda *a: " ".join(f'"{_go_str(x)}"' for x in a),
        "squote": lambda *a: " ".join(f"'{_go_str(x)}'" for x in a),
        "upper": lambda s: _go_str(s).upper(),
        "lower": lambda s: _go_str(s).lower(),
        "title": lambda s: _go_str(s).title(),
        "trim": lambda s: _go_str(s).strip(),
        "trunc": lambda n, s: _go_str(s)[: int(n)]
        if int(n) >= 0
        else _go_str(s)[int(n) :],
        "trimSuffix": lambda suf, s: _go_str(s)[: -len(suf)]
        if suf and _go_str(s).endswith(suf)
        else _go_str(s),
        "trimPrefix": lambda pre, s: _go_str(s)[len(pre) :]
        if pre and _go_str(s).startswith(pre)
        else _go_str(s),
        "replace": lambda old, new, s: _go_str(s).replace(old, new),
        "indent": lambda n, s: _indent(int(n), _go_str(s)),
        "nindent": lambda n, s: "\n" + _indent(int(n), _go_str(s)),
        "toYaml": _to_yaml,
        "printf": _printf,
        "print": lambda *a: "".join(_go_str(x) for x in a),
        "eq": lambda a, *b: any(a == x for x in b),
        "ne": lambda a, b: a != b,
        "lt": lambda a, b: a < b,
        "le": lambda a, b: a <= b,
        "gt": lambda a, b: a > b,
        "ge": lambda a, b: a >= b,
        "and": lambda *a: next((x for x in a if not _truthy(x)), a[-1]),
        "or": lambda *a: next((x for x in a if _truthy(x)), a[-1]),
        "not": lambda v: not _truthy(v),
        "empty": lambda v: not _truthy(v),
        "coalesce": lambda *a: next((x for x in a if _truthy(x)), None),
        "required": required,
        "len": lambda v: len(v),
        "include": render_template,
        "tpl": render_string,
        "list": lambda *a: list(a),
        "dict": lambda *a: {a[i]: a[i + 1] for i in range(0, len(a), 2)},
        "add": lambda *a: sum(a),
        "sub": lambda a, b: a - b,
        "int": lambda v: int(v),
        "toString": _go_str,
        "kindIs": lambda kind, v: {
            "map": isinstance(v, dict),
            "slice": isinstance(v, (list, tuple)),
            "string": isinstance(v, str),
            "bool": isinstance(v, bool),
        }.get(kind, False),
        "hasKey": lambda d, k: isinstance(d, dict) and k in d,
        "contains": lambda sub, s: sub in _go_str(s),
        "semverCompare": _semver_compare,
    }


class _Renderer:
    def __init__(self, templates: Dict[str, list], root):
        self.templates = templates
        self.root = root
        self.funcs = _make_funcs(self._include, self._tpl)

    # include "name" dot → string
    def _include(self, name, dot=None):
        body = self.templates.get(name)
        if body is None:
            raise ChartError(f"undefined template {name!r}")
        ctx = _Ctx(self.root, dot if dot is not None else self.root, {"$": self.root}, self.templates)
        return self._render(body, ctx)

    # tpl "string" dot → re-parse and render the string as a template
    def _tpl(self, s, dot=None):
        body, _, _, _ = _parse(_tokenize(_go_str(s)), 0, self.templates, stop=())
        ctx = _Ctx(self.root, dot if dot is not None else self.root, {"$": self.root}, self.templates)
        return self._render(body, ctx)

    def _eval_segment(self, seg: str, ctx: _Ctx, piped, expr: str):
        toks: List[str] = []
        # group parenthesized sub-pipelines into single tokens
        depth, cur = 0, []
        for t in _TOKEN.findall(seg):
            if t == "(":
                if depth:
                    cur.append(t)
                depth += 1
            elif t == ")":
                depth -= 1
                if depth:
                    cur.append(t)
                else:
                    toks.append("(" + " ".join(cur) + ")")
                    cur = []
            elif depth:
                cur.append(t)
            else:
                toks.append(t)
        if depth:
            raise ChartError(f"unbalanced parens in {{{{ {expr} }}}}")

        def atom(tok):
            if tok.startswith("(") and tok.endswith(")"):
                return self._eval_pipeline(tok[1:-1], ctx)
            return _eval_atom(tok, ctx, expr)

        if not toks:
            raise ChartError(f"empty pipeline segment in {{{{ {expr} }}}}")
        head = toks[0]
        if head in self.funcs:
            args = [atom(t) for t in toks[1:]]
            if piped is not _NO_PIPE:
                args.append(piped)
            return self.funcs[head](*args)
        if len(toks) > 1:
            raise ChartError(
                f"unsupported function {head!r} in {{{{ {expr} }}}}"
            )
        return atom(head)

    def _eval_pipeline(self, expr: str, ctx: _Ctx):
        piped = _NO_PIPE
        for seg in _split_top(expr, "|"):
            seg = seg.strip()
            if not seg:
                continue
            piped = self._eval_segment(seg, ctx, piped, expr)
        return piped

    def _render(self, nodes, ctx: _Ctx) -> str:
        out: List[str] = []
        for node in nodes:
            if isinstance(node, _Text):
                out.append(node.s)
            elif isinstance(node, _Pipe):
                out.append(_go_str(self._eval_pipeline(node.expr, ctx)))
            elif isinstance(node, _Var):
                ctx.vars[node.name] = self._eval_pipeline(node.expr, ctx)
            elif isinstance(node, _If):
                done = False
                for cond, body in node.branches:
                    if _truthy(self._eval_pipeline(cond, ctx)):
                        out.append(self._render(body, ctx))
                        done = True
                        break
                if not done:
                    out.append(self._render(node.else_body, ctx))
            elif isinstance(node, _With):
                v = self._eval_pipeline(node.expr, ctx)
                if _truthy(v):
                    out.append(self._render(node.body, ctx.child(dot=v)))
                else:
                    out.append(self._render(node.else_body, ctx))
            elif isinstance(node, _Range):
                v = self._eval_pipeline(node.expr, ctx)
                items: List[Tuple[Any, Any]]
                if isinstance(v, dict):
                    items = sorted(v.items())  # Go ranges maps in key order
                elif isinstance(v, (list, tuple)):
                    items = list(enumerate(v))
                elif v is None:
                    items = []
                else:
                    raise ChartError(f"cannot range over {type(v).__name__}")
                if not items:
                    out.append(self._render(node.else_body, ctx))
                for k, item in items:
                    sub = ctx.child(dot=item)
                    if len(node.decl) == 1:
                        sub.vars[node.decl[0]] = item
                    elif len(node.decl) == 2:
                        sub.vars[node.decl[0]] = k
                        sub.vars[node.decl[1]] = item
                    out.append(self._render(node.body, sub))
            elif isinstance(node, _Template):
                parts = _split_top(node.expr, " ")
                name = parts[0].strip().strip("\"'")
                dot_expr = " ".join(p for p in parts[1:] if p.strip())
                dot = self._eval_pipeline(dot_expr, ctx) if dot_expr else None
                out.append(self._include(name, dot))
        return "".join(out)


_NO_PIPE = object()


# ---------------------------------------------------------------------------
# chart loading (ProcessChart equivalents)
# ---------------------------------------------------------------------------


def _deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def render_chart(name: str, path: str, values_override: Optional[dict] = None) -> List[str]:
    """Chart dir → list of rendered YAML document strings (the manifests
    the reference's renderResources returns: NOTES.txt removed, empty
    manifests dropped — chart.go:104-140)."""
    chart_yaml = os.path.join(path, "Chart.yaml")
    values_yaml = os.path.join(path, "values.yaml")
    tmpl_dir = os.path.join(path, "templates")
    if not os.path.isdir(tmpl_dir):
        raise ChartError(f"{path}: no templates/ directory")
    chart_meta = {}
    if os.path.exists(chart_yaml):
        with open(chart_yaml) as f:
            chart_meta = yaml.safe_load(f) or {}
    # only application charts are installable (chart.go:66-73)
    ctype = chart_meta.get("type", "")
    if ctype not in ("", "application"):
        raise ChartError(f"{ctype} charts are not installable")
    values = {}
    if os.path.exists(values_yaml):
        with open(values_yaml) as f:
            values = yaml.safe_load(f) or {}
    if values_override:
        values = _deep_merge(values, values_override)
    root = {
        "Values": values,
        "Release": {
            "Name": name,
            "Namespace": "default",
            "Revision": 1,
            "Service": "Helm",
            "IsInstall": True,
            "IsUpgrade": False,
        },
        "Chart": {
            **chart_meta,
            # engine exposes metadata capitalized (Chart.Name etc.)
            "Name": chart_meta.get("name", name),
            "Version": chart_meta.get("version", ""),
            "AppVersion": chart_meta.get("appVersion", ""),
        },
        "Capabilities": {"KubeVersion": {"Version": "v1.20.5", "GitVersion": "v1.20.5", "Major": "1", "Minor": "20"}},
        "Template": {"BasePath": os.path.join(name, "templates")},
    }

    templates: Dict[str, list] = {}
    render_files: List[Tuple[str, list]] = []
    for fname in sorted(os.listdir(tmpl_dir)):
        fpath = os.path.join(tmpl_dir, fname)
        if not os.path.isfile(fpath):
            continue
        is_helper = fname.startswith("_")
        if not (fname.endswith((".yaml", ".yml", ".tpl", ".txt"))):
            continue
        with open(fpath) as f:
            tokens = _tokenize(f.read())
        nodes, _, _, _ = _parse(tokens, 0, templates, stop=())
        # helpers contribute defines only; NOTES.txt is rendered then
        # discarded by the reference — skip it outright
        if is_helper or fname == "NOTES.txt":
            continue
        render_files.append((fname, nodes))

    renderer = _Renderer(templates, root)
    docs = []
    for fname, nodes in render_files:
        ctx = _Ctx(root, root, {"$": root}, templates)
        try:
            text = renderer._render(nodes, ctx)
        except ChartError as e:
            raise ChartError(f"{fname}: {e}") from None
        if text.strip():
            docs.append(text)
    return docs


def chart_objects(name: str, path: str) -> List[dict]:
    objs = []
    for doc in render_chart(name, path):
        for obj in yaml.safe_load_all(doc):
            if isinstance(obj, dict) and obj.get("kind"):
                objs.append(obj)
    return objs
