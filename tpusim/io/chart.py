"""Minimal Helm-chart rendering for app manifests (ref: pkg/chart/chart.go
ProcessChart, which renders a chart through the Helm engine to YAML docs).

This framework supports the common simulator use-case — charts whose
templates only interpolate scalar values — without a Go-template engine:
`{{ .Values.x.y }}`, `{{ .Release.Name }}`, `{{ .Chart.Name }}` and the
`default`/`quote` pipe forms are substituted; any other template directive
raises ChartError with a pointer to pre-render the chart with `helm
template` instead (the rendered YAML is then a plain app path).
"""

from __future__ import annotations

import os
import re
from typing import List

import yaml


class ChartError(ValueError):
    pass


_EXPR = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}")
_COMMENT = re.compile(r"\{\{-?\s*/\*.*?\*/\s*-?\}\}", re.S)


def _lookup(path: str, scope: dict):
    cur = scope
    for part in path.split("."):
        if not part:
            continue
        if not isinstance(cur, dict) or part not in cur:
            raise KeyError(path)
        cur = cur[part]
    return cur


def _render_expr(expr: str, scope: dict) -> str:
    # pipe forms: `.Values.x | default "v"`, `... | quote`
    parts = [p.strip() for p in expr.split("|")]
    head = parts[0]
    if not head.startswith("."):
        raise ChartError(f"unsupported template directive: {{{{ {expr} }}}}")
    try:
        val = _lookup(head[1:], scope)
    except KeyError:
        val = None
    for pipe in parts[1:]:
        if pipe.startswith("default"):
            if val in (None, ""):
                arg = pipe[len("default") :].strip().strip("\"'")
                val = arg
        elif pipe == "quote":
            if val is None:
                raise ChartError(f"undefined value: {{{{ {expr} }}}}")
            val = f'"{val}"'
        else:
            raise ChartError(f"unsupported pipe: {pipe}")
    if val is None:
        raise ChartError(f"undefined value: {{{{ {expr} }}}}")
    return str(val)


def render_chart(name: str, path: str) -> List[str]:
    """Chart dir → list of rendered YAML document strings."""
    chart_yaml = os.path.join(path, "Chart.yaml")
    values_yaml = os.path.join(path, "values.yaml")
    tmpl_dir = os.path.join(path, "templates")
    if not os.path.isdir(tmpl_dir):
        raise ChartError(f"{path}: no templates/ directory")
    chart_meta = {}
    if os.path.exists(chart_yaml):
        with open(chart_yaml) as f:
            chart_meta = yaml.safe_load(f) or {}
    values = {}
    if os.path.exists(values_yaml):
        with open(values_yaml) as f:
            values = yaml.safe_load(f) or {}
    scope = {
        "Values": values,
        "Release": {"Name": name, "Namespace": "default"},
        "Chart": {"Name": chart_meta.get("name", name),
                  "Version": chart_meta.get("version", "")},
    }
    docs = []
    for fname in sorted(os.listdir(tmpl_dir)):
        if not fname.endswith((".yaml", ".yml")):
            continue
        if fname.startswith("_"):  # helpers need real Go templates
            raise ChartError(f"{fname}: helper templates unsupported")
        with open(os.path.join(tmpl_dir, fname)) as f:
            text = _COMMENT.sub("", f.read())
        rendered = _EXPR.sub(lambda m: _render_expr(m.group(1), scope), text)
        docs.append(rendered)
    return docs


def chart_objects(name: str, path: str) -> List[dict]:
    objs = []
    for doc in render_chart(name, path):
        for obj in yaml.safe_load_all(doc):
            if isinstance(obj, dict) and obj.get("kind"):
                objs.append(obj)
    return objs
