"""Kubernetes-YAML cluster/workload ingestion.

The reference feeds the simulator with plain k8s manifests: Node and Pod
YAMLs for the cluster (example/new1/test-cluster/), plus workload objects
(Deployment/ReplicaSet/ReplicationController/Job/CronJob/StatefulSet/
DaemonSet) that are expanded into pods host-side before scheduling
(ref: pkg/simulator/utils.go:142-186 GetObjectFromYamlContent +
pkg/utils/utils.go:150-421 MakeValidPodsBy*). This module is the TPU-native
equivalent: manifests parse straight into the host-side NodeRow/PodRow
structs that tpusim.io.trace lowers to device arrays — there is no object
graph or fake API server in between.

Resource conventions mirror the reference's annotation schema
(open-gpu-share/utils/const.go:4-14):
  alibabacloud.com/gpu-milli      per-GPU milli request (pods)
  alibabacloud.com/gpu-count      number of GPUs (pods + node allocatable)
  alibabacloud.com/gpu-card-model GPU model (node label / pod annotation)
  alibabacloud.com/cpu-model      CPU model (node label / pod annotation)
  alibabacloud.com/creation-time  unix seconds (event ordering)
  alibabacloud.com/deletion-time  unix seconds (deletion events)
  simon/pod-unscheduled           pod failed in the snapshot it came from
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import yaml

from tpusim.io.trace import NodeRow, PodRow

ANNO_GPU_MILLI = "alibabacloud.com/gpu-milli"
ANNO_GPU_COUNT = "alibabacloud.com/gpu-count"
ANNO_GPU_MODEL = "alibabacloud.com/gpu-card-model"
ANNO_CPU_MODEL = "alibabacloud.com/cpu-model"
ANNO_CREATION_TIME = "alibabacloud.com/creation-time"
ANNO_DELETION_TIME = "alibabacloud.com/deletion-time"
ANNO_UNSCHEDULED = "simon/pod-unscheduled"
LABEL_HOSTNAME = "kubernetes.io/hostname"
from tpusim.io.storage import (
    ANNO_NODE_LOCAL_STORAGE,
    ANNO_POD_LOCAL_STORAGE,
    maybe_json,
)

_BINARY_SUFFIX = {"Ki": 1024, "Mi": 1024**2, "Gi": 1024**3, "Ti": 1024**4}
_DECIMAL_SUFFIX = {"k": 10**3, "K": 10**3, "M": 10**6, "G": 10**9, "T": 10**12}


def parse_cpu_milli(q) -> int:
    """k8s CPU quantity → milli-cores ("4" → 4000, "250m" → 250)."""
    if q is None:
        return 0
    s = str(q).strip()
    if not s:
        return 0
    if s.endswith("m"):
        return int(float(s[:-1]))
    return int(float(s) * 1000)


def parse_mem_mib(q) -> int:
    """k8s memory quantity → MiB ("256000Mi" → 256000, "1Gi" → 1024)."""
    if q is None:
        return 0
    s = str(q).strip()
    if not s:
        return 0
    for suf, mult in _BINARY_SUFFIX.items():
        if s.endswith(suf):
            return int(float(s[: -len(suf)]) * mult) // (1024**2)
    for suf, mult in _DECIMAL_SUFFIX.items():
        if s.endswith(suf):
            return int(float(s[: -len(suf)]) * mult) // (1024**2)
    return int(float(s)) // (1024**2)


def _meta(obj: dict) -> Tuple[str, str, dict, dict]:
    meta = obj.get("metadata") or {}
    return (
        meta.get("name", ""),
        meta.get("namespace", ""),
        meta.get("annotations") or {},
        meta.get("labels") or {},
    )


def node_from_k8s(obj: dict) -> NodeRow:
    """corev1.Node manifest → NodeRow (ref: utils/node.go:6-40 getters;
    GPU count from allocatable `alibabacloud.com/gpu-count`, model from the
    gpu-card-model label)."""
    name, _, annotations, labels = _meta(obj)
    status = obj.get("status") or {}
    alloc = status.get("allocatable") or status.get("capacity") or {}
    gpu = int(float(alloc.get(ANNO_GPU_COUNT, 0) or 0))
    model = labels.get(ANNO_GPU_MODEL, "") or annotations.get(ANNO_GPU_MODEL, "")
    cpu_model = labels.get(ANNO_CPU_MODEL, "") or annotations.get(ANNO_CPU_MODEL, "")
    storage = maybe_json(annotations.get(ANNO_NODE_LOCAL_STORAGE))
    return NodeRow(
        name=name,
        cpu_milli=parse_cpu_milli(alloc.get("cpu")),
        memory_mib=parse_mem_mib(alloc.get("memory")),
        gpu=gpu,
        model=model if gpu > 0 else "",
        cpu_model=cpu_model,
        local_storage=storage,
    )


def _container_requests(spec: dict) -> Tuple[int, int]:
    """Sum of container requests, falling back to the limit PER RESOURCE
    when a request is unset (k8s defaulting semantics, as in
    resourcehelper.PodRequestsAndLimits)."""
    cpu = mem = 0
    for c in spec.get("containers") or []:
        res = c.get("resources") or {}
        req = res.get("requests") or {}
        lim = res.get("limits") or {}
        cpu += parse_cpu_milli(req.get("cpu", lim.get("cpu")))
        mem += parse_mem_mib(req.get("memory", lim.get("memory")))
    return cpu, mem


def pod_from_k8s(obj: dict) -> PodRow:
    """corev1.Pod manifest → PodRow (ref: utils.GetPodResource,
    pkg/utils/utils.go:1008-1029 + MakeValidPod sanitization :424-506 —
    sanitization here is implicit: only the scheduling-relevant fields
    survive the parse)."""
    name, namespace, annotations, _ = _meta(obj)
    spec = obj.get("spec") or {}
    cpu, mem = _container_requests(spec)
    num_gpu = int(float(annotations.get(ANNO_GPU_COUNT, 0) or 0))
    gpu_milli = int(float(annotations.get(ANNO_GPU_MILLI, 0) or 0)) if num_gpu else 0
    gpu_milli = max(0, min(gpu_milli, 1000))
    gpu_spec = annotations.get(ANNO_GPU_MODEL, "") if num_gpu else ""
    selector = spec.get("nodeSelector") or {}
    pinned = spec.get("nodeName") or selector.get(LABEL_HOSTNAME)
    meta = obj.get("metadata") or {}
    owners = meta.get("ownerReferences") or []
    owner_kind = owners[0].get("kind", "") if owners else ""
    return PodRow(
        name=f"{namespace}/{name}" if namespace else name,
        cpu_milli=cpu,
        memory_mib=mem,
        num_gpu=num_gpu,
        gpu_milli=gpu_milli,
        gpu_spec=gpu_spec,
        creation_time=int(float(annotations.get(ANNO_CREATION_TIME, 0) or 0)),
        deletion_time=int(float(annotations.get(ANNO_DELETION_TIME, 0) or 0)),
        pinned_node=pinned,
        unscheduled=str(annotations.get(ANNO_UNSCHEDULED, "")).lower() == "true",
        node_selector=dict(selector) or None,
        tolerations=bool(spec.get("tolerations")),
        local_storage=maybe_json(annotations.get(ANNO_POD_LOCAL_STORAGE)),
        # DaemonSet-owned raw pods are excluded from the schedulable
        # workload, like GetValidPodExcludeDaemonSet's ownerReference check
        workload_kind=owner_kind,
        workload_name=owners[0].get("name", "") if owners else "",
    )


def _pods_from_template(
    obj: dict, kind: str, replicas_field: str = "replicas"
) -> List[PodRow]:
    """Workload object → `replicas` PodRows named `<name>-<ordinal>`
    (ref: MakeValidPodsByReplicaSet et al., pkg/utils/utils.go:155-285;
    StatefulSet ordinal naming :279 generalized to all kinds — names only
    feed reporting, not placement)."""
    name, namespace, _, _ = _meta(obj)
    spec = obj.get("spec") or {}
    raw = spec.get(replicas_field)
    replicas = 1 if raw is None else int(raw)  # explicit 0 means zero pods
    template = spec.get("template") or {}
    pods = []
    for ordinal in range(replicas):
        t = {
            "metadata": {
                **(template.get("metadata") or {}),
                "name": f"{name}-{ordinal}",
                "namespace": namespace,
            },
            "spec": template.get("spec") or {},
        }
        p = pod_from_k8s(t)
        p.workload_kind = kind
        p.workload_name = name
        pods.append(p)
    return pods


def pods_from_workload(obj: dict) -> Optional[List[PodRow]]:
    """Expand one workload manifest into pods; None if `obj` is not a
    workload kind (ref: GetValidPodExcludeDaemonSet dispatch,
    pkg/simulator/utils.go:79-139)."""
    kind = obj.get("kind", "")
    if kind in ("Deployment", "ReplicaSet", "ReplicationController"):
        return _pods_from_template(obj, kind)
    if kind == "StatefulSet":
        return _pods_from_template(obj, kind)
    if kind == "Job":
        return _pods_from_template(obj, kind, replicas_field="completions")
    if kind == "CronJob":
        # CronJob → one manual Job instantiation (utils.go:246-260)
        name, namespace, _, _ = _meta(obj)
        job_spec = ((obj.get("spec") or {}).get("jobTemplate") or {}).get("spec") or {}
        job = {
            "kind": "Job",
            "metadata": {"name": name, "namespace": namespace},
            "spec": job_spec,
        }
        return _pods_from_template(job, "Job", replicas_field="completions")
    return None


def daemonset_pods(obj: dict, node_names: Sequence[str]) -> List[PodRow]:
    """DaemonSet → one pod per node, pinned by hostname affinity
    (ref: MakeValidPodByDaemonset + node pinning, pkg/utils/utils.go:884-929;
    driven per-node from core.go:117-123)."""
    name, namespace, _, _ = _meta(obj)
    spec = obj.get("spec") or {}
    template = spec.get("template") or {}
    pods = []
    for node in node_names:
        t = {
            "metadata": {
                **(template.get("metadata") or {}),
                "name": f"{name}-{node}",
                "namespace": namespace,
            },
            "spec": dict(template.get("spec") or {}),
        }
        p = pod_from_k8s(t)
        p.pinned_node = node
        p.workload_kind = "DaemonSet"
        p.workload_name = name
        pods.append(p)
    return pods


def yaml_files_in_dir(path: str) -> List[str]:
    """Recursive *.yaml/*.yml walk, sorted for determinism
    (ref: GetYamlContentFromDirectory, pkg/utils/utils.go)."""
    out = []
    for root, _, files in os.walk(path):
        for f in sorted(files):
            if f.endswith((".yaml", ".yml")):
                out.append(os.path.join(root, f))
    return sorted(out)


def load_objects(paths: Iterable[str]) -> List[dict]:
    objs = []
    for p in paths:
        with open(p) as f:
            for doc in yaml.safe_load_all(f):
                if isinstance(doc, dict) and doc.get("kind"):
                    objs.append(doc)
    return objs


class ClusterResource:
    """Typed buckets of parsed manifests — the array-era stand-in for
    simulator.ResourceTypes (ref: pkg/simulator/core.go ResourceTypes)."""

    def __init__(self):
        self.nodes: List[NodeRow] = []
        self.pods: List[PodRow] = []
        self.daemonsets: List[dict] = []
        self.other: List[dict] = []  # PDB/Service/StorageClass/PVC/… (inert)

    @property
    def node_names(self) -> List[str]:
        return [n.name for n in self.nodes]

    def workload_pods(self) -> List[PodRow]:
        """Pods to schedule, excluding DaemonSet-owned ones
        (ref: GetValidPodExcludeDaemonSet, pkg/simulator/utils.go:79-139)."""
        return [p for p in self.pods if p.workload_kind != "DaemonSet"]

    def daemonset_pods(self) -> List[PodRow]:
        out = []
        for ds in self.daemonsets:
            out.extend(daemonset_pods(ds, self.node_names))
        return out


def load_cluster_from_dir(path: str) -> ClusterResource:
    """YAML dir → ClusterResource (ref:
    simulator.CreateClusterResourceFromClusterConfig, simulator.go:880-895;
    per-node `<name>.json` storage files attach open-local inventories like
    MatchAndSetLocalStorageAnnotationOnNode)."""
    if not os.path.isdir(path):
        raise FileNotFoundError(f"cluster config directory not found: {path}")
    res = load_cluster_from_objects(load_objects(yaml_files_in_dir(path)))
    from tpusim.io.storage import match_local_storage_files

    storage = match_local_storage_files(res.node_names, path)
    for n in res.nodes:
        if n.name in storage and n.local_storage is None:
            n.local_storage = storage[n.name]
    return res


ANNO_CONFIG_MIRROR = "kubernetes.io/config.mirror"
ANNO_CONFIG_SOURCE = "kubernetes.io/config.source"


def is_static_pod(obj: dict) -> bool:
    """A pod whose config source is not the API server (ref:
    kubetypes.IsStaticPod, used by CreateClusterResourceFromClient at
    simulator.go:766-771 to decide which raw pods survive ingestion)."""
    ann = (obj.get("metadata") or {}).get("annotations") or {}
    src = ann.get(ANNO_CONFIG_SOURCE, "")
    return bool(src and src != "api") or ANNO_CONFIG_MIRROR in ann


def load_cluster_from_dump(path: str) -> ClusterResource:
    """Real-cluster snapshot ingestion: a `kubectl get
    nodes,pods,deployments,... -o yaml` dump file (or a directory of such
    files) → ClusterResource.

    Preserves the capability of the reference's kubeConfig mode
    (CreateClusterResourceFromClient, simulator.go:746-830) without a live
    API server, with the same object semantics: every Node is kept; raw
    Pods are kept only when static (non-static pods are dropped because the
    workload objects re-expand into fresh pods that the simulation
    re-schedules — simulator.go:759-771); workload controllers
    (Deployment/RS/RC/Job/CronJob/StatefulSet/DaemonSet) expand as usual.

    `kind: List` envelopes (kubectl's multi-object output) are flattened.
    A kubeconfig credential file is rejected with guidance — it names a
    live cluster this environment cannot reach.
    """
    paths = yaml_files_in_dir(path) if os.path.isdir(path) else [path]
    objs: List[dict] = []
    for obj in load_objects(paths):
        if obj.get("kind") == "List":
            objs.extend(
                i
                for i in obj.get("items") or []
                if isinstance(i, dict) and i.get("kind")
            )
        elif obj.get("kind") == "Config" and "clusters" in obj:
            raise ValueError(
                f"{path} is a kubeconfig credential file, not a dump; use "
                "tpusim.io.kube_client.load_cluster_from_client (the "
                "applier's kubeConfig path routes there automatically), or "
                "ingest a dump: kubectl get nodes,pods,deployments,"
                "statefulsets,daemonsets -A -o yaml > dump.yaml"
            )
        else:
            objs.append(obj)
    objs = _filter_cluster_objects(objs)
    return load_cluster_from_objects(objs)


def _filter_cluster_objects(objs: Sequence[dict]) -> List[dict]:
    """CreateClusterResourceFromClient's object-filtering rules applied to
    an already-listed object set (simulator.go:759-771, 830-836, 881-891):
    raw Pods only when static, no Deployment-owned ReplicaSets, no
    CronJob-owned Jobs — a full `kubectl get -A` dump contains both owners
    and their children, which would otherwise double-expand workload pods."""

    def owned_by(obj, kind):
        return any(
            ref.get("kind") == kind
            for ref in (obj.get("metadata") or {}).get("ownerReferences") or []
        )

    out = []
    for o in objs:
        kind = o.get("kind")
        if kind == "Pod" and not is_static_pod(o):
            continue
        if kind == "ReplicaSet" and owned_by(o, "Deployment"):
            continue
        if kind == "Job" and owned_by(o, "CronJob"):
            continue
        out.append(o)
    return out


def load_cluster_from_objects(objs: Sequence[dict]) -> ClusterResource:
    res = ClusterResource()
    for obj in objs:
        kind = obj.get("kind", "")
        if kind == "Node":
            res.nodes.append(node_from_k8s(obj))
        elif kind == "Pod":
            res.pods.append(pod_from_k8s(obj))
        elif kind == "DaemonSet":
            res.daemonsets.append(obj)
        else:
            pods = pods_from_workload(obj)
            if pods is not None:
                res.pods.extend(pods)
            else:
                res.other.append(obj)
    res.nodes.sort(key=lambda n: n.name)  # name-sort before the random
    # tie-break prefix permutation (simulator.go:584-588)
    return res
