"""Live-cluster ingestion: a thin Kubernetes API client over stdlib HTTP.

Re-creates the reference's kubeConfig mode
(`CreateClusterResourceFromClient`, pkg/simulator/simulator.go:746-878):
connect to the API server named by a kubeconfig credential file, list the
same 13 resource collections, and apply the same object-filtering rules —
every Node kept, raw Pods kept only when static (workload objects
re-expand fresh pods the simulation re-schedules, simulator.go:759-771),
Deployment-owned ReplicaSets and CronJob-owned Jobs skipped
(simulator.go:830-836, 881-891 ownedByDeployment/ownedByCronJob).

No kubernetes-client dependency: kubeconfig parsing (server URL, CA bundle,
client cert/key, bearer token, exec credential plugins per the client-go
ExecCredential contract) + urllib over TLS is all the List calls need.
Group/version fallbacks cover both the reference's k8s v1.20 API surface
(policy/v1beta1, batch/v1beta1 CronJobs) and current clusters (policy/v1,
batch/v1). Only legacy auth-provider users (in-process Go plugins with no
external contract) are rejected, with guidance.

Tested against a recorded API fixture (tests/test_kube_client.py spins a
local HTTP server replaying canned list responses) — no live cluster
required, same as the rest of the suite.
"""

from __future__ import annotations

import base64
import json
import os
import re
import ssl
import tempfile
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence

import yaml


# Clock-skew margin for exec-credential expirationTimestamp checks:
# stamps stale by no more than this many seconds are accepted (client-go
# parity — upstream uses the stamp only for refresh scheduling). Override
# with TPUSIM_EXEC_CRED_SKEW_S for hosts with worse clock discipline.
EXEC_CRED_SKEW_MARGIN_S = 30.0

# Transient-failure retry policy for the List calls (client-go's default
# rest client retries connection resets and Retry-After'd statuses; a
# single raw urlopen used to turn one flaky LB hop into a failed
# ingestion). Attempts are capped-exponential with jitter; 429/5xx honor
# a server Retry-After header. TPUSIM_HTTP_RETRIES overrides the total
# attempt count (default 3; 1 disables retrying).
HTTP_RETRY_ATTEMPTS = 3
HTTP_RETRY_BASE_S = 0.5
HTTP_RETRY_CAP_S = 8.0
HTTP_RETRY_STATUSES = frozenset({429} | set(range(500, 600)))


def retryable_conn_excs() -> tuple:
    """The connection-level exception vocabulary every HTTP retry loop
    in the tree shares (kube_client.get, svc.client, svc.fleet._post):
    resets, refusals, half-closed keep-alives, and urllib's URLError
    wrapper. A fleet worker retries REFUSED too — a restarting
    coordinator refuses connections for a moment, and a worker must
    treat that as a stall, not a death (the submit CLI, facing a human,
    fails fast on refused instead; it filters before calling)."""
    import http.client

    return (ConnectionResetError, ConnectionRefusedError,
            http.client.RemoteDisconnected, urllib.error.URLError,
            TimeoutError)


def is_retryable_status(code: int) -> bool:
    """True for HTTP statuses the shared backoff schedule retries:
    429 and every 5xx (the Retry-After-bearing family)."""
    return int(code) in HTTP_RETRY_STATUSES


def _retry_attempts() -> int:
    try:
        return max(1, int(os.environ.get("TPUSIM_HTTP_RETRIES",
                                         HTTP_RETRY_ATTEMPTS)))
    except ValueError:
        return HTTP_RETRY_ATTEMPTS


def _retry_delay_s(attempt: int, retry_after=None) -> float:
    """Sleep before retry `attempt` (1-based count of failures so far):
    a server-provided Retry-After wins (delta-seconds form; HTTP-date
    values fall back to the computed backoff), else capped exponential
    base*2^(attempt-1) with half-magnitude jitter so a fleet of clients
    does not re-dogpile the API server in lockstep."""
    import random

    if retry_after is not None:
        try:
            return max(0.0, min(float(retry_after), 4 * HTTP_RETRY_CAP_S))
        except (TypeError, ValueError):
            pass  # HTTP-date form: not worth a date parser here
    delay = min(HTTP_RETRY_BASE_S * (2 ** (attempt - 1)), HTTP_RETRY_CAP_S)
    return delay * (0.5 + 0.5 * random.random())


def with_backoff(call, max_attempts: int = 8, stop_event=None):
    """Drive one HTTP call on the SHARED capped-exponential-backoff-
    with-jitter schedule (ISSUE 14 satellite finishing what PR 13
    started): the generic retry loop the fleet's worker POSTs / byte
    uploads and the scheduler-extender round-trips ride. KubeClient.get
    keeps its own loop over the SAME primitives (_retry_delay_s /
    is_retryable_status) because its 404/403-are-answers semantics wrap
    the status handling differently. `call()` returns (code, headers,
    body); connection-level errors (retryable_conn_excs — including
    REFUSED: a restarting server refuses for a moment, and to a retrying
    client that is a stall, not a death) and 429/5xx answers
    (is_retryable_status) are retried honoring a server Retry-After; the
    final attempt's answer (or exception) surfaces.

    `stop_event` aborts the RETRY schedule (the last answer surfaces at
    once and backoff sleeps wake early) — a SIGTERM'd worker whose
    draining coordinator answers 503 + Retry-After must exit its idle
    claim loop promptly, not ride out eight 2-second retries first."""
    import time

    def stopped():
        return stop_event is not None and stop_event.is_set()

    def wait(delay):
        if stop_event is not None:
            stop_event.wait(delay)
        else:
            time.sleep(delay)

    for attempt in range(1, max_attempts + 1):
        try:
            code, headers, body = call()
        except retryable_conn_excs():
            if attempt >= max_attempts or stopped():
                raise
            wait(_retry_delay_s(attempt))
            continue
        if (is_retryable_status(code) and attempt < max_attempts
                and not stopped()):
            wait(_retry_delay_s(
                attempt, (headers or {}).get("Retry-After")
            ))
            continue
        return code, headers, body


def parse_url_list(urls) -> List[str]:
    """A comma-separated coordinator list (`--join u1,u2` /
    `submit --url u1,u2`, ISSUE 17) → ordered, deduped URL list with
    trailing slashes trimmed. Accepts a single URL, a comma string, or
    an iterable; raises ValueError on an empty result so a typo'd flag
    fails loudly at startup, not as a mid-sweep stall."""
    if isinstance(urls, str):
        parts = urls.split(",")
    else:
        parts = list(urls or [])
    out: List[str] = []
    for p in parts:
        p = str(p).strip().rstrip("/")
        if p and p not in out:
            out.append(p)
    if not out:
        raise ValueError(f"no coordinator URLs in {urls!r}")
    return out


class KubeClientError(RuntimeError):
    pass


# (list path candidates, singular kind) — first candidate that doesn't 404
# wins; mirrors the reference's list order (simulator.go:750-878)
LIST_ENDPOINTS = [
    (["/api/v1/nodes"], "Node"),
    (["/api/v1/pods"], "Pod"),
    (
        [
            "/apis/policy/v1beta1/poddisruptionbudgets",
            "/apis/policy/v1/poddisruptionbudgets",
        ],
        "PodDisruptionBudget",
    ),
    (["/api/v1/services"], "Service"),
    (["/apis/storage.k8s.io/v1/storageclasses"], "StorageClass"),
    (["/api/v1/persistentvolumeclaims"], "PersistentVolumeClaim"),
    (["/api/v1/replicationcontrollers"], "ReplicationController"),
    (["/apis/apps/v1/deployments"], "Deployment"),
    (["/apis/apps/v1/replicasets"], "ReplicaSet"),
    (["/apis/apps/v1/statefulsets"], "StatefulSet"),
    (["/apis/apps/v1/daemonsets"], "DaemonSet"),
    (
        ["/apis/batch/v1beta1/cronjobs", "/apis/batch/v1/cronjobs"],
        "CronJob",
    ),
    (["/apis/batch/v1/jobs"], "Job"),
]


def _run_exec_plugin(spec: dict, kubeconfig_path: str, cluster: dict = None):
    """Run a kubeconfig exec credential plugin per the client-go
    ExecCredential contract (client.authentication.k8s.io): invoke
    `command args...` with the configured env plus KUBERNETES_EXEC_INFO,
    parse the ExecCredential JSON it prints, and return
    (token, client_cert_pem, client_key_pem) — whichever the status
    carries. The reference gets this behavior from client-go inside
    clientcmd.BuildConfigFromFlags (utils.go:855)."""
    import subprocess

    command = spec.get("command")
    if not command:
        raise KubeClientError(
            f"kubeconfig {kubeconfig_path}: user.exec has no command"
        )
    api_version = spec.get("apiVersion") or "client.authentication.k8s.io/v1"
    env = dict(os.environ)
    for e in spec.get("env") or []:
        if e.get("name"):
            v = e.get("value")
            # only an explicit null means empty (0/false pass as "0"/"False")
            env[e["name"]] = "" if v is None else str(v)
    exec_spec: dict = {"interactive": False}
    if spec.get("provideClusterInfo") and cluster is not None:
        # client-go passes the target cluster to the plugin when asked
        # (ExecConfig.ProvideClusterInfo -> spec.cluster in the handshake)
        info = {"server": cluster.get("server", "")}
        if cluster.get("certificate-authority-data") is not None:
            info["certificate-authority-data"] = cluster[
                "certificate-authority-data"
            ]
        if cluster.get("insecure-skip-tls-verify") is not None:
            info["insecure-skip-tls-verify"] = cluster[
                "insecure-skip-tls-verify"
            ]
        exec_spec["cluster"] = info
    env["KUBERNETES_EXEC_INFO"] = json.dumps(
        {
            "apiVersion": api_version,
            "kind": "ExecCredential",
            "spec": exec_spec,
        }
    )
    argv = [command] + [str(a) for a in spec.get("args") or []]
    try:
        out = subprocess.run(
            argv, env=env, capture_output=True, text=True, timeout=60,
            check=True,
        ).stdout
    except OSError as e:
        # missing binary, missing exec bit, bad interpreter, ...
        raise KubeClientError(
            f"exec credential plugin {command!r} not runnable: {e} "
            f"(kubeconfig {kubeconfig_path})"
        ) from e
    except subprocess.CalledProcessError as e:
        raise KubeClientError(
            f"exec credential plugin {command!r} failed "
            f"(exit {e.returncode}): {e.stderr.strip()[:500]}"
        ) from e
    except subprocess.TimeoutExpired as e:
        raise KubeClientError(
            f"exec credential plugin {command!r} timed out"
        ) from e
    try:
        cred = json.loads(out)
    except json.JSONDecodeError as e:
        raise KubeClientError(
            f"exec credential plugin {command!r} printed invalid JSON: "
            f"{out.strip()[:200]}"
        ) from e
    if cred.get("kind") != "ExecCredential":
        raise KubeClientError(
            f"exec credential plugin {command!r} returned kind "
            f"{cred.get('kind')!r}, expected ExecCredential"
        )
    # client-go's exec authenticator rejects a response whose apiVersion
    # differs from the configured exec.apiVersion (exec.go newAuthenticator
    # response validation); mirror that instead of silently accepting
    if cred.get("apiVersion") and cred["apiVersion"] != api_version:
        raise KubeClientError(
            f"exec credential plugin {command!r} returned apiVersion "
            f"{cred['apiVersion']!r}, expected the configured {api_version!r}"
        )
    status = cred.get("status") or {}
    exp = status.get("expirationTimestamp")
    if exp:
        import datetime

        try:
            exp_dt = datetime.datetime.fromisoformat(
                str(exp).replace("Z", "+00:00")
            )
        except ValueError as e:
            raise KubeClientError(
                f"exec credential plugin {command!r} returned an unparseable "
                f"expirationTimestamp {exp!r}"
            ) from e
        if exp_dt.tzinfo is None:
            # RFC3339 always carries an offset; be lenient and read a naive
            # stamp as UTC rather than crash comparing naive vs aware
            exp_dt = exp_dt.replace(tzinfo=datetime.timezone.utc)
        # client-go only uses expirationTimestamp to decide when to re-run
        # the plugin and still sends the returned token; hard-failing on
        # any stale stamp would abort ingestion on mere clock skew between
        # this host and the plugin's clock. Allow a skew margin
        # (TPUSIM_EXEC_CRED_SKEW_S, default 30s) and only treat
        # credentials stale beyond it as fatal.
        try:
            margin_s = float(
                os.environ.get("TPUSIM_EXEC_CRED_SKEW_S",
                               EXEC_CRED_SKEW_MARGIN_S)
            )
        except ValueError:
            margin_s = EXEC_CRED_SKEW_MARGIN_S
        now = datetime.datetime.now(datetime.timezone.utc)
        if exp_dt + datetime.timedelta(seconds=margin_s) <= now:
            # a long-expired credential would only surface later as an
            # opaque 401; fail with the actual cause instead
            raise KubeClientError(
                f"exec credential plugin {command!r} returned an expired "
                f"credential (expirationTimestamp {exp}, more than "
                f"{margin_s:g}s stale)"
            )
    token = status.get("token")
    cert = status.get("clientCertificateData")
    key = status.get("clientKeyData")
    if bool(cert) != bool(key):
        raise KubeClientError(
            f"exec credential plugin {command!r} returned only one half of "
            "the clientCertificateData/clientKeyData pair"
        )
    if not token and not cert:
        raise KubeClientError(
            f"exec credential plugin {command!r} returned neither a token "
            "nor a client certificate/key pair"
        )
    return token, cert, key


class KubeClient:
    """Minimal GET-only client for one kubeconfig context."""

    def __init__(self, kubeconfig_path: str, timeout: float = 30.0):
        self.timeout = timeout
        self._tmp_files: List[str] = []
        with open(kubeconfig_path) as f:
            cfg = yaml.safe_load(f) or {}
        if "clusters" not in cfg:
            raise KubeClientError(
                f"{kubeconfig_path} is not a kubeconfig credential file"
            )
        ctx_name = cfg.get("current-context") or (
            (cfg.get("contexts") or [{}])[0].get("name")
        )
        ctx = next(
            (
                c.get("context", {})
                for c in cfg.get("contexts") or []
                if c.get("name") == ctx_name
            ),
            {},
        )
        cluster = next(
            (
                c.get("cluster", {})
                for c in cfg.get("clusters") or []
                if c.get("name") == ctx.get("cluster")
                or len(cfg.get("clusters", [])) == 1
            ),
            {},
        )
        user = next(
            (
                u.get("user", {})
                for u in cfg.get("users") or []
                if u.get("name") == ctx.get("user")
                or len(cfg.get("users", [])) == 1
            ),
            {},
        )
        self.server = (cluster.get("server") or "").rstrip("/")
        if not self.server:
            raise KubeClientError(
                f"kubeconfig {kubeconfig_path} names no cluster server"
            )
        self._headers = {"Accept": "application/json"}
        token = user.get("token")
        if not token and user.get("tokenFile"):
            token = open(user["tokenFile"]).read().strip()
        if not token and user.get("exec"):
            # GKE/EKS-style exec credential plugin: run the configured
            # binary per the client-go ExecCredential contract (the
            # reference's client runs these transparently through
            # clientcmd.BuildConfigFromFlags, utils.go:843-882)
            token, cert_data, key_data = _run_exec_plugin(
                user["exec"], kubeconfig_path, cluster
            )
            if cert_data:
                # re-encode the plugin's PEM as -data kubeconfig keys so
                # the cert path below is byte-for-byte the static-
                # credential flow (incl. temp-file cleanup); the double
                # transform is a few KB once per client
                user = dict(
                    user,
                    **{
                        "client-certificate-data": base64.b64encode(
                            cert_data.encode()
                        ).decode(),
                        "client-key-data": base64.b64encode(
                            key_data.encode()
                        ).decode(),
                    },
                )
        if token:
            self._headers["Authorization"] = f"Bearer {token}"
        elif user.get("auth-provider"):
            # legacy auth-provider plugins (in-process Go libraries in
            # client-go) have no external contract to speak — fail with
            # guidance instead of an opaque 401 from the server
            raise KubeClientError(
                f"kubeconfig {kubeconfig_path} authenticates via a legacy "
                "auth-provider, which this client does not run. Migrate "
                "the user to an exec plugin or mint a static token (e.g. "
                "`kubectl create token <sa>`) into the `token:` field."
            )
        self._ssl_ctx = self._make_ssl_context(cluster, user)

    def _materialize(self, data_b64: Optional[str], path: Optional[str]) -> Optional[str]:
        """Inline base64 material → temp file path (ssl wants files; 0600
        perms via NamedTemporaryFile). Tracked and removed in __del__ so
        decoded keys don't outlive the client on disk."""
        if path:
            return path
        if not data_b64:
            return None
        f = tempfile.NamedTemporaryFile("wb", delete=False, suffix=".pem")
        f.write(base64.b64decode(data_b64))
        f.close()
        self._tmp_files.append(f.name)
        return f.name

    def __del__(self):
        for p in getattr(self, "_tmp_files", []):
            try:
                os.unlink(p)
            except OSError:
                pass

    def _make_ssl_context(self, cluster: dict, user: dict):
        if self.server.startswith("http://"):
            return None
        ca = self._materialize(
            cluster.get("certificate-authority-data"),
            cluster.get("certificate-authority"),
        )
        ctx = ssl.create_default_context(cafile=ca)
        if cluster.get("insecure-skip-tls-verify"):
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        cert = self._materialize(
            user.get("client-certificate-data"), user.get("client-certificate")
        )
        key = self._materialize(
            user.get("client-key-data"), user.get("client-key")
        )
        if cert:
            ctx.load_cert_chain(cert, key)
        return ctx

    def get(self, path: str) -> dict:
        """One List call with transient-failure retries: 429/5xx responses
        (honoring Retry-After) and connection-level URLError/OSError get
        capped-exponential-backoff re-attempts (default 3 total,
        TPUSIM_HTTP_RETRIES override); 404/403 are semantic answers the
        group-version fallback machinery consumes and never retry."""
        import time

        req = urllib.request.Request(
            self.server + path, headers=self._headers
        )
        attempts = _retry_attempts()
        for attempt in range(1, attempts + 1):
            try:
                with urllib.request.urlopen(
                    req, timeout=self.timeout, context=self._ssl_ctx
                ) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    raise FileNotFoundError(path) from e
                if e.code == 403:
                    # RBAC denial; list_all treats this like 404 so a denied
                    # deprecated group-version (e.g. policy/v1beta1) can fall
                    # through to a listable candidate (e.g. policy/v1)
                    raise PermissionError(
                        f"GET {path}: HTTP 403 {e.reason}"
                    ) from e
                if e.code in HTTP_RETRY_STATUSES and attempt < attempts:
                    time.sleep(_retry_delay_s(
                        attempt, (e.headers or {}).get("Retry-After")
                    ))
                    continue
                raise KubeClientError(
                    f"GET {path} failed: HTTP {e.code} {e.reason}"
                    + (f" after {attempt} attempts" if attempt > 1 else "")
                ) from e
            except (urllib.error.URLError, OSError) as e:
                if attempt < attempts:
                    time.sleep(_retry_delay_s(attempt))
                    continue
                raise KubeClientError(
                    f"cannot reach API server {self.server}: {e}"
                    + (f" after {attempt} attempts" if attempt > 1 else "")
                ) from e

    def list_all(self, paths: Sequence[str], kind: str) -> List[dict]:
        """First listable endpoint → items with kind/apiVersion injected
        (k8s list responses carry the kind only on the envelope). 404 and
        403 both fall through to the next group-version candidate — a
        deprecated path may be RBAC-denied while the current one is
        listable; only all-candidates-failed aborts."""
        last: Optional[Exception] = None
        denied = False
        for path in paths:
            try:
                body = self.get(path)
            except FileNotFoundError as e:
                last = e
                continue
            except PermissionError as e:
                last, denied = e, True
                continue
            api_version = body.get("apiVersion") or "v1"
            items = []
            for item in body.get("items") or []:
                item = dict(item)
                item.setdefault("kind", kind)
                item.setdefault("apiVersion", api_version)
                items.append(item)
            return items
        if kind in ("PodDisruptionBudget", "CronJob") and not denied:
            return []  # optional API groups may be absent entirely
        raise KubeClientError(f"unable to list {kind}: {last}")

    def list_cluster_objects(self) -> List[dict]:
        """The 13 collections of CreateClusterResourceFromClient, with its
        filtering rules applied (static pods, ownership dedup) — the SAME
        filter the dump path runs (k8s_yaml._filter_cluster_objects), so
        live and offline ingestion can never disagree on survivors."""
        from tpusim.io.k8s_yaml import _filter_cluster_objects

        objs: List[dict] = []
        for paths, kind in LIST_ENDPOINTS:
            objs.extend(self.list_all(paths, kind))
        return _filter_cluster_objects(objs)


def is_kubeconfig_file(path: str) -> bool:
    """Heuristic the applier uses to pick client vs dump ingestion: a
    kubeconfig is `kind: Config` with a clusters list. Large files get a
    cheap head-of-file marker scan before the full parse: a positive marker
    (kind: Config / clusters:) routes to the kubeconfig parse, a dump
    marker (items: / any other top-level kind) skips the double parse, and
    only a head with neither — e.g. a kubeconfig whose huge users: block
    precedes both markers — pays the full parse to decide."""
    if not os.path.isfile(path):
        return False
    if os.path.getsize(path) > 1 << 20:
        try:
            with open(path, errors="replace") as f:
                head = f.read(64 << 10)
        except OSError:
            return False
        # kubeconfig top-level keys at column 0 (either may sit beyond the
        # head in a large file — key order varies); dumps are object
        # lists/streams whose top-level markers differ. A positive marker
        # routes to the kubeconfig parse, a dump marker (`items:` list /
        # `kind: List`/typed kinds) short-circuits to dump ingestion, and an
        # inconclusive head falls through to the full parse — so a >1MB
        # kubeconfig whose markers sit past the head (e.g. a huge `users:`
        # block with embedded certs first) is never misrouted.
        if not re.search(r"^(kind: Config\b|clusters:)", head, re.M):
            # any other top-level kind (List, Node, Pod, ... — incl. typed
            # YAML streams) or an items: list marks a dump without paying
            # the full multi-MB parse
            if re.search(r"^(items:|kind: \w+)", head, re.M):
                return False
    try:
        with open(path) as f:
            doc = yaml.safe_load(f)
    except (yaml.YAMLError, OSError, UnicodeDecodeError):
        # unreadable / binary / non-UTF8 → not a kubeconfig; let the dump
        # loader produce its own typed error
        return False
    return isinstance(doc, dict) and doc.get("kind") == "Config" and "clusters" in doc


def load_cluster_from_client(kubeconfig_path: str):
    """kubeconfig → live API server → ClusterResource
    (CreateClusterResourceFromClient semantics end to end)."""
    from tpusim.io.k8s_yaml import load_cluster_from_objects

    client = KubeClient(kubeconfig_path)
    return load_cluster_from_objects(client.list_cluster_objects())
