"""Trace data-prep tooling: CSV → YAML converters + experiment input trees.

Re-creates the reference's data pipeline surface
(`/root/reference/data/pod_csv_to_yaml.py:1-160`,
`/root/reference/data/prepare_input.sh`, and the node side its
`node_yaml/openb_node_list_gpu_node.yaml` artifact implies) so users who
regenerate the reference's YAML inputs from raw CSV traces find the same
tools here. This framework's simulator ingests CSV directly
(tpusim.io.trace), so these converters exist for (a) drop-in compatibility
with YAML-based cluster-config directories (`python -m tpusim apply`
consumes them via tpusim.io.k8s_yaml) and (b) interchange with the
reference itself.

Differences from the reference converter, both deliberate:
- creation/deletion-time annotations ARE emitted (the reference comments
  them out, pod_csv_to_yaml.py:117-118, losing event ordering); with them
  the YAML round-trips losslessly back to the CSV's scheduling-relevant
  fields — pinned by tests/test_data_prep.py.
- no pandas dependency (stdlib csv; the YAML emit order matches).
"""

from __future__ import annotations

import csv
import os
import shutil
from pathlib import Path
from typing import Iterable, List, Optional

import yaml

from tpusim.io.k8s_yaml import (
    ANNO_CPU_MODEL,
    ANNO_CREATION_TIME,
    ANNO_DELETION_TIME,
    ANNO_GPU_COUNT,
    ANNO_GPU_MILLI,
    ANNO_GPU_MODEL,
)

# the reference converter's fixed pod scaffolding (pod_csv_to_yaml.py:30-52)
POD_NAMESPACE = "paib-gpu"
CONTAINER_NAME = "main"
CONTAINER_IMAGE = "tensorflow:latest"


def _pod_obj(row: dict, namespace: str = POD_NAMESPACE) -> dict:
    """One pod CSV row → the reference's Pod manifest shape
    (pod_csv_to_yaml.py generate_pod_yaml + output_pod)."""
    requests = {"cpu": f"{int(row['cpu_milli'])}m"}
    if row.get("memory_mib"):
        requests["memory"] = f"{int(row['memory_mib'])}Mi"
    annotations = {}
    num_gpu = int(row.get("num_gpu") or 0)
    if num_gpu != 0:
        milli = int(row.get("gpu_milli") or 1000)
        # clamp exactly like the reference (pod_csv_to_yaml.py:110)
        milli = "1000" if milli > 1000 else str(milli) if milli > 0 else "0"
        annotations[ANNO_GPU_MILLI] = milli
        annotations[ANNO_GPU_COUNT] = str(num_gpu)
        spec = "|".join(x for x in (row.get("gpu_spec") or "").split("|") if x)
        if spec:
            annotations[ANNO_GPU_MODEL] = spec
    # event ordering survives the round trip (the reference drops these)
    if row.get("creation_time"):
        annotations[ANNO_CREATION_TIME] = str(int(row["creation_time"]))
    if row.get("deletion_time"):
        annotations[ANNO_DELETION_TIME] = str(int(row["deletion_time"]))
    meta = {"name": row["name"], "namespace": namespace}
    if annotations:
        meta["annotations"] = annotations
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": meta,
        "spec": {
            "containers": [
                {
                    "name": CONTAINER_NAME,
                    "image": CONTAINER_IMAGE,
                    "imagePullPolicy": "Always",
                    "resources": {
                        "requests": dict(requests),
                        "limits": dict(requests),
                    },
                }
            ],
            "restartPolicy": "OnFailure",
            "dnsPolicy": "Default",
        },
    }


def _node_obj(row: dict) -> dict:
    """One node CSV row → the reference's Node manifest shape
    (data/node_yaml/openb_node_list_gpu_node.yaml; cpu-model labels are the
    `2 - Add CPU models to YAML nodes.ipynb` step)."""
    name = row.get("sn") or row["name"]
    gpu = int(row.get("gpu") or 0)
    labels = {
        "beta.kubernetes.io/os": "linux",
        "kubernetes.io/os": "linux",
        "kubernetes.io/hostname": name,
    }
    if gpu > 0 and row.get("model"):
        labels[ANNO_GPU_MODEL] = row["model"]
    if row.get("cpu_model"):
        labels[ANNO_CPU_MODEL] = row["cpu_model"]
    resources = {
        "cpu": f"{int(row['cpu_milli'])}m",
        "memory": f"{int(row['memory_mib'])}Mi",
        "pods": "1001",
        ANNO_GPU_COUNT: str(gpu),
        ANNO_GPU_MILLI: str(gpu * 1000),
    }
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "labels": labels},
        "status": {
            "allocatable": dict(resources),
            "capacity": dict(resources),
        },
    }


def _write_multidoc(objs: Iterable[dict], out_path) -> int:
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    n = 0
    with open(out_path, "w") as f:
        for i, obj in enumerate(objs):
            if i:
                f.write("\n---\n\n")
            yaml.dump(obj, f, default_flow_style=False)
            n += 1
    return n


def pod_csv_to_yaml(
    csv_path, out_path=None, namespace: str = POD_NAMESPACE
) -> Path:
    """openb pod CSV → multi-document Pod YAML (ref: pod_csv_to_yaml.py
    __main__: output lands in <stem>/<stem>.yaml next to the cwd unless
    out_path is given)."""
    csv_path = Path(csv_path)
    if out_path is None:
        out_dir = Path(csv_path.stem)
        out_path = out_dir / (csv_path.stem + ".yaml")
    with open(csv_path, newline="") as f:
        rows = list(csv.DictReader(f))
    n = _write_multidoc((_pod_obj(r, namespace) for r in rows), out_path)
    print(f"OUTPUT: {out_path} (len: {n})")
    return Path(out_path)


def node_csv_to_yaml(csv_path, out_path=None) -> Path:
    """openb node CSV → multi-document Node YAML (the artifact the
    reference ships pre-generated as node_yaml/openb_node_list_gpu_node.yaml)."""
    csv_path = Path(csv_path)
    if out_path is None:
        out_path = Path(csv_path.stem) / (csv_path.stem + ".yaml")
    with open(csv_path, newline="") as f:
        rows = list(csv.DictReader(f))
    n = _write_multidoc((_node_obj(r) for r in rows), out_path)
    print(f"OUTPUT: {out_path} (len: {n})")
    return Path(out_path)


def prepare_input(
    csv_dir, out_dir, node_csv: Optional[str] = None
) -> List[Path]:
    """prepare_input.sh equivalent: for every openb_pod_list*.csv under
    csv_dir, create <out_dir>/<trace>/ holding the trace's pod YAML plus the
    shared node YAML — the cluster-config directory layout `python -m tpusim
    apply` (and the reference's `simon apply`) consumes."""
    csv_dir = Path(csv_dir)
    out_dir = Path(out_dir)
    if node_csv is None:
        node_csv = csv_dir / "openb_node_list_gpu_node.csv"
    node_yaml_tmp = out_dir / "_node" / "openb_node_list_gpu_node.yaml"
    node_csv_to_yaml(node_csv, node_yaml_tmp)
    made = []
    for pod_csv in sorted(csv_dir.glob("openb_pod_list*.csv")):
        trace_dir = out_dir / pod_csv.stem
        trace_dir.mkdir(parents=True, exist_ok=True)
        shutil.copy(node_yaml_tmp, trace_dir / node_yaml_tmp.name)
        pod_csv_to_yaml(pod_csv, trace_dir / (pod_csv.stem + ".yaml"))
        made.append(trace_dir)
    shutil.rmtree(node_yaml_tmp.parent)
    return made
