"""Cluster snapshot export/import (ref: pkg/simulator/export.go +
scripts/inject_origin_workload_into_snapshot.py).

Three snapshot surfaces, schema-compatible with the reference so its
analysis/plotting/resume tooling works unchanged:
- pod snapshot YAML (export.go:20-77): every pod re-emitted as a k8s Pod doc
  whose binding is moved into a `kubernetes.io/hostname` nodeSelector so a
  future run re-binds identically; unscheduled pods carry the
  `simon/pod-unscheduled` annotation.
- pod snapshot CSV (export.go:82-200): 14-column schema incl. gpu_index and
  per-model memory derates.
- node snapshot CSV (export.go:202-312): fixed 8-GPU columns
  gpu_milli_left_0..7 (+ per-device mem-left), same as the input trace.

The YAML loader ingests both our exports and reference-style workload YAML
(data/pod_csv_to_yaml.py output), which is also the Applier's pod-ingestion
path. inject_snapshot_workload implements the warm-start trick of
scripts/inject_origin_workload_into_snapshot.py:27-40: rename snapshot pods
with an -ss<id> suffix and pin creation-time to the epoch so they sort before
any new workload.
"""

from __future__ import annotations

import csv
from dataclasses import replace
from typing import List, Optional, Sequence

import numpy as np
import yaml

from tpusim.constants import GPU_MEMORY_MIB, GPU_MODELS, MILLI
from tpusim.io.trace import NodeRow, PodRow

# annotation keys (ref: open-gpu-share/utils/const.go:4-14)
ANNO_GPU_MILLI = "alibabacloud.com/gpu-milli"
ANNO_GPU_COUNT = "alibabacloud.com/gpu-count"
ANNO_GPU_INDEX = "alibabacloud.com/gpu-index"
ANNO_GPU_MODEL = "alibabacloud.com/gpu-card-model"
ANNO_CPU_MODEL = "alibabacloud.com/cpu-model"
ANNO_CREATION_TIME = "alibabacloud.com/creation-time"
ANNO_DELETION_TIME = "alibabacloud.com/deletion-time"
ANNO_UNSCHEDULED = "simon/pod-unscheduled"  # ref: pkg/type/const.go
ANNO_ASSUME_TIME = "alibabacloud.com/assume-time"  # scheduling latency
HOSTNAME_LABEL = "kubernetes.io/hostname"
SCHEDULER_NAME = "simon-scheduler"


def _gpu_index_str(dev_mask) -> str:
    """Device ids joined by '-' (ref: DevIdSep, utils/pod.go)."""
    return "-".join(str(i) for i in np.flatnonzero(np.asarray(dev_mask)))


def pod_to_yaml_obj(
    pod: PodRow,
    node_name: Optional[str] = None,
    dev_mask=None,
    unscheduled: bool = False,
    assume_time_ns: Optional[int] = None,
) -> dict:
    """One trace pod → k8s Pod object (dict), reference-schema annotations.

    assume_time_ns stamps `alibabacloud.com/assume-time` alongside the
    gpu-index annotation, like the reference's Reserve step
    (UpdatePodDeviceAnnoSpec, open-gpu-share/utils/pod.go:164-174 writes
    time.Now().UnixNano()). The replay is compiled, so per-pod wall times
    do not exist; callers pass a deterministic nanotime series that
    preserves scheduling order (the annotation's purpose is latency/order
    tracing, utils/const.go:9)."""
    annotations = {}
    if pod.num_gpu > 0:
        annotations[ANNO_GPU_MILLI] = str(pod.gpu_milli)
        annotations[ANNO_GPU_COUNT] = str(pod.num_gpu)
        if pod.gpu_spec:
            annotations[ANNO_GPU_MODEL] = pod.gpu_spec
        if dev_mask is not None and node_name is not None:
            idx = _gpu_index_str(dev_mask)
            if idx:
                annotations[ANNO_GPU_INDEX] = idx
                if assume_time_ns is not None:
                    annotations[ANNO_ASSUME_TIME] = str(int(assume_time_ns))
    if pod.creation_time:
        annotations[ANNO_CREATION_TIME] = str(pod.creation_time)
    if pod.deletion_time:
        annotations[ANNO_DELETION_TIME] = str(pod.deletion_time)
    if unscheduled:
        annotations[ANNO_UNSCHEDULED] = "true"

    requests = {"cpu": f"{pod.cpu_milli}m"}
    if pod.memory_mib:
        requests["memory"] = f"{pod.memory_mib}Mi"
    spec = {
        "containers": [
            {
                "name": "main",
                "image": "tensorflow:latest",
                "resources": {"requests": requests, "limits": dict(requests)},
            }
        ],
        "restartPolicy": "OnFailure",
        "schedulerName": SCHEDULER_NAME,
    }
    if node_name is not None and not unscheduled:
        spec["nodeSelector"] = {HOSTNAME_LABEL: node_name}
    meta = {"name": pod.name, "namespace": "default"}
    if annotations:
        meta["annotations"] = annotations
    return {"apiVersion": "v1", "kind": "Pod", "metadata": meta, "spec": spec}


def export_pod_snapshot_yaml(
    pods: Sequence[PodRow],
    placed_node: np.ndarray,
    dev_mask: np.ndarray,
    node_names: Sequence[str],
    path: str,
    creation_rank: Optional[np.ndarray] = None,
):
    """ref: ExportPodSnapshotInYaml (export.go:20-77): scheduled pods pinned
    via nodeSelector, unscheduled ones annotated. Placed GPU pods carry the
    assume-time annotation: a fixed epoch base + the pod's creation-event
    position (`creation_rank`, falling back to list order), standing in for
    the reference's per-Reserve time.Now() stamps — fixed (not wall clock)
    so identical runs export byte-identical snapshots, like the pinned
    LogSink timestamps, while sorting by assume-time still recovers
    scheduling order."""
    base_ns = 946684800_000_000_000  # 2000-01-01T00:00:00Z in unix nanos
    docs = []
    for i, p in enumerate(pods):
        n = int(placed_node[i])
        order = i if creation_rank is None else int(creation_rank[i])
        if n >= 0:
            docs.append(
                pod_to_yaml_obj(
                    p, node_names[n], dev_mask[i],
                    assume_time_ns=base_ns + max(order, 0),
                )
            )
        else:
            docs.append(pod_to_yaml_obj(p, unscheduled=True))
    with open(path, "w") as f:
        yaml.safe_dump_all(docs, f, sort_keys=False)


def export_pod_snapshot_csv(
    pods: Sequence[PodRow],
    placed_node: np.ndarray,
    dev_mask: np.ndarray,
    nodes: Sequence[NodeRow],
    path: str,
):
    """ref: ExportPodSnapshotInCSV (export.go:82-200)."""
    header = [
        "pod", "namespace", "ip", "cpu_milli", "memory_mib",
        "num_gpu", "gpu_index", "gpu_mem_ratio", "gpu_milli",
        "model", "gpu_mem_mib_each", "gpu_mem_mib", "gpu_type_req",
        "creation_time",
    ]
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(header)
        for i, p in enumerate(pods):
            n = int(placed_node[i])
            model = nodes[n].model if n >= 0 and nodes[n].model else "CPU"
            mem_each = GPU_MEMORY_MIB.get(model, 0)
            w.writerow(
                [
                    p.name,
                    "default",
                    nodes[n].name if n >= 0 else "",
                    p.cpu_milli,
                    "",  # memory_mib: skipped by the reference too
                    p.num_gpu,
                    _gpu_index_str(dev_mask[i]) if n >= 0 else "",
                    p.gpu_milli // 10,
                    p.gpu_milli,
                    model,
                    mem_each,
                    p.gpu_milli * mem_each // MILLI,
                    p.gpu_spec if p.gpu_spec else "<none>",
                    p.creation_time or "",
                ]
            )


def export_node_snapshot_csv(state, nodes: Sequence[NodeRow], num_pods, path: str):
    """ref: ExportNodeSnapshotInCSV (export.go:202-312); `state` is the final
    NodeState (host numpy), num_pods the per-node pod count i32[N]."""
    header = (
        ["name", "ip", "model", "cpu", "gpu", "memory_mib", "gpu_mem_mib_each",
         "num_pod", "cpu_milli_left", "memory_mib_left"]
        + [c for i in range(8) for c in (f"gpu_milli_left_{i}", f"gpu_mem_mib_left_{i}")]
        + ["gpu_milli_left", "gpu_mem_mib_left"]
    )
    cpu_left = np.asarray(state.cpu_left)
    gpu_left = np.asarray(state.gpu_left)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(header)
        for i, n in enumerate(nodes):
            model = n.model if n.model else "CPU"
            mem_each = GPU_MEMORY_MIB.get(model, 0)
            row = [
                n.name, "", model, n.cpu_milli // MILLI, n.gpu, n.memory_mib,
                mem_each, int(num_pods[i]), int(cpu_left[i]), n.memory_mib,
            ]
            total_milli = total_mem = 0
            for d in range(8):
                left = int(gpu_left[i][d]) if d < n.gpu else 0
                mem_left = left * mem_each // MILLI
                total_milli += left
                total_mem += mem_left
                row += [left, mem_left]
            row += [total_milli, total_mem]
            w.writerow(row)


def _parse_quantity_milli(q) -> int:
    s = str(q)
    if s.endswith("m"):
        return int(float(s[:-1]))
    return int(float(s) * MILLI)


def _parse_quantity_mib(q) -> int:
    s = str(q)
    units = {"Mi": 1, "Gi": 1024, "Ki": 1.0 / 1024, "Ti": 1024 * 1024}
    for suffix, mult in units.items():
        if s.endswith(suffix):
            return int(float(s[: -len(suffix)]) * mult)
    return int(float(s)) // (1024 * 1024)  # plain bytes


def load_pod_yaml(path: str) -> List[PodRow]:
    """Ingest reference-style pod YAML (pod_csv_to_yaml.py output or our own
    snapshot) → PodRow list. The pinned node (if any) lands in
    PodRow.pinned_node for re-binding."""
    pods: List[PodRow] = []
    with open(path) as f:
        for doc in yaml.safe_load_all(f):
            if not doc or doc.get("kind") != "Pod":
                continue
            meta = doc.get("metadata", {})
            anno = meta.get("annotations") or {}
            spec = doc.get("spec", {})
            containers = spec.get("containers", [])
            cpu = mem = 0
            for c in containers:
                req = (c.get("resources") or {}).get("requests") or {}
                if "cpu" in req:
                    cpu += _parse_quantity_milli(req["cpu"])
                if "memory" in req:
                    mem += _parse_quantity_mib(req["memory"])
            num_gpu = int(anno.get(ANNO_GPU_COUNT, 0))
            pods.append(
                PodRow(
                    name=meta.get("name", ""),
                    cpu_milli=cpu,
                    memory_mib=mem,
                    num_gpu=num_gpu,
                    gpu_milli=int(anno.get(ANNO_GPU_MILLI, 0)) if num_gpu else 0,
                    gpu_spec=anno.get(ANNO_GPU_MODEL, ""),
                    creation_time=int(anno.get(ANNO_CREATION_TIME, 0)),
                    deletion_time=int(anno.get(ANNO_DELETION_TIME, 0)),
                    pinned_node=(spec.get("nodeSelector") or {}).get(HOSTNAME_LABEL),
                    unscheduled=anno.get(ANNO_UNSCHEDULED) == "true",
                )
            )
    return pods


def inject_snapshot_workload(
    snapshot_pods: Sequence[PodRow], snapshot_id: int = 0
) -> List[PodRow]:
    """Warm-start injection (ref:
    scripts/inject_origin_workload_into_snapshot.py:27-40): suffix snapshot
    pod names with -ss<id> and pin creation-time to the epoch so they sort
    (and thus schedule) before any new workload pods."""
    return [
        replace(p, name=f"{p.name}-ss{snapshot_id}", creation_time=0, deletion_time=0)
        for p in snapshot_pods
    ]
