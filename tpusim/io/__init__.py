"""Trace/config ingestion and snapshot export (ref: data/, scripts/,
pkg/api/v1alpha1, pkg/simulator/export.go)."""
