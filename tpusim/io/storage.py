"""open-local storage extension: node VG/device inventory + pod volume
requests (ref: pkg/utils/utils.go:555-668 NodeStorage/VolumeRequest/
GetPodLocalPVCs, pkg/simulator/utils.go:325-343
MatchAndSetLocalStorageAnnotationOnNode, pkg/utils/const.go:16-27 SC names).

In the reference revision this extension is ingest + reporting: per-node
storage JSON (from `<node-name>.json` files beside the cluster YAMLs, or the
`simon/node-local-storage` node annotation) feeds the Node Local Storage
report table (apply.go:440-490) and the MaxVG occupancy verdict
(apply.go:550-631); pod volume annotations (`simon/pod-local-storage`)
synthesize PVCs. No registered scheduler plugin consumes storage, so it does
not constrain placement — faithfully mirrored here.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

ANNO_NODE_LOCAL_STORAGE = "simon/node-local-storage"
ANNO_POD_LOCAL_STORAGE = "simon/pod-local-storage"


def maybe_json(raw):
    """Annotation value → dict (annotations arrive as JSON strings; snapshot
    round-trips may already carry dicts). Malformed JSON → None, matching the
    reference's log-and-skip (utils.go:612-615)."""
    if raw is None or not isinstance(raw, str):
        return raw
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        return None

OPEN_LOCAL_SC_LVM = "open-local-lvm"
YODA_SC_LVM = "yoda-lvm-default"
LVM_SC_NAMES = (OPEN_LOCAL_SC_LVM, YODA_SC_LVM)


@dataclass
class VG:
    """LVM volume group (ref: open-local SharedResource)."""

    name: str
    capacity: int  # bytes
    requested: int = 0  # bytes


@dataclass
class StorageDevice:
    """Exclusive disk (ref: open-local ExclusiveResource)."""

    device: str
    capacity: int  # bytes
    media_type: str = ""  # HDD | SSD
    is_allocated: bool = False


@dataclass
class NodeStorage:
    """ref: utils.go:555-558."""

    vgs: List[VG] = field(default_factory=list)
    devices: List[StorageDevice] = field(default_factory=list)


@dataclass
class Volume:
    """ref: utils.go:561-567 (size serialized as a string in the JSON)."""

    size: int  # bytes
    kind: str  # LVM | HDD | SSD
    sc_name: str = ""


@dataclass
class PVC:
    """Synthesized claim (ref: GetPodLocalPVCs, utils.go:620-668)."""

    name: str
    namespace: str
    sc_name: str
    size: int


def parse_node_storage(raw) -> Optional[NodeStorage]:
    """JSON (string or dict) → NodeStorage (ref: GetNodeStorage,
    utils.go:572-585)."""
    if raw is None:
        return None
    data = json.loads(raw) if isinstance(raw, str) else raw
    return NodeStorage(
        vgs=[
            VG(
                name=v.get("name", ""),
                capacity=int(v.get("capacity", 0) or 0),
                requested=int(v.get("requested", 0) or 0),
            )
            for v in data.get("vgs") or []
        ],
        devices=[
            StorageDevice(
                device=d.get("device", ""),
                capacity=int(d.get("capacity", 0) or 0),
                media_type=d.get("mediaType", d.get("media_type", "")) or "",
                is_allocated=bool(d.get("isAllocated", d.get("is_allocated", False))),
            )
            for d in data.get("devices") or []
        ],
    )


def parse_pod_storage(raw) -> Optional[List[Volume]]:
    """JSON (string or dict) → volume list (ref: GetPodStorage,
    utils.go:606-618; Volume.Size is a JSON string)."""
    if raw is None:
        return None
    data = json.loads(raw) if isinstance(raw, str) else raw
    return [
        Volume(
            size=int(v.get("size", 0) or 0),
            kind=v.get("kind", ""),
            sc_name=v.get("scName", v.get("sc_name", "")) or "",
        )
        for v in data.get("volumes") or []
    ]


def pod_local_pvcs(
    pod_name: str, namespace: str, volumes: Sequence[Volume]
) -> Tuple[List[PVC], List[PVC]]:
    """Volumes → (lvm PVCs, device PVCs) (ref: GetPodLocalPVCs,
    utils.go:620-668: unsupported kinds are skipped; LVM storage classes go
    to the lvm list, everything else to the device list)."""
    lvm, device = [], []
    for i, v in enumerate(volumes):
        if v.kind not in ("LVM", "HDD", "SSD"):
            continue
        pvc = PVC(
            name=f"pvc-{pod_name}-{i}",
            namespace=namespace,
            sc_name=v.sc_name,
            size=v.size,
        )
        (lvm if v.sc_name in LVM_SC_NAMES else device).append(pvc)
    return lvm, device


def match_local_storage_files(node_names: Sequence[str], path: str) -> Dict[str, dict]:
    """`<node-name>.json` files in the cluster-config dir → per-node raw
    storage info (ref: MatchAndSetLocalStorageAnnotationOnNode,
    pkg/simulator/utils.go:325-343)."""
    found: Dict[str, dict] = {}
    if not os.path.isdir(path):
        return found
    names = set(node_names)
    for fname in sorted(os.listdir(path)):
        if not fname.endswith(".json"):
            continue
        name = fname[: -len(".json")]
        if name not in names:
            continue
        try:
            with open(os.path.join(path, fname)) as f:
                found[name] = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
    return found


def cluster_vg_totals(storages: Sequence[Optional[NodeStorage]]) -> Tuple[int, int]:
    """(requested, capacity) bytes over all VGs (ref: apply.go:590-612
    totalVGResource accumulation)."""
    req = cap = 0
    for st in storages:
        if st is None:
            continue
        for vg in st.vgs:
            req += vg.requested
            cap += vg.capacity
    return req, cap


# ---------------------------------------------------------------------------
# Replay checkpoints — exact resume of the chunked event scan
# ---------------------------------------------------------------------------
#
# A checkpoint is the engine's complete scan carry (table_engine.Flat/
# BlockedTableCarry, or the shard engine's gathered snapshot) plus the
# telemetry accumulated so far — including, on decision-recording runs
# (ISSUE 4), the per-event DecisionRecord stream as `dec_<field>` arrays
# beside event_node/event_dev, so a resumed run's provenance is continuous —
# written after every completed segment of a
# chunked replay (driver.SimulatorConfig.checkpoint_every). Files are
# content-addressed like the Bellman series cache (driver._bellman_cache_path):
# the name is the sha256 of everything that determines the run — a source-code
# version salt, the initial state, the pod specs, the event stream, the PRNG
# key, the tie-break rank, and a config string (record_decisions included:
# the two layouts must never mix) — so a resumed process can only
# ever pick up a checkpoint of the *identical* run, and any code or input
# change silently starts fresh instead of resuming into divergence. All carry
# leaves are exact dtypes (i32/bool/u32), so a save/load round-trip is
# bit-transparent and resume reproduces the uninterrupted scan exactly
# (pinned by tests/test_checkpoint.py). The same checkpoint_digest helper
# also signs the decision JSONL payload (obs.decisions.write_decisions),
# so torn/edited provenance files fail loudly on read.

CHECKPOINT_SUFFIX = ".ckpt.npz"


def checkpoint_digest(chunks) -> str:
    """sha256 hex over an iterable of byte chunks — the content key of one
    replay run. Callers feed every run-defining input (see the section
    comment); the driver prepends its source-version salt."""
    import hashlib

    h = hashlib.sha256()
    for c in chunks:
        h.update(c)
    return h.hexdigest()


def checkpoint_path(cache_dir: str, digest: str, cursor: int) -> str:
    return os.path.join(
        cache_dir, f"{digest}.e{cursor:010d}{CHECKPOINT_SUFFIX}"
    )


def save_checkpoint(
    cache_dir: str, digest: str, cursor: int, arrays: Dict[str, "object"]
) -> str:
    """Write one checkpoint atomically (tmp + rename, the Bellman-cache
    discipline — a killed writer leaves no torn file). `arrays` maps leaf
    names to numpy arrays; `cursor` is the number of events already
    consumed. Returns the file path."""
    import numpy as np

    os.makedirs(cache_dir, exist_ok=True)
    path = checkpoint_path(cache_dir, digest, cursor)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, __cursor__=np.int64(cursor), **arrays)
    os.replace(tmp, path)
    return path


def iter_checkpoints(cache_dir: str, digest: str) -> list:
    """Every (cursor, path) checkpoint of a run digest, NEWEST first.
    Foreign files never match — the digest prefix is the whole
    contract."""
    if not cache_dir or not os.path.isdir(cache_dir):
        return []
    out = []
    prefix = digest + ".e"
    for fname in os.listdir(cache_dir):
        if not (fname.startswith(prefix) and fname.endswith(CHECKPOINT_SUFFIX)):
            continue
        try:
            cursor = int(fname[len(prefix):-len(CHECKPOINT_SUFFIX)])
        except ValueError:
            continue
        out.append((cursor, os.path.join(cache_dir, fname)))
    out.sort(reverse=True)
    return out


def find_checkpoint(cache_dir: str, digest: str) -> Optional[Tuple[int, str]]:
    """Latest (cursor, path) checkpoint for a run digest, or None."""
    cands = iter_checkpoints(cache_dir, digest)
    return cands[0] if cands else None


def load_valid_checkpoint(cache_dir: str, digest: str, validate=None,
                          on_skip=None, max_cursor: Optional[int] = None,
                          delete_invalid: bool = True):
    """(cursor, arrays, path) of the NEWEST checkpoint that loads AND
    passes `validate(arrays)` (ISSUE 10 torn-checkpoint tolerance): a
    corrupt/truncated `.ckpt.npz` — a machine killed mid-write on a
    filesystem without atomic rename, a short copy, an edited file — is
    skipped (and deleted, so it cannot shadow future saves) with an
    `on_skip(path, err)` callback instead of crashing the resume, and
    the run continues from the newest VALID predecessor. Returns None
    when no usable checkpoint exists (a fresh start is always safe —
    content addressing guarantees it).

    `max_cursor` bounds the search to cursors <= that event — the fork
    index's nearest-checkpoint-at-or-before-divergence walk (ISSUE 16):
    newer checkpoints of the base run are NOT candidates (their carries
    already consumed post-divergence events) and are left untouched, not
    deleted — they still serve later-diverging forks.

    `delete_invalid=False` skips unusable files without unlinking them —
    a fork reader probing ANOTHER run's checkpoint ladder must never
    destroy files it merely failed to interpret (a layout mismatch from
    different padded geometry is the reader's problem, not corruption)."""
    for cursor, path in iter_checkpoints(cache_dir, digest):
        if max_cursor is not None and cursor > max_cursor:
            continue
        try:
            cur, arrays = load_checkpoint(path)
            if cur != cursor:
                raise ValueError(
                    f"cursor mismatch: file says {cur}, name says {cursor}"
                )
            if validate is not None:
                validate(arrays)
            return cursor, arrays, path
        except Exception as err:
            if on_skip is not None:
                on_skip(path, err)
            if delete_invalid:
                try:
                    os.unlink(path)
                except OSError:
                    pass
    return None


def load_checkpoint(path: str) -> Tuple[int, Dict[str, "object"]]:
    """(cursor, {leaf name: numpy array}) from a checkpoint file."""
    import numpy as np

    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files if k != "__cursor__"}
        cursor = int(z["__cursor__"])
    return cursor, arrays


# ---------------------------------------------------------------------------
# Init-table cache — content-keyed reuse of the K-node-sweep table build
# ---------------------------------------------------------------------------
#
# make_table_builders.init_tables dominates short scale-lane runs (~27 s at
# N=100k on the 2-vCPU backend, ROADMAP open item) yet is a pure function of
# (engine source, scheduling config, initial state, pod types, typical
# pods) — NOT of the event stream or PRNG key (no table-ized column kernel
# consumes rng). So the driver caches the three tables on disk under the
# same content-addressing discipline as checkpoints: the digest is the
# engine-source salt + config + every input the build reads, any code or
# input change misses silently, and a hit feeds the arrays back through
# `make_table_replay(...)(..., tables=...)` bit-identically (every blocked
# aggregate derives from the tables). obs records hit/miss per run.

TABLES_SUFFIX = ".tables.npz"


def tables_path(cache_dir: str, digest: str) -> str:
    return os.path.join(cache_dir, f"{digest}{TABLES_SUFFIX}")


def find_tables(cache_dir: str, digest: str) -> Optional[str]:
    """Path of a cached table build for this digest, or None."""
    if not cache_dir:
        return None
    path = tables_path(cache_dir, digest)
    return path if os.path.isfile(path) else None


def save_tables(cache_dir: str, digest: str, arrays: Dict[str, "object"]) -> str:
    """Persist one table build atomically (tmp + rename, the checkpoint
    discipline). `arrays` maps table names to numpy arrays."""
    import numpy as np

    os.makedirs(cache_dir, exist_ok=True)
    path = tables_path(cache_dir, digest)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)
    return path


def load_tables(path: str) -> Dict[str, "object"]:
    import numpy as np

    with np.load(path) as z:
        return {k: z[k] for k in z.files}


# ---------------------------------------------------------------------------
# Digest-signed JSONL — the shared persistence primitive of decision files
# (obs.decisions, ISSUE 4) and service results (tpusim.svc, ISSUE 7)
# ---------------------------------------------------------------------------
#
# Format: one header line (a JSON object carrying at least `schema` and
# `digest` = sha256 over the payload lines) followed by the payload, one
# JSON document per line. The digest makes torn/truncated/hand-edited
# files fail loudly on read instead of producing silently wrong answers;
# writes are atomic (tmp + os.replace — the checkpoint discipline), so a
# killed writer leaves no half-file behind.


def payload_digest(lines) -> str:
    """sha256 hex over payload lines, newline-terminated each — the
    torn-file detector of the signed-JSONL format."""
    import hashlib

    h = hashlib.sha256()
    for line in lines:
        h.update(line.encode())
        h.update(b"\n")
    return h.hexdigest()


def write_signed_jsonl(path: str, header: dict, lines) -> str:
    """Write header + payload lines atomically; the header gains a
    `digest` key over the payload. Returns the file path."""
    lines = list(lines)
    header = dict(header)
    header["digest"] = payload_digest(lines)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(header, sort_keys=True, separators=(",", ":")))
        f.write("\n")
        for line in lines:
            f.write(line + "\n")
    os.replace(tmp, path)
    return path


def read_signed_jsonl(path: str, schema: str = ""):
    """(header, payload lines) from a signed-JSONL file; verifies the
    schema (when given) and the payload digest so a torn/edited file
    raises ValueError instead of reading back wrong."""
    with open(path) as f:
        raw = [l.rstrip("\n") for l in f if l.strip()]
    if not raw:
        raise ValueError(f"{path}: empty signed-JSONL file")
    header = json.loads(raw[0])
    if schema and header.get("schema") != schema:
        raise ValueError(
            f"{path}: not a {schema} file (schema={header.get('schema')!r})"
        )
    payload = raw[1:]
    digest = payload_digest(payload)
    if digest != header.get("digest"):
        raise ValueError(
            f"{path}: payload digest mismatch (torn or edited file): "
            f"header {header.get('digest')} != computed {digest}"
        )
    return header, payload


def file_sha256(path: str) -> str:
    """sha256 hex of a file's raw bytes — the per-FILE integrity key of
    the fleet transfer plane (ISSUE 13): the register handshake carries
    it for every hosted trace CSV, so a no-shared-fs worker can verify
    a downloaded (possibly resumed) file before parsing a single row."""
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_bytes_atomic(path: str, data: bytes) -> str:
    """Write raw bytes atomically (tmp + os.replace — the checkpoint
    discipline): a killed writer leaves the previous file intact, never
    a torn one. The coordinator's result-upload landing path (ISSUE 13)
    rides this so a half-received upload can never become a half-written
    result file. The tmp name is pid AND thread scoped: the upload
    handlers run on a ThreadingHTTPServer, so two concurrent duplicate
    uploads of one digest share a pid — a pid-only tmp would let one
    thread truncate the other's half-written file mid-rename."""
    import threading

    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    return path


def write_signed_json(path: str, header: dict, doc: dict) -> str:
    """Single-document convenience over write_signed_jsonl (ISSUE 12,
    the lease-file plane): one canonical-JSON payload line under the
    digest-signed header. Atomic like every write here — a `kill -9`'d
    writer leaves the previous file intact, never a torn one."""
    line = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return write_signed_jsonl(path, header, [line])


def read_signed_json(path: str, schema: str = ""):
    """(header, doc) from a single-document signed-JSON file; raises
    ValueError on a torn/edited/multi-document file exactly like
    read_signed_jsonl."""
    header, payload = read_signed_jsonl(path, schema)
    if len(payload) != 1:
        raise ValueError(
            f"{path}: want exactly one payload document, found "
            f"{len(payload)}"
        )
    return header, json.loads(payload[0])


# the SLO plane's history snapshot (ISSUE 20): the active coordinator
# periodically persists its tsdb ring here (signed-JSON, atomic) so a
# promoted standby adopts metrics history instead of starting blind
TSDB_SNAPSHOT_BASENAME = "tsdb.snapshot.json"


def tsdb_snapshot_path(artifact_dir: str) -> str:
    return os.path.join(artifact_dir, TSDB_SNAPSHOT_BASENAME)


# ---------------------------------------------------------------------------
# Hash-chained append-only JSONL — the control-plane audit log (ISSUE 19)
# ---------------------------------------------------------------------------
#
# The signed-JSONL format above is write-once: the digest covers the whole
# payload, so appending means rewriting the file. The audit log needs the
# opposite discipline — an append-only file that accretes one record per
# control-plane decision for the life of a deployment — so integrity moves
# from a whole-file digest to a per-record hash chain: every record carries
# `prev` = sha256 of its predecessor's exact line bytes (genesis: 64 zeros).
# An edited record breaks every successor's `prev`; a truncated file is
# caught by the `<path>.head` sidecar (atomically rewritten on each append
# with the record count + tip hash). The sidecar may lag the chain by
# appends that crashed between the line write and the head rewrite — verify
# therefore accepts a chain LONGER than the head says, as long as the
# head's recorded tip is exactly where the head says it is; a chain
# SHORTER than the head, or with a different record at the head's cursor,
# fails loudly. Appends serialize across processes via flock on the chain
# file itself (the HA smoke runs two coordinators over one artifact dir).

CHAIN_GENESIS = "0" * 64
CHAIN_HEAD_SUFFIX = ".head"
CHAIN_HEAD_SCHEMA = "tpusim-chain-head/1"


def chain_digest(line: str) -> str:
    """sha256 hex of one chain line's exact bytes (no newline)."""
    import hashlib

    return hashlib.sha256(line.encode()).hexdigest()


def _chain_tip(path: str):
    """(record count, tip hash) of an existing chain file. Reads the
    whole file — audit logs are control-plane-decision sized, not
    event-stream sized. A torn final line (a writer killed mid-append on
    a filesystem without atomic small appends) is NOT silently dropped:
    appending under a torn tail would orphan the chain, so raise."""
    n, tip = 0, CHAIN_GENESIS
    with open(path) as f:
        for raw in f:
            line = raw.rstrip("\n")
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                raise ValueError(
                    f"{path}: torn record after {n} chained entries"
                )
            if not isinstance(doc, dict):
                raise ValueError(f"{path}: record {n} is not an object")
            n += 1
            tip = chain_digest(line)
    return n, tip


def chain_append(path: str, doc: dict) -> str:
    """Append one record to a hash-chained JSONL; returns the written
    line. The record gains `prev` (the predecessor's line hash) and the
    head sidecar is atomically rewritten. Safe across processes (flock)
    and threads (the flock covers the read-tip/append/rewrite-head
    critical section; Python-level callers add their own mutex only to
    keep intra-process contention off the syscall path)."""
    import fcntl

    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a+") as f:
        fcntl.flock(f.fileno(), fcntl.LOCK_EX)
        try:
            n, prev = (
                _chain_tip(path) if os.path.getsize(path)
                else (0, CHAIN_GENESIS)
            )
            body = dict(doc)
            body["prev"] = prev
            line = json.dumps(body, sort_keys=True, separators=(",", ":"))
            f.write(line + "\n")
            f.flush()
            write_signed_json(
                path + CHAIN_HEAD_SUFFIX,
                {"schema": CHAIN_HEAD_SCHEMA},
                {"n": n + 1, "tip": chain_digest(line)},
            )
        finally:
            fcntl.flock(f.fileno(), fcntl.LOCK_UN)
    return line


def chain_records(path: str):
    """Every (record, line hash) of a chain file, verifying each link.
    Raises ValueError on a broken chain (edited record), a torn tail,
    or a non-object record — the loud half of `tpusim audit`."""
    out = []
    prev = CHAIN_GENESIS
    with open(path) as f:
        for i, raw in enumerate(f):
            line = raw.rstrip("\n")
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                raise ValueError(
                    f"{path}: torn record at line {i + 1} "
                    f"(writer killed mid-append, or hand-edited)"
                )
            if not isinstance(doc, dict):
                raise ValueError(f"{path}: line {i + 1} is not an object")
            if doc.get("prev") != prev:
                raise ValueError(
                    f"{path}: chain broken at record {len(out)} — "
                    f"prev {doc.get('prev')!r} != expected {prev!r} "
                    f"(an earlier record was edited or removed)"
                )
            h = chain_digest(line)
            out.append((doc, h))
            prev = h
    return out


def chain_verify(path: str) -> int:
    """Verify a hash-chained JSONL end-to-end against its head sidecar;
    returns the record count. Raises ValueError on ANY tamper signal:
    a broken link (edit), a missing head sidecar, a chain shorter than
    the head claims, or a different record at the head's cursor
    (truncate-and-regrow)."""
    records = chain_records(path)
    head_path = path + CHAIN_HEAD_SUFFIX
    if not os.path.isfile(head_path):
        raise ValueError(
            f"{path}: head sidecar {head_path} missing — cannot rule "
            f"out truncation"
        )
    _, head = read_signed_json(head_path, CHAIN_HEAD_SCHEMA)
    n, tip = int(head.get("n", -1)), head.get("tip", "")
    if n < 0 or n > len(records):
        raise ValueError(
            f"{path}: truncated — head records {n} entries, file has "
            f"{len(records)}"
        )
    if n > 0 and records[n - 1][1] != tip:
        raise ValueError(
            f"{path}: record {n - 1} does not match the head tip "
            f"(file truncated and regrown, or edited)"
        )
    return len(records)


def prune_checkpoints(cache_dir: str, digest: str, keep_cursor: int,
                      keep: int = 0) -> None:
    """Drop a run's checkpoints below `keep_cursor` (each save supersedes
    its predecessors; only the newest is ever resumed from). Missing files
    are fine — concurrent resumers may race here.

    `keep` is the retention knob (ISSUE 16, SimulatorConfig.
    checkpoint_keep): 0 keeps the historical resume-only behavior
    (delete everything below keep_cursor), < 0 retains EVERY checkpoint
    (the warm-state fork-source mode — the svc fork index needs the
    whole mid-trace ladder, not just the newest), and N > 0 retains the
    newest N checkpoints and drops the rest (bounded disk for long base
    runs whose forks only ever diverge near the tail)."""
    if keep < 0 or not cache_dir or not os.path.isdir(cache_dir):
        return
    cands = iter_checkpoints(cache_dir, digest)  # newest first
    doomed = (
        cands[keep:] if keep > 0
        else [(c, p) for c, p in cands if c < keep_cursor]
    )
    for _, path in doomed:
        try:
            os.unlink(path)
        except OSError:
            pass
