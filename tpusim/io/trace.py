"""openb trace ingestion: CSV → device arrays.

Replaces the reference's CSV → YAML → k8s-object pipeline
(data/pod_csv_to_yaml.py + pkg/simulator/utils.go GetObjectFromYamlContent):
the trace loads straight into NodeState / PodSpec struct-of-arrays.

Node CSV schema (data/README.md): sn, cpu_milli, memory_mib, gpu, model.
Pod CSV schema: name, cpu_milli, memory_mib, num_gpu, gpu_milli, gpu_spec,
qos, pod_phase, creation_time, deletion_time, scheduled_time.

gpu_milli sanitization follows pod_csv_to_yaml.py: clamp to (0, 1000];
values > 1000 → 1000; only meaningful when num_gpu > 0.
"""

from __future__ import annotations

import csv
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from tpusim.constants import (
    CPU_MODEL_IDS,
    register_gpu_model,
    MAX_GPUS_PER_NODE,
    NO_GPU,
    gpu_spec_to_mask,
)
from tpusim.types import NodeState, PodSpec, make_node_state


@dataclass
class PodRow:
    """One trace pod, host-side (ref: PodResource + trace annotations)."""

    name: str
    cpu_milli: int
    memory_mib: int
    num_gpu: int
    gpu_milli: int
    gpu_spec: str = ""
    qos: str = ""
    pod_phase: str = ""
    creation_time: int = 0
    deletion_time: int = 0
    scheduled_time: int = 0
    # snapshot-resume fields (ref: export.go:44-58 nodeSelector pinning +
    # the simon/pod-unscheduled annotation)
    pinned_node: Optional[str] = None
    unscheduled: bool = False
    # k8s-manifest fields (tpusim.io.k8s_yaml): queue-sort inputs
    # (pkg/algo) and workload provenance (AddWorkloadInfoToPod)
    node_selector: Optional[dict] = None
    tolerations: bool = False
    workload_kind: str = ""
    workload_name: str = ""
    # open-local volume request (tpusim.io.storage; ref: the
    # simon/pod-local-storage annotation, pkg/utils/utils.go:606-618)
    local_storage: Optional[dict] = None

    @property
    def total_gpu_milli(self) -> int:
        return self.gpu_milli * self.num_gpu

    def spec_key(self) -> tuple:
        """Identity for typical-pod histogramming (GetPodResource fields that
        enter the PodResource map key, frag.go:292-310)."""
        return (self.cpu_milli, self.gpu_milli, self.num_gpu, self.gpu_spec)


@dataclass
class NodeRow:
    name: str
    cpu_milli: int
    memory_mib: int
    gpu: int
    model: str = ""
    cpu_model: str = ""
    # open-local storage inventory (tpusim.io.storage; ref: the
    # simon/node-local-storage annotation, pkg/utils/utils.go:572-585)
    local_storage: Optional[dict] = None


def _sanitize_gpu_milli(num_gpu: int, gpu_milli) -> int:
    if num_gpu == 0:
        return 0
    try:
        m = int(float(gpu_milli))
    except (TypeError, ValueError):
        m = 1000
    if m > 1000:
        return 1000
    if m <= 0:
        return 0
    return m


def load_node_csv(path: str) -> List[NodeRow]:
    rows = []
    with open(path, newline="") as f:
        for r in csv.DictReader(f):
            model = (r.get("model") or "").strip()
            if model.lower() == "nan":
                model = ""
            rows.append(
                NodeRow(
                    name=r["sn"],
                    cpu_milli=int(float(r["cpu_milli"])),
                    memory_mib=int(float(r["memory_mib"])),
                    gpu=int(float(r["gpu"])),
                    model=model,
                    cpu_model=(r.get("cpu_model") or "").strip(),
                )
            )
    return rows


def load_pod_csv(path: str) -> List[PodRow]:
    rows = []
    with open(path, newline="") as f:
        for r in csv.DictReader(f):
            num_gpu = int(float(r["num_gpu"]))
            spec = (r.get("gpu_spec") or "").strip()
            if spec.lower() == "nan":
                spec = ""
            rows.append(
                PodRow(
                    name=r["name"],
                    cpu_milli=int(float(r["cpu_milli"])),
                    memory_mib=int(float(r.get("memory_mib") or 0)),
                    num_gpu=num_gpu,
                    gpu_milli=_sanitize_gpu_milli(num_gpu, r.get("gpu_milli")),
                    gpu_spec=spec if num_gpu > 0 else "",
                    qos=r.get("qos", ""),
                    pod_phase=r.get("pod_phase", ""),
                    creation_time=int(float(r.get("creation_time") or 0)),
                    deletion_time=int(float(r.get("deletion_time") or 0)),
                    scheduled_time=int(float(r.get("scheduled_time") or 0)),
                )
            )
    return rows


def nodes_to_state(nodes: Sequence[NodeRow]) -> NodeState:
    """NodeRow list → all-idle NodeState (ref: node YAML → corev1.Node →
    NodeResource)."""
    gpu_type = np.array(
        [register_gpu_model(n.model) if n.model else NO_GPU for n in nodes],
        np.int32,
    )
    cpu_type = np.array(
        [CPU_MODEL_IDS.get(n.cpu_model, 0) for n in nodes], np.int32
    )
    for n in nodes:
        if n.gpu > MAX_GPUS_PER_NODE:
            raise ValueError(f"node {n.name}: {n.gpu} GPUs > {MAX_GPUS_PER_NODE}")
    return make_node_state(
        cpu_cap=[n.cpu_milli for n in nodes],
        mem_cap=[n.memory_mib for n in nodes],
        gpu_cnt=[n.gpu for n in nodes],
        gpu_type=gpu_type,
        cpu_type=cpu_type,
    )


def pods_to_specs(
    pods: Sequence[PodRow], node_index: dict = None, device: bool = True
) -> PodSpec:
    """PodRow list → batched PodSpec arrays. node_index maps node names to
    row indices for nodeSelector-pinned pods (snapshot resume, export.go
    hostname pinning); pods pinned to unknown nodes become unschedulable,
    pinned to index len(node_index) which no arange(num_nodes) entry matches
    (-1 is reserved for "unconstrained"). device=False keeps the arrays on
    host (numpy) — callers that pad/stack several spec sets before one
    upload (driver.schedule_pods_batch) avoid per-leaf round-trips."""
    import jax.numpy as jnp

    def pin(p: PodRow) -> int:
        if p.pinned_node is None or node_index is None:
            return -1
        return node_index.get(p.pinned_node, len(node_index))

    conv = jnp.asarray if device else (lambda a: a)
    return PodSpec(
        cpu=conv(np.array([p.cpu_milli for p in pods], np.int32)),
        mem=conv(np.array([p.memory_mib for p in pods], np.int32)),
        gpu_milli=conv(np.array([p.gpu_milli for p in pods], np.int32)),
        gpu_num=conv(np.array([p.num_gpu for p in pods], np.int32)),
        gpu_mask=conv(
            np.array([gpu_spec_to_mask(p.gpu_spec) for p in pods], np.int32)
        ),
        pinned=conv(np.array([pin(p) for p in pods], np.int32)),
    )


def build_events(
    pods: Sequence[PodRow], use_timestamps: bool = False
) -> Tuple[np.ndarray, np.ndarray]:
    """Pod list → (ev_kind i32[E], ev_pod i32[E]).

    use_timestamps=False mirrors the experiment pipeline (creation/deletion
    annotations commented out in pod_csv_to_yaml.py:119-120): one creation
    event per pod in list order, no deletions. use_timestamps=True mirrors
    the annotation-driven path (simulator.go:672-717): creation + deletion
    events stable-sorted by timestamp.

    Pods carrying the `simon/pod-unscheduled` annotation get EV_SKIP events:
    the reference never re-schedules them, appending them straight to the
    failed list (simulator.go:391-399).
    """
    from tpusim.sim.engine import EV_CREATE, EV_DELETE, EV_SKIP

    def kind_of(p: PodRow) -> int:
        return EV_SKIP if p.unscheduled else EV_CREATE

    if not use_timestamps:
        kind = np.array([kind_of(p) for p in pods], np.int32)
        idx = np.arange(len(pods), dtype=np.int32)
        return kind, idx
    events = []
    for i, p in enumerate(pods):
        events.append((p.creation_time, kind_of(p), i))
        if p.deletion_time and not p.unscheduled:
            events.append((p.deletion_time, EV_DELETE, i))
    events.sort(key=lambda e: e[0])  # python sort is stable
    kind = np.array([e[1] for e in events], np.int32)
    idx = np.array([e[2] for e in events], np.int32)
    return kind, idx


def tiebreak_rank(num_nodes: int, seed: int = 42) -> np.ndarray:
    """Random permutation standing in for the reference's 4-digit random
    node-name prefixes + lexicographic selectHost tie-break
    (simulator.go:584-588; generic_scheduler.go:199-203): rank[i] = position
    of node i in the prefixed lexicographic order."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(num_nodes)
    rank = np.empty(num_nodes, np.int32)
    rank[perm] = np.arange(num_nodes, dtype=np.int32)
    return rank
