"""Fragmentation math — the FGD core (ref: pkg/utils/frag.go).

Everything is expressed for a single node against the [T]-vector typical-pod
distribution and vmapped over nodes. The per-(node, typical-pod) classifier
and the frag-amount accumulation are exact re-derivations of
frag.go:460-493 (GetNodePodFrag) and frag.go:148-203
(NodeGpuShareFragAmount / ...Score); golden values from
pkg/utils/frag_test.go pin the semantics.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from tpusim.constants import (
    NO_ACCESS,
    NUM_FRAG_CLASSES,
    Q1_LACK_BOTH,
    Q2_LACK_GPU,
    Q3_SATISFIED,
    Q4_LACK_CPU,
    XL_SATISFIED,
    XR_LACK_CPU,
)
from tpusim.ops.resource import can_host_on_gpu, gpu_frag_milli, is_accessible
from tpusim.types import NodeState, TypicalPods

# Single-pod kernels from the resource algebra, lifted over the typical-pod
# axis — one definition shared with the placement path.
_can_host_t = jax.vmap(can_host_on_gpu, in_axes=(None, 0, 0))
_frag_milli_t = jax.vmap(gpu_frag_milli, in_axes=(None, 0))


def frag_class(cpu_left, gpu_left, gpu_type, tp: TypicalPods):
    """Classify how each typical pod 'sees' this node → i32[T] class ids
    (ref: frag.go:460-493 GetNodePodFrag).

    Decision order matters and is preserved: no-GPU pod → XL/XR; no model
    access → NA; GPU hostable → Q3/Q4 by CPU; else Q2/Q1 by CPU.
    """
    cpu_ok = cpu_left >= tp.cpu  # [T]
    acc = is_accessible(gpu_type, tp.gpu_mask)  # [T]
    can_host = _can_host_t(gpu_left, tp.gpu_milli, tp.gpu_num)  # [T]
    return jnp.where(
        tp.gpu_milli == 0,
        jnp.where(cpu_ok, XL_SATISFIED, XR_LACK_CPU),
        jnp.where(
            ~acc,
            NO_ACCESS,
            jnp.where(
                can_host,
                jnp.where(cpu_ok, Q3_SATISFIED, Q4_LACK_CPU),
                jnp.where(cpu_ok, Q2_LACK_GPU, Q1_LACK_BOTH),
            ),
        ),
    ).astype(jnp.int32)


def node_frag_amounts(cpu_left, gpu_left, gpu_type, tp: TypicalPods):
    """Per-class frag amounts f32[7] for one node
    (ref: frag.go:148-188 NodeGpuShareFragAmount).

    Q3 pods split the node's idle GPU milli: devices individually too small
    count toward Q2 (freq × gpuFragMilli), the rest stays in Q3. Every other
    class contributes freq × total idle milli to its own bucket.
    """
    cls = frag_class(cpu_left, gpu_left, gpu_type, tp)  # [T]
    total_left = gpu_left.sum().astype(jnp.float32)
    frag_small = _frag_milli_t(gpu_left, tp.gpu_milli).astype(jnp.float32)  # [T]
    is_q3 = cls == Q3_SATISFIED
    onehot = jax.nn.one_hot(cls, NUM_FRAG_CLASSES, dtype=jnp.float32)  # [T,7]
    base = onehot * (tp.freq * total_left)[:, None]  # non-Q3 rows correct
    q3_contrib = jnp.zeros((tp.size, NUM_FRAG_CLASSES), jnp.float32)
    q3_contrib = q3_contrib.at[:, Q2_LACK_GPU].set(tp.freq * frag_small)
    q3_contrib = q3_contrib.at[:, Q3_SATISFIED].set(tp.freq * (total_left - frag_small))
    contrib = jnp.where(is_q3[:, None], q3_contrib, base)
    return contrib.sum(0)


def frag_sum_except_q3(amounts):
    """ref: frag.go:411-418 FragAmountSumExceptQ3."""
    return amounts.sum(-1) - amounts[..., Q3_SATISFIED]


def frag_sum_q1q2q4(amounts):
    """ref: frag.go:420-425 FragAmountSumQ1Q2Q4."""
    return (
        amounts[..., Q1_LACK_BOTH]
        + amounts[..., Q2_LACK_GPU]
        + amounts[..., Q4_LACK_CPU]
    )


def node_frag_score(cpu_left, gpu_left, gpu_type, tp: TypicalPods):
    """Scalar frag score = sum of all classes except Q3
    (ref: frag.go:200-203 NodeGpuShareFragAmountScore)."""
    return frag_sum_except_q3(node_frag_amounts(cpu_left, gpu_left, gpu_type, tp))


# Vmapped over the node axis: NodeState arrays → f32[N, 7] / f32[N].
cluster_frag_amounts = jax.vmap(
    lambda s, tp: node_frag_amounts(s.cpu_left, s.gpu_left, s.gpu_type, tp),
    in_axes=(NodeState(0, 0, 0, 0, 0, 0, 0, 0, 0), None),
)
cluster_frag_scores = jax.vmap(
    lambda s, tp: node_frag_score(s.cpu_left, s.gpu_left, s.gpu_type, tp),
    in_axes=(NodeState(0, 0, 0, 0, 0, 0, 0, 0, 0), None),
)


@partial(jax.jit, static_argnames=())
def cluster_frag_report(state: NodeState, tp: TypicalPods):
    """Cluster-level frag aggregate (ref: analysis.go:59-121
    ClusterGpuFragReport, origin variant): returns
    (cluster_amounts f32[7], frag_gpu_milli, frag_ratio_pct, q124_ratio_pct).
    """
    amounts = cluster_frag_amounts(state, tp).sum(0)
    idle = amounts.sum()
    frag = frag_sum_except_q3(amounts)
    q124 = frag_sum_q1q2q4(amounts)
    return amounts, frag, 100.0 * frag / idle, 100.0 * q124 / idle


def node_frag_bellman(node, typical, max_depth: int = 64, memo=None, stats=None):
    """Host-side Bellman expected-frag value function
    (ref: frag.go:231-283 NodeGpuFragBellman).

    Unbounded memoized recursion is hostile to XLA (SURVEY.md §7.3), so this
    stays a host implementation used for reporting/tests.
    `node` is (cpu_left:int, gpu_left:tuple[int,...], gpu_type:int); `typical`
    is a list of (cpu, gpu_milli, gpu_num, gpu_mask, freq) tuples. Pass a
    dict as `memo` to share the flattened-state cache across calls (the
    reference's cross-event `fragMemo sync.Map`, simulator.go:58). Pass a
    dict as `stats` to collect {"truncations", "max_depth_seen"} — the Go
    code has no depth limit, so callers can assert the defensive cutoff
    never fires on real traces.

    The recursion keeps the device vector canonically sorted DESCENDING
    (value permutation-invariant, like the reference's Flatten dedup key),
    computes per-distinct-milli fit counts once per state, and performs the
    least-free-fitting Sub as an O(8) splice — ~10x over the naive form.
    tests/test_frag.py pins equivalence against a direct transcription of
    the definition.
    """
    memo = {} if memo is None else memo
    t_arr = list(typical)
    # distinct positive per-GPU requests across the distribution
    millis = sorted({t[1] for t in t_arr if t[1] > 0})

    def rec(cpu_left, g, gpu_type, cum_prob, depth):
        # g: tuple sorted descending. Memo hit takes precedence over the
        # cum_prob cutoff (frag.go:233-239).
        key = (cpu_left, g, gpu_type)
        v = memo.get(key)
        if v is not None:
            return v
        total = sum(g)
        if total == 0 or total * cum_prob < 1:
            return 0.0
        # fit count per distinct milli: g is sorted desc, so devices >= m
        # form a prefix — one merged two-pointer pass
        nfit = {}
        i = len(g)
        for m in millis:  # ascending m -> shrinking prefix
            while i > 0 and g[i - 1] < m:
                i -= 1
            nfit[m] = i
        node_bit = (1 << gpu_type) if gpu_type >= 0 else 0

        ratio_except_q3 = 0.0
        for cpu, milli, num, mask, p in t_arr:
            # class != Q3 (classify order: XL/XR, NA, Q3/Q4, Q2/Q1)
            if (
                milli == 0
                or (mask != 0 and not (mask & node_bit))
                or nfit[milli] < num
                or cpu_left < cpu
            ):
                ratio_except_q3 += p
        if stats is not None and depth > stats.get("max_depth_seen", 0):
            stats["max_depth_seen"] = depth
        if depth >= max_depth:
            # Defensive truncation (the Go code has no depth limit; its
            # cum_prob cutoff bounds recursion in practice). Do NOT memoize:
            # the truncated value would poison shallow-depth revisits.
            if stats is not None:
                stats["truncations"] = stats.get("truncations", 0) + 1
            return float(total)
        if ratio_except_q3 < 0.999:
            pv = 0.0
            for cpu, milli, num, mask, p in t_arr:
                if p == 0.0:  # zero-frequency padding rows contribute 0
                    continue
                # sub (least-free fitting devices; no accessibility check,
                # matching the definition's Sub)
                if cpu_left < cpu or len(g) < num:
                    pv += total * p
                    continue
                if num == 0 or milli == 0:
                    # milli == 0 with num > 0: the naive Sub decrements num
                    # devices by 0 — state unchanged beyond the CPU debit
                    pv += p * rec(cpu_left - cpu, g, gpu_type, cum_prob * p, depth + 1)
                    continue
                j = nfit[milli]  # fitting devices are g[0..j)
                if j < num:
                    pv += total * p
                    continue
                # take the num least-free fitting: g[j-num..j), each -milli;
                # re-sorting is a splice since only a contiguous run changed
                taken = [x - milli for x in g[j - num : j]]
                rest = list(g[:j - num]) + list(g[j:])
                g2 = tuple(sorted(rest + taken, reverse=True))
                pv += p * rec(cpu_left - cpu, g2, gpu_type, cum_prob * p, depth + 1)
            frag = pv
        else:
            frag = float(total)
        memo[key] = frag
        return frag

    cpu_left, gpu_left, gpu_type = node
    return rec(
        int(cpu_left),
        tuple(sorted((int(x) for x in gpu_left), reverse=True)),
        int(gpu_type),
        1.0,
        0,
    )


def _node_frag_bellman_naive(node, typical, max_depth: int = 64, memo=None):
    """Direct transcription of the definition (kept as the oracle for
    tests/test_frag.py's equivalence check against the optimized form)."""
    memo = {} if memo is None else memo
    t_arr = list(typical)

    def classify(cpu_left, gpu_left, gpu_type, t):
        cpu, milli, num, mask, _ = t
        if milli == 0:
            return XL_SATISFIED if cpu_left >= cpu else XR_LACK_CPU
        node_bit = (1 << gpu_type) if gpu_type >= 0 else 0
        if mask != 0 and not (mask & node_bit):
            return NO_ACCESS
        fit = sum(1 for g in gpu_left if g >= milli)
        if fit >= num:
            return Q3_SATISFIED if cpu_left >= cpu else Q4_LACK_CPU
        return Q2_LACK_GPU if cpu_left >= cpu else Q1_LACK_BOTH

    def sub(cpu_left, gpu_left, t):
        cpu, milli, num, _, _ = t
        if cpu_left < cpu or len(gpu_left) < num:
            return None
        g = list(gpu_left)
        if num == 0:
            return cpu_left - cpu, tuple(g)
        order = sorted(range(len(g)), key=lambda i: (g[i], i))
        need = num
        for i in order:
            if milli <= g[i]:
                g[i] -= milli
                need -= 1
                if need == 0:
                    return cpu_left - cpu, tuple(g)
        return None

    def rec(cpu_left, gpu_left, gpu_type, cum_prob, depth):
        # Memo hit takes precedence over the cum_prob cutoff (frag.go:233-239:
        # the dp load happens before the gpuMilliLeftTotal checks).
        key = (cpu_left, tuple(sorted(gpu_left, reverse=True)), gpu_type)
        if key in memo:
            return memo[key]
        total = sum(gpu_left)
        if total == 0:
            return 0.0
        if total * cum_prob < 1:
            return 0.0
        ratio_except_q3 = sum(
            t[4]
            for t in t_arr
            if classify(cpu_left, gpu_left, gpu_type, t) != Q3_SATISFIED
        )
        if depth >= max_depth:
            # Defensive truncation (the Go code has no depth limit; its
            # cum_prob cutoff bounds recursion in practice). Do NOT memoize:
            # the truncated value would poison shallow-depth revisits.
            return float(total)
        if ratio_except_q3 < 0.999:
            pv = 0.0
            for t in t_arr:
                p = t[4]
                nxt = sub(cpu_left, gpu_left, t)
                if nxt is None:
                    pv += total * p
                else:
                    pv += p * rec(nxt[0], nxt[1], gpu_type, cum_prob * p, depth + 1)
            frag = pv
        else:
            frag = float(total)
        memo[key] = frag
        return frag

    cpu_left, gpu_left, gpu_type = node
    return rec(int(cpu_left), tuple(int(g) for g in gpu_left), int(gpu_type), 1.0, 0)
