"""Resource algebra kernels: fit tests, placement (Sub), eviction (Add).

Re-implements pkg/type/resource.go:454-531 (Sub/Add), frag.go:447-458
(CanNodeHostPodOnGpuMemory), utils.go:950-1005 (IsNodeAccessibleToPod) and
cache/gpunodeinfo.go:136-204 (AllocateGpuId) as shape-static JAX functions
over a single node's device vector `gpu_left: i32[8]`; everything vmaps over
the node axis. 0-milli padding slots never fit a >0 request, so no explicit
device-count masking is needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpusim.constants import MAX_GPUS_PER_NODE, MILLI


def is_accessible(node_gpu_type, pod_gpu_mask):
    """GPU-model constraint check (ref: utils.go:957-1005).

    pod_gpu_mask == 0 (no constraint) → accessible anywhere, including
    CPU-only nodes. Otherwise the node's model bit must be set; CPU-only
    nodes (gpu_type == -1) match nothing.
    """
    node_bit = jnp.where(
        node_gpu_type >= 0, jnp.int32(1) << node_gpu_type.astype(jnp.int32), 0
    )
    return (pod_gpu_mask == 0) | ((pod_gpu_mask & node_bit) != 0)


def can_host_on_gpu(gpu_left, pod_gpu_milli, pod_gpu_num):
    """True if >= gpu_num devices each have >= gpu_milli free
    (ref: frag.go:447-458). Only meaningful for pod_gpu_milli > 0."""
    fit = (gpu_left >= pod_gpu_milli) & (pod_gpu_milli > 0)
    return fit.sum() >= pod_gpu_num


def gpu_frag_milli(gpu_left, pod_gpu_milli):
    """Total free milli on devices individually too small for the pod
    (ref: frag.go:205-213 GetGpuFragMilliByNodeResAndPodRes)."""
    return jnp.where(gpu_left < pod_gpu_milli, gpu_left, 0).sum()


def can_allocate(gpu_left, pod_gpu_milli, pod_gpu_num):
    """Feasibility of the Filter-phase AllocateGpuId two-pointer packer
    (ref: gpunodeinfo.go:169-201).

    The greedy pointer consumes floor(left/milli) request-units per device
    before advancing, so feasibility is exactly
    sum_d floor(left_d / milli) >= gpu_num. (For whole-GPU pods, milli==1000,
    this degenerates to can_host_on_gpu; trace pods with gpu_num > 1 always
    request milli == 1000 — pod.go:111-123 panics otherwise.)
    """
    units = jnp.where(pod_gpu_milli > 0, gpu_left // jnp.maximum(pod_gpu_milli, 1), 0)
    return units.sum() >= pod_gpu_num


def _stable_asc_order(gpu_left):
    """Ascending stable order of device indices (ref: resource.go:179-197)."""
    return jnp.argsort(gpu_left, stable=True)


def select_devices_packed(gpu_left, pod_gpu_milli, pod_gpu_num):
    """Sub's device choice: take gpu_num fitting devices, least-free first,
    ties by device index (ref: resource.go:454-480).

    Returns (dev_mask: bool[8], ok: bool).
    """
    order = _stable_asc_order(gpu_left)
    fit_sorted = (gpu_left[order] >= pod_gpu_milli) & (pod_gpu_milli > 0)
    take_sorted = fit_sorted & (jnp.cumsum(fit_sorted) <= pod_gpu_num)
    dev_mask = jnp.zeros_like(fit_sorted).at[order].set(take_sorted)
    ok = take_sorted.sum() >= pod_gpu_num
    return dev_mask, ok


def sub_pod(cpu_left, mem_left, gpu_left, pod):
    """Schedule the pod onto the node (ref: resource.go:454-480 Sub).

    Returns (cpu_left', mem_left', gpu_left', dev_mask, ok). On ok == False
    the returned state must be discarded by the caller (Go returns an error).
    Note Sub itself does not check memory; the scheduler's Filter does.
    """
    dev_mask, gpu_ok = select_devices_packed(gpu_left, pod.gpu_milli, pod.gpu_num)
    ok = (cpu_left >= pod.cpu) & ((pod.gpu_num == 0) | gpu_ok)
    new_gpu = gpu_left - dev_mask.astype(jnp.int32) * pod.gpu_milli
    return (
        cpu_left - pod.cpu,
        mem_left - pod.mem,
        jnp.where(pod.gpu_num > 0, new_gpu, gpu_left),
        dev_mask & (pod.gpu_num > 0),
        ok,
    )


def add_pod(cpu_left, mem_left, gpu_left, pod, dev_mask):
    """Evict the pod, returning its resources to the known devices
    (ref: resource.go:482-531 Add with a valid gpu-index list)."""
    return (
        cpu_left + pod.cpu,
        mem_left + pod.mem,
        gpu_left + dev_mask.astype(jnp.int32) * pod.gpu_milli,
    )


def allocate_exclusive(gpu_left, pod_total_milli):
    """First fully-free devices, in index order, until the whole-GPU request
    is covered (ref: resource.go:383-403 AllocateExclusiveGpuId).

    Returns a bool[8] device mask (empty if not enough idle devices).
    """
    free = gpu_left == MILLI
    need = (pod_total_milli + MILLI - 1) // MILLI
    take = free & (jnp.cumsum(free) <= need)
    enough = free.sum() * MILLI >= pod_total_milli
    return take & enough


def allocate_two_pointer(gpu_left, pod_gpu_milli, pod_gpu_num):
    """Reserve-phase AllocateGpuId for multi-GPU pods
    (ref: gpunodeinfo.go:182-201): walk devices in index order, taking
    floor(left/milli) request-units from each until gpu_num are packed.

    Returns (per-device unit counts i32[8], ok). With milli == 1000 (always
    true for trace multi-GPU pods) the counts are a 0/1 mask of the first
    gpu_num fully-fitting devices.
    """
    units = jnp.where(pod_gpu_milli > 0, gpu_left // jnp.maximum(pod_gpu_milli, 1), 0)
    cum = jnp.cumsum(units)
    prev = cum - units
    take = jnp.clip(pod_gpu_num - prev, 0, units)
    ok = cum[-1] >= pod_gpu_num
    return take, ok


def allocate_share_best(gpu_left, pod_gpu_milli):
    """Tightest-fit device for a share-GPU pod (ref: open_gpu_share.go:285-304
    allocateGpuIdBasedOnBestFit, and gpunodeinfo.go:169-181): min free milli
    among fitting devices, first index on ties. Returns device id or -1."""
    fits = gpu_left >= pod_gpu_milli
    key = jnp.where(fits, gpu_left, jnp.iinfo(jnp.int32).max)
    dev = jnp.argmin(key)  # argmin takes the first index on ties
    return jnp.where(fits.any(), dev, -1).astype(jnp.int32)


def allocate_share_worst(gpu_left, pod_gpu_milli):
    """Loosest-fit device (ref: open_gpu_share.go:306-325): max free milli
    among fitting devices, first index on ties."""
    fits = gpu_left >= pod_gpu_milli
    key = jnp.where(fits, gpu_left, jnp.iinfo(jnp.int32).min)
    dev = jnp.argmax(key)
    return jnp.where(fits.any(), dev, -1).astype(jnp.int32)


def allocate_share_random(gpu_left, pod_gpu_milli, key):
    """Uniform-random fitting device (ref: open_gpu_share.go:327-343
    reservoir sampling == uniform choice)."""
    fits = gpu_left >= pod_gpu_milli
    n = fits.sum()
    u = jax.random.uniform(key, (MAX_GPUS_PER_NODE,))
    score = jnp.where(fits, u, -1.0)
    dev = jnp.argmax(score)
    return jnp.where(n > 0, dev, -1).astype(jnp.int32)


def flatten_gpu_left(gpu_left):
    """Canonical dedup/memo key: devices sorted descending, padded to 8
    (ref: resource.go:199-215 Flatten)."""
    return -jnp.sort(-gpu_left)
