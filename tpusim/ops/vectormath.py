"""Vector helpers (ref: pkg/utils/utils.go:1181-1272).

Used by the DotProduct (Tetris) policy and the cosine-similarity descheduler.
The Go versions return -1 on malformed input; shapes are static here so only
the zero-magnitude guard survives.
"""

from __future__ import annotations

import jax.numpy as jnp


def cosine_similarity(a, b):
    """ref: utils.go:1196-1219 CalculateVectorCosineSimilarity; -1 when either
    vector has zero magnitude."""
    ma = jnp.sqrt((a * a).sum(-1))
    mb = jnp.sqrt((b * b).sum(-1))
    ok = (ma > 0) & (mb > 0)
    return jnp.where(ok, (a * b).sum(-1) / jnp.where(ok, ma * mb, 1.0), -1.0)


def dot_product(a, b):
    """ref: utils.go:1246-1256."""
    return (a * b).sum(-1)


def l2_norm_diff(a, b):
    """ref: utils.go:1258-1267 (squared L2 distance)."""
    d = a - b
    return (d * d).sum(-1)


def normalize_by(vec, norm):
    """Element-wise vec/norm with zero where norm <= 0
    (ref: utils.go:1221-1244 NormalizeVector)."""
    return jnp.where(norm > 0, vec / jnp.where(norm > 0, norm, 1.0), 0.0)


def sigmoid(x):
    """ref: plugin_utils.go:76-78."""
    return 1.0 / (1.0 + jnp.exp(-x))
