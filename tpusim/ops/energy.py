"""Node power model (ref: pkg/type/resource.go:533-563 GetEnergyConsumptionNode
and open-gpu-share/utils/const.go:48-121 energy tables).

GPU power: fully-idle devices draw idle watts, every other device draws full
watts (even minimally-used ones). CPU power: 2 vCPUs per physical core;
whole CPU packages flip from idle to full wattage as cores become busy.
"""

from __future__ import annotations

import jax.numpy as jnp

from tpusim.constants import (
    CPU_FULL_W,
    CPU_IDLE_W,
    CPU_NCORES,
    GPU_FULL_W,
    GPU_IDLE_W,
    MILLI,
)


def gpu_power_watts(gpu_left, gpu_cnt, gpu_type):
    """GPU watts for one node (ref: resource.go:537-545): fully-idle devices
    draw idle watts, every other device draws full watts."""
    gpu_idle_w = jnp.asarray(GPU_IDLE_W)
    gpu_full_w = jnp.asarray(GPU_FULL_W)
    num_idle_gpus = (gpu_left == MILLI).sum().astype(jnp.float32)
    num_working = gpu_cnt.astype(jnp.float32) - num_idle_gpus
    idle_w = jnp.where(gpu_type >= 0, gpu_idle_w[jnp.maximum(gpu_type, 0)], 0.0)
    full_w = jnp.where(gpu_type >= 0, gpu_full_w[jnp.maximum(gpu_type, 0)], 0.0)
    return idle_w * num_idle_gpus + full_w * num_working


def gpu_busy_delta_watts(gpu_type):
    """Per-device watts increase when a fully-idle device becomes working."""
    gpu_idle_w = jnp.asarray(GPU_IDLE_W)
    gpu_full_w = jnp.asarray(GPU_FULL_W)
    return jnp.where(
        gpu_type >= 0,
        gpu_full_w[jnp.maximum(gpu_type, 0)] - gpu_idle_w[jnp.maximum(gpu_type, 0)],
        0.0,
    )


def cpu_power_watts(cpu_left, cpu_cap, cpu_type):
    """CPU watts for one node (ref: resource.go:547-559): 2 vCPUs per
    physical core; whole packages flip from idle to full wattage."""
    cpu_idle_w = jnp.asarray(CPU_IDLE_W)
    cpu_full_w = jnp.asarray(CPU_FULL_W)
    cpu_ncores = jnp.asarray(CPU_NCORES)
    real_cores = jnp.ceil(cpu_cap.astype(jnp.float32) / MILLI / 2)
    idle_cores = jnp.floor(cpu_left.astype(jnp.float32) / MILLI / 2)
    working_cores = real_cores - idle_cores
    ncores = cpu_ncores[cpu_type]
    num_cpus = jnp.ceil(real_cores / ncores)
    active_cpus = jnp.ceil(working_cores / ncores)
    idle_cpus = num_cpus - active_cpus
    return cpu_idle_w[cpu_type] * idle_cpus + cpu_full_w[cpu_type] * active_cpus


def node_power(cpu_left, cpu_cap, gpu_left, gpu_cnt, gpu_type, cpu_type):
    """Returns (cpu_watts, gpu_watts) for one node; vmap over nodes."""
    return (
        cpu_power_watts(cpu_left, cpu_cap, cpu_type),
        gpu_power_watts(gpu_left, gpu_cnt, gpu_type),
    )
