from tpusim.ops import frag, resource, energy, vectormath

__all__ = ["frag", "resource", "energy", "vectormath"]
