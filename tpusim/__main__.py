import sys

from tpusim.cli import main

sys.exit(main())
