"""Multi-chip scale-out: shard the node axis over a device mesh.

The reference's only scale-out is process-level fan-out (xargs --max-procs,
experiments/README.md step 2) and a 16-way in-process parallelize helper over
nodes (vendored generic_scheduler.go:473-560). Here the node dimension itself
is sharded over a `jax.sharding.Mesh` axis ("nodes"): every policy/frag
kernel is embarrassingly parallel over nodes, so Filter+Score run fully local
to each chip and XLA inserts the cross-chip collectives (an all-reduce
max/argmin pair) only for the selectHost reduction and the cluster-level
metric sums — the natural ICI traffic pattern for this workload.

The event loop stays a lax.scan whose carry (NodeState) keeps the node-axis
sharding across iterations; per-event scatter updates touch one node row and
XLA keeps them local to the owning chip.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpusim.constants import NO_GPU
from tpusim.types import NodeState

NODE_AXIS = "nodes"

_INT_MAX = np.int32(np.iinfo(np.int32).max)


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D device mesh over the node axis."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def pad_nodes(
    state: NodeState, rank: jnp.ndarray, multiple: int
) -> Tuple[NodeState, jnp.ndarray]:
    """Pad the node axis to a multiple of the mesh size with never-feasible,
    never-chosen, metric-inert rows: mem_left = -1 fails every fit test (pod
    mem requests are >= 0), rank = INT_MAX loses every tie-break, and
    cpu_left = cpu_cap = gpu_cnt = 0 keeps the row out of every cluster
    aggregate (usage, power, frag all see an empty node)."""
    n = state.num_nodes
    pad = (-n) % multiple
    if pad == 0:
        return state, rank

    def pad0(x, fill=0):
        width = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, width, constant_values=fill)

    padded = NodeState(
        cpu_left=pad0(state.cpu_left),
        cpu_cap=pad0(state.cpu_cap),
        mem_left=pad0(state.mem_left, -1),
        mem_cap=pad0(state.mem_cap),
        gpu_left=pad0(state.gpu_left),
        gpu_cnt=pad0(state.gpu_cnt),
        gpu_type=pad0(state.gpu_type, NO_GPU),
        cpu_type=pad0(state.cpu_type),
        aff_cnt=pad0(state.aff_cnt),
    )
    return padded, jnp.concatenate(
        [rank, jnp.full(pad, _INT_MAX, jnp.int32)]
    )


def state_sharding(mesh: Mesh) -> NodeState:
    """NodeState pytree of NamedShardings: every array split on axis 0."""
    s = NamedSharding(mesh, P(NODE_AXIS))
    return NodeState(*([s] * len(NodeState._fields)))


def shard_state(state: NodeState, mesh: Mesh) -> NodeState:
    """Place NodeState arrays onto the mesh, node axis sharded. The node
    count must already be a multiple of the mesh size (see pad_nodes)."""
    return jax.device_put(state, state_sharding(mesh))


def _shard_replay_fn(inner, mesh: Mesh, extra_replicated: int):
    """Re-jit a (jit-wrapped) replay with the node axis of the cluster state
    split over `mesh`. Replay signatures are
    (state, pods, [types,] ev_kind, ev_pod, tp, key, tiebreak_rank); the
    state is node-sharded, the tie-break rank follows it, everything else is
    replicated. extra_replicated = number of extra leading args between
    `pods` and `ev_kind` (the table engine's PodTypes)."""
    fn = inner.__wrapped__ if hasattr(inner, "__wrapped__") else inner
    repl = NamedSharding(mesh, P())
    return jax.jit(
        fn,
        in_shardings=(
            state_sharding(mesh),  # state
            None,  # pods (replicated, let XLA decide)
            *([None] * extra_replicated),
            repl,  # ev_kind
            repl,  # ev_pod
            None,  # typical pods
            repl,  # key
            NamedSharding(mesh, P(NODE_AXIS)),  # tiebreak_rank
        ),
    )


def make_sharded_replay(
    policies: Sequence[Tuple[object, int]],
    mesh: Mesh,
    gpu_sel: str = "best",
    report: bool = True,
):
    """Sharded twin of tpusim.sim.engine.make_replay: same trace-replay scan,
    jitted with the node axis of the cluster state split over `mesh` and
    everything else (pod batch, event stream, typical pods) replicated."""
    from tpusim.sim.engine import make_replay

    return _shard_replay_fn(
        make_replay(policies, gpu_sel=gpu_sel, report=report), mesh, 0
    )


def make_sharded_table_replay(
    policies: Sequence[Tuple[object, int]],
    mesh: Mesh,
    gpu_sel: str = "best",
):
    """Sharded twin of tpusim.sim.table_engine.make_table_replay: the
    [policy, K, N] score/feasibility/device tables inherit the node-axis
    sharding from the cluster state, so per-event work is the one-column
    refresh local to the owning chip plus the selectHost all-reduce.
    Metric-free like the engine it wraps — report series come from the
    shared post-pass (tpusim.sim.metrics) over the replicated telemetry."""
    from tpusim.sim.table_engine import make_table_replay

    # force the flat select: this engine's premise is letting the SPMD
    # partitioner shard the flat [.., N] tables along the node axis; the
    # blocked layout's block-summary tables would be partitioned
    # unpredictably (the explicit-collective shard_engine is the path that
    # composes with blocking — see its block_size knob)
    return _shard_replay_fn(
        make_table_replay(policies, gpu_sel=gpu_sel, block_size=-1), mesh, 1
    )
