"""Explicit-collective sharded replay (shard_map) — flat per-event cost.

The first sharded engine (tpusim.parallel.sharding) re-jits the table engine
with node-axis in_shardings and lets XLA's SPMD partitioner insert the
collectives. That proves equality, but the partitioner turns the per-event
dynamic gathers/scatters at the winning node's index (state.gpu_left[node],
.at[node].add, the dirty-column refresh) into whole-array movement, so
us/event GROWS with mesh size (MULTICHIP round-2 table: 2751 -> 9731 us/event
from 1 -> 8 virtual devices).

This engine writes the communication by hand with jax.shard_map, the way the
scaling-book recipe says to when the partitioner's choices matter:

  - Filter/Score/table refresh are LOCAL: each shard owns N/D node rows and
    the matching [K, N/D] score-table shard; the dirty-node column refresh
    runs on every shard but only the owner's masked write lands.
  - selectHost is a local argmax + THREE scalar collectives: pmax of the
    best local score, pmin of the winning tie-break rank among score-tied
    shards, psum of the winner's global node id (ranks are a permutation,
    so exactly one shard contributes). Lexicographically identical to the
    global (max score, min rank) selection in sim.step.select_and_bind.
  - Reserve/Bind are OWNER-LOCAL: the owning shard computes the device mask
    from its local row (sim.step.choose_devices — the same helper the
    global engine binds with) and applies the row update; one [8]-wide psum
    publishes the device mask for the replicated bookkeeping arrays.
  - Per-event metrics never touch the loop at all: like every engine since
    round 5, the replay is metric-free and the report series is
    reconstructed from the replicated (event_node, event_dev) telemetry by
    the shared post-pass (tpusim.sim.metrics) — byte-identical to the
    single-device engines by construction, vs the reference recomputing
    cluster metrics after every event (simulator.go:426-427).

Per-event collective payload: 3 scalars + one 8-lane mask, independent of
N and D — the us/event curve stays flat as the mesh grows (MULTICHIP.md).
Placements are bit-identical to the single-device table engine.

Since ISSUE 11 the step body is SOFTWARE-PIPELINED one event deep, the
way Round 6 restructured the single-device table engine: each iteration
first applies the PREVIOUS event's deferred commit (the replicated
`sim.step.PendingCommit` register riding ShardTableCarry — owner-masked
state scatters via `apply_commit_sharded`, replicated [P+1] bookkeeping
writes) and only then reads state/tables, so every carried buffer is
written before it is read and XLA aliases the scatters in place instead
of taking a whole-buffer defensive copy per event. Under the fault lane
the fault step kinds flow through the same discipline: the DECISION
(victim draw, queue bookkeeping — fc is read-modify-write in-line, it is
small) happens at the event, while the state/bookkeeping WRITES ride a
second register (`fault_lane.FaultPending`) applied right after the bind
commit at the top of the next iteration. The collective payload is
untouched and placements/telemetry/counters are bit-identical to the
unpipelined body by construction (the same scatters land before anything
reads them); `pipelined=False` keeps the old in-body commit for A/B
measurement (bench_multichip --scale-lane). At nloc = N/D >= ~10k the
eliminated copies dominate the loop — the 1M-node lane headline
(MULTICHIP.md "The 1M-node lane").
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpusim.constants import MAX_GPUS_PER_NODE, MAX_NODE_SCORE
from tpusim.obs import series as obs_series
from tpusim.obs.counters import counter_delta, zero_counters
from tpusim.obs.decisions import DECISION_TOPK, DecisionRecord, no_decision
from tpusim.policies.base import (
    NORMALIZE_DEGENERATE,
    feasible_min_max,
    minmax_scale_i32,
)
from tpusim.sim.engine import ReplayResult
from tpusim.sim.step import (
    PendingCommit,
    apply_commit,
    apply_commit_sharded,
    block_reduce,
    choose_devices,
    make_pending_commit,
    no_pending_commit,
    packed_argmax,
    packed_topk,
)
from tpusim.sim.table_engine import (
    PodTypes,
    _pad_rank,
    _row_state,
    make_table_builders,
    reject_randomized,
    resolve_block_size,
    selector_index,
)
from tpusim.types import NodeState, PodSpec

from tpusim.parallel.sharding import NODE_AXIS

_INT_MAX = jnp.int32(jnp.iinfo(jnp.int32).max)


class ShardTableCarry(NamedTuple):
    """Complete sharded-engine state between two events — the shard_map
    scan carry, promoted to a pytree the driver can gather to host
    (np.asarray on each leaf collects the shards), checkpoint, and feed
    back in; jit re-shards it against the same mesh on resume, so the
    continued scan is bit-identical to the uninterrupted one. state and
    the packed table / block summaries are node-axis sharded; everything
    else is replicated (identical on every shard by construction)."""

    state: NodeState  # node-axis sharded, [nloc] rows per shard
    packed_tbl: jnp.ndarray  # i32[K, nloc(_p), npol+2] scores|sdev|feas
    lt: jnp.ndarray  # i32[K, nloc/B] block max totals ([0,0] when flat)
    lr: jnp.ndarray  # i32[K, nloc/B] block min winner ranks
    lwn: jnp.ndarray  # i32[K, nloc/B] block winner LOCAL node indices
    # the software-pipeline register (ISSUE 11): the previous event's
    # deferred commit, replicated (node is the GLOBAL winner id); inert
    # no_pending_commit forever on pipelined=False builds
    pend: PendingCommit
    dirty: jnp.ndarray  # i32 global node id to refresh next (replicated)
    placed: jnp.ndarray  # i32[P+1] (replicated; dummy row absorbs the
    #                      pipelined commit's skip writes, like the table
    #                      engines — finish() strips it)
    masks: jnp.ndarray  # bool[P+1, 8]
    failed: jnp.ndarray  # bool[P+1]
    arr_cpu: jnp.ndarray  # i32
    arr_gpu: jnp.ndarray  # i32
    key: jnp.ndarray  # PRNG key after the events consumed so far
    # i32[obs.NUM_COUNTERS] exact in-scan counters (tpusim.obs.counters)
    # — replicated: every shard adds the same delta from the replicated
    # (kind, node) decision. `rebuilds` stays 0 here (block summaries
    # refresh unconditionally; there is no drift-cond to count).
    ctr: jnp.ndarray


def make_shardmap_table_replay(policies, mesh, gpu_sel: str = "best",
                               report: bool = False, block_size: int = 0,
                               decisions: bool = False,
                               series_every: int = 0,
                               faults: bool = False,
                               pipelined: bool = True):
    """Build the explicit-collective sharded replayer. The node count must
    already be padded to a multiple of the mesh size (parallel.pad_nodes)
    and `state`/`tiebreak_rank` sharded over it (parallel.shard_state).
    Metric-free like every engine; build the report series with
    tpusim.sim.metrics.compute_event_metrics over the replicated
    telemetry.

    block_size (resolve_block_size over the PER-DEVICE node count) turns
    on blocked local selectHost inputs for configs whose policies all use
    normalize == "none": each shard keeps per-(type, block-of-B) summaries
    (max total, min tie-break rank, winner node) refreshed only at the
    touched node's block, so the per-device selectHost reduction consumes
    nloc/B block maxima instead of nloc node rows. The cross-device
    collective payload itself was already N-independent (3 scalars + one
    8-lane mask) and is unchanged — the block maxima shrink what each
    device reduces before contributing its scalar. Normalized policies
    (minmax/pwr need global extrema collectives per event) keep the flat
    local path regardless of block_size.

    decisions=True (ISSUE 4) additionally emits the per-event
    DecisionRecord stream. The top-K summaries CROSS the collective: each
    shard reduces its local score rows to its top-DECISION_TOPK
    (total, rank, global node id) candidates, an all_gather collects the
    D×K summaries, and the replicated merge reruns the SAME packed-key
    top-K over them — exact because the global k-th best always lies
    within its own shard's local top-K, and the (max total, min rank)
    combine is the one every engine selects with. The winner's
    per-policy raw/normalized columns and the feasible count cross as
    owner-masked psums. Per-event collective payload grows by
    3×DECISION_TOPK i32 lanes + (2×num_policies + 1) scalars — still
    independent of N and D.

    series_every > 0 (ISSUE 5) additionally emits the in-scan
    SeriesSample stream (tpusim.obs.series). Every sample field is an
    integer reduction, so the shard decomposition is exact: util
    histogram / DOWN count / per-category frag cross as psums of
    per-shard integer partials (cluster_stats rounds each NODE's frag
    row to whole milli BEFORE summing, so the total cannot depend on the
    node partition); normalized score extrema cross as the same
    pmin/pmax pair the flat select path normalizes with, then the
    per-policy hi/lo cross as one pmax/pmin each. Mesh pad rows are
    masked by their rank == INT_MAX sentinel (they carry the DOWN
    nodes' mem_left == -1 and must count as neither). Samples land only
    at stride points (a replicated cond), so the extra collective
    payload amortizes to O(1/series_every) per event. ys become
    (node, dev[, dec][, ser]) in that order, like the table engine.

    pipelined=True (ISSUE 11, the default) software-pipelines the step
    body one event deep (module docstring): the Bind scatter and — under
    faults — the fault-step row writes ride pending registers applied at
    the top of the next iteration, so the body is strictly
    write-then-read and the per-event whole-buffer state copies vanish.
    Bit-identical to pipelined=False (the pre-ISSUE-11 in-body commit,
    kept for A/B measurement) for every policy/mix/gpu_sel and under the
    fault lane; both paths share one carry layout ([P+1] bookkeeping +
    the — possibly inert — pend register), so the driver's chunked
    checkpoint dispatch is knob-agnostic."""
    if report:
        raise ValueError(
            "the shard_map engine replays metric-free; build the report "
            "series with tpusim.sim.metrics.compute_event_metrics"
        )
    if faults and (decisions or series_every):
        raise ValueError(
            "the in-scan fault plane (faults=True) does not combine with "
            "decisions/series builds on the shard engine"
        )
    if faults:
        # fault transitions touch exactly one node row, so the DOWN
        # masking IS the mem_left == -1 pad sentinel the local Filter
        # already rejects; the requeue scatter and disruption counters
        # are replicated bookkeeping (identical on every shard), and the
        # state row resets/returns are owner-masked via the global-id
        # row mask. The recover frag-delta capture stays OFF here — a
        # psum of f32 partials cannot be bit-equal to the single-device
        # cluster sum (ENGINES.md Round 14).
        from tpusim.sim import fault_lane as _fl
    reject_randomized(policies, gpu_sel)
    sel_idx = selector_index(policies, gpu_sel)
    _columns, _init_tables = make_table_builders(policies, sel_idx)
    npol = len(policies)
    n_dev = mesh.shape[NODE_AXIS]
    all_none_norm = all(fn.normalize == "none" for fn, _ in policies)

    def _local_totals(rows, wts):
        """Weighted totals with -INT_MAX at infeasible entries from a
        packed-layout slice [..., C] (none-normalize configs only).
        `wts` is the traced i32[num_pol] weight operand (ISSUE 6)."""
        tot = jnp.zeros(rows.shape[:-1], jnp.int32)
        for i in range(npol):
            tot = tot + wts[i] * rows[..., i]
        return jnp.where(rows[..., npol + 1] != 0, tot, -_INT_MAX)

    def _resolve_bsz(nloc: int, k_types: int) -> int:
        return (
            resolve_block_size(block_size, nloc, k_types)
            if all_none_norm else 0
        )

    def _init_shard(state, rank, pods, types, tp, key, wts,
                    fault_carry0=None):
        """Per-shard carry at event 0: local table shards + blocked local
        summaries + replicated bookkeeping (state/rank are the LOCAL node
        rows; wts is the replicated weight operand)."""
        nloc = state.num_nodes
        num_pods = pods.cpu.shape[0]

        key, k_init = jax.random.split(key)
        s0, d0, f0 = _init_tables(state, types, tp, k_init)
        packed_tbl = jnp.concatenate(
            [jnp.moveaxis(s0, 0, -1), d0[..., None],
             f0.astype(jnp.int32)[..., None]],
            axis=-1,
        )  # [K, nloc, C]

        k_types = int(types.share.cpu.shape[0]) + int(types.whole.cpu.shape[0])
        bsz = _resolve_bsz(nloc, k_types)

        if bsz:
            nbl = -(-nloc // bsz)
            nloc_p = nbl * bsz
            if nloc_p != nloc:
                # sentinel columns: feas 0 -> -INT_MAX totals, never chosen
                packed_tbl = jnp.pad(
                    packed_tbl, ((0, 0), (0, nloc_p - nloc), (0, 0))
                )
            rank_p = _pad_rank(rank, nloc_p)
            loffs = jnp.arange(nbl, dtype=jnp.int32) * bsz
            lt, lr, la = block_reduce(
                _local_totals(packed_tbl, wts).reshape(k_types, nbl, bsz),
                rank_p.reshape(nbl, bsz),
            )
            lwn = loffs[None, :] + la  # [K, nbl] local winner node indices
        else:
            lt = lr = lwn = jnp.zeros((0, 0), jnp.int32)

        # one extra dummy row absorbs skip-event writes of the pipelined
        # commit (PendingCommit.pod_write); sliced off by finish(). The
        # unpipelined path shares the layout (its in-body writes never
        # touch the dummy row), so both knobs run one carry shape.
        placed = jnp.full(num_pods + 1, -1, jnp.int32)
        masks = jnp.zeros((num_pods + 1, MAX_GPUS_PER_NODE), jnp.bool_)
        failed = jnp.zeros(num_pods + 1, jnp.bool_)
        z = jnp.int32(0)
        base = ShardTableCarry(
            state, packed_tbl, lt, lr, lwn, no_pending_commit(num_pods),
            z, placed, masks, failed, z, z, key, zero_counters(),
        )
        if not faults:
            return base
        fcp = _fl.pad_fault_carry(fault_carry0)
        if pipelined:
            return (base, fcp, _fl.no_fault_pending(num_pods + 1))
        return (base, fcp)

    def _chunk_shard(carry, rank, pods, types, ev_kind, ev_pod, tp, wts,
                     fault_ops=None):
        """Advance a per-shard carry over one event segment (the scan the
        one-shot replay runs over the whole stream). `wts` must be the
        weight vector the carry was initialized under (the blocked local
        summaries embed it)."""
        base0 = carry[0] if faults else carry
        nloc = base0.state.num_nodes
        me = jax.lax.axis_index(NODE_AXIS)
        offset = (me * nloc).astype(jnp.int32)
        gids = offset + jnp.arange(nloc, dtype=jnp.int32)
        num_pods = pods.cpu.shape[0]
        type_id = types.type_id
        k_types = int(types.share.cpu.shape[0]) + int(types.whole.cpu.shape[0])
        bsz = _resolve_bsz(nloc, k_types)
        rank_p = (
            _pad_rank(rank, base0.packed_tbl.shape[1]) if bsz else rank
        )

        def body(carry, ev):
            fpend = None
            if faults:
                if pipelined:
                    carry, fc, fpend = carry
                else:
                    carry, fc = carry
                kind, idx, fpos, farg, faux = ev
            (state, packed_tbl, lt, lr, lwn, pend, dirty, placed, masks,
             failed, arr_cpu, arr_gpu, key, ctr) = carry
            if pipelined:
                # apply the PREVIOUS event's deferred scatters first —
                # every carried buffer is written before anything reads
                # it this iteration, so all updates alias in place
                # (sim.step.PendingCommit; the state half is owner-masked
                # on this shard's local row window)
                state, placed, masks, failed = apply_commit_sharded(
                    state, placed, masks, failed, pend, offset, nloc
                )
                if faults:
                    # ... then the previous event's fault writes (row
                    # reset / evict return / victim clearing) — the same
                    # in-line order the unpipelined body commits in
                    state, placed, masks, failed = _fl.apply_fault_pending(
                        state, placed, masks, failed, fpend, offset, nloc
                    )
            if not faults:
                kind, idx = ev
                kc = jnp.clip(kind, 0, 2)
            else:
                from tpusim.sim.engine import EV_RETRY

                is_slot = kind == EV_RETRY
                fc, has_pop, rpod = _fl.pop_retry(fc, is_slot, fpos, farg)
                idx = jnp.where(has_pop, rpod, idx)
                kc = jnp.where(
                    is_slot, jnp.where(has_pop, 0, 2),
                    jnp.clip(kind, 0, 2),
                )
            pod = jax.tree.map(lambda a: a[idx], pods)
            t_id = type_id[idx]
            key, k_col, k_sel = jax.random.split(key, 3)

            # dirty-column refresh: ONLY the owning shard computes (a real
            # lax.cond branch — non-owners skip the K-type scoring sweep
            # entirely, which also keeps the single-host virtual mesh from
            # paying D redundant refreshes per event)
            li = dirty - offset
            owns_d = (li >= 0) & (li < nloc)
            lic = jnp.clip(li, 0, nloc - 1)

            if pipelined:
                # no whole-buffer operand may cross the cond boundary
                # (ISSUE 11): XLA copies big buffers captured by branch
                # computations, so the cond closes over only the
                # PRE-GATHERED one-node row, and the column write is an
                # owner-masked OOB-drop scatter — non-owners write
                # nothing instead of reading back the old column. No
                # packed_tbl read, no DUS: the update touches exactly
                # one column's elements.
                row1 = _row_state(state, lic)

                def refresh_col_p():
                    cs, cd, cf = _columns(row1, types, tp, k_col)
                    return jnp.concatenate(
                        [cs.T, cd[:, None],
                         cf.astype(jnp.int32)[:, None]],
                        axis=-1,
                    )  # [K, C]

                col = jax.lax.cond(
                    owns_d,
                    refresh_col_p,
                    lambda: jnp.zeros(
                        (k_types, npol + 2), jnp.int32
                    ),
                )
                tgt_col = jnp.where(
                    owns_d, lic, packed_tbl.shape[1]
                )
                packed_tbl = packed_tbl.at[:, tgt_col, :].set(
                    col, mode="drop"
                )
            else:
                # the cond computes only the [K, 1, C] column (non-owners
                # reuse the old slice); the table write itself stays
                # OUTSIDE the cond so XLA can alias the
                # dynamic_update_slice in place — a cond returning the
                # whole table forces a full-buffer copy per event
                def refresh_col():
                    cs, cd, cf = _columns(
                        _row_state(state, lic), types, tp, k_col
                    )
                    return jnp.concatenate(
                        [cs.T, cd[:, None],
                         cf.astype(jnp.int32)[:, None]],
                        axis=-1,
                    )[:, None, :]

                new_col = jax.lax.cond(
                    owns_d,
                    refresh_col,
                    lambda: jax.lax.dynamic_slice_in_dim(
                        packed_tbl, lic, 1, axis=1
                    ),
                )
                packed_tbl = jax.lax.dynamic_update_slice_in_dim(
                    packed_tbl, new_col, lic, axis=1
                )

            if bsz:
                # dirty-block summary refresh for all K types: non-owner
                # shards recompute an unchanged block (idempotent), owners
                # fold the refreshed column in — O(K*B) either way
                blk = lic // bsz
                j0 = blk * bsz
                rows_blk = jax.lax.dynamic_slice(
                    packed_tbl, (0, j0, 0),
                    (k_types, bsz, npol + 2),
                )
                rank_blk = jax.lax.dynamic_slice(rank_p, (j0,), (bsz,))
                bm, brk, bar = block_reduce(
                    _local_totals(rows_blk, wts), rank_blk
                )
                lt = jax.lax.dynamic_update_slice(lt, bm[:, None], (0, blk))
                lr = jax.lax.dynamic_update_slice(lr, brk[:, None], (0, blk))
                lwn = jax.lax.dynamic_update_slice(
                    lwn, (j0 + bar)[:, None], (0, blk)
                )

            if series_every:
                # in-scan series sample (ISSUE 5): replicated stride
                # clock; every field crosses the mesh as an exact integer
                # collective (module docstring). All shards take the same
                # cond branch (processed is replicated), so the
                # collectives inside it always pair up.
                processed = ctr[0] + ctr[3] + ctr[4]

                def _build_sample():
                    real = rank < _INT_MAX  # mesh pad rows: rank sentinel
                    hist_l, down_l, frag_l = obs_series.cluster_stats(
                        state, tp, node_mask=real
                    )
                    hist = jax.lax.psum(hist_l, NODE_AXIS)
                    down = jax.lax.psum(down_l, NODE_AXIS)
                    frag = jax.lax.psum(frag_l, NODE_AXIS)
                    rows_t = jax.lax.dynamic_index_in_dim(
                        packed_tbl, t_id, 0, False
                    )  # [nloc(_p), C]; block pad columns are infeasible
                    feas_l = rows_t[:, npol + 1] != 0
                    feas_cnt = jax.lax.psum(
                        feas_l.sum().astype(jnp.int32), NODE_AXIS
                    )
                    any_f = feas_cnt > 0
                    his, los = [], []
                    for i, (fn, _) in enumerate(policies):
                        raw = rows_t[:, i]
                        if fn.normalize in ("minmax", "pwr"):
                            # local extrema + pmin/pmax = the global
                            # reduction, scaled by the same core the
                            # unsharded engines normalize with
                            lo_l, hi_l = feasible_min_max(raw, feas_l)
                            nrm = minmax_scale_i32(
                                raw, feas_l,
                                jax.lax.pmin(lo_l, NODE_AXIS),
                                jax.lax.pmax(hi_l, NODE_AXIS),
                                NORMALIZE_DEGENERATE[fn.normalize],
                            )
                        else:  # RandomScore cannot reach the shard engine
                            nrm = raw
                        hi_i = jax.lax.pmax(
                            jnp.max(jnp.where(feas_l, nrm, -_INT_MAX)),
                            NODE_AXIS,
                        )
                        lo_i = jax.lax.pmin(
                            jnp.min(jnp.where(feas_l, nrm, _INT_MAX)),
                            NODE_AXIS,
                        )
                        his.append(jnp.where(any_f, hi_i, 0))
                        los.append(jnp.where(any_f, lo_i, 0))
                    return obs_series.SeriesSample(
                        pos=processed.astype(jnp.int32),
                        util_hist=hist,
                        nodes_down=down,
                        feasible=feas_cnt,
                        frag=frag,
                        score_hi=jnp.stack(his).astype(jnp.int32),
                        score_lo=jnp.stack(los).astype(jnp.int32),
                    )

                ser = obs_series.emit_from_scan(
                    series_every, processed, _build_sample, npol
                )
            else:
                ser = ()

            def do_create():
                if bsz:
                    # blocked local selectHost: reduce nloc/B block
                    # summaries instead of nloc rows; the 3-scalar
                    # collective combine below is unchanged
                    lt_row = jax.lax.dynamic_index_in_dim(lt, t_id, 0, False)
                    lr_row = jax.lax.dynamic_index_in_dim(lr, t_id, 0, False)
                    lw_row = jax.lax.dynamic_index_in_dim(lwn, t_id, 0, False)
                    blk_i, best_l, okb = packed_argmax(
                        lt_row, lt_row != -_INT_MAX, lr_row
                    )
                    am_l = lw_row[blk_i]
                    rank_l = jnp.where(okb, lr_row[blk_i], _INT_MAX)
                    # pinned pods: exactly one candidate, owned by exactly
                    # one shard — the winner is the pinned node iff Filter
                    # passes there (the flat path encodes the same through
                    # its feasibility mask)
                    pin_l = pod.pinned - offset
                    owns_pin = (pin_l >= 0) & (pin_l < nloc)
                    pin_c = jnp.clip(pin_l, 0, nloc - 1)
                    pin_row = jax.lax.dynamic_slice(
                        packed_tbl, (t_id, pin_c, 0), (1, 1, npol + 2)
                    )[0, 0]
                    pin_ok = owns_pin & (pin_row[npol + 1] != 0)
                    pin_tot = jnp.zeros((), jnp.int32)
                    for i in range(npol):
                        pin_tot = pin_tot + wts[i] * pin_row[i]
                    pinned = pod.pinned >= 0
                    best_l = jnp.where(
                        pinned, jnp.where(pin_ok, pin_tot, -_INT_MAX), best_l
                    )
                    rank_l = jnp.where(
                        pinned, jnp.where(pin_ok, rank[pin_c], _INT_MAX),
                        rank_l,
                    )
                    am_l = jnp.where(pinned, pin_c, am_l)
                    if decisions:
                        # full local rows for the provenance capture
                        # (none-normalize configs only: norm == raw)
                        rows_t = jax.lax.dynamic_index_in_dim(
                            packed_tbl, t_id, 0, False
                        )  # [nloc_p, C]
                        nloc_p = rows_t.shape[0]
                        gids_p = offset + jnp.arange(nloc_p, dtype=jnp.int32)
                        d_raws = rows_t[:, :npol].T
                        d_norms = d_raws
                        d_feas = (rows_t[:, npol + 1] != 0) & (
                            (pod.pinned < 0) | (gids_p == pod.pinned)
                        )
                        d_tot = _local_totals(rows_t, wts)
                        d_rank = rank_p
                else:
                    row = packed_tbl[t_id]  # [nloc, C]
                    feasible = (row[:, npol + 1] != 0) & (
                        (pod.pinned < 0) | (gids == pod.pinned)
                    )
                    total = jnp.zeros(nloc, jnp.int32)
                    d_raw_rows, d_norm_rows = [], []
                    for i, (fn, _) in enumerate(policies):
                        raw = row[:, i]
                        nrm = raw
                        if fn.normalize in ("minmax", "pwr"):
                            # local extrema + pmin/pmax = the global
                            # reduction; the scaling core is the same code
                            # the unsharded engines normalize with
                            lo_l, hi_l = feasible_min_max(raw, feasible)
                            lo = jax.lax.pmin(lo_l, NODE_AXIS)
                            hi = jax.lax.pmax(hi_l, NODE_AXIS)
                            nrm = minmax_scale_i32(
                                raw, feasible, lo, hi,
                                0 if fn.normalize == "minmax"
                                else MAX_NODE_SCORE,
                            )
                        if decisions:
                            d_raw_rows.append(raw)
                            d_norm_rows.append(nrm)
                        total = total + wts[i] * nrm

                    # selectHost: local argmax + 3 scalar collectives
                    best_l = jnp.max(jnp.where(feasible, total, -_INT_MAX))
                    wkey = jnp.where(
                        feasible & (total == best_l), -rank, -_INT_MAX
                    )
                    am_l = jnp.argmax(wkey).astype(jnp.int32)
                    rank_l = -wkey[am_l]  # INT_MAX when no candidate
                    if decisions:
                        d_raws = jnp.stack(d_raw_rows)
                        d_norms = jnp.stack(d_norm_rows)
                        d_feas = feasible
                        d_tot = total
                        d_rank = rank
                g_best = jax.lax.pmax(best_l, NODE_AXIS)
                g_rank = jax.lax.pmin(
                    jnp.where(best_l == g_best, rank_l, _INT_MAX), NODE_AXIS
                )
                ok = g_best != -_INT_MAX
                win = ok & (best_l == g_best) & (rank_l == g_rank)
                gnode = jax.lax.psum(
                    jnp.where(win, offset + am_l, 0), NODE_AXIS
                ).astype(jnp.int32)

                # Reserve: owner-local device choice; one [8] psum
                # publishes the device mask for the replicated bookkeeping
                # (the Bind scatter runs outside the switch — see below)
                ln = jnp.clip(gnode - offset, 0, nloc - 1)
                owner = (gnode >= offset) & (gnode < offset + nloc)
                if bsz:
                    pdev = jax.lax.dynamic_slice(
                        packed_tbl, (t_id, ln, npol), (1, 1, 1)
                    )[0, 0, 0]
                else:
                    pdev = row[ln, npol]
                dmask_l = choose_devices(
                    state.gpu_left[ln], pod, pdev, gpu_sel, k_sel
                ) & ok
                dev_mask = (
                    jax.lax.psum(
                        jnp.where(owner, dmask_l, False).astype(jnp.int32),
                        NODE_AXIS,
                    )
                    > 0
                )
                node_f = jnp.where(ok, gnode, -1).astype(jnp.int32)
                if not decisions:
                    return node_f, dev_mask
                # ---- decision provenance (replicated) ----
                # local top-K candidates -> (total, rank, global id)
                # summaries across the collective -> replicated merge with
                # the same packed-key top-K every engine orders by. Exact:
                # the global k-th best is inside its shard's local top-K.
                lpos, ltot, lrnk, lok = packed_topk(
                    d_tot, d_feas, d_rank, DECISION_TOPK
                )
                lgid = jnp.where(lok, offset + lpos, -1).astype(jnp.int32)
                ag = jax.lax.all_gather(
                    jnp.stack([ltot, lrnk, lgid]), NODE_AXIS
                )  # [D, 3, K]
                gtot = ag[:, 0, :].reshape(-1)
                grnk = ag[:, 1, :].reshape(-1)
                ggid = ag[:, 2, :].reshape(-1)
                mpos, mtot, mrnk, mok = packed_topk(
                    gtot, ggid >= 0, grnk, DECISION_TOPK
                )
                mnode = jnp.where(
                    mok, ggid[jnp.maximum(mpos, 0)], -1
                ).astype(jnp.int32)
                # winner columns + feasible count: owner-masked psums
                win_raw = jax.lax.psum(
                    jnp.where(owner & ok, d_raws[:, ln], 0), NODE_AXIS
                ).astype(jnp.int32)
                win_norm = jax.lax.psum(
                    jnp.where(owner & ok, d_norms[:, ln], 0), NODE_AXIS
                ).astype(jnp.int32)
                feas_cnt = jax.lax.psum(
                    d_feas.sum().astype(jnp.int32), NODE_AXIS
                )
                if bsz:
                    nbl = lt.shape[1]
                    blk_g = jax.lax.psum(
                        jnp.where(owner & ok, me * nbl + ln // bsz, 0),
                        NODE_AXIS,
                    ).astype(jnp.int32)
                    win_blk = jnp.where(ok, blk_g, -1).astype(jnp.int32)
                else:
                    win_blk = jnp.int32(-1)
                dec = DecisionRecord(
                    node=node_f,
                    total=jnp.where(ok, g_best, 0).astype(jnp.int32),
                    raw=win_raw,
                    norm=win_norm,
                    topk_node=mnode,
                    topk_total=mtot,
                    topk_rank=mrnk,
                    feasible=feas_cnt,
                    block=win_blk,
                )
                return node_f, dev_mask, dec

            def do_delete():
                base = placed[idx], masks[idx]
                return base + ((no_decision(npol),) if decisions else ())

            def do_skip():
                base = (
                    jnp.int32(-1), jnp.zeros(MAX_GPUS_PER_NODE, jnp.bool_)
                )
                return base + ((no_decision(npol),) if decisions else ())

            # either way the event decision is only the replicated
            # (node, dev_mask[, dec]) — a carried buffer returned from a
            # branch cannot alias the carry (the round-6 restructure);
            # the pipelined path goes further and drops the switch itself
            if pipelined:
                # no lax.switch around the create path (ISSUE 11): branch
                # computations capture the score-table/state buffers, and
                # XLA materializes whole-buffer copies for captured
                # conditional operands — the dominant per-event cost at
                # nloc >= ~100k. The create computation is pure (the
                # commit is deferred through the register), so it runs
                # UNCONDITIONALLY and the small (node, dev[, dec])
                # results merge by event kind. Collectives now run on
                # every event (delete/skip included) with the same
                # per-event payload; all shards agree on kc, so they
                # always pair up.
                outs_c = do_create()
                outs_d = do_delete()
                outs_s = do_skip()
                outs = tuple(
                    jax.tree.map(
                        lambda a, b, c: jnp.where(
                            kc == 0, a, jnp.where(kc == 1, b, c)
                        ),
                        oc, od, os_,
                    )
                    for oc, od, os_ in zip(outs_c, outs_d, outs_s)
                )
            else:
                outs = jax.lax.switch(kc, [do_create, do_delete, do_skip])
            if decisions:
                node, dev, dec = outs
            else:
                node, dev = outs
            is_create = kc == 0
            is_delete = kc == 1
            if pipelined:
                # defer this event's scatters to the next iteration: the
                # register is replicated (node is the GLOBAL winner id);
                # apply_commit_sharded owner-masks the state half
                pend = make_pending_commit(kc, idx, node, dev, pod,
                                           num_pods)
                if faults:
                    # retry creates accumulate ever-failed with OR (the
                    # segmented path's per-segment `|=`); base creates
                    # still overwrite (they run once per pod)
                    pend = pend._replace(failed_val=jnp.where(
                        is_slot, failed[idx] | (node < 0), node < 0
                    ))
            else:
                lbind = jnp.clip(node - offset, 0, nloc - 1)
                apply = (node >= 0) & (node >= offset) & (
                    node < offset + nloc
                )
                rs = jnp.where(is_delete, 1, -1)  # delete returns
                from tpusim.policies.clustering import pod_affinity_class

                cls = pod_affinity_class(pod)
                state = state._replace(
                    cpu_left=state.cpu_left.at[lbind].add(
                        jnp.where(apply, rs * pod.cpu, 0)
                    ),
                    mem_left=state.mem_left.at[lbind].add(
                        jnp.where(apply, rs * pod.mem, 0)
                    ),
                    gpu_left=state.gpu_left.at[lbind].add(
                        jnp.where(apply, rs, 0)
                        * dev.astype(jnp.int32) * pod.gpu_milli
                    ),
                    aff_cnt=state.aff_cnt.at[lbind, jnp.maximum(cls, 0)].add(
                        jnp.where(apply & (cls >= 0), -rs, 0)
                    ),
                )
                placed = placed.at[idx].set(
                    jnp.where(is_create, node,
                              jnp.where(is_delete, -1, placed[idx]))
                )
                masks = masks.at[idx].set(
                    jnp.where(is_create, dev,
                              jnp.where(is_delete, False, masks[idx]))
                )
                failed = failed.at[idx].set(
                    jnp.where(
                        is_create,
                        # retry attempts accumulate ever-failed with OR
                        # (the segmented path's per-segment `|=`)
                        (failed[idx] & is_slot & is_create) | (node < 0)
                        if faults else node < 0,
                        failed[idx],
                    )
                )
            arr_cpu = arr_cpu + jnp.where(is_create, pod.cpu, 0)
            arr_gpu = arr_gpu + jnp.where(is_create, pod.total_gpu_milli(), 0)
            # node == -1 (failed create) leaves no owner, so every shard
            # skips the next refresh — same as the pre-restructure behavior
            dirty = jnp.where(kc == 2, dirty, node)
            ctr = ctr + counter_delta(kc, node)
            if faults:
                if pipelined:
                    # decide the fault step now (it reads only committed
                    # bookkeeping — the current event can never both bind
                    # AND fault), defer its writes one iteration
                    fpend, fc, ftouch, fy = _fl.plan_fault_step(
                        placed, masks, fc, pods, kind, farg, faux, fpos,
                        fault_ops,
                    )
                else:
                    # masked fault transitions: state row ops owner-masked
                    # by the global-id row mask, bookkeeping replicated
                    (state, placed, masks, failed, fc, ftouch, fy) = (
                        _fl.apply_fault_step(
                            state, placed, masks, failed, fc, pods, kind,
                            farg, faux, fpos, fault_ops, tp, gids, False,
                        )
                    )
                fc, lat, _ = _fl.commit_retry(
                    fc, has_pop, rpod, node, fpos, farg, fault_ops.params
                )
                fy = fy._replace(
                    rpod=jnp.where(has_pop, rpod, -1).astype(jnp.int32),
                    lat=lat,
                )
                dirty = jnp.where(ftouch >= 0, ftouch, dirty)
                node = jnp.where(ftouch >= 0, ftouch, node)
            new_carry = ShardTableCarry(
                state, packed_tbl, lt, lr, lwn, pend, dirty, placed,
                masks, failed, arr_cpu, arr_gpu, key, ctr,
            )
            ys = (
                (node, dev)
                + ((dec,) if decisions else ())
                + ((ser,) if series_every else ())
            )
            if faults:
                if pipelined:
                    return (new_carry, fc, fpend), ys + (fy,)
                return (new_carry, fc), ys + (fy,)
            return new_carry, ys

        xs = (
            (ev_kind, ev_pod, fault_ops.pos, fault_ops.arg, fault_ops.aux)
            if faults else (ev_kind, ev_pod)
        )
        carry, ys = jax.lax.scan(body, carry, xs)
        return (carry,) + tuple(ys)

    state_specs = NodeState(*([P(NODE_AXIS)] * len(NodeState._fields)))
    spec_r = PodSpec(*([P()] * 6))
    types_specs = PodTypes(spec_r, spec_r, P())
    from tpusim.types import TypicalPods

    tp_specs = TypicalPods(*([P()] * len(TypicalPods._fields)))
    # the carry's table shards / block summaries live on the node axis;
    # bookkeeping — the pipeline register included — is replicated
    # (identical on every shard by construction)
    pend_specs = PendingCommit(*([P()] * len(PendingCommit._fields)))
    carry_specs = ShardTableCarry(
        state=state_specs,
        packed_tbl=P(None, NODE_AXIS),
        lt=P(None, NODE_AXIS), lr=P(None, NODE_AXIS), lwn=P(None, NODE_AXIS),
        pend=pend_specs,
        dirty=P(), placed=P(), masks=P(), failed=P(),
        arr_cpu=P(), arr_gpu=P(), key=P(), ctr=P(),
    )

    def _wrap(fn, in_specs, out_specs):
        if hasattr(jax, "shard_map"):
            return jax.shard_map(
                fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False,
            )
        # pre-0.5 jax spells it jax.experimental.shard_map.shard_map
        from jax.experimental.shard_map import shard_map as _shard_map

        return _shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )

    # decision records and series samples are replicated outputs
    # (collective-merged topk / psummed integer reductions), like the
    # (node, dev) telemetry
    dec_specs = DecisionRecord(*([P()] * len(DecisionRecord._fields)))
    ser_specs = obs_series.SeriesSample(
        *([P()] * len(obs_series.SeriesSample._fields))
    )
    if faults:
        # retry queue, disruption counters, streams, fault telemetry, and
        # the deferred fault register are all replicated — identical on
        # every shard by construction
        fc_specs = _fl.FaultCarry(*([P()] * len(_fl.FaultCarry._fields)))
        fops_specs = _fl.FaultOps(*([P()] * len(_fl.FaultOps._fields)))
        fy_specs = _fl.FaultY(*([P()] * len(_fl.FaultY._fields)))
        if pipelined:
            fp_specs = _fl.FaultPending(
                *([P()] * len(_fl.FaultPending._fields))
            )
            carry_specs = (carry_specs, fc_specs, fp_specs)
        else:
            carry_specs = (carry_specs, fc_specs)
    mapped_init = _wrap(
        _init_shard,
        (state_specs, P(NODE_AXIS), spec_r, types_specs, tp_specs, P(),
         P()) + ((fc_specs,) if faults else ()),
        carry_specs,
    )
    mapped_chunk = _wrap(
        _chunk_shard,
        (carry_specs, P(NODE_AXIS), spec_r, types_specs, P(), P(), tp_specs,
         P()) + ((fops_specs,) if faults else ()),
        (carry_specs, P(), P())
        + ((dec_specs,) if decisions else ())
        + ((ser_specs,) if series_every else ())
        + ((fy_specs,) if faults else ()),
    )

    from tpusim.sim.step import resolve_weights

    @jax.jit
    def _init_carry_j(state, pods, types, tp, key, tiebreak_rank, wts,
                      fault_carry0=None):
        if faults:
            return mapped_init(state, tiebreak_rank, pods, types, tp, key,
                               wts, fault_carry0)
        return mapped_init(state, tiebreak_rank, pods, types, tp, key, wts)

    def _run_chunk_impl(carry, pods, types, ev_kind, ev_pod, tp,
                        tiebreak_rank, wts, fault_ops=None):
        if faults:
            outs = mapped_chunk(
                carry, tiebreak_rank, pods, types, ev_kind, ev_pod, tp,
                wts, fault_ops,
            )
        else:
            outs = mapped_chunk(
                carry, tiebreak_rank, pods, types, ev_kind, ev_pod, tp, wts
            )
        return outs[0], tuple(outs[1:])

    _run_chunk_j = jax.jit(_run_chunk_impl)
    # the donating twin (ISSUE 11): the input carry's shards are donated
    # to the outputs, so a chunked 1M-node replay stops reallocating its
    # O(N*K) table shards every segment; the caller must treat the input
    # carry as consumed (the driver snapshots to host before advancing)
    _run_chunk_don = jax.jit(_run_chunk_impl, donate_argnums=0)

    # weights resolve OUTSIDE the jitted functions (ISSUE 6): the weight
    # vector is always a traced operand, never a baked constant, so one
    # compiled shard_map scan serves every weight vector of the family
    def init_carry(state, pods, types, tp, key, tiebreak_rank,
                   weights=None, fault_carry0=None):
        if faults:
            return _init_carry_j(
                state, pods, types, tp, key, tiebreak_rank,
                resolve_weights(policies, weights), fault_carry0,
            )
        return _init_carry_j(
            state, pods, types, tp, key, tiebreak_rank,
            resolve_weights(policies, weights),
        )

    def run_chunk(carry, pods, types, ev_kind, ev_pod, tp, tiebreak_rank,
                  weights=None, fault_ops=None):
        if faults:
            return _run_chunk_j(
                carry, pods, types, ev_kind, ev_pod, tp, tiebreak_rank,
                resolve_weights(policies, weights), fault_ops,
            )
        return _run_chunk_j(
            carry, pods, types, ev_kind, ev_pod, tp, tiebreak_rank,
            resolve_weights(policies, weights),
        )

    def run_chunk_donated(carry, pods, types, ev_kind, ev_pod, tp,
                          tiebreak_rank, weights=None, fault_ops=None):
        """run_chunk with the input carry DONATED to the outputs
        (ISSUE 11): the chunk scan reuses the carry's table/state shards
        instead of reallocating them every segment. The passed carry is
        consumed — snapshot it first if it must survive."""
        if faults:
            return _run_chunk_don(
                carry, pods, types, ev_kind, ev_pod, tp, tiebreak_rank,
                resolve_weights(policies, weights), fault_ops,
            )
        return _run_chunk_don(
            carry, pods, types, ev_kind, ev_pod, tp, tiebreak_rank,
            resolve_weights(policies, weights),
        )

    run_chunk_donated._cache_size = _run_chunk_don._cache_size

    def _finish_impl(carry):
        """Post-scan epilogue: apply the last event's still-pending
        commit(s) on the gathered GLOBAL view (pend.node is a global id,
        so sim.step.apply_commit applies directly; the registers are
        inert no-ops on pipelined=False builds) and strip the dummy
        bookkeeping row. A finished carry must not be resumed."""
        fpend_f = None
        if faults:
            if pipelined:
                carry, _fc, fpend_f = carry
            else:
                carry, _fc = carry
        state, placed, masks, failed = apply_commit(
            carry.state, carry.placed, carry.masks, carry.failed,
            carry.pend,
        )
        if fpend_f is not None:
            state, placed, masks, failed = _fl.apply_fault_pending(
                state, placed, masks, failed, fpend_f, 0,
                state.num_nodes,
            )
        return state, placed[:-1], masks[:-1], failed[:-1]

    finish = jax.jit(_finish_impl)

    @jax.jit
    def _replay_impl(state, pods, types, ev_kind, ev_pod, tp, key,
                     tiebreak_rank, wts, fault_ops=None,
                     fault_carry0=None) -> ReplayResult:
        carry = _init_carry_j(state, pods, types, tp, key, tiebreak_rank,
                              wts, fault_carry0)
        carry, ys = _run_chunk_j(
            carry, pods, types, ev_kind, ev_pod, tp, tiebreak_rank, wts,
            fault_ops,
        )
        state_f, placed, masks, failed = _finish_impl(carry)
        nodes, devs = ys[0], ys[1]
        rest = list(ys[2:])
        decs = rest.pop(0) if decisions else None
        sers = rest.pop(0) if series_every else None
        if faults:
            base = carry[0]
            fc = carry[1]
            return ReplayResult(
                state_f, placed, masks, failed, None,
                nodes, devs, base.ctr, None, None, rest.pop(0),
                _fl.trim_fault_carry(fc),
            )
        return ReplayResult(
            state_f, placed, masks, failed, None,
            nodes, devs, carry.ctr, decs, sers,
        )

    def replay(state, pods, types, ev_kind, ev_pod, tp, key,
               tiebreak_rank, weights=None, fault_ops=None,
               fault_carry0=None) -> ReplayResult:
        if faults:
            return _replay_impl(
                state, pods, types, ev_kind, ev_pod, tp, key,
                tiebreak_rank, resolve_weights(policies, weights),
                fault_ops, fault_carry0,
            )
        return _replay_impl(
            state, pods, types, ev_kind, ev_pod, tp, key, tiebreak_rank,
            resolve_weights(policies, weights),
        )

    # checkpoint/resume surface (driver chunked dispatch): a host gather of
    # the carry (np.asarray per leaf) is the snapshot; jit re-shards it on
    # the way back in, and the continued scan is bit-identical
    replay.init_carry = init_carry
    replay.run_chunk = run_chunk
    replay.run_chunk_donated = run_chunk_donated
    replay.finish = finish
    replay.engine = _replay_impl  # the weight-operand jitted impl
    return replay
