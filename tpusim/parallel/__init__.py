from tpusim.parallel.shard_engine import make_shardmap_table_replay
from tpusim.parallel.sharding import (
    make_mesh,
    make_sharded_replay,
    make_sharded_table_replay,
    pad_nodes,
    shard_state,
    state_sharding,
)

__all__ = [
    "make_mesh",
    "make_sharded_replay",
    "make_sharded_table_replay",
    "make_shardmap_table_replay",
    "pad_nodes",
    "shard_state",
    "state_sharding",
]
