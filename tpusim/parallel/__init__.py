from tpusim.parallel.shard_engine import make_shardmap_table_replay
from tpusim.parallel.sharding import (
    make_mesh,
    make_sharded_replay,
    make_sharded_table_replay,
    pad_nodes,
    shard_state,
    state_sharding,
)

__all__ = [
    "make_mesh",
    "make_sharded_replay",
    "make_sharded_table_replay",
    "make_shardmap_table_replay",
    "pad_nodes",
    "shard_state",
    "state_sharding",
]

# Virtual-mesh bootstrap (force_virtual_cpu_devices) deliberately does NOT
# live or re-export here: importing this package — even for a submodule —
# initializes the JAX backend through its module graph, after which the
# platform switch is a no-op. Import it from tpusim.virtual_mesh instead.
