from tpusim.parallel.sharding import (
    make_mesh,
    make_sharded_replay,
    pad_nodes,
    shard_state,
    state_sharding,
)

__all__ = [
    "make_mesh",
    "make_sharded_replay",
    "pad_nodes",
    "shard_state",
    "state_sharding",
]
