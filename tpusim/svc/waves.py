"""Continuous batching for warm-state what-if jobs (ISSUE 16).

A ForkWave serves every fork job of one (family, base run) pair through
the driver's ChunkWave: B lanes step through the donated `run_chunk`
twin together, one vmapped dispatch per chunk, and — the continuous
part — a job that arrives while the wave is running JOINS at the next
chunk boundary by replacing a free (padding) lane via the scatter
entry, instead of waiting for the wave to drain. Lanes finish
independently (a fork that diverges late replays a longer tail than one
that diverges early), so results stream out per lane the moment that
lane's events are consumed — the admission→result latency of a short
fork is its own tail-replay time, not the wave's.

Per-lane bookkeeping lives here, host-side: the event cursor, the inert
EV_SKIP pad count (corrects the skip counter exactly like the sweep
path's bucket-padding correction), join timestamps for the latency
instrumentation, and tail-relative progress ticks (a forked job's
/progress reports ITS tail's events/s and ETA, never the base run's
clock). The numeric work — restore, step, scatter, finish, and the
bit-identity discipline that makes a warm fork byte-equal to its
from-event-0 replay — is the driver's (sim.driver.ChunkWave).
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

import numpy as np


class ForkWave:
    """One family's continuous-batching wave (see module docstring)."""

    def __init__(self, wave, monitor=None, out=None):
        self.wave = wave  # sim.driver.ChunkWave
        self.monitor = monitor
        self.out = out
        self.waves_run = 0  # completed serve() calls (join waves ride one)
        self.joins = 0  # jobs that joined a RUNNING wave at a boundary
        self.degrades = 0  # forks that fell back to full replay

    # ---- lane construction ----

    def _lane_for(self, job) -> dict:
        """Per-lane host state for one fork job: its divergent stream,
        its starting carry (restored warm for mode 'fork', event-0 cold
        for mode 'full' or on degrade), and the counters the result
        document needs."""
        base_digest, fork_event, mode, tail = job.spec.fork
        evk, evp, real = self.wave.fork_stream(fork_event, tail)
        cursor, carry, degrade = 0, None, False
        if mode == "fork":
            found = self.wave.restore_lane(fork_event)
            if found is not None:
                cursor, carry = found
            else:
                degrade = True
                self.degrades += 1
                if self.out is not None:
                    print(
                        f"[Degrade] fork {job.digest[:12]}…: no usable "
                        f"base checkpoint at-or-before event "
                        f"{fork_event} — full replay from event 0",
                        file=self.out,
                    )
        if carry is None:
            carry = self.wave.init_lane()
        return {
            "job": job, "evk": evk, "evp": evp, "real": real,
            "cursor": cursor, "c0": cursor, "pads": 0,
            "degrade": degrade, "mode": mode, "base": base_digest,
            "fork_event": int(fork_event), "carry": carry,
            "joined": time.time(),
        }

    def _skip_chunk(self):
        from tpusim.sim.engine import EV_SKIP

        C = self.wave.chunk
        bk = np.asarray(self.wave.base_kind)
        bp = np.asarray(self.wave.base_pod)
        return (np.full(C, EV_SKIP, bk.dtype), np.zeros(C, bp.dtype))

    def _chunk_rows(self, lane) -> tuple:
        """Slice lane's next chunk from its stream, padding a final
        partial chunk with inert EV_SKIPs (tracked for the counter
        correction)."""
        from tpusim.sim.engine import EV_SKIP

        C = self.wave.chunk
        seg_k = lane["evk"][lane["cursor"]: lane["cursor"] + C]
        seg_p = lane["evp"][lane["cursor"]: lane["cursor"] + C]
        pad = C - len(seg_k)
        if pad:
            seg_k = np.concatenate(
                [seg_k, np.full(pad, EV_SKIP, seg_k.dtype)]
            )
            seg_p = np.concatenate([seg_p, np.zeros(pad, seg_p.dtype)])
            lane["pads"] += pad
        return seg_k, seg_p

    def _publish(self, lane, **fields) -> None:
        if self.monitor is None:
            return
        # tail-relative honesty (ISSUE 16 satellite): done/total/rate
        # count THIS fork's replayed events — the restored base prefix
        # never inflates the rate, and the ETA is the tail's
        executed = max(0, min(lane["cursor"], lane["real"]) - lane["c0"])
        total = max(1, lane["real"] - lane["c0"])
        dt = max(time.time() - lane["joined"], 1e-9)
        rate = executed / dt
        self.monitor.publish_job_progress(
            lane["job"].id,
            dict(
                fields, phase="forking", done=executed, total=total,
                ev_per_s=rate,
                eta_s=(total - executed) / rate if rate > 0 else 0.0,
                source_cursor=lane["c0"], degrade=lane["degrade"],
                mode=lane["mode"],
            ),
        )

    # ---- the serve loop ----

    def serve(self, jobs: List, claim_more: Optional[Callable] = None,
              on_join: Optional[Callable] = None,
              on_done: Optional[Callable] = None) -> None:
        """Run one continuous wave: start with `jobs` (<= lane width),
        admit late arrivals from `claim_more(n_free)` at every chunk
        boundary, finish lanes independently. Callbacks:

          on_join(job)                    a job's lane begins stepping
                                          (initial members AND joiners)
          on_done(job, lane: SweepLane, meta: dict)
                                          that job's result is final

        meta carries the serving telemetry the result document and the
        latency gate read: events_executed (<= tail + one chunk, the
        warm-state win), events_total, source_cursor, degrade, mode.
        """
        from tpusim.sim.driver import lane_from_arrays

        B = self.wave.lanes
        slots: List[Optional[dict]] = [None] * B
        pending = list(jobs)
        for i in range(min(len(pending), B)):
            slots[i] = self._lane_for(pending.pop(0))
            if on_join is not None:
                on_join(slots[i]["job"])
            self._publish(slots[i])
        active = [s for s in slots if s is not None]
        if not active:
            return
        # free slots replicate the first lane's carry: they are stepped
        # with EV_SKIP chunks (inert) until a joiner's scatter replaces
        # them. Every occupied lane ENTERS via the scatter entry —
        # initial members and boundary joiners share one code path, so
        # the first wave primes the same executable a later join
        # dispatches (the zero-recompile census counts joins for free).
        filler = active[0]["carry"]
        batch = self.wave.stack([filler] * B)
        for i, s in enumerate(slots):
            if s is not None:
                batch = self.wave.scatter(batch, s["carry"], i)
                s["carry"] = None  # the batch owns it now

        while any(s is not None for s in slots):
            ck_rows, cp_rows = [], []
            for s in slots:
                if s is None:
                    k, p = self._skip_chunk()
                else:
                    k, p = self._chunk_rows(s)
                ck_rows.append(k)
                cp_rows.append(p)
            batch = self.wave.step(
                batch, np.stack(ck_rows), np.stack(cp_rows)
            )
            for i, s in enumerate(slots):
                if s is None:
                    continue
                s["cursor"] = min(s["cursor"] + self.wave.chunk, s["real"])
                if s["cursor"] >= s["real"]:
                    st, placed, masks, failed, ctr = (
                        self.wave.finish_lane(batch, i)
                    )
                    p = self.wave.p
                    lane = lane_from_arrays(
                        st, np.asarray(placed)[:p],
                        np.asarray(masks)[:p], np.asarray(failed)[:p],
                        np.asarray(ctr), self.wave.sim.typical,
                        s["job"].spec.weights, s["job"].spec.seed,
                        s["real"], pad_skips=s["pads"],
                    )
                    meta = {
                        "events_executed": s["real"] - s["c0"],
                        "events_total": s["real"],
                        "source_cursor": s["c0"],
                        "degrade": s["degrade"],
                        "mode": s["mode"],
                        "base": s["base"],
                        "fork_event": s["fork_event"],
                    }
                    self._publish(s, phase="done")
                    if on_done is not None:
                        on_done(s["job"], lane, meta)
                    slots[i] = None
                else:
                    self._publish(s)
            # the chunk boundary: admit pending + late-arriving jobs
            # into free lanes (continuous batching — a joiner replaces
            # a padding lane via ONE scatter dispatch)
            free = [i for i, s in enumerate(slots) if s is None]
            if free and claim_more is not None:
                got = claim_more(len(free) - len(pending))
                if got:
                    pending.extend(got)
            while free and pending:
                i = free.pop(0)
                s = self._lane_for(pending.pop(0))
                batch = self.wave.scatter(batch, s["carry"], i)
                s["carry"] = None
                slots[i] = s
                if any(x is not None and x is not s for x in slots):
                    self.joins += 1
                if on_join is not None:
                    on_join(s["job"])
                self._publish(s)
        self.waves_run += 1

    def executables(self) -> int:
        return self.wave.executables()
