"""Client side of the replay service: `tpusim submit` (ISSUE 7).

POSTs job documents to a `tpusim serve --jobs` endpoint and polls them
to completion. Backpressure is first-class: a 429 answer sleeps the
server-provided Retry-After (falling back to kube_client's capped-
exponential-with-jitter schedule — the SAME delay discipline its List
retries use, so a fleet of submitters never dogpiles the service) and
re-submits the remainder; dedup on the service side makes re-submitting
an already-accepted document harmless.

Coordinator HA (ISSUE 17): `submit_and_wait` accepts a comma-separated
coordinator LIST and rotates through it on the shared backoff schedule
when the current coordinator is lost — connection refused/lost past the
retry budget, a standby's 503, or a post-failover 404 for a job id the
dead leader assigned (`CoordinatorLost`). Re-submission after a rotate
is idempotent: job digests dedup server-side, completed work answers
from the result cache. Mutating POSTs carry the fleet bearer token
(`token=` or TPUSIM_FLEET_TOKEN); the token never reaches a log line.
"""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request
from typing import List, Optional, Sequence, Tuple

from tpusim.io.kube_client import _retry_delay_s

TERMINAL = ("done", "failed")


class ServiceError(RuntimeError):
    pass


class CoordinatorLost(ServiceError):
    """One coordinator URL can no longer serve this flow (dead,
    standby, or restarted with fresh job ids) — the caller's cue to
    rotate to the next URL in its list and re-submit."""


def _token(token: Optional[str]) -> str:
    """Explicit token, else TPUSIM_FLEET_TOKEN, else "" (auth off)."""
    if token is not None:
        return str(token)
    return os.environ.get("TPUSIM_FLEET_TOKEN", "").strip()


class JobsFailed(ServiceError):
    """Some jobs reached status=failed server-side. Carries the failure
    descriptions AND the successful jobs' fetched results, so `tpusim
    submit` can print what succeeded and still exit nonzero (the
    partial-failure contract)."""

    def __init__(self, message: str, failed, results):
        super().__init__(message)
        self.failed = list(failed)  # final job descriptions, status=failed
        self.results = list(results)  # fetched results of the done jobs


def _request(url: str, data: Optional[bytes] = None,
             timeout: float = 30.0,
             content_type: str = "application/json",
             headers: Optional[dict] = None
             ) -> Tuple[int, dict, dict]:
    """(status, headers, parsed JSON body); HTTP errors with a JSON body
    (the service's 4xx/5xx answers) are returned, transport errors
    raise. `content_type` marks non-JSON request bodies (the fleet's
    raw signed-result uploads, ISSUE 13); answers are always JSON.
    `headers` adds extra request headers (the bearer token, ISSUE 17)."""
    hdrs = dict(headers or {})
    if data:
        hdrs["Content-Type"] = content_type
    req = urllib.request.Request(url, data=data, headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), json.loads(
                resp.read().decode() or "null"
            )
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read().decode() or "null")
        except (json.JSONDecodeError, UnicodeDecodeError):
            body = {"error": str(e)}
        return e.code, dict(e.headers or {}), body


def submit_jobs(url: str, docs: Sequence[dict], max_retries: int = 8,
                timeout: float = 30.0, out=None,
                token: Optional[str] = None,
                trace: Optional[str] = None) -> List[dict]:
    """POST every job document, honoring 429/Retry-After backpressure:
    rejected remainders are re-submitted after the advertised delay
    (dedup makes overlap safe). Returns the accepted job descriptions in
    submission order; raises ServiceError on a 400 or when the queue
    never drains within max_retries rounds, CoordinatorLost when this
    coordinator is unreachable or a standby (the rotate cue).

    `trace` is the flight-recorder trace id (ISSUE 19): minted here when
    absent and sent as the X-Tpusim-Trace header, so the whole submit —
    including backpressure retries — stitches as one journey. Callers
    that rotate coordinators pass the SAME id to every attempt."""
    import http.client

    from tpusim.obs.trace import TRACE_HEADER, new_trace_id
    from tpusim.svc.auth import bearer_headers

    url = url.rstrip("/")
    auth = dict(bearer_headers(_token(token)))
    auth[TRACE_HEADER] = trace or new_trace_id()
    pending = list(docs)
    accepted: List[dict] = []
    for attempt in range(1, max_retries + 1):
        body = json.dumps({"jobs": pending}).encode()
        try:
            code, headers, doc = _request(url + "/jobs", body, timeout,
                                          headers=auth)
        except (ConnectionResetError,
                http.client.RemoteDisconnected, urllib.error.URLError) as e:
            # a draining/restarting service (ISSUE 10 graceful shutdown)
            # resets the connection mid-POST; accepted specs are
            # persisted server-side and dedup makes the full-list retry
            # safe — treat it exactly like backpressure. Connection
            # REFUSED is different: nothing is listening (down service,
            # typo'd --url) and must fail fast, not burn the whole
            # backoff schedule.
            reason = getattr(e, "reason", e)
            if isinstance(e, ConnectionRefusedError) or isinstance(
                reason, ConnectionRefusedError
            ):
                raise CoordinatorLost(
                    f"POST /jobs: connection refused at {url} — is the "
                    "service running?"
                )
            if attempt >= max_retries:
                raise CoordinatorLost(
                    f"POST /jobs kept failing ({type(e).__name__}: {e}) "
                    f"after {max_retries} attempts"
                )
            delay = _retry_delay_s(attempt)
            if out is not None:
                print(
                    f"[submit] connection lost ({type(e).__name__}; "
                    f"service draining/restarting?), retrying in "
                    f"{delay:.1f}s", file=out,
                )
            time.sleep(delay)
            continue
        if code in (200, 202):
            accepted.extend(doc.get("jobs", [doc]))
            return accepted
        if code == 400:
            raise ServiceError(f"rejected: {doc.get('error', doc)}")
        if code == 401:
            raise ServiceError(
                "POST /jobs -> HTTP 401: bearer token missing or "
                "rejected (--token-file / TPUSIM_FLEET_TOKEN)"
            )
        if code == 503:
            # drain answer: the service is finishing its in-flight batch
            # before exiting; the restarted process recovers persisted
            # specs, so waiting + resubmitting is the right move. A
            # STANDBY's 503 (ISSUE 17) is different after a couple of
            # polls: this coordinator is deliberately not leading —
            # rotate instead of waiting out the schedule.
            if doc.get("role") == "standby" and attempt >= 2:
                raise CoordinatorLost(
                    f"{url} is a standby coordinator (epoch "
                    f"{doc.get('epoch', '?')})"
                )
            if attempt >= max_retries:
                raise CoordinatorLost(
                    f"service stayed draining after {max_retries} attempts"
                )
            delay = _retry_delay_s(attempt, headers.get("Retry-After"))
            if out is not None:
                print(
                    f"[submit] service draining, retrying in {delay:.1f}s",
                    file=out,
                )
            time.sleep(delay)
            continue
        if code == 429:
            got = doc.get("accepted") or []
            accepted.extend(got)
            rej = doc.get("rejected_indices")
            if rej is not None:
                # the service names exactly which docs it turned away
                # (ISSUE 12: quota rejections can be non-prefix — a
                # cold-family doc AFTER a quota-full one is accepted)
                pending = [pending[i] for i in rej if i < len(pending)]
            else:
                pending = pending[len(got):]
            if attempt >= max_retries:
                break
            delay = _retry_delay_s(attempt, headers.get("Retry-After"))
            if out is not None:
                # a per-family admission quota 429 (ISSUE 12) names the
                # hogging family — say so, it's actionable ("your trace
                # is hot", not "the service is overloaded")
                what = (
                    f"family quota full for {doc['family']}"
                    if doc.get("family") else "queue full"
                )
                print(
                    f"[submit] {what} ({len(pending)} left), "
                    f"retrying in {delay:.1f}s", file=out,
                )
            time.sleep(delay)
            continue
        raise ServiceError(f"POST /jobs -> HTTP {code}: {doc}")
    raise ServiceError(
        f"queue stayed full after {max_retries} attempts "
        f"({len(pending)} jobs unsubmitted)"
    )


def wait_jobs(url: str, job_ids: Sequence[str], timeout: float = 300.0,
              poll_s: float = 0.0) -> List[dict]:
    """Poll GET /jobs/<id> until every job is terminal; returns their
    final descriptions in order. Raises ServiceError on timeout.

    The inter-poll sleep is the kube_client capped-exponential-backoff-
    with-jitter schedule (io.kube_client._retry_delay_s — ONE shared
    delay utility for every HTTP retry/poll loop in the tree): rounds
    that observe no progress back off up to the 8 s cap so a fleet of
    ES/CMA tuning clients (ISSUE 9) does not hammer the service through
    a long generation, and any job reaching terminal resets the schedule
    so a steadily-draining queue is polled briskly. `poll_s > 0` caps
    the delay (the fast-test knob); 0 uses the shared schedule as-is."""
    url = url.rstrip("/")
    deadline = time.time() + timeout
    last = {jid: None for jid in job_ids}
    attempt = 0  # idle polls since the last observed progress (1-based
    # in the shared helper: the first sleep is the base delay)
    while time.time() < deadline:
        busy = False
        progressed = False
        for jid in job_ids:
            if last[jid] and last[jid]["status"] in TERMINAL:
                continue
            try:
                code, _, doc = _request(f"{url}/jobs/{jid}")
            except OSError as e:
                # the coordinator died mid-poll (ISSUE 17): the caller
                # rotates + re-submits — digests make that idempotent
                raise CoordinatorLost(
                    f"GET /jobs/{jid}: {type(e).__name__}: {e}"
                )
            if code == 404:
                # a failed-over coordinator assigned NEW ids to the
                # recovered specs; ours died with the old leader
                raise CoordinatorLost(
                    f"job {jid} unknown at {url} (coordinator "
                    "restarted or failed over)"
                )
            if code == 503:
                raise CoordinatorLost(
                    f"{url} answered 503 for {jid} (standby/draining)"
                )
            if code != 200:
                raise ServiceError(f"GET /jobs/{jid} -> HTTP {code}: {doc}")
            last[jid] = doc
            if doc["status"] in TERMINAL:
                progressed = True
            else:
                busy = True
        if not busy:
            return [last[jid] for jid in job_ids]
        attempt = 1 if progressed else attempt + 1
        delay = _retry_delay_s(attempt)
        if poll_s > 0:
            delay = min(delay, poll_s)
        time.sleep(min(delay, max(deadline - time.time(), 0.0)))
    stuck = [j for j, d in last.items()
             if not d or d["status"] not in TERMINAL]
    raise ServiceError(f"jobs still running after {timeout}s: {stuck}")


def fetch_results(url: str, job_ids: Sequence[str],
                  timeout: float = 30.0) -> List[dict]:
    """GET /jobs/<id>/result for every (terminal) job."""
    url = url.rstrip("/")
    out = []
    for jid in job_ids:
        try:
            code, _, doc = _request(f"{url}/jobs/{jid}/result",
                                    timeout=timeout)
        except OSError as e:
            raise CoordinatorLost(
                f"GET /jobs/{jid}/result: {type(e).__name__}: {e}"
            )
        if code in (404, 503):
            raise CoordinatorLost(
                f"GET /jobs/{jid}/result -> HTTP {code} at {url}"
            )
        if code != 200:
            raise ServiceError(
                f"GET /jobs/{jid}/result -> HTTP {code}: {doc}"
            )
        out.append(doc)
    return out


def format_results_table(results: Sequence[dict]) -> str:
    """Per-job summary table — the `tpusim submit` output (one row per
    job: weights, seed, tune, placed/failed, gpu_alloc, frag)."""
    head = (
        f"{'job':>4} {'weights':<24} {'seed':>6} {'tune':>5} "
        f"{'placed':>7} {'failed':>7} {'gpu_alloc%':>10} "
        f"{'frag_gpu_milli':>15}"
    )
    rows = [head, "-" * len(head)]
    for i, r in enumerate(results):
        wstr = ",".join(str(int(x)) for x in r.get("weights", []))
        rows.append(
            f"{i:>4} {wstr:<24} {r.get('seed', ''):>6} "
            f"{r.get('tune', 0):>5} {r.get('placed', ''):>7} "
            f"{r.get('failed', ''):>7} "
            f"{r.get('gpu_alloc_pct', 0.0):>10.2f} "
            f"{r.get('frag_gpu_milli', 0.0):>15.0f}"
        )
    return "\n".join(rows)


def submit_and_wait(url: str, docs: Sequence[dict], timeout: float = 300.0,
                    out=None, poll_s: float = 0.0,
                    token: Optional[str] = None) -> List[dict]:
    """The whole `tpusim submit` flow: POST (with backpressure retries),
    poll to terminal, fetch results. When any job failed server-side,
    raises JobsFailed carrying BOTH the failure descriptions and the
    done jobs' fetched results — the caller can report partial success
    and must exit nonzero. `poll_s > 0` caps the inter-poll delay — the
    knob latency-sensitive interactive what-if clients (and the serve-
    latency gate) use so a millisecond-scale warm fork is not measured
    through a second-scale poll schedule.

    `url` may be a comma-separated coordinator LIST (ISSUE 17): a
    CoordinatorLost at any stage rotates to the next URL on the shared
    backoff schedule and re-submits the SAME docs there — job digests
    dedup server-side and finished work answers from the result cache,
    so a coordinator failover costs a stall, never duplicate runs."""
    from tpusim.io.kube_client import parse_url_list
    from tpusim.obs.trace import new_trace_id

    urls = parse_url_list(url)
    deadline = time.time() + timeout
    rounds = 2 * len(urls)
    last_lost: Optional[CoordinatorLost] = None
    # one trace id for the whole flow: a failover rotation re-submits
    # under the SAME id, so the stitched timeline shows one journey
    # crossing coordinators rather than two disconnected ones
    tid = new_trace_id()
    for round_ in range(1, rounds + 1):
        cur = urls[0]
        try:
            accepted = submit_jobs(cur, docs, out=out, token=token,
                                   trace=tid)
            ids = [a["id"] for a in accepted]
            final = wait_jobs(
                cur, ids, timeout=max(deadline - time.time(), 1.0),
                poll_s=poll_s,
            )
            failed = [d for d in final if d["status"] == "failed"]
            if failed:
                done_ids = [
                    d["id"] for d in final if d["status"] == "done"
                ]
                raise JobsFailed(
                    "job(s) failed: "
                    + "; ".join(f"{d['id']}: {d.get('error', '?')}"
                                for d in failed),
                    failed,
                    fetch_results(cur, done_ids) if done_ids else [],
                )
            return fetch_results(cur, ids)
        except CoordinatorLost as err:
            last_lost = err
            if round_ >= rounds or time.time() >= deadline:
                break
            urls = urls[1:] + urls[:1]
            delay = _retry_delay_s(min(round_, 4))
            if out is not None:
                print(
                    f"[submit] coordinator lost ({err}); rotating to "
                    f"{urls[0]} in {delay:.1f}s", file=out,
                )
            time.sleep(min(delay, max(deadline - time.time(), 0.0)))
    raise last_lost if last_lost is not None else ServiceError(
        "submit_and_wait made no attempt"
    )
