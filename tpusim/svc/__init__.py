"""tpusim.svc — the queueing what-if replay service (ISSUE 7), grown
into a kill-tolerant worker fleet (ISSUE 12).

Fuses the pieces the earlier rounds landed into simulation-as-a-service:
POSTed what-if jobs (policy weights x seed x tune factor x fault
schedule over a hosted trace) are content-digest-dedup'd (svc.jobs),
grouped into compatible batches by jaxpr identity (svc.batcher), and
served through the vmapped multi-trace sweep — one compiled scan per
batch, zero recompiles across batches differing only in operands
(svc.worker) — with an HTTP plane grown onto the PR 5 MonitorServer
(svc.api) and a backpressure-honoring client (svc.client, `tpusim
submit`). The fleet layer (ISSUE 12): many worker PROCESSES drain the
one queue under leased job ownership (svc.leases — signed lease files,
renew-on-heartbeat, clock-skew-tolerant expiry) with orphan stealing
(svc.batcher claim/steal, svc.fleet coordinator + `tpusim worker
--join`); results are at-least-once but digest-idempotent, so a
`kill -9` mid-batch costs a lease timeout, never a wrong or lost
answer.
"""

from tpusim.svc.api import JobService, start_job_server  # noqa: F401
from tpusim.svc.batcher import (  # noqa: F401
    Job,
    JobQueue,
    QueueFull,
    QuotaFull,
)
from tpusim.svc.fleet import (  # noqa: F401
    FleetService,
    WorkerRegistry,
    ensure_local_trace,
    resolve_worker_mode,
    run_worker,
    spawn_local_workers,
    worker_command,
)
from tpusim.svc.supervisor import Supervisor  # noqa: F401
from tpusim.svc.jobs import (  # noqa: F401
    JobSpec,
    docs_from_payload,
    find_result,
    job_digest,
    jobs_from_grid,
    spec_to_payload,
    validate_job,
    write_result,
)
from tpusim.svc.worker import (  # noqa: F401
    LeaseKeeper,
    TraceRef,
    Worker,
    load_trace,
)
