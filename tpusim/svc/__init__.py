"""tpusim.svc — the queueing what-if replay service (ISSUE 7).

Fuses the pieces the earlier rounds landed into simulation-as-a-service:
POSTed what-if jobs (policy weights x seed x tune factor over a hosted
trace) are content-digest-dedup'd (svc.jobs), grouped into compatible
batches by jaxpr identity (svc.batcher), and served by ONE worker thread
through the vmapped multi-trace sweep — one compiled scan per batch,
zero recompiles across batches differing only in operands (svc.worker)
— with an HTTP plane grown onto the PR 5 MonitorServer (svc.api) and a
backpressure-honoring client (svc.client, `tpusim submit`).
"""

from tpusim.svc.api import JobService, start_job_server  # noqa: F401
from tpusim.svc.batcher import Job, JobQueue, QueueFull  # noqa: F401
from tpusim.svc.jobs import (  # noqa: F401
    JobSpec,
    docs_from_payload,
    find_result,
    job_digest,
    jobs_from_grid,
    validate_job,
    write_result,
)
from tpusim.svc.worker import TraceRef, Worker, load_trace  # noqa: F401
