"""The self-healing fleet supervisor (ISSUE 13).

PR 12's `serve --jobs --workers N` spawned N children once and merely
REAPED the dead (released their leases, printed a line) — a fleet that
only ever shrinks. This controller owns the children end to end:

  respawn     a reaped child is replaced, under CAPPED EXPONENTIAL
              backoff keyed to consecutive fast exits — a child that
              lived a while resets the schedule, a child that dies at
              startup doubles it, so a broken worker binary costs
              seconds of spawn attempts per minute, not a fork bomb.
  breaker     the crash-loop circuit breaker: K respawns inside a
              W-second window opens it — respawning STOPS, /healthz
              degrades to 503 (FleetService.health folds `healthy()`
              in), and /queue says exactly why (`describe()` rides
              FleetService.queue_fields). A crash loop is an outage to
              report, not a treadmill to run.
  autoscale   `--workers N --max-workers M`: a queue backlog deeper
              than the live fleet can chew (depth > alive x
              depth_per_worker) spawns an extra child up to M; a queue
              idle past `scale_idle_s` drains one back down to N —
              gracefully, via SIGTERM (the worker CLI's drain flag
              finishes the in-flight batch), and a draining child is
              never respawned.

Everything is poll-driven (the serve loop calls `poll()` on its watch
cadence) and clock-injectable (`now` params), so the whole state
machine is testable with fake children and fake time — no processes,
no sleeps (tests/test_supervisor.py). The spawn callable is injected
too: the CLI passes a `tpusim worker --join` Popen factory
(svc.fleet.worker_command), the WAN smoke passes one that gives each
worker an isolated cache dir, and the crash-loop drill passes one that
exits immediately.
"""

from __future__ import annotations

import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional


@dataclass
class Child:
    """One supervised worker process (or a test fake exposing pid,
    poll(), send_signal(), kill(), wait())."""

    proc: object
    spawned_unix: float
    draining: bool = False  # SIGTERM'd by scale-down: exit expected,
    # never respawned

    @property
    def pid(self) -> int:
        return int(getattr(self.proc, "pid", 0))


@dataclass
class BreakerState:
    open: bool = False
    reason: str = ""
    opened_unix: float = 0.0
    trips: int = 0
    respawn_times: List[float] = field(default_factory=list)


class Supervisor:
    """See module docstring. Thread-safety: `poll()` runs on ONE thread
    (the serve loop); `describe()`/`healthy()` are read by HTTP handler
    threads — all state mutations hold `_lock`."""

    def __init__(self, spawn_fn: Callable[[int], object], workers: int,
                 max_workers: int = 0, *,
                 backoff_base_s: float = 0.5, backoff_cap_s: float = 30.0,
                 breaker_k: int = 5, breaker_window_s: float = 30.0,
                 healthy_after_s: float = 5.0,
                 load_fn: Optional[Callable[[], int]] = None,
                 depth_per_worker: int = 8,
                 scale_idle_s: float = 10.0,
                 scale_cooldown_s: float = 2.0,
                 on_exit: Optional[Callable[[int], object]] = None,
                 out=None):
        if workers < 1:
            raise ValueError(f"workers must be >= 1, got {workers}")
        if max_workers and max_workers < workers:
            raise ValueError(
                f"--max-workers {max_workers} must be >= --workers "
                f"{workers}"
            )
        self.spawn_fn = spawn_fn
        self.base = int(workers)  # the floor the respawner maintains
        self.max = int(max_workers) if max_workers else int(workers)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.breaker_k = int(breaker_k)
        self.breaker_window_s = float(breaker_window_s)
        self.healthy_after_s = float(healthy_after_s)
        self.load_fn = load_fn  # () -> queued depth (autoscale signal)
        self.depth_per_worker = int(depth_per_worker)
        self.scale_idle_s = float(scale_idle_s)
        self.scale_cooldown_s = float(scale_cooldown_s)
        self.on_exit = on_exit  # pid -> ignored (fleet.release_dead)
        self.out = out
        self.children: List[Child] = []
        # standby/demoted mode (ISSUE 17): a paused supervisor reaps
        # but neither respawns nor autoscales — the HA serve loop
        # pauses at demotion and resumes at promotion
        self.paused = False
        self.breaker = BreakerState()
        self._failures = 0  # consecutive fast exits (backoff key)
        self._next_spawn_unix = 0.0
        self._next_scale_unix = 0.0
        self._idle_since: Optional[float] = None
        self._spawned_total = 0
        self.counters = {
            "spawns": 0, "respawns": 0, "exits": 0,
            "scale_ups": 0, "scale_downs": 0,
        }
        self._lock = threading.Lock()
        # the audit chain (ISSUE 19): respawns and breaker trips are
        # control-plane decisions — each appends a chained record when
        # the serve loop wired an obs.audit.AuditLog here
        self.audit = None

    def _audit(self, kind: str, **fields) -> None:
        if self.audit is not None:
            self.audit.emit(kind, **fields)

    # ---- lifecycle ----

    def _spawn(self, now: float) -> Child:
        proc = self.spawn_fn(self._spawned_total)
        self._spawned_total += 1
        self.counters["spawns"] += 1
        child = Child(proc=proc, spawned_unix=now)
        self.children.append(child)
        if self.out is not None:
            print(f"[supervisor] spawned worker pid {child.pid} "
                  f"({len(self.children)} alive)", file=self.out)
        return child

    def start(self, now: Optional[float] = None) -> "Supervisor":
        now = time.time() if now is None else now
        with self._lock:
            while not self.paused and len(self.children) < self.base:
                self._spawn(now)
        return self

    def pause(self) -> None:
        """Stop respawning and autoscaling (standby / demoted
        coordinator, ISSUE 17). Live children keep running: a demoted
        leader's local workers are harmlessly fenced by its 503s and
        pick work back up the moment it re-acquires leadership."""
        with self._lock:
            self.paused = True

    def resume(self, now: Optional[float] = None) -> None:
        """Promotion: re-arm spawning (the next poll() fills the floor
        immediately — no leftover backoff from the paused era)."""
        now = time.time() if now is None else now
        with self._lock:
            self.paused = False
            self._next_spawn_unix = 0.0
            while len([c for c in self.children if not c.draining]) \
                    < self.base:
                self._spawn(now)

    def stop(self, timeout: float = 10.0) -> None:
        """Drain every child: SIGTERM (graceful — the worker CLI's stop
        flag finishes the in-flight batch), escalate to kill past the
        timeout (leases make even that safe)."""
        with self._lock:
            children = list(self.children)
            self.children = []
        for c in children:
            if c.proc.poll() is None:
                try:
                    c.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.time() + timeout
        for c in children:
            remaining = max(deadline - time.time(), 0.1)
            try:
                c.proc.wait(remaining)
            except Exception:
                if self.out is not None:
                    print(f"[supervisor] worker pid {c.pid} ignored "
                          "SIGTERM — killing (leases cover it)",
                          file=self.out)
                try:
                    c.proc.kill()
                except OSError:
                    pass

    # ---- the state machine ----

    def _backoff_s(self) -> float:
        if self._failures <= 0:
            return 0.0
        return min(
            self.backoff_base_s * (2 ** (self._failures - 1)),
            self.backoff_cap_s,
        )

    def _trip_breaker(self, now: float) -> None:
        self.breaker.open = True
        self.breaker.trips += 1
        self.breaker.opened_unix = now
        self.breaker.reason = (
            f"crash loop: {self.breaker_k} respawns within "
            f"{self.breaker_window_s:.0f}s — respawning stopped; fix "
            "the worker (see its stderr) and restart the coordinator "
            "or call reset_breaker()"
        )
        self._audit("breaker_trip", respawns=self.breaker_k,
                    window_s=self.breaker_window_s,
                    trips=self.breaker.trips)
        if self.out is not None:
            print(f"[supervisor] CIRCUIT BREAKER OPEN: "
                  f"{self.breaker.reason}", file=self.out)

    def reset_breaker(self) -> None:
        """Re-arm after the operator fixed the crash cause."""
        with self._lock:
            self.breaker.open = False
            self.breaker.reason = ""
            self.breaker.respawn_times = []
            self._failures = 0
            self._next_spawn_unix = 0.0

    def poll(self, now: Optional[float] = None) -> dict:
        """One supervision pass: reap exited children (releasing their
        leases via on_exit), respawn under backoff/breaker, and apply
        the autoscale policy. Returns the events of THIS pass (reaped
        pids, spawned pids, breaker flag) for the caller's logging."""
        now = time.time() if now is None else now
        events = {"reaped": [], "spawned": [], "breaker_open": False}
        with self._lock:
            # 1. reap
            for child in list(self.children):
                rc = child.proc.poll()
                if rc is None:
                    continue
                self.children.remove(child)
                self.counters["exits"] += 1
                events["reaped"].append(child.pid)
                lifetime = now - child.spawned_unix
                if child.draining:
                    # a scale-down drain completing is the plan working
                    if self.out is not None:
                        print(f"[supervisor] drained worker pid "
                              f"{child.pid} (scale-down)", file=self.out)
                elif lifetime < self.healthy_after_s:
                    self._failures += 1
                else:
                    self._failures = 0
                if self.on_exit is not None and not child.draining:
                    try:
                        self.on_exit(child.pid)
                    except Exception:
                        pass
                if not child.draining and self.out is not None:
                    print(
                        f"[supervisor] worker pid {child.pid} exited "
                        f"(rc {rc}, lived {lifetime:.1f}s); "
                        f"{'respawn pending' if not self.breaker.open else 'breaker open — NOT respawning'}",
                        file=self.out,
                    )

            alive = [c for c in self.children if not c.draining]

            # 2. respawn toward the floor (breaker + backoff + pause
            # gated)
            while (len(alive) < self.base and not self.breaker.open
                   and not self.paused
                   and now >= self._next_spawn_unix):
                window = [
                    t for t in self.breaker.respawn_times
                    if t > now - self.breaker_window_s
                ]
                self.breaker.respawn_times = window
                if len(window) >= self.breaker_k:
                    self._trip_breaker(now)
                    events["breaker_open"] = True
                    break
                child = self._spawn(now)
                alive.append(child)
                self.counters["respawns"] += 1
                self._audit("respawn", new_pid=child.pid,
                            failures=self._failures,
                            backoff_s=round(self._backoff_s(), 3))
                self.breaker.respawn_times.append(now)
                self._next_spawn_unix = now + self._backoff_s()
                events["spawned"].append(child.pid)

            # 3. autoscale (only armed when max > base and a load
            # signal exists)
            if (self.load_fn is not None and self.max > self.base
                    and not self.breaker.open and not self.paused):
                try:
                    depth = int(self.load_fn())
                except Exception:
                    depth = 0
                if depth > 0:
                    self._idle_since = None
                if (depth > len(alive) * self.depth_per_worker
                        and len(alive) < self.max
                        and now >= self._next_scale_unix):
                    child = self._spawn(now)
                    self.counters["scale_ups"] += 1
                    self._next_scale_unix = now + self.scale_cooldown_s
                    events["spawned"].append(child.pid)
                    if self.out is not None:
                        print(
                            f"[supervisor] scale-up: depth {depth} > "
                            f"{self.depth_per_worker}/worker across "
                            f"{len(alive)} worker(s)", file=self.out,
                        )
                elif depth == 0 and len(alive) > self.base:
                    if self._idle_since is None:
                        self._idle_since = now
                    elif (now - self._idle_since >= self.scale_idle_s
                          and now >= self._next_scale_unix):
                        # drain the NEWEST surplus child gracefully
                        victim = max(alive, key=lambda c: c.spawned_unix)
                        victim.draining = True
                        try:
                            victim.proc.send_signal(signal.SIGTERM)
                        except OSError:
                            pass
                        self.counters["scale_downs"] += 1
                        self._next_scale_unix = now + self.scale_cooldown_s
                        self._idle_since = now
                        if self.out is not None:
                            print(
                                f"[supervisor] scale-down: draining pid "
                                f"{victim.pid} (idle "
                                f"{self.scale_idle_s:.0f}s)",
                                file=self.out,
                            )
        return events

    # ---- introspection (the /queue + /healthz surfaces) ----

    def alive(self) -> int:
        with self._lock:
            return len([c for c in self.children if not c.draining])

    def describe(self) -> dict:
        """The /queue `supervisor` block — including WHY respawning
        stopped when the breaker is open (ISSUE 13: '/queue says
        why')."""
        with self._lock:
            alive = [c for c in self.children if not c.draining]
            return {
                "workers": self.base,
                "max_workers": self.max,
                "paused": self.paused,
                "alive": len(alive),
                "draining": len(self.children) - len(alive),
                "pids": [c.pid for c in self.children],
                **self.counters,
                "consecutive_fast_exits": self._failures,
                "respawn_backoff_s": round(self._backoff_s(), 3),
                "breaker": {
                    "state": "open" if self.breaker.open else "closed",
                    "trips": self.breaker.trips,
                    "threshold": self.breaker_k,
                    "window_s": self.breaker_window_s,
                    "reason": self.breaker.reason,
                },
            }

    def healthy(self):
        """(ok, fields) for the fleet /healthz hook: an open breaker is
        a degraded service — the fleet cannot self-heal."""
        with self._lock:
            ok = not self.breaker.open
            return ok, {
                "supervisor_breaker": (
                    "open" if self.breaker.open else "closed"
                ),
                **({"supervisor_breaker_reason": self.breaker.reason}
                   if self.breaker.open else {}),
            }
