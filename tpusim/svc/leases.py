"""Leased job ownership — the fleet's crash-recovery plane (ISSUE 12).

A lease is the on-disk claim a worker stakes on the jobs of one batch:
`<job digest>.lease.json` beside the job's spec/result files in the
artifact dir, written through the io.storage signed-JSON discipline
(atomic tmp+rename, payload-digest header), naming the worker, its pid,
the deadline, and the batch's full member list. The protocol:

  claim    the worker writes one lease per batch member BEFORE
           dispatching (os.replace also atomically overwrites a dead
           predecessor's stale lease — stealing IS re-claiming).
  renew    while the batch is in flight the worker rewrites its leases
           with a pushed-out deadline — on heartbeat ticks when the scan
           emits them, and on a fallback timer (the vmapped sweep strips
           in-scan heartbeats), every lease_s/3.
  release  completion deletes the lease; the signed result file is the
           durable record from then on.
  steal    a lease whose deadline passed (plus the clock-skew margin,
           below) marks its jobs orphaned: any live worker may re-claim
           them. Results stay byte-identical because the job digest pins
           the trajectory and result writes are atomic whole-file
           replaces of identical bytes — a duplicate completion by a
           worker that was presumed dead (hung, then resumed) is a
           silent no-op, not a conflict.

Expiry honors a clock-skew margin (`TPUSIM_LEASE_SKEW_S`, default 2 s —
the TPUSIM_EXEC_CRED_SKEW_S pattern, ISSUE 1): lease files may be
judged by a DIFFERENT host than the one that wrote them, and a lease
must never be stolen merely because two clocks disagree by a second.

Torn/edited/foreign lease files are skipped AND deleted with a
`[Degrade]` warning (the io.storage.load_valid_checkpoint pattern): a
lost lease only makes its jobs steal-eligible immediately, which is
always safe — content addressing guarantees a re-run converges on the
same bytes.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import List, Optional, Tuple

LEASE_SUFFIX = ".lease.json"
LEASE_SCHEMA = "tpusim-svc-lease/1"

# default lease duration; the serve CLI's --lease-s. Renewal runs at a
# third of it, so one missed renewal never expires a healthy worker.
DEFAULT_LEASE_S = 15.0


# Fail-loud env parsing (ISSUE 13 satellite): a typo'd
# TPUSIM_LEASE_SKEW_S used to fall back silently — a mis-set margin
# can make every lease either immortal or instantly stealable across
# a whole fleet, and the operator deserves to hear about it at the
# first read, with the variable named. The helper moved to
# tpusim.envutil (ISSUE 15 satellite) so the Pallas VMEM budget and
# future knobs share ONE validation path; the local alias keeps the
# svc-side call sites and tests stable.
from tpusim.envutil import float_env as _float_env


def lease_skew_s() -> float:
    """Clock-skew margin added to every expiry judgement (env
    TPUSIM_LEASE_SKEW_S, default 2 s). Unparseable values fail loudly
    at read (`_float_env`) — never silently, never deep in the expiry
    path."""
    return _float_env("TPUSIM_LEASE_SKEW_S", 2.0)


def default_lease_s() -> float:
    """The lease duration used when no --lease-s override is given:
    env TPUSIM_LEASE_S (same fail-loud validation; must be > 0) or
    DEFAULT_LEASE_S. A whole-fleet knob — workers learn the value from
    the register handshake, so only the coordinator reads it."""
    val = _float_env("TPUSIM_LEASE_S", DEFAULT_LEASE_S)
    if val <= 0.0:
        raise ValueError(
            f"TPUSIM_LEASE_S must be > 0 seconds, got {val}"
        )
    return val


def lease_path(artifact_dir: str, digest: str) -> str:
    return os.path.join(artifact_dir, f"{digest}{LEASE_SUFFIX}")


def write_lease(artifact_dir: str, digest: str, worker: str, pid: int,
                deadline_unix: float, members) -> str:
    """Stake (or renew, or steal — os.replace is the whole story) one
    job's lease. `members` is the batch's full digest list, so a single
    surviving lease file names every sibling a reaper should check."""
    from tpusim.io.storage import write_signed_json

    header = {"schema": LEASE_SCHEMA, "job": digest}
    doc = {
        "worker": str(worker),
        "pid": int(pid),
        "deadline_unix": float(deadline_unix),
        "members": [str(m) for m in members],
    }
    return write_signed_json(lease_path(artifact_dir, digest), header, doc)


def _degrade(path: str, err) -> None:
    print(
        f"[Degrade] skipping torn/foreign lease file {path} "
        f"({type(err).__name__}: {err}); deleted — its jobs are "
        "steal-eligible now",
        file=sys.stderr,
    )


def read_lease(artifact_dir: str, digest: str,
               on_skip=None) -> Optional[dict]:
    """The lease document for one job digest, or None. A file that fails
    the signed-JSON verification (torn write on a non-atomic filesystem,
    a hand edit, a foreign header) is DELETED and reported through
    `on_skip(path, err)` (default: a `[Degrade]` stderr line) — the
    load_valid_checkpoint pattern: never crash, never trust, never let a
    bad file shadow future claims."""
    from tpusim.io.storage import read_signed_json

    path = lease_path(artifact_dir, digest)
    if not os.path.isfile(path):
        return None
    try:
        header, doc = read_signed_json(path, LEASE_SCHEMA)
        if header.get("job") != digest:
            raise ValueError("foreign lease file (job digest mismatch)")
        if not isinstance(doc.get("worker"), str) or "deadline_unix" not in doc:
            raise ValueError("malformed lease document")
        return doc
    except (OSError, ValueError, json.JSONDecodeError) as err:
        (on_skip or _degrade)(path, err)
        try:
            os.unlink(path)
        except OSError:
            pass
        return None


def delete_lease(artifact_dir: str, digest: str) -> None:
    try:
        os.unlink(lease_path(artifact_dir, digest))
    except OSError:
        pass


def lease_expired(lease: dict, now: Optional[float] = None,
                  skew_s: Optional[float] = None) -> bool:
    """True when the lease's deadline has passed by MORE than the
    clock-skew margin — the only condition under which stealing is
    legitimate. A lease from a clock `skew_s` ahead of ours is still
    honored until the margin is exhausted."""
    if now is None:
        now = time.time()
    if skew_s is None:
        skew_s = lease_skew_s()
    return float(now) > float(lease.get("deadline_unix", 0.0)) + skew_s


def scan_leases(artifact_dir: str,
                on_skip=None) -> List[Tuple[str, dict]]:
    """Every (digest, lease doc) in the artifact dir, torn files skipped
    and deleted (read_lease semantics) — the reaper's and the restart
    recovery's work list."""
    if not os.path.isdir(artifact_dir):
        return []
    from tpusim.svc.coord import COORD_LEASE_BASENAME

    out = []
    for fname in sorted(os.listdir(artifact_dir)):
        if not fname.endswith(LEASE_SUFFIX):
            continue
        if fname == COORD_LEASE_BASENAME:
            # the leadership lease (ISSUE 17) shares the suffix but has
            # its own schema + reaper — never judge it as a job lease
            # (read_lease would "helpfully" delete it as foreign).
            continue
        digest = fname[: -len(LEASE_SUFFIX)]
        doc = read_lease(artifact_dir, digest, on_skip=on_skip)
        if doc is not None:
            out.append((digest, doc))
    return out
