"""HTTP plane of the replay service: the POST side of `tpusim serve`
(ISSUE 7).

JobService is a MonitorServer extension app (obs.server.add_app), so one
listener carries both planes — the PR 5 observability GETs (/metrics,
/healthz, /progress with per-job windows) and the job plane:

  POST /jobs             submit one job object or {"jobs": [...]};
                         202 on enqueue, 200 when every job was answered
                         from the digest cache, 400 on a malformed spec,
                         429 + Retry-After on a full queue (the
                         kube_client backoff contract)
  GET  /jobs/<id>        lifecycle: queued/batched/running/done/failed +
                         batch/lane placement
  GET  /jobs/<id>/result result document (placements summary, gpu_alloc,
                         frag, counters); 409 while the job is still in
                         flight, 404 for unknown ids
  GET  /queue            depth, capacity, batches formed, dedup hits,
                         compiled sweep-executable count (the PR 6
                         jit._cache_size() zero-recompile check, live)

start_job_server wires the full stack — queue + worker + monitor — and
is what `tpusim serve DIR --jobs` and the smoke/test surfaces drive.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
from typing import Dict, Optional, Tuple

from tpusim.obs import trace as obs_trace
from tpusim.svc import jobs as svc_jobs
from tpusim.svc.auth import check as _auth_check
from tpusim.svc.batcher import JobQueue, QueueFull, QuotaFull
from tpusim.svc.worker import TraceRef, Worker

_JSON = "application/json"


def _json_body(code: int, doc, headers: Optional[dict] = None):
    body = (json.dumps(doc, sort_keys=True) + "\n").encode()
    if headers:
        return code, _JSON, body, headers
    return code, _JSON, body


class JobService:
    """The extension app MonitorServer routes /jobs and /queue to."""

    # MonitorServer hands us the raw query string (the /events filters)
    accepts_query = True

    # bound on the digest -> trace-id map: FIFO like the monitor's
    # per-job progress window — a long-lived service must not grow
    # per-job state forever
    MAX_TRACE_IDS = 1024

    def __init__(self, queue: JobQueue, worker: Optional[Worker],
                 traces: Dict[str, TraceRef], artifact_dir: str,
                 monitor=None, policy_presets: Optional[dict] = None):
        self.queue = queue
        self.worker = worker  # in-process Worker, or None in fleet mode
        self.traces = dict(traces)
        self.artifact_dir = artifact_dir
        self.monitor = monitor
        # named learned-policy presets (ISSUE 14): preset name ->
        # [(policy name, weight)] pairs, expanded at submit time so the
        # queued/persisted/claimed spec is an ordinary policies job —
        # workers and the digest vocabulary never see preset names
        self.policy_presets = dict(policy_presets or {})
        # the fleet coordinator app (svc.fleet.FleetService) when
        # `serve --jobs --workers N` runs; None for the single
        # in-process worker of PR 7
        self.fleet = None
        # bearer token guarding every mutating endpoint (ISSUE 17);
        # empty = auth disabled. FleetService reads it via its `token`
        # property so both planes enforce ONE secret.
        self.token = ""
        # flight recorder (ISSUE 19): per-process span file + chained
        # audit log, armed by start_job_server / the CLI. Both optional:
        # a bare JobService in a unit test records nothing.
        self.spans = None  # obs.trace.SpanRecorder
        self.audit = None  # obs.audit.AuditLog
        # the SLO plane (ISSUE 20), armed by start_job_server: metrics
        # history ring, alert rule engine, and the sampler thread
        # driving both. A bare JobService in a unit test has none.
        self.tsdb = None  # obs.tsdb.TSDB
        self.alerts = None  # obs.alerts.AlertEngine
        self.sampler = None  # obs.tsdb.MetricsSampler
        # job digest -> trace id, fed by the submit header (or minted
        # here) and handed to workers at claim time so every process
        # tags its spans with the id minted at submit
        self.trace_ids: Dict[str, str] = {}
        # submit path serializes digest lookup + enqueue so concurrent
        # duplicate POSTs dedup instead of double-running
        self._submit_lock = threading.Lock()

    def trace_of(self, digest: str) -> str:
        return self.trace_ids.get(digest, "")

    def adopt_history(self, out=None) -> int:
        """Splice the predecessor's persisted tsdb snapshot into this
        process's ring and start (or resume) sampling — the metrics
        half of a takeover (ISSUE 20): a promoted standby serves
        /query with the deposed leader's history behind its own new
        samples instead of starting blind. Also the non-HA restart
        path: a rebooted coordinator adopts its own last snapshot.
        Returns the number of buckets adopted; a torn/edited snapshot
        is refused loudly (and sampling still resumes — fresh history
        beats no history)."""
        if self.tsdb is None:
            return 0
        n = 0
        try:
            n = self.tsdb.adopt(self.artifact_dir)
        except ValueError as err:
            if out is not None:
                print(f"[slo] refusing torn/edited tsdb snapshot: "
                      f"{err}", file=out)
        if self.sampler is not None:
            self.sampler.resume()
        if n and out is not None:
            print(f"[slo] adopted {n} history bucket(s) from the "
                  f"previous coordinator's snapshot", file=out)
        return n

    def publish_job(self, job) -> None:
        """Push a job's lifecycle change into the monitor's per-job
        /progress map (the fleet completion path publishes here on the
        worker's behalf)."""
        if self.monitor is not None:
            self.monitor.publish_job_progress(
                job.id, {"status": job.status, "worker": job.worker or ""}
            )

    # ---- submission (shared by HTTP and in-process callers) ----

    def submit_payload(self, payload: dict, trace_id: str = "") -> dict:
        """Validate + dedup + enqueue one job document. Returns the job
        description (with `cached` marking digest-cache answers); raises
        ValueError (→ 400) or QueueFull (→ 429). `trace_id` is the
        flight-recorder id off the submit header (minted here for
        in-process callers); it tags the admission span and is handed
        to whichever worker later claims the job — it NEVER enters the
        spec or its digest (two submits of one spec must still dedup)."""
        t_admit = time.time()
        payload = svc_jobs.expand_policy_preset(
            payload, self.policy_presets
        )
        if isinstance(payload, dict) and payload.get("fork"):
            payload = self._resolve_fork(payload)
        spec = svc_jobs.validate_job(payload)
        trace = self.traces.get(spec.trace)
        if trace is None:
            raise ValueError(
                f"unknown trace {spec.trace!r} (hosted: "
                f"{', '.join(sorted(self.traces)) or 'none'})"
            )
        digest = svc_jobs.job_digest(spec, trace.digest)
        tid = trace_id or obs_trace.new_trace_id()
        self.trace_ids[digest] = tid
        while len(self.trace_ids) > self.MAX_TRACE_IDS:
            self.trace_ids.pop(next(iter(self.trace_ids)))
        with self._submit_lock:
            cached = svc_jobs.find_result(self.artifact_dir, digest)
            job = self.queue.submit(spec, digest, cached_result=cached)
            if cached is None:
                # persist the accepted spec BEFORE it becomes runnable: a
                # crash mid-batch leaves a recoverable `.job.json` on
                # disk instead of a job stranded in `running` forever
                # (recover_pending_jobs requeues it at the next startup)
                svc_jobs.write_job_spec(self.artifact_dir, digest, payload)
        if self.spans is not None:
            self.spans.emit(
                obs_trace.SPAN_ADMIT, t_admit, time.time(),
                job=digest, trace=tid,
                cached=bool(cached is not None),
            )
        if self.monitor is not None:
            self.monitor.publish_job_progress(
                job.id, {"status": job.status, "phase": "submitted"}
            )
        return job.describe()

    def _resolve_fork(self, payload: dict) -> dict:
        """Expand a fork submission against the fork index (ISSUE 16):
        the client sends only the handle — base job digest, divergence
        event, tail (and mode) — and the base's full spec payload is
        merged in, so a fork is BY CONSTRUCTION the same replay as its
        base up to the divergence event. Any explicitly-supplied field
        must EQUAL the base's: the checkpointed carry embeds the base's
        weights in its blocked summaries, so a weight-changing fork can
        never restore from a base checkpoint — reject it loudly here
        instead of silently replaying cold."""
        from tpusim.svc import forks as svc_forks

        fork = payload.get("fork")
        if not isinstance(fork, dict):
            raise ValueError(
                'fork must be an object: {"base": <base job digest>, '
                '"event": E, "tail": [[kind, pod], ...]}'
            )
        base_digest = str(fork.get("base", ""))
        entry = svc_forks.load_base_entry(self.artifact_dir, base_digest)
        if entry is None:
            raise ValueError(
                f"fork base {base_digest[:12] or '?'}… has no finished "
                'base run on this service — submit {"base": true, ...} '
                "for the trace first and wait for it to finish"
            )
        base_payload = {
            k: v for k, v in entry["spec"].items() if k != "base"
        }
        base_spec = svc_jobs.validate_job(base_payload)
        merged = dict(base_payload)
        merged.update(
            {k: v for k, v in payload.items() if k != "fork"}
        )
        merged["fork"] = fork
        spec = svc_jobs.validate_job(merged)
        for field in ("trace", "policies", "weights", "seed", "gpu_sel",
                      "norm", "dim_ext", "tune", "tune_seed", "engine"):
            if getattr(spec, field) == getattr(base_spec, field):
                continue
            hint = ""
            if field in ("weights", "policies"):
                hint = (
                    " — the base checkpoints' carry embeds the base's "
                    "weight vector (blocked score summaries), so a "
                    "weight-changing what-if can never restore warm; "
                    "run it as its own base job"
                )
            raise ValueError(
                f"fork field {field!r} differs from base "
                f"{base_digest[:12]}… "
                f"({getattr(spec, field)!r} != "
                f"{getattr(base_spec, field)!r}): a warm-state fork "
                f"replays the base bit-identically up to the divergence "
                f"event{hint}"
            )
        return merged

    # ---- the MonitorServer app hook ----

    def handle(self, method: str, path: str, body: bytes, headers=None,
               query: str = ""):
        if path == "/jobs" and method == "POST":
            # auth BEFORE any parsing: a 401 must not leak whether the
            # body would have been a valid spec or a known digest
            if not _auth_check(headers, self.token):
                return _json_body(
                    401, {"error": "missing or invalid bearer token"}
                )
            if self.fleet is not None and self.fleet.role != "leader":
                return self.fleet.standby_503()
            return self._post_jobs(body, obs_trace.header_trace(headers))
        if path == "/queue" and method == "GET":
            return self._get_queue()
        if path == "/events" and method == "GET":
            return self._get_events(query)
        if path.startswith("/jobs/"):
            if method != "GET":
                return _json_body(405, {"error": "method not allowed"})
            rest = path[len("/jobs/"):]
            if rest.endswith("/result"):
                return self._get_result(rest[: -len("/result")])
            return self._get_job(rest)
        return None  # not ours: fall through to the monitor built-ins

    def _post_jobs(self, body: bytes, trace_id: str = ""):
        try:
            payload = json.loads(body.decode() or "null")
        except (json.JSONDecodeError, UnicodeDecodeError) as err:
            return _json_body(400, {"error": f"bad JSON body: {err}"})
        is_batch = isinstance(payload, dict) and "jobs" in payload
        docs = payload["jobs"] if is_batch else [payload]
        if not isinstance(docs, list) or not docs:
            return _json_body(
                400, {"error": 'want a job object or {"jobs": [...]}'}
            )
        accepted = []
        first_429: Optional[QueueFull] = None
        rejected_indices = []
        for i, doc in enumerate(docs):
            try:
                accepted.append(self.submit_payload(doc, trace_id))
            except ValueError as err:
                # reject the lot on the first malformed doc: a half-
                # accepted batch would make retries re-submit (harmless,
                # dedup'd) but hides the error from casual clients
                return _json_body(
                    400, {"error": str(err), "accepted": accepted}
                )
            except QueueFull as err:
                # backpressure: the rejected doc waits, but the REST of
                # the batch still gets its admission attempt — a hot
                # family at its quota must not block a cold family's
                # jobs riding the same POST (the ISSUE 12 quota goal),
                # and even on a full queue a later duplicate can still
                # answer from the digest cache. The 429 body lists the
                # rejected docs' indices so the client retries exactly
                # those; a QuotaFull additionally names the family.
                if first_429 is None:
                    first_429 = err
                rejected_indices.append(i)
        if first_429 is not None:
            body = {"error": str(first_429), "accepted": accepted,
                    "rejected_indices": rejected_indices,
                    "retry_after_s": first_429.retry_after_s}
            if isinstance(first_429, QuotaFull):
                body["family"] = first_429.family
                body["family_quota"] = first_429.quota
            return _json_body(
                429, body,
                headers={"Retry-After": str(first_429.retry_after_s)},
            )
        all_cached = all(d["status"] == "done" for d in accepted)
        doc = {"jobs": accepted} if is_batch else accepted[0]
        return _json_body(200 if all_cached else 202, doc)

    def _get_job(self, job_id: str):
        job = self.queue.get(job_id)
        if job is None:
            return _json_body(404, {"error": f"unknown job {job_id!r}"})
        return _json_body(200, job.describe())

    def _get_result(self, job_id: str):
        job = self.queue.get(job_id)
        if job is None:
            return _json_body(404, {"error": f"unknown job {job_id!r}"})
        if job.status == "failed":
            return _json_body(
                500, {"error": job.error or "job failed", "id": job.id}
            )
        if job.status != "done" or job.result is None:
            return _json_body(
                409,
                {"error": f"job {job.id} is {job.status}; result not "
                 "ready", "status": job.status},
            )
        return _json_body(200, job.result)

    def _get_events(self, query: str = ""):
        """The audit-log query endpoint (ISSUE 19): bounded tail of the
        chained control-plane log, filterable by kind/job/worker. The
        read path link-checks the whole chain, so an edited log answers
        500 with the verifier's complaint, never silently wrong data."""
        from tpusim.obs import audit as obs_audit

        q = urllib.parse.parse_qs(query or "")

        def one(key, default=""):
            vals = q.get(key) or [default]
            return vals[0]

        try:
            # `limit` is the cursor-pagination spelling (ISSUE 20);
            # `n` stays as the original tail parameter — same clamp
            n = min(max(int(one("limit", "") or one("n", "50")), 1), 500)
            after = max(int(one("after", "0")), 0)
        except ValueError:
            return _json_body(
                400, {"error": "n, limit and after must be integers"}
            )
        try:
            events = obs_audit.tail(
                self.artifact_dir, n=n, kind=one("kind"),
                job=one("job"), worker=one("worker"), after=after,
            )
        except ValueError as err:
            return _json_body(
                500, {"error": f"audit chain unreadable: {err}"}
            )
        # next_after: the cursor a delta poller passes back — the
        # highest chain seq this response covers (records are
        # seq-stamped by obs_audit.tail). No events -> echo the cursor.
        next_after = max([r.get("seq", 0) for r in events] + [after])
        return _json_body(
            200, {"events": events, "n": len(events),
                  "next_after": next_after}
        )

    def _get_queue(self):
        """The aggregated /queue document (ISSUE 12): queue + quota
        stats, plus — in fleet mode — the per-worker rows (depth served,
        leases held, steals benefited, executables) and fleet totals;
        in single-worker mode, the in-process worker's numbers."""
        stats = self.queue.stats()
        if self.worker is not None:
            stats["sweep_executables"] = self.worker.sweep_executables()
            stats["batches_run"] = self.worker.batches_run
            stats["waves"] = self.worker.wave_stats()
        if self.fleet is not None:
            stats.update(self.fleet.queue_fields())
        stats["traces"] = sorted(self.traces)
        stats["policy_presets"] = sorted(self.policy_presets)
        return _json_body(200, stats)


def recover_pending_jobs(service: JobService, out=None) -> int:
    """Restart recovery (ISSUE 10 satellite; batched for the standby-
    promotion path, ISSUE 20): requeue every persisted job spec with no
    signed result — a service killed mid-batch answers its stranded
    jobs after restart instead of leaving them `running` forever.

    Two passes instead of the old one-submit_payload-per-spec loop: a
    LOCK-FREE validation pass (preset expansion, fork resolution, spec
    validation, digest recompute, result-cache probe — the expensive
    re-verification), then ONE JobQueue.submit_many under one lock
    acquisition, so a takeover with hundreds of queued jobs re-admits
    in a single pass. Returns the number requeued; malformed or
    no-longer-valid specs (code drift changes the digest, a hosted
    trace vanished) are skipped with a note, never fatal; a full queue
    stops the batch and leaves the rest for the clients' retries."""
    pending = svc_jobs.pending_job_specs(service.artifact_dir)
    if not pending:
        return 0
    t_admit = time.time()
    prepared = []  # (persisted digest, recomputed digest, spec, payload, cached)
    for digest, payload in pending:
        try:
            p = svc_jobs.expand_policy_preset(payload,
                                              service.policy_presets)
            if isinstance(p, dict) and p.get("fork"):
                p = service._resolve_fork(p)
            spec = svc_jobs.validate_job(p)
            trace = service.traces.get(spec.trace)
            if trace is None:
                raise ValueError(
                    f"unknown trace {spec.trace!r} (hosted: "
                    f"{', '.join(sorted(service.traces)) or 'none'})"
                )
            new_digest = svc_jobs.job_digest(spec, trace.digest)
            cached = svc_jobs.find_result(service.artifact_dir,
                                          new_digest)
            prepared.append((digest, new_digest, spec, p, cached))
        except ValueError as err:
            if out is not None:
                print(
                    f"[serve] skipping unrecoverable job "
                    f"{digest[:12]}…: {err}", file=out,
                )
    with service._submit_lock:
        jobs, leftover = service.queue.submit_many(
            [(spec, d, cached) for _, d, spec, _, cached in prepared]
        )
    t_done = time.time()
    for job, (old_digest, new_digest, _, p, cached) in zip(jobs,
                                                           prepared):
        tid = obs_trace.new_trace_id()
        service.trace_ids[new_digest] = tid
        if cached is None and new_digest != old_digest:
            # code drift moved the digest: persist under the NEW name
            # so the next crash recovers the job the queue now runs
            svc_jobs.write_job_spec(service.artifact_dir, new_digest, p)
        if service.spans is not None:
            service.spans.emit(
                obs_trace.SPAN_ADMIT, t_admit, t_done,
                job=new_digest, trace=tid,
                cached=bool(cached is not None),
            )
        if service.monitor is not None:
            service.monitor.publish_job_progress(
                job.id, {"status": job.status, "phase": "recovered"}
            )
    while len(service.trace_ids) > service.MAX_TRACE_IDS:
        service.trace_ids.pop(next(iter(service.trace_ids)))
    n = len(jobs)
    if n and service.audit is not None:
        # one batch record, not n flocked appends: the takeover path
        # must not serialize on the audit lock per queued job
        service.audit.emit(
            "requeue", n=n, reason="recovered-specs",
            jobs=[d[:12] for _, d, _, _, _ in prepared[:16]],
        )
    if leftover and out is not None:
        print(
            f"[serve] recovery stopped at a full queue ({leftover} "
            f"spec(s) left for the clients' retries)", file=out,
        )
    if n and out is not None:
        print(f"[serve] requeued {n} interrupted job(s) from "
              f"{service.artifact_dir}", file=out)
    return n


def start_job_server(
    artifact_dir: str, traces: Dict[str, TraceRef], listen: str = "",
    lane_width: int = 8, queue_size: int = 64, bucket: int = 512,
    table_cache_dir: str = "", compile_cache_dir: str = "",
    start_worker: bool = True, recover: bool = True, out=None,
    fleet: bool = False, lease_s: float = 0.0, family_quota: int = 0,
    policy_presets: Optional[dict] = None, token: str = "",
    coord=None, slo_file: str = "", slo_rules=None,
) -> Tuple[object, JobService, Optional[Worker]]:
    """Wire the full service: MonitorServer (+ heartbeat-fed /progress)
    with the JobService app, a bounded JobQueue, and either the single
    in-process Worker thread (PR 7) or — fleet=True (ISSUE 12) — the
    FleetService coordinator app (/workers/register|claim|renew|
    complete) that external worker PROCESSES drain the queue through.
    Returns (server, service, worker); worker is None in fleet mode.
    Caller owns shutdown (srv.begin_drain(); worker.stop(); srv.stop()).
    start_worker=False leaves batch dispatch to the caller
    (deterministic tests); recover=True requeues crash-interrupted jobs
    from the artifact dir before serving — in fleet mode it additionally
    ADOPTS still-live lease files (a coordinator restart under live
    workers must not double-hand-out their batches). `family_quota`
    arms the per-family admission cap; `lease_s` overrides the lease
    duration (svc.leases.DEFAULT_LEASE_S). `token` arms bearer auth on
    every mutating endpoint (ISSUE 17); `coord` (a
    svc.coord.CoordinatorState, fleet mode only) arms HA — epoch-fenced
    mutations, standby 503s, and recovery deferred until this process
    actually holds the leadership lease. `slo_file` (or `slo_rules`, a
    pre-validated list) arms the SLO plane (ISSUE 20): the tsdb
    history ring + sampler thread, the alert rule engine, and the
    /query + /alerts endpoints — a standby's sampler starts PAUSED and
    resumes at promotion via service.adopt_history()."""
    from tpusim.obs.server import MonitorServer

    srv = MonitorServer(listen)
    queue = JobQueue(maxsize=queue_size, lane_width=lane_width,
                     family_quota=family_quota, lease_s=lease_s)
    worker = None
    if not fleet:
        worker = Worker(
            queue, traces, artifact_dir, bucket=bucket, monitor=srv,
            table_cache_dir=table_cache_dir,
            compile_cache_dir=compile_cache_dir,
        )
    service = JobService(queue, worker, traces, artifact_dir, monitor=srv,
                         policy_presets=policy_presets)
    service.bucket = bucket  # the register handshake hands it to workers
    service.token = str(token or "")
    # flight recorder (ISSUE 19): every coordinator process writes its
    # own span file (HA pairs share the artifact dir, so the name is
    # pid-scoped) and appends control-plane decisions to the chained
    # audit log. Always armed — the log IS the operational record.
    from tpusim.obs.audit import AuditLog
    from tpusim.obs.trace import SpanRecorder

    proc = f"coord-{os.getpid()}"
    service.spans = SpanRecorder(artifact_dir, proc)
    service.audit = AuditLog(artifact_dir, proc)
    if coord is not None:
        coord.audit = service.audit

    # capability routing (ISSUE 17): tell the queue what each family
    # actually NEEDS, judged against the hosted trace — claim_batch only
    # hands fault-family or large-N work to workers declaring support.
    def _family_needs(spec):
        ref = service.traces.get(spec.trace)
        n_nodes = len(ref.nodes) if ref is not None else 0
        return {"fault": bool(spec.fault), "nodes": int(n_nodes),
                "mem_bytes": 0}

    queue.family_needs_fn = _family_needs
    srv.add_app(service)
    if fleet:
        from tpusim.svc.fleet import FleetService

        service.fleet = FleetService(service, lease_s=lease_s, out=out)
        service.fleet.coord = coord
        srv.add_app(service.fleet)
        # fleet /healthz: 503 only when NO worker is live
        srv.health_hook = service.fleet.health

    # the SLO plane (ISSUE 20): live per-kind latency summaries on
    # /metrics, the tsdb history ring + sampler, the alert rule engine,
    # and the /query + /alerts read surface. Always armed — history and
    # alerting ARE the operational record, like the audit chain.
    from tpusim.obs import alerts as obs_alerts
    from tpusim.obs import tsdb as obs_tsdb
    from tpusim.obs.emitters import latency_summary_lines

    srv.metrics_extra_fn = (
        lambda: latency_summary_lines(queue.latency_percentiles())
    )
    service.tsdb = obs_tsdb.TSDB()
    rules = (slo_rules if slo_rules is not None
             else obs_alerts.load_rules(slo_file))
    service.alerts = obs_alerts.AlertEngine(
        service.tsdb, rules, audit=service.audit
    )
    srv.add_app(obs_tsdb.TsdbApp(service.tsdb, service.alerts))
    # page-severity burn flips /healthz readiness detail — composed
    # over the fleet's worker-liveness hook, never replacing it
    srv.health_hook = service.alerts.compose_health(srv.health_hook)
    # a standby must not sample: only the leader writes history (and
    # the snapshot file) — promotion adopts + resumes (adopt_history)
    standby = coord is not None and coord.role != "leader"
    service.sampler = obs_tsdb.MetricsSampler(
        service.tsdb, obs_tsdb.ServiceCollector(service),
        alerts=service.alerts, artifact_dir=artifact_dir,
        paused=standby,
    )
    service.sampler.start()
    srv.on_stop(service.sampler.stop)
    if recover and (coord is None or coord.role == "leader"):
        # before start(): recovered jobs must be queued before the first
        # client request can observe the service. A standby defers —
        # adoption happens at promotion (the CLI's takeover path), when
        # the epoch fence guarantees the old leader can no longer act.
        recover_pending_jobs(service, out=out)
        if service.fleet is not None:
            service.fleet.adopt_leases(out=out)
    if not standby:
        # a booting leader adopts its own last snapshot: metrics
        # history survives a graceful restart, not just a failover
        service.adopt_history(out=out)
    srv.start()
    srv.attach_heartbeat()
    srv.publish_progress(phase="serving-jobs")
    if start_worker and worker is not None:
        worker.start()
    return srv, service, worker
