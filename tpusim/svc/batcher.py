"""Batcher: the bounded FIFO job queue + batch formation (ISSUE 7),
grown into the fleet's claim/steal plane (ISSUE 12).

The queue is the service's backpressure boundary: `submit` on a full
queue raises QueueFull, which the HTTP plane answers as 429 with a
Retry-After header — the same contract tpusim.io.kube_client's retry
loop already honors client-side (capped-exponential backoff, the
server-provided delay wins), so a tpusim-built client dogpiles neither
the service nor, transitively, the device. Per-family admission quotas
(ISSUE 12 satellite) add a second 429 surface: `family_quota > 0` caps
how deep any ONE job family may queue, so a hot trace cannot starve the
rest — a quota overflow raises QuotaFull (a QueueFull subclass carrying
the family label), distinguishable in the 429 body.

Batch formation is FIFO with compatibility grouping — the queue is
logically SHARDED by family key: the next batch is the OLDEST queued
job plus every other queued job sharing its family key
(JobSpec.family_key — the jaxpr-identity rule: same trace + policy
family + scoring methods + engine), in submission order, up to the
worker's lane width. Jobs whose family differs ride later batches —
possibly singleton lanes — so one incompatible job can delay but never
starve the stream.

The fleet operations (ISSUE 12): `claim_batch(worker)` is batch
formation with OWNERSHIP — claimed jobs carry the worker id and an
in-memory lease deadline (mirroring the signed lease FILES the worker
writes, svc.leases). `steal_expired()` is the orphan reaper: any job
whose lease deadline passed without completion is requeued at the FRONT
of its family shard in original submission order (steal ordering: an
orphan never loses its place to younger work), so the next live
worker's claim re-runs it. `renew()` pushes a live worker's deadlines
out; `release_worker()` requeues everything a deregistered/dead worker
held. Duplicate completions — a stolen job finished by BOTH the thief
and a not-actually-dead original owner — are a silent dedup
(`dup_completions` counter), never a conflict: job digests pin the
trajectory, so both results are byte-identical. Everything here is
host-side bookkeeping under one lock.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tpusim.svc.jobs import JobSpec

# job lifecycle: queued -> batched (claimed/leased) -> running ->
# done | failed, with batched/running -> queued again on a steal
# (dedup'd submissions adopt the original job — same id, same record)
STATUSES = ("queued", "batched", "running", "done", "failed")


class QueueFull(RuntimeError):
    """Bounded queue overflow — the 429/Retry-After surface."""

    def __init__(self, depth: int, retry_after_s: int):
        super().__init__(
            f"job queue full ({depth} queued); retry after "
            f"{retry_after_s}s"
        )
        self.depth = depth
        self.retry_after_s = retry_after_s


class QuotaFull(QueueFull):
    """Per-family admission-quota overflow (ISSUE 12 satellite): the
    queue has room, but THIS family's shard is at its cap — a hot trace
    must not starve the rest. Same 429 + Retry-After surface, with the
    family label in the body so clients can tell backpressure kinds
    apart."""

    def __init__(self, family: str, depth: int, quota: int,
                 retry_after_s: int):
        RuntimeError.__init__(
            self,
            f"family quota full ({depth}/{quota} queued for "
            f"{family}); retry after {retry_after_s}s"
        )
        self.family = family
        self.depth = depth
        self.quota = quota
        self.retry_after_s = retry_after_s


@dataclass
class Job:
    """One submitted job's runtime record."""

    id: str
    spec: JobSpec
    digest: str
    status: str = "queued"
    batch: int = -1  # batch sequence number once grouped
    lane: int = -1  # lane index inside its batch's sweep
    cached: bool = False  # answered from the digest cache, never ran
    result: Optional[dict] = None
    error: str = ""
    submitted_unix: float = field(default_factory=time.time)
    finished_unix: float = 0.0
    seq: int = 0  # submission order (steal requeue preserves it)
    worker: str = ""  # owning worker id while claimed (ISSUE 12)
    lease_deadline_unix: float = 0.0  # in-memory lease mirror
    stolen: int = 0  # times this job was reclaimed from a dead worker
    # the serving-latency instrumentation (ISSUE 16): admission ->
    # claim -> dispatch -> result wall-clock stamps. `claimed` is set by
    # claim_batch/claim_family, `dispatched` by the worker the moment
    # the job's lane actually begins executing (for a continuous-
    # batching joiner that is its wave-join boundary, not the batch
    # claim), `finished` by mark_done/mark_failed. A steal clears the
    # claim/dispatch stamps — the retry's latency is measured fresh.
    claimed_unix: float = 0.0
    dispatched_unix: float = 0.0
    # steal visibility (ISSUE 19): how many times this job was handed
    # to a worker (every claim stamping increments), who owned it when
    # a steal cleared the claim (the audit record's worker), and the
    # wall-clock the abandoned attempts consumed — mark_done subtracts
    # it for the steals-ADJUSTED admission->result latency view
    attempts: int = 0
    last_worker: str = ""
    steal_lost_s: float = 0.0

    def kind(self) -> str:
        """Latency-bucket vocabulary: base | fork | full | plain —
        fork/full split by mode so the SLO gate can compare the warm
        path against its from-event-0 twin."""
        if self.spec.base:
            return "base"
        if self.spec.fork:
            return "full" if self.spec.fork[2] == "full" else "fork"
        return "plain"

    def describe(self) -> dict:
        """The GET /jobs/<id> document."""
        out = {
            "id": self.id,
            "digest": self.digest,
            "status": self.status,
            "cached": self.cached,
            "trace": self.spec.trace,
            "weights": list(self.spec.weights),
            "seed": self.spec.seed,
            "tune": self.spec.tune,
        }
        if self.batch >= 0:
            out["batch"] = self.batch
            out["lane"] = self.lane
        if self.worker:
            out["worker"] = self.worker
        if self.stolen:
            out["stolen"] = self.stolen
        if self.error:
            out["error"] = self.error
        # per-job latency ladder (ISSUE 16): every stamp that exists,
        # plus the end-to-end admission->result latency once terminal
        out["submitted_unix"] = self.submitted_unix
        if self.claimed_unix:
            out["claimed_unix"] = self.claimed_unix
            out["claim_latency_s"] = (
                self.claimed_unix - self.submitted_unix
            )
        if self.dispatched_unix:
            out["dispatched_unix"] = self.dispatched_unix
        if self.attempts:
            out["attempts"] = self.attempts
        if self.steal_lost_s:
            out["steal_lost_s"] = round(self.steal_lost_s, 3)
        if self.finished_unix:
            out["finished_unix"] = self.finished_unix
            out["latency_s"] = self.finished_unix - self.submitted_unix
            if self.steal_lost_s:
                # what the latency WOULD have been had no attempt been
                # abandoned — the steals-adjusted view (ISSUE 19)
                out["adjusted_latency_s"] = max(
                    out["latency_s"] - self.steal_lost_s, 0.0
                )
        return out


class JobQueue:
    """Bounded, family-sharded FIFO queue + job registry (thread-safe).
    `family_quota > 0` caps any one family's queued depth (QuotaFull);
    `lease_s` is the in-memory lease duration claim_batch stamps on
    claimed jobs (mirrored by the signed lease files, svc.leases)."""

    def __init__(self, maxsize: int = 64, lane_width: int = 8,
                 retry_after_s: int = 2, family_quota: int = 0,
                 lease_s: float = 0.0):
        from tpusim.svc.leases import default_lease_s

        if maxsize < 1 or lane_width < 1:
            raise ValueError(
                f"maxsize and lane_width must be >= 1 "
                f"(got {maxsize}, {lane_width})"
            )
        if family_quota < 0:
            raise ValueError(f"family_quota must be >= 0, got {family_quota}")
        self.maxsize = int(maxsize)
        self.lane_width = int(lane_width)
        self.retry_after_s = int(retry_after_s)
        self.family_quota = int(family_quota)
        self.lease_s = float(lease_s) if lease_s > 0 else default_lease_s()
        self._cond = threading.Condition()
        self._queue: List[Job] = []  # submission order within shards
        self._jobs: Dict[str, Job] = {}  # id -> Job (all lifecycles)
        self._by_digest: Dict[str, Job] = {}  # digest -> canonical Job
        self._seq = 0
        self._batches = 0
        self.stats_counters = {
            "submitted": 0, "dedup_hits": 0, "rejected": 0,
            "done": 0, "failed": 0,
            # the fleet counters (ISSUE 12): quota 429s, orphan steals,
            # lease expiries observed, and silently-dedup'd duplicate
            # completions of stolen jobs
            "quota_rejected": 0, "steals": 0, "lease_expired": 0,
            "dup_completions": 0,
            # capability routing (ISSUE 17): claims that found queued
            # work but nothing THIS worker declared support for
            "starved_claims": 0,
        }
        # capability routing (ISSUE 17): spec -> needs dict
        # ({"fault": bool, "nodes": int, "mem_bytes": int}); the
        # coordinator installs a trace-aware version (api.start_job_
        # server) so above-threshold-N families route only to workers
        # declaring the capacity. None -> spec-only needs (fault flag).
        self.family_needs_fn = None
        # admission->result latency samples per job kind (ISSUE 16):
        # bounded ring per bucket, fed by mark_done (cached dedup hits
        # never ran, so they never sample); /queue serves p50/p99
        self._latency: Dict[str, List[float]] = {}
        # the steals-ADJUSTED twin (ISSUE 19): same samples minus each
        # job's steal_lost_s — raw p99 answers "what did users see",
        # adjusted p99 answers "what would the fleet do without deaths"
        self._latency_adj: Dict[str, List[float]] = {}
        self._latency_cap = 1024
        # ever-increasing completion count per kind — the SLO plane's
        # event cursor (ISSUE 20): latency_samples_since() slices the
        # ring by completions-seen, so each alert-engine tick observes
        # every completion exactly once instead of re-reading the ring
        self._latency_total: Dict[str, int] = {}

    # ---- submission / lookup ----

    def submit(self, spec: JobSpec, digest: str,
               cached_result: Optional[dict] = None) -> Job:
        """Register a job. A digest already known (queued, running, or
        done) dedups to the existing Job — the duplicate never touches
        the queue or the device. `cached_result` short-circuits a fresh
        digest straight to done (the disk-cache hit). Raises QueueFull
        when a genuinely new job meets a full queue, QuotaFull when its
        FAMILY shard is at the per-family admission cap."""
        with self._cond:
            job = self._submit_locked(spec, digest, cached_result)
            self._cond.notify_all()
            return job

    def _submit_locked(self, spec: JobSpec, digest: str,
                       cached_result: Optional[dict] = None) -> Job:
        """submit()'s body under an ALREADY-HELD self._cond — the
        single-lock core both submit and submit_many share (ISSUE 20).
        Does not notify; callers do, once per lock hold."""
        existing = self._by_digest.get(digest)
        if existing is not None and existing.status != "failed":
            self.stats_counters["dedup_hits"] += 1
            return existing
        if cached_result is not None:
            job = self._new_job(spec, digest)
            job.status = "done"
            job.cached = True
            job.result = cached_result
            job.finished_unix = time.time()
            self.stats_counters["dedup_hits"] += 1
            self.stats_counters["done"] += 1
            return job
        if len(self._queue) >= self.maxsize:
            self.stats_counters["rejected"] += 1
            raise QueueFull(len(self._queue), self.retry_after_s)
        if self.family_quota > 0:
            fam = spec.family_key()
            depth = sum(
                1 for j in self._queue if j.spec.family_key() == fam
            )
            if depth >= self.family_quota:
                self.stats_counters["quota_rejected"] += 1
                raise QuotaFull(
                    spec.family_label(), depth, self.family_quota,
                    self.retry_after_s,
                )
        job = self._new_job(spec, digest)
        self._queue.append(job)
        self.stats_counters["submitted"] += 1
        return job

    def submit_many(self, items) -> Tuple[List[Job], int]:
        """Batched admission (ISSUE 20, the standby-promotion path):
        `items` is [(spec, digest, cached_result)], folded in under ONE
        lock acquisition with ONE claimant wakeup — a takeover with
        hundreds of queued specs re-admits in a single pass instead of
        serially bouncing the queue lock per job. Returns (jobs,
        leftover): one Job per accepted item in order; a full queue (or
        an at-quota family) stops the batch, and `leftover` counts the
        items never attempted — the same stop-at-backpressure contract
        recovery's serial loop had."""
        items = list(items)
        jobs: List[Job] = []
        with self._cond:
            for spec, digest, cached in items:
                try:
                    jobs.append(
                        self._submit_locked(spec, digest, cached)
                    )
                except QueueFull:
                    break
            if jobs:
                self._cond.notify_all()
        return jobs, len(items) - len(jobs)

    def _new_job(self, spec: JobSpec, digest: str) -> Job:
        self._seq += 1
        job = Job(id=f"j{self._seq:05d}-{digest[:10]}", spec=spec,
                  digest=digest, seq=self._seq)
        self._jobs[job.id] = job
        self._by_digest[digest] = job
        return job

    def get(self, job_id: str) -> Optional[Job]:
        with self._cond:
            return self._jobs.get(job_id)

    def get_by_digest(self, digest: str) -> Optional[Job]:
        """The canonical Job of a digest (the fleet completion path is
        digest-keyed: job IDs do not survive a coordinator restart,
        digests do)."""
        with self._cond:
            return self._by_digest.get(digest)

    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    # ---- capability routing (ISSUE 17) ----

    def _needs(self, spec: JobSpec) -> dict:
        """What serving this spec's family requires of a worker."""
        if self.family_needs_fn is not None:
            try:
                return dict(self.family_needs_fn(spec))
            except Exception:
                pass  # a broken needs fn must not wedge claims
        return {"fault": bool(spec.fault), "nodes": 0, "mem_bytes": 0}

    def eligible(self, spec: JobSpec, caps: Optional[dict]) -> bool:
        """May a worker with these capability tags serve this spec's
        family? No caps (a pre-ISSUE-17 worker, or the local in-process
        one) means unrestricted — every pre-existing flow is unchanged.
        A worker declares: fault_lanes (fault-schedule sweep support,
        default True), max_nodes (biggest trace it will take, 0 =
        unlimited), memory_bytes (approximate host/device memory, 0 =
        undeclared)."""
        if not caps:
            return True
        needs = self._needs(spec)
        if needs.get("fault") and not caps.get("fault_lanes", True):
            return False
        max_nodes = int(caps.get("max_nodes") or 0)
        if max_nodes and int(needs.get("nodes") or 0) > max_nodes:
            return False
        mem = int(caps.get("memory_bytes") or 0)
        if mem and int(needs.get("mem_bytes") or 0) > mem:
            return False
        return True

    def starved_families(self, caps_list) -> List[str]:
        """Family labels with queued work that NO live worker's
        capability tags can serve — the `/queue` starvation surface.
        Only meaningful when there ARE live workers (an empty fleet is
        'no workers', not 'no capable workers'): callers pass the live
        registry's caps and skip the call when it is empty."""
        caps_list = [c or {} for c in caps_list]
        out: List[str] = []
        with self._cond:
            seen = set()
            for j in self._queue:
                fam = j.spec.family_key()
                if fam in seen:
                    continue
                seen.add(fam)
                if not any(self.eligible(j.spec, c) for c in caps_list):
                    out.append(j.spec.family_label())
        return out

    # ---- batch formation: the claim side of the lease protocol ----

    def claim_batch(self, worker: str, timeout: Optional[float] = None,
                    linger_s: float = 0.0,
                    now: Optional[float] = None,
                    caps: Optional[dict] = None) -> List[Job]:
        """Pop the next batch FOR `worker`: the oldest queued job + every
        queued job sharing its family key (the family shard), FIFO
        order, up to lane_width — each claimed job stamped with the
        worker id and an in-memory lease deadline (now + lease_s).
        Blocks up to `timeout` for work; an empty list means none
        arrived. `linger_s` is the batching window: once work exists,
        wait up to that long for the rest of a concurrent submission
        wave to land (a wave split across two batches costs two scans —
        and, when the stragglers carry bigger tuned traces, a recompile
        the one-batch form would have amortized).

        `caps` (ISSUE 17) makes the claim capability-aware: the batch
        family is the OLDEST queued family this worker's tags can
        serve — FIFO preserved within eligible work, ineligible
        families left in place for a capable claimer (never reordered,
        never dropped). Queued work with nothing eligible counts a
        `starved_claims` tick and returns empty immediately."""
        with self._cond:
            if not self._queue:
                self._cond.wait(timeout)
            if not self._queue:
                return []
            if linger_s > 0:
                deadline = time.time() + linger_s
                while len(self._queue) < self.lane_width:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
            fam = None
            if caps:
                for j in self._queue:
                    if self.eligible(j.spec, caps):
                        fam = j.spec.family_key()
                        break
                if fam is None:
                    self.stats_counters["starved_claims"] += 1
                    return []
            else:
                fam = self._queue[0].spec.family_key()
            batch = [
                j for j in self._queue if j.spec.family_key() == fam
            ][: self.lane_width]
            taken = set(id(j) for j in batch)
            self._queue = [j for j in self._queue if id(j) not in taken]
            self._batches += 1
            claim_t = now if now is not None else time.time()
            lease_deadline = claim_t + self.lease_s
            for lane, job in enumerate(batch):
                job.status = "batched"
                job.batch = self._batches
                job.lane = lane
                job.worker = str(worker)
                job.lease_deadline_unix = lease_deadline
                job.claimed_unix = claim_t
                job.attempts += 1
            self._cond.notify_all()
            return batch

    def claim_family(self, worker: str, family_key,
                     max_n: int = 0,
                     now: Optional[float] = None) -> List[Job]:
        """Non-blocking targeted claim: up to max_n QUEUED jobs of ONE
        family, FIFO order — the continuous-batching join path
        (ISSUE 16): a worker whose wave for this family is running
        polls at every chunk boundary, and late arrivals replace
        padding lanes instead of waiting for the wave to drain. Same
        ownership/lease stamping as claim_batch."""
        if max_n <= 0:
            return []
        with self._cond:
            batch = [
                j for j in self._queue
                if j.spec.family_key() == family_key
            ][: int(max_n)]
            if not batch:
                return []
            taken = set(id(j) for j in batch)
            self._queue = [j for j in self._queue if id(j) not in taken]
            claim_t = now if now is not None else time.time()
            lease_deadline = claim_t + self.lease_s
            for job in batch:
                job.status = "batched"
                job.worker = str(worker)
                job.lease_deadline_unix = lease_deadline
                job.claimed_unix = claim_t
                job.attempts += 1
            self._cond.notify_all()
            return batch

    def next_batch(self, timeout: Optional[float] = None,
                   linger_s: float = 0.0) -> List[Job]:
        """Back-compat single-worker pop: claim_batch as 'local'."""
        return self.claim_batch("local", timeout=timeout, linger_s=linger_s)

    # ---- the steal/renew side (ISSUE 12) ----

    def steal_expired(self, now: Optional[float] = None) -> List[Job]:
        """The orphan reaper: every claimed-but-unfinished job whose
        in-memory lease deadline has passed is requeued at the FRONT of
        the queue in ORIGINAL submission order (steal ordering: an
        orphan outranks younger queued work — it was admitted first and
        has already waited a full lease), cleared of its owner, and
        counted. Any live worker's next claim re-runs it; its result is
        byte-identical by the digest argument, so even a not-actually-
        dead owner racing the thief is harmless. Returns the stolen
        jobs. In-memory deadlines share one clock, so no skew margin
        applies here (the FILE judgement in svc.leases adds one)."""
        if now is None:
            now = time.time()
        with self._cond:
            stolen = [
                j for j in self._jobs.values()
                if j.status in ("batched", "running") and j.worker
                and now > j.lease_deadline_unix
            ]
            if not stolen:
                return []
            stolen.sort(key=lambda j: j.seq)
            for job in stolen:
                job.last_worker = job.worker
                if job.claimed_unix:
                    job.steal_lost_s += max(now - job.claimed_unix, 0.0)
                job.status = "queued"
                job.worker = ""
                job.lease_deadline_unix = 0.0
                job.batch = -1
                job.lane = -1
                job.stolen += 1
                job.claimed_unix = 0.0
                job.dispatched_unix = 0.0
            self.stats_counters["lease_expired"] += len(stolen)
            self.stats_counters["steals"] += len(stolen)
            self._queue = stolen + self._queue
            self._cond.notify_all()
            return stolen

    def renew(self, worker: str, digests,
              now: Optional[float] = None) -> "tuple":
        """Push out the lease deadlines of `worker`'s in-flight jobs.
        Returns (renewed digests, lost digests): a digest the worker no
        longer owns — stolen after an expiry, or finished by a thief —
        lands in `lost`, telling a slow-but-alive worker to stop
        renewing (finishing the batch anyway is safe, just wasted
        work)."""
        if now is None:
            now = time.time()
        renewed, lost = [], []
        with self._cond:
            for digest in digests:
                job = self._by_digest.get(digest)
                if (job is not None and job.worker == str(worker)
                        and job.status in ("batched", "running")):
                    job.lease_deadline_unix = now + self.lease_s
                    renewed.append(digest)
                else:
                    lost.append(digest)
        return renewed, lost

    def release_worker(self, worker: str) -> List[Job]:
        """Requeue everything `worker` holds — the explicit form of
        steal_expired for a worker KNOWN to be gone (deregistration, a
        reaped child process): no need to wait out the lease. Counts as
        steals, not lease expiries."""
        with self._cond:
            held = [
                j for j in self._jobs.values()
                if j.status in ("batched", "running")
                and j.worker == str(worker)
            ]
            if not held:
                return []
            held.sort(key=lambda j: j.seq)
            now = time.time()
            for job in held:
                job.last_worker = job.worker
                if job.claimed_unix:
                    job.steal_lost_s += max(now - job.claimed_unix, 0.0)
                job.status = "queued"
                job.worker = ""
                job.lease_deadline_unix = 0.0
                job.batch = -1
                job.lane = -1
                job.stolen += 1
                job.claimed_unix = 0.0
                job.dispatched_unix = 0.0
            self.stats_counters["steals"] += len(held)
            self._queue = held + self._queue
            self._cond.notify_all()
            return held

    def claim_specific(self, worker: str, digests,
                       deadline_unix: float) -> List[Job]:
        """Assign SPECIFIC queued jobs to a worker with an explicit
        deadline — the coordinator-restart lease-adoption path (a live
        lease file proves a worker already owns these jobs; handing
        them out again would double-run). Returns the jobs actually
        claimed (queued ones only)."""
        with self._cond:
            claimed = []
            for digest in digests:
                job = self._by_digest.get(digest)
                if job is None or job.status != "queued":
                    continue
                self._queue = [j for j in self._queue if j is not job]
                job.status = "batched"
                job.worker = str(worker)
                job.lease_deadline_unix = float(deadline_unix)
                job.claimed_unix = time.time()
                job.attempts += 1
                claimed.append(job)
            return claimed

    def jobs_of_worker(self, worker: str) -> List[Job]:
        """The claimed/running jobs a worker currently owns (its live
        leases — the /queue per-worker `leases_held` view)."""
        with self._cond:
            return [
                j for j in self._jobs.values()
                if j.status in ("batched", "running")
                and j.worker == str(worker)
            ]

    # ---- worker-side lifecycle transitions ----

    def mark_running(self, batch: List[Job]) -> None:
        with self._cond:
            for job in batch:
                if job.status == "batched":
                    job.status = "running"

    def mark_done(self, job: Job, result: dict) -> None:
        """Complete a job. Completing an ALREADY-done job — the stolen-
        job race: thief and presumed-dead owner both finish — is a
        silent dedup (the results are byte-identical by construction;
        the first completion stands)."""
        with self._cond:
            if job.status == "done":
                self.stats_counters["dup_completions"] += 1
                return
            job.status = "done"
            job.result = result
            job.worker = ""
            job.lease_deadline_unix = 0.0
            job.finished_unix = time.time()
            self.stats_counters["done"] += 1
            lat = job.finished_unix - job.submitted_unix
            samples = self._latency.setdefault(job.kind(), [])
            samples.append(lat)
            kind = job.kind()
            self._latency_total[kind] = self._latency_total.get(kind, 0) + 1
            if len(samples) > self._latency_cap:
                del samples[: len(samples) - self._latency_cap]
            adj = self._latency_adj.setdefault(job.kind(), [])
            adj.append(max(lat - job.steal_lost_s, 0.0))
            if len(adj) > self._latency_cap:
                del adj[: len(adj) - self._latency_cap]

    def mark_failed(self, job: Job, error: str) -> None:
        with self._cond:
            if job.status == "done":
                # a late failure report for a job a thief already
                # completed: the success stands (same dedup rule)
                self.stats_counters["dup_completions"] += 1
                return
            job.status = "failed"
            job.error = str(error)
            job.worker = ""
            job.lease_deadline_unix = 0.0
            job.finished_unix = time.time()
            self.stats_counters["failed"] += 1
            # a failed digest must not swallow future submissions of the
            # same job (submit() skips failed entries already; dropping
            # the mapping keeps the registry from pinning the failure)
            if self._by_digest.get(job.digest) is job:
                del self._by_digest[job.digest]

    # ---- introspection (the GET /queue document) ----

    def family_depths(self) -> Dict[str, int]:
        """Queued depth per family label — the admission-quota view."""
        with self._cond:
            out: Dict[str, int] = {}
            for j in self._queue:
                label = j.spec.family_label()
                out[label] = out.get(label, 0) + 1
            return out

    def latency_samples_since(self, cursors: Dict[str, int]
                              ) -> Dict[str, List[float]]:
        """Latency samples of completions PAST each kind's cursor,
        advancing the cursors in place (ISSUE 20). The SLO sampler's
        event feed: burn-rate math wants per-completion goodness, and
        the cumulative ring p99 can't give it (one slow job pins the
        p99 for the ring's whole lifetime). Completions that fell off
        the bounded ring between polls are surfaced as what remains —
        the cursor still advances past them, never double-counting."""
        with self._cond:
            out: Dict[str, List[float]] = {}
            for kind, total in self._latency_total.items():
                new = total - int(cursors.get(kind, 0))
                if new <= 0:
                    continue
                samples = self._latency.get(kind) or []
                out[kind] = list(samples[-min(new, len(samples)):])
                cursors[kind] = total
            return out

    def latency_percentiles(self) -> Dict[str, dict]:
        """{kind: {count, p50_s, p99_s}} over the bounded admission->
        result sample rings — the /queue latency view and the
        serve-latency gate's SLO input (nearest-rank percentiles, so
        small smoke samples are exact, not interpolated)."""
        def _pct(s, q):
            n = len(s)
            return s[min(n - 1, max(0, int(q * n + 0.999999) - 1))]

        with self._cond:
            out: Dict[str, dict] = {}
            for kind, samples in self._latency.items():
                if not samples:
                    continue
                s = sorted(samples)
                row = {
                    "count": len(s),
                    "p50_s": _pct(s, 0.50),
                    "p99_s": _pct(s, 0.99),
                }
                adj = sorted(self._latency_adj.get(kind) or [])
                if adj:
                    # the steals-adjusted twin (ISSUE 19): the same
                    # samples with each job's abandoned-attempt wall
                    # subtracted — the gap between the pairs IS the
                    # latency cost of worker deaths
                    row["adjusted_p50_s"] = _pct(adj, 0.50)
                    row["adjusted_p99_s"] = _pct(adj, 0.99)
                out[kind] = row
            return out

    def stats(self) -> dict:
        fams = self.family_depths()
        lat = self.latency_percentiles()
        with self._cond:
            return {
                "depth": len(self._queue),
                "capacity": self.maxsize,
                "lane_width": self.lane_width,
                "batches_formed": self._batches,
                "family_quota": self.family_quota,
                "families": fams,
                "lease_s": self.lease_s,
                "latency": lat,
                **self.stats_counters,
            }

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until every submitted job reached a terminal state
        (test/smoke helper). True on idle, False on timeout."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._cond:
                busy = [
                    j for j in self._jobs.values()
                    if j.status not in ("done", "failed")
                ]
            if not busy:
                return True
            time.sleep(0.02)
        return False
