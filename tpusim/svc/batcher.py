"""Batcher: the bounded FIFO job queue + batch formation (ISSUE 7).

The queue is the service's backpressure boundary: `submit` on a full
queue raises QueueFull, which the HTTP plane answers as 429 with a
Retry-After header — the same contract tpusim.io.kube_client's retry
loop already honors client-side (capped-exponential backoff, the
server-provided delay wins), so a tpusim-built client dogpiles neither
the service nor, transitively, the device.

Batch formation is FIFO with compatibility grouping: the next batch is
the OLDEST queued job plus every other queued job sharing its family
key (JobSpec.family_key — the jaxpr-identity rule: same trace + policy
family + scoring methods + engine), in submission order, up to the
worker's lane width. Jobs whose family differs ride later batches —
possibly singleton lanes — so one incompatible job can delay but never
starve the stream. Everything here is host-side bookkeeping under one
lock; the single Worker thread is the only consumer.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpusim.svc.jobs import JobSpec

# job lifecycle: queued -> batched -> running -> done | failed
# (dedup'd submissions adopt the original job — same id, same record)
STATUSES = ("queued", "batched", "running", "done", "failed")


class QueueFull(RuntimeError):
    """Bounded queue overflow — the 429/Retry-After surface."""

    def __init__(self, depth: int, retry_after_s: int):
        super().__init__(
            f"job queue full ({depth} queued); retry after "
            f"{retry_after_s}s"
        )
        self.depth = depth
        self.retry_after_s = retry_after_s


@dataclass
class Job:
    """One submitted job's runtime record."""

    id: str
    spec: JobSpec
    digest: str
    status: str = "queued"
    batch: int = -1  # batch sequence number once grouped
    lane: int = -1  # lane index inside its batch's sweep
    cached: bool = False  # answered from the digest cache, never ran
    result: Optional[dict] = None
    error: str = ""
    submitted_unix: float = field(default_factory=time.time)
    finished_unix: float = 0.0

    def describe(self) -> dict:
        """The GET /jobs/<id> document."""
        out = {
            "id": self.id,
            "digest": self.digest,
            "status": self.status,
            "cached": self.cached,
            "trace": self.spec.trace,
            "weights": list(self.spec.weights),
            "seed": self.spec.seed,
            "tune": self.spec.tune,
        }
        if self.batch >= 0:
            out["batch"] = self.batch
            out["lane"] = self.lane
        if self.error:
            out["error"] = self.error
        return out


class JobQueue:
    """Bounded FIFO queue + job registry (thread-safe)."""

    def __init__(self, maxsize: int = 64, lane_width: int = 8,
                 retry_after_s: int = 2):
        if maxsize < 1 or lane_width < 1:
            raise ValueError(
                f"maxsize and lane_width must be >= 1 "
                f"(got {maxsize}, {lane_width})"
            )
        self.maxsize = int(maxsize)
        self.lane_width = int(lane_width)
        self.retry_after_s = int(retry_after_s)
        self._cond = threading.Condition()
        self._queue: List[Job] = []  # submission order
        self._jobs: Dict[str, Job] = {}  # id -> Job (all lifecycles)
        self._by_digest: Dict[str, Job] = {}  # digest -> canonical Job
        self._seq = 0
        self._batches = 0
        self.stats_counters = {
            "submitted": 0, "dedup_hits": 0, "rejected": 0,
            "done": 0, "failed": 0,
        }

    # ---- submission / lookup ----

    def submit(self, spec: JobSpec, digest: str,
               cached_result: Optional[dict] = None) -> Job:
        """Register a job. A digest already known (queued, running, or
        done) dedups to the existing Job — the duplicate never touches
        the queue or the device. `cached_result` short-circuits a fresh
        digest straight to done (the disk-cache hit). Raises QueueFull
        when a genuinely new job meets a full queue."""
        with self._cond:
            existing = self._by_digest.get(digest)
            if existing is not None and existing.status != "failed":
                self.stats_counters["dedup_hits"] += 1
                return existing
            if cached_result is not None:
                job = self._new_job(spec, digest)
                job.status = "done"
                job.cached = True
                job.result = cached_result
                job.finished_unix = time.time()
                self.stats_counters["dedup_hits"] += 1
                self.stats_counters["done"] += 1
                return job
            if len(self._queue) >= self.maxsize:
                self.stats_counters["rejected"] += 1
                raise QueueFull(len(self._queue), self.retry_after_s)
            job = self._new_job(spec, digest)
            self._queue.append(job)
            self.stats_counters["submitted"] += 1
            self._cond.notify_all()
            return job

    def _new_job(self, spec: JobSpec, digest: str) -> Job:
        self._seq += 1
        job = Job(id=f"j{self._seq:05d}-{digest[:10]}", spec=spec,
                  digest=digest)
        self._jobs[job.id] = job
        self._by_digest[digest] = job
        return job

    def get(self, job_id: str) -> Optional[Job]:
        with self._cond:
            return self._jobs.get(job_id)

    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    # ---- batch formation (the single Worker thread's pop) ----

    def next_batch(self, timeout: Optional[float] = None,
                   linger_s: float = 0.0) -> List[Job]:
        """Pop the next batch: the oldest queued job + every queued job
        sharing its family key, FIFO order, up to lane_width. Blocks up
        to `timeout` for work; an empty list means none arrived.
        `linger_s` is the batching window: once work exists, wait up to
        that long for the rest of a concurrent submission wave to land
        (a wave split across two batches costs two scans — and, when the
        stragglers carry bigger tuned traces, a recompile the one-batch
        form would have amortized)."""
        with self._cond:
            if not self._queue:
                self._cond.wait(timeout)
            if not self._queue:
                return []
            if linger_s > 0:
                deadline = time.time() + linger_s
                while len(self._queue) < self.lane_width:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
            fam = self._queue[0].spec.family_key()
            batch = [
                j for j in self._queue if j.spec.family_key() == fam
            ][: self.lane_width]
            taken = set(id(j) for j in batch)
            self._queue = [j for j in self._queue if id(j) not in taken]
            self._batches += 1
            for lane, job in enumerate(batch):
                job.status = "batched"
                job.batch = self._batches
                job.lane = lane
            self._cond.notify_all()
            return batch

    # ---- worker-side lifecycle transitions ----

    def mark_running(self, batch: List[Job]) -> None:
        with self._cond:
            for job in batch:
                job.status = "running"

    def mark_done(self, job: Job, result: dict) -> None:
        with self._cond:
            job.status = "done"
            job.result = result
            job.finished_unix = time.time()
            self.stats_counters["done"] += 1

    def mark_failed(self, job: Job, error: str) -> None:
        with self._cond:
            job.status = "failed"
            job.error = str(error)
            job.finished_unix = time.time()
            self.stats_counters["failed"] += 1
            # a failed digest must not swallow future submissions of the
            # same job (submit() skips failed entries already; dropping
            # the mapping keeps the registry from pinning the failure)
            if self._by_digest.get(job.digest) is job:
                del self._by_digest[job.digest]

    # ---- introspection (the GET /queue document) ----

    def stats(self) -> dict:
        with self._cond:
            return {
                "depth": len(self._queue),
                "capacity": self.maxsize,
                "lane_width": self.lane_width,
                "batches_formed": self._batches,
                **self.stats_counters,
            }

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until every submitted job reached a terminal state
        (test/smoke helper). True on idle, False on timeout."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._cond:
                busy = [
                    j for j in self._jobs.values()
                    if j.status not in ("done", "failed")
                ]
            if not busy:
                return True
            time.sleep(0.02)
        return False
