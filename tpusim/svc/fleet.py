"""The worker fleet: many processes draining one JobQueue (ISSUE 12).

PR 10 made ONE worker crash-safe (spec persistence, SIGTERM drain); this
module promotes that per-worker lifecycle into a fleet protocol. The
coordinator — `tpusim serve --jobs --workers N` — owns the HTTP plane,
the bounded JobQueue, and the artifact dir; worker PROCESSES (spawned
locally, or joined from other hosts with `tpusim worker --join URL`
against a shared filesystem) pull batches over four POST endpoints:

  /workers/register   identity + the hosting handshake: lease duration,
                      lane width, artifact dir, and the hosted traces'
                      CSV paths + content digests (the worker re-loads
                      and digest-verifies them — version/trace skew
                      fails loudly at join time, not as wrong results)
  /workers/claim      the queue pop with OWNERSHIP: a family-sharded
                      FIFO batch stamped with the worker id and a lease
                      deadline; every claim first runs the orphan
                      reaper (JobQueue.steal_expired), so ANY live
                      worker's poll reclaims a dead worker's jobs —
                      no operator action, no dedicated janitor
  /workers/renew      deadline extension while a batch is in flight
                      (the worker ALSO rewrites its signed lease files,
                      svc.leases — the on-disk mirror that survives a
                      coordinator restart)
  /workers/complete   digest-keyed completion: the coordinator loads
                      the signed result the worker wrote into the
                      shared artifact dir; completing an already-done
                      job (the stolen-job race) is a silent dedup

At-least-once + idempotent = exactly-once results: a `kill -9` mid-batch
loses nothing — the specs are on disk (PR 10), the lease expires, a live
worker steals, and the re-run's result is byte-identical because the job
digest pins the whole trajectory and result writes are atomic whole-file
replaces. The shared warm state (the PR 6 persistent compile cache +
content-keyed table cache) means a freshly joined worker's first batch
skips the ~5 s compile.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpusim.svc import jobs as svc_jobs
from tpusim.svc import leases as svc_leases
from tpusim.svc.api import _json_body
from tpusim.svc.batcher import Job, JobQueue


# ---------------------------------------------------------------------------
# Worker registry
# ---------------------------------------------------------------------------


@dataclass
class WorkerInfo:
    """One registered worker's coordinator-side record."""

    id: str
    pid: int = 0
    host: str = ""
    joined_unix: float = field(default_factory=time.time)
    last_seen_unix: float = field(default_factory=time.time)
    claims: int = 0
    batches: int = 0
    jobs_done: int = 0
    jobs_failed: int = 0
    first_dispatch_s: float = 0.0
    last_dispatch_s: float = 0.0
    sweep_executables: int = 0
    steals_benefited: int = 0  # stolen jobs this worker re-ran

    def live(self, now: float, window_s: float) -> bool:
        return (now - self.last_seen_unix) <= window_s


class WorkerRegistry:
    """The fleet roster. MonitorServer is a ThreadingHTTPServer, so
    register/claim/renew/complete handlers run CONCURRENTLY — the
    roster map and the auto-id counter are lock-guarded; the per-worker
    stat fields are scalar writes only ever made by that worker's own
    requests."""

    def __init__(self, lease_s: float):
        import threading

        self.lease_s = float(lease_s)
        self.workers: Dict[str, WorkerInfo] = {}
        self._auto = 0
        self._lock = threading.Lock()

    @property
    def live_window_s(self) -> float:
        # three missed renewals = presumed dead for the LIVENESS view
        # (lease expiry is judged per job, not per worker)
        return max(3.0 * self.lease_s, 3.0)

    def register(self, worker_id: str, pid: int, host: str) -> WorkerInfo:
        with self._lock:
            if not worker_id:
                self._auto += 1
                worker_id = f"w{self._auto:03d}-{pid or 0}"
            info = self.workers.get(worker_id)
            if info is None:
                info = WorkerInfo(id=worker_id, pid=int(pid or 0),
                                  host=str(host or ""))
                self.workers[worker_id] = info
            else:  # re-register after a coordinator restart or reconnect
                info.pid = int(pid or info.pid)
                info.host = str(host or info.host)
                info.last_seen_unix = time.time()
            return info

    def touch(self, worker_id: str) -> Optional[WorkerInfo]:
        with self._lock:
            info = self.workers.get(worker_id)
        if info is not None:
            info.last_seen_unix = time.time()
        return info

    def live_count(self, now: Optional[float] = None) -> int:
        if now is None:
            now = time.time()
        with self._lock:
            snapshot = list(self.workers.values())
        return sum(
            1 for w in snapshot if w.live(now, self.live_window_s)
        )

    def describe(self, queue: Optional[JobQueue] = None) -> dict:
        now = time.time()
        rows = {}
        with self._lock:
            snapshot = list(self.workers.values())
        for w in snapshot:
            rows[w.id] = {
                "pid": w.pid,
                "host": w.host,
                "live": w.live(now, self.live_window_s),
                "last_seen_s": round(now - w.last_seen_unix, 2),
                "claims": w.claims,
                "batches": w.batches,
                "jobs_done": w.jobs_done,
                "jobs_failed": w.jobs_failed,
                "steals_benefited": w.steals_benefited,
                "sweep_executables": w.sweep_executables,
                "first_dispatch_s": round(w.first_dispatch_s, 3),
                "last_dispatch_s": round(w.last_dispatch_s, 3),
                "leases_held": (
                    len(queue.jobs_of_worker(w.id)) if queue else 0
                ),
            }
        return rows


# ---------------------------------------------------------------------------
# Coordinator-side HTTP app
# ---------------------------------------------------------------------------


class FleetService:
    """The /workers/* extension app (MonitorServer.add_app) the job
    coordinator mounts beside JobService. Holds the registry and the
    steal/adopt logic; the JobQueue it drives is JobService's."""

    def __init__(self, service, lease_s: float = 0.0, out=None):
        self.service = service  # svc.api.JobService
        self.queue: JobQueue = service.queue
        if lease_s > 0:
            self.queue.lease_s = float(lease_s)
        self.registry = WorkerRegistry(self.queue.lease_s)
        self.out = out
        self.total_steals_cleaned = 0

    # ---- request routing ----

    def handle(self, method: str, path: str, body: bytes):
        if not path.startswith("/workers"):
            return None
        if path == "/workers" and method == "GET":
            return _json_body(
                200, {"workers": self.registry.describe(self.queue),
                      "live": self.registry.live_count()}
            )
        if method != "POST":
            return _json_body(405, {"error": "method not allowed"})
        try:
            doc = json.loads(body.decode() or "null")
        except (json.JSONDecodeError, UnicodeDecodeError) as err:
            return _json_body(400, {"error": f"bad JSON body: {err}"})
        if not isinstance(doc, dict):
            return _json_body(400, {"error": "want a JSON object"})
        if path == "/workers/register":
            return self._register(doc)
        if path == "/workers/claim":
            return self._claim(doc)
        if path == "/workers/renew":
            return self._renew(doc)
        if path == "/workers/complete":
            return self._complete(doc)
        return _json_body(404, {"error": f"unknown fleet path {path}"})

    def _known(self, doc):
        wid = str(doc.get("worker") or "")
        info = self.registry.touch(wid)
        if info is None:
            # a coordinator restart wiped the roster: tell the worker to
            # re-register (409 — the run_worker loop handles it)
            return None, _json_body(
                409, {"error": f"unknown worker {wid!r}", "register": True}
            )
        return info, None

    def _register(self, doc):
        info = self.registry.register(
            str(doc.get("worker") or ""), doc.get("pid") or 0,
            str(doc.get("host") or ""),
        )
        if self.out is not None:
            print(f"[fleet] worker {info.id} joined (pid {info.pid})",
                  file=self.out)
        traces = {
            name: {
                "nodes_csv": t.nodes_csv, "pods_csv": t.pods_csv,
                "max_pods": t.max_pods, "digest": t.digest,
            }
            for name, t in self.service.traces.items()
        }
        return _json_body(200, {
            "worker": info.id,
            "lease_s": self.queue.lease_s,
            "lane_width": self.queue.lane_width,
            "artifact_dir": os.path.abspath(self.service.artifact_dir),
            "bucket": getattr(self.service, "bucket", 512),
            "traces": traces,
        })

    def release_dead(self, pid: int) -> int:
        """Instant reclaim for a worker KNOWN dead (the serve loop
        reaped its child process): release everything it held — no
        need to wait out the lease — and clean its lease files.
        Returns the number of jobs released."""
        with self.registry._lock:
            wid = next(
                (w.id for w in self.registry.workers.values()
                 if w.pid == int(pid)), None,
            )
        if wid is None:
            return 0
        held = self.queue.release_worker(wid)
        for job in held:
            svc_leases.delete_lease(self.service.artifact_dir, job.digest)
        if held and self.out is not None:
            print(
                f"[fleet] released {len(held)} job(s) of dead worker "
                f"{wid} (pid {pid}) for immediate re-claim",
                file=self.out,
            )
        return len(held)

    def steal_sweep(self) -> List[Job]:
        """Run the orphan reaper and clean the dead owners' lease files
        (the coordinator's half of stealing; the re-claiming worker's
        fresh lease write is the other half)."""
        stolen = self.queue.steal_expired()
        for job in stolen:
            svc_leases.delete_lease(self.service.artifact_dir, job.digest)
            if self.out is not None:
                print(
                    f"[fleet] lease expired on {job.id} "
                    f"({job.digest[:12]}…) — requeued for stealing",
                    file=self.out,
                )
        self.total_steals_cleaned += len(stolen)
        return stolen

    def _claim(self, doc):
        info, err = self._known(doc)
        if err is not None:
            return err
        self.steal_sweep()
        info.claims += 1
        batch = self.queue.claim_batch(info.id, timeout=0.0,
                                       linger_s=0.05)
        # stolen-but-already-finished shortcut: a thief's claim of a job
        # whose (presumed dead, actually slow) owner DID write the
        # signed result answers from disk — never re-runs the device
        ready: List[Job] = []
        for job in batch:
            cached = svc_jobs.find_result(
                self.service.artifact_dir, job.digest
            )
            if cached is not None:
                self.queue.mark_done(job, cached)
                svc_jobs.delete_job_spec(
                    self.service.artifact_dir, job.digest
                )
                continue
            if job.stolen:
                info.steals_benefited += 1
            ready.append(job)
        deadline = time.time() + self.queue.lease_s
        return _json_body(200, {
            "jobs": [
                {
                    "id": j.id, "digest": j.digest,
                    "spec": svc_jobs.spec_to_payload(j.spec),
                    "stolen": j.stolen,
                }
                for j in ready
            ],
            "deadline_unix": deadline,
            "lease_s": self.queue.lease_s,
        })

    def _renew(self, doc):
        info, err = self._known(doc)
        if err is not None:
            return err
        digests = doc.get("digests") or []
        renewed, lost = self.queue.renew(info.id, digests)
        return _json_body(200, {
            "renewed": renewed, "lost": lost,
            "deadline_unix": time.time() + self.queue.lease_s,
        })

    def _complete(self, doc):
        info, err = self._known(doc)
        if err is not None:
            return err
        done = doc.get("done") or []
        failed = doc.get("failed") or {}
        acked = dup = 0
        for digest in done:
            job = self.queue.get_by_digest(digest)
            result = svc_jobs.find_result(
                self.service.artifact_dir, digest
            )
            if job is None:
                dup += 1  # finished after a restart reset the registry
                continue
            if result is None:
                if job.worker != info.id:
                    dup += 1  # a non-owner's resultless claim is noise
                    continue
                self.queue.mark_failed(
                    job, "completion reported but no valid signed "
                    "result on disk"
                )
                info.jobs_failed += 1
                continue
            before = self.queue.stats_counters["dup_completions"]
            self.queue.mark_done(job, result)
            if self.queue.stats_counters["dup_completions"] > before:
                dup += 1
            else:
                acked += 1
                info.jobs_done += 1
            svc_jobs.delete_job_spec(self.service.artifact_dir, digest)
            self.service.publish_job(job)
        for digest, msg in failed.items():
            job = self.queue.get_by_digest(digest)
            if job is None:
                continue
            # only the CURRENT owner may fail a job: a stalled worker
            # whose batch was stolen reports failures for jobs another
            # worker is validly running (or that were requeued) — those
            # reports are late noise, not verdicts. The done path needs
            # no such guard (results are idempotent; failures are not).
            if job.worker != info.id:
                dup += 1
                continue
            self.queue.mark_failed(job, str(msg))
            info.jobs_failed += 1
            svc_jobs.delete_job_spec(
                self.service.artifact_dir, digest
            )
            self.service.publish_job(job)
        info.batches += 1
        if doc.get("dispatch_s"):
            info.last_dispatch_s = float(doc["dispatch_s"])
            if not info.first_dispatch_s:
                info.first_dispatch_s = float(doc["dispatch_s"])
        if doc.get("sweep_executables") is not None:
            info.sweep_executables = int(doc["sweep_executables"])
        return _json_body(200, {"acked": acked, "dup": dup})

    # ---- restart recovery (the lease-file half) ----

    def adopt_leases(self, out=None) -> int:
        """Coordinator-restart recovery (runs after recover_pending_jobs
        requeued the pending specs): a job whose lease FILE is still
        LIVE — within deadline + skew — belongs to a worker that may
        well still be computing it, so re-attach the claim instead of
        letting the queue hand it out twice; expired files are cleaned
        (their jobs stay queued — already stolen, in effect). Returns
        the number of adopted jobs."""
        adopted = 0
        for digest, lease in svc_leases.scan_leases(
            self.service.artifact_dir
        ):
            job = self.queue.get_by_digest(digest)
            if svc_leases.lease_expired(lease):
                svc_leases.delete_lease(self.service.artifact_dir, digest)
                self.queue.stats_counters["lease_expired"] += 1
                continue
            if job is None or job.status != "queued":
                continue
            wid = str(lease.get("worker") or "")
            info = self.registry.register(
                wid, lease.get("pid") or 0, ""
            )
            claimed = self.queue.claim_specific(
                wid, [digest], float(lease["deadline_unix"])
            )
            adopted += len(claimed)
            if claimed and out is not None:
                print(
                    f"[fleet] adopted live lease of {wid} on "
                    f"{digest[:12]}… (deadline in "
                    f"{lease['deadline_unix'] - time.time():.1f}s)",
                    file=out,
                )
            info.last_seen_unix = time.time()
        return adopted

    # ---- the /queue aggregation fields ----

    def queue_fields(self) -> dict:
        rows = self.registry.describe(self.queue)
        return {
            "workers": rows,
            "workers_live": self.registry.live_count(),
            "batches_run": sum(r["batches"] for r in rows.values()),
            "sweep_executables": sum(
                r["sweep_executables"] for r in rows.values()
            ),
        }

    def health(self):
        """MonitorServer.health_hook: the fleet coordinator is healthy
        while ANY worker is live; it degrades to 503 only when none
        are (the ISSUE 12 /healthz contract)."""
        live = self.registry.live_count()
        return live > 0, {
            "workers_live": live,
            "workers_known": len(self.registry.workers),
        }


# ---------------------------------------------------------------------------
# The worker process (`tpusim worker --join URL`)
# ---------------------------------------------------------------------------


def _post(url: str, path: str, doc: dict, timeout: float = 30.0):
    from tpusim.svc.client import _request

    return _request(
        url.rstrip("/") + path,
        json.dumps(doc).encode(), timeout=timeout,
    )


def run_worker(url: str, worker_id: str = "", poll_s: float = 0.2,
               max_batches: int = 0, table_cache_dir: str = "",
               compile_cache_dir: str = "", out=None,
               stop_event=None) -> int:
    """The fleet worker's main loop: register, then claim/run/complete
    until stopped (or `max_batches` served — the test/smoke bound).
    Returns the number of batches served. SIGTERM handling is the
    caller's (the CLI installs a drain flag via `stop_event`); a
    `kill -9` needs no handling — that is what the leases are for."""
    import http.client
    import urllib.error

    from tpusim.io.kube_client import _retry_delay_s
    from tpusim.svc.client import ServiceError
    from tpusim.svc.worker import Worker, load_trace

    host = os.uname().nodename if hasattr(os, "uname") else ""
    reg = None
    for attempt in range(1, 9):
        try:
            code, _, reg = _post(url, "/workers/register", {
                "worker": worker_id, "pid": os.getpid(), "host": host,
            })
        except (ConnectionResetError, ConnectionRefusedError,
                http.client.RemoteDisconnected,
                urllib.error.URLError):
            # the coordinator may still be binding its socket
            if attempt >= 8:
                raise ServiceError(
                    f"could not reach the coordinator at {url}"
                )
            time.sleep(_retry_delay_s(attempt))
            continue
        if code != 200:
            raise ServiceError(
                f"POST /workers/register -> HTTP {code}: {reg}"
            )
        break
    wid = reg["worker"]
    lease_s = float(reg["lease_s"])
    artifact_dir = reg["artifact_dir"]

    traces = {}
    for name, meta in (reg.get("traces") or {}).items():
        t = load_trace(
            name, meta["nodes_csv"], meta["pods_csv"],
            max_pods=int(meta.get("max_pods") or 0),
        )
        if t.digest != meta["digest"]:
            # trace skew: this worker would compute results under a
            # DIFFERENT digest vocabulary — refuse to serve
            raise ServiceError(
                f"hosted trace {name!r} digest mismatch: coordinator "
                f"{meta['digest'][:12]}… vs local {t.digest[:12]}… "
                "(differing CSVs or code version)"
            )
        traces[name] = t

    queue = JobQueue(
        maxsize=max(4 * int(reg["lane_width"]), 8),
        lane_width=int(reg["lane_width"]), lease_s=lease_s,
    )
    worker = Worker(
        queue, traces, artifact_dir, bucket=int(reg.get("bucket") or 512),
        table_cache_dir=table_cache_dir,
        compile_cache_dir=compile_cache_dir,
        worker_id=wid, lease_files=True,
    )

    def renew_remote(digests):
        code, _, doc = _post(url, "/workers/renew",
                             {"worker": wid, "digests": list(digests)})
        if code != 200:
            return []
        return doc.get("lost") or []

    worker.renew_cb = renew_remote

    from tpusim.sim.driver import enable_compile_cache

    enable_compile_cache(compile_cache_dir)
    if out is not None:
        print(
            f"[worker {wid}] joined {url} (pid {os.getpid()}, "
            f"{len(traces)} trace(s), lease {lease_s:.1f}s)", file=out,
        )

    served = 0
    while stop_event is None or not stop_event.is_set():
        try:
            code, _, doc = _post(url, "/workers/claim", {"worker": wid})
        except (ConnectionResetError, ConnectionRefusedError,
                http.client.RemoteDisconnected,
                urllib.error.URLError):
            # coordinator restarting: its recovery requeues everything;
            # keep polling on the shared backoff schedule
            time.sleep(max(poll_s, 0.5))
            continue
        if code == 409:
            # roster wiped by a coordinator restart — re-register
            _post(url, "/workers/register", {
                "worker": wid, "pid": os.getpid(), "host": host,
            })
            continue
        if code != 200:
            time.sleep(max(poll_s, 0.5))
            continue
        jobs_docs = doc.get("jobs") or []
        if not jobs_docs:
            time.sleep(poll_s)
            continue

        batch, skew_failed = [], {}
        for lane, jd in enumerate(jobs_docs):
            try:
                spec = svc_jobs.validate_job(jd["spec"])
                digest = svc_jobs.job_digest(
                    spec, traces[spec.trace].digest
                )
                if digest != jd["digest"]:
                    raise ValueError(
                        "job digest mismatch (coordinator/worker "
                        "version skew)"
                    )
            except (KeyError, ValueError) as err:
                skew_failed[jd.get("digest", "?")] = str(err)
                continue
            batch.append(Job(
                id=jd["id"], spec=spec, digest=jd["digest"],
                status="batched", batch=served + 1, lane=lane,
                worker=wid,
            ))
        if batch:
            worker.run_batch(batch)
            served += 1
        done = [j.digest for j in batch if j.status == "done"]
        failed = {
            j.digest: j.error for j in batch if j.status == "failed"
        }
        failed.update(skew_failed)
        try:
            _post(url, "/workers/complete", {
                "worker": wid, "done": done, "failed": failed,
                "dispatch_s": worker.last_dispatch_s,
                "sweep_executables": worker.sweep_executables(),
            })
        except (ConnectionResetError, ConnectionRefusedError,
                http.client.RemoteDisconnected,
                urllib.error.URLError):
            # results + spec deletions are already on disk — a restarted
            # coordinator reconciles from there (its claim shortcut)
            pass
        if out is not None and batch:
            print(
                f"[worker {wid}] batch {served}: {len(done)} done, "
                f"{len(failed)} failed "
                f"({worker.last_dispatch_s:.2f}s dispatch)", file=out,
            )
        if max_batches and served >= max_batches:
            break
    worker.stop()
    return served


# ---------------------------------------------------------------------------
# Local fleet spawning (`tpusim serve --jobs --workers N`)
# ---------------------------------------------------------------------------


def spawn_local_workers(url: str, n: int, table_cache_dir: str = "",
                        compile_cache_dir: str = "",
                        out=None) -> List[subprocess.Popen]:
    """Spawn N `tpusim worker --join` processes against this
    coordinator. They inherit the environment (JAX_PLATFORMS etc.) and
    share the persistent compile cache + table cache dirs — the warm
    state that makes a joiner's first batch skip the compile."""
    procs = []
    for _ in range(int(n)):
        # no --id: the coordinator assigns pid-scoped ids, so a joiner
        # spawned later can never collide with (and inherit the stats
        # of) an earlier worker's roster entry
        cmd = [sys.executable, "-m", "tpusim", "worker", "--join", url]
        if table_cache_dir:
            cmd += ["--table-cache-dir", table_cache_dir]
        if compile_cache_dir:
            cmd += ["--compile-cache-dir", compile_cache_dir]
        procs.append(subprocess.Popen(cmd))
        if out is not None:
            print(f"[fleet] spawned worker process pid {procs[-1].pid}",
                  file=out)
    return procs


def stop_workers(procs, timeout: float = 10.0, out=None) -> None:
    """Drain the spawned fleet: SIGTERM each child (graceful — the
    CLI's stop flag finishes the in-flight batch), escalate to SIGKILL
    past the timeout (leases make even that safe)."""
    for p in procs:
        if p.poll() is None:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
    deadline = time.time() + timeout
    for p in procs:
        remaining = max(deadline - time.time(), 0.1)
        try:
            p.wait(remaining)
        except subprocess.TimeoutExpired:
            if out is not None:
                print(f"[fleet] worker pid {p.pid} ignored SIGTERM — "
                      "killing (leases cover it)", file=out)
            p.kill()
