"""The worker fleet: many processes draining one JobQueue (ISSUE 12),
grown wide-area in ISSUE 13 — workers need NO shared filesystem.

PR 10 made ONE worker crash-safe (spec persistence, SIGTERM drain); this
module promotes that per-worker lifecycle into a fleet protocol. The
coordinator — `tpusim serve --jobs --workers N` — owns the HTTP plane,
the bounded JobQueue, and the artifact dir; worker PROCESSES (spawned
locally, or joined from ANY host with `tpusim worker --join URL`) pull
batches over the /workers/* POST endpoints plus, for no-shared-fs
("remote" mode) workers, the transfer plane (ISSUE 13):

  GET  /traces/<name>[/nodes.csv|/pods.csv]
                      digest-named trace download: the handshake
                      carries per-file sha256 + the trace content
                      digest; the worker caches by digest, resumes
                      partial transfers (Range), re-downloads on
                      mismatch, and refuses to serve on residual skew
  POST /results/<digest>
                      signed-result upload: the coordinator verifies
                      the payload digest BEFORE the atomic rename — a
                      torn or forged upload is a 400 + [Degrade]
                      warning, never a half-written result file
  POST /leases        the remote workers' lease mirror: the
                      coordinator writes/deletes its own signed lease
                      files (op=stake|release), keeping the on-disk
                      recovery plane identical for both modes

Every worker→coordinator request rides the shared kube_client
capped-exponential-backoff-with-jitter schedule honoring Retry-After
(`_with_backoff`), so a coordinator restart mid-claim is a stall, not a
dead worker. The original shared-filesystem endpoints:

  /workers/register   identity + the hosting handshake: lease duration,
                      lane width, artifact dir, and the hosted traces'
                      CSV paths + content digests (the worker re-loads
                      and digest-verifies them — version/trace skew
                      fails loudly at join time, not as wrong results)
  /workers/claim      the queue pop with OWNERSHIP: a family-sharded
                      FIFO batch stamped with the worker id and a lease
                      deadline; every claim first runs the orphan
                      reaper (JobQueue.steal_expired), so ANY live
                      worker's poll reclaims a dead worker's jobs —
                      no operator action, no dedicated janitor
  /workers/renew      deadline extension while a batch is in flight
                      (the worker ALSO rewrites its signed lease files,
                      svc.leases — the on-disk mirror that survives a
                      coordinator restart)
  /workers/complete   digest-keyed completion: the coordinator loads
                      the signed result the worker wrote into the
                      shared artifact dir; completing an already-done
                      job (the stolen-job race) is a silent dedup

At-least-once + idempotent = exactly-once results: a `kill -9` mid-batch
loses nothing — the specs are on disk (PR 10), the lease expires, a live
worker steals, and the re-run's result is byte-identical because the job
digest pins the whole trajectory and result writes are atomic whole-file
replaces. The shared warm state (the PR 6 persistent compile cache +
content-keyed table cache) means a freshly joined worker's first batch
skips the ~5 s compile.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpusim.obs import trace as obs_trace
from tpusim.svc import jobs as svc_jobs
from tpusim.svc import leases as svc_leases
from tpusim.svc.api import _json_body
from tpusim.svc.auth import bearer_headers
from tpusim.svc.auth import check as auth_check
from tpusim.svc.batcher import Job, JobQueue


# ---------------------------------------------------------------------------
# Worker registry
# ---------------------------------------------------------------------------


@dataclass
class WorkerInfo:
    """One registered worker's coordinator-side record."""

    id: str
    pid: int = 0
    host: str = ""
    joined_unix: float = field(default_factory=time.time)
    last_seen_unix: float = field(default_factory=time.time)
    claims: int = 0
    batches: int = 0
    jobs_done: int = 0
    jobs_failed: int = 0
    first_dispatch_s: float = 0.0
    last_dispatch_s: float = 0.0
    sweep_executables: int = 0
    steals_benefited: int = 0  # stolen jobs this worker re-ran
    # the topology view (ISSUE 13): how this worker reaches the
    # artifact plane — "shared-fs" (reads trace CSVs by path, writes
    # results directly) or "remote" (digest-verified download/upload
    # over HTTP, no shared filesystem) — plus its reported transfer
    # counters (downloads/uploads/bytes/resumes/sha retries)
    mode: str = "shared-fs"
    transfers: dict = field(default_factory=dict)
    # the MEASURED capability profile (ISSUE 19), beside the caps the
    # worker merely declared: EWMA of reported batch dispatch walls,
    # the compile-cache probable-hit count (obs.spans.note_compile_cache
    # heuristic, counted worker-side), and the worker's own pushed
    # exposition-format snapshot (merged worker-labeled into /metrics)
    ewma_dispatch_s: float = 0.0
    probable_hits: int = 0
    metrics_text: str = ""
    # capability tags (ISSUE 17): what this worker declared at
    # registration — backend name, device count, approximate memory
    # bytes, fault-lane support, and the biggest trace it will take
    # (max_nodes, 0 = unlimited). claim_batch routes families by these.
    caps: dict = field(default_factory=dict)

    def live(self, now: float, window_s: float) -> bool:
        return (now - self.last_seen_unix) <= window_s

    def profile(self, now: float) -> dict:
        """The measured profile row for /workers: what this worker
        actually does — smoothed dispatch wall, transfer throughput
        since join, compile-cache hit rate — as opposed to what its
        caps tags declared at registration."""
        tr = self.transfers or {}
        moved = (int(tr.get("download_bytes") or 0)
                 + int(tr.get("upload_bytes") or 0))
        return {
            "ewma_dispatch_s": round(self.ewma_dispatch_s, 3),
            "transfer_bps": round(
                moved / max(now - self.joined_unix, 1e-6), 1
            ),
            "compile_hit_rate": (
                round(self.probable_hits / self.batches, 3)
                if self.batches else 0.0
            ),
        }


class WorkerRegistry:
    """The fleet roster. MonitorServer is a ThreadingHTTPServer, so
    register/claim/renew/complete handlers run CONCURRENTLY — the
    roster map and the auto-id counter are lock-guarded; the per-worker
    stat fields are scalar writes only ever made by that worker's own
    requests."""

    def __init__(self, lease_s: float):
        import threading

        self.lease_s = float(lease_s)
        self.workers: Dict[str, WorkerInfo] = {}
        self._auto = 0
        self._lock = threading.Lock()

    @property
    def live_window_s(self) -> float:
        # three missed renewals = presumed dead for the LIVENESS view
        # (lease expiry is judged per job, not per worker)
        return max(3.0 * self.lease_s, 3.0)

    def register(self, worker_id: str, pid: int, host: str,
                 mode: str = "", caps: Optional[dict] = None) -> WorkerInfo:
        with self._lock:
            if not worker_id:
                self._auto += 1
                worker_id = f"w{self._auto:03d}-{pid or 0}"
            info = self.workers.get(worker_id)
            if info is None:
                info = WorkerInfo(id=worker_id, pid=int(pid or 0),
                                  host=str(host or ""))
                self.workers[worker_id] = info
            else:  # re-register after a coordinator restart or reconnect
                info.pid = int(pid or info.pid)
                info.host = str(host or info.host)
                info.last_seen_unix = time.time()
            if mode:
                info.mode = str(mode)
            if isinstance(caps, dict):
                info.caps = dict(caps)
            return info

    def live_caps(self, now: Optional[float] = None) -> List[dict]:
        """The capability tags of every LIVE worker — the starvation
        judge's input (a family no live worker can serve is starved;
        an empty fleet is a different problem)."""
        if now is None:
            now = time.time()
        with self._lock:
            snapshot = list(self.workers.values())
        return [
            w.caps or {} for w in snapshot
            if w.live(now, self.live_window_s)
        ]

    def touch(self, worker_id: str) -> Optional[WorkerInfo]:
        with self._lock:
            info = self.workers.get(worker_id)
        if info is not None:
            info.last_seen_unix = time.time()
        return info

    def live_count(self, now: Optional[float] = None) -> int:
        if now is None:
            now = time.time()
        with self._lock:
            snapshot = list(self.workers.values())
        return sum(
            1 for w in snapshot if w.live(now, self.live_window_s)
        )

    def describe(self, queue: Optional[JobQueue] = None) -> dict:
        now = time.time()
        rows = {}
        with self._lock:
            snapshot = list(self.workers.values())
        for w in snapshot:
            rows[w.id] = {
                "pid": w.pid,
                "host": w.host,
                "mode": w.mode,
                "caps": dict(w.caps),
                "transfers": dict(w.transfers),
                "live": w.live(now, self.live_window_s),
                "last_seen_s": round(now - w.last_seen_unix, 2),
                "claims": w.claims,
                "batches": w.batches,
                "jobs_done": w.jobs_done,
                "jobs_failed": w.jobs_failed,
                "steals_benefited": w.steals_benefited,
                "sweep_executables": w.sweep_executables,
                "first_dispatch_s": round(w.first_dispatch_s, 3),
                "last_dispatch_s": round(w.last_dispatch_s, 3),
                "profile": w.profile(now),
                "leases_held": (
                    len(queue.jobs_of_worker(w.id)) if queue else 0
                ),
            }
        return rows


# ---------------------------------------------------------------------------
# Coordinator-side HTTP app
# ---------------------------------------------------------------------------


class FleetService:
    """The /workers/* extension app (MonitorServer.add_app) the job
    coordinator mounts beside JobService. Holds the registry and the
    steal/adopt logic; the JobQueue it drives is JobService's."""

    def __init__(self, service, lease_s: float = 0.0, out=None):
        self.service = service  # svc.api.JobService
        self.queue: JobQueue = service.queue
        if lease_s > 0:
            self.queue.lease_s = float(lease_s)
        self.registry = WorkerRegistry(self.queue.lease_s)
        self.out = out
        self.total_steals_cleaned = 0
        # the supervisor owning `--workers N` children (svc.supervisor,
        # ISSUE 13), or None when workers join only from outside; /queue
        # and /healthz surface its respawn/breaker state when set
        self.supervisor = None
        # the HA plane (ISSUE 17): a CoordinatorState when leadership
        # leases are armed (the serve CLI / the fencing tests); None
        # keeps every single-coordinator flow unfenced and unchanged
        self.coord = None
        # families already warned about in a [Degrade] line — once per
        # family per process, not once per /queue poll
        self._starve_warned = set()
        # coordinator-side transfer-plane counters (ISSUE 13)
        self.transfers = {
            "trace_requests": 0, "trace_bytes": 0,
            "uploads_ok": 0, "uploads_rejected": 0, "lease_posts": 0,
        }

    # ---- the HA + auth gates (ISSUE 17) ----

    @property
    def epoch(self) -> int:
        return self.coord.epoch if self.coord is not None else 0

    @property
    def role(self) -> str:
        return self.coord.role if self.coord is not None else "leader"

    @property
    def token(self) -> str:
        return getattr(self.service, "token", "") or ""

    # ---- the flight recorder (ISSUE 19): the audit log + span
    # recorder live on JobService (one pair per coordinator process);
    # every control-plane decision below witnesses itself through them

    @property
    def audit(self):
        return getattr(self.service, "audit", None)

    @property
    def spans(self):
        return getattr(self.service, "spans", None)

    def _audit(self, kind: str, job: str = "", worker: str = "",
               **fields):
        log = self.audit
        if log is not None:
            log.emit(kind, job=job, worker=worker, **fields)

    def _unauthorized(self, path: str = ""):
        # one uniform body for missing/malformed/forged tokens, issued
        # BEFORE any digest parsing — a 401 never reveals whether a
        # digest (or worker, or trace) exists. The audit record carries
        # the path only: token material never enters the chain.
        self._audit("auth_401", path=path)
        return _json_body(
            401, {"error": "missing or invalid bearer token"}
        )

    def standby_503(self):
        return _json_body(
            503,
            {"error": "standby coordinator — not the leader",
             "role": self.role, "epoch": self.epoch},
            headers={"Retry-After": "2"},
        )

    def _fence(self, doc: dict):
        """Epoch fencing (ISSUE 17): judge the op's coordinator-epoch
        stamp against ours. Older → 409 `{"stale_epoch": true,
        "register": true}` (the worker re-registers and adopts the new
        epoch). NEWER → the sender holds proof a newer leader exists,
        so WE are the deposed one: demote on the spot and answer 409
        `{"deposed": true}`. Unstamped ops (pre-HA workers, HA off)
        pass untouched."""
        if self.coord is None:
            return None
        op_epoch = doc.get("epoch")
        if op_epoch is None:
            return None
        try:
            op_epoch = int(op_epoch)
        except (TypeError, ValueError):
            return _json_body(400, {"error": "epoch must be an integer"})
        mine = self.epoch
        if op_epoch < mine:
            self._audit("fence_409", worker=str(doc.get("worker") or ""),
                        detail="stale_epoch", op_epoch=op_epoch,
                        epoch=mine)
            return _json_body(409, {
                "error": f"stale coordinator epoch {op_epoch} "
                         f"(current {mine})",
                "stale_epoch": True, "epoch": mine, "register": True,
            })
        if op_epoch > mine:
            self.coord.note_epoch(op_epoch)
            self._audit("fence_409", worker=str(doc.get("worker") or ""),
                        detail="deposed", op_epoch=op_epoch, epoch=mine)
            return _json_body(409, {
                "error": f"op carries epoch {op_epoch} > ours ({mine}) "
                         "— this coordinator was deposed and has "
                         "demoted itself",
                "deposed": True, "epoch": op_epoch,
            })
        return None

    # ---- request routing ----

    def handle(self, method: str, path: str, body: bytes, headers=None):
        mine = (path in ("/traces", "/leases", "/workers")
                or path.startswith(("/traces/", "/results/", "/workers/")))
        if mine and method == "POST":
            # admission first (auth runs before ANY path/digest
            # parsing), then leadership: a standby must not mutate
            # shared state even for a validly-authed worker
            if not auth_check(headers, self.token):
                return self._unauthorized(path)
            if self.role != "leader":
                return self.standby_503()
        # the fleet-aggregated metrics view (ISSUE 19): read-only, so
        # it answers in front of MonitorServer's single-run builtin
        if path == "/metrics" and method == "GET":
            return self._metrics()
        # the transfer plane (ISSUE 13): trace download, result upload,
        # and the remote workers' lease mirror — all digest-guarded
        if path == "/traces" and method == "GET":
            return _json_body(200, {
                "traces": {
                    name: self._trace_meta(t)
                    for name, t in self.service.traces.items()
                }
            })
        if path.startswith("/traces/") and method == "GET":
            return self._get_trace(path, headers)
        if path.startswith("/results/") and method == "POST":
            return self._accept_result(path, body, headers)
        if path == "/leases" and method == "POST":
            return self._leases(body)
        if not path.startswith("/workers"):
            return None
        if path == "/workers" and method == "GET":
            return _json_body(
                200, {"workers": self.registry.describe(self.queue),
                      "live": self.registry.live_count()}
            )
        if method != "POST":
            return _json_body(405, {"error": "method not allowed"})
        try:
            doc = json.loads(body.decode() or "null")
        except (json.JSONDecodeError, UnicodeDecodeError) as err:
            return _json_body(400, {"error": f"bad JSON body: {err}"})
        if not isinstance(doc, dict):
            return _json_body(400, {"error": "want a JSON object"})
        if path == "/workers/register":
            # never fenced: register is HOW a worker adopts the new
            # epoch after a takeover
            return self._register(doc)
        fenced = self._fence(doc)
        if fenced is not None:
            return fenced
        if path == "/workers/claim":
            return self._claim(doc)
        if path == "/workers/renew":
            return self._renew(doc)
        if path == "/workers/complete":
            return self._complete(doc)
        return _json_body(404, {"error": f"unknown fleet path {path}"})

    # ---- the transfer plane (ISSUE 13) ----

    @staticmethod
    def _safe_digest(s: str) -> bool:
        """True when `s` is usable as a file stem inside the artifact
        dir: digests are lowercase sha256 hex, and anything else —
        path separators, dot-dot, empty — must be rejected BEFORE it
        reaches an os.path.join (the /leases and /results endpoints
        take these strings off the wire)."""
        s = str(s)
        return bool(s) and all(c in "0123456789abcdef" for c in s) \
            and len(s) <= 128

    def _trace_meta(self, t) -> dict:
        return {
            "nodes_csv": t.nodes_csv, "pods_csv": t.pods_csv,
            "max_pods": t.max_pods, "digest": t.digest,
            "nodes_sha256": t.nodes_sha256, "pods_sha256": t.pods_sha256,
            "nodes_bytes": t.nodes_bytes, "pods_bytes": t.pods_bytes,
        }

    def _get_trace(self, path: str, headers):
        """GET /traces/<name> (meta JSON) and /traces/<name>/nodes.csv |
        pods.csv (the raw file, Range-resumable) — the download half of
        the no-shared-fs transport: the worker verifies each file
        against the handshake's sha256 and the parsed trace against the
        content digest, so a truncated or skewed transfer can only fail
        loudly, never run the wrong trace."""
        parts = path[len("/traces/"):].split("/")
        trace = self.service.traces.get(parts[0])
        if trace is None:
            return _json_body(
                404, {"error": f"unknown trace {parts[0]!r} (hosted: "
                      f"{', '.join(sorted(self.service.traces))})"}
            )
        if len(parts) == 1:
            return _json_body(200, self._trace_meta(trace))
        which = parts[1] if len(parts) == 2 else ""
        src = {"nodes.csv": trace.nodes_csv,
               "pods.csv": trace.pods_csv}.get(which)
        if not src:
            return _json_body(
                404, {"error": f"unknown trace file {which!r} "
                      "(want nodes.csv or pods.csv)"}
            )
        sha = {"nodes.csv": trace.nodes_sha256,
               "pods.csv": trace.pods_sha256}[which]
        try:
            size = os.path.getsize(src)
            start = 0
            rng = str((headers or {}).get("Range") or "").strip()
            if rng:
                import re as _re

                m = _re.match(r"bytes=(\d+)-$", rng)
                # >= : a Range at exactly EOF (a fully-written .part
                # that died pre-rename) is 416, never an empty 206
                # with an inverted Content-Range
                if m is None or int(m.group(1)) >= size:
                    return (416, "text/plain", b"",
                            {"Content-Range": f"bytes */{size}"})
                start = int(m.group(1))
            # seek + read the suffix only: a resume of the last few
            # bytes must not cost an O(file) read per retry
            with open(src, "rb") as f:
                if start:
                    f.seek(start)
                data = f.read()
        except OSError as err:
            return _json_body(
                500, {"error": f"hosted trace file unreadable: {err}"}
            )
        self.transfers["trace_requests"] += 1
        self.transfers["trace_bytes"] += len(data)
        hdrs = {"X-Content-SHA256": sha, "Accept-Ranges": "bytes"}
        if start > 0:
            hdrs["Content-Range"] = f"bytes {start}-{size - 1}/{size}"
            return (206, "text/csv", data, hdrs)
        return (200, "text/csv", data, hdrs)

    def _accept_result(self, path: str, body: bytes, headers=None):
        """POST /results/<digest> — the upload half: the bytes must
        verify as a signed result for EXACTLY this digest before the
        atomic rename lands them; a torn or forged upload is rejected
        with a [Degrade] warning and the artifact dir keeps no partial
        file (svc.jobs.accept_result_upload)."""
        digest = path[len("/results/"):]
        if not self._safe_digest(digest):
            return _json_body(404, {"error": f"bad result path {path!r}"})
        t_verify = time.time()
        try:
            svc_jobs.accept_result_upload(
                self.service.artifact_dir, digest, body
            )
        except (ValueError, json.JSONDecodeError) as err:
            self.transfers["uploads_rejected"] += 1
            self._audit("degrade", job=digest, reason="rejected-upload",
                        detail=str(err))
            print(
                f"[Degrade] rejected result upload for {digest[:12]}… "
                f"({err}); nothing written — the worker retries or the "
                "lease expires",
                file=self.out if self.out is not None else sys.stderr,
            )
            return _json_body(400, {"error": f"rejected upload: {err}"})
        self.transfers["uploads_ok"] += 1
        if self.spans is not None:
            tid = (obs_trace.header_trace(headers)
                   or self.service.trace_of(digest))
            self.spans.emit(
                obs_trace.SPAN_VERIFY, t_verify, time.time(),
                job=digest, trace=tid, bytes=len(body),
            )
        return _json_body(200, {"stored": digest, "bytes": len(body)})

    def _leases(self, body: bytes):
        """POST /leases — the remote workers' lease mirror: the
        COORDINATOR writes/deletes the signed lease files on their
        behalf (op=stake|release), so the on-disk recovery plane
        (adoption, reaping, skew-judged expiry) is identical for
        shared-fs and remote workers. Lenient about roster membership:
        the lease file itself is the proof that matters."""
        try:
            doc = json.loads(body.decode() or "null")
        except (json.JSONDecodeError, UnicodeDecodeError) as err:
            return _json_body(400, {"error": f"bad JSON body: {err}"})
        if not isinstance(doc, dict):
            return _json_body(400, {"error": "want a JSON object"})
        fenced = self._fence(doc)
        if fenced is not None:
            return fenced
        members = [str(m) for m in doc.get("members") or []]
        if not members:
            return _json_body(400, {"error": "want a members list"})
        op = str(doc.get("op") or "stake")
        if op not in ("stake", "release"):
            return _json_body(
                400, {"error": f"op must be stake|release, got {op!r}"}
            )
        bad = [m for m in members if not self._safe_digest(m)]
        if bad:
            # members become file stems under the artifact dir — a
            # traversal payload ("../../x") must die here, loudly,
            # before any os.path.join sees it
            return _json_body(
                400, {"error": f"member(s) are not job digests: "
                      f"{[b[:40] for b in bad]}"}
            )
        wid = str(doc.get("worker") or "")
        self.transfers["lease_posts"] += 1
        self.registry.touch(wid)
        if op == "release":
            for d in members:
                svc_leases.delete_lease(self.service.artifact_dir, d)
            return _json_body(200, {"released": len(members)})
        deadline = time.time() + self.queue.lease_s
        for d in members:
            svc_leases.write_lease(
                self.service.artifact_dir, d, wid,
                int(doc.get("pid") or 0), deadline, members,
            )
        return _json_body(
            200, {"staked": len(members), "deadline_unix": deadline}
        )

    def _known(self, doc):
        wid = str(doc.get("worker") or "")
        info = self.registry.touch(wid)
        if info is None:
            # a coordinator restart wiped the roster: tell the worker to
            # re-register (409 — the run_worker loop handles it)
            return None, _json_body(
                409, {"error": f"unknown worker {wid!r}", "register": True}
            )
        return info, None

    def _register(self, doc):
        info = self.registry.register(
            str(doc.get("worker") or ""), doc.get("pid") or 0,
            str(doc.get("host") or ""), mode=str(doc.get("mode") or ""),
            caps=doc.get("caps"),
        )
        if self.out is not None:
            print(f"[fleet] worker {info.id} joined (pid {info.pid}"
                  f"{', ' + info.mode if doc.get('mode') else ''})",
                  file=self.out)
        traces = {
            name: self._trace_meta(t)
            for name, t in self.service.traces.items()
        }
        return _json_body(200, {
            "worker": info.id,
            "lease_s": self.queue.lease_s,
            "lane_width": self.queue.lane_width,
            "artifact_dir": os.path.abspath(self.service.artifact_dir),
            "bucket": getattr(self.service, "bucket", 512),
            "traces": traces,
            # the handshake is how a worker learns the coordinator
            # epoch it must stamp every subsequent op with (ISSUE 17)
            "epoch": self.epoch,
        })

    def release_dead(self, pid: int) -> int:
        """Instant reclaim for a worker KNOWN dead (the serve loop
        reaped its child process): release everything it held — no
        need to wait out the lease — and clean its lease files.
        Returns the number of jobs released."""
        with self.registry._lock:
            wid = next(
                (w.id for w in self.registry.workers.values()
                 if w.pid == int(pid)), None,
            )
        if wid is None:
            return 0
        held = self.queue.release_worker(wid)
        for job in held:
            svc_leases.delete_lease(self.service.artifact_dir, job.digest)
            self._audit("requeue", job=job.digest, worker=wid,
                        reason="worker-dead", dead_pid=int(pid))
        if held and self.out is not None:
            print(
                f"[fleet] released {len(held)} job(s) of dead worker "
                f"{wid} (pid {pid}) for immediate re-claim",
                file=self.out,
            )
        return len(held)

    def steal_sweep(self) -> List[Job]:
        """Run the orphan reaper and clean the dead owners' lease files
        (the coordinator's half of stealing; the re-claiming worker's
        fresh lease write is the other half)."""
        stolen = self.queue.steal_expired()
        for job in stolen:
            svc_leases.delete_lease(self.service.artifact_dir, job.digest)
            self._audit("steal", job=job.digest,
                        worker=getattr(job, "last_worker", ""),
                        reason="lease_expired",
                        attempts=getattr(job, "attempts", 0))
            if self.out is not None:
                print(
                    f"[fleet] lease expired on {job.id} "
                    f"({job.digest[:12]}…) — requeued for stealing",
                    file=self.out,
                )
        self.total_steals_cleaned += len(stolen)
        return stolen

    def starved_families(self) -> List[str]:
        """Queued families NO live worker's declared capabilities can
        serve (ISSUE 17) — the `/queue` visibility + one loud
        `[Degrade]` per family. Empty when the fleet is empty: that is
        'no workers', a different (already-visible) problem."""
        caps_list = self.registry.live_caps()
        if not caps_list:
            return []
        starved = self.queue.starved_families(caps_list)
        for fam in starved:
            if fam not in self._starve_warned:
                self._starve_warned.add(fam)
                print(
                    f"[Degrade] queued family {fam} is STARVED: no "
                    "live worker declares the capabilities it needs "
                    "(fault-lane support / max_nodes / memory) — it "
                    "waits until a capable worker joins",
                    file=self.out if self.out is not None else sys.stderr,
                )
        return starved

    def _claim(self, doc):
        info, err = self._known(doc)
        if err is not None:
            return err
        self.steal_sweep()
        info.claims += 1
        batch = self.queue.claim_batch(info.id, timeout=0.0,
                                       linger_s=0.05,
                                       caps=info.caps or None)
        if not batch and self.queue.depth() > 0:
            # this worker found only work it cannot serve — judge the
            # whole fleet so a truly starved family is loud, not a
            # silent forever-queued row
            self.starved_families()
        # stolen-but-already-finished shortcut: a thief's claim of a job
        # whose (presumed dead, actually slow) owner DID write the
        # signed result answers from disk — never re-runs the device
        ready: List[Job] = []
        for job in batch:
            cached = svc_jobs.find_result(
                self.service.artifact_dir, job.digest
            )
            if cached is not None:
                self.queue.mark_done(job, cached)
                svc_jobs.delete_job_spec(
                    self.service.artifact_dir, job.digest
                )
                continue
            if job.stolen:
                info.steals_benefited += 1
            ready.append(job)
        now = time.time()
        deadline = now + self.queue.lease_s
        handed = []
        for j in ready:
            # the trace id rides the claim answer (ISSUE 19): the
            # worker tags its dispatch/upload spans with the SAME id
            # the submit minted — no shared state beyond this field
            tid = self.service.trace_of(j.digest)
            if self.spans is not None:
                # queue_wait closes at hand-out; a re-claim after a
                # steal re-emits it with the attempt count, so the
                # stitched timeline shows both waits
                self.spans.emit(
                    obs_trace.SPAN_QUEUE_WAIT, j.submitted_unix, now,
                    job=j.digest, trace=tid, worker=info.id,
                    stolen=int(j.stolen),
                    attempts=getattr(j, "attempts", 0),
                )
            handed.append({
                "id": j.id, "digest": j.digest,
                "spec": svc_jobs.spec_to_payload(j.spec),
                "stolen": j.stolen,
                "trace": tid,
            })
        return _json_body(200, {
            "jobs": handed,
            "deadline_unix": deadline,
            "lease_s": self.queue.lease_s,
            "epoch": self.epoch,
        })

    def _renew(self, doc):
        info, err = self._known(doc)
        if err is not None:
            return err
        digests = doc.get("digests") or []
        renewed, lost = self.queue.renew(info.id, digests)
        return _json_body(200, {
            "renewed": renewed, "lost": lost,
            "deadline_unix": time.time() + self.queue.lease_s,
        })

    def _complete(self, doc):
        info, err = self._known(doc)
        if err is not None:
            return err
        done = doc.get("done") or []
        failed = doc.get("failed") or {}
        acked = dup = 0
        for digest in done:
            job = self.queue.get_by_digest(digest)
            t_verify = time.time()
            result = svc_jobs.find_result(
                self.service.artifact_dir, digest
            )
            if result is not None and info.mode != "remote" \
                    and self.spans is not None:
                # shared-fs jobs never cross _accept_result, so the
                # signature check above IS their verify hop — witness
                # it (remote uploads were witnessed at upload time)
                self.spans.emit(
                    obs_trace.SPAN_VERIFY, t_verify, time.time(),
                    job=digest, trace=self.service.trace_of(digest),
                )
            if job is None:
                dup += 1  # finished after a restart reset the registry
                continue
            if result is None:
                if job.worker != info.id:
                    dup += 1  # a non-owner's resultless claim is noise
                    continue
                self.queue.mark_failed(
                    job, "completion reported but no valid signed "
                    "result on disk"
                )
                info.jobs_failed += 1
                continue
            before = self.queue.stats_counters["dup_completions"]
            self.queue.mark_done(job, result)
            if self.queue.stats_counters["dup_completions"] > before:
                dup += 1
            else:
                acked += 1
                info.jobs_done += 1
            svc_jobs.delete_job_spec(self.service.artifact_dir, digest)
            self.service.publish_job(job)
        for digest, msg in failed.items():
            job = self.queue.get_by_digest(digest)
            if job is None:
                continue
            # only the CURRENT owner may fail a job: a stalled worker
            # whose batch was stolen reports failures for jobs another
            # worker is validly running (or that were requeued) — those
            # reports are late noise, not verdicts. The done path needs
            # no such guard (results are idempotent; failures are not).
            if job.worker != info.id:
                dup += 1
                continue
            self.queue.mark_failed(job, str(msg))
            info.jobs_failed += 1
            svc_jobs.delete_job_spec(
                self.service.artifact_dir, digest
            )
            self.service.publish_job(job)
        info.batches += 1
        if doc.get("dispatch_s"):
            d = float(doc["dispatch_s"])
            info.last_dispatch_s = d
            if not info.first_dispatch_s:
                info.first_dispatch_s = d
            # the measured profile (ISSUE 19): first sample seeds the
            # EWMA, then 0.7/0.3 smoothing — slow enough to damp one
            # cold compile, fast enough to notice a degraded host
            info.ewma_dispatch_s = (
                d if not info.ewma_dispatch_s
                else 0.7 * info.ewma_dispatch_s + 0.3 * d
            )
        if doc.get("probable_hits") is not None:
            try:
                info.probable_hits = int(doc["probable_hits"])
            except (TypeError, ValueError):
                pass
        pushed = doc.get("metrics_text")
        if isinstance(pushed, str) and pushed:
            from tpusim.obs.emitters import parse_prometheus_text
            try:
                parse_prometheus_text(pushed)
            except ValueError:
                pass  # an unparseable push never poisons the merge
            else:
                info.metrics_text = pushed
        if doc.get("sweep_executables") is not None:
            info.sweep_executables = int(doc["sweep_executables"])
        if isinstance(doc.get("transfers"), dict):
            info.transfers = {
                k: int(v) for k, v in doc["transfers"].items()
            }
        return _json_body(200, {"acked": acked, "dup": dup})

    # ---- the fleet-aggregated /metrics (ISSUE 19) ----

    def _metrics(self):
        """GET /metrics, fleet edition: the coordinator's own snapshot
        (MonitorServer.metrics_text, present once a run record was
        published) + fleet-level gauges + every LIVE worker's pushed
        snapshot re-emitted under a `worker="<id>"` label. Every label
        value rides escape_label_value, `# TYPE` declarations are
        emitted once per name across the whole merge, and the result
        must round-trip parse_prometheus_text — the bench gate scrapes
        and re-parses it. Name spaces keep the merge collision-free:
        the base snapshot owns `tpusim_*` run-record names, the fleet
        gauges own `tpusim_fleet_*`, worker pushes own
        `tpusim_worker_*` (worker_metrics_text)."""
        from tpusim.obs.emitters import (escape_label_value,
                                         parse_prometheus_text)

        lines: List[str] = []
        typed = set()

        def declare(name: str):
            if name not in typed:
                typed.add(name)
                lines.append(f"# TYPE {name} gauge")

        # include_extra: the live per-kind latency summaries (ISSUE 20)
        # ride the merged scrape under the same names the tsdb samples
        monitor = getattr(self.service, "monitor", None)
        base = (monitor.metrics_text(include_extra=True)
                if monitor is not None else "")
        if base:
            for ln in base.rstrip("\n").splitlines():
                if ln.startswith("# TYPE "):
                    parts = ln.split()
                    if len(parts) >= 3:
                        typed.add(parts[2])
                lines.append(ln)
        now = time.time()
        declare("tpusim_fleet_workers_live")
        lines.append(
            f"tpusim_fleet_workers_live {self.registry.live_count(now)}"
        )
        declare("tpusim_fleet_queue_depth")
        lines.append(f"tpusim_fleet_queue_depth {self.queue.depth()}")
        for fam, depth in sorted(self.queue.family_depths().items()):
            declare("tpusim_fleet_family_depth")
            lines.append(
                'tpusim_fleet_family_depth{family="%s"} %d'
                % (escape_label_value(fam), depth)
            )
        with self.registry._lock:
            snapshot = list(self.registry.workers.values())
        for w in sorted(snapshot, key=lambda w: w.id):
            if not w.metrics_text:
                continue
            if not w.live(now, self.registry.live_window_s):
                continue  # a dead worker's last push is history, not state
            try:
                series = parse_prometheus_text(w.metrics_text)
            except ValueError:
                continue  # _complete validates, but never trust stale state
            wl = escape_label_value(w.id)
            for (name, labels) in sorted(series):
                declare(name)
                pairs = [
                    f'{k}="{escape_label_value(v)}"' for k, v in labels
                ] + [f'worker="{wl}"']
                lines.append(
                    f"{name}{{{','.join(pairs)}}} {series[(name, labels)]}"
                )
        text = "\n".join(lines) + "\n"
        return (200, "text/plain; version=0.0.4; charset=utf-8",
                text.encode())

    # ---- restart recovery (the lease-file half) ----

    def adopt_leases(self, out=None) -> int:
        """Coordinator-restart recovery (runs after recover_pending_jobs
        requeued the pending specs): a job whose lease FILE is still
        LIVE — within deadline + skew — belongs to a worker that may
        well still be computing it, so re-attach the claim instead of
        letting the queue hand it out twice; expired files are cleaned
        (their jobs stay queued — already stolen, in effect). Returns
        the number of adopted jobs."""
        adopted = 0
        for digest, lease in svc_leases.scan_leases(
            self.service.artifact_dir
        ):
            job = self.queue.get_by_digest(digest)
            if svc_leases.lease_expired(lease):
                svc_leases.delete_lease(self.service.artifact_dir, digest)
                self.queue.stats_counters["lease_expired"] += 1
                self._audit("lease_expired", job=digest,
                            worker=str(lease.get("worker") or ""),
                            reason="expired-at-adoption")
                continue
            if job is None or job.status != "queued":
                continue
            wid = str(lease.get("worker") or "")
            info = self.registry.register(
                wid, lease.get("pid") or 0, ""
            )
            claimed = self.queue.claim_specific(
                wid, [digest], float(lease["deadline_unix"])
            )
            adopted += len(claimed)
            if claimed and out is not None:
                print(
                    f"[fleet] adopted live lease of {wid} on "
                    f"{digest[:12]}… (deadline in "
                    f"{lease['deadline_unix'] - time.time():.1f}s)",
                    file=out,
                )
            info.last_seen_unix = time.time()
        return adopted

    # ---- the /queue aggregation fields ----

    def queue_fields(self) -> dict:
        from tpusim.svc.auth import describe as auth_describe

        rows = self.registry.describe(self.queue)
        out = {
            "workers": rows,
            "workers_live": self.registry.live_count(),
            "batches_run": sum(r["batches"] for r in rows.values()),
            "sweep_executables": sum(
                r["sweep_executables"] for r in rows.values()
            ),
            "transfer": dict(self.transfers),
            # the HA + auth surfaces (ISSUE 17): role/epoch for the
            # operator, auth armed-or-not (NEVER token material), and
            # the families currently starved for a capable worker
            "role": self.role,
            "epoch": self.epoch,
            "auth": auth_describe(self.token),
            "starved_families": self.starved_families(),
        }
        if self.supervisor is not None:
            # respawns, backoff, breaker state + reason, autoscale
            # counters — /queue "says why" (ISSUE 13)
            out["supervisor"] = self.supervisor.describe()
        return out

    def health(self):
        """MonitorServer.health_hook: the fleet coordinator is healthy
        while ANY worker is live (the ISSUE 12 contract) AND the
        supervisor's crash-loop circuit breaker is closed (ISSUE 13):
        a breaker held open means the fleet cannot self-heal — that is
        a loud 503, not three quiet respawn attempts per second."""
        live = self.registry.live_count()
        ok = live > 0
        extra = {
            "workers_live": live,
            "workers_known": len(self.registry.workers),
            # role + epoch (ISSUE 17): `leader|standby` here; the
            # /healthz handler overrides role to `draining` during a
            # graceful shutdown (MonitorServer owns that flag)
            "role": self.role,
            "epoch": self.epoch,
        }
        if self.role == "standby":
            # a standby with no workers is doing its one job: watching
            # the leadership lease. It is healthy by existing.
            return True, extra
        if self.supervisor is not None:
            sup_ok, sup_fields = self.supervisor.healthy()
            extra.update(sup_fields)
            ok = ok and sup_ok
        return ok, extra


# ---------------------------------------------------------------------------
# The worker process (`tpusim worker --join URL`)
# ---------------------------------------------------------------------------


def _with_backoff(call, max_attempts: int = 8, stop_event=None):
    """The shared kube_client.with_backoff schedule (ISSUE 14 satellite:
    the loop moved INTO kube_client beside retryable_conn_excs /
    is_retryable_status so the fleet, the extender client, and the rest
    client all ride one implementation; this thin alias keeps the fleet's
    internal call sites and test monkeypatch points stable)."""
    from tpusim.io.kube_client import with_backoff

    return with_backoff(call, max_attempts=max_attempts,
                        stop_event=stop_event)


def _trace_headers(token: str, trace: str) -> dict:
    """Auth + trace-propagation headers for one fleet hop (ISSUE 19):
    the trace id rides X-Tpusim-Trace on every worker→coordinator POST
    so both sides tag the same journey without shared state."""
    headers = bearer_headers(token)
    if trace:
        headers[obs_trace.TRACE_HEADER] = str(trace)
    return headers


def _post(url: str, path: str, doc: dict, timeout: float = 30.0,
          max_attempts: int = 8, stop_event=None, token: str = "",
          trace: str = ""):
    from tpusim.svc.client import _request

    full = url.rstrip("/") + path
    data = json.dumps(doc).encode()
    return _with_backoff(
        lambda: _request(full, data, timeout=timeout,
                         headers=_trace_headers(token, trace)),
        max_attempts=max_attempts, stop_event=stop_event,
    )


def _post_bytes(url: str, path: str, data: bytes, timeout: float = 60.0,
                max_attempts: int = 8, token: str = "", trace: str = ""):
    """POST raw bytes (the signed-result upload) on the same backoff
    schedule as _post."""
    from tpusim.svc.client import _request

    full = url.rstrip("/") + path
    return _with_backoff(
        lambda: _request(full, data, timeout=timeout,
                         content_type="application/octet-stream",
                         headers=_trace_headers(token, trace)),
        max_attempts=max_attempts,
    )


class CoordinatorRing:
    """Multi-coordinator failover client (ISSUE 17): an ordered URL
    list (`--join u1,u2`), one live cursor. Every post rides the
    shared `with_backoff` schedule against the CURRENT coordinator;
    when that coordinator stays unreachable past the whole schedule —
    or keeps answering 503 (a standby, or a draining leader) — the
    cursor rotates to the next URL and the call is retried there. With
    a single URL this degrades to exactly the pre-HA behavior (the
    final answer or exception surfaces).

    Carries the bearer token so every mutating call through the ring
    is authenticated; the token itself never appears in any log line.
    """

    def __init__(self, urls, token: str = "", stop_event=None):
        from tpusim.io.kube_client import parse_url_list

        self.urls = parse_url_list(urls)
        self.token = str(token or "")
        self.stop_event = stop_event
        self._idx = 0

    @property
    def url(self) -> str:
        return self.urls[self._idx]

    def rotate(self) -> str:
        self._idx = (self._idx + 1) % len(self.urls)
        return self.url

    def _attempts_per_url(self, max_attempts: int) -> int:
        # with alternatives available, give up on one coordinator
        # sooner — the schedule is shared, the budget is split
        return max_attempts if len(self.urls) == 1 else min(max_attempts, 3)

    def _drive(self, fn, max_attempts: int):
        from tpusim.io.kube_client import retryable_conn_excs
        from tpusim.svc.client import ServiceError

        last_exc = None
        answer = None
        per_url = self._attempts_per_url(max_attempts)
        for i in range(len(self.urls)):
            try:
                answer = fn(self.url, per_url)
            except retryable_conn_excs() as err:
                last_exc = err
                if len(self.urls) > 1:
                    self.rotate()
                continue
            code = answer[0]
            if code == 503 and i < len(self.urls) - 1:
                # a standby (or a draining leader) said "not me" —
                # the next coordinator in the ring may be leading
                self.rotate()
                continue
            return answer
        if answer is not None:
            return answer
        if last_exc is not None:
            raise last_exc
        raise ServiceError(f"no coordinator reachable in {self.urls}")

    def post(self, path: str, doc: dict, timeout: float = 30.0,
             max_attempts: int = 8, stop_event=None, trace: str = ""):
        return self._drive(
            lambda u, ma: _post(
                u, path, doc, timeout=timeout, max_attempts=ma,
                stop_event=stop_event or self.stop_event,
                token=self.token, trace=trace,
            ),
            max_attempts,
        )

    def post_bytes(self, path: str, data: bytes, timeout: float = 60.0,
                   max_attempts: int = 8, trace: str = ""):
        return self._drive(
            lambda u, ma: _post_bytes(
                u, path, data, timeout=timeout, max_attempts=ma,
                token=self.token, trace=trace,
            ),
            max_attempts,
        )


def _get_bytes(url: str, path: str, offset: int = 0,
               timeout: float = 60.0):
    """(code, headers, raw bytes) of one coordinator GET; offset > 0
    sends a Range header (the partial-transfer resume)."""
    import urllib.error
    import urllib.request

    req = urllib.request.Request(url.rstrip("/") + path)
    if offset > 0:
        req.add_header("Range", f"bytes={int(offset)}-")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers or {}), e.read()


def new_transfer_counters() -> dict:
    """The worker-side transfer counters reported on every complete
    POST and surfaced per worker in /workers (ISSUE 13)."""
    return {
        "downloads": 0, "download_bytes": 0, "resumed": 0,
        "sha_retries": 0, "uploads": 0, "upload_bytes": 0,
        "upload_failed": 0,
    }


def worker_metrics_text(served: int, jobs_done: int, jobs_failed: int,
                        dispatch_s: float, probable_hits: int,
                        counters: dict) -> str:
    """The worker's own exposition-format snapshot, pushed on every
    complete POST and re-emitted under a `worker="<id>"` label by the
    coordinator's merged /metrics (ISSUE 19). Unlabeled here on
    purpose: the coordinator owns the worker label, so the
    escape_label_value hygiene lives at exactly one merge point."""
    pairs = [
        ("tpusim_worker_batches", int(served)),
        ("tpusim_worker_jobs_done", int(jobs_done)),
        ("tpusim_worker_jobs_failed", int(jobs_failed)),
        ("tpusim_worker_last_dispatch_seconds", round(dispatch_s, 6)),
        ("tpusim_worker_probable_compile_hits", int(probable_hits)),
        ("tpusim_worker_download_bytes",
         int(counters.get("download_bytes") or 0)),
        ("tpusim_worker_upload_bytes",
         int(counters.get("upload_bytes") or 0)),
    ]
    lines = []
    for name, val in pairs:
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {val}")
    return "\n".join(lines) + "\n"


def _part_path(dest: str) -> str:
    # pid-scoped so two workers sharing one trace cache never append
    # into each other's partial transfer
    return f"{dest}.{os.getpid()}.part"


def _adopt_orphan_part(dest: str) -> None:
    """Claim a DEAD predecessor's partial download so crash-resume
    actually reaches across a respawn: pid-scoped .part names keep live
    writers apart, but a worker that was kill -9'd mid-transfer leaves
    a part its respawned successor (new pid) could neither resume nor
    clean. Adopt the largest part whose pid no longer exists (a dead
    pid cannot write again, so the rename is race-free against its
    owner); unlink the other dead ones."""
    mine = _part_path(dest)
    if os.path.isfile(mine):
        return
    d, base = os.path.split(dest)
    dead = []
    try:
        names = os.listdir(d or ".")
    except OSError:
        return
    for fname in names:
        if not (fname.startswith(base + ".") and fname.endswith(".part")):
            continue
        pid_s = fname[len(base) + 1:-len(".part")]
        if not pid_s.isdigit() or int(pid_s) == os.getpid():
            continue
        try:
            os.kill(int(pid_s), 0)
            continue  # owner still alive: hands off
        except ProcessLookupError:
            pass
        except (PermissionError, OSError):
            continue  # exists (other uid) or unknowable: hands off
        path = os.path.join(d, fname)
        try:
            dead.append((os.path.getsize(path), path))
        except OSError:
            pass
    if not dead:
        return
    dead.sort(reverse=True)
    try:
        os.replace(dead[0][1], mine)
    except OSError:
        return
    for _, path in dead[1:]:
        try:
            os.unlink(path)
        except OSError:
            pass


def fetch_trace_file(url: str, rel: str, dest: str, sha256: str,
                     counters: Optional[dict] = None, out=None,
                     max_attempts: int = 8) -> str:
    """Download one hosted trace file to `dest`, resuming a partial
    transfer (Range from the .part file's size) and verifying the raw
    bytes against the handshake's sha256. A verification miss wipes the
    partial file and re-downloads from byte 0 ONCE; a second miss is a
    loud failure (the coordinator is serving different bytes than it
    advertised — version skew, never something to paper over). The
    completed file lands by atomic rename, so a cached dest is always
    whole."""
    from tpusim.io.storage import file_sha256
    from tpusim.svc.client import ServiceError

    if counters is None:
        counters = new_transfer_counters()
    _adopt_orphan_part(dest)
    part = _part_path(dest)
    for round_ in (1, 2):
        offset = os.path.getsize(part) if os.path.isfile(part) else 0
        if offset > 0 and sha256 and file_sha256(part) == sha256:
            # the predecessor had actually finished the bytes and died
            # between write and rename — nothing left to transfer
            os.replace(part, dest)
            return dest
        if offset > 0:
            counters["resumed"] += 1
        code, headers, data = _with_backoff(
            lambda: _get_bytes(url, rel, offset=offset),
            max_attempts=max_attempts,
        )
        if code == 416:
            # stale oversized .part (the file shrank server-side):
            # restart clean
            try:
                os.unlink(part)
            except OSError:
                pass
            offset = 0
            code, headers, data = _with_backoff(
                lambda: _get_bytes(url, rel, offset=0),
                max_attempts=max_attempts,
            )
        if code not in (200, 206):
            raise ServiceError(f"GET {rel} -> HTTP {code}")
        mode = "ab" if (code == 206 and offset > 0) else "wb"
        with open(part, mode) as f:
            f.write(data)
        counters["downloads"] += 1
        counters["download_bytes"] += len(data)
        got = file_sha256(part)
        want = sha256 or (headers or {}).get("X-Content-SHA256") or ""
        if not want or got == want:
            os.replace(part, dest)
            return dest
        counters["sha_retries"] += 1
        if out is not None:
            print(
                f"[worker] {rel}: sha256 mismatch after download "
                f"(got {got[:12]}…, want {want[:12]}…) — "
                f"{'re-downloading from byte 0' if round_ == 1 else 'giving up'}",
                file=out,
            )
        try:
            os.unlink(part)
        except OSError:
            pass
    raise ServiceError(
        f"downloaded {rel} twice and the sha256 still mismatches the "
        "register handshake (coordinator/worker version or content "
        "skew) — refusing to parse it"
    )


def ensure_local_trace(url: str, name: str, meta: dict, cache_dir: str,
                       counters: Optional[dict] = None, out=None):
    """The remote worker's trace acquisition: a local cache keyed by
    the trace CONTENT digest (`<cache>/traces/<digest>/{nodes,pods}.csv`)
    — a cache hit (file present, sha256 matching the handshake) costs
    zero HTTP; a miss/mismatch re-downloads with resume; and the parsed
    trace must reproduce the coordinator's content digest exactly or
    the worker refuses to serve (the ISSUE 12 skew contract, now over
    the wire). Returns a TraceRef."""
    from tpusim.io.storage import file_sha256
    from tpusim.svc.client import ServiceError
    from tpusim.svc.worker import load_trace

    ddir = os.path.join(cache_dir, "traces", str(meta["digest"]))
    os.makedirs(ddir, exist_ok=True)
    paths = {}
    for which, sha_key in (("nodes.csv", "nodes_sha256"),
                           ("pods.csv", "pods_sha256")):
        dest = os.path.join(ddir, which)
        sha = str(meta.get(sha_key) or "")
        if os.path.isfile(dest) and sha and file_sha256(dest) == sha:
            paths[which] = dest
            continue
        if os.path.isfile(dest):
            # cached bytes no longer match the handshake: force a
            # fresh download (the re-download-on-mismatch contract)
            if counters is not None:
                counters["sha_retries"] += 1
            try:
                os.unlink(dest)
            except OSError:
                pass
        fetch_trace_file(
            url, f"/traces/{name}/{which}", dest, sha,
            counters=counters, out=out,
        )
        paths[which] = dest
    t = load_trace(
        name, paths["nodes.csv"], paths["pods.csv"],
        max_pods=int(meta.get("max_pods") or 0),
    )
    if t.digest != meta["digest"]:
        raise ServiceError(
            f"hosted trace {name!r} content-digest mismatch after a "
            f"verified download: coordinator {meta['digest'][:12]}… vs "
            f"local parse {t.digest[:12]}… (code version skew)"
        )
    return t


def resolve_worker_mode(mode: str, reg: dict) -> str:
    """auto → shared-fs iff the coordinator's artifact dir AND every
    hosted trace CSV are readable from this host (same machine or a
    genuinely shared filesystem — the digest checks still guard
    content skew); anything unreachable means this worker runs in
    remote mode: digest-verified downloads, result uploads, lease
    POSTs. Explicit modes pass through untouched."""
    if mode in ("shared-fs", "remote"):
        return mode
    if mode not in ("", "auto"):
        raise ValueError(
            f"worker mode must be auto | shared-fs | remote, got {mode!r}"
        )
    if not os.path.isdir(reg.get("artifact_dir") or ""):
        return "remote"
    for meta in (reg.get("traces") or {}).values():
        if not (os.path.isfile(meta.get("nodes_csv") or "")
                and os.path.isfile(meta.get("pods_csv") or "")):
            return "remote"
    return "shared-fs"


def run_worker(url: str, worker_id: str = "", poll_s: float = 0.2,
               max_batches: int = 0, table_cache_dir: str = "",
               compile_cache_dir: str = "", out=None,
               stop_event=None, mode: str = "auto",
               cache_dir: str = "", token: str = "",
               caps: Optional[dict] = None) -> int:
    """The fleet worker's main loop: register, then claim/run/complete
    until stopped (or `max_batches` served — the test/smoke bound).
    Returns the number of batches served. SIGTERM handling is the
    caller's (the CLI installs a drain flag via `stop_event`); a
    `kill -9` needs no handling — that is what the leases are for.

    `mode` (ISSUE 13): "shared-fs" reads the coordinator's trace CSVs
    by path and writes results straight into the shared artifact dir
    (the ISSUE 12 behavior); "remote" needs NO shared filesystem —
    traces are downloaded into a digest-keyed local cache, results are
    written locally then UPLOADED (the coordinator digest-verifies
    before the atomic rename), and leases are staked/released via POST
    /leases; "auto" (default) probes the handshake's paths and picks.
    Every POST rides the shared capped-backoff-with-jitter schedule
    honoring Retry-After, so a coordinator restart mid-claim is a
    stall, not a dead worker.

    `url` may be a comma-separated coordinator LIST (ISSUE 17): the
    worker rotates through it via CoordinatorRing when the current
    coordinator dies or demotes to standby, re-registering after an
    epoch bump — a coordinator failover is a stall, not lost work.
    `token` authenticates every mutating POST; `caps` are the
    capability tags declared at registration (default:
    svc.worker.local_caps())."""
    from tpusim.io.kube_client import retryable_conn_excs
    from tpusim.svc.client import ServiceError
    from tpusim.svc.worker import Worker, load_trace, local_caps

    host = os.uname().nodename if hasattr(os, "uname") else ""
    if caps is None:
        caps = local_caps()
    ring = CoordinatorRing(url, token=token, stop_event=stop_event)
    try:
        code, _, reg = ring.post("/workers/register", {
            "worker": worker_id, "pid": os.getpid(), "host": host,
            "caps": caps,
        }, stop_event=stop_event)
    except retryable_conn_excs() as err:
        raise ServiceError(
            f"could not reach any coordinator in {ring.urls} "
            f"({type(err).__name__}: {err})"
        )
    if code == 401:
        raise ServiceError(
            "POST /workers/register -> HTTP 401: bearer token missing "
            "or rejected (--token-file / TPUSIM_FLEET_TOKEN)"
        )
    if code != 200:
        raise ServiceError(
            f"POST /workers/register -> HTTP {code}: {reg}"
        )
    wid = reg["worker"]
    lease_s = float(reg["lease_s"])
    epoch = int(reg.get("epoch") or 0)
    counters = new_transfer_counters()
    # the flight-recorder state (ISSUE 19): trace ids arrive on the
    # claim answer, keyed by digest; every subsequent hop for that job
    # rides the id as an X-Tpusim-Trace header. current_trace is the
    # last batch's lead id — the claim/re-register hops' best context.
    trace_ids: Dict[str, str] = {}
    current_trace = ""
    probable_hits = 0
    jobs_done_total = 0
    jobs_failed_total = 0

    def stamp(doc: dict) -> dict:
        # every mirrored lease/complete/claim op carries the
        # coordinator epoch (ISSUE 17) — the fencing stamp
        if epoch:
            doc["epoch"] = epoch
        return doc

    def re_register() -> int:
        # after a takeover the ring may already point at the new
        # leader; registering there adopts ITS epoch for all
        # subsequent stamps
        nonlocal epoch
        code, _, r = ring.post("/workers/register", {
            "worker": wid, "pid": os.getpid(), "host": host,
            "mode": mode, "caps": caps,
        }, trace=current_trace)
        if code == 200:
            new_epoch = int(r.get("epoch") or 0)
            if out is not None and new_epoch != epoch:
                print(
                    f"[worker {wid}] re-registered at {ring.url} "
                    f"(epoch {epoch} -> {new_epoch})", file=out,
                )
            epoch = new_epoch
        return code

    mode = resolve_worker_mode(mode, reg)
    # record the resolved topology in the roster (register is an
    # idempotent update — /workers shows mode per worker)
    re_register()

    traces = {}
    if mode == "remote":
        if not cache_dir:
            import tempfile

            cache_dir = os.path.join(
                tempfile.gettempdir(), "tpusim-worker-cache"
            )
        artifact_dir = os.path.join(cache_dir, "artifacts")
        os.makedirs(artifact_dir, exist_ok=True)
        # remote-mode spans land in the worker's LOCAL artifact cache —
        # `tpusim trace` stitches them only where the dir is shared
        # (the documented limitation; the local fleet shares it)
        recorder = obs_trace.SpanRecorder(artifact_dir, f"worker-{wid}")
        for name, meta in (reg.get("traces") or {}).items():
            with recorder.span(obs_trace.SPAN_TRANSFER,
                               trace_name=name) as sp:
                traces[name] = ensure_local_trace(
                    ring.url, name, meta, cache_dir, counters=counters,
                    out=out,
                )
                sp.meta["download_bytes"] = counters["download_bytes"]
    else:
        artifact_dir = reg["artifact_dir"]
        recorder = obs_trace.SpanRecorder(artifact_dir, f"worker-{wid}")
        for name, meta in (reg.get("traces") or {}).items():
            t = load_trace(
                name, meta["nodes_csv"], meta["pods_csv"],
                max_pods=int(meta.get("max_pods") or 0),
            )
            if t.digest != meta["digest"]:
                # trace skew: this worker would compute results under a
                # DIFFERENT digest vocabulary — refuse to serve
                raise ServiceError(
                    f"hosted trace {name!r} digest mismatch: coordinator "
                    f"{meta['digest'][:12]}… vs local {t.digest[:12]}… "
                    "(differing CSVs or code version)"
                )
            traces[name] = t

    queue = JobQueue(
        maxsize=max(4 * int(reg["lane_width"]), 8),
        lane_width=int(reg["lane_width"]), lease_s=lease_s,
    )
    worker = Worker(
        queue, traces, artifact_dir, bucket=int(reg.get("bucket") or 512),
        table_cache_dir=table_cache_dir,
        compile_cache_dir=compile_cache_dir,
        worker_id=wid, lease_files=True,
    )

    def renew_remote(digests):
        # one 409 (epoch bump / wiped roster) earns an immediate
        # re-register + retry so in-flight work keeps its lease across
        # a coordinator failover instead of riding out a steal
        digests = list(digests)
        for attempt in (1, 2):
            code, _, doc = ring.post(
                "/workers/renew",
                stamp({"worker": wid, "digests": digests}),
                trace=trace_ids.get(digests[0], "") if digests else "",
            )
            if code == 409 and attempt == 1:
                re_register()
                continue
            if code != 200:
                return []
            return doc.get("lost") or []
        return []

    worker.renew_cb = renew_remote
    if mode == "remote":
        # the lease FILES live on the coordinator's disk (adoption and
        # reaping are unchanged) — a no-shared-fs worker mirrors them
        # over POST /leases; short retry budgets keep the keeper thread
        # from stalling a whole renewal period on a flaky link
        def _stake(members):
            members = list(members)
            return ring.post(
                "/leases",
                stamp({"op": "stake", "worker": wid,
                       "pid": os.getpid(), "members": members}),
                max_attempts=3,
                trace=(trace_ids.get(members[0], "")
                       if members else ""),
            )

        def _release(members):
            members = list(members)
            return ring.post(
                "/leases",
                stamp({"op": "release", "worker": wid,
                       "members": members}),
                max_attempts=3,
                trace=(trace_ids.get(members[0], "")
                       if members else ""),
            )

        worker.lease_stake_cb = _stake
        worker.lease_release_cb = _release

    from tpusim.sim.driver import enable_compile_cache

    enable_compile_cache(compile_cache_dir)
    if out is not None:
        print(
            f"[worker {wid}] joined {ring.url} ({mode}, pid "
            f"{os.getpid()}, {len(traces)} trace(s), lease "
            f"{lease_s:.1f}s)", file=out,
        )

    served = 0
    while stop_event is None or not stop_event.is_set():
        t_claim = time.time()
        try:
            # the IDLE path carries the stop_event: a drain must not
            # wait out the whole backoff schedule against a draining
            # coordinator's 503s (uploads/completions below finish
            # regardless — that is the graceful half)
            code, _, doc = ring.post("/workers/claim",
                                     stamp({"worker": wid}),
                                     stop_event=stop_event,
                                     trace=current_trace)
        except retryable_conn_excs():
            # every coordinator down longer than the whole backoff
            # schedule: recovery requeues everything; keep polling
            time.sleep(max(poll_s, 0.5))
            continue
        if code == 409:
            # roster wiped by a coordinator restart, or our epoch
            # stamp is stale after a takeover — re-register (the ring
            # already points at whichever coordinator answered)
            re_register()
            continue
        if code != 200:
            time.sleep(max(poll_s, 0.5))
            continue
        resp_epoch = int((doc or {}).get("epoch") or 0)
        if resp_epoch and epoch and resp_epoch < epoch:
            # the worker-side fence (ISSUE 17): a resurrected
            # old-epoch leader handed us work — refuse it and move to
            # the coordinator whose epoch matches what we adopted
            if out is not None:
                print(
                    f"[worker {wid}] rejecting claim from {ring.url} "
                    f"(epoch {resp_epoch} < {epoch} — deposed "
                    "leader); rotating", file=out,
                )
            ring.rotate()
            time.sleep(max(poll_s, 0.5))
            continue
        jobs_docs = doc.get("jobs") or []
        if not jobs_docs:
            time.sleep(poll_s)
            continue
        # adopt the claim answer's trace ids (ISSUE 19): each job's
        # remaining hops — dispatch, upload, complete, lease mirror —
        # tag themselves with the id the submit minted
        t_claimed = time.time()
        for jd in jobs_docs:
            d = str(jd.get("digest") or "")
            tid = str(jd.get("trace") or "")
            if d:
                trace_ids[d] = tid
            recorder.emit(obs_trace.SPAN_CLAIM, t_claim, t_claimed,
                          job=d, trace=tid,
                          stolen=int(jd.get("stolen") or 0))
        current_trace = str(jobs_docs[0].get("trace") or "")

        batch, skew_failed = [], {}
        for lane, jd in enumerate(jobs_docs):
            try:
                spec = svc_jobs.validate_job(jd["spec"])
                digest = svc_jobs.job_digest(
                    spec, traces[spec.trace].digest
                )
                if digest != jd["digest"]:
                    raise ValueError(
                        "job digest mismatch (coordinator/worker "
                        "version skew)"
                    )
            except (KeyError, ValueError) as err:
                skew_failed[jd.get("digest", "?")] = str(err)
                continue
            batch.append(Job(
                id=jd["id"], spec=spec, digest=jd["digest"],
                status="batched", batch=served + 1, lane=lane,
                worker=wid,
            ))
        # one dispatch span per job, OPEN across run_batch: a kill -9
        # mid-batch leaves begins with no ends — the stitcher renders
        # them ABANDONED, the visible corpse the steal accounts for
        dispatch_spans = {
            j.digest: recorder.begin(
                obs_trace.SPAN_DISPATCH, job=j.digest,
                trace=trace_ids.get(j.digest, ""), lane=j.lane,
                stolen=int(j.stolen),
            )
            for j in batch
        }
        if batch:
            worker.run_batch(batch)
            served += 1
            # the compile-cache heuristic (obs.spans.note_compile_cache):
            # a batch dispatch wall under 2 s means the persistent
            # cache almost certainly served the executable
            if 0 < worker.last_dispatch_s < 2.0:
                probable_hits += 1
        for j in batch:
            recorder.end(dispatch_spans[j.digest], status=j.status,
                         dispatch_s=worker.last_dispatch_s)
        done = [j.digest for j in batch if j.status == "done"]
        failed = {
            j.digest: j.error for j in batch if j.status == "failed"
        }
        failed.update(skew_failed)
        if mode == "remote" and done:
            # the upload half (ISSUE 13): ship each signed result's
            # BYTES to the coordinator, which digest-verifies before
            # the atomic rename — completion below then finds them on
            # ITS disk. An upload the coordinator rejects (impossible
            # for bytes our own read just verified, short of a forged
            # proxy) demotes the job to failed so the loud complete
            # path reports it.
            still_done = []
            for d in done:
                data = svc_jobs.result_bytes(artifact_dir, d)
                if data is None:
                    failed[d] = "local signed result vanished/torn"
                    continue
                t_upload = time.time()
                try:
                    code, _, up = ring.post_bytes(
                        f"/results/{d}", data,
                        trace=trace_ids.get(d, ""),
                    )
                except retryable_conn_excs():
                    code, up = 0, {"error": "coordinator unreachable"}
                recorder.emit(obs_trace.SPAN_UPLOAD, t_upload,
                              time.time(), job=d,
                              trace=trace_ids.get(d, ""),
                              code=code, bytes=len(data))
                if code == 200:
                    counters["uploads"] += 1
                    counters["upload_bytes"] += len(data)
                    still_done.append(d)
                elif 400 <= code < 500:
                    # a definitive rejection (torn/forged verdict from
                    # the coordinator) is terminal — report it loudly
                    counters["upload_failed"] += 1
                    failed[d] = (
                        f"result upload -> HTTP {code}: "
                        f"{(up or {}).get('error', up)}"
                    )
                else:
                    # transport failure / 5xx after the whole backoff
                    # schedule: the result is correct and sitting in
                    # local scratch — do NOT report the job at all, so
                    # the lease expires and a steal either re-runs it
                    # or (after our later re-upload) answers from disk.
                    # Demoting to failed here would make a transient
                    # partition terminal.
                    counters["upload_failed"] += 1
                    if out is not None:
                        print(
                            f"[worker {wid}] result upload for "
                            f"{d[:12]}… failed transiently (HTTP "
                            f"{code}); leaving the job to lease "
                            "expiry", file=out,
                        )
            done = still_done
        elif done:
            # shared-fs publish half: run_batch already wrote the
            # signed results into the shared artifact dir — witness
            # each publish so the stitched timeline is mode-invariant
            # (upload = the result reaching the shared store; the
            # coordinator's verify span lands at complete time)
            for d in done:
                t_pub = time.time()
                data = svc_jobs.result_bytes(artifact_dir, d)
                recorder.emit(obs_trace.SPAN_UPLOAD, t_pub, time.time(),
                              job=d, trace=trace_ids.get(d, ""),
                              bytes=len(data) if data else 0,
                              shared_fs=1)
        jobs_done_total += len(done)
        jobs_failed_total += len(failed)
        for attempt in (1, 2):
            try:
                code, _, _ack = ring.post("/workers/complete", stamp({
                    "worker": wid, "done": done, "failed": failed,
                    "dispatch_s": worker.last_dispatch_s,
                    "sweep_executables": worker.sweep_executables(),
                    "transfers": counters,
                    # the measured-profile push (ISSUE 19)
                    "probable_hits": probable_hits,
                    "metrics_text": worker_metrics_text(
                        served, jobs_done_total, jobs_failed_total,
                        worker.last_dispatch_s, probable_hits,
                        counters,
                    ),
                }), trace=current_trace)
            except retryable_conn_excs():
                # results + spec deletions are already on disk — a
                # restarted coordinator reconciles from there (its
                # claim shortcut)
                break
            if code == 409 and attempt == 1:
                # epoch bump mid-batch: adopt the new epoch and report
                # the SAME completion once more — mark_done dedups, so
                # across-epoch duplicates are silent, never conflicts
                re_register()
                continue
            break
        if out is not None and batch:
            print(
                f"[worker {wid}] batch {served}: {len(done)} done, "
                f"{len(failed)} failed "
                f"({worker.last_dispatch_s:.2f}s dispatch)", file=out,
            )
        # finished journeys no longer need their trace ids (the map
        # would otherwise grow one entry per job served, forever)
        for d in list(done) + list(failed):
            trace_ids.pop(d, None)
        if max_batches and served >= max_batches:
            break
    worker.stop()
    return served


# ---------------------------------------------------------------------------
# Local fleet spawning (`tpusim serve --jobs --workers N`)
# ---------------------------------------------------------------------------


def worker_command(url: str, table_cache_dir: str = "",
                   compile_cache_dir: str = "", mode: str = "",
                   cache_dir: str = "", token_file: str = "") -> List[str]:
    """The `tpusim worker --join` argv for one spawned child — shared
    by spawn_local_workers and the supervisor's spawn_fn (ISSUE 13).
    No --id: the coordinator assigns pid-scoped ids, so a respawned or
    later-joined child can never collide with (and inherit the stats
    of) an earlier worker's roster entry."""
    cmd = [sys.executable, "-m", "tpusim", "worker", "--join", url]
    if table_cache_dir:
        cmd += ["--table-cache-dir", table_cache_dir]
    if compile_cache_dir:
        cmd += ["--compile-cache-dir", compile_cache_dir]
    if mode:
        cmd += ["--mode", mode]
    if cache_dir:
        cmd += ["--cache-dir", cache_dir]
    if token_file:
        # the token travels as a file PATH, never argv material — a
        # `ps` on the worker host shows the path, not the secret
        cmd += ["--token-file", token_file]
    return cmd


def spawn_local_workers(url: str, n: int, table_cache_dir: str = "",
                        compile_cache_dir: str = "",
                        out=None, token_file: str = "") -> List[subprocess.Popen]:
    """Spawn N `tpusim worker --join` processes against this
    coordinator. They inherit the environment (JAX_PLATFORMS etc.) and
    share the persistent compile cache + table cache dirs — the warm
    state that makes a joiner's first batch skip the compile."""
    procs = []
    for _ in range(int(n)):
        cmd = worker_command(
            url, table_cache_dir=table_cache_dir,
            compile_cache_dir=compile_cache_dir, token_file=token_file,
        )
        procs.append(subprocess.Popen(cmd))
        if out is not None:
            print(f"[fleet] spawned worker process pid {procs[-1].pid}",
                  file=out)
    return procs


def stop_workers(procs, timeout: float = 10.0, out=None) -> None:
    """Drain the spawned fleet: SIGTERM each child (graceful — the
    CLI's stop flag finishes the in-flight batch), escalate to SIGKILL
    past the timeout (leases make even that safe)."""
    for p in procs:
        if p.poll() is None:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
    deadline = time.time() + timeout
    for p in procs:
        remaining = max(deadline - time.time(), 0.1)
        try:
            p.wait(remaining)
        except subprocess.TimeoutExpired:
            if out is not None:
                print(f"[fleet] worker pid {p.pid} ignored SIGTERM — "
                      "killing (leases cover it)", file=out)
            p.kill()
