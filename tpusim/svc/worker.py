"""Worker: ONE thread owns the device and serves every tenant (ISSUE 7).

The worker drains the JobQueue batch by batch and dispatches each batch
through the multi-trace vmapped sweep (driver.schedule_pods_sweep_multi)
— so a whole batch of what-if jobs costs one compiled scan, and across
batches the one-jaxpr-per-family contract holds: per-family Simulators
are cached (sharing the weight-operand engines, the content-keyed table
cache entry, and the persistent compile cache), batches are padded to a
FIXED lane width (a 3-job batch repeats its tail job into the dead
lanes — vmap's axis size is jaxpr structure), and per-family pod/event
shape high-water marks are sticky (the driver's min_pods/min_events
floors), so consecutive batches differing only in weights/seeds/tune
factors reuse ONE compiled executable — `jit._cache_size()` stable, the
acceptance criterion.

Results are summarized host-side (placements, counters, gpu_alloc,
frag, a placements digest for cheap bit-identity checks), persisted as
digest-signed JSONL (svc.jobs.write_result), and marked on the queue.
A batch that raises marks its jobs failed and the worker keeps serving
— one poisoned job family must not take the service down.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from tpusim.svc import jobs as svc_jobs
from tpusim.svc.batcher import Job, JobQueue


@dataclass
class TraceRef:
    """One hosted trace: the cluster + workload every job of this ref
    replays, plus their content digest (part of every job digest)."""

    name: str
    nodes: list
    pods: list
    digest: str


def load_trace(name: str, nodes_csv: str, pods_csv: str,
               max_pods: int = 0) -> TraceRef:
    """Load a hosted trace from node/pod CSVs (`tpusim serve --jobs
    --nodes ... --pods ...`); max_pods > 0 truncates the workload (the
    smoke/prefix knob)."""
    from tpusim.io.trace import load_node_csv, load_pod_csv

    nodes = load_node_csv(nodes_csv)
    pods = load_pod_csv(pods_csv)
    if max_pods > 0:
        pods = pods[:max_pods]
    return TraceRef(
        name=name, nodes=nodes, pods=pods,
        digest=svc_jobs.trace_digest(nodes, pods),
    )


def summarize_lane(lane, job: Job) -> dict:
    """SweepLane -> the persisted/HTTP result document: the shared
    per-lane term vocabulary (learn.objective.lane_terms — ONE code
    path, so a remote tuning client's terms_from_result reads back
    exactly what a local lane yields, the ISSUE 9 bit-identity
    contract) plus the job's identity fields and the full placements
    (i32 node per pod; -1 = unplaced; the terms' sha256 over
    placed_node+dev_mask makes bit-identity against a standalone run
    one string compare)."""
    from tpusim.learn.objective import lane_terms
    from tpusim.obs.counters import COUNTER_FIELDS

    out = lane_terms(lane)
    out.update({
        "job": job.digest,
        "trace": job.spec.trace,
        "policies": [list(p) for p in job.spec.policies],
        "weights": list(job.spec.weights),
        "seed": job.spec.seed,
        "tune": job.spec.tune,
        "placed_node": np.asarray(lane.placed_node, np.int32).tolist(),
    })
    if lane.counters is not None:
        out["counters"] = {
            f: int(c) for f, c in zip(COUNTER_FIELDS, lane.counters)
        }
    if lane.disruption is not None:
        # chaos lanes (ISSUE 10): the full DisruptionMetrics scalar
        # summary rides the result document beside the objective terms
        out["disruption"] = lane.disruption.as_dict()
    return out


class Worker:
    """The single batch-serving thread (see module docstring)."""

    def __init__(self, queue: JobQueue, traces: Dict[str, TraceRef],
                 artifact_dir: str, bucket: int = 512, monitor=None,
                 table_cache_dir: str = "", compile_cache_dir: str = "",
                 linger_s: float = 0.05):
        self.queue = queue
        self.traces = dict(traces)
        self.artifact_dir = artifact_dir
        self.bucket = int(bucket)
        self.monitor = monitor  # MonitorServer (per-job /progress) or None
        self.table_cache_dir = table_cache_dir
        self.compile_cache_dir = compile_cache_dir
        self.linger_s = float(linger_s)  # batching window (JobQueue.next_batch)
        self._sims: dict = {}  # family_key -> Simulator
        self._shape_hw: dict = {}  # family_key -> (max pods, max events)
        self._sweep_fns: set = set()  # jitted sweep wrappers dispatched
        self.batches_run = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle ----

    def start(self) -> "Worker":
        from tpusim.sim.driver import enable_compile_cache

        enable_compile_cache(self.compile_cache_dir)
        self._thread = threading.Thread(
            target=self._loop, name="tpusim-svc-worker", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            batch = self.queue.next_batch(
                timeout=0.2, linger_s=self.linger_s
            )
            if batch:
                self.run_batch(batch)

    # ---- per-family simulator cache ----

    def _sim_for(self, job: Job):
        """The family's shared Simulator: one weight-operand engine, one
        table-cache entry, one typical-pod distribution for every tenant
        of the family."""
        from tpusim.sim.driver import Simulator, SimulatorConfig

        key = job.spec.family_key()
        sim = self._sims.get(key)
        if sim is None:
            trace = self.traces[job.spec.trace]
            cfg = SimulatorConfig(
                policies=job.spec.policies,
                gpu_sel_method=job.spec.gpu_sel,
                norm_method=job.spec.norm,
                dim_ext_method=job.spec.dim_ext,
                engine=job.spec.engine,
                report_per_event=False,
                shuffle_pod=False,
                seed=42,
                table_cache_dir=self.table_cache_dir,
            )
            sim = Simulator(trace.nodes, cfg)
            sim.set_workload_pods(trace.pods)
            sim.set_typical_pods()
            self._sims[key] = sim
        return sim

    # ---- the batch dispatch ----

    def run_batch(self, batch: List[Job]) -> None:
        """Serve one compatible batch through a single vmapped sweep.
        Public so smoke/tests can drive it synchronously."""
        self.queue.mark_running(batch)
        self._publish(batch, phase="running")
        try:
            lanes = self._dispatch(batch)
        except Exception as err:  # poisoned family: fail the jobs, live on
            msg = f"{type(err).__name__}: {err}"
            for job in batch:
                self.queue.mark_failed(job, msg)
                # terminal: drop the persisted spec so restart recovery
                # does not re-run the poisoned batch forever
                svc_jobs.delete_job_spec(self.artifact_dir, job.digest)
            self._publish(batch, phase="failed", error=msg)
            return
        for job, lane in zip(batch, lanes):
            result = summarize_lane(lane, job)
            svc_jobs.write_result(self.artifact_dir, job.digest, result)
            self.queue.mark_done(job, result)
            # terminal: the signed result is the durable record now
            svc_jobs.delete_job_spec(self.artifact_dir, job.digest)
        self.batches_run += 1
        self._publish(batch, phase="done")

    def _dispatch(self, batch: List[Job]):
        from tpusim.sim.driver import (
            _sweep_engine_multi,
            schedule_pods_sweep_multi,
        )

        if batch[0].spec.fault:
            return self._dispatch_chaos(batch)
        sim = self._sim_for(batch[0])
        key = batch[0].spec.family_key()
        # tag the shared heartbeat stream with this batch's lead job so
        # /progress keeps per-job windows apart (obs.heartbeat, ISSUE 7
        # satellite); the vmapped sweep itself strips in-scan heartbeats,
        # but chunked/standalone replays of the same sim honor it
        sim._hb_job = batch[0].id

        pods_list = [
            sim.prepare_pods(
                tuning_ratio=j.spec.tune, tuning_seed=j.spec.tune_seed
            )
            for j in batch
        ]
        weights = [list(j.spec.weights) for j in batch]
        seeds = [j.spec.seed for j in batch]
        # pad to the FIXED lane width by repeating the tail job: vmap's
        # axis size is jaxpr structure, so a short batch must not compile
        # its own executable; dead lanes are sliced off below
        n = len(batch)
        while len(weights) < self.queue.lane_width:
            pods_list.append(pods_list[-1])
            weights.append(weights[-1])
            seeds.append(seeds[-1])

        # sticky per-family shape floors (see module docstring): without
        # them a later batch of slightly smaller tuned traces would land
        # on a smaller padded shape and recompile. The event count is the
        # real build_events length under the family's event ordering
        # (sweep_multi builds the same streams right after — this extra
        # host-side O(P) pass per lane is noise next to the scan), not a
        # bound: an inflated floor would pad dead EV_SKIPs into every
        # future scan
        from tpusim.io.trace import build_events

        p_max = max(len(p) for p in pods_list)
        e_max = max(
            len(build_events(p, sim.cfg.use_timestamps)[0])
            for p in pods_list
        )
        hw_p, hw_e = self._shape_hw.get(key, (0, 0))
        hw_p, hw_e = max(hw_p, p_max), max(hw_e, e_max)
        self._shape_hw[key] = (hw_p, hw_e)

        sim._reset_run_state()
        lanes = schedule_pods_sweep_multi(
            sim, pods_list, np.asarray(weights, np.int32), seeds=seeds,
            bucket=self.bucket, min_pods=hw_p, min_events=hw_e,
        )[:n]
        # track the jitted sweep wrapper actually dispatched so /queue
        # can report the compiled-executable count (the PR 6
        # jit._cache_size() zero-recompile check, now a live metric)
        used_table = sim._last_engine.startswith("table")
        self._sweep_fns.add(_sweep_engine_multi(
            sim._table_fn.engine.replay if used_table
            else sim.replay_fn.engine,
            table=used_table,
        ))
        return lanes

    def _dispatch_chaos(self, batch: List[Job]):
        """Fault-job batches (ISSUE 10): ONE compiled chaos sweep — the
        family key pins one (trace, tune), so every lane replays the
        same base stream under its own fault schedule/weights/seed.
        Lane-vs-standalone bit-identity and the zero-recompile contract
        are the driver's (schedule_pods_sweep_faults)."""
        from tpusim.sim.driver import schedule_pods_sweep_faults

        sim = self._sim_for(batch[0])
        sim._hb_job = batch[0].id
        pods = sim.prepare_pods(
            tuning_ratio=batch[0].spec.tune,
            tuning_seed=batch[0].spec.tune_seed,
        )
        jobs = list(batch)
        n = len(batch)
        while len(jobs) < self.queue.lane_width:
            jobs.append(jobs[-1])  # tail-repeat padding (vmap axis size)
        weights = np.asarray(
            [list(j.spec.weights) for j in jobs], np.int32
        )
        seeds = [j.spec.seed for j in jobs]
        fault_specs = [j.spec.fault_config() for j in jobs]
        sim._reset_run_state()
        if sim.typical is None:
            sim.set_typical_pods()
        lanes = schedule_pods_sweep_faults(
            sim, pods, weights, fault_specs, seeds=seeds,
            bucket=self.bucket,
        )[:n]
        self._sweep_fns.add(sim._last_sweep_fn)
        return lanes

    # ---- introspection ----

    def sweep_executables(self) -> int:
        """Compiled sweep executables across every family served — the
        /queue `sweep_executables` field. Stable across batches differing
        only in weights/seeds/tunes (zero recompiles); grows only when a
        new job family or padded shape genuinely needs a new jaxpr."""
        return sum(fn._cache_size() for fn in self._sweep_fns)

    def _publish(self, batch: Sequence[Job], **fields) -> None:
        if self.monitor is None:
            return
        for job in batch:
            self.monitor.publish_job_progress(
                job.id,
                dict(fields, status=job.status, batch=job.batch,
                     lane=job.lane),
            )
