"""Worker: one batch-serving loop per process (ISSUE 7, fleet-grown in
ISSUE 12).

The worker drains the JobQueue batch by batch and dispatches each batch
through the multi-trace vmapped sweep (driver.schedule_pods_sweep_multi)
— so a whole batch of what-if jobs costs one compiled scan, and across
batches the one-jaxpr-per-family contract holds: per-family Simulators
are cached (sharing the weight-operand engines, the content-keyed table
cache entry, and the persistent compile cache), batches are padded to a
FIXED lane width (a 3-job batch repeats its tail job into the dead
lanes — vmap's axis size is jaxpr structure), and per-family pod/event
shape high-water marks are sticky (the driver's min_pods/min_events
floors), so consecutive batches differing only in weights/seeds/tune
factors — and, since ISSUE 12, fault schedules: the chaos dispatch
folded into the one path — reuse ONE compiled executable —
`jit._cache_size()` stable, the acceptance criterion.

Every batch runs under the lease protocol (ISSUE 12): run_batch stakes
signed lease files before dispatching, a LeaseKeeper renews them on
heartbeat ticks plus a fallback timer, and completion releases them —
so a `kill -9`'d worker's batch is steal-eligible after one lease. The
same Worker class serves both deployments: the single in-process thread
of PR 7 (claiming from the shared queue directly) and the fleet worker
process (svc.fleet.run_worker, claiming over HTTP with `renew_cb`
pointed at the coordinator).

Results are summarized host-side (placements, counters, gpu_alloc,
frag, a placements digest for cheap bit-identity checks), persisted as
digest-signed JSONL (svc.jobs.write_result), and marked on the queue.
A batch that raises marks its jobs failed and the worker keeps serving
— one poisoned job family must not take the service down.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from tpusim.svc import jobs as svc_jobs
from tpusim.svc import leases as svc_leases
from tpusim.svc.batcher import Job, JobQueue


class LeaseKeeper:
    """Renews a batch's leases while it is in flight (ISSUE 12): a
    fallback timer fires every lease_s/3, and heartbeat ticks from the
    scan poke an immediate renewal (the ISSUE's renew-on-heartbeat —
    the timer covers vmapped sweeps, whose builds strip the in-scan
    heartbeat). Each renewal rewrites the signed lease files AND calls
    `renew_cb(digests)` — the queue update in-process, an HTTP POST on
    a fleet worker. A renewal learning its leases were LOST (stolen
    after a stall) just logs: finishing anyway is harmless — the
    completion dedups."""

    def __init__(self, artifact_dir: str, worker_id: str, lease_s: float,
                 members: Sequence[str], renew_cb=None, out=None,
                 stake_cb=None, release_cb=None):
        self.artifact_dir = artifact_dir
        self.worker_id = worker_id
        self.lease_s = float(lease_s)
        self.members = [str(m) for m in members]
        self.renew_cb = renew_cb
        # remote-mode callbacks (ISSUE 13): a no-shared-fs worker cannot
        # write lease FILES into the coordinator's artifact dir, so
        # stake_cb(members)/release_cb(members) POST /leases instead and
        # the COORDINATOR writes/deletes its own signed files — the
        # on-disk mirror (adoption, reaping) is unchanged. None = the
        # shared-fs local file writes.
        self.stake_cb = stake_cb
        self.release_cb = release_cb
        self.out = out
        self._stop = threading.Event()
        self._poke = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.renewals = 0

    def renew_now(self) -> None:
        # ask the authority FIRST: a digest the coordinator reports lost
        # (stolen after a stall) now belongs to a thief whose lease file
        # this keeper must never overwrite again — nor delete at stop()
        # — so lost members leave the set before any file write
        if self.renew_cb is not None:
            try:
                lost = set(self.renew_cb(self.members))
            except Exception:
                lost = set()  # coordinator unreachable: keep staking;
                # it will steal if we really stall
            if lost:
                self.members = [m for m in self.members if m not in lost]
                if self.out is not None:
                    print(
                        f"[worker {self.worker_id}] lease(s) lost to a "
                        f"steal: "
                        f"{', '.join(str(x)[:12] for x in sorted(lost))}"
                        " — finishing anyway (duplicate completion "
                        "dedups)",
                        file=self.out,
                    )
        if self.stake_cb is not None:
            try:
                self.stake_cb(self.members)
            except Exception:
                pass  # coordinator unreachable mid-renewal: same story
                # as a lost renew_cb — keep computing, it will steal if
                # we really stall, and completion dedups
        else:
            deadline = time.time() + self.lease_s
            for d in self.members:
                svc_leases.write_lease(
                    self.artifact_dir, d, self.worker_id, os.getpid(),
                    deadline, self.members,
                )
        self.renewals += 1

    def on_heartbeat(self, _info) -> None:
        """obs.heartbeat listener: a live scan tick proves the worker is
        healthy — renew without waiting for the timer."""
        self._poke.set()

    def _loop(self) -> None:
        period = max(self.lease_s / 3.0, 0.05)
        last = time.time()
        while not self._stop.is_set():
            if self._poke.wait(period):
                self._poke.clear()
            if self._stop.is_set():
                return
            # heartbeat ticks can arrive many times a second — renewing
            # more often than period/3 is pure churn
            if time.time() - last >= period / 3.0:
                self.renew_now()
                last = time.time()

    def start(self) -> "LeaseKeeper":
        from tpusim.obs import heartbeat

        self.renew_now()  # the initial claim stake
        heartbeat.add_listener(self.on_heartbeat)
        self._thread = threading.Thread(
            target=self._loop, name="tpusim-lease-keeper", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, release: bool = True) -> None:
        from tpusim.obs import heartbeat

        self._stop.set()
        self._poke.set()
        heartbeat.remove_listener(self.on_heartbeat)
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None
        if release:
            if self.release_cb is not None:
                try:
                    self.release_cb(self.members)
                except Exception:
                    pass  # the coordinator's reaper cleans expired
                    # files anyway; a lost release is a timeout, not
                    # a leak
            else:
                for d in self.members:
                    svc_leases.delete_lease(self.artifact_dir, d)


@dataclass
class TraceRef:
    """One hosted trace: the cluster + workload every job of this ref
    replays, plus their content digest (part of every job digest). The
    CSV source paths ride along when load_trace built it — the fleet
    register handshake (ISSUE 12) hands them to joining workers, which
    re-load and digest-verify the trace themselves."""

    name: str
    nodes: list
    pods: list
    digest: str
    nodes_csv: str = ""
    pods_csv: str = ""
    max_pods: int = 0
    # per-FILE integrity (ISSUE 13): sha256 + size of the raw CSV bytes,
    # so a no-shared-fs worker can verify a (possibly resumed) download
    # before parsing, and resume partial transfers against a known size
    nodes_sha256: str = ""
    pods_sha256: str = ""
    nodes_bytes: int = 0
    pods_bytes: int = 0


def load_trace(name: str, nodes_csv: str, pods_csv: str,
               max_pods: int = 0) -> TraceRef:
    """Load a hosted trace from node/pod CSVs (`tpusim serve --jobs
    --nodes ... --pods ...`); max_pods > 0 truncates the workload (the
    smoke/prefix knob)."""
    from tpusim.io.storage import file_sha256
    from tpusim.io.trace import load_node_csv, load_pod_csv

    nodes = load_node_csv(nodes_csv)
    pods = load_pod_csv(pods_csv)
    if max_pods > 0:
        pods = pods[:max_pods]
    return TraceRef(
        name=name, nodes=nodes, pods=pods,
        digest=svc_jobs.trace_digest(nodes, pods),
        nodes_csv=os.path.abspath(nodes_csv),
        pods_csv=os.path.abspath(pods_csv),
        max_pods=int(max_pods),
        nodes_sha256=file_sha256(nodes_csv),
        pods_sha256=file_sha256(pods_csv),
        nodes_bytes=os.path.getsize(nodes_csv),
        pods_bytes=os.path.getsize(pods_csv),
    )


def local_caps() -> dict:
    """The capability tags this process declares in the fleet register
    handshake (ISSUE 17): accelerator backend + local device count
    (from jax when importable; cpu/1 otherwise — a handshake must never
    crash on a worker without the toolchain warm), approximate host
    memory, fault-lane support (every engine in this tree carries the
    chaos dispatch, so True unless an operator override says otherwise),
    and max_nodes (0 = no cluster-size ceiling). The coordinator routes
    claims against these tags (JobQueue.eligible)."""
    backend, devices = "cpu", 1
    try:
        import jax

        backend = str(jax.default_backend())
        devices = int(jax.local_device_count())
    except Exception:
        pass  # capability probing is best-effort, never fatal
    mem = 0
    try:
        mem = int(os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES"))
    except (ValueError, OSError, AttributeError):
        pass
    return {
        "backend": backend,
        "devices": devices,
        "memory_bytes": mem,
        "fault_lanes": True,
        "max_nodes": 0,
    }


def summarize_lane(lane, job: Job) -> dict:
    """SweepLane -> the persisted/HTTP result document: the shared
    per-lane term vocabulary (learn.objective.lane_terms — ONE code
    path, so a remote tuning client's terms_from_result reads back
    exactly what a local lane yields, the ISSUE 9 bit-identity
    contract) plus the job's identity fields and the full placements
    (i32 node per pod; -1 = unplaced; the terms' sha256 over
    placed_node+dev_mask makes bit-identity against a standalone run
    one string compare)."""
    from tpusim.learn.objective import lane_terms
    from tpusim.obs.counters import COUNTER_FIELDS

    out = lane_terms(lane)
    out.update({
        "job": job.digest,
        "trace": job.spec.trace,
        "policies": [list(p) for p in job.spec.policies],
        "weights": list(job.spec.weights),
        "seed": job.spec.seed,
        "tune": job.spec.tune,
        "placed_node": np.asarray(lane.placed_node, np.int32).tolist(),
    })
    if lane.counters is not None:
        out["counters"] = {
            f: int(c) for f, c in zip(COUNTER_FIELDS, lane.counters)
        }
    if lane.disruption is not None:
        # chaos lanes (ISSUE 10): the full DisruptionMetrics scalar
        # summary rides the result document beside the objective terms
        out["disruption"] = lane.disruption.as_dict()
    return out


class Worker:
    """The single batch-serving thread (see module docstring)."""

    def __init__(self, queue: JobQueue, traces: Dict[str, TraceRef],
                 artifact_dir: str, bucket: int = 512, monitor=None,
                 table_cache_dir: str = "", compile_cache_dir: str = "",
                 linger_s: float = 0.05, worker_id: str = "",
                 lease_files: bool = True):
        self.queue = queue
        self.traces = dict(traces)
        self.artifact_dir = artifact_dir
        self.bucket = int(bucket)
        self.monitor = monitor  # MonitorServer (per-job /progress) or None
        self.table_cache_dir = table_cache_dir
        self.compile_cache_dir = compile_cache_dir
        self.linger_s = float(linger_s)  # batching window (JobQueue.next_batch)
        # fleet identity (ISSUE 12): the id the lease files and the
        # /queue per-worker rows carry; in-process workers default to a
        # pid-scoped local id
        self.worker_id = str(worker_id) or f"local-{os.getpid()}"
        # lease files are the cross-process protocol; tests driving
        # run_batch synchronously can switch them off
        self.lease_files = bool(lease_files)
        self._sims: dict = {}  # family_key -> Simulator
        self._shape_hw: dict = {}  # family_key -> (max pods, max events)
        self._sweep_fns: set = set()  # jitted sweep wrappers dispatched
        self._waves: dict = {}  # family_key -> svc.waves.ForkWave
        self.batches_run = 0
        self.last_dispatch_s = 0.0  # wall of the newest run_batch
        self.first_dispatch_s = 0.0  # wall of the FIRST (compile) batch
        # lease renewal sink: digests -> lost list. In-process workers
        # renew the shared queue directly; a fleet worker (svc.fleet)
        # swaps in the coordinator's POST /workers/renew.
        self.renew_cb = lambda ds: self.queue.renew(self.worker_id, ds)[1]
        # remote-mode lease plane (ISSUE 13): svc.fleet.run_worker wires
        # these at POST /leases when the worker shares no filesystem
        # with the coordinator; None keeps the local signed-file writes
        self.lease_stake_cb = None
        self.lease_release_cb = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle ----

    def start(self) -> "Worker":
        from tpusim.sim.driver import enable_compile_cache

        enable_compile_cache(self.compile_cache_dir)
        self._thread = threading.Thread(
            target=self._loop, name="tpusim-svc-worker", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            # reap orphans first: with several in-process workers on one
            # queue, any live worker's idle pass reclaims expired leases
            self.queue.steal_expired()
            batch = self.queue.claim_batch(
                self.worker_id, timeout=0.2, linger_s=self.linger_s
            )
            if batch:
                self.run_batch(batch)

    # ---- per-family simulator cache ----

    def _sim_for(self, job: Job):
        """The family's shared Simulator: one weight-operand engine, one
        table-cache entry, one typical-pod distribution for every tenant
        of the family."""
        from tpusim.sim.driver import Simulator, SimulatorConfig

        key = job.spec.family_key()
        sim = self._sims.get(key)
        if sim is None:
            trace = self.traces[job.spec.trace]
            cfg = SimulatorConfig(
                policies=job.spec.policies,
                gpu_sel_method=job.spec.gpu_sel,
                norm_method=job.spec.norm,
                dim_ext_method=job.spec.dim_ext,
                engine=job.spec.engine,
                report_per_event=False,
                shuffle_pod=False,
                seed=42,
                table_cache_dir=self.table_cache_dir,
            )
            sim = Simulator(trace.nodes, cfg)
            sim.set_workload_pods(trace.pods)
            sim.set_typical_pods()
            self._sims[key] = sim
        # tag scans with this worker's id (obs.heartbeat, ISSUE 12): a
        # fleet's /progress streams say WHICH worker is scanning
        sim._hb_worker = self.worker_id
        return sim

    # ---- the batch dispatch ----

    def run_batch(self, batch: List[Job]) -> None:
        """Serve one compatible batch, under the lease protocol
        (ISSUE 12): signed lease files are staked before dispatch,
        renewed while the scan runs (heartbeat ticks + the fallback
        timer), and released on completion — a `kill -9` mid-batch
        leaves expired leases any live worker can steal. Three routes
        (family keys keep them unmixed): base jobs advance their trace
        once through the chunked path and persist the checkpoint ladder
        + fork-index entry; fork/full jobs ride the family's continuous
        ForkWave (late arrivals JOIN it at chunk boundaries, so
        all_jobs can outgrow the claimed batch); everything else is the
        vmapped sweep. Public so smoke/tests can drive it
        synchronously."""
        self.queue.mark_running(batch)
        self._publish(batch, phase="running")
        members = [j.digest for j in batch]
        keeper = None
        if self.lease_files:
            keeper = LeaseKeeper(
                self.artifact_dir, self.worker_id, self.queue.lease_s,
                members, renew_cb=self.renew_cb,
                stake_cb=self.lease_stake_cb,
                release_cb=self.lease_release_cb,
            ).start()
        all_jobs = list(batch)  # grows when joiners enter a fork wave
        t0 = time.perf_counter()
        try:
            if batch[0].spec.base:
                for job in batch:
                    job.dispatched_unix = time.time()
                    self._run_base(job)
            elif batch[0].spec.fork:
                self._run_fork_wave(batch, keeper, all_jobs)
            else:
                now = time.time()
                for job in batch:
                    job.dispatched_unix = now
                lanes = self._dispatch(batch)
                for job, lane in zip(batch, lanes):
                    self._complete(job, lane)
        except Exception as err:  # poisoned family: fail the jobs, live on
            msg = f"{type(err).__name__}: {err}"
            undone = [j for j in all_jobs if j.status != "done"]
            for job in undone:
                self.queue.mark_failed(job, msg)
                # terminal: drop the persisted spec so restart recovery
                # does not re-run the poisoned batch forever
                svc_jobs.delete_job_spec(self.artifact_dir, job.digest)
            if keeper is not None:
                keeper.stop(release=True)
            self._publish(undone, phase="failed", error=msg)
            return
        self.last_dispatch_s = time.perf_counter() - t0
        if self.batches_run == 0:
            self.first_dispatch_s = self.last_dispatch_s
        if keeper is not None:
            keeper.stop(release=True)
        self.batches_run += 1
        self._publish(all_jobs, phase="done")

    def _complete(self, job: Job, lane, fork_meta: Optional[dict] = None,
                  base_meta: Optional[dict] = None) -> None:
        """One job's terminal bookkeeping: summarize, persist the signed
        result, mark done, drop the spec. Fork/base serving telemetry
        rides the result document (`result["fork"]` / `result["base_run"]`
        — what the latency gate and what-if clients read)."""
        result = summarize_lane(lane, job)
        if fork_meta is not None:
            result["fork"] = dict(fork_meta)
        if base_meta is not None:
            result["base_run"] = dict(base_meta)
        svc_jobs.write_result(self.artifact_dir, job.digest, result)
        self.queue.mark_done(job, result)
        # terminal: the signed result is the durable record now
        svc_jobs.delete_job_spec(self.artifact_dir, job.digest)

    def _dispatch(self, batch: List[Job]):
        """ONE dispatch path for fault-free AND fault batches (the
        ISSUE 12 fold): every batch rides schedule_pods_sweep_multi, and
        a fault family simply adds per-lane fault schedules — compiled
        against each lane's OWN tuned stream — as operands. Mixed
        fault/tune/weight jobs of one family therefore share one
        compiled scan (the family key no longer pins a tune factor for
        fault jobs)."""
        from tpusim.sim.driver import schedule_pods_sweep_multi

        sim = self._sim_for(batch[0])
        key = batch[0].spec.family_key()
        # tag the shared heartbeat stream with this batch's lead job so
        # /progress keeps per-job windows apart (obs.heartbeat, ISSUE 7
        # satellite); the vmapped sweep itself strips in-scan heartbeats,
        # but chunked/standalone replays of the same sim honor it
        sim._hb_job = batch[0].id

        pods_list = [
            sim.prepare_pods(
                tuning_ratio=j.spec.tune, tuning_seed=j.spec.tune_seed
            )
            for j in batch
        ]
        weights = [list(j.spec.weights) for j in batch]
        seeds = [j.spec.seed for j in batch]
        faulted = bool(batch[0].spec.fault)
        fault_specs = (
            [j.spec.fault_config() for j in batch] if faulted else None
        )
        # pad to the FIXED lane width by repeating the tail job: vmap's
        # axis size is jaxpr structure, so a short batch must not compile
        # its own executable; dead lanes are sliced off below. The tail's
        # PREPARED pods (and compiled fault plan, via the driver's plan
        # cache) are reused, not recomputed per dead lane.
        n = len(batch)
        while len(weights) < self.queue.lane_width:
            pods_list.append(pods_list[-1])
            weights.append(weights[-1])
            seeds.append(seeds[-1])
            if fault_specs is not None:
                fault_specs.append(fault_specs[-1])

        # sticky per-family shape floors (see module docstring): without
        # them a later batch of slightly smaller tuned traces would land
        # on a smaller padded shape and recompile. The event count is the
        # real build_events length under the family's event ordering
        # (sweep_multi builds the same streams right after — this extra
        # host-side O(P) pass per lane is noise next to the scan), not a
        # bound: an inflated floor would pad dead EV_SKIPs into every
        # future scan. Fault families additionally keep their merged-
        # stream/draw-table/capacity floors on the Simulator itself
        # (sim._chaos_hw, the schedule_pods_sweep_faults discipline).
        from tpusim.io.trace import build_events

        p_max = max(len(p) for p in pods_list)
        e_max = max(
            len(build_events(p, sim.cfg.use_timestamps)[0])
            for p in pods_list
        )
        hw_p, hw_e = self._shape_hw.get(key, (0, 0))
        hw_p, hw_e = max(hw_p, p_max), max(hw_e, e_max)
        self._shape_hw[key] = (hw_p, hw_e)

        sim._reset_run_state()
        if sim.typical is None:
            sim.set_typical_pods()
        lanes = schedule_pods_sweep_multi(
            sim, pods_list, np.asarray(weights, np.int32), seeds=seeds,
            bucket=self.bucket, min_pods=hw_p, min_events=hw_e,
            fault_specs=fault_specs,
        )[:n]
        # track the jitted sweep wrapper actually dispatched so /queue
        # can report the compiled-executable count (the PR 6
        # jit._cache_size() zero-recompile check, now a live metric).
        # Both paths record the wrapper on the sim (the fault tail
        # always did; the plain path joined it when donate_streams made
        # the wrapper choice depend on the report flag, ISSUE 15) — so
        # the count follows the wrapper ACTUALLY dispatched
        self._sweep_fns.add(sim._last_sweep_fn)
        return lanes

    # ---- the warm-state serving plane (ISSUE 16) ----

    def _chunked_sim(self, job: Job):
        """The exact-replay Simulator a base run or fork wave executes
        on. Unlike the sweep cache (weights/seeds are vmap operands
        there), the chunked path bakes THIS job's weights into
        cfg.policies and THIS job's seed into cfg.seed — both feed the
        run digest its checkpoints are content-addressed under, which
        is precisely how a weight-changing fork can never match a base
        checkpoint. Cached per (family, weights, seed); forks of one
        base all share one entry because the fork index pins their
        weights/seed to the base's."""
        from tpusim.sim.driver import Simulator, SimulatorConfig
        from tpusim.svc import forks as svc_forks

        spec = job.spec
        key = (spec.family_key(), tuple(spec.weights), int(spec.seed))
        sim = self._sims.get(key)
        if sim is None:
            trace = self.traces[spec.trace]
            cfg = SimulatorConfig(
                policies=tuple(
                    (name, int(w))
                    for (name, _), w in zip(spec.policies, spec.weights)
                ),
                gpu_sel_method=spec.gpu_sel,
                norm_method=spec.norm,
                dim_ext_method=spec.dim_ext,
                # forced off "auto": only the table engine has the
                # chunked carry surface the checkpoint ladder rides
                engine="table",
                report_per_event=False,
                shuffle_pod=False,
                seed=int(spec.seed),
                table_cache_dir=self.table_cache_dir,
                checkpoint_dir=svc_forks.checkpoint_dir(self.artifact_dir),
                checkpoint_keep=-1,  # base ladders must survive the run
            )
            sim = Simulator(trace.nodes, cfg)
            sim.set_workload_pods(trace.pods)
            sim.set_typical_pods()
            self._sims[key] = sim
        sim._hb_worker = self.worker_id
        sim._hb_job = job.id
        return sim

    def _checkpoint_every(self, events: int) -> int:
        """Base-run chunk length: ~32 rungs across the trace, capped at
        the serving bucket. The fork latency bound is `tail + one
        chunk`, so shorter chunks mean warmer forks AND more wave steps
        for a full replay — the p99 separation the latency gate
        enforces; 32 keeps the per-base checkpoint count (and the base
        run's write overhead) modest."""
        return max(1, min(self.bucket, -(-int(events) // 32)))

    def _run_base(self, job: Job) -> None:
        """Advance one base trace through the chunked table path,
        persisting every mid-trace carry (checkpoint_keep=-1) and the
        fork-index entry that makes the ladder discoverable."""
        from tpusim.io.trace import build_events
        from tpusim.sim.driver import _bucket_sizes, lane_from_run
        from tpusim.svc import forks as svc_forks

        spec = job.spec
        sim = self._chunked_sim(job)
        prep = sim.prepare_pods(
            tuning_ratio=spec.tune, tuning_seed=spec.tune_seed
        )
        e = len(build_events(prep, sim.cfg.use_timestamps)[0])
        sim.cfg.checkpoint_every = self._checkpoint_every(e)
        sim._reset_run_state()
        sim.schedule_pods(prep)
        p = len(prep)
        # the replay padded events up to the bucket geometry: correct
        # the skip counter exactly like the sweep path does
        _, e2 = _bucket_sizes(p, e, 512)
        lane = lane_from_run(
            sim, spec.weights, spec.seed, pad_skips=e2 - e
        )
        svc_forks.write_base_entry(
            self.artifact_dir, job.digest, sim.last_run_digest,
            sim.cfg.checkpoint_every, e, p,
            svc_jobs.spec_to_payload(spec),
        )
        meta = {
            "run_digest": str(sim.last_run_digest),
            "checkpoint_every": int(sim.cfg.checkpoint_every),
            "events": int(e),
            "pods": int(p),
        }
        self._complete(job, lane, base_meta=meta)

    def _fork_wave_for(self, job: Job):
        """The family's ForkWave (one ChunkWave = three jitted entries,
        shared by every fork of the base — the zero-recompile census).
        The chunk length comes from the base's fork-index entry so lane
        restore cursors land exactly on the base ladder's rungs; a
        missing entry (fleet worker without the coordinator's artifact
        dir) falls back to the same derivation the base used — forks
        then degrade per-lane to full replay, loudly."""
        from tpusim.sim.driver import ChunkWave
        from tpusim.svc import forks as svc_forks
        from tpusim.svc.waves import ForkWave

        key = job.spec.family_key()
        fw = self._waves.get(key)
        if fw is None:
            spec = job.spec
            sim = self._chunked_sim(job)
            prep = sim.prepare_pods(
                tuning_ratio=spec.tune, tuning_seed=spec.tune_seed
            )
            entry = svc_forks.load_base_entry(
                self.artifact_dir, spec.fork[0]
            )
            if entry is not None:
                chunk = int(entry["checkpoint_every"])
            else:
                from tpusim.io.trace import build_events

                e = len(build_events(prep, sim.cfg.use_timestamps)[0])
                chunk = self._checkpoint_every(e)
            wave = ChunkWave(
                sim, prep, lanes=self.queue.lane_width, chunk=chunk
            )
            fw = ForkWave(wave, monitor=self.monitor, out=sys.stderr)
            self._waves[key] = fw
        return fw

    def _run_fork_wave(self, batch: List[Job], keeper,
                       all_jobs: List[Job]) -> None:
        """Serve one fork family's batch through its continuous
        ForkWave: claimed jobs fill lanes, and at every chunk boundary
        the wave pulls MORE queued jobs of the family off the queue
        (claim_family) — the late arrival joins the running wave instead
        of waiting behind it. Joiners enter the lease set (and all_jobs,
        so the poisoned-batch path fails them too)."""
        fw = self._fork_wave_for(batch[0])
        fw.wave.sim._hb_job = batch[0].id
        key = batch[0].spec.family_key()

        def claim_more(n: int) -> List[Job]:
            if n <= 0:
                return []
            got = self.queue.claim_family(self.worker_id, key, n)
            if got:
                self.queue.mark_running(got)
                all_jobs.extend(got)
                if keeper is not None:
                    keeper.members.extend(j.digest for j in got)
                    keeper.renew_now()
            return got

        def on_join(job: Job) -> None:
            if not job.dispatched_unix:
                job.dispatched_unix = time.time()
            self._publish([job], phase="running")

        def on_done(job: Job, lane, meta: dict) -> None:
            self._complete(job, lane, fork_meta=meta)

        fw.serve(
            batch, claim_more=claim_more, on_join=on_join,
            on_done=on_done,
        )

    # ---- introspection ----

    def wave_executables(self) -> int:
        """Compiled executables across every ForkWave served (step +
        scatter + finish per family) — stable across fork waves AND
        boundary joins, the serve-latency gate's zero-recompile
        check."""
        return sum(fw.executables() for fw in self._waves.values())

    def wave_stats(self) -> dict:
        """Continuous-batching counters for /queue."""
        return {
            "families": len(self._waves),
            "waves_run": sum(f.waves_run for f in self._waves.values()),
            "joins": sum(f.joins for f in self._waves.values()),
            "degrades": sum(f.degrades for f in self._waves.values()),
            "executables": self.wave_executables(),
        }

    def sweep_executables(self) -> int:
        """Compiled sweep executables across every family served — the
        /queue `sweep_executables` field. Stable across batches differing
        only in weights/seeds/tunes (zero recompiles); grows only when a
        new job family or padded shape genuinely needs a new jaxpr."""
        return sum(fn._cache_size() for fn in self._sweep_fns)

    def _publish(self, batch: Sequence[Job], **fields) -> None:
        if self.monitor is None:
            return
        for job in batch:
            self.monitor.publish_job_progress(
                job.id,
                dict(fields, status=job.status, batch=job.batch,
                     lane=job.lane),
            )
