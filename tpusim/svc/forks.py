"""Checkpoint-keyed fork index of the what-if serving plane (ISSUE 16).

A BASE job ({"base": true, ...}) advances its trace once through the
chunked table path with `checkpoint_keep=-1`, leaving every mid-trace
carry on disk as a content-addressed checkpoint (the PR 2 discipline:
`<run digest>.e<cursor>.ckpt.npz`). This module persists the small
durable record that makes those checkpoints *discoverable* by later
fork jobs — the fork index entry:

  <base job digest>.base.json     (digest-signed JSON, atomic write)

mapping the base JOB digest (the handle clients hold) to the base RUN
digest (the content key the checkpoint files are named under), plus the
replay geometry a fork needs to reproduce the base's padded shapes
(events, pods, checkpoint_every) and the base's full spec payload — the
vocabulary the serving endpoint merges into fork submissions so a fork
is BY CONSTRUCTION the same replay as its base up to the divergence
event (same trace, policies, weights, seed, knobs). A fork that tries
to change weights changes operand bytes, changes the run digest, and
finds no checkpoint — the index makes that rejection loud at submit
time instead of a silent cold replay.

Entries are tiny, content-addressed, and idempotent to rewrite; a torn
or foreign entry is deleted and treated as missing (the base run can
always be re-submitted — content addressing makes recomputation safe).
"""

from __future__ import annotations

import os
from typing import Optional

BASE_SCHEMA = "tpusim-svc-base/1"
BASE_SUFFIX = ".base.json"

# svc checkpoint landing zone, shared by base writers and fork readers
CHECKPOINT_SUBDIR = "checkpoints"


def checkpoint_dir(artifact_dir: str) -> str:
    return os.path.join(artifact_dir, CHECKPOINT_SUBDIR)


def base_entry_path(artifact_dir: str, digest: str) -> str:
    return os.path.join(artifact_dir, f"{digest}{BASE_SUFFIX}")


def write_base_entry(artifact_dir: str, digest: str, run_digest: str,
                     every: int, events: int, pods: int,
                     spec_payload: dict) -> str:
    """Persist one finished base run's fork-index entry (atomic,
    signed). `digest` is the base JOB digest; `run_digest` is the
    driver's content key its checkpoint files are named under."""
    from tpusim.io.storage import write_signed_json

    os.makedirs(artifact_dir, exist_ok=True)
    return write_signed_json(
        base_entry_path(artifact_dir, digest),
        {"schema": BASE_SCHEMA, "job": digest},
        {
            "run_digest": str(run_digest),
            "checkpoint_every": int(every),
            "events": int(events),
            "pods": int(pods),
            "spec": spec_payload,
        },
    )


def load_base_entry(artifact_dir: str, digest: str) -> Optional[dict]:
    """The fork-index entry for a base JOB digest, or None. Torn /
    foreign / digest-mismatched files are deleted and treated as
    missing — the serving endpoint then answers 400 ("base not
    finished") and the client re-runs the base."""
    from tpusim.io.storage import read_signed_json

    path = base_entry_path(artifact_dir, digest)
    if not os.path.isfile(path):
        return None
    try:
        header, doc = read_signed_json(path, BASE_SCHEMA)
        if (header.get("job") != digest or not isinstance(doc, dict)
                or not isinstance(doc.get("spec"), dict)
                or not doc.get("run_digest")):
            raise ValueError("foreign or malformed base entry")
        return doc
    except (OSError, ValueError):
        try:
            os.unlink(path)
        except OSError:
            pass
        return None


def nearest_checkpoint(ck_dir: str, run_digest: str,
                       fork_event: int) -> Optional[int]:
    """Cursor of the newest persisted base checkpoint at-or-before the
    divergence event, or None — the fork index's core lookup. Purely a
    directory listing: no file is opened, nothing is deleted (torn
    files are the LOADER's problem, and the loader walks back)."""
    from tpusim.io.storage import iter_checkpoints

    for cursor, _ in iter_checkpoints(ck_dir, run_digest):
        if cursor <= int(fork_event):
            return cursor
    return None
