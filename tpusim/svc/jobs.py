"""Job plane of the what-if replay service (ISSUE 7).

A job is one what-if replay request over a trace the service hosts: the
policy family, a weight vector, a seed, and the gpu-sel/tune knobs —
exactly the axes the reference grids with a process per experiment
(1020 replays, experiments/README.md) and the config-axis sweep
(ISSUE 6) turned into traced operands. Everything else about a job is
derived:

  digest   content key via io.storage.checkpoint_digest — the engine-
           source salt + the trace content digest + the canonical spec
           tuple. Two identical submissions share one digest, so the
           second is answered from the result cache without touching
           the device (the dedup contract), and any code change makes
           every old result silently miss instead of serving stale
           placements (the checkpoint-vocabulary discipline).
  family   the batching compatibility key: jobs sharing (trace, policy
           names, gpu_sel, norm, dim_ext, engine) run ONE jaxpr — their
           weights/seeds/tune factors are sweep operands — so the
           batcher packs them onto a single compiled scan.

Results persist as digest-signed JSONL files in the artifact dir
(io.storage.write_signed_jsonl — the decisions-file torn-write
discipline, ISSUE 4): `<job digest>.result.jsonl`, atomic rename,
payload digest verified on read.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

from tpusim.policies import POLICY_NAMES, is_policy_name

RESULT_SCHEMA = "tpusim-svc-result/1"
RESULT_SUFFIX = ".result.jsonl"

ENGINES = ("auto", "table", "sequential")

# every key a job document may carry — unknown keys are rejected loudly
# (a typo'd "wieghts" must not silently become a default-weight replay)
JOB_KEYS = frozenset((
    "trace", "policies", "weights", "seed", "gpu_sel", "norm", "dim_ext",
    "tune", "tune_seed", "engine", "fault", "base", "fork",
))

# the per-job fork document's vocabulary (ISSUE 16): a what-if job that
# shares a BASE run's history up to a divergence event and replays only
# its own tail from the nearest persisted carry. `mode="full"` forces
# the from-event-0 replay of the SAME divergent stream — the A/B twin
# the latency SLO and bit-identity checks compare against (a distinct
# digest, so the comparison is never answered from the fork's cache).
FORK_FIELDS = ("base", "event", "tail", "mode")
FORK_MODES = ("fork", "full")

# tail event kinds a fork may inject — EV_CREATE/EV_DELETE from
# tpusim.sim.engine, spelled as ints so validation stays jax-free
# (pinned against the engine constants by tests/test_fork.py)
FORK_EV_KINDS = (0, 1)

# the per-job fault document's vocabulary == FaultConfig's fields
# (tpusim.sim.faults); canonical order for the spec tuple
FAULT_FIELDS = (
    "mtbf_events", "mttr_events", "evict_every_events", "seed",
    "max_retries", "backoff_base", "backoff_cap", "queue_capacity",
)

DEFAULT_POLICIES = (("FGDScore", 1000),)


@dataclass(frozen=True)
class JobSpec:
    """One validated what-if replay request (all fields hashable — the
    spec tuple is the digest's canonical form)."""

    trace: str = "default"
    policies: Tuple[Tuple[str, int], ...] = DEFAULT_POLICIES
    weights: Tuple[int, ...] = ()  # resolved vector, len == len(policies)
    seed: int = 42
    gpu_sel: str = "best"
    norm: str = "max"
    dim_ext: str = "share"
    tune: float = 0.0  # workload tuning ratio (0 = untuned trace)
    tune_seed: int = 233
    engine: str = "auto"
    # fault what-if (ISSUE 10): the FaultConfig values in FAULT_FIELDS
    # order, or () for a fault-free replay. A sweep OPERAND like
    # weights/seed/tune — fault jobs batch onto one compiled chaos scan.
    fault: Tuple = ()
    # base-run flag (ISSUE 16): advance this trace ONCE through the
    # chunked table path, persisting every mid-trace carry as a fork
    # source — the warm state that what-if forks restore from.
    base: bool = False
    # fork what-if (ISSUE 16): (base job digest, divergence event,
    # mode, ((kind, pod), ...) tail), or () for a plain replay. The
    # base digest keys the family so fork waves share one compiled
    # chunk; mode "full" pins the from-event-0 A/B twin.
    fork: Tuple = ()

    def family_key(self) -> tuple:
        """Batching compatibility key — everything that shapes the
        compiled sweep's jaxpr. Weights, seed, tune factor, and the
        fault schedule are traced operands (ISSUE 6/7/10), so jobs
        differing only in them pack onto one compiled scan. One
        exception remains: fault jobs batch separately from fault-free
        ones (the fault build is a different jaxpr). The tune pinning
        fault batches used to carry is gone (ISSUE 12): the merged
        fault stream is a per-lane operand of the multi-trace sweep, so
        mixed fault/tune/weight jobs ride one compiled scan.

        Fork jobs (ISSUE 16) batch per BASE run — their lanes share the
        base's restored carry and padded geometry, so the base digest
        joins the key (mode does not: the "full" A/B twin rides the
        same wave). Base jobs run standalone chunked replays, never a
        sweep, so each is its own family. Plain jobs keep the exact
        historical 7-tuple (`+ ()` is identity)."""
        marker: tuple = ()
        if self.fork:
            marker = (("fork", self.fork[0]),)
        elif self.base:
            marker = (("base",),)
        return (
            self.trace, tuple(n for n, _ in self.policies),
            self.gpu_sel, self.norm, self.dim_ext, self.engine,
            bool(self.fault),
        ) + marker

    def family_label(self) -> str:
        """Human/JSON-friendly rendering of family_key — the per-family
        admission-quota surface in /queue and the QuotaFull 429 body
        (ISSUE 12)."""
        parts = [
            self.trace, "+".join(n for n, _ in self.policies),
            self.gpu_sel, self.norm, self.dim_ext, self.engine,
            "fault" if self.fault else "nofault",
        ]
        if self.fork:
            parts.append(f"fork:{str(self.fork[0])[:12]}")
        elif self.base:
            parts.append("base")
        return "|".join(parts)

    def canonical(self) -> tuple:
        """The digest's canonical form: every field, deterministic order,
        tune as a repr-stable float. base/fork markers append only when
        set — every pre-ISSUE-16 job digest (and its cached result) is
        unchanged."""
        return (
            self.trace, self.policies, self.weights, self.seed,
            self.gpu_sel, self.norm, self.dim_ext, float(self.tune),
            self.tune_seed, self.engine,
        ) + ((self.fault,) if self.fault else ()) \
          + (("base",) if self.base else ()) \
          + ((("fork",) + self.fork,) if self.fork else ())

    def fault_config(self):
        """The job's FaultConfig, or None for a fault-free replay."""
        if not self.fault:
            return None
        from tpusim.sim.faults import FaultConfig

        return FaultConfig(**dict(zip(FAULT_FIELDS, self.fault)))


def validate_job(payload: dict) -> JobSpec:
    """Job document -> JobSpec, failing loudly (ValueError with a usable
    message) on anything malformed — the 400 surface of POST /jobs."""
    if not isinstance(payload, dict):
        raise ValueError(
            f"job must be a JSON object, got {type(payload).__name__}"
        )
    if "policy_preset" in payload:
        # presets are a SERVICE-side vocabulary (ISSUE 14): the serving
        # JobService expands the name into policies before validation
        # (expand_policy_preset), so a preset key reaching here means
        # the service has no such preset registered — or the caller
        # bypassed the service entirely
        raise ValueError(
            "policy_preset is expanded by the serving endpoint (serve "
            "--policy-preset NAME=artifact.json); this service has no "
            f"preset named {payload.get('policy_preset')!r}"
        )
    unknown = set(payload) - JOB_KEYS
    if unknown:
        raise ValueError(
            f"unknown job key(s) {sorted(unknown)} (known: "
            f"{sorted(JOB_KEYS)})"
        )

    raw_pol = payload.get("policies", [list(p) for p in DEFAULT_POLICIES])
    if (
        not isinstance(raw_pol, (list, tuple)) or not raw_pol
        or not all(
            isinstance(p, (list, tuple)) and len(p) == 2
            and isinstance(p[0], str) for p in raw_pol
        )
    ):
        raise ValueError(
            'policies must be a non-empty list of [name, weight] pairs, '
            f"got {raw_pol!r}"
        )
    policies = []
    for name, w in raw_pol:
        if not is_policy_name(name):
            raise ValueError(
                f"unknown policy {name!r} (known: "
                f"{', '.join(POLICY_NAMES)}, LearnedScore[<feature>])"
            )
        policies.append((name, _as_int(w, f"policies[{name}] weight")))

    weights = payload.get("weights")
    if weights is None:
        weights = [w for _, w in policies]
    if not isinstance(weights, (list, tuple)) or len(weights) != len(policies):
        raise ValueError(
            f"weights must list one integer per policy "
            f"({len(policies)} expected), got {weights!r}"
        )
    weights = tuple(_as_int(w, f"weights[{i}]") for i, w in enumerate(weights))

    engine = str(payload.get("engine", "auto"))
    if engine not in ENGINES:
        raise ValueError(
            f"engine must be one of {ENGINES}, got {engine!r} (pallas has "
            "no batched sweep form)"
        )
    # the scheduler-config vocabulary (config.scheduler._validate_methods):
    # an unknown method string would not fail downstream — sim.step's
    # gpu_sel dispatch falls through to a default branch — so a typo'd
    # 'bets' would run, return plausibly-wrong placements, and cache them
    # under the typo'd digest. Same fail-loudly bar as the key check.
    gpu_sel = str(payload.get("gpu_sel", "best"))
    if gpu_sel not in ("best", "worst", "random") + tuple(POLICY_NAMES):
        raise ValueError(
            f"gpu_sel must be best | worst | random | a score-plugin "
            f"name, got {gpu_sel!r}"
        )
    norm = str(payload.get("norm", "max"))
    if norm not in ("node", "pod", "max"):
        raise ValueError(f"norm must be node | pod | max, got {norm!r}")
    dim_ext = str(payload.get("dim_ext", "share"))
    if dim_ext not in ("merge", "share", "divide", "extend"):
        raise ValueError(
            f"dim_ext must be merge | share | divide | extend, got "
            f"{dim_ext!r}"
        )
    tune = payload.get("tune", 0.0)
    try:
        tune = float(tune)
    except (TypeError, ValueError):
        raise ValueError(f"tune must be a number, got {tune!r}")
    if tune < 0:
        raise ValueError(f"tune must be >= 0, got {tune}")

    fault = payload.get("fault")
    fault_t: Tuple = ()
    if fault is not None:
        if not isinstance(fault, dict):
            raise ValueError(
                f"fault must be an object of FaultConfig fields "
                f"({', '.join(FAULT_FIELDS)}), got {fault!r}"
            )
        unknown = set(fault) - set(FAULT_FIELDS)
        if unknown:
            raise ValueError(
                f"unknown fault key(s) {sorted(unknown)} (known: "
                f"{sorted(FAULT_FIELDS)})"
            )
        from tpusim.sim.faults import FaultConfig

        fc = FaultConfig(**fault)
        if fc.mtbf_events <= 0 and fc.evict_every_events <= 0:
            raise ValueError(
                "fault needs mtbf_events > 0 or evict_every_events > 0 "
                "(an empty schedule is a fault-free job — drop the key)"
            )
        fault_t = tuple(
            float(getattr(fc, f)) if f.endswith("_events")
            else int(getattr(fc, f))
            for f in FAULT_FIELDS
        )

    base = payload.get("base", False)
    if not isinstance(base, bool):
        raise ValueError(f"base must be a boolean, got {base!r}")
    fork = payload.get("fork")
    fork_t: Tuple = ()
    if fork is not None:
        fork_t = _validate_fork(fork)
    if base and fork_t:
        raise ValueError(
            "base excludes fork: a base run IS the shared history forks "
            "restore from — fork it in a second job"
        )
    if (base or fork_t) and fault_t:
        raise ValueError(
            "base/fork exclude fault: the fault lane's retry carry has "
            "no checkpoint surface yet — run fault what-ifs as plain "
            "jobs"
        )
    if (base or fork_t) and engine == "sequential":
        raise ValueError(
            "base/fork need the chunked carry surface — engine must be "
            "auto or table, not sequential"
        )

    return JobSpec(
        fault=fault_t,
        base=base,
        fork=fork_t,
        trace=str(payload.get("trace", "default")),
        policies=tuple(policies),
        weights=weights,
        seed=_as_int(payload.get("seed", 42), "seed"),
        gpu_sel=gpu_sel,
        norm=norm,
        dim_ext=dim_ext,
        tune=tune,
        tune_seed=_as_int(payload.get("tune_seed", 233), "tune_seed"),
        engine=engine,
    )


def expand_policy_preset(payload: dict, presets: dict) -> dict:
    """Replace a job document's `policy_preset` reference with the named
    preset's [(name, weight)] pairs (ISSUE 14, `serve --policy-preset`).
    Returns a NEW payload (the caller's document is not mutated — it may
    be persisted/retried verbatim). A preset excludes explicit policies/
    weights: the preset IS the scoring family, and letting weights
    override it would serve a different model under the preset's name."""
    if not isinstance(payload, dict) or "policy_preset" not in payload:
        return payload
    name = payload["policy_preset"]
    if not isinstance(name, str):
        # a list/dict here would TypeError out of dict.get -> a 500 the
        # retry vocabulary treats as transient; malformed shapes must be
        # clean 400s like every other bad-job field
        raise ValueError(
            f"policy_preset must be a preset NAME string, got "
            f"{type(name).__name__}"
        )
    pairs = (presets or {}).get(name)
    if pairs is None:
        raise ValueError(
            f"unknown policy preset {name!r} (registered: "
            f"{', '.join(sorted(presets or {})) or 'none'})"
        )
    if "policies" in payload or "weights" in payload:
        raise ValueError(
            "policy_preset excludes explicit policies/weights (the "
            "preset IS the scoring family)"
        )
    out = {k: v for k, v in payload.items() if k != "policy_preset"}
    out["policies"] = [[str(n), int(w)] for n, w in pairs]
    return out


def _as_int(v, what: str) -> int:
    if isinstance(v, bool) or not isinstance(v, int):
        raise ValueError(f"{what} must be an integer, got {v!r}")
    return int(v)


def _validate_fork(fork) -> Tuple:
    """Fork document -> the canonical fork tuple
    (base_digest, event, mode, ((kind, pod), ...)), failing loudly."""
    if not isinstance(fork, dict):
        raise ValueError(
            f"fork must be an object of {{{', '.join(FORK_FIELDS)}}}, "
            f"got {fork!r}"
        )
    unknown = set(fork) - set(FORK_FIELDS)
    if unknown:
        raise ValueError(
            f"unknown fork key(s) {sorted(unknown)} (known: "
            f"{sorted(FORK_FIELDS)})"
        )
    base = fork.get("base")
    if (not isinstance(base, str) or len(base) != 64
            or any(c not in "0123456789abcdef" for c in base)):
        raise ValueError(
            "fork.base must be the 64-hex job digest of a FINISHED base "
            f"run (POST {{'base': true, ...}} first), got {base!r}"
        )
    event = _as_int(fork.get("event"), "fork.event")
    if event < 0:
        raise ValueError(f"fork.event must be >= 0, got {event}")
    mode = fork.get("mode", "fork")
    if mode not in FORK_MODES:
        raise ValueError(
            f"fork.mode must be one of {FORK_MODES} (fork = warm tail "
            f"replay, full = forced from-event-0 twin), got {mode!r}"
        )
    tail = fork.get("tail")
    if not isinstance(tail, (list, tuple)) or not tail:
        raise ValueError(
            "fork.tail must be a non-empty list of [kind, pod] pairs "
            f"(kind {FORK_EV_KINDS[0]} = create, {FORK_EV_KINDS[1]} = "
            f"delete), got {tail!r}"
        )
    tail_t = []
    for i, pair in enumerate(tail):
        if not isinstance(pair, (list, tuple)) or len(pair) != 2:
            raise ValueError(
                f"fork.tail[{i}] must be a [kind, pod] pair, got {pair!r}"
            )
        kind = _as_int(pair[0], f"fork.tail[{i}] kind")
        pod = _as_int(pair[1], f"fork.tail[{i}] pod")
        if kind not in FORK_EV_KINDS:
            raise ValueError(
                f"fork.tail[{i}] kind must be one of {FORK_EV_KINDS} "
                f"(create/delete), got {kind}"
            )
        if pod < 0:
            raise ValueError(
                f"fork.tail[{i}] pod must be >= 0, got {pod}"
            )
        tail_t.append((kind, pod))
    return (base, event, mode, tuple(tail_t))


def spec_to_payload(spec: JobSpec) -> dict:
    """JobSpec -> the job document that validates back to the IDENTICAL
    spec (and therefore digest) — the fleet claim handshake's wire form
    (ISSUE 12): the coordinator hands claimed jobs to workers as
    documents, the worker revalidates and digest-verifies them, so a
    version-skewed worker fails the job loudly instead of silently
    running a different replay. validate_job(spec_to_payload(s)) == s
    is pinned by tests/test_fleet.py."""
    doc = {
        "trace": spec.trace,
        "policies": [[n, int(w)] for n, w in spec.policies],
        "weights": [int(w) for w in spec.weights],
        "seed": int(spec.seed),
        "gpu_sel": spec.gpu_sel,
        "norm": spec.norm,
        "dim_ext": spec.dim_ext,
        "tune": float(spec.tune),
        "tune_seed": int(spec.tune_seed),
        "engine": spec.engine,
    }
    if spec.fault:
        doc["fault"] = {
            f: (float(v) if f.endswith("_events") else int(v))
            for f, v in zip(FAULT_FIELDS, spec.fault)
        }
    if spec.base:
        doc["base"] = True
    if spec.fork:
        doc["fork"] = {
            "base": spec.fork[0],
            "event": int(spec.fork[1]),
            "mode": spec.fork[2],
            "tail": [[int(k), int(p)] for k, p in spec.fork[3]],
        }
    return doc


# keys an apply-style grid document may carry: the per-row vectors plus
# every scalar JOB_KEYS field that applies to all rows ("fault" is a
# shared chaos schedule; per-row "fault_seeds" vary its seed — the
# disruption-frontier grid: one trace, B fault seeds, one POST)
GRID_SHARED_KEYS = ("trace", "policies", "gpu_sel", "norm", "dim_ext",
                    "engine", "tune_seed", "fault")
GRID_KEYS = frozenset(
    ("weights", "seeds", "tunes", "fault_seeds") + GRID_SHARED_KEYS
)


def docs_from_payload(payload):
    """Submit-file payload -> job documents, routing by shape: a list of
    job objects or a {"jobs": [...]} wrapper passes through, a bare
    list-of-rows or a dict whose `weights` is a list of ROWS expands
    via jobs_from_grid, and anything else is ONE job document (note
    `weights` as a flat vector is a JOB_KEYS field of a single job, not
    a one-row grid — `tpusim submit` must not misroute it)."""
    if isinstance(payload, list):
        if payload and isinstance(payload[0], dict):
            return list(payload)
        return jobs_from_grid(payload)
    if isinstance(payload, dict):
        if "jobs" in payload:
            return jobs_from_grid(payload)
        w = payload.get("weights")
        if (isinstance(w, (list, tuple)) and w
                and isinstance(w[0], (list, tuple))):
            return jobs_from_grid(payload)
    return [payload]


def jobs_from_grid(payload, default_policies=None):
    """Expand an apply-style weights grid into per-row job documents —
    the `tpusim submit weights.json` convenience: a bare [[w, ...], ...]
    list or {"weights": [[...]], "seeds": [...], "tunes": [...], ...}
    becomes one job per row (the scalar GRID_SHARED_KEYS — trace,
    policies, gpu_sel, norm, dim_ext, engine, tune_seed — apply to
    every row; unknown keys are rejected loudly, matching validate_job:
    a singular "seed"/"tune" typo must not silently run every row at
    the defaults). Full job documents ({"jobs": [...]}) pass through
    untouched."""
    if isinstance(payload, dict) and "jobs" in payload:
        jobs = payload["jobs"]
        if not isinstance(jobs, list) or not jobs:
            raise ValueError('"jobs" must be a non-empty list of job objects')
        return list(jobs)
    if isinstance(payload, dict):
        unknown = set(payload) - GRID_KEYS
        if unknown:
            # the singular-key guard: a typo'd singular form of a
            # per-row vector must fail naming its plural, not silently
            # run every row at the defaults ("weight" joined the list
            # with the learned-scoring lane's tuned-payload round-trip,
            # ISSUE 9)
            singular = {"seed": "seeds", "tune": "tunes",
                        "weight": "weights"}
            hits = sorted(k for k in unknown if k in singular)
            hint = (
                "; per-row vectors are plural — "
                + ", ".join(f'"{singular[k]}", not "{k}"' for k in hits)
                if hits else
                '; per-row vectors are plural — "weights"/"seeds"/'
                '"tunes", not "weight"/"seed"/"tune"'
            )
            raise ValueError(
                f"unknown grid key(s) {sorted(unknown)} (known: "
                f"{sorted(GRID_KEYS)}{hint})"
            )
        weights = payload.get("weights")
        seeds = payload.get("seeds")
        tunes = payload.get("tunes")
        fault_seeds = payload.get("fault_seeds")
        shared = {k: payload[k] for k in GRID_SHARED_KEYS if k in payload}
        if fault_seeds is not None and "fault" not in shared:
            raise ValueError(
                '"fault_seeds" needs a shared "fault" document to vary '
                "the seed of"
            )
    else:
        weights, seeds, tunes, shared = payload, None, None, {}
        fault_seeds = None
    if not weights:
        raise ValueError(
            "no weight rows (want [[w, ...], ...], "
            '{"weights": [[...]], "seeds": [...], "tunes": [...]}, or '
            '{"jobs": [...]})'
        )
    if "policies" not in shared and default_policies is not None:
        shared["policies"] = [list(p) for p in default_policies]
    b = len(weights)
    for name, vals in (("seeds", seeds), ("tunes", tunes),
                       ("fault_seeds", fault_seeds)):
        if vals is not None and len(vals) != b:
            raise ValueError(
                f"{name} has {len(vals)} entries for {b} weight rows"
            )
    out = []
    for i, row in enumerate(weights):
        job = dict(shared)
        job["weights"] = list(row)
        if seeds is not None:
            job["seed"] = seeds[i]
        if tunes is not None:
            job["tune"] = tunes[i]
        if fault_seeds is not None:
            job["fault"] = dict(job["fault"], seed=fault_seeds[i])
        out.append(job)
    return out


# ---------------------------------------------------------------------------
# Content digest + signed result persistence
# ---------------------------------------------------------------------------


def job_digest(spec: JobSpec, trace_digest: str) -> str:
    """Content key of one job: the engine-source version salt (any
    engine/policy code change invalidates every cached result), the
    hosted trace's content digest (a changed CSV is a different job),
    and the canonical spec tuple."""
    from tpusim.io.storage import checkpoint_digest
    from tpusim.sim.driver import _engine_source_digest

    def chunks():
        yield _engine_source_digest()
        yield str(trace_digest).encode()
        yield repr(spec.canonical()).encode()

    return checkpoint_digest(chunks())


def trace_digest(nodes: Sequence, pods: Sequence) -> str:
    """Content digest of a hosted trace (NodeRow/PodRow lists — their
    dataclass reprs are value-complete, so this keys on content, not on
    file paths or mtimes)."""
    from tpusim.io.storage import checkpoint_digest

    def chunks():
        for n in nodes:
            yield repr(n).encode()
        for p in pods:
            yield repr(p).encode()

    return checkpoint_digest(chunks())


def result_path(artifact_dir: str, digest: str) -> str:
    return os.path.join(artifact_dir, f"{digest}{RESULT_SUFFIX}")


# ---------------------------------------------------------------------------
# Job-spec persistence — crash/restart recovery (ISSUE 10 satellite)
# ---------------------------------------------------------------------------
#
# Accepted jobs used to live only in the in-memory JobQueue: a service
# killed mid-batch stranded them in `running` forever (the client polls a
# job id the restarted process has never heard of). Now every accepted
# job document persists as `<digest>.job.json` BEFORE it is runnable, and
# `tpusim serve --jobs` startup requeues every spec with no signed result
# (svc.api.recover_pending_jobs) — the crash simply becomes a retry. The
# spec file is tiny, atomic (tmp + rename), and content-addressed by the
# job digest, so re-accepting the same document is an idempotent
# overwrite and completed jobs are skipped by their result file.

JOB_SUFFIX = ".job.json"
JOB_SPEC_SCHEMA = "tpusim-svc-job/1"


def job_path(artifact_dir: str, digest: str) -> str:
    return os.path.join(artifact_dir, f"{digest}{JOB_SUFFIX}")


def write_job_spec(artifact_dir: str, digest: str, payload: dict) -> str:
    """Persist one ACCEPTED job document (the validated submission
    payload — revalidating it on recovery rebuilds the identical spec
    and digest)."""
    os.makedirs(artifact_dir, exist_ok=True)
    path = job_path(artifact_dir, digest)
    doc = {"schema": JOB_SPEC_SCHEMA, "job": digest, "spec": payload}
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True, separators=(",", ":"))
        f.write("\n")
    os.replace(tmp, path)
    return path


def delete_job_spec(artifact_dir: str, digest: str) -> None:
    """Drop a job's persisted spec once it reaches a TERMINAL state: a
    done job's result file is its durable record (and the dedup key), a
    failed job must NOT be requeued by every future restart (a poisoned
    batch would re-fail forever) — its failure stays queryable for the
    session and the client's re-submit is an explicit retry."""
    try:
        os.unlink(job_path(artifact_dir, digest))
    except OSError:
        pass


def pending_job_specs(artifact_dir: str):
    """[(digest, spec payload)] of persisted jobs with NO valid signed
    result — the restart-recovery work list. Torn/foreign spec files are
    deleted and skipped (content addressing makes a lost spec merely a
    job the client will re-submit)."""
    if not os.path.isdir(artifact_dir):
        return []
    out = []
    for fname in sorted(os.listdir(artifact_dir)):
        if not fname.endswith(JOB_SUFFIX):
            continue
        path = os.path.join(artifact_dir, fname)
        digest = fname[: -len(JOB_SUFFIX)]
        try:
            with open(path) as f:
                doc = json.load(f)
            if (doc.get("schema") != JOB_SPEC_SCHEMA
                    or doc.get("job") != digest
                    or not isinstance(doc.get("spec"), dict)):
                raise ValueError("foreign or malformed job-spec file")
        except (OSError, ValueError, json.JSONDecodeError):
            try:
                os.unlink(path)
            except OSError:
                pass
            continue
        if find_result(artifact_dir, digest) is not None:
            continue  # already answered — nothing to recover
        out.append((digest, doc["spec"]))
    return out


def write_result(artifact_dir: str, digest: str, result: dict) -> str:
    """Persist one job result as digest-signed JSONL (atomic; the
    decisions-file discipline). The header names the job digest so a
    renamed/foreign file never matches on read."""
    from tpusim.io import storage

    header = {"schema": RESULT_SCHEMA, "job": digest}
    line = json.dumps(result, sort_keys=True, separators=(",", ":"))
    return storage.write_signed_jsonl(
        result_path(artifact_dir, digest), header, [line]
    )


def verify_result_bytes(digest: str, data: bytes) -> dict:
    """Validate UPLOADED signed-result bytes before a single byte lands
    on disk (ISSUE 13, the no-shared-fs upload path): the bytes must
    parse as a signed-JSONL result file whose header names THIS job
    digest and whose payload digest verifies — a torn (truncated
    mid-transfer) or forged (wrong job, edited payload) upload raises
    ValueError and the coordinator answers 400 without writing
    anything. Returns the parsed result document."""
    try:
        raw = [
            ln for ln in data.decode("utf-8").split("\n") if ln.strip()
        ]
    except UnicodeDecodeError as err:
        raise ValueError(f"result upload is not UTF-8 text: {err}")
    if not raw:
        raise ValueError("empty result upload")
    header = json.loads(raw[0])
    if not isinstance(header, dict):
        # a non-object first line must be a clean 400 rejection, not an
        # AttributeError that the HTTP plane answers as a retryable 500
        raise ValueError(
            f"header line is {type(header).__name__}, want a JSON object"
        )
    if header.get("schema") != RESULT_SCHEMA:
        raise ValueError(
            f"not a {RESULT_SCHEMA} document "
            f"(schema={header.get('schema')!r})"
        )
    if header.get("job") != digest:
        raise ValueError(
            f"foreign result upload: header names job "
            f"{str(header.get('job'))[:12]}…, URL names {digest[:12]}…"
        )
    payload = raw[1:]
    from tpusim.io.storage import payload_digest

    got = payload_digest(payload)
    if got != header.get("digest"):
        raise ValueError(
            "payload digest mismatch (torn or forged upload): header "
            f"{header.get('digest')} != computed {got}"
        )
    if len(payload) != 1:
        raise ValueError(
            f"want exactly one payload document, found {len(payload)}"
        )
    return json.loads(payload[0])


def accept_result_upload(artifact_dir: str, digest: str,
                         data: bytes) -> dict:
    """Land one verified result upload atomically: verify_result_bytes
    first (raises on torn/forged bytes — nothing is written), then an
    atomic whole-file replace, so the artifact dir only ever holds
    complete, digest-valid result files. Re-uploading identical bytes
    (the duplicate-completion race over the wire) is an idempotent
    overwrite. Returns the parsed result document."""
    result = verify_result_bytes(digest, data)
    from tpusim.io.storage import write_bytes_atomic

    # normalize to exactly what write_result would have produced
    # locally: content already verified, so the bytes ARE the file
    write_bytes_atomic(result_path(artifact_dir, digest), data)
    return result


def result_bytes(artifact_dir: str, digest: str) -> Optional[bytes]:
    """Raw bytes of a job's VALID signed result file, or None — the
    worker side of the upload path reads these (validity via
    find_result first, so a torn local file is never uploaded)."""
    if find_result(artifact_dir, digest) is None:
        return None
    with open(result_path(artifact_dir, digest), "rb") as f:
        return f.read()


def find_result(artifact_dir: str, digest: str) -> Optional[dict]:
    """Load a persisted result for this job digest, or None. Torn /
    digest-mismatched / foreign files are DELETED and treated as a miss
    — content addressing makes recomputation always safe, and a bad file
    left behind would shadow every future write."""
    from tpusim.io import storage

    path = result_path(artifact_dir, digest)
    if not os.path.isfile(path):
        return None
    try:
        header, payload = storage.read_signed_jsonl(path, RESULT_SCHEMA)
        if header.get("job") != digest or len(payload) != 1:
            raise ValueError("foreign or malformed result file")
        return json.loads(payload[0])
    except (OSError, ValueError, json.JSONDecodeError):
        try:
            os.unlink(path)
        except OSError:
            pass
        return None
