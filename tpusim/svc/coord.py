"""Coordinator HA — the epoch-fenced leadership lease (ISSUE 17).

PR 12/13 made the *workers* stateless and kill-tolerant; the
coordinator stayed the single point of failure. This module removes it
with the same design argument: the artifact dir is the one source of
truth, so leadership is just one more signed file in it.

The active coordinator stakes `coordinator.lease.json` (written through
`io.storage.write_signed_json` — atomic tmp+rename, payload-digest
header) carrying an **epoch counter** and a deadline, and renews it on
a LeaseKeeper-style timer at lease_s/3. A standby (`tpusim serve
--jobs --standby`) watches the file and takes over when it goes stale:
it bumps the epoch, stakes the lease, and re-adopts pending job specs
(`recover_pending_jobs`), live worker leases (`claim_specific` via
`FleetService.adopt_leases`), the fork index, and the policy presets —
all of which live in the artifact dir already.

**Epoch fencing** guards the split-brain window. Every fleet op
(claim/renew/complete/leases) is stamped with the coordinator epoch
the worker learned at registration:

  op epoch < ours   the sender registered with a deposed leader →
                    409 `{"stale_epoch": true, "register": true}`;
                    the worker re-registers and adopts the new epoch.
  op epoch > ours   a worker holds proof that a NEWER leader exists →
                    WE are the deposed one: answer 409 `{"deposed":
                    true}` and demote to standby on the spot. A
                    resurrected old leader therefore fences itself on
                    the first op it sees, before it can corrupt state.

Exactly-once still holds across a failover for the PR 12 reasons: job
digests pin trajectories, result writes are atomic whole-file replaces
of identical bytes, and duplicate completions dedup silently.

Torn/edited `coordinator.lease.json` files are skipped AND deleted
with a `[Degrade]` warning (the load_valid_checkpoint pattern): a lost
leadership lease only makes the cluster leaderless for one takeover
interval, which is always safe.

Knobs (fail-loud through tpusim.envutil, naming the variable):
`TPUSIM_COORD_LEASE_S` (leadership lease duration, default 6 s; the
standby takes over roughly one lease + skew after a leader dies) and
`TPUSIM_COORD_SKEW_S` (cross-host clock margin on staleness
judgements, default 2 s — the TPUSIM_LEASE_SKEW_S pattern).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Optional

from tpusim.envutil import float_env as _float_env

# Lives beside the per-job `<digest>.lease.json` files; scan_leases
# skips this reserved name so the job-lease reaper never judges (or
# deletes) the leadership lease.
COORD_LEASE_BASENAME = "coordinator.lease.json"
COORD_LEASE_SCHEMA = "tpusim-svc-coord/1"

DEFAULT_COORD_LEASE_S = 6.0


def coord_lease_s() -> float:
    """Leadership lease duration (env TPUSIM_COORD_LEASE_S, default
    6 s). Renewal runs at a third of it; a standby takes over about one
    lease + skew after the leader stops renewing. Must be > 0 — fails
    loudly naming the variable (the PR 15 envutil pattern)."""
    val = _float_env("TPUSIM_COORD_LEASE_S", DEFAULT_COORD_LEASE_S)
    if val <= 0.0:
        raise ValueError(
            f"TPUSIM_COORD_LEASE_S must be > 0 seconds, got {val}"
        )
    return val


def coord_skew_s() -> float:
    """Clock-skew margin on every leadership-staleness judgement (env
    TPUSIM_COORD_SKEW_S, default 2 s): the lease may be judged by a
    different host than the one that wrote it, and leadership must
    never change hands merely because two clocks disagree."""
    return _float_env("TPUSIM_COORD_SKEW_S", 2.0)


def coord_lease_path(artifact_dir: str) -> str:
    return os.path.join(artifact_dir, COORD_LEASE_BASENAME)


def write_coord_lease(artifact_dir: str, epoch: int, leader: str,
                      pid: int, url: str, deadline_unix: float) -> str:
    from tpusim.io.storage import write_signed_json

    header = {"schema": COORD_LEASE_SCHEMA, "role": "coordinator"}
    doc = {
        "epoch": int(epoch),
        "leader": str(leader),
        "pid": int(pid),
        "url": str(url),
        "deadline_unix": float(deadline_unix),
    }
    return write_signed_json(coord_lease_path(artifact_dir), header, doc)


def _degrade(path: str, err) -> None:
    print(
        f"[Degrade] skipping torn/foreign coordinator lease {path} "
        f"({type(err).__name__}: {err}); deleted — the cluster is "
        "leaderless until the next stake",
        file=sys.stderr,
    )


def read_coord_lease(artifact_dir: str, on_skip=None) -> Optional[dict]:
    """The leadership lease document, or None. Torn/edited/foreign
    files are DELETED and reported through `on_skip(path, err)`
    (default: a `[Degrade]` stderr line) — never trusted, never fatal,
    never allowed to wedge a takeover."""
    from tpusim.io.storage import read_signed_json

    path = coord_lease_path(artifact_dir)
    if not os.path.isfile(path):
        return None
    try:
        header, doc = read_signed_json(path, COORD_LEASE_SCHEMA)
        if header.get("role") != "coordinator":
            raise ValueError("foreign lease file (not a coordinator lease)")
        if not isinstance(doc.get("epoch"), int) or "deadline_unix" not in doc:
            raise ValueError("malformed coordinator lease document")
        return doc
    except (OSError, ValueError, json.JSONDecodeError) as err:
        (on_skip or _degrade)(path, err)
        try:
            os.unlink(path)
        except OSError:
            pass
        return None


def coord_lease_stale(doc: dict, now: Optional[float] = None,
                      skew_s: Optional[float] = None) -> bool:
    """True when the leadership deadline has passed by MORE than the
    clock-skew margin — the only condition under which a standby may
    take over."""
    if now is None:
        now = time.time()
    if skew_s is None:
        skew_s = coord_skew_s()
    return float(now) > float(doc.get("deadline_unix", 0.0)) + skew_s


def delete_coord_lease(artifact_dir: str) -> None:
    try:
        os.unlink(coord_lease_path(artifact_dir))
    except OSError:
        pass


class CoordinatorState:
    """One coordinator's view of the leadership protocol: its role
    (`leader` | `standby`), its epoch, and the stake/renew/acquire
    transitions over the shared lease file. Pure protocol — no threads,
    no HTTP — so the tier-1 fencing matrix drives it synchronously; the
    renewal timer lives in CoordKeeper and the serve loop.

    Thread-safety: `epoch`/`role` are read by HTTP handler threads and
    written under `_lock` by the serve loop / keeper; both are simple
    attribute reads (atomic in CPython), and fencing tolerates a
    one-op-stale view by construction.
    """

    def __init__(self, artifact_dir: str, name: str, url: str = "",
                 lease_s: Optional[float] = None,
                 skew_s: Optional[float] = None, out=None):
        self.artifact_dir = str(artifact_dir)
        self.name = str(name)
        self.url = str(url)
        self.lease_s = float(lease_s) if lease_s else coord_lease_s()
        self.skew_s = float(skew_s) if skew_s is not None else coord_skew_s()
        self.out = out
        self.epoch = 0  # highest epoch this process has observed
        self.role = "standby"
        self.takeovers = 0
        self.demotions = 0
        self._lock = threading.Lock()
        # the audit chain (ISSUE 19): an obs.audit.AuditLog when the
        # serve loop wired one — takeover/epoch-bump/deposition are
        # control-plane decisions, so each appends a chained record
        self.audit = None

    def _say(self, msg: str) -> None:
        if self.out is not None:
            print(msg, file=self.out)

    def _audit(self, kind: str, **fields) -> None:
        if self.audit is not None:
            self.audit.emit(kind, **fields)

    # ---- transitions ----

    def try_acquire(self, now: Optional[float] = None) -> bool:
        """Attempt to become (or stay) the leader. Succeeds when the
        on-disk lease is absent, stale past the skew margin, or our
        own; a live foreign lease means someone else leads — remember
        their epoch (for fencing) and stay standby."""
        now = time.time() if now is None else now
        with self._lock:
            doc = read_coord_lease(self.artifact_dir)
            if doc is not None:
                seen = int(doc.get("epoch", 0))
                if (doc.get("leader") != self.name
                        and not coord_lease_stale(doc, now, self.skew_s)):
                    self.epoch = max(self.epoch, seen)
                    if self.role != "standby":
                        self.role = "standby"
                    return False
                if doc.get("leader") == self.name and self.role == "leader":
                    # already leading — just renew in place
                    self._stake(now)
                    return True
            seen = int(doc.get("epoch", 0)) if doc else 0
            self.epoch = max(self.epoch, seen) + 1
            self.role = "leader"
            self.takeovers += 1
            self._stake(now)
            self._audit("epoch_bump", coordinator=self.name,
                        from_epoch=seen, epoch=self.epoch)
            self._audit("takeover", coordinator=self.name,
                        epoch=self.epoch,
                        prior_lease="stale" if doc else "absent")
            self._say(
                f"[coord] {self.name} took leadership at epoch "
                f"{self.epoch} (previous lease: "
                f"{'stale' if doc else 'absent'})"
            )
            return True

    def _stake(self, now: float) -> None:
        write_coord_lease(
            self.artifact_dir, self.epoch, self.name, os.getpid(),
            self.url, now + self.lease_s,
        )

    def renew(self, now: Optional[float] = None) -> bool:
        """Push the leadership deadline out. Returns False — after
        demoting — when the on-disk lease names a newer epoch: a
        standby took over while we were wedged, and overwriting its
        lease would be the split-brain this module exists to prevent."""
        now = time.time() if now is None else now
        with self._lock:
            if self.role != "leader":
                return False
            doc = read_coord_lease(self.artifact_dir)
            if doc is not None and int(doc.get("epoch", 0)) > self.epoch:
                self._demote_locked(
                    f"coordinator lease shows epoch "
                    f"{int(doc['epoch'])} > ours ({self.epoch})"
                )
                return False
            self._stake(now)
            return True

    def note_epoch(self, epoch: int) -> bool:
        """Record an epoch observed in a fleet op. Returns True when it
        deposes us (op epoch newer than ours while we believed we were
        the leader) — the caller answers 409 `{"deposed": true}`."""
        epoch = int(epoch)
        with self._lock:
            if epoch <= self.epoch:
                return False
            deposed = self.role == "leader"
            self.epoch = epoch
            if deposed:
                self._demote_locked(
                    f"a fleet op carried epoch {epoch} > ours"
                )
            return deposed

    def demote(self, reason: str = "") -> None:
        with self._lock:
            if self.role == "leader":
                self._demote_locked(reason)

    def _demote_locked(self, reason: str) -> None:
        self.role = "standby"
        self.demotions += 1
        self._audit("deposed", coordinator=self.name,
                    epoch=self.epoch, reason=reason)
        print(
            f"[Degrade] coordinator {self.name} DEPOSED at epoch "
            f"{self.epoch}{': ' + reason if reason else ''} — demoting "
            "to standby (mutating endpoints now answer 503)",
            file=sys.stderr,
        )
        self._say(f"[coord] {self.name} demoted to standby ({reason})")

    def release(self) -> None:
        """Graceful shutdown: delete our own lease so a standby takes
        over immediately instead of waiting out the deadline. Never
        deletes a successor's lease."""
        with self._lock:
            if self.role != "leader":
                return
            doc = read_coord_lease(self.artifact_dir)
            if doc is not None and doc.get("leader") == self.name:
                delete_coord_lease(self.artifact_dir)
            self.role = "standby"


class CoordKeeper:
    """The leadership renewal timer — LeaseKeeper's little sibling.
    Renews at lease_s/3 so one missed tick never deposes a healthy
    leader; a renew() that discovers deposition stops the timer and
    fires `on_deposed` (the serve loop drops back to standby watch)."""

    def __init__(self, state: CoordinatorState, on_deposed=None):
        self.state = state
        self.on_deposed = on_deposed
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "CoordKeeper":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="coord-keeper", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        period = max(self.state.lease_s / 3.0, 0.05)
        while not self._stop.wait(period):
            try:
                ok = self.state.renew()
            except Exception as err:  # keep renewing through fs hiccups
                print(
                    f"[coord] renew failed ({type(err).__name__}: "
                    f"{err}); retrying", file=sys.stderr,
                )
                continue
            if not ok:
                if self.on_deposed is not None:
                    try:
                        self.on_deposed()
                    except Exception:
                        pass
                return

    def stop(self, release: bool = False) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if release:
            self.state.release()
