"""Bearer-token admission for the fleet planes (ISSUE 17).

Digest verification (PR 13) guards *integrity* — a tampered upload or
trace chunk is rejected by content hash. It never guarded *admission*:
anyone who could reach the coordinator could submit jobs, claim work,
or complete someone else's digest. This module adds the missing gate:
a shared bearer token, loaded once at serve/worker/submit startup from
`--token-file` or `TPUSIM_FLEET_TOKEN` (the envutil fail-loud
pattern — a configured-but-unreadable token file is a startup error
naming the path, never a silently open fleet), checked on every
mutating endpoint with a constant-time compare.

Rules the call sites follow:

  * the check runs BEFORE any path/digest parsing, so a 401 never
    leaks whether a digest exists;
  * 401 bodies are uniform (`{"error": "missing or invalid bearer
    token"}`) for missing, malformed, and forged tokens alike;
  * token material never reaches a log line or the `/queue` document —
    `describe()` is the only sanctioned rendering.

An empty token disables the gate (the single-host default; every
pre-ISSUE-17 flow is unchanged).
"""

from __future__ import annotations

import hmac
import os
from typing import Optional

ENV_TOKEN = "TPUSIM_FLEET_TOKEN"
_HEADER = "Authorization"
_PREFIX = "Bearer "


def load_token(token_file: str = "") -> str:
    """The fleet token: the file's stripped contents when
    `--token-file` is given (fail-loud on an unreadable path), else
    `TPUSIM_FLEET_TOKEN`, else "" (auth disabled)."""
    if token_file:
        try:
            with open(token_file, "r", encoding="utf-8") as f:
                tok = f.read().strip()
        except OSError as err:
            raise ValueError(
                f"--token-file {token_file} is unreadable "
                f"({type(err).__name__}: {err}) — refusing to start "
                "with auth half-configured"
            )
        if not tok:
            raise ValueError(
                f"--token-file {token_file} is empty — refusing to "
                "start with auth half-configured"
            )
        return tok
    return os.environ.get(ENV_TOKEN, "").strip()


def check(headers, token: str) -> bool:
    """True when the request may mutate state: auth disabled, or the
    `Authorization: Bearer <token>` header matches under
    `hmac.compare_digest`. `headers` is any case-insensitive-get
    mapping (http.client Message) or a plain dict."""
    if not token:
        return True
    raw = (headers or {}).get(_HEADER) or ""
    if not raw.startswith(_PREFIX):
        return False
    return hmac.compare_digest(
        raw[len(_PREFIX):].encode("utf-8"), token.encode("utf-8")
    )


def bearer_headers(token: Optional[str]) -> dict:
    """The request-side half: headers to attach to a mutating call."""
    if not token:
        return {}
    return {_HEADER: _PREFIX + token}


def describe(token: str) -> str:
    """The ONLY way token state reaches a log line or `/queue`: armed
    or off, length only — never material, never a digest of it."""
    return f"enabled ({len(token)} chars)" if token else "disabled"
