"""KubeSchedulerConfiguration parsing + simulator defaulting.

Accepts the reference's scheduler-config YAML surface
(example/original/test-scheduler-config.yaml) and applies the same forced
defaults as the reference (ref: GetAndSetSchedulerConfig,
pkg/simulator/utils.go:217-323): percentageOfNodesToScore=100, scheduler
name `simon-scheduler`, DefaultBinder disabled in favor of the Simon bind.

Policy selection follows the reference convention: the enabled Score
plugins (with weights) pick the policy mix; per-plugin `pluginConfig` args
carry `dimExtMethod` / `normMethod` / `gpuSelMethod`
(ref: pkg/type/config.go:50-61 plugin-config structs).

k8s built-in score plugins that the simulator always disables
(ImageLocality, NodeAffinity, …) are accepted in the YAML and ignored —
they have no analogue over the array state.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

import yaml

SCHEDULER_NAME = "simon-scheduler"  # ref: pkg/type/const.go DefaultSchedulerName
API_VERSIONS = (
    "kubescheduler.config.k8s.io/v1beta1",
    "kubescheduler.config.k8s.io/v1beta2",
    "kubescheduler.config.k8s.io/v1",
)

# score plugins this framework implements (ref: pkg/type/const.go:4-13)
KNOWN_SCORE_PLUGINS = (
    "Simon",
    "RandomScore",
    "DotProductScore",
    "GpuClusteringScore",
    "GpuPackingScore",
    "BestFitScore",
    "FGDScore",
    "PWRScore",
)
# vendored-k8s score plugins force-disabled by the reference; silently inert
IGNORED_SCORE_PLUGINS = (
    "ImageLocality",
    "NodeAffinity",
    "PodTopologySpread",
    "TaintToleration",
    "NodeResourcesBalancedAllocation",
    "InterPodAffinity",
    "NodeResourcesLeastAllocated",
    "NodePreferAvoidPods",
)


@dataclass
class SchedulerConfig:
    policies: List[Tuple[str, int]] = field(default_factory=list)
    gpu_sel_method: str = "best"  # best|worst|random|<score-plugin name>
    dim_ext_method: str = "share"  # merge|share|divide|extend
    norm_method: str = "max"  # node|pod|max
    percentage_of_nodes_to_score: int = 100
    scheduler_name: str = SCHEDULER_NAME
    # HTTP scheduler extenders (tpusim.sim.extender.ExtenderConfig tuple;
    # ref: simulator.go:196 WithExtenders pass-through)
    extenders: tuple = ()

    def policy_tuple(self) -> Tuple[Tuple[str, int], ...]:
        return tuple(self.policies)


class SchedulerConfigError(ValueError):
    pass


def default_scheduler_config() -> SchedulerConfig:
    """No-config default (ref: GetAndSetSchedulerConfig's built-in profile:
    Simon + BestFit + Random + DotProduct + FGD + PWR all enabled at weight
    1, utils.go:251-272)."""
    return SchedulerConfig(
        policies=[
            ("Simon", 1),
            ("BestFitScore", 1),
            ("RandomScore", 1),
            ("DotProductScore", 1),
            ("FGDScore", 1),
            ("PWRScore", 1),
        ]
    )


def parse_scheduler_config(doc: dict) -> SchedulerConfig:
    if doc.get("kind") != "KubeSchedulerConfiguration":
        raise SchedulerConfigError(
            f"expected kind=KubeSchedulerConfiguration, got {doc.get('kind')}"
        )
    if doc.get("apiVersion") not in API_VERSIONS:
        raise SchedulerConfigError(
            f"unsupported apiVersion {doc.get('apiVersion')}"
        )
    # The reference accepts but overrides these (utils.go:234-235 forces
    # percentageOfNodesToScore=100; extenders pass through to the vendored
    # scheduler, simulator.go:185-197). This build has no extender protocol
    # and always scores every node, so reject configs that ask otherwise
    # rather than silently computing something different.
    pct = doc.get("percentageOfNodesToScore")
    if pct is not None:
        try:
            if float(pct) != int(pct):
                raise ValueError
            pct = int(pct)
        except (TypeError, ValueError, OverflowError):
            raise SchedulerConfigError(
                f"percentageOfNodesToScore={pct!r} is not an integer"
            ) from None
    if pct is not None and pct != 100:
        raise SchedulerConfigError(
            f"percentageOfNodesToScore={pct} unsupported: this simulator "
            "always scores 100% of nodes (the reference forces the same, "
            "utils.go:234)"
        )
    extenders = _parse_extenders(doc.get("extenders") or [])
    profiles = doc.get("profiles") or []
    if not profiles:
        cfg = default_scheduler_config()
        cfg.extenders = extenders
        return cfg
    profile = profiles[0]
    plugins = profile.get("plugins") or {}
    score = plugins.get("score") or {}

    # k8s profile-merge semantics (vendored defaultPlugins.Apply): the
    # `disabled` list strips plugins from the DEFAULT set only; `enabled`
    # entries are then appended and always win. The reference's own example
    # configs list a plugin in both (disable-everything boilerplate + the
    # chosen policy re-enabled), so skipping enabled-plugins-in-disabled
    # would silently fall back to the wrong profile. The k8s built-in score
    # defaults the boilerplate strips are exactly IGNORED_SCORE_PLUGINS,
    # which have no analogue over the array state — so `disabled` carries
    # no further information here.
    cfg = SchedulerConfig()
    for p in score.get("enabled") or []:
        name = p.get("name")
        if name in IGNORED_SCORE_PLUGINS:
            continue
        if name not in KNOWN_SCORE_PLUGINS:
            raise SchedulerConfigError(f"unknown score plugin: {name}")
        cfg.policies.append((name, int(p.get("weight", 1) or 1)))
    if not cfg.policies:
        cfg = default_scheduler_config()

    # pluginConfig args: last writer wins per arg, matching the reference's
    # per-plugin structs all carrying the same three knobs
    for pc in profile.get("pluginConfig") or []:
        args = pc.get("args") or {}
        if "dimExtMethod" in args:
            cfg.dim_ext_method = str(args["dimExtMethod"])
        if "normMethod" in args:
            cfg.norm_method = str(args["normMethod"])
        if "gpuSelMethod" in args:
            cfg.gpu_sel_method = str(args["gpuSelMethod"])

    # forced defaults (utils.go:234-235, 312)
    cfg.percentage_of_nodes_to_score = 100
    cfg.scheduler_name = profile.get("schedulerName") or SCHEDULER_NAME
    cfg.extenders = extenders
    _validate_methods(cfg)
    return cfg


def _parse_extenders(entries) -> tuple:
    """`extenders:` list → ExtenderConfig tuple (the v1beta1 Extender
    fields; apis/config/types.go:109). The reference hands these straight
    to the vendored scheduler (simulator.go:196); here they drive the
    host-loop extender replay (tpusim.sim.extender). Verbs this build
    cannot honor are rejected loudly rather than silently dropped."""
    from tpusim.sim.extender import ExtenderConfig

    out = []
    for e in entries:
        if not isinstance(e, dict) or not e.get("urlPrefix"):
            raise SchedulerConfigError(
                f"extender entry must be a mapping with urlPrefix: {e!r}"
            )
        for unsupported in ("bindVerb", "preemptVerb"):
            if e.get(unsupported):
                raise SchedulerConfigError(
                    f"extender {unsupported} is not supported: binding/"
                    "preemption are array scatter updates in this "
                    "simulator, not delegable side effects"
                )
        if e.get("enableHTTPS") and str(e["urlPrefix"]).startswith("http:"):
            raise SchedulerConfigError(
                "extender enableHTTPS=true with an http:// urlPrefix"
            )
        managed = tuple(
            str(m.get("name"))
            for m in (e.get("managedResources") or [])
            if isinstance(m, dict) and m.get("name")
        )
        # k8s validation requires a positive weight whenever prioritizeVerb
        # is set (ValidateExtender); coercing an explicit `weight: 0` to 1
        # would silently score with a weight the config never asked for
        weight = e.get("weight")
        if e.get("prioritizeVerb") and weight is not None and int(weight) < 1:
            raise SchedulerConfigError(
                f"extender weight must be a positive integer when "
                f"prioritizeVerb is set, got {weight!r}"
            )
        out.append(
            ExtenderConfig(
                url_prefix=str(e["urlPrefix"]),
                filter_verb=str(e.get("filterVerb") or ""),
                prioritize_verb=str(e.get("prioritizeVerb") or ""),
                weight=int(weight or 1),
                node_cache_capable=bool(e.get("nodeCacheCapable")),
                ignorable=bool(e.get("ignorable")),
                managed_resources=managed,
                http_timeout_s=_parse_duration_s(e.get("httpTimeout"), 30.0),
            )
        )
    return tuple(out)


_DURATION_UNITS = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 1e-3}


def _parse_duration_s(value, default: float) -> float:
    """httpTimeout is a metav1.Duration: a Go duration string ('30s',
    '1m30s', '500ms') in real configs; bare numbers are read as seconds."""
    if value is None or value == "":
        return default
    if isinstance(value, (int, float)):
        return float(value)
    import re as _re

    parts = _re.findall(r"(\d+(?:\.\d+)?)(h|ms|m|s)", str(value))
    if not parts or "".join(f"{n}{u}" for n, u in parts) != str(value):
        raise SchedulerConfigError(
            f"extender httpTimeout {value!r} is not a duration "
            "('30s', '1m30s', '500ms') or a number of seconds"
        )
    return sum(float(n) * _DURATION_UNITS[u] for n, u in parts)


def _validate_methods(cfg: SchedulerConfig) -> None:
    if cfg.dim_ext_method not in ("merge", "share", "divide", "extend"):
        raise SchedulerConfigError(f"bad dimExtMethod: {cfg.dim_ext_method}")
    if cfg.norm_method not in ("node", "pod", "max"):
        raise SchedulerConfigError(f"bad normMethod: {cfg.norm_method}")
    sel_ok = ("best", "worst", "random") + tuple(KNOWN_SCORE_PLUGINS)
    if cfg.gpu_sel_method not in sel_ok:
        raise SchedulerConfigError(f"bad gpuSelMethod: {cfg.gpu_sel_method}")


def load_scheduler_config(path: str = "") -> SchedulerConfig:
    if not path:
        return default_scheduler_config()
    from tpusim.config.simon import load_yaml_lenient

    doc = load_yaml_lenient(path)
    if not isinstance(doc, dict):
        raise SchedulerConfigError(f"{path}: not a YAML mapping")
    return parse_scheduler_config(doc)
