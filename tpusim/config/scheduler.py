"""KubeSchedulerConfiguration parsing + simulator defaulting.

Accepts the reference's scheduler-config YAML surface
(example/original/test-scheduler-config.yaml) and applies the same forced
defaults as the reference (ref: GetAndSetSchedulerConfig,
pkg/simulator/utils.go:217-323): percentageOfNodesToScore=100, scheduler
name `simon-scheduler`, DefaultBinder disabled in favor of the Simon bind.

Policy selection follows the reference convention: the enabled Score
plugins (with weights) pick the policy mix; per-plugin `pluginConfig` args
carry `dimExtMethod` / `normMethod` / `gpuSelMethod`
(ref: pkg/type/config.go:50-61 plugin-config structs).

k8s built-in score plugins that the simulator always disables
(ImageLocality, NodeAffinity, …) are accepted in the YAML and ignored —
they have no analogue over the array state.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

import yaml

SCHEDULER_NAME = "simon-scheduler"  # ref: pkg/type/const.go DefaultSchedulerName
API_VERSIONS = (
    "kubescheduler.config.k8s.io/v1beta1",
    "kubescheduler.config.k8s.io/v1beta2",
    "kubescheduler.config.k8s.io/v1",
)

# score plugins this framework implements (ref: pkg/type/const.go:4-13)
KNOWN_SCORE_PLUGINS = (
    "Simon",
    "RandomScore",
    "DotProductScore",
    "GpuClusteringScore",
    "GpuPackingScore",
    "BestFitScore",
    "FGDScore",
    "PWRScore",
)
# vendored-k8s score plugins force-disabled by the reference; silently inert
IGNORED_SCORE_PLUGINS = (
    "ImageLocality",
    "NodeAffinity",
    "PodTopologySpread",
    "TaintToleration",
    "NodeResourcesBalancedAllocation",
    "InterPodAffinity",
    "NodeResourcesLeastAllocated",
    "NodePreferAvoidPods",
)


@dataclass
class SchedulerConfig:
    policies: List[Tuple[str, int]] = field(default_factory=list)
    gpu_sel_method: str = "best"  # best|worst|random|<score-plugin name>
    dim_ext_method: str = "share"  # merge|share|divide|extend
    norm_method: str = "max"  # node|pod|max
    percentage_of_nodes_to_score: int = 100
    scheduler_name: str = SCHEDULER_NAME

    def policy_tuple(self) -> Tuple[Tuple[str, int], ...]:
        return tuple(self.policies)


class SchedulerConfigError(ValueError):
    pass


def default_scheduler_config() -> SchedulerConfig:
    """No-config default (ref: GetAndSetSchedulerConfig's built-in profile:
    Simon + BestFit + Random + DotProduct + FGD + PWR all enabled at weight
    1, utils.go:251-272)."""
    return SchedulerConfig(
        policies=[
            ("Simon", 1),
            ("BestFitScore", 1),
            ("RandomScore", 1),
            ("DotProductScore", 1),
            ("FGDScore", 1),
            ("PWRScore", 1),
        ]
    )


def parse_scheduler_config(doc: dict) -> SchedulerConfig:
    if doc.get("kind") != "KubeSchedulerConfiguration":
        raise SchedulerConfigError(
            f"expected kind=KubeSchedulerConfiguration, got {doc.get('kind')}"
        )
    if doc.get("apiVersion") not in API_VERSIONS:
        raise SchedulerConfigError(
            f"unsupported apiVersion {doc.get('apiVersion')}"
        )
    # The reference accepts but overrides these (utils.go:234-235 forces
    # percentageOfNodesToScore=100; extenders pass through to the vendored
    # scheduler, simulator.go:185-197). This build has no extender protocol
    # and always scores every node, so reject configs that ask otherwise
    # rather than silently computing something different.
    pct = doc.get("percentageOfNodesToScore")
    if pct is not None:
        try:
            if float(pct) != int(pct):
                raise ValueError
            pct = int(pct)
        except (TypeError, ValueError, OverflowError):
            raise SchedulerConfigError(
                f"percentageOfNodesToScore={pct!r} is not an integer"
            ) from None
    if pct is not None and pct != 100:
        raise SchedulerConfigError(
            f"percentageOfNodesToScore={pct} unsupported: this simulator "
            "always scores 100% of nodes (the reference forces the same, "
            "utils.go:234)"
        )
    if doc.get("extenders"):
        raise SchedulerConfigError(
            "scheduler extenders are not supported: there is no external "
            "extender protocol over the array state"
        )
    profiles = doc.get("profiles") or []
    if not profiles:
        return default_scheduler_config()
    profile = profiles[0]
    plugins = profile.get("plugins") or {}
    score = plugins.get("score") or {}

    # k8s profile-merge semantics (vendored defaultPlugins.Apply): the
    # `disabled` list strips plugins from the DEFAULT set only; `enabled`
    # entries are then appended and always win. The reference's own example
    # configs list a plugin in both (disable-everything boilerplate + the
    # chosen policy re-enabled), so skipping enabled-plugins-in-disabled
    # would silently fall back to the wrong profile. The k8s built-in score
    # defaults the boilerplate strips are exactly IGNORED_SCORE_PLUGINS,
    # which have no analogue over the array state — so `disabled` carries
    # no further information here.
    cfg = SchedulerConfig()
    for p in score.get("enabled") or []:
        name = p.get("name")
        if name in IGNORED_SCORE_PLUGINS:
            continue
        if name not in KNOWN_SCORE_PLUGINS:
            raise SchedulerConfigError(f"unknown score plugin: {name}")
        cfg.policies.append((name, int(p.get("weight", 1) or 1)))
    if not cfg.policies:
        cfg = default_scheduler_config()

    # pluginConfig args: last writer wins per arg, matching the reference's
    # per-plugin structs all carrying the same three knobs
    for pc in profile.get("pluginConfig") or []:
        args = pc.get("args") or {}
        if "dimExtMethod" in args:
            cfg.dim_ext_method = str(args["dimExtMethod"])
        if "normMethod" in args:
            cfg.norm_method = str(args["normMethod"])
        if "gpuSelMethod" in args:
            cfg.gpu_sel_method = str(args["gpuSelMethod"])

    # forced defaults (utils.go:234-235, 312)
    cfg.percentage_of_nodes_to_score = 100
    cfg.scheduler_name = profile.get("schedulerName") or SCHEDULER_NAME
    _validate_methods(cfg)
    return cfg


def _validate_methods(cfg: SchedulerConfig) -> None:
    if cfg.dim_ext_method not in ("merge", "share", "divide", "extend"):
        raise SchedulerConfigError(f"bad dimExtMethod: {cfg.dim_ext_method}")
    if cfg.norm_method not in ("node", "pod", "max"):
        raise SchedulerConfigError(f"bad normMethod: {cfg.norm_method}")
    sel_ok = ("best", "worst", "random") + tuple(KNOWN_SCORE_PLUGINS)
    if cfg.gpu_sel_method not in sel_ok:
        raise SchedulerConfigError(f"bad gpuSelMethod: {cfg.gpu_sel_method}")


def load_scheduler_config(path: str = "") -> SchedulerConfig:
    if not path:
        return default_scheduler_config()
    from tpusim.config.simon import load_yaml_lenient

    doc = load_yaml_lenient(path)
    if not isinstance(doc, dict):
        raise SchedulerConfigError(f"{path}: not a YAML mapping")
    return parse_scheduler_config(doc)
