"""The simulator's own CR: `apiVersion: simon/v1alpha1, kind: Config`.

Faithful schema + validation of the reference's config object
(ref: pkg/api/v1alpha1/types.go:13-109; validation pkg/apply/apply.go:252-286)
so existing cluster-config YAMLs drive this framework unchanged.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional

import yaml

from tpusim.sim.typical import TypicalPodsConfig

API_VERSION = "simon/v1alpha1"
KIND = "Config"


@dataclass
class ExportConfig:
    """ref: types.go:70-73."""

    pod_snapshot_yaml_file_prefix: str = ""
    node_snapshot_csv_file_prefix: str = ""


@dataclass
class WorkloadInflationConfig:
    """ref: types.go:78-81."""

    ratio: float = 1.0
    seed: int = 233


@dataclass
class WorkloadTuningConfig:
    """ref: types.go:86-89. ratio <= 0 means no effect."""

    ratio: float = 0.0
    seed: int = 233


@dataclass
class DescheduleConfig:
    """ref: types.go:94-97."""

    ratio: float = 0.0
    policy: str = ""


@dataclass
class CustomConfig:
    """ref: types.go:57-65 + TypicalPodsConfig :104-109."""

    shuffle_pod: bool = False
    export: ExportConfig = field(default_factory=ExportConfig)
    inflation: WorkloadInflationConfig = field(
        default_factory=WorkloadInflationConfig
    )
    tuning: WorkloadTuningConfig = field(default_factory=WorkloadTuningConfig)
    new_workload_config: str = ""
    deschedule: DescheduleConfig = field(default_factory=DescheduleConfig)
    typical_pods: TypicalPodsConfig = field(default_factory=TypicalPodsConfig)
    # Annotation-driven create+delete replay (ref: simulator.go:672-717).
    # The reference has no CR knob for this — the mode is implied by
    # creation-time/deletion-time annotations being present on the pods
    # (its experiment pipeline strips them, pod_csv_to_yaml.py:119-120,
    # which degrades the stable timestamp sort to list order). Since this
    # build ingests traces that always carry timestamps, the switch is
    # explicit.
    use_timestamps: bool = False
    # Replay engine selection (no reference counterpart — the engines are
    # this build's execution strategies, ENGINES.md):
    # auto | sequential | table | pallas. Validated by Simulator.__init__.
    engine: str = "auto"
    # Device-mesh width for the explicit-collective shard_map engine
    # (MULTICHIP.md): 0 = single device; N > 1 shards the node axis over
    # an N-device jax.sharding.Mesh. The multi-chip analogue of the
    # reference's process fan-out (experiments/README.md step 2).
    mesh: int = 0


@dataclass
class AppInfo:
    """ref: types.go AppInfo (name/path/chart)."""

    name: str
    path: str
    chart: bool = False


@dataclass
class SimonCR:
    name: str = ""
    custom_cluster: str = ""  # YAML dir with node/pod manifests
    kube_config: str = ""  # real-cluster path (gated: no cluster here)
    app_list: List[AppInfo] = field(default_factory=list)
    new_node: str = ""  # parsed for schema parity; unused by the reference
    # revision too (no consumer of SimonSpec.NewNode in pkg/)
    custom_config: CustomConfig = field(default_factory=CustomConfig)


class ConfigError(ValueError):
    pass


def _expand_tabs_outside_quotes(text: str) -> str:
    """Replace whitespace tabs with spaces, leaving tabs inside single/
    double-quoted scalars intact (those are valid YAML data)."""
    out = []
    for line in text.split("\n"):
        quote = ""
        buf = []
        for ch in line:
            if quote:
                if ch == quote:
                    quote = ""
                buf.append(ch)
            elif ch in "\"'":
                quote = ch
                buf.append(ch)
            elif ch == "\t":
                buf.append("    ")
            else:
                buf.append(ch)
        out.append("".join(buf))
    return "\n".join(out)


def load_yaml_lenient(path: str):
    """YAML load tolerating literal TABs (the reference's example configs
    use tab-indented comments, which Go's sigs.k8s.io/yaml accepts but
    strict YAML rejects). On a tab ScannerError, retry with whitespace tabs
    expanded to spaces (quoted scalars untouched) — drop-in compatibility
    with the reference's shipped files."""
    with open(path) as f:
        text = f.read()
    try:
        return yaml.safe_load(text)
    except yaml.error.YAMLError as e:
        if "\\t" not in str(e) and "'\t'" not in str(e):
            raise
        return yaml.safe_load(_expand_tabs_outside_quotes(text))


def _typical(d: dict) -> TypicalPodsConfig:
    return TypicalPodsConfig(
        is_involved_cpu_pods=bool(d.get("isInvolvedCpuPods", False)),
        pod_popularity_threshold=int(d.get("podPopularityThreshold", 0)),
        pod_increase_step=int(d.get("podIncreaseStep", 0)),
        gpu_res_weight=float(d.get("gpuResWeight", 0.0)),
    )


def parse_simon_cr(doc: dict, base_dir: str = ".") -> SimonCR:
    if doc.get("apiVersion") != API_VERSION or doc.get("kind") != KIND:
        raise ConfigError(
            f"expected apiVersion={API_VERSION} kind={KIND}, got "
            f"{doc.get('apiVersion')}/{doc.get('kind')}"
        )
    spec = doc.get("spec") or {}
    cluster = spec.get("cluster") or {}
    custom_cluster = cluster.get("customConfig", "") or ""
    kube_config = cluster.get("kubeConfig", "") or ""
    # exactly one source of cluster truth (apply.go:252-286 validate)
    if bool(custom_cluster) == bool(kube_config):
        raise ConfigError(
            "spec.cluster must set exactly one of customConfig / kubeConfig"
        )

    cc_raw = spec.get("customConfig") or {}
    exp = cc_raw.get("exportConfig") or {}
    infl = cc_raw.get("workloadInflationConfig") or {}
    tune = cc_raw.get("workloadTuningConfig") or {}
    desch = cc_raw.get("descheduleConfig") or {}
    cc = CustomConfig(
        shuffle_pod=bool(cc_raw.get("shufflePod", False)),
        export=ExportConfig(
            pod_snapshot_yaml_file_prefix=str(
                exp.get("podSnapshotYamlFilePrefix") or ""
            ),
            node_snapshot_csv_file_prefix=str(
                exp.get("nodeSnapshotCSVFilePrefix") or ""
            ),
        ),
        inflation=WorkloadInflationConfig(
            ratio=float(infl.get("ratio", 1.0) or 1.0),
            seed=int(infl.get("seed", 233) or 233),
        ),
        tuning=WorkloadTuningConfig(
            ratio=float(tune.get("ratio", 0.0) or 0.0),
            seed=int(tune.get("seed", 233) or 233),
        ),
        new_workload_config=str(cc_raw.get("newWorkloadConfig") or ""),
        deschedule=DescheduleConfig(
            ratio=float(desch.get("ratio", 0.0) or 0.0),
            policy=str(desch.get("policy") or ""),
        ),
        typical_pods=_typical(cc_raw.get("typicalPodsConfig") or {}),
        use_timestamps=bool(cc_raw.get("useTimestamps", False)),
        engine=str(cc_raw.get("engine") or "auto"),
        mesh=int(cc_raw.get("mesh") or 0),
    )

    apps = []
    for a in spec.get("appList") or []:
        path = a.get("path", "")
        if not path:
            raise ConfigError(f"appList entry {a.get('name')!r} has no path")
        if not os.path.isabs(path):
            path = os.path.join(base_dir, path)
        apps.append(
            AppInfo(
                name=a.get("name", ""),
                path=path,
                chart=bool(a.get("chart", False)),
            )
        )
    if custom_cluster and not os.path.isabs(custom_cluster):
        custom_cluster = os.path.join(base_dir, custom_cluster)
    if kube_config and not os.path.isabs(kube_config):
        kube_config = os.path.join(base_dir, kube_config)
    return SimonCR(
        name=(doc.get("metadata") or {}).get("name", ""),
        custom_cluster=custom_cluster,
        kube_config=kube_config,
        app_list=apps,
        new_node=str(spec.get("newNode") or ""),
        custom_config=cc,
    )


def load_simon_cr(path: str, base_dir: Optional[str] = None) -> SimonCR:
    """Read + validate a cluster-config YAML. Relative paths inside the CR
    resolve against `base_dir` (default: cwd, matching the reference's
    project-relative convention)."""
    doc = load_yaml_lenient(path)
    if not isinstance(doc, dict):
        raise ConfigError(f"{path}: not a YAML mapping")
    return parse_simon_cr(doc, base_dir or ".")
