from tpusim.config.simon import (
    AppInfo,
    CustomConfig,
    DescheduleConfig,
    ExportConfig,
    SimonCR,
    WorkloadInflationConfig,
    WorkloadTuningConfig,
    load_simon_cr,
)
from tpusim.config.scheduler import SchedulerConfig, load_scheduler_config

__all__ = [
    "AppInfo",
    "CustomConfig",
    "DescheduleConfig",
    "ExportConfig",
    "SimonCR",
    "WorkloadInflationConfig",
    "WorkloadTuningConfig",
    "load_simon_cr",
    "SchedulerConfig",
    "load_scheduler_config",
]
