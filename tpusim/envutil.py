"""Fail-loud environment-knob parsing, shared across subsystems.

One helper family for every `TPUSIM_*` tuning variable: an unparseable
or out-of-range value raises a ValueError NAMING THE VARIABLE at the
first read instead of silently falling back to the default (ISSUE 15
satellite, generalizing the svc/leases.py `_float_env` pattern from
ISSUE 13). A typo'd knob that silently reverts is worse than a crash:
a mis-set lease skew can make a whole fleet's leases instantly
stealable, and a mis-set Pallas VMEM budget silently re-opens the
graceful-degradation path the operator thought they had widened.
"""

from __future__ import annotations

import os


def float_env(name: str, default: float, minimum: float = 0.0) -> float:
    """Read one float env knob, failing LOUDLY on an unparseable or
    out-of-range value, with the variable named in the message."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not a valid number (want a float, e.g. "
            f"{name}={default}); unset it to use the default {default}"
        )
    if val != val or val in (float("inf"), float("-inf")) \
            or val < minimum:
        raise ValueError(
            f"{name}={raw!r} must be a finite number >= {minimum} "
            f"(got {val}); unset it to use the default {default}"
        )
    return val


def int_env(name: str, default: int, minimum: int = 0) -> int:
    """Read one integer env knob, failing LOUDLY on a non-integer or
    out-of-range value, with the variable named in the message. The
    float twin's contract, for byte/count knobs (int() also accepts
    '  16777216 ' but rejects '14MB' and '1.5e7' — sizes are exact)."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not a valid integer (want e.g. "
            f"{name}={default}); unset it to use the default {default}"
        )
    if val < minimum:
        raise ValueError(
            f"{name}={raw!r} must be an integer >= {minimum} "
            f"(got {val}); unset it to use the default {default}"
        )
    return val
