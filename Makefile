# Convenience targets (the package is pure Python + an optional on-demand
# C++ component; there is no build step — ref parity: Makefile builds bin/simon).

.PHONY: test test-tpu bench bench-scale bench-scale-smoke sweep native clean

test:
	python -m pytest tests/ -q

# on-accelerator lane: golden frag values + engine equivalence on the chip
test-tpu:
	TPUSIM_TPU_TESTS=1 python -m pytest tests/ -m tpu -q

bench:
	python bench.py

bench-scale:
	python bench_scale.py

# fast scale-lane regression gate on the CPU backend: 10k nodes trips the
# blocked table-engine select (ENGINES.md "blocked table" row); a few
# thousand pods keep the whole run to a couple of minutes
bench-scale-smoke:
	JAX_PLATFORMS=cpu python bench_scale.py --nodes 10000 --pods 5000 --chunk 5000

sweep:
	python experiments/sweep.py

native:
	g++ -O2 -shared -fPIC -o tpusim/native/_bellman.so tpusim/native/bellman.cpp

clean:
	rm -f tpusim/native/_bellman.so
	find . -name __pycache__ -type d -exec rm -rf {} +
