# Convenience targets (the package is pure Python + an optional on-demand
# C++ component; there is no build step — ref parity: Makefile builds bin/simon).

.PHONY: test test-fast test-tpu bench bench-scale bench-scale-smoke resume-smoke profile-smoke serve-smoke sweep-smoke svc-smoke serve-latency-smoke tune-smoke policy-smoke pallas-hbm-smoke chaos-smoke mesh-chaos-smoke fleet-chaos-smoke fleet-wan-smoke fleet-ha-smoke fleet-trace-smoke slo-smoke bench-gate sweep native clean

# full suite, INCLUDING @pytest.mark.slow tests (pallas interpreter
# sweeps, openb kill/resume, the full Bellman replay)
test:
	python -m pytest tests/ -q

# the tier-1 lane (ROADMAP.md verify command): slow-marked tests excluded
test-fast:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'

# on-accelerator lane: golden frag values + engine equivalence on the chip
test-tpu:
	TPUSIM_TPU_TESTS=1 python -m pytest tests/ -m tpu -q

bench:
	python bench.py

bench-scale:
	python bench_scale.py

# fast scale-lane regression gate on the CPU backend: 10k nodes trips the
# blocked table-engine select (ENGINES.md "blocked table" row); a few
# thousand pods keep the whole run to a couple of minutes
bench-scale-smoke:
	JAX_PLATFORMS=cpu python bench_scale.py --nodes 10000 --pods 5000 --chunk 5000

# kill/resume gate (ENGINES.md "Checkpoint/resume"): replay an openb
# prefix, kill the run right after a mid-trace checkpoint lands, resume in
# a fresh process, and assert the final placements/metrics/tables are
# byte-identical to the uninterrupted run — plus the fault-injection
# determinism suite, the obs telemetry-continuity/counter-invariance
# suite, and the decision-provenance suite (cross-engine record
# invariance incl. the shard top-K collective, decision-stream
# kill/resume + fault-segment continuity, openb explain/diff goldens),
# and the live-telemetry suite (in-scan series cross-engine invariance,
# series kill/resume + fault-segment continuity, /metrics-vs-textfile
# equality, serve smoke), and the config-axis sweep suite (weight-operand
# cross-engine bit-identity, the B=16 openb acceptance). Runs the full
# files including slow-marked cases (the synthetic kill/resume +
# telemetry subsets are already wired into tier-1).
resume-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_checkpoint.py tests/test_deschedule.py tests/test_fork.py tests/test_faults.py tests/test_fault_lane.py tests/test_obs.py tests/test_decisions.py tests/test_series.py tests/test_sweep.py tests/test_svc.py tests/test_svc_fork.py tests/test_learn.py tests/test_pipeline.py tests/test_fleet.py tests/test_ha.py tests/test_transfer.py tests/test_trace_audit.py tests/test_supervisor.py tests/test_policy_learned.py tests/test_blocked_engine.py tests/test_pallas_hbm.py tests/test_table_engine.py tests/test_parallel.py tests/test_pallas_engine.py tests/test_batch.py tests/test_kube_client.py -q

# config-axis sweep smoke (ENGINES.md "Round 11"): the weight-operand /
# vmapped-sweep suite (cross-engine bit-identity under traced weights,
# the B=16 openb acceptance incl. the one-compile and marginal-cost
# bounds), then a small end-to-end `bench_scale --sweep` row through the
# persistent compilation cache. Runs the slow-marked cases tier-1 skips.
sweep-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_sweep.py -q
	JAX_PLATFORMS=cpu TPUSIM_COMPILE_CACHE_DIR=.tpusim_obs/compile_cache \
		python bench_scale.py --nodes 1500 --pods 2000 --sweep 4

# observability smoke (ENGINES.md "Round 8"/"Round 10"): a small
# profiled scale run emitting the full artifact set — JSONL run record
# (spans with the compile/execute split + exact scan counters + the
# in-scan series block), Prometheus textfile, Chrome-trace timeline
# (with series counter tracks) — under the ignored .tpusim_obs/ scratch
# dir, never the repo root
profile-smoke:
	JAX_PLATFORMS=cpu python bench_scale.py --nodes 2000 --pods 2000 \
		--chunk 1000 --heartbeat 500 --series-every 100 \
		--profile .tpusim_obs/scale_profile.jsonl \
		--metrics-out .tpusim_obs/scale_metrics.prom \
		--trace-out .tpusim_obs/scale_trace.json

# live-monitoring smoke (ENGINES.md "Round 10"): regenerate the profile
# artifacts, then point `tpusim serve --once` at the scratch dir — one
# poll, a real HTTP self-scrape, exit 0 iff /metrics parses as
# exposition text. The long-running form (`tpusim serve .tpusim_obs`)
# is the second-terminal view of a live checkpointed run.
serve-smoke: profile-smoke
	JAX_PLATFORMS=cpu python -m tpusim serve .tpusim_obs --once --listen :0

# replay-service smoke (ENGINES.md "Round 12"): boot `serve --jobs` on
# an ephemeral port, POST a 4-job grid (weights + tune-factor variants
# plus an exact duplicate) over real HTTP, poll to done, and assert the
# service contracts — the duplicate answered from the digest cache, the
# fresh jobs batched onto ONE compiled sweep, and a second weights+tune
# wave adding ZERO executables (jit._cache_size() stable).
svc-smoke:
	JAX_PLATFORMS=cpu python -m tpusim.obs.gate --svc-only

# interactive what-if serving smoke (ENGINES.md "Round 20"): the
# warm-state fork plane over real HTTP — a base job leaves its
# checkpoint ladder + fork-index entry, then a wave of warm forks and
# their from-event-0 "full" twins (more jobs than lanes: late arrivals
# JOIN the running wave at chunk boundaries). Hard checks: every fork
# bit-identical to its twin, every fork executed <= tail + one chunk
# events, wave executables UNCHANGED across the join wave
# (jit._cache_size() live), and the warm forks' admission->result p99
# under the hard SLO AND >= 3x faster than the full-replay p99.
serve-latency-smoke:
	JAX_PLATFORMS=cpu python -m tpusim.obs.gate --serve-latency-only

# learned-scoring smoke (ENGINES.md "Round 13"): run `tpusim tune`'s
# loop on a tiny synthetic trace for 3 generations on the local backend
# and hard-check the lane's contracts — ONE compiled sweep executable
# across every generation (jit._cache_size() stable: weights are traced
# operands, the population is one vmapped scan), the digest-signed
# tuning log reads back, and a resume of the finished log is a
# byte-identical no-op.
tune-smoke:
	JAX_PLATFORMS=cpu python -m tpusim.obs.gate --tune-only

# learned-policy smoke (ENGINES.md "Round 18"): the LearnedScore lane
# end-to-end on a tiny synthetic trace with a forced 2-device virtual
# mesh — imitation round-trip off a recorded FGD teacher (dataset
# builder feasibility cross-check + train + i32 export), the signed
# artifact replaying BIT-identically on the sequential/flat/blocked/
# shard engines, one-executable ES policy search (hard
# jit._cache_size() check), signed-artifact round-trip + torn-file
# rejection, and a served policy preset answering a submit job with
# the exact local placements.
policy-smoke:
	JAX_PLATFORMS=cpu python -m tpusim.obs.gate --policy-only

# HBM-residency pallas smoke (ENGINES.md "Round 19"): the fused Pallas
# engine past the old N <= 4096 VMEM ceiling — a synthetic N=8192/K=151
# trace replayed by the HBM-resident-table kernel in interpreter mode,
# WITHOUT degrading to the blocked table engine, bit-identical
# placements/devices to it; the two-tier residency auto-select pinned
# at both tiers (vmem below the ceiling, hbm above, degrade only when
# neither fits), the documented HBM ceiling >= 256k nodes at K=151,
# and the kernel's exact in-kernel DMA counters (waits == starts — no
# leaked transfers) present in the obs run record.
pallas-hbm-smoke:
	JAX_PLATFORMS=cpu python -m tpusim.obs.gate --pallas-hbm-only

# chaos-sweep smoke (ENGINES.md "Round 14"): a tiny B-lane fault sweep
# (one trace, varying fault seed/MTBF/evict cadence as per-lane
# operands) with the hard contracts — ONE compiled chaos executable, a
# second wave of DIFFERENT schedules adding ZERO executables
# (jit._cache_size() stable), and lane 0's placements +
# DisruptionMetrics reconciling exactly against the standalone
# single-lane run_with_faults path.
chaos-smoke:
	JAX_PLATFORMS=cpu python -m tpusim.obs.gate --chaos-only

# mesh-chaos smoke (ENGINES.md "Round 15"): the pipelined shard engine
# on a small forced-virtual mesh — a FAULTED mesh replay must reconcile
# the single-device fault lane exactly (retry pops + DOWN-row resets
# through the pending registers) with the frag-delta degrade loud, and
# a chunked replay with buffer DONATION armed must hold ONE compiled
# executable across equal-size chunks, consume its input carries, keep
# the live-buffer census stable (nothing re-materialized), and finish
# bit-identical to the one-shot replay. Also prints the advisory
# comparison of the newest committed MULTICHIP_r*.json scale capture.
mesh-chaos-smoke:
	JAX_PLATFORMS=cpu python -m tpusim.obs.gate --mesh-chaos-only

# fleet-chaos smoke (ENGINES.md "Round 16"): the kill-tolerant worker
# fleet end-to-end — a single-worker reference run (cold caches), then
# a coordinator + 3 worker PROCESSES on the same caches with a random
# `kill -9` mid-batch. Hard checks: 100% of accepted jobs reach signed
# results BYTE-identical to the single-worker run, the dead worker's
# leases are stolen without operator action (/queue steals +
# lease_expired), and a fresh joiner's first batch skips the cold
# compile via the shared persistent-compile/table caches.
fleet-chaos-smoke:
	JAX_PLATFORMS=cpu python -m tpusim.obs.gate --fleet-chaos-only

# fleet-wan smoke (ENGINES.md "Round 17"): the wide-area fleet with NO
# shared filesystem — a coordinator hosting TWO traces behind a flaky
# HTTP shim (drops/delays ~20% of transfer requests), a supervisor
# spawning remote-mode workers with fully isolated per-worker dirs
# (digest-verified trace downloads, signed-result uploads, lease
# POSTs), a random `kill -9` of a remote worker mid-batch, and a
# forced crash loop. Hard checks: 100% completion with per-file byte
# identity vs the single-worker reference, the supervisor's respawn
# counter >= 1 in /queue, remote transfer counters live in /workers, a
# torn upload rejected with nothing written, and the crash loop
# tripping the circuit breaker into a loud degraded /healthz instead
# of spinning.
fleet-wan-smoke:
	JAX_PLATFORMS=cpu python -m tpusim.obs.gate --fleet-wan-only

# fleet-ha smoke (ENGINES.md "Round 21"): coordinator failover end to
# end — a token-armed leader + standby CLI pair sharing one artifact
# dir, two workers joined against BOTH urls, jobs submitted through
# the failover client, then `kill -9` of the LEADER while leases are
# held mid-batch. Hard checks: the standby promotes at a bumped epoch
# (role/epoch live on /healthz), workers re-register and finish 100%
# of jobs with per-file byte identity vs a single-coordinator
# reference, a stale-epoch op answers 409, every mutating endpoint
# rejects missing/forged tokens with 401, the resurrected old leader
# fences itself to standby, and token material never reaches /queue.
fleet-ha-smoke:
	JAX_PLATFORMS=cpu python -m tpusim.obs.gate --fleet-ha-only

# fleet-trace smoke (ENGINES.md "Round 22"): the fleet flight recorder
# end-to-end — a coordinator + supervised worker pair over real HTTP,
# jobs submitted BEFORE the workers join, then `kill -9` of the first
# lease-holder mid-batch. Hard checks: every job completes with a
# gap-free stitched cross-process timeline (admission/queue-wait/claim/
# dispatch/upload/verify spans all carrying the ONE trace id minted at
# submit; zero orphan spans; the killed worker's half-open attempt
# stitched as ABANDONED), the `tpusim trace` / `tpusim audit` verbs
# exit 0 against the artifact dir (Chrome-trace export written), the
# hash-chained audit log verifies end-to-end recording BOTH the steal
# and the supervisor's respawn, and the aggregated coordinator
# /metrics parses as exposition text with a worker=-labeled series set
# for every live worker that served a batch.
fleet-trace-smoke:
	JAX_PLATFORMS=cpu python -m tpusim.obs.gate --fleet-trace-only

# SLO-plane smoke (ENGINES.md "Round 23"): the metrics-history +
# burn-rate alerting plane end-to-end over real HTTP. A coordinator
# armed with a tight --slo-file fork-p99 burn rule serves a base run,
# then a COLD fork wave (the induced latency regression) fires the
# burn-rate page — visible on /alerts, flipping /healthz to 503 with
# the alert named, shown by `tpusim top --once`, with the native
# per-kind latency summary on /metrics, the event series on /query,
# cursor pagination on /events, and the kind=alert record in a
# VERIFYING hash-chained audit log — then warm forks (recovery)
# displace the burn windows and the alert RESOLVES under live traffic.
# A forced crash loop trips the supervisor breaker and fires the
# built-in breaker-open page. Finally a leader + standby CLI pair:
# kill -9 the leader, the standby promotes at a bumped epoch and
# ADOPTS the signed tsdb snapshot — /query history splices with no
# gap (pre-kill points within snapshot cadence of the kill).
slo-smoke:
	JAX_PLATFORMS=cpu python -m tpusim.obs.gate --slo-only

# bench regression gate (tpusim.obs.gate): re-run the headline openb FGD
# measurement under profiling and diff it against the newest committed
# BENCH_r*.json baseline — exact on events/placements/gpu_alloc
# (machine-independent), tolerance-gated on same-backend throughput,
# advisory on cross-backend throughput. Also smoke-checks the decision
# JSONL round-trip (ISSUE 4), that a live /metrics scrape of the smoke
# record parses and is byte-equal to the emitted textfile (ISSUE 5),
# the one-compile sweep contract (ISSUE 6), the replay-service POST
# path — dedup + zero recompiles (ISSUE 7, the svc-smoke check) — and
# the interactive what-if serving plane (ISSUE 16, the
# serve-latency-smoke check: warm forks bit-identical to from-0 twins,
# boundary joins with zero recompiles, hard admission->result p99
# SLO), and the learned-scoring loop (ISSUE 9, the tune-smoke check: one
# executable across generations, signed resumable log), and the chaos
# sweep (ISSUE 10, the chaos-smoke check: fault schedules as operands —
# zero recompiles across waves, lane-vs-standalone disruption
# reconciliation), and the worker fleet (ISSUE 12, the
# fleet-chaos-smoke check: kill -9 mid-batch, orphan stealing,
# byte-identical results, warm-joiner compile skip), and the wide-area
# fleet (ISSUE 13, the fleet-wan-smoke check: no-shared-fs workers
# under injected transfer faults, supervisor respawn, circuit
# breaker), and coordinator HA (ISSUE 17, the fleet-ha-smoke check:
# kill -9 the leader mid-batch, epoch-fenced standby takeover, auth
# probes, byte-identity vs a single-coordinator reference), and the
# fleet flight recorder (ISSUE 19, the fleet-trace-smoke check:
# stitched cross-process timelines across a kill -9 + steal, the
# hash-chained audit log, aggregated per-worker /metrics), and the SLO
# plane (ISSUE 20, the slo-smoke check: induced fork regression fires
# a burn-rate page that resolves under recovery traffic, breaker trip
# pages, /query history survives a kill -9 takeover). Exit 1 on
# regression; artifacts land in .tpusim_obs/.
bench-gate:
	JAX_PLATFORMS=cpu python -m tpusim.obs.gate

sweep:
	python experiments/sweep.py

native:
	g++ -O2 -shared -fPIC -o tpusim/native/_bellman.so tpusim/native/bellman.cpp

clean:
	rm -f tpusim/native/_bellman.so
	find . -name __pycache__ -type d -exec rm -rf {} +
