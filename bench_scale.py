#!/usr/bin/env python
"""Synthetic scale stress: 100k-node cluster / 1M-pod stream under FGD
(BASELINE.json config 5 — "Synthetic 100k-node / 1M-pod stress").

The openb cluster (1523 nodes) is tiled out to --nodes heterogeneous nodes
(same SKU mix) and a --pods creation stream is sampled from the openb
typical-pod distribution. --engine picks the replay engine (the fused
Pallas engine's VMEM-resident tables bound its N; the table engine scales
to 100k nodes — measured table in ENGINES.md); for the node-axis sharded
multi-device path see tpusim.parallel and tests/test_parallel.py.

    python bench_scale.py                     # 100k nodes, 1M pods, 1 chip
    python bench_scale.py --nodes 10000 --pods 100000
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


def synth_cluster(num_nodes: int, seed: int = 0):
    import numpy as np

    from tpusim.io.trace import load_node_csv

    base = load_node_csv(os.path.join(REPO, "data/csv/openb_node_list_gpu_node.csv"))
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, len(base), num_nodes)
    rows = []
    for i, j in enumerate(idx):
        b = base[int(j)]
        rows.append(
            type(b)(
                name=f"synth-{i:06d}",
                cpu_milli=b.cpu_milli,
                memory_mib=b.memory_mib,
                gpu=b.gpu,
                model=b.model,
                cpu_model=b.cpu_model,
            )
        )
    return rows


def synth_pods(num_pods: int, seed: int = 1):
    import numpy as np

    from tpusim.io.trace import load_pod_csv

    base = load_pod_csv(os.path.join(REPO, "data/csv/openb_pod_list_default.csv"))
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, len(base), num_pods)
    rows = []
    for i, j in enumerate(idx):
        b = base[int(j)]
        rows.append(
            type(b)(
                name=f"sp-{i:07d}",
                cpu_milli=b.cpu_milli,
                memory_mib=b.memory_mib,
                num_gpu=b.num_gpu,
                gpu_milli=b.gpu_milli,
                gpu_spec=b.gpu_spec,
            )
        )
    return rows


def run_sweep_bench(args, sim, cache_dir):
    """`--sweep B[,B...]` (ISSUE 6): measure the config-axis sweep — one
    row per batch size B with the cold wall (first dispatch, incl. the
    ONE scan compile the whole weight grid shares), the warm wall, and
    the marginal per-config cost against a standalone warm replay of the
    same workload. The weight rows are distinct (base - i per config) so
    every lane is a real what-if, yet all of them run one jaxpr — the
    one-compile-per-job-family contract `replay.engine` carries."""
    import jax
    import numpy as np

    from tpusim.io.trace import build_events, pods_to_specs
    from tpusim.obs import bench as obs_bench
    from tpusim.sim.driver import schedule_pods_sweep

    bs = sorted({int(x) for x in str(args.sweep).split(",") if x.strip()})
    if not bs or min(bs) < 1:
        raise SystemExit(f"--sweep wants positive batch sizes, got {args.sweep!r}")

    trace = sim.prepare_pods()
    specs = pods_to_specs(trace)
    ev_kind, ev_pod = build_events(trace)
    events = len(ev_kind)
    cfg = sim.cfg
    base_w = np.asarray([w for _, w in cfg.policies], np.int32)

    # standalone warm baseline: the regular single-config replay the
    # marginal per-config cost is judged against (same protocol as
    # bench.py: one compile run, then a warm minimum)
    import jax.numpy as jnp

    ev_kind_d, ev_pod_d = jnp.asarray(ev_kind), jnp.asarray(ev_pod)
    key = jax.random.PRNGKey(cfg.seed)

    def standalone():
        # same bucket as schedule_pods_sweep's default so both sides pad
        # the event stream identically — the per-config ratio compares
        # equal replay lengths
        res = sim.run_events(
            sim.init_state, specs, ev_kind_d, ev_pod_d, key, bucket=512
        )
        jax.block_until_ready(res.state)

    m0 = obs_bench.measure(standalone, warm_runs=2)
    standalone_warm = m0["min_s"]
    print(
        f"[sweep] standalone nodes={args.nodes} pods={args.pods} "
        f"events={events} engine={sim._last_engine} "
        f"warm={standalone_warm:.3f}s (first incl. compile "
        f"{m0['first_s']:.1f}s)"
    )

    rows = []
    for b in bs:
        # distinct rows: every lane is a genuine what-if configuration
        grid = np.stack([base_w - i for i in range(b)]).astype(np.int32)
        box = {}

        def run_b(grid=grid, box=box):
            box["lanes"] = schedule_pods_sweep(sim, trace, grid)

        m = obs_bench.measure(run_b, warm_runs=2)
        per_cfg = m["min_s"] / b
        ratio = per_cfg / standalone_warm if standalone_warm else 0.0
        row = obs_bench.round_row({
            "b": b,
            "events": events,
            "engine": sim._last_engine,
            "cold_s": m["first_s"],
            "warm_s": m["min_s"],
            "per_config_s": per_cfg,
            "ratio_vs_standalone": round(ratio, 3),
            "placed_lane0": box["lanes"][0].placed,
        })
        rows.append(row)
        print(
            f"[sweep] B={b} cold={row['cold_s']:.1f}s "
            f"warm={row['warm_s']:.3f}s per_config={row['per_config_s']:.3f}s "
            f"ratio_vs_standalone={row['ratio_vs_standalone']:.3f} "
            f"engine={row['engine']}"
        )

    if args.sweep_out:
        payload = {
            # BENCH_rNN.json-shape capture WITHOUT a `parsed` key: the
            # gate must never mistake sweep rows for the headline
            # throughput baseline — it reads the `sweep` block instead
            "cmd": "python bench_scale.py --sweep "
            + ",".join(str(b) for b in bs)
            + f" --nodes {args.nodes} --pods {args.pods}",
            "rc": 0,
            "sweep": {
                "nodes": args.nodes,
                "pods": args.pods,
                "events": events,
                "policies": [name for name, _ in cfg.policies],
                "backend": jax.default_backend(),
                "compile_cache": bool(cache_dir),
                "standalone_warm_s": round(standalone_warm, 3),
                "standalone_cold_s": round(m0["first_s"], 3),
                "rows": rows,
            },
        }
        obs_bench.write_json(args.sweep_out, payload)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=100_000)
    ap.add_argument("--pods", type=int, default=1_000_000)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument(
        "--engine", type=str, default="auto",
        help="replay engine (auto | sequential | table | pallas): the "
        "N-scaling comparison in ENGINES.md runs table vs pallas at "
        "several --nodes values",
    )
    ap.add_argument(
        "--block-size", type=int, default=0,
        help="table-engine select layout (SimulatorConfig.block_size): "
        "0 = auto (blocked incremental reductions at large N), > 0 "
        "forces that block size, -1 forces the flat O(N) select — the "
        "blocked-vs-flat rows in ENGINES.md compare 0 against -1",
    )
    ap.add_argument(
        "--unswitched", action="store_true",
        help="flat-path select layout A/B (ENGINES.md Round 18): run "
        "the unconditional-select form instead of the event switch "
        "(SimulatorConfig.unswitched_select); bit-identical, throughput "
        "differs per backend",
    )
    ap.add_argument(
        "--pallas-residency", default="auto", metavar="auto|vmem|hbm",
        help="fused-Pallas table residency (SimulatorConfig."
        "table_residency, ENGINES.md Round 19): where the [K, N] score "
        "tables live — 'vmem' is the all-resident kernel (ceiling "
        "N <= 4096 at K = 151), 'hbm' the HBM-resident-table kernel "
        "with per-event double-buffered DMA (ceiling >= 256k), 'auto' "
        "the two-tier footprint select; bit-identical either way",
    )
    ap.add_argument(
        "--pallas-ceiling", action="store_true",
        help="print the two-tier Pallas residency ceiling sweep instead "
        "of running: for each tier the max N whose footprint fits the "
        "TPUSIM_PALLAS_VMEM_BYTES budget at this run's K/policy shape "
        "(the ENGINES.md Round 19 capture), then exit",
    )
    ap.add_argument(
        "--chunk",
        type=int,
        default=200_000,
        help="events per device dispatch (a single multi-minute XLA "
        "execution can exceed the TPU transport's per-call limits; state "
        "carries across chunks, which is exact for this creation-only "
        "stream — mixed create/delete streams must replay in one call)",
    )
    # observability (tpusim.obs; README "Profiling & telemetry")
    ap.add_argument(
        "--profile", default="", metavar="PATH",
        help="profile the run (phase spans with compile/execute split, "
        "exact scan counters) and append the JSONL run record here",
    )
    ap.add_argument(
        "--metrics-out", default="", metavar="PATH",
        help="write a Prometheus textfile snapshot of the run telemetry",
    )
    ap.add_argument(
        "--trace-out", default="", metavar="PATH",
        help="write a Chrome-trace timeline of the phase spans",
    )
    ap.add_argument(
        "--table-cache", default="", metavar="DIR",
        help="content-keyed init_tables cache dir: repeat runs skip the "
        "~27 s N=100k table build bit-identically "
        "(SimulatorConfig.table_cache_dir)",
    )
    ap.add_argument(
        "--heartbeat", type=int, default=0, metavar="EVENTS",
        help="in-scan progress line (events/s, ETA) every N events — "
        "long scans are no longer silent (0 = off)",
    )
    ap.add_argument(
        "--series-every", type=int, default=0, metavar="EVENTS",
        help="sample the in-scan cluster time-series plane every N "
        "processed events (0 = off); lands in the --profile JSONL and "
        "as --trace-out counter tracks (README \"Live monitoring\")",
    )
    ap.add_argument(
        "--listen", default="", metavar="[HOST]:PORT",
        help="serve /metrics, /healthz, /progress over HTTP for the "
        "run's lifetime (tpusim.obs.server; bare :PORT binds loopback)",
    )
    # config-axis sweep bench (ISSUE 6; ENGINES.md "Round 11"): replace
    # the scale run with the vmapped weight-sweep measurement
    ap.add_argument(
        "--sweep", default="", metavar="B[,B...]",
        help="measure the config-axis sweep instead of the scale run: "
        "for each batch size B, one row with cold (incl. compile) and "
        "warm wall of a B-config vmapped weight sweep plus the marginal "
        "per-config cost against a standalone warm replay "
        "(e.g. --sweep 1,4,16)",
    )
    ap.add_argument(
        "--sweep-out", default="", metavar="PATH",
        help="write the sweep rows as a BENCH_rNN.json-style capture "
        "(a `sweep` block; `make bench-gate` reads the newest committed "
        "one for its advisory sweep comparison)",
    )
    ap.add_argument(
        "--compile-cache-dir", default="", metavar="DIR",
        help="JAX persistent compilation cache "
        "(SimulatorConfig.compile_cache_dir / $TPUSIM_COMPILE_CACHE_DIR): "
        "re-runs of the same job family load the compiled scan from disk "
        "instead of re-compiling",
    )
    args = ap.parse_args()
    if args.chunk <= 0:
        ap.error("--chunk must be positive")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpusim.constants import MILLI
    from tpusim.io.trace import build_events, pods_to_specs
    from tpusim.sim.driver import (
        Simulator,
        SimulatorConfig,
        enable_compile_cache,
    )
    from tpusim.sim.typical import TypicalPodsConfig

    # persistent compilation cache (ISSUE 6 satellite): wired BEFORE the
    # first jitted dispatch so the scan compile lands in / loads from it
    cache_dir = enable_compile_cache(args.compile_cache_dir)
    if cache_dir:
        print(f"[obs] compile cache at {cache_dir}", file=sys.stderr)

    if args.unswitched and args.block_size >= 0:
        # unswitched_select only alters the FLAT scan body; under the
        # auto/blocked layouts the knob is inert and the A/B would read
        # as a bogus "layout is neutral"
        ap.error("--unswitched measures the flat select layout: pass "
                 "--block-size -1")
    nodes = synth_cluster(args.nodes, args.seed)
    pods = synth_pods(args.pods, args.seed + 1)

    if args.pallas_ceiling:
        # the ceiling-sweep capture (ISSUE 15): pure footprint math at
        # this run's K/policy shape — no replay, no device
        from tpusim.io.trace import pods_to_specs as _pts
        from tpusim.sim import pallas_engine as _pe
        from tpusim.sim.table_engine import build_pod_types as _bpt

        _types = _bpt(_pts(pods))
        _k = int(_types.share.cpu.shape[0]) + int(_types.whole.cpu.shape[0])
        budget = _pe.vmem_budget()
        print(f"[pallas-ceiling] budget {budget} bytes, K={_k}, "
              f"num_pol=1, P={args.pods}, E={args.pods}")
        for n_probe in (2048, 4096, 8192, 65536, 262144, 1048576):
            tier = _pe.select_residency(n_probe, _k, 1, args.pods,
                                        args.pods)
            print(f"[pallas-ceiling] N={n_probe:>8}: "
                  f"{tier or 'degrade (blocked table engine)'}")
        print(f"[pallas-ceiling] HBM-tier max N at this shape: "
              f"{_pe.hbm_ceiling_nodes(_k, 1, 1, args.pods, args.pods)}")
        print(f"[pallas-ceiling] reference (K=151, small workload): "
              f"{_pe.hbm_ceiling_nodes(151, 1, 1)}")
        return
    profiling = bool(args.profile or args.metrics_out or args.trace_out)
    cfg = SimulatorConfig(
        policies=(("FGDScore", 1000),),
        gpu_sel_method="FGDScore",
        seed=args.seed,
        report_per_event=False,
        engine=args.engine,
        block_size=args.block_size,
        unswitched_select=args.unswitched,
        profile=profiling,
        heartbeat_every=args.heartbeat,
        series_every=args.series_every,
        table_cache_dir=args.table_cache,
        table_residency=args.pallas_residency,
        typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
    )
    sim = Simulator(nodes, cfg)
    sim.set_workload_pods(pods)
    sim.set_typical_pods()

    if args.sweep:
        run_sweep_bench(args, sim, cache_dir)
        return

    specs = pods_to_specs(pods)
    ev_kind, ev_pod = build_events(pods)
    ev_kind, ev_pod = jnp.asarray(ev_kind), jnp.asarray(ev_pod)
    key = jax.random.PRNGKey(args.seed)

    from tpusim.sim.table_engine import build_pod_types, resolve_block_size

    types = build_pod_types(specs)  # hoisted: identical for every chunk
    k_types = int(types.share.cpu.shape[0]) + int(types.whole.cpu.shape[0])
    # the block size the table engine will resolve for this shape (0 = flat)
    eff_block = resolve_block_size(args.block_size, args.nodes, k_types)

    from tpusim.obs import bench as obs_bench

    # live monitoring endpoint (--listen): up before the first dispatch
    # so a scraper watches the whole run, /progress fed by the heartbeat
    monitor = None
    if args.listen:
        from tpusim.obs.server import MonitorServer

        monitor = MonitorServer(args.listen).start()
        monitor.attach_heartbeat()
        monitor.publish_progress(phase="starting", nodes=args.nodes,
                                 pods=args.pods)
        print(f"[obs] monitoring at {monitor.url} "
              "(/metrics /healthz /progress)", file=sys.stderr)

    box = {}

    def run_chunked():
        state = sim.init_state
        failed_chunks = []
        ser_logs = []
        for lo in range(0, int(ev_kind.shape[0]), args.chunk):
            hi = min(lo + args.chunk, int(ev_kind.shape[0]))
            res = sim.run_events(
                state, specs, ev_kind[lo:hi], ev_pod[lo:hi], key,
                bucket=args.chunk, types=types,
            )
            state = res.state
            # keep the reduction on device; pull once after the run
            failed_chunks.append(res.ever_failed.sum())
            if res.series is not None:
                # each chunk's scan restarts its stride clock at 0 —
                # rebase onto the run-global event position like the
                # driver's fault loop does
                from tpusim.obs.series import log_from_stacked

                ser_logs.append(log_from_stacked(res.series, base_pos=lo))
        jax.block_until_ready(state)
        box["out"] = (
            state, int(sum(int(np.asarray(f)) for f in failed_chunks))
        )
        box["series"] = ser_logs  # last run's logs (cold run overwritten)

    # shared cold + warm protocol (tpusim.obs.bench): one compile run,
    # one warm run — the historical bench_scale shape
    m = obs_bench.measure(run_chunked, warm_runs=1)
    final_state, failed = box["out"]
    first, wall = m["first_s"], m["min_s"]

    placed = int(args.pods - failed)
    s = jax.tree.map(np.asarray, final_state)
    slot = np.arange(s.gpu_left.shape[1])[None, :] < s.gpu_cnt[:, None]
    alloc = 100.0 * np.where(slot, MILLI - s.gpu_left, 0).sum() / (
        s.gpu_cnt.sum() * MILLI
    )
    print(
        f"[scale] nodes={args.nodes} pods={args.pods} "
        f"engine={sim._last_engine} block={eff_block or 'flat'} "
        f"wall={wall:.1f}s "
        f"(first incl. compile {first:.1f}s) placed={placed} "
        f"throughput={placed / wall:.0f} placements/s "
        f"us_per_event={1e6 * wall / args.pods:.1f} gpu_alloc={alloc:.2f}%"
        + (f" table_cache={sim.obs.table_cache}" if args.table_cache else "")
    )

    series_block = None
    if args.series_every and box.get("series"):
        from tpusim.obs.series import concat_series, series_to_record

        series_block = series_to_record(
            concat_series(box["series"]), args.series_every,
            [name for name, _ in cfg.policies],
        )

    if profiling or monitor is not None:
        from tpusim.obs import emitters, note_compile_cache

        note_compile_cache(
            sim.obs, enabled=bool(cache_dir), cache_dir=cache_dir or ""
        )
        telemetry = sim.run_telemetry()
        record = emitters.build_record(
            telemetry,
            meta={"bench": "bench_scale", "nodes": args.nodes,
                  "pods": args.pods, "block": eff_block},
            series=series_block,
        )
        counter_series = None
        if args.trace_out:
            counter_series = sim.event_counter_series()
            if series_block is not None:
                from tpusim.obs.series import series_from_record, series_tracks

                counter_series.update(
                    series_tracks(series_from_record(series_block))
                )
        for p in emitters.emit_record(
            record, telemetry.spans,
            jsonl=args.profile,
            metrics=args.metrics_out,
            trace=args.trace_out,
            counter_series=counter_series,
        ):
            print(f"[obs] wrote {p}", file=sys.stderr)
        if monitor is not None:
            monitor.publish_record(record)
            monitor.publish_progress(phase="done", events_done=args.pods,
                                     events_total=args.pods)


if __name__ == "__main__":
    main()
