#!/usr/bin/env python3
"""CSV pod trace → YAML manifests (drop-in for the reference's
data/pod_csv_to_yaml.py CLI: same argv, same <stem>/<stem>.yaml output
layout). Implementation in tpusim.io.data_prep.

Usage:
    python3 data/pod_csv_to_yaml.py data/csv/openb_pod_list_gpuspec10.csv
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from tpusim.io.data_prep import pod_csv_to_yaml

if __name__ == "__main__":
    if len(sys.argv) < 2:
        sys.exit(__doc__)
    src = Path(sys.argv[1])
    if not src.exists():
        sys.exit(f"CSV File: {src} does not exist")
    pod_csv_to_yaml(src, sys.argv[2] if len(sys.argv) > 2 else None)
