#!/usr/bin/env python3
"""Trace statistics — the reference's two stats notebooks as a CLI
(`data/0 - Workloads stats.ipynb`, `data/1 - Nodes stats.ipynb`):
pod-category population + GPU-request shares per class (incl. within the
multi-GPU class), and the per-GPU-model node inventory. stdlib only.

Usage:
    python3 data/trace_stats.py data/csv/openb_pod_list_gpushare60.csv
    python3 data/trace_stats.py data/csv/openb_node_list_all_node.csv
    python3 data/trace_stats.py          # both defaults
"""

from __future__ import annotations

import csv
import sys
from collections import defaultdict
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def pod_category(num_gpu: int, gpu_milli: int) -> str:
    """The notebook's category conditions (Workloads stats, cell 3)."""
    if num_gpu == 0:
        return "NO-GPU"
    if num_gpu == 1 and gpu_milli < 1000:
        return "Share-GPU"
    if gpu_milli == 1000:
        return f"{num_gpu}-GPU"
    return f"{num_gpu}x{gpu_milli}m"  # not present in openb traces


def workload_stats(path):
    rows = list(csv.DictReader(open(path)))
    cats = defaultdict(int)
    req = defaultdict(int)
    for r in rows:
        c = pod_category(int(r["num_gpu"]), int(r["gpu_milli"] or 0))
        cats[c] += 1
        req[c] += int(r["num_gpu"]) * int(r["gpu_milli"] or 0)
    total_req = sum(req.values()) or 1

    def order(c):
        return (c != "NO-GPU", c != "Share-GPU", c)

    print(f"\n== workload stats: {path} ({len(rows)} pods)")
    print(f"{'category':>10s} {'task pop %':>11s} {'GPU-req %':>10s}")
    for c in sorted(cats, key=order):
        print(
            f"{c:>10s} {100.0 * cats[c] / len(rows):10.2f}% "
            f"{100.0 * req[c] / total_req:9.2f}%"
        )
    multi = {c: v for c, v in req.items() if c not in ("NO-GPU", "Share-GPU", "1-GPU")}
    mt = sum(multi.values())
    if mt:
        print("GPU-req % within the multi-GPU class:")
        for c in sorted(multi, key=order):
            print(f"{c:>10s} {100.0 * multi[c] / mt:10.2f}%")


def node_stats(path):
    rows = list(csv.DictReader(open(path)))
    by_model = defaultdict(list)
    for r in rows:
        by_model[r.get("model") or "<no GPU>"].append(r)
    print(f"\n== node stats: {path} ({len(rows)} nodes)")
    print(
        f"{'model':>10s} {'nodes':>6s} {'gpus':>6s} {'gpu/node':>9s} "
        f"{'cpu_milli/node':>15s} {'memory_mib/node':>16s}"
    )
    for model in sorted(by_model):
        ns = by_model[model]
        gpus = sum(int(n["gpu"]) for n in ns)
        print(
            f"{model:>10s} {len(ns):6d} {gpus:6d} {gpus / len(ns):9.2f} "
            f"{sum(int(n['cpu_milli']) for n in ns) / len(ns):15.1f} "
            f"{sum(int(n['memory_mib']) for n in ns) / len(ns):16.1f}"
        )


def main(argv):
    paths = argv or [
        str(REPO / "data/csv/openb_pod_list_gpushare60.csv"),
        str(REPO / "data/csv/openb_node_list_all_node.csv"),
    ]
    for p in paths:
        with open(p, newline="") as f:
            header = f.readline()
        if "num_gpu" in header:
            workload_stats(p)
        else:
            node_stats(p)


if __name__ == "__main__":
    main(sys.argv[1:])
