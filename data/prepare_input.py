#!/usr/bin/env python3
"""Generate per-trace cluster-config input folders from the CSV traces
(drop-in for the reference's data/prepare_input.sh): every
openb_pod_list*.csv becomes <out>/<trace>/ holding its pod YAML plus the
shared node YAML, ready for `python -m tpusim apply` (or the reference's
`simon apply`). Implementation in tpusim.io.data_prep.

Usage:
    python3 data/prepare_input.py [csv_dir] [out_dir]
    python3 data/prepare_input.py data/csv data/input
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from tpusim.io.data_prep import prepare_input

if __name__ == "__main__":
    csv_dir = sys.argv[1] if len(sys.argv) > 1 else "data/csv"
    out_dir = sys.argv[2] if len(sys.argv) > 2 else "data/input"
    made = prepare_input(csv_dir, out_dir)
    print(f"prepared {len(made)} trace folders under {out_dir}")
