#!/usr/bin/env python
"""Paper-figure plots from the merged discrete CSVs
(ref: experiments/plot/plot_openb_{alloc,frag_amount,frag_ratio}.py and the
*_alloc_bar.py family → Fig 7, 9, 11-14 of the FGD paper).

Input: experiments/analysis_results/analysis_{allo,frag,frag_ratio}_discrete.csv
(from experiments/merge.py). Output: PNGs under --out-dir.

Design notes (dataviz method): line charts for the load-sweep curves
(change-over-time job), grouped bars for per-variant allocation (magnitude
across categories). Policies take a fixed categorical palette slot —
validated 8-hue set, assigned by policy id order, never cycled — with a
legend always present and direct terminal labels on ≤4-series figures.
Static matplotlib renders: the hover layer is N/A.
"""

from __future__ import annotations

import argparse
import csv
from collections import defaultdict
from pathlib import Path

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

# validated categorical palette (dataviz reference instance, light mode),
# fixed slot per policy id — identity follows the policy, never its rank
PALETTE = {
    "01-Random": "#2a78d6",
    "02-DotProd": "#eb6834",
    "03-GpuClustering": "#1baf7a",
    "04-GpuPacking": "#eda100",
    "05-BestFit": "#e87ba4",
    "06-FGD": "#008300",
    "07-PWR": "#4a3aa7",
    "08-Custom": "#e34948",
}
TEXT_PRIMARY = "#0b0b0b"
TEXT_SECONDARY = "#52514e"
SURFACE = "#fcfcfb"
GRID = "#e4e3df"

LOAD_COLS = [str(x) for x in range(0, 131)]


def _style(ax, xlabel, ylabel, title):
    ax.set_facecolor(SURFACE)
    ax.grid(True, color=GRID, linewidth=0.8, zorder=0)
    for side in ("top", "right"):
        ax.spines[side].set_visible(False)
    for side in ("left", "bottom"):
        ax.spines[side].set_color(GRID)
    ax.tick_params(colors=TEXT_SECONDARY, labelsize=9)
    ax.set_xlabel(xlabel, color=TEXT_SECONDARY, fontsize=10)
    ax.set_ylabel(ylabel, color=TEXT_SECONDARY, fontsize=10)
    ax.set_title(title, color=TEXT_PRIMARY, fontsize=11, loc="left")


def load_discrete(path: Path):
    """→ {(workload, policy): [(load%, mean value over seeds)]}"""
    acc = defaultdict(lambda: defaultdict(list))
    with open(path, newline="") as f:
        for r in csv.DictReader(f):
            key = (r["workload"], r["sc_policy"])
            for col in LOAD_COLS:
                v = r.get(col)
                if v not in (None, ""):
                    acc[key][int(col)].append(float(v))
    return {
        key: sorted((x, sum(vs) / len(vs)) for x, vs in series.items())
        for key, series in acc.items()
    }


def plot_curves(data, workload, ylabel, title, out_png):
    fig, ax = plt.subplots(figsize=(6.4, 4.2), dpi=150)
    fig.patch.set_facecolor(SURFACE)
    policies = sorted({p for w, p in data if w == workload})
    for policy in policies:
        series = data[(workload, policy)]
        xs = [x for x, _ in series]
        ys = [y for _, y in series]
        ax.plot(
            xs,
            ys,
            color=PALETTE.get(policy, TEXT_SECONDARY),
            linewidth=2,
            label=policy,
            zorder=3,
        )
    _style(ax, "Arrived workload (% of cluster GPU capacity)", ylabel, title)
    ax.legend(
        frameon=False, fontsize=8, labelcolor=TEXT_PRIMARY, loc="upper left"
    )
    fig.tight_layout()
    fig.savefig(out_png, facecolor=SURFACE)
    plt.close(fig)
    print(f"[plot] {out_png}")


def plot_variant_bars(data, variant_prefix, at_load, ylabel, title, out_png):
    """Grouped bars: x = trace variants of one family, group = policy
    (ref: plot_openb_{gpushare,gpuspec,multigpu,nongpu}_alloc_bar.py)."""
    workloads = sorted({w for w, _ in data if variant_prefix in w})
    policies = sorted({p for _, p in data})
    if not workloads:
        print(f"[plot] no workloads matching {variant_prefix}, skipping")
        return
    fig, ax = plt.subplots(figsize=(7.2, 4.2), dpi=150)
    fig.patch.set_facecolor(SURFACE)
    n = len(policies)
    width = 0.8 / n
    for j, policy in enumerate(policies):
        xs, ys = [], []
        for i, w in enumerate(workloads):
            series = dict(data.get((w, policy), []))
            if at_load in series:
                xs.append(i + (j - n / 2 + 0.5) * width)
                ys.append(series[at_load])
        ax.bar(
            xs,
            ys,
            width=width * 0.92,  # 2px-equivalent gap between adjacent bars
            color=PALETTE.get(policy, TEXT_SECONDARY),
            label=policy,
            zorder=3,
        )
    ax.set_xticks(range(len(workloads)))
    ax.set_xticklabels(
        [w.replace("openb_pod_list_", "") for w in workloads],
        rotation=20,
        ha="right",
    )
    _style(ax, "Trace variant", ylabel, title)
    ax.legend(frameon=False, fontsize=8, labelcolor=TEXT_PRIMARY, ncol=2)
    fig.tight_layout()
    fig.savefig(out_png, facecolor=SURFACE)
    plt.close(fig)
    print(f"[plot] {out_png}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--results", default="experiments/analysis_results")
    ap.add_argument("--out-dir", default="experiments/plot/figures")
    ap.add_argument("--workload", default="openb_pod_list_default")
    ap.add_argument("--at-load", type=int, default=130)
    args = ap.parse_args()
    results = Path(args.results)
    out = Path(args.out_dir)
    out.mkdir(parents=True, exist_ok=True)

    allo = results / "analysis_allo_discrete.csv"
    if allo.is_file():
        data = load_discrete(allo)
        plot_curves(
            data,
            args.workload,
            "GPU allocation ratio (%)",
            f"GPU allocation vs arrived load — {args.workload}",
            out / "openb_alloc.png",
        )
        for fam, label in (
            ("gpushare", "GPU-sharing"),
            ("gpuspec", "GPU-type-constrained"),
            ("multigpu", "multi-GPU"),
            ("cpu", "non-GPU"),
        ):
            plot_variant_bars(
                data,
                fam,
                args.at_load,
                f"GPU allocation ratio @ {args.at_load}% (%)",
                f"Allocation across {label} trace variants",
                out / f"openb_{fam}_alloc_bar.png",
            )
    frag = results / "analysis_frag_discrete.csv"
    if frag.is_file():
        plot_curves(
            load_discrete(frag),
            args.workload,
            "Fragmented GPU milli (×10³)",
            f"Fragmentation amount vs arrived load — {args.workload}",
            out / "openb_frag_amount.png",
        )
    fratio = results / "analysis_frag_ratio_discrete.csv"
    if fratio.is_file():
        plot_curves(
            load_discrete(fratio),
            args.workload,
            "Fragmentation ratio (%)",
            f"Fragmentation ratio vs arrived load — {args.workload}",
            out / "openb_frag_ratio.png",
        )


if __name__ == "__main__":
    main()
