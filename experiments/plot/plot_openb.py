#!/usr/bin/env python
"""Paper-figure plots from the merged discrete CSVs — figure-for-figure
with the reference's plot family (experiments/plot/plot_openb_alloc.py,
plot_openb_frag_{amount,ratio}.py, plot_openb_{gpushare,gpuspec,multigpu,
nongpu}_alloc_bar.py → the FGD paper's Fig 7, 9, 11-14).

Content semantics match the reference scripts exactly:
  - openb_alloc: UNALLOCATED GPU % (100 − alloc ratio) vs arrived load,
    median over seeds + 25-75 percentile band, the 6 cached policies, the
    'Ideal' diagonal, x ∈ [75, 120], y ∈ [0, 25] (plot_openb_alloc.py:83-103)
  - openb_frag_amount / openb_frag_ratio: median + band, x ∈ [0, 120]
    (plot_openb_frag_amount.py:76-97; note the reference's frag_amount
    y-label is a copy-paste of the ratio label — ours says what the axis is)
  - the 4 alloc-bar families: unallocated GPU % AT 100% ARRIVED LOAD,
    sd error bars, the reference's trace subsets with its percent x-labels
    (plot_openb_*_alloc_bar.py:16-21, 75-110)

Input: analysis_{allo,frag,frag_ratio}_discrete.csv (experiments/merge.py —
same schema as the reference's expected_results). Output: PNGs.

--compare-with <dir> additionally loads a second results dir (e.g. the
reference's expected_results) through the SAME pipeline and prints the
numeric differences of every plotted series (medians per x, bar heights) —
the figure-level validation story in experiments/plot/README.md (this image
has no PDF rasterizer, so figures are compared at plotted-series level, one
abstraction below pixels).

Design notes (dataviz method): line charts for the load-sweep curves,
grouped bars for per-variant allocation; policies take fixed categorical
palette slots; percentile bands at 18% opacity fills.
"""

from __future__ import annotations

import argparse
import csv
from collections import defaultdict
from pathlib import Path
from statistics import median, pstdev, quantiles

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

# validated categorical palette (dataviz reference instance, light mode),
# fixed slot per policy id — identity follows the policy, never its rank
PALETTE = {
    "01-Random": "#2a78d6",
    "02-DotProd": "#eb6834",
    "03-GpuClustering": "#1baf7a",
    "04-GpuPacking": "#eda100",
    "05-BestFit": "#e87ba4",
    "06-FGD": "#008300",
    "07-PWR": "#4a3aa7",
    "08-Custom": "#e34948",
}
TEXT_PRIMARY = "#0b0b0b"
TEXT_SECONDARY = "#52514e"
SURFACE = "#fcfcfb"
GRID = "#e4e3df"

LOAD_COLS = [str(x) for x in range(0, 131)]

# the 6 reference-cached policies, legend order of the reference curves
# (plot_openb_alloc.py:66 policy_keep)
POLICY_KEEP = [
    "01-Random", "02-DotProd", "03-GpuClustering",
    "04-GpuPacking", "05-BestFit", "06-FGD",
]

# the bar families (plot_openb_*_alloc_bar.py:16-21 + label maps :75-84)
BAR_FAMILIES = {
    "nongpu": (
        "Proportion of non-GPU workloads in terms of task number",
        [("openb_pod_list_cpu050", "5%"), ("openb_pod_list_cpu100", "10%"),
         ("openb_pod_list_cpu200", "20%"), ("openb_pod_list_cpu250", "25%")],
    ),
    "gpushare": (
        "Proportion of GPU-sharing workloads in terms of GPU requests",
        [("openb_pod_list_gpushare20", "20%"),
         ("openb_pod_list_gpushare40", "40%"),
         ("openb_pod_list_gpushare60", "60%"),
         ("openb_pod_list_gpushare80", "80%"),
         ("openb_pod_list_gpushare100", "100%")],
    ),
    "gpuspec": (
        "Proportion of workloads with GPU type constraints in terms of GPU requests",
        [("openb_pod_list_gpuspec10", "10%"), ("openb_pod_list_gpuspec20", "20%"),
         ("openb_pod_list_gpuspec25", "25%"), ("openb_pod_list_gpuspec33", "33%")],
    ),
    "multigpu": (
        "Proportion of multi-GPU workloads in terms of GPU requests",
        [("openb_pod_list_multigpu20", "20%"),
         ("openb_pod_list_multigpu30", "30%"),
         ("openb_pod_list_multigpu40", "40%"),
         ("openb_pod_list_multigpu50", "50%")],
    ),
}


def _style(ax, xlabel, ylabel, title):
    ax.set_facecolor(SURFACE)
    ax.grid(True, color=GRID, linewidth=0.8, zorder=0)
    for side in ("top", "right"):
        ax.spines[side].set_visible(False)
    for side in ("left", "bottom"):
        ax.spines[side].set_color(GRID)
    ax.tick_params(colors=TEXT_SECONDARY, labelsize=9)
    ax.set_xlabel(xlabel, color=TEXT_SECONDARY, fontsize=10)
    ax.set_ylabel(ylabel, color=TEXT_SECONDARY, fontsize=10)
    ax.set_title(title, color=TEXT_PRIMARY, fontsize=11, loc="left")


def load_discrete(path: Path):
    """→ {(workload, policy): {load%: [per-seed values]}}"""
    acc = defaultdict(lambda: defaultdict(list))
    with open(path, newline="") as f:
        for r in csv.DictReader(f):
            key = (r["workload"], r["sc_policy"])
            for col in LOAD_COLS:
                v = r.get(col)
                if v not in (None, ""):
                    acc[key][int(col)].append(float(v))
    return acc


def curve_series(data, workload, policy, transform=lambda v: v):
    """Plotted line content: per-x (median, p25, p75) over seeds —
    linear-interpolation percentiles (statistics.quantiles "inclusive" ==
    numpy's default method, the estimator seaborn's ("pi", 50) band uses)."""
    series = data.get((workload, policy))
    if not series:
        return []
    out = []
    for x in sorted(series):
        vs = sorted(transform(v) for v in series[x])
        if len(vs) == 1:
            p25 = p75 = vs[0]
        else:
            qs = quantiles(vs, n=4, method="inclusive")
            p25, p75 = qs[0], qs[2]
        out.append((x, median(vs), p25, p75))
    return out


def plot_curves(data, workload, ylabel, title, out_png, transform=lambda v: v,
                xlim=(0, 120), ylim=None, ideal=False):
    fig, ax = plt.subplots(figsize=(6.4, 4.2), dpi=150)
    fig.patch.set_facecolor(SURFACE)
    for policy in POLICY_KEEP:
        series = curve_series(data, workload, policy, transform)
        if not series:
            continue
        xs = [s[0] for s in series]
        color = PALETTE.get(policy, TEXT_SECONDARY)
        ax.fill_between(
            xs, [s[2] for s in series], [s[3] for s in series],
            color=color, alpha=0.18, linewidth=0, zorder=2,
        )
        ax.plot(
            xs, [s[1] for s in series], color=color, linewidth=2,
            label=policy, zorder=3,
        )
    if ideal:
        ax.plot(
            [0, 100], [100, 0], linestyle=":", color="grey", alpha=0.8,
            label="Ideal", zorder=3,
        )
    _style(ax, "Arrived workload (% of cluster GPU capacity)", ylabel, title)
    if xlim:
        ax.set_xlim(*xlim)
    if ylim:
        ax.set_ylim(*ylim)
    ax.legend(frameon=False, fontsize=8, labelcolor=TEXT_PRIMARY, loc="best")
    fig.tight_layout()
    fig.savefig(out_png, facecolor=SURFACE)
    plt.close(fig)
    print(f"[plot] {out_png}")


def bar_heights(data, family, at_load=100):
    """Plotted bar content: {(trace label, policy): (mean unalloc, sd)} at
    the reference's 100%-arrived-load sample."""
    _, traces = BAR_FAMILIES[family]
    out = {}
    for workload, label in traces:
        for policy in POLICY_KEEP:
            vals = data.get((workload, policy), {}).get(at_load)
            if vals:
                un = [100.0 - v for v in vals]
                out[(label, policy)] = (
                    sum(un) / len(un),
                    pstdev(un) if len(un) > 1 else 0.0,
                )
    return out


def plot_variant_bars(data, family, title, out_png):
    xlabel, traces = BAR_FAMILIES[family]
    heights = bar_heights(data, family)
    labels = [lab for _, lab in traces if any(k[0] == lab for k in heights)]
    if not labels:
        print(f"[plot] no workloads for {family}, skipping")
        return
    fig, ax = plt.subplots(figsize=(7.2, 4.0), dpi=150)
    fig.patch.set_facecolor(SURFACE)
    n = len(POLICY_KEEP)
    width = 0.8 / n
    # reference bar order: FGD first (plot_openb_*_alloc_bar.py policy_keep)
    for j, policy in enumerate(reversed(POLICY_KEEP)):
        xs, ys, errs = [], [], []
        for i, lab in enumerate(labels):
            if (lab, policy) in heights:
                m, sd = heights[(lab, policy)]
                xs.append(i + (j - n / 2 + 0.5) * width)
                ys.append(m)
                errs.append(sd)
        ax.bar(
            xs, ys, width=width * 0.92, yerr=errs, capsize=2,
            error_kw={"ecolor": TEXT_SECONDARY, "elinewidth": 0.8},
            color=PALETTE.get(policy, TEXT_SECONDARY), label=policy, zorder=3,
        )
    ax.set_xticks(range(len(labels)))
    ax.set_xticklabels(labels)
    _style(ax, xlabel, "Unallocated GPU (%) @ 100% arrived load", title)
    ax.set_ylim(0, 22)
    ax.legend(frameon=False, fontsize=8, labelcolor=TEXT_PRIMARY, ncol=3)
    fig.tight_layout()
    fig.savefig(out_png, facecolor=SURFACE)
    plt.close(fig)
    print(f"[plot] {out_png}")


def compare_results(ours_dir: Path, ref_dir: Path, workload: str):
    """Numeric diff of every plotted series between two results dirs run
    through the identical pipeline (see module docstring)."""
    print(f"\n[compare] {ours_dir} vs {ref_dir}")
    for fname, transform, what in (
        ("analysis_allo_discrete.csv", lambda v: 100.0 - v, "unalloc curve"),
        ("analysis_frag_ratio_discrete.csv", lambda v: v, "frag-ratio curve"),
        ("analysis_frag_discrete.csv", lambda v: v, "frag-amount curve"),
    ):
        a, b = ours_dir / fname, ref_dir / fname
        if not (a.is_file() and b.is_file()):
            print(f"  {what}: missing file, skipped")
            continue
        da, db = load_discrete(a), load_discrete(b)
        worst = (0.0, "")
        for policy in POLICY_KEEP:
            sa = dict(
                (x, m) for x, m, _, _ in curve_series(da, workload, policy, transform)
            )
            sb = dict(
                (x, m) for x, m, _, _ in curve_series(db, workload, policy, transform)
            )
            for x in sorted(set(sa) & set(sb)):
                d = abs(sa[x] - sb[x])
                if d > worst[0]:
                    worst = (d, f"{policy}@{x}%")
        print(f"  {what} ({workload}): max |Δ median| = {worst[0]:.2f} at {worst[1]}")
    a = ours_dir / "analysis_allo_discrete.csv"
    b = ref_dir / "analysis_allo_discrete.csv"
    if a.is_file() and b.is_file():
        da, db = load_discrete(a), load_discrete(b)
        for family in BAR_FAMILIES:
            ha, hb = bar_heights(da, family), bar_heights(db, family)
            common = set(ha) & set(hb)
            if not common:
                print(f"  {family} bars: no common cells, skipped")
                continue
            worst = max(common, key=lambda k: abs(ha[k][0] - hb[k][0]))
            print(
                f"  {family} bars: max |Δ mean height| = "
                f"{abs(ha[worst][0] - hb[worst][0]):.2f} at {worst}"
            )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--results", default="experiments/analysis_results")
    ap.add_argument("--out-dir", default="experiments/plot/figures")
    ap.add_argument("--workload", default="openb_pod_list_default")
    ap.add_argument(
        "--compare-with", default=None,
        help="second results dir (e.g. the reference's expected_results); "
        "print numeric diffs of every plotted series",
    )
    args = ap.parse_args()
    results = Path(args.results)
    out = Path(args.out_dir)
    out.mkdir(parents=True, exist_ok=True)

    allo = results / "analysis_allo_discrete.csv"
    if allo.is_file():
        data = load_discrete(allo)
        plot_curves(
            data, args.workload, "Unallocated GPU (%)",
            f"Unallocated GPU vs arrived load — {args.workload}",
            out / "openb_alloc.png",
            transform=lambda v: 100.0 - v,
            xlim=(75, 120), ylim=(0, 25), ideal=True,
        )
        for family in BAR_FAMILIES:
            plot_variant_bars(
                data, family,
                f"Unallocated GPU across {family} trace variants",
                out / f"openb_{family}_alloc_bar.png",
            )
    frag = results / "analysis_frag_discrete.csv"
    if frag.is_file():
        plot_curves(
            load_discrete(frag), args.workload,
            "Fragmented GPU (% of cluster capacity)",
            f"Fragmentation amount vs arrived load — {args.workload}",
            out / "openb_frag_amount.png",
            xlim=(0, 120),
        )
    fratio = results / "analysis_frag_ratio_discrete.csv"
    if fratio.is_file():
        plot_curves(
            load_discrete(fratio), args.workload,
            "Frag / Total (%)",
            f"Fragmentation ratio vs arrived load — {args.workload}",
            out / "openb_frag_ratio.png",
            xlim=(0, 120),
        )
    if args.compare_with:
        compare_results(results, Path(args.compare_with), args.workload)


if __name__ == "__main__":
    main()
