#!/usr/bin/env python
"""Single-process experiment sweep (replaces the reference's
`run_scripts.sh | xargs --max-procs=128` fleet, experiments/README.md
step 2: 1020 experiments, ~10 h on a 256-vCPU machine).

Runs the (trace × policy × seed) grid in ONE process so every experiment
after the first reuses the compiled replay engines (tpusim.sim.engine /
table_engine caches + the driver's shape bucketing over pod/event/typical
axes). Bellman memos stay scoped per experiment — sharing them would make
report values depend on sweep order (see tpusim/sim/driver.py).

    python experiments/sweep.py --traces openb_pod_list_default \
        --methods 06-FGD 01-Random --seeds 3
    python experiments/sweep.py            # full 10-method × 21 × 10 grid
    python experiments/sweep.py --fast     # skip per-event report lines

Each experiment writes the same per-directory outputs as experiments/run.py
(simon.log + analysis CSVs) under --out-root/<trace>/<method>/<tune>/<seed>,
so experiments/merge.py and the plot scripts work unchanged.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "experiments"))

from generate_run_scripts import METHODS, TRACES  # noqa: E402

import run as runner  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-root", default="experiments/data")
    ap.add_argument("--tune", type=float, default=1.3)
    ap.add_argument("--seeds", type=int, default=10, help="seeds 42..42+n-1")
    ap.add_argument("--traces", nargs="*", default=None)
    ap.add_argument("--methods", nargs="*", default=None, help="method ids")
    ap.add_argument("--fast", action="store_true", help="no per-event report")
    ap.add_argument(
        "--no-batch", action="store_true",
        help="run seeds one-by-one instead of one vmapped replay per group",
    )
    args = ap.parse_args(argv)

    traces = args.traces or TRACES
    methods = [m for m in METHODS if args.methods is None or m[0] in args.methods]
    groups = [(trace, m) for trace in traces for m in methods]
    seeds = list(range(42, 42 + args.seeds))
    total = len(groups) * len(seeds)
    t_all = time.perf_counter()
    done = 0
    # pipelined groups: group i's host tails (bellman, log/CSV writes) run
    # while groups i+1/i+2's vmapped replays execute on the chip — the
    # only concurrency a 1-vCPU host driving a remote accelerator has.
    # Two groups of lookahead cover the case where one group's device
    # phase outlasts the next group's host build, so the eventual fetch
    # never blocks. Each entry: {"trace","mid","pending","st","t0"}
    from collections import deque

    LOOKAHEAD = 2
    inflight = deque()

    def transient(e) -> bool:
        # the TPU tunnel occasionally drops a remote call mid-sweep; a
        # transient runtime/RPC failure must not kill a multi-hour grid.
        # Deterministic errors (bad flags, missing traces, filesystem
        # errors, assertion bugs) surface immediately.
        import jax

        if isinstance(
            e,
            (FileNotFoundError, FileExistsError, IsADirectoryError,
             NotADirectoryError, PermissionError),
        ):
            return False
        return isinstance(e, (jax.errors.JaxRuntimeError, OSError))

    def run_group_unpipelined(trace, mid, pending):
        """Retry path: run one group start-to-finish (batch, then per-seed
        fallback granularity on the last attempt)."""
        for attempt in range(3):
            try:
                if len(pending) > 1 and not args.no_batch:
                    runner.run_experiment_batch(
                        [runner.get_args(a) for _, a, _ in pending]
                    )
                    for _, argv_exp, marker in pending:
                        marker.write_text(" ".join(argv_exp))
                else:
                    # per-seed markers: a failure on a late seed must not
                    # discard earlier seeds' completion records
                    for _, argv_exp, marker in pending:
                        if marker.exists() and marker.read_text() == " ".join(
                            argv_exp
                        ):
                            continue
                        runner.run_experiment(runner.get_args(argv_exp))
                        marker.write_text(" ".join(argv_exp))
                return
            except Exception as e:  # noqa: BLE001 — transient() filters
                if not transient(e) or attempt == 2:
                    raise
                print(
                    f"[sweep] {trace} {mid} seeds="
                    f"{[s for s, _, _ in pending]} attempt {attempt + 1} "
                    f"failed ({e}); retrying",
                    flush=True,
                )
                time.sleep(5)

    def flush(entry):
        nonlocal done
        try:
            runner.finish_experiment_batch(entry["st"])
            for _, argv_exp, marker in entry["pending"]:
                marker.write_text(" ".join(argv_exp))
        except Exception as e:  # noqa: BLE001 — transient() filters
            if not transient(e):
                raise
            print(
                f"[sweep] {entry['trace']} {entry['mid']} finish failed "
                f"({e}); re-running group unpipelined",
                flush=True,
            )
            run_group_unpipelined(
                entry["trace"], entry["mid"], entry["pending"]
            )
        done += len(entry["pending"])
        print(
            f"[sweep {done}/{total}] {entry['trace']} {entry['mid']} "
            f"seeds={[s for s, _, _ in entry['pending']]} "
            f"{time.perf_counter() - entry['t0']:.1f}s "
            f"(total {time.perf_counter() - t_all:.0f}s)",
            flush=True,
        )

    for trace, (mid, flags, gpusel, dimext, norm) in groups:
        # one group = the same experiment across seeds; uncached seeds run
        # as ONE vmapped device replay (driver.run_batch) unless --no-batch
        pending = []
        for seed in seeds:
            outdir = f"{args.out_root}/{trace}/{mid}/{args.tune}/{seed}"
            argv_exp = (
                ["-d", outdir, "-f", trace]
                + flags.split()
                + ["-gpusel", gpusel, "-dimext", dimext, "-norm", norm,
                   "-tune", str(args.tune), "-tuneseed", str(seed),
                   "--shuffle-pod", "true"]
                + (["--no-per-event-report"] if args.fast else [])
            )
            # resume marker: written only after a fully-finished experiment,
            # keyed on the exact argv so --fast and full runs never alias
            marker = Path(outdir) / ".sweep_done"
            if marker.exists() and marker.read_text() == " ".join(argv_exp):
                done += 1
                print(
                    f"[sweep {done}/{total}] {trace} {mid} seed={seed} "
                    f"cached, skipping",
                    flush=True,
                )
                continue
            pending.append((seed, argv_exp, marker))
        if not pending:
            continue
        t0 = time.perf_counter()
        if len(pending) > 1 and not args.no_batch:
            try:
                st = runner.dispatch_experiment_batch(
                    [runner.get_args(a) for _, a, _ in pending]
                )
            except Exception as e:  # noqa: BLE001 — transient() filters
                if not transient(e):
                    raise
                while inflight:
                    flush(inflight.popleft())
                run_group_unpipelined(trace, mid, pending)
                done += len(pending)
                print(
                    f"[sweep {done}/{total}] {trace} {mid} (retried) "
                    f"{time.perf_counter() - t0:.1f}s",
                    flush=True,
                )
                continue
            inflight.append({
                "trace": trace, "mid": mid, "pending": pending,
                "st": st, "t0": t0,
            })
            while len(inflight) > LOOKAHEAD:
                flush(inflight.popleft())
        else:
            while inflight:
                flush(inflight.popleft())
            run_group_unpipelined(trace, mid, pending)
            done += len(pending)
            print(
                f"[sweep {done}/{total}] {trace} {mid} "
                f"seeds={[s for s, _, _ in pending]} "
                f"{time.perf_counter() - t0:.1f}s "
                f"(total {time.perf_counter() - t_all:.0f}s)",
                flush=True,
            )
    while inflight:
        flush(inflight.popleft())
    print(f"[sweep] {total} experiments in {time.perf_counter() - t_all:.0f}s")


if __name__ == "__main__":
    main()
