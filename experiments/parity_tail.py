#!/usr/bin/env python
"""frag@90 parity-tail analysis: seed-distribution comparison for the worst
cells of the 102-cell matrix (PARITY.md).

The 10-seed-mean frag@90 deltas peak at ~3 pt on a handful of
(cpu050/multigpu20/gpushare40) x (GpuClustering/GpuPacking) cells. This tool
re-runs those cells at many seeds on this framework and compares the
resulting distribution against the reference's 10 per-seed values
(experiments/analysis/expected_results/analysis_frag_ratio_discrete.csv),
reporting mean +/- std, ranges, and the two-sample overlap — the evidence
PARITY.md's "seed noise" attribution rests on.

    python experiments/sweep.py --out-root /tmp/parity30 \
        --traces openb_pod_list_cpu050 openb_pod_list_multigpu20 \
        --methods 03-GpuClustering 04-GpuPacking --seeds 30
    python experiments/sweep.py --out-root /tmp/parity30 \
        --traces openb_pod_list_gpushare40 --methods 04-GpuPacking --seeds 30
    python experiments/merge.py --data-root /tmp/parity30 --out /tmp/parity30_merged
    python experiments/parity_tail.py --merged /tmp/parity30_merged
"""

from __future__ import annotations

import argparse
import csv
import math
import statistics
from pathlib import Path

REF = Path("/root/reference/experiments/analysis/expected_results")

CELLS = [
    ("openb_pod_list_cpu050", "04-GpuPacking"),
    ("openb_pod_list_multigpu20", "04-GpuPacking"),
    ("openb_pod_list_cpu050", "03-GpuClustering"),
    ("openb_pod_list_multigpu20", "03-GpuClustering"),
    ("openb_pod_list_gpushare40", "04-GpuPacking"),
    # round 4: the one >1pt plotted-series delta outside the round-3
    # analysis — the default trace's DotProd frag@90 curve (VERDICT r3 §6)
    ("openb_pod_list_default", "02-DotProd"),
]


def per_seed(path: Path, load_col: str = "90"):
    out = {}
    with open(path, newline="") as f:
        for row in csv.DictReader(f):
            key = (row["workload"], row["sc_policy"])
            out.setdefault(key, []).append(float(row[load_col]))
    return out


def fmt(vals):
    m = statistics.mean(vals)
    s = statistics.stdev(vals) if len(vals) > 1 else 0.0
    return m, s, min(vals), max(vals)


def welch_t(a, b):
    """Welch's t statistic + approximate dof (no scipy in the image; |t|<2
    at these dofs means the means are statistically indistinguishable)."""
    ma, mb = statistics.mean(a), statistics.mean(b)
    va, vb = statistics.variance(a), statistics.variance(b)
    na, nb = len(a), len(b)
    se2 = va / na + vb / nb
    t = (ma - mb) / math.sqrt(se2) if se2 else 0.0
    dof = se2**2 / (
        (va / na) ** 2 / (na - 1) + (vb / nb) ** 2 / (nb - 1)
    ) if se2 else 1.0
    return t, dof


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--merged", default="/tmp/parity30_merged")
    ap.add_argument("--load", default="90", help="arrived-load percent column")
    ap.add_argument("--ref", default=str(REF / "analysis_frag_ratio_discrete.csv"))
    args = ap.parse_args(argv)

    ours = per_seed(Path(args.merged) / "analysis_frag_ratio_discrete.csv", args.load)
    ref = per_seed(Path(args.ref), args.load)

    print(
        f"frag ratio @ {args.load}% arrived load — per-seed distributions "
        "(ref 10 seeds vs ours)\n"
    )
    print(
        f"{'cell':45s} {'ref mean±std [min,max]':28s} "
        f"{'ours mean±std [min,max]':28s} {'Δmean':>6s} {'|t|':>5s}"
    )
    for cell in CELLS:
        r = ref.get(cell)
        o = ours.get(cell)
        if not r or not o:
            print(f"{cell}: missing data (ref={bool(r)}, ours={bool(o)})")
            continue
        rm, rs, rlo, rhi = fmt(r)
        om, os_, olo, ohi = fmt(o)
        t, dof = welch_t(r, o)
        print(
            f"{cell[0][15:] + ' × ' + cell[1]:45s} "
            f"{rm:6.2f}±{rs:5.2f} [{rlo:5.1f},{rhi:5.1f}]   "
            f"{om:6.2f}±{os_:5.2f} [{olo:5.1f},{ohi:5.1f}]   "
            f"{om - rm:+6.2f} {abs(t):5.2f}  (n={len(o)}, dof≈{dof:.0f})"
        )


if __name__ == "__main__":
    main()
