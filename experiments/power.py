#!/usr/bin/env python
"""Merged power-analysis deliverable — the fork's notebooks 1-3 power
outputs as one CLI over the merged discrete artifact.

The reference fork's distinguishing deliverable is its power comparison:
`1 - Parse results.ipynb` builds per-seed power / usage-efficiency /
failed-pod curves on a cumulative-workload axis and averages them per
(trace, policy); `2 - Generate plots.ipynb` turns them into the
power-savings-vs-FGD figure (plot_energy_savings -> pwrsaving_<level>.pdf),
the GRAR comparison figure (plot_comparison_metric -> gpuocc_<level>.pdf)
and the failed-relative plot (plot_failed_relative); `3 - Generate
tables.ipynb` emits LaTeX GRAR tables per trace family. This tool produces
all of those from experiments/merge.py's *_discrete CSVs alone:

  power_savings_<workload>.png   % cluster power savings vs the reference
                                 policy at each arrived-load %
                                 (plot_energy_savings, notebook 2 cell 4)
  usage_efficiency_<workload>.png  GRAR curves (plot_comparison_metric on
                                 usage_efficiency, notebook 2 cells 2/9)
  failed_relative_<workload>.png cumulative failed pods minus the
                                 reference policy's (plot_failed_relative,
                                 notebook 2 cell 3)
  power_tables.md / .tex         GRAR at 100% load per trace family
                                 (notebook 3 cells 5-6) + mean cluster
                                 watts at 100% load with savings vs the
                                 reference policy

Curves are seed-means, like the notebooks (sum(dfs)/len(dfs)); the load
axis is the integer arrived-load percent of the *_discrete schema (the
notebooks' cumulative_workload 0..1 maps to 0..100 here).

    python experiments/power.py --merged experiments/analysis_results \
        --out experiments/analysis_results/power
"""

from __future__ import annotations

import argparse
import csv
import re
import sys
from collections import defaultdict
from pathlib import Path
from statistics import mean

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt

sys.path.insert(0, str(Path(__file__).parent / "plot"))
from plot_openb import LOAD_COLS, PALETTE, SURFACE, _style  # noqa: E402

REFERENCE_POLICY = "06-FGD"  # notebook 2 cell 9: reference_competitor = 'FGD'


def load_curves(path: Path, series: str = None):
    """merged *_discrete CSV -> {(workload, policy): {load%: seed-mean}}.

    `series` filters analysis_pwr_discrete.csv rows (cluster/cpu/gpu);
    None for the single-series files. Refuses mixed tuning ratios, like
    compare.py — averaging across tunes is meaningless."""
    acc = defaultdict(lambda: defaultdict(list))
    tunes = set()
    with open(path, newline="") as f:
        for r in csv.DictReader(f):
            if series is not None and r.get("series") != series:
                continue
            tunes.add(r.get("tune"))
            key = (r["workload"], r["sc_policy"])
            for col in LOAD_COLS:
                v = r.get(col)
                if v not in (None, ""):
                    acc[key][int(col)].append(float(v))
    if len(tunes) > 1:
        raise SystemExit(
            f"{path} mixes tuning ratios {sorted(tunes)}; run power.py on a "
            "single-tune artifact (averaging across tunes is meaningless)"
        )
    return {
        key: {x: mean(vs) for x, vs in per_load.items()}
        for key, per_load in acc.items()
    }


def _policy_color(policy):
    return PALETTE.get(policy, PALETTE["08-Custom"])


def _plot_policies(curves, workload, value_fn, ylabel, title, out_png,
                   xlim=(0, 100)):
    """One line per policy (skipping any value_fn returns None for)."""
    fig, ax = plt.subplots(figsize=(6.4, 4.2), dpi=150)
    fig.patch.set_facecolor(SURFACE)
    drew = False
    for (wl, policy) in sorted(curves):
        if wl != workload:
            continue
        pts = value_fn(policy, curves[(wl, policy)])
        if not pts:
            continue
        xs, ys = zip(*pts)
        ax.plot(xs, ys, color=_policy_color(policy), linewidth=1.6,
                label=policy, zorder=3)
        drew = True
    if not drew:
        plt.close(fig)
        return False
    _style(ax, "arrived GPU load (% of cluster capacity)", ylabel, title)
    ax.set_xlim(xlim)
    ax.legend(fontsize=7, ncol=2, framealpha=0.9)
    fig.tight_layout()
    fig.savefig(out_png)
    plt.close(fig)
    return True


def plot_power_savings(pwr, workload, out_png):
    """plot_energy_savings (notebook 2 cell 4): per policy,
    (ref_power - policy_power) / ref_power * 100 at each load."""
    ref = pwr.get((workload, REFERENCE_POLICY))
    if not ref:
        return False

    def value_fn(policy, curve):
        if policy == REFERENCE_POLICY:
            return None
        return [
            (x, 100.0 * (ref[x] - y) / ref[x])
            for x, y in sorted(curve.items())
            if x in ref and ref[x] > 0 and x <= 100
        ]

    return _plot_policies(
        pwr, workload, value_fn,
        f"% cluster power savings vs {REFERENCE_POLICY}",
        f"Power savings vs {REFERENCE_POLICY} — {workload}", out_png,
    )


def plot_usage_efficiency(usage, workload, out_png):
    """plot_comparison_metric on usage_efficiency (notebook 2 cells 2/9);
    the fork plots x in [0.8, 1.0] -> loads 80..100 here."""

    def value_fn(policy, curve):
        return [(x, y) for x, y in sorted(curve.items()) if 80 <= x <= 100]

    return _plot_policies(
        usage, workload, value_fn,
        "GPU allocated vs requested ratio (GRAR)",
        f"GPU usage efficiency — {workload}", out_png, xlim=(80, 100),
    )


def plot_failed_relative(failed, workload, out_png):
    """plot_failed_relative (notebook 2 cell 3): cumulative failed pods
    minus the reference policy's, per load."""
    ref = failed.get((workload, REFERENCE_POLICY))
    if not ref:
        return False

    def value_fn(policy, curve):
        if policy == REFERENCE_POLICY:
            return None
        return [
            (x, y - ref[x]) for x, y in sorted(curve.items())
            if x in ref and x <= 100
        ]

    return _plot_policies(
        failed, workload, value_fn,
        f"cumulative failed pods vs {REFERENCE_POLICY}",
        f"Failed pods relative to {REFERENCE_POLICY} — {workload}", out_png,
    )


def _split_family(workload):
    """openb_pod_list_cpu050 -> ('openb_pod_list_cpu', '050')
    (notebook 3 cell 4 split_string)."""
    m = re.match(r"([a-zA-Z_]+)(\d+)$", workload)
    return m.groups() if m else (workload, "")


def _at_load(curve, load=100):
    """Value at the target load; nearest sampled load below if the exact
    sample is missing (short traces may stop a hair under 100%)."""
    if not curve:
        return None
    if load in curve:
        return curve[load]
    below = [x for x in curve if x <= load]
    return curve[max(below)] if below else None


def build_tables(usage, pwr):
    """GRAR per trace family (notebook 3 cell 5: value at full load, one
    column per trace percentage) + cluster power at 100% with savings."""
    grar = {}  # family -> {policy: {perc: value}}
    for (workload, policy), curve in usage.items():
        fam, perc = _split_family(workload)
        v = _at_load(curve)
        if v is not None:
            grar.setdefault(fam, {}).setdefault(policy, {})[perc] = v
    power = {}  # workload -> {policy: watts@100}
    for (workload, policy), curve in pwr.items():
        v = _at_load(curve)
        if v is not None:
            power.setdefault(workload, {})[policy] = v
    return grar, power


def emit_tables(grar, power, out_dir: Path):
    md, tex = [], []
    for fam in sorted(grar):
        percs = sorted({p for pol in grar[fam].values() for p in pol})
        headers = ["Scheduling Policy"] + [
            f"GRAR ({p}%)" if p else "GRAR" for p in percs
        ]
        md.append(f"## GRAR — {fam}\n")
        md.append("| " + " | ".join(headers) + " |")
        md.append("|" + "---|" * len(headers))
        tex.append(f"% GRAR — {fam}")
        tex.append("\\begin{tabular}{" + "c" * len(headers) + "}")
        tex.append(
            " & ".join(
                "\\textbf{%s}" % h.replace("%", "\\%") for h in headers
            )
            + " \\\\ \\hline"
        )
        for policy in sorted(grar[fam]):
            vals = [grar[fam][policy].get(p) for p in percs]
            cells = ["" if v is None else f"{v:.3f}" for v in vals]
            md.append("| " + " | ".join([policy] + cells) + " |")
            tex.append(
                " & ".join([f"\\textbf{{{policy}}}".replace("_", "\\_")] + cells)
                + " \\\\"
            )
        tex.append("\\end{tabular}\n")
        md.append("")
    md.append("## Cluster power at 100% arrived load\n")
    md.append(f"| Workload | Policy | Watts | Savings vs {REFERENCE_POLICY} |")
    md.append("|---|---|---|---|")
    tex.append("% Cluster power at 100% arrived load")
    tex.append("\\begin{tabular}{llrr}")
    tex.append(
        "\\textbf{Workload} & \\textbf{Policy} & \\textbf{Watts} & "
        f"\\textbf{{Savings vs {REFERENCE_POLICY}}} \\\\ \\hline"
    )
    for workload in sorted(power):
        ref = power[workload].get(REFERENCE_POLICY)
        for policy in sorted(power[workload]):
            w = power[workload][policy]
            sav = (
                f"{100.0 * (ref - w) / ref:+.2f}%"
                if ref and policy != REFERENCE_POLICY
                else "—"
            )
            md.append(f"| {workload} | {policy} | {w:,.0f} | {sav} |")
            tex.append(
                f"{workload} & {policy} & {w:,.0f} & {sav} \\\\".replace(
                    "_", "\\_"
                ).replace("%", "\\%").replace("—", "--")
            )
    tex.append("\\end{tabular}")
    (out_dir / "power_tables.md").write_text("\n".join(md) + "\n")
    (out_dir / "power_tables.tex").write_text("\n".join(tex) + "\n")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--merged", default="experiments/analysis_results")
    ap.add_argument("--out", default=None,
                    help="output dir (default: <merged>/power)")
    args = ap.parse_args()
    merged = Path(args.merged)
    out_dir = Path(args.out) if args.out else merged / "power"
    out_dir.mkdir(parents=True, exist_ok=True)

    pwr_csv = merged / "analysis_pwr_discrete.csv"
    if not pwr_csv.is_file():
        raise SystemExit(
            f"{pwr_csv} not found — regenerate the artifact with "
            "experiments/merge.py (adds the power/usage/failed merges)"
        )
    pwr = load_curves(pwr_csv, series="cluster")
    usage = load_curves(merged / "analysis_usage_discrete.csv")
    failed_csv = merged / "analysis_failed_discrete.csv"
    failed = load_curves(failed_csv) if failed_csv.is_file() else {}

    workloads = sorted({wl for wl, _ in pwr})
    n_figs = 0
    for wl in workloads:
        n_figs += bool(
            plot_power_savings(pwr, wl, out_dir / f"power_savings_{wl}.png")
        )
        n_figs += bool(
            plot_usage_efficiency(
                usage, wl, out_dir / f"usage_efficiency_{wl}.png"
            )
        )
        if failed:
            n_figs += bool(
                plot_failed_relative(
                    failed, wl, out_dir / f"failed_relative_{wl}.png"
                )
            )
    grar, power = build_tables(usage, pwr)
    emit_tables(grar, power, out_dir)
    print(
        f"[power] {n_figs} figures + power_tables.{{md,tex}} "
        f"({len(workloads)} workloads) → {out_dir}"
    )


if __name__ == "__main__":
    main()
