#!/usr/bin/env python
"""Log → CSV analysis (ref: scripts/analysis.py in the Go simulator repo).

Parses the simulator's logrus-format log lines into the same four CSV
families the reference harness produces per experiment:

  analysis.csv       one summary row: meta + unscheduled count + per-tag
                     allocation ratios/amounts + frag-class percentages
                     (from the 16-line Cluster Analysis block)
  analysis_frag.csv  per-event frag series: origin_milli/origin_ratio/
                     origin_q124 (ref parses `[Report]; Frag amount: ...`)
  analysis_allo.csv  per-event allocation series: used_nodes/used_gpus/
                     used_gpu_milli/total_gpus/arrived_gpu_milli (+ CPU)
  analysis_cdol.csv  per-event create/delete timeline with cumulative pods
  analysis_pwr.csv   per-event power series: cluster/CPU/GPU watts

Line formats are identical to the reference's (tpusim.sim.reports emits
them), so either harness's analyzer can read either simulator's logs.
The parser stops at the `there are N unscheduled pods` stop marker, like
the reference's log_to_csv.
"""

from __future__ import annotations

import argparse
import csv
import os
import re
import sys
from pathlib import Path
from typing import Dict, List

# script mode (`python experiments/analysis.py`): the package lives one
# level up (the run.py/sweep.py pattern)
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

ALLO_KEYS = ["MilliCpu", "Memory", "Gpu", "MilliGpu"]
QUAD_KEYS = [
    "q1_lack_both",
    "q2_lack_gpu",
    "q3_satisfied",
    "q4_lack_cpu",
    "xl_satisfied",
    "xr_lack_cpu",
    "no_access",
    "frag_gpu_milli",
]
INFOMSG = "level=info msg="

# PodResource.Repr spec extractor (reference merge_fail_pods.py applies the
# same shape to its analysis_fail.out) — shared by the log parser and the
# direct path (which applies it to the reprs it generates itself)
FAIL_SPEC_RE = re.compile(
    r"<CPU:\s*([\d.]+), GPU: (\d+) x \{(\d+)\s*\}m "
    r"\(CPUREQ: [^)]*\) \(GPUREQ: ([^)]*)\)>"
)


def fail_spec_key(line: str):
    """(cpu_milli, num_gpu, gpu_milli, gpu_type) from a Repr line, or None."""
    m = FAIL_SPEC_RE.search(line)
    if not m:
        return None
    return (
        round(float(m.group(1)) * 1000),
        int(m.group(2)),
        int(m.group(3)),
        m.group(4),
    )


def fail_table(fail_specs: Dict[tuple, int]) -> Dict[str, list]:
    """Reference merged schema (merge_fail_pods.py): one row per distinct
    failed request spec, ordered by frequency, gpu_type "" → "<none>"."""
    fail = {
        "order": [],
        "num_pod": [],
        "cpu_milli": [],
        "num_gpu": [],
        "gpu_milli": [],
        "gpu_type_req": [],
    }
    ranked = sorted(fail_specs.items(), key=lambda kv: (-kv[1], kv[0]))
    for order, ((cpu, ngpu, milli, gtype), count) in enumerate(ranked):
        fail["order"].append(order)
        fail["num_pod"].append(count)
        fail["cpu_milli"].append(cpu)
        fail["num_gpu"].append(ngpu)
        fail["gpu_milli"].append(milli)
        fail["gpu_type_req"].append(
            "<none>" if gtype in ("", "ANY", "NONE") else gtype
        )
    return fail


def camel_to_snake(name: str) -> str:
    """Single-sourced from the report emitter so the direct and log-parse
    lanes can never disagree on summary key names."""
    from tpusim.sim.reports import camel_to_snake as _c2s

    return _c2s(name)


def parse_log(path: str, meta: Dict[str, str] = None) -> Dict[str, dict]:
    """One log file → {'summary': {...}, 'frag': {col: [...]}, 'allo': ...,
    'cdol': ..., 'pwr': ...}."""
    summary: Dict[str, object] = dict(meta or {})
    summary["unscheduled"] = 0
    frag: Dict[str, List[float]] = {}
    allo: Dict[str, List[int]] = {}
    pwr: Dict[str, List[float]] = {}
    cdol = {"id": [], "event": [], "pod_name": [], "cum_pod": []}
    fail_specs: Dict[tuple, int] = {}  # (cpu, ngpu, milli, type) -> count
    in_fail_block = False
    cum = 0
    live = set()  # pods currently created (ref: analysis.py cdol_pod_dict)
    tag = ""
    analysis_countdown = 0

    with open(path) as f:
        for raw in f:
            if INFOMSG not in raw:
                continue
            line = raw.split(INFOMSG, 1)[1].strip()
            if line.startswith('"'):
                line = line[1:]
            line = line.rstrip('"').rstrip()
            if line.endswith("\\n"):
                line = line[:-2]

            if "Number of original workload pods" in line:
                summary["origin_pods"] = int(line.split(":")[1].strip())
            if "there are" in line and "unscheduled pods" in line:
                summary["unscheduled"] = int(
                    line.split("unscheduled pods")[0].split("there are")[1].strip()
                )
                break

            # "Failed Pods in detail:" block (utils.go:1344-1354): group the
            # PodResource.Repr lines by request spec, like the reference's
            # merge_fail_pods.py does to its analysis_fail.out
            if line.startswith("Failed Pods in detail"):
                in_fail_block = True
                continue
            if in_fail_block:
                key = fail_spec_key(line)
                if key is not None:
                    fail_specs[key] = fail_specs.get(key, 0) + 1
                    continue
                in_fail_block = False

            if "Cluster Analysis" in line and "(" in line:
                tag = line.split(")")[0].split("(")[1]
                analysis_countdown = 16
                continue
            if analysis_countdown > 0:
                analysis_countdown -= 1
                item = line.strip().split(":")
                if len(item) > 1:
                    key, value = item[0].strip(), item[1].strip()
                    if key in ALLO_KEYS:
                        summary[camel_to_snake(key + tag)] = float(
                            value.split("%")[0]
                        )
                        summary[camel_to_snake(key + "Amount" + tag)] = float(
                            value.split("(")[1].split("/")[0]
                        )
                        summary[camel_to_snake(key + "Total")] = float(
                            value.split(")")[0].split("/")[1]
                        )
                    elif key in QUAD_KEYS:
                        summary[camel_to_snake(key + tag)] = float(
                            value.split("(")[1].split("%")[0].strip()
                        )
                continue

            if line.startswith("[Report]"):
                parts = line.split(";")
                if len(parts) == 5:  # origin variant
                    remark = parts[4].split("(")[1].split(")")[0].strip()
                    frag.setdefault(f"{remark}_milli", []).append(
                        float(parts[1].split(":")[1])
                    )
                    frag.setdefault(f"{remark}_ratio", []).append(
                        float(parts[2].split(":")[1].strip().rstrip("%"))
                    )
                    frag.setdefault(f"{remark}_q124", []).append(
                        float(parts[3].split(":")[1].strip().rstrip("%"))
                    )
                elif len(parts) == 4:  # bellman variant
                    remark = parts[3].split("(")[1].split(")")[0].strip()
                    frag.setdefault(f"{remark}_milli", []).append(
                        float(parts[1].split(":")[1])
                    )
                    frag.setdefault(f"{remark}_ratio", []).append(
                        float(parts[2].split(":")[1].strip().rstrip("%"))
                    )
            elif line.startswith("[Alloc]"):
                parts = line.split(";")
                keys = [
                    "used_nodes",
                    "used_gpus",
                    "used_gpu_milli",
                    "total_gpus",
                    "arrived_gpu_milli",
                ]
                for key, part in zip(keys, parts[1:]):
                    allo.setdefault(key, []).append(int(part.split(":")[1].strip()))
            elif line.startswith("[AllocCPU]"):
                parts = line.split(";")
                for key, part in zip(
                    ["used_cpu_milli", "arrived_cpu_milli"], parts[1:]
                ):
                    allo.setdefault(key, []).append(int(part.split(":")[1].strip()))
            elif line.startswith("[Power]"):
                parts = line.split(";")
                for key, part in zip(
                    ["power_cluster", "power_cluster_CPU", "power_cluster_GPU"],
                    parts[1:],
                ):
                    pwr.setdefault(key, []).append(float(part.split(":")[1].strip()))
            elif line.startswith("[deletePod]") and "non-scheduled" in line:
                if cdol["event"]:  # the preceding create failed — roll back
                    cdol["event"][-1] = "failed"
                    cdol["cum_pod"][-1] = cum = cum - 1
                    live.discard(cdol["pod_name"][-1])
            elif "attempt to" in line and " pod(" in line and line.startswith("["):
                event_id = int(line.split("]")[0][1:])
                verb = line.split("attempt to ")[1].split()[0]
                pod_name = line.split("pod(")[1].split(")")[0]
                if verb == "create":
                    cum += 1
                    live.add(pod_name)
                elif pod_name in live:
                    cum -= 1
                    live.discard(pod_name)
                else:
                    # delete of a pod whose creation failed: no cumsum change,
                    # renamed to keep event counts aligned (ref: analysis.py
                    # "skipped" branch)
                    verb = "skipped"
                cdol["id"].append(event_id)
                cdol["event"].append(verb)
                cdol["pod_name"].append(pod_name)
                cdol["cum_pod"].append(cum)

    return {
        "summary": summary,
        "frag": frag,
        "allo": allo,
        "cdol": cdol,
        "pwr": pwr,
        "fail": fail_table(fail_specs),
    }


def _write_series_csv(path: Path, series: Dict[str, list]):
    if not series:
        return
    n = max(len(v) for v in series.values())
    cols = [
        [str(v[i]) if i < len(v) else "" for i in range(n)]
        for v in series.values()
    ]
    # join-based fast path (~3x csv.writer over the 16k-row series, ×5
    # files ×2100 experiments); byte-identical to csv.writer for values
    # needing no quoting — anything else falls back to the real writer
    if any(
        any(ch in cell for ch in ',"\r\n')
        for col in cols for cell in col
    ):
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(series.keys())
            for i in range(n):
                w.writerow([col[i] for col in cols])
        return
    lines = [",".join(series.keys())]
    lines.extend(",".join(row) for row in zip(*cols))
    with open(path, "w", newline="") as f:
        f.write("\r\n".join(lines) + "\r\n")


def _write_experiment_csvs(exp: Path, rows: List[dict], result: dict):
    """The per-experiment CSV family from a parse_log/build_result_from_sim
    result dict — shared by both analysis lanes so file layout and cell
    conversion can never drift."""
    cols: List[str] = []
    for r in rows:
        cols.extend(k for k in r if k not in cols)
    with open(exp / "analysis.csv", "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=cols)
        w.writeheader()
        w.writerows(rows)
    _write_series_csv(exp / "analysis_frag.csv", result["frag"])
    _write_series_csv(exp / "analysis_allo.csv", result["allo"])
    _write_series_csv(exp / "analysis_cdol.csv", result["cdol"])
    _write_series_csv(exp / "analysis_pwr.csv", result["pwr"])
    # always reconcile (a stale file from a previous run of this directory
    # would otherwise be merged as current data)
    fail_csv = exp / "analysis_fail.csv"
    if result["fail"]["order"]:
        _write_series_csv(fail_csv, result["fail"])
    elif fail_csv.exists():
        fail_csv.unlink()


def analyze_dir(exp_dir: str, meta: Dict[str, str] = None) -> dict:
    """Parse every *.log under exp_dir, write analysis{,_frag,_allo,_cdol,
    _pwr}.csv beside them (one experiment per directory in this harness).
    This is the log-compat lane; the sweep's default is analyze_sim."""
    exp = Path(exp_dir)
    logs = sorted(exp.glob("*.log"))
    if not logs:
        raise FileNotFoundError(f"no *.log under {exp_dir}")
    rows = []
    result = None
    for log in logs:
        result = parse_log(str(log), meta)
        rows.append(result["summary"])
    # series CSVs reflect the last log (harness runs one log per dir)
    _write_experiment_csvs(exp, rows, result)
    return result


def build_result_from_sim(sim, meta: Dict[str, str] = None) -> dict:
    """parse_log's result dict built directly from the driver's structured
    stashes — no log round trip. Byte-identical to parsing the log this
    run wrote: every float passes through the SAME formatted string the
    log line carries (tpusim.sim.reports.event_report_series /
    cluster_analysis_block), every ordering mirrors the parser's insertion
    order, and the stop-marker semantics (only events logged before
    `finish()` count) hold because the driver stashes exactly what it
    logged."""
    import numpy as np

    from tpusim.sim.engine import EV_CREATE, EV_DELETE
    from tpusim.sim.reports import pod_resource_repr

    summary: Dict[str, object] = dict(meta or {})
    summary["unscheduled"] = 0
    summary["origin_pods"] = len(sim.workload_pods)
    summary.update(sim.analysis_summary)
    summary["unscheduled"] = len(sim.last_result.unscheduled_pods)

    frag: Dict[str, list] = {}
    allo: Dict[str, list] = {}
    pwr: Dict[str, list] = {}
    cdol = {"id": [], "event": [], "pod_name": [], "cum_pod": []}
    cum = 0
    live = set()
    for rep in sim.event_reports:
        kinds = rep["kinds"]
        active = (kinds == EV_CREATE) | (kinds == EV_DELETE)
        s = rep["series"]
        # [Report] families: float() of the same formatted strings the log
        # lines embed (event_report_series)
        for key in ("origin_milli", "origin_ratio", "origin_q124"):
            frag.setdefault(key, []).extend(
                s[key][active].astype(np.float64).tolist()
            )
        if "bellman_milli" in s:
            for key in ("bellman_milli", "bellman_ratio"):
                frag.setdefault(key, []).extend(
                    s[key][active].astype(np.float64).tolist()
                )
        for key in (
            "used_nodes", "used_gpus", "used_gpu_milli",
        ):
            allo.setdefault(key, []).extend(rep[key][active].tolist())
        allo.setdefault("total_gpus", []).extend(
            [int(rep["total_gpus"])] * int(active.sum())
        )
        for key in ("arrived_gpu_milli", "used_cpu_milli", "arrived_cpu_milli"):
            allo.setdefault(key, []).extend(rep[key][active].tolist())
        for key in ("power_cluster", "power_cluster_CPU", "power_cluster_GPU"):
            pwr.setdefault(key, []).extend(
                s[key][active].astype(np.float64).tolist()
            )
        # cdol timeline (the parser's create/delete/failed/skipped calculus
        # over the attempt + rollback lines), vectorized — a 10k-iteration
        # Python loop was ~half of this lane's cost at sweep scale. Event
        # streams carry at most one create and one later delete per pod
        # name (build_events), so "name in live" at a delete collapses to:
        # successfully created earlier in THIS replay, or live carried
        # over from an earlier replay (deschedule victims re-create pods
        # the main replay left live).
        names = rep["pod_names"]
        failed = rep["failed"]
        act = np.flatnonzero(active)
        if len(act):
            k_act = kinds[act]
            is_create = k_act == EV_CREATE
            fail_act = failed[act]
            name_act = names[act]
            create_pos = {
                name_act[j]: j for j in np.flatnonzero(is_create)
            }
            e_act = len(act)
            cpos = np.fromiter(
                (create_pos.get(n, e_act) for n in name_act),
                np.int64, count=e_act,
            )
            cposc = np.minimum(cpos, e_act - 1)
            created_ok_before = (
                (cpos < np.arange(e_act)) & ~fail_act[cposc]
            )
            prev_live = np.fromiter(
                (n in live for n in name_act), bool, count=e_act
            )
            is_delete_live = ~is_create & (created_ok_before | prev_live)
            verbs = np.where(
                is_create,
                np.where(fail_act, "failed", "create"),
                np.where(is_delete_live, "delete", "skipped"),
            )
            delta = np.where(
                is_create & ~fail_act, 1, np.where(is_delete_live, -1, 0)
            )
            cums = cum + np.cumsum(delta)
            cum = int(cums[-1])
            # carry the live set across replays (net effect of this one)
            for j in np.flatnonzero(is_create & ~fail_act):
                live.add(name_act[j])
            for j in np.flatnonzero(is_delete_live):
                live.discard(name_act[j])
            cdol["id"].extend(act.tolist())
            cdol["event"].extend(verbs.tolist())
            cdol["pod_name"].extend(name_act.tolist())
            cdol["cum_pod"].extend(cums.tolist())

    # fail block: the same Repr -> regex -> grouping the parser applies,
    # run over the reprs this run logged (sim.report_failed stash)
    fail_specs: Dict[tuple, int] = {}
    for pods in sim.failed_pod_lists:
        for p in pods:
            key = fail_spec_key(
                pod_resource_repr(p.cpu_milli, p.num_gpu, p.gpu_milli, p.gpu_spec)
            )
            if key is not None:
                fail_specs[key] = fail_specs.get(key, 0) + 1

    return {
        "summary": summary,
        "frag": frag,
        "allo": allo,
        "cdol": cdol,
        "pwr": pwr,
        "fail": fail_table(fail_specs),
    }


def analyze_sim(sim, exp_dir: str, meta: Dict[str, str] = None) -> dict:
    """Direct analysis lane: the same CSV family analyze_dir writes, built
    from the driver's arrays instead of re-parsing the log (the log-line →
    regex → CSV round trip was ~1/3 of sweep wall clock; the log itself is
    still written for the reference-format contract)."""
    exp = Path(exp_dir)
    result = build_result_from_sim(sim, meta)
    _write_experiment_csvs(exp, [result["summary"]], result)
    return result


def diff_decision_runs(path_a: str, path_b: str, buckets: int = 10) -> dict:
    """Divergence tracing between two decision JSONLs (ISSUE 4; the
    `tpusim diff` logic, exposed here so sweep analyses can diff
    policies programmatically): {'first': first-divergence dict or None,
    'histogram': bucketed divergence counts, 'text': the formatted
    report}. The per-event placement series these files carry is exactly
    the comparison the paper's FGD-vs-baseline argument rests on —
    which event diverged first, and where divergence concentrates."""
    from tpusim.obs import decisions as obs_decisions

    ha, ra = obs_decisions.read_decisions(path_a)
    hb, rb = obs_decisions.read_decisions(path_b)
    return obs_decisions.run_diff(
        ha, ra, hb, rb,
        label_a=os.path.basename(path_a),
        label_b=os.path.basename(path_b),
        buckets=buckets,
    )


def plot_series(run_jsonl: str, out_png: str = "") -> str:
    """Plot the in-scan series block of a run-record JSONL (ISSUE 5; a
    `tpusim apply --profile --series-every` output): four panels over the
    event axis — node-utilization histogram occupancy bands, frag by FGD
    category, feasible/DOWN/retry counts, per-policy normalized score
    hi/lo envelope. Renders straight from the record (no simulator, no
    recomputation — the `tpusim report` contract, as a figure). Returns
    the PNG path written (default: beside the JSONL)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import numpy as np

    from tpusim.obs.emitters import read_jsonl
    from tpusim.obs.series import series_from_record

    records = [r for r in read_jsonl(run_jsonl) if r.get("series")]
    if not records:
        raise ValueError(
            f"{run_jsonl}: no record carries a series block (was the run "
            "made with --series-every and --profile?)"
        )
    series = records[-1]["series"]
    log = series_from_record(series)
    pos = np.asarray(log.pos)

    fig, axes = plt.subplots(4, 1, figsize=(9, 11), sharex=True)
    ax = axes[0]
    hist = np.asarray(log.util_hist)
    nb = hist.shape[1]
    ax.stackplot(
        pos, hist.T,
        labels=[f"{100 * b // nb}-{100 * (b + 1) // nb}%"
                for b in range(nb)],
        cmap="viridis",
    )
    ax.set_ylabel("GPU nodes by occupancy")
    ax.legend(fontsize=6, ncol=5, loc="upper left")

    ax = axes[1]
    frag = np.asarray(log.frag)
    for j, name in enumerate(series.get("frag_categories", [])):
        col = frag[:, j]
        if col.any():
            ax.plot(pos, col / 1000.0, label=name)
    ax.set_ylabel("frag (GPUs)")
    ax.legend(fontsize=7)

    ax = axes[2]
    ax.plot(pos, np.asarray(log.feasible), label="feasible nodes")
    ax.plot(pos, np.asarray(log.nodes_down), label="nodes DOWN")
    ax.plot(pos, np.asarray(log.retry_depth), label="retry queue")
    ax.set_ylabel("count")
    ax.legend(fontsize=7)

    ax = axes[3]
    hi = np.asarray(log.score_hi)
    lo = np.asarray(log.score_lo)
    for i, pol in enumerate(series.get("policies", [])):
        (line,) = ax.plot(pos, hi[:, i], label=pol)
        ax.fill_between(pos, lo[:, i], hi[:, i], alpha=0.15,
                        color=line.get_color())
    ax.set_ylabel("normalized score hi/lo")
    ax.set_xlabel(f"event (stride {series.get('every')})")
    ax.legend(fontsize=7)

    fig.suptitle(os.path.basename(run_jsonl))
    fig.tight_layout()
    out_png = out_png or (os.path.splitext(run_jsonl)[0] + "_series.png")
    fig.savefig(out_png, dpi=120)
    plt.close(fig)
    return out_png


def plot_tuning(log_jsonl: str, out_png: str = "") -> str:
    """Plot a tuning log (ISSUE 9; a `tpusim tune --log` output) to PNG:
    two panels over the generation axis — the objective curves (per-gen
    best, running best, population mean/min band, optional robustness
    eval) and the optimizer's mean weight trajectory per policy.
    Renders straight from the digest-signed log (tpusim.learn.read_log
    verifies it) — no simulator, no recomputation. Returns the PNG
    path (default: beside the log)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import numpy as np

    from tpusim.learn.loop import read_log
    from tpusim.obs.emitters import tuning_curve_series

    header, records = read_log(log_jsonl)
    if not records:
        raise ValueError(f"{log_jsonl}: tuning log has no generations")
    tracks = tuning_curve_series(records)
    gens = np.asarray(tracks["tune_gen"])

    fig, axes = plt.subplots(2, 1, figsize=(9, 7), sharex=True)
    ax = axes[0]
    ax.plot(gens, tracks["tune_best"], label="best so far", lw=2)
    ax.plot(gens, tracks["tune_gen_best"], label="generation best")
    ax.plot(gens, tracks["tune_mean"], label="population mean",
            ls="--")
    ax.fill_between(gens, tracks["tune_min"], tracks["tune_gen_best"],
                    alpha=0.15)
    if "tune_robust" in tracks:
        ax.plot(gens, tracks["tune_robust"], label="robust (faulted)",
                ls=":")
    ax.set_ylabel("objective")
    ax.legend(fontsize=7)

    ax = axes[1]
    means = np.asarray([r["state"]["mean"] for r in records])
    names = [n for n, _ in header["config"]["policies"]]
    for i, name in enumerate(names):
        ax.plot(gens, means[:, i], label=name)
    ax.set_ylabel("mean weight")
    ax.set_xlabel("generation")
    ax.legend(fontsize=7)

    fig.suptitle(os.path.basename(log_jsonl))
    fig.tight_layout()
    out_png = out_png or (os.path.splitext(log_jsonl)[0] + "_tuning.png")
    fig.savefig(out_png, dpi=120)
    plt.close(fig)
    return out_png


def main():
    ap = argparse.ArgumentParser(description="simulator log → analysis CSVs")
    ap.add_argument("-g", "--log-dir", help="experiment directory")
    ap.add_argument(
        "-f",
        "--failed-pods",
        action="store_true",
        help="also list failed pods (ref: failed_pods_in_detail)",
    )
    ap.add_argument(
        "--diff-decisions", nargs=2, metavar=("RUN_A", "RUN_B"),
        help="diff two decision JSONLs (tpusim apply --decisions-out) "
        "instead of parsing logs: first divergence + histogram",
    )
    ap.add_argument(
        "--plot-series", metavar="RUN_JSONL",
        help="plot the in-scan series block of a run-record JSONL "
        "(tpusim apply --profile --series-every) to PNG — utilization "
        "bands, frag by category, feasible/DOWN/retry, score envelopes",
    )
    ap.add_argument(
        "--plot-tuning", metavar="TUNE_JSONL",
        help="plot a tuning log (tpusim tune --log) to PNG — objective "
        "curves per generation + the mean weight trajectory per policy",
    )
    ap.add_argument(
        "-o", "--out", default="",
        help="output PNG path for --plot-series / --plot-tuning "
        "(default: beside the JSONL, *_series.png / *_tuning.png)",
    )
    args = ap.parse_args()
    if args.plot_series:
        try:
            path = plot_series(args.plot_series, args.out)
        except (OSError, ValueError) as err:
            print(f"analysis --plot-series: {err}", file=sys.stderr)
            return 2
        print(f"[analysis] wrote {path}")
        return 0
    if args.plot_tuning:
        try:
            path = plot_tuning(args.plot_tuning, args.out)
        except (OSError, ValueError) as err:
            print(f"analysis --plot-tuning: {err}", file=sys.stderr)
            return 2
        print(f"[analysis] wrote {path}")
        return 0
    if args.diff_decisions:
        # exit codes mirror `tpusim diff`: 0 identical, 1 divergence,
        # 2 unusable input (missing/torn file, runs from different
        # traces) — a one-line error, never a traceback read as exit 1
        try:
            d = diff_decision_runs(*args.diff_decisions)
        except (OSError, ValueError) as err:
            print(f"analysis --diff-decisions: {err}", file=sys.stderr)
            return 2
        print(d["text"])
        return 1 if d["first"] else 0
    if not args.log_dir:
        ap.error("-g/--log-dir is required (unless --diff-decisions / "
                 "--plot-series / --plot-tuning)")
    result = analyze_dir(args.log_dir)
    s = result["summary"]
    print(
        f"[analysis] {args.log_dir}: unscheduled={s.get('unscheduled')}"
        f" milli_gpu_init={s.get('milli_gpu_init_schedule')}"
    )
    if args.failed_pods:
        fails = [
            e
            for e, name in zip(result["cdol"]["event"], result["cdol"]["pod_name"])
            if e == "failed"
        ]
        print(f"[analysis] failed pods: {len(fails)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
