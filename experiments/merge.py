#!/usr/bin/env python
"""Merge per-experiment analysis CSVs into the discrete cross-experiment
tables (ref: experiments/analysis/merge_{alloc,frag,frag_ratio}_discrete.py
+ merge_fail_pods.py + analysis_merge.sh, all in one tool).

Walks <data-root>/<workload>/<policy>/<tune>/<seed>/analysis_allo.csv (the
layout experiments/run.py + generate_run_scripts.py produce) and emits:

  analysis_allo_discrete.csv        GPU allocation ratio (%) sampled at each
                                    integer arrived-load percent 0..130
  analysis_frag_discrete.csv        frag amount (% of cluster GPU capacity,
                                    the reference's unit) at same samples
  analysis_frag_ratio_discrete.csv  frag ratio (%) at same samples
  analysis_fail_pods.csv            unscheduled-pod count per experiment
  analysis_pwr_discrete.csv         cluster/cpu/gpu watts at same samples
                                    (one row per experiment per series —
                                    the fork's power deliverable, notebook
                                    "1 - Parse results" cells 2/4)
  analysis_usage_discrete.csv       used/arrived GPU milli ratio (GRAR /
                                    usage_efficiency, notebook cell 8)
  analysis_failed_discrete.csv      cumulative failed-pod count at same
                                    samples (notebook cell 2 sched df)

Row key: (workload, sc_policy, tune, seed) — the schema of
experiments/analysis/expected_results/*.csv in the reference, so its
plotting notebooks work on these files unchanged.
"""

from __future__ import annotations

import argparse
import csv
import math
from pathlib import Path


def read_csv_dict(path: Path):
    with open(path, newline="") as f:
        rows = list(csv.DictReader(f))
    return rows


def discretize(series_x, series_y, lo=0, hi=130):
    """Sample y at each integer percent of x (ref merge_alloc_discrete.py:
    exact-match bucket, else mean of x within ±1).

    Single pass over the series (the naive per-target rescan is quadratic
    and dominates merge time at artifact scale: 131 targets × ~20k samples
    × hundreds of experiments)."""
    exact = {}  # target -> [sum, n] for round(x) == target
    near = {}  # target -> [sum, n] for target-1 <= x <= target+1
    for x, y in zip(series_x, series_y):
        r = round(x)
        if lo <= r <= hi:
            b = exact.setdefault(r, [0.0, 0])
            b[0] += y
            b[1] += 1
        for t in range(max(lo, math.ceil(x - 1)), min(hi, math.floor(x + 1)) + 1):
            b = near.setdefault(t, [0.0, 0])
            b[0] += y
            b[1] += 1
    out = {}
    for target in range(lo, hi + 1):
        b = exact.get(target) or near.get(target)
        if b:
            out[target] = round(b[0] / b[1], 2)
    return out


def merge(data_root: Path, out_dir: Path):
    allo_rows, frag_rows, fratio_rows, fail_rows = [], [], [], []
    fail_detail_rows = []  # ref: merge_fail_pods.py → analysis_fail.csv
    pwr_rows = []  # power series (fork notebook "1 - Parse results" cell 2)
    usage_rows = []  # used/arrived GPU ratio (notebook cell 8 usage_efficiency)
    failed_rows = []  # cumulative failed pods (notebook cell 2 sched df)
    for allo_file in sorted(data_root.glob("*/*/*/*/analysis_allo.csv")):
        exp_dir = allo_file.parent
        seed = exp_dir.name
        tune = exp_dir.parent.name
        policy = exp_dir.parent.parent.name
        workload = exp_dir.parent.parent.parent.name
        key = {
            "workload": workload,
            "sc_policy": policy,
            "tune": tune,
            "seed": seed,
        }

        allo = read_csv_dict(allo_file)
        if not allo:
            continue
        total_gpus = int(float(allo[0]["total_gpus"]))
        # percent of cluster GPU capacity: milli / total_gpus / 10
        arrive = [float(r["arrived_gpu_milli"]) / total_gpus / 10 for r in allo]
        alloc = [float(r["used_gpu_milli"]) / total_gpus / 10 for r in allo]
        row = dict(key, total_gpus=total_gpus)
        row.update(discretize(arrive, alloc))
        allo_rows.append(row)

        frag_file = exp_dir / "analysis_frag.csv"
        if frag_file.is_file():
            frag = read_csv_dict(frag_file)
            n = min(len(frag), len(arrive))
            # frag amount as PERCENT of cluster GPU capacity — the
            # reference's unit (merge_frag_discrete.py:88:
            # 100 * frag_milli / 1000 / total_gpu_num), so its plot scripts
            # read these files unchanged
            fmilli = [
                float(r["origin_milli"]) / total_gpus / 10 for r in frag[:n]
            ]
            fratio = [float(r["origin_ratio"]) for r in frag[:n]]
            row = dict(key, total_gpus=total_gpus)
            row.update(discretize(arrive[:n], fmilli))
            frag_rows.append(row)
            row = dict(key, total_gpus=total_gpus)
            row.update(discretize(arrive[:n], fratio))
            fratio_rows.append(row)

        # merged power curves (the fork's distinguishing deliverable: its
        # "1 - Parse results" notebook builds per-seed power/efficiency/
        # failure curves on a cumulative-workload axis and averages them;
        # here the same series are sampled at integer arrived-load percent
        # like every other *_discrete table, one row per (experiment, series))
        pwr_file = exp_dir / "analysis_pwr.csv"
        if pwr_file.is_file():
            pwr = read_csv_dict(pwr_file)
            n = min(len(pwr), len(arrive))
            for series, col in (
                ("cluster", "power_cluster"),
                ("cpu", "power_cluster_CPU"),
                ("gpu", "power_cluster_GPU"),
            ):
                vals = [float(r[col]) for r in pwr[:n]]
                row = dict(key, total_gpus=total_gpus, series=series)
                row.update(discretize(arrive[:n], vals))
                pwr_rows.append(row)

        # GPU usage efficiency = used / arrived milli (GRAR; guard the
        # pre-arrival zero rows the notebook's interpolation papers over)
        usage = [
            float(r["used_gpu_milli"]) / max(float(r["arrived_gpu_milli"]), 1.0)
            for r in allo
        ]
        row = dict(key, total_gpus=total_gpus)
        row.update(discretize(arrive, usage))
        usage_rows.append(row)

        cdol_file = exp_dir / "analysis_cdol.csv"
        if cdol_file.is_file():
            cdol = read_csv_dict(cdol_file)
            n = min(len(cdol), len(arrive))
            cum, curve = 0, []
            for r in cdol[:n]:
                cum += 1 if r["event"] == "failed" else 0
                curve.append(float(cum))
            row = dict(key, total_gpus=total_gpus)
            row.update(discretize(arrive[:n], curve))
            failed_rows.append(row)

        summary_file = exp_dir / "analysis.csv"
        if summary_file.is_file():
            summary = read_csv_dict(summary_file)
            if summary:
                fail_rows.append(
                    dict(key, unscheduled=summary[0].get("unscheduled", ""))
                )

        detail_file = exp_dir / "analysis_fail.csv"
        if detail_file.is_file():
            for r in read_csv_dict(detail_file):
                fail_detail_rows.append(dict(key, **r))

    out_dir.mkdir(parents=True, exist_ok=True)
    if fail_detail_rows:
        cols = [
            "workload", "sc_policy", "tune", "seed", "order", "num_pod",
            "cpu_milli", "num_gpu", "gpu_milli", "gpu_type_req",
        ]
        with open(out_dir / "analysis_fail.csv", "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=cols)
            w.writeheader()
            w.writerows(fail_detail_rows)
        print(
            f"[merge] {len(fail_detail_rows)} rows → "
            f"{out_dir / 'analysis_fail.csv'}"
        )
    for name, rows in (
        ("analysis_allo_discrete.csv", allo_rows),
        ("analysis_frag_discrete.csv", frag_rows),
        ("analysis_frag_ratio_discrete.csv", fratio_rows),
        ("analysis_fail_pods.csv", fail_rows),
        ("analysis_pwr_discrete.csv", pwr_rows),
        ("analysis_usage_discrete.csv", usage_rows),
        ("analysis_failed_discrete.csv", failed_rows),
    ):
        if not rows:
            continue
        cols = ["workload", "sc_policy", "tune", "seed", "total_gpus", "series"]
        extra = sorted(
            {k for r in rows for k in r if k not in cols},
            key=lambda k: (isinstance(k, str), k),
        )
        cols = [c for c in cols if any(c in r for r in rows)] + extra
        with open(out_dir / name, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=cols)
            w.writeheader()
            w.writerows(rows)
        print(f"[merge] {len(rows)} rows → {out_dir / name}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-root", default="experiments/data")
    ap.add_argument("--out-dir", default="experiments/analysis_results")
    args = ap.parse_args()
    merge(Path(args.data_root), Path(args.out_dir))


if __name__ == "__main__":
    main()
