#!/usr/bin/env python
"""Merge per-experiment analysis CSVs into the discrete cross-experiment
tables (ref: experiments/analysis/merge_{alloc,frag,frag_ratio}_discrete.py
+ merge_fail_pods.py + analysis_merge.sh, all in one tool).

Walks <data-root>/<workload>/<policy>/<tune>/<seed>/analysis_allo.csv (the
layout experiments/run.py + generate_run_scripts.py produce) and emits:

  analysis_allo_discrete.csv        GPU allocation ratio (%) sampled at each
                                    integer arrived-load percent 0..130
  analysis_frag_discrete.csv        frag amount (% of cluster GPU capacity,
                                    the reference's unit) at same samples
  analysis_frag_ratio_discrete.csv  frag ratio (%) at same samples
  analysis_fail_pods.csv            unscheduled-pod count per experiment
  analysis_pwr_discrete.csv         cluster/cpu/gpu watts at same samples
                                    (one row per experiment per series —
                                    the fork's power deliverable, notebook
                                    "1 - Parse results" cells 2/4)
  analysis_usage_discrete.csv       used/arrived GPU milli ratio (GRAR /
                                    usage_efficiency, notebook cell 8)
  analysis_failed_discrete.csv      cumulative failed-pod count at same
                                    samples (notebook cell 2 sched df)

Row key: (workload, sc_policy, tune, seed) — the schema of
experiments/analysis/expected_results/*.csv in the reference, so its
plotting notebooks work on these files unchanged.
"""

from __future__ import annotations

import argparse
import csv
from pathlib import Path

import numpy as np
import pandas as pd


def _read_csv(path: Path) -> pd.DataFrame:
    """pd.read_csv that treats a zero-byte/truncated-header file (a sweep
    killed mid-experiment leaves those) as empty instead of aborting the
    whole artifact merge — the graceful-skip behavior of the DictReader it
    replaced."""
    try:
        return pd.read_csv(path)
    except pd.errors.EmptyDataError:
        return pd.DataFrame()


def read_csv_dict(path: Path):
    with open(path, newline="") as f:
        rows = list(csv.DictReader(f))
    return rows


def discretize(series_x, series_y, lo=0, hi=130):
    """Sample y at each integer percent of x (ref merge_alloc_discrete.py:
    exact-match bucket, else mean of x within ±1).

    Vectorized single pass; accumulation order matches the original scalar
    loop exactly (np.add.at applies contributions in index-array order and
    the candidate grid flattens row-major = per-sample ascending targets),
    so the f64 bucket sums — and therefore the rounded merged cells — are
    bit-identical to the loop it replaces. The scalar loop was the
    dominant merge cost at artifact scale (131 targets × ~20k samples ×
    2100 experiments)."""
    x = np.asarray(series_x, np.float64)
    y = np.asarray(series_y, np.float64)
    width = hi - lo + 1
    esum = np.zeros(width)
    ecnt = np.zeros(width, np.int64)
    r = np.round(x)  # banker's rounding, same as builtins.round
    in_r = (r >= lo) & (r <= hi)
    ri = r[in_r].astype(np.int64) - lo
    np.add.at(esum, ri, y[in_r])
    np.add.at(ecnt, ri, 1)

    nsum = np.zeros(width)
    ncnt = np.zeros(width, np.int64)
    c0 = np.ceil(x - 1).astype(np.int64)
    c1 = np.floor(x + 1).astype(np.int64)
    cand = c0[:, None] + np.arange(3)[None, :]  # [n, 3] ascending per row
    mask = (cand <= c1[:, None]) & (cand >= lo) & (cand <= hi)
    np.add.at(nsum, (cand - lo)[mask], np.broadcast_to(y[:, None], cand.shape)[mask])
    np.add.at(ncnt, (cand - lo)[mask], 1)

    out = {}
    for target in range(lo, hi + 1):
        i = target - lo
        # round() on a np.float64 delegates to numpy's scaled rounding,
        # which can land one ulp off Python's correctly-rounded round(x, 2)
        # — cast to builtin float so cells match the scalar-loop original
        if ecnt[i]:
            out[target] = round(float(esum[i]) / int(ecnt[i]), 2)
        elif ncnt[i]:
            out[target] = round(float(nsum[i]) / int(ncnt[i]), 2)
    return out


def merge(data_root: Path, out_dir: Path):
    allo_rows, frag_rows, fratio_rows, fail_rows = [], [], [], []
    fail_detail_rows = []  # ref: merge_fail_pods.py → analysis_fail.csv
    pwr_rows = []  # power series (fork notebook "1 - Parse results" cell 2)
    usage_rows = []  # used/arrived GPU ratio (notebook cell 8 usage_efficiency)
    failed_rows = []  # cumulative failed pods (notebook cell 2 sched df)
    for allo_file in sorted(data_root.glob("*/*/*/*/analysis_allo.csv")):
        exp_dir = allo_file.parent
        seed = exp_dir.name
        tune = exp_dir.parent.name
        policy = exp_dir.parent.parent.name
        workload = exp_dir.parent.parent.parent.name
        key = {
            "workload": workload,
            "sc_policy": policy,
            "tune": tune,
            "seed": seed,
        }

        # pandas' C parser for the big per-event series (csv.DictReader
        # was ~30% of merge wall); arithmetic stays elementwise f64,
        # identical to the float()-per-cell loops it replaces
        allo = _read_csv(allo_file)
        if not len(allo):
            continue
        total_gpus = int(allo["total_gpus"].iloc[0])
        arr_milli = allo["arrived_gpu_milli"].to_numpy(np.float64)
        used_milli = allo["used_gpu_milli"].to_numpy(np.float64)
        # percent of cluster GPU capacity: milli / total_gpus / 10
        arrive = arr_milli / total_gpus / 10
        alloc = used_milli / total_gpus / 10
        row = dict(key, total_gpus=total_gpus)
        row.update(discretize(arrive, alloc))
        allo_rows.append(row)

        frag_file = exp_dir / "analysis_frag.csv"
        if frag_file.is_file() and len(frag := _read_csv(frag_file)):
            n = min(len(frag), len(arrive))
            # frag amount as PERCENT of cluster GPU capacity — the
            # reference's unit (merge_frag_discrete.py:88:
            # 100 * frag_milli / 1000 / total_gpu_num), so its plot scripts
            # read these files unchanged
            fmilli = (
                frag["origin_milli"].to_numpy(np.float64)[:n] / total_gpus / 10
            )
            fratio = frag["origin_ratio"].to_numpy(np.float64)[:n]
            row = dict(key, total_gpus=total_gpus)
            row.update(discretize(arrive[:n], fmilli))
            frag_rows.append(row)
            row = dict(key, total_gpus=total_gpus)
            row.update(discretize(arrive[:n], fratio))
            fratio_rows.append(row)

        # merged power curves (the fork's distinguishing deliverable: its
        # "1 - Parse results" notebook builds per-seed power/efficiency/
        # failure curves on a cumulative-workload axis and averages them;
        # here the same series are sampled at integer arrived-load percent
        # like every other *_discrete table, one row per (experiment, series))
        pwr_file = exp_dir / "analysis_pwr.csv"
        if pwr_file.is_file() and len(pwr := _read_csv(pwr_file)):
            n = min(len(pwr), len(arrive))
            for series, col in (
                ("cluster", "power_cluster"),
                ("cpu", "power_cluster_CPU"),
                ("gpu", "power_cluster_GPU"),
            ):
                vals = pwr[col].to_numpy(np.float64)[:n]
                row = dict(key, total_gpus=total_gpus, series=series)
                row.update(discretize(arrive[:n], vals))
                pwr_rows.append(row)

        # GPU usage efficiency = used / arrived milli (GRAR; guard the
        # pre-arrival zero rows the notebook's interpolation papers over)
        usage = used_milli / np.maximum(arr_milli, 1.0)
        row = dict(key, total_gpus=total_gpus)
        row.update(discretize(arrive, usage))
        usage_rows.append(row)

        cdol_file = exp_dir / "analysis_cdol.csv"
        if cdol_file.is_file():
            events = _read_csv(cdol_file).get("event", pd.Series([])).to_numpy()
            n = min(len(events), len(arrive))
            curve = np.cumsum(events[:n] == "failed").astype(np.float64)
            row = dict(key, total_gpus=total_gpus)
            row.update(discretize(arrive[:n], curve))
            failed_rows.append(row)

        summary_file = exp_dir / "analysis.csv"
        if summary_file.is_file():
            summary = read_csv_dict(summary_file)
            if summary:
                fail_rows.append(
                    dict(key, unscheduled=summary[0].get("unscheduled", ""))
                )

        detail_file = exp_dir / "analysis_fail.csv"
        if detail_file.is_file():
            for r in read_csv_dict(detail_file):
                fail_detail_rows.append(dict(key, **r))

    out_dir.mkdir(parents=True, exist_ok=True)
    if fail_detail_rows:
        cols = [
            "workload", "sc_policy", "tune", "seed", "order", "num_pod",
            "cpu_milli", "num_gpu", "gpu_milli", "gpu_type_req",
        ]
        with open(out_dir / "analysis_fail.csv", "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=cols)
            w.writeheader()
            w.writerows(fail_detail_rows)
        print(
            f"[merge] {len(fail_detail_rows)} rows → "
            f"{out_dir / 'analysis_fail.csv'}"
        )
    for name, rows in (
        ("analysis_allo_discrete.csv", allo_rows),
        ("analysis_frag_discrete.csv", frag_rows),
        ("analysis_frag_ratio_discrete.csv", fratio_rows),
        ("analysis_fail_pods.csv", fail_rows),
        ("analysis_pwr_discrete.csv", pwr_rows),
        ("analysis_usage_discrete.csv", usage_rows),
        ("analysis_failed_discrete.csv", failed_rows),
    ):
        if not rows:
            continue
        cols = ["workload", "sc_policy", "tune", "seed", "total_gpus", "series"]
        extra = sorted(
            {k for r in rows for k in r if k not in cols},
            key=lambda k: (isinstance(k, str), k),
        )
        cols = [c for c in cols if any(c in r for r in rows)] + extra
        with open(out_dir / name, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=cols)
            w.writeheader()
            w.writerows(rows)
        print(f"[merge] {len(rows)} rows → {out_dir / name}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-root", default="experiments/data")
    ap.add_argument("--out-dir", default="experiments/analysis_results")
    args = ap.parse_args()
    merge(Path(args.data_root), Path(args.out_dir))


if __name__ == "__main__":
    main()
