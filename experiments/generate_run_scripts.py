#!/usr/bin/env python
"""Emit one `experiments/run.py` bash line per (trace × policy × seed)
(ref: experiments/run_scripts/generate_run_scripts.py).

Usage: python experiments/generate_run_scripts.py > run_scripts.sh
       bash run_scripts.sh                      # or: xargs -P for parallel

The default sweep covers the reference's full AllMethodList × trace grid:
10 method rows (6 headline policies, 07-PWR, and the PWR/FGD weighted mixes
08/11/12) × 21 openb trace variants × 10 seeds at tuning ratio 1.3 and
shuffled pod order = 2100 commands. The reference's cached 1020-experiment
matrix is the 6-headline-policy × 17-trace subset; reproduce it with

  --methods 01-Random 02-DotProd 03-GpuClustering 04-GpuPacking \
            05-BestFit 06-FGD
"""

from __future__ import annotations

import argparse
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

TRACES = [
    "openb_pod_list_default",
    "openb_pod_list_cpu037",
    "openb_pod_list_cpu050",
    "openb_pod_list_cpu072",
    "openb_pod_list_cpu100",
    "openb_pod_list_cpu200",
    "openb_pod_list_cpu250",
    "openb_pod_list_cpu300",
    "openb_pod_list_gpushare20",
    "openb_pod_list_gpushare40",
    "openb_pod_list_gpushare60",
    "openb_pod_list_gpushare80",
    "openb_pod_list_gpushare100",
    "openb_pod_list_gpuspec10",
    "openb_pod_list_gpuspec20",
    "openb_pod_list_gpuspec25",
    "openb_pod_list_gpuspec33",
    "openb_pod_list_multigpu20",
    "openb_pod_list_multigpu30",
    "openb_pod_list_multigpu40",
    "openb_pod_list_multigpu50",
]

# (id, policy flags, gpusel, dimext, norm) — the reference's AllMethodList
# rows 01-07 plus the PWR/FGD weighted mixes 08/11/12 (its 09/10 ids are
# unused there too)
METHODS = [
    ("01-Random", "-Random 1000", "random", "merge", "max"),
    ("02-DotProd", "-DotProd 1000", "best", "merge", "max"),
    ("03-GpuClustering", "-GpuClustering 1000", "best", "share", "max"),
    ("04-GpuPacking", "-GpuPacking 1000", "best", "share", "max"),
    ("05-BestFit", "-BestFit 1000", "best", "share", "max"),
    ("06-FGD", "-FGD 1000", "FGDScore", "share", "max"),
    ("07-PWR", "-PWR 1000", "PWRScore", "share", "max"),
    ("08-PWR_500_FGD_500", "-PWR 500 -FGD 500", "FGDScore", "share", "max"),
    ("11-PWR_100_FGD_900", "-PWR 100 -FGD 900", "FGDScore", "share", "max"),
    ("12-PWR_50_FGD_950", "-PWR 50 -FGD 950", "FGDScore", "share", "max"),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-root", default="experiments/data")
    ap.add_argument("--tune", type=float, default=1.3)
    ap.add_argument("--seeds", type=int, default=10, help="seeds 42..42+n-1")
    ap.add_argument("--traces", nargs="*", default=None)
    ap.add_argument("--methods", nargs="*", default=None, help="method ids")
    ap.add_argument(
        "--fast", action="store_true", help="skip per-event reporting"
    )
    args = ap.parse_args()

    traces = args.traces or TRACES
    methods = [
        m for m in METHODS if args.methods is None or m[0] in args.methods
    ]
    fast = " --no-per-event-report" if args.fast else ""
    for trace in traces:
        for mid, flags, gpusel, dimext, norm in methods:
            for seed in range(42, 42 + args.seeds):
                outdir = f"{args.out_root}/{trace}/{mid}/{args.tune}/{seed}"
                print(
                    f"mkdir -p {outdir} && "
                    f"python experiments/run.py -d {outdir} -f {trace} "
                    f"{flags} -gpusel {gpusel} -dimext {dimext} -norm {norm} "
                    f"-tune {args.tune} -tuneseed {seed} --shuffle-pod true"
                    f"{fast} "
                    f"> {outdir}/terminal.out 2>&1"
                )


if __name__ == "__main__":
    main()
