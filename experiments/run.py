#!/usr/bin/env python
"""One-experiment runner (ref: scripts/generate_config_and_run.py).

Mirrors the reference harness's flag surface — trace file, score-policy
weights, tuning/inflation/deschedule knobs, typical-pod knobs, snapshot
export prefixes — but drives the TPU simulator in-process from the CSV
trace instead of generating YAML configs and shelling out to a Go binary.
With --emit-configs it additionally writes the equivalent cluster-config
and scheduler-config YAML (md5-suffixed, like the reference), so the same
experiment can be reproduced through `python -m tpusim apply`.

Writes <exp-dir>/simon.log (reference-format log lines) and then runs
experiments/analysis.py over it, producing analysis{,_frag,_allo,_cdol,
_pwr}.csv in the same directory.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from hashlib import md5
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def _enable_compile_cache():
    """Persistent XLA compilation cache for the experiment harness: the
    sweep's compiles are per-(policy × trace-shape-bucket) and amortize
    over only ~10 experiments each within one run — cached, a regeneration
    run pays zero recompiles. Override the location with
    TPUSIM_COMPILE_CACHE (empty string disables)."""
    cache_dir = os.environ.get(
        "TPUSIM_COMPILE_CACHE", str(REPO / ".jax_cache")
    )
    if not cache_dir:
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


_enable_compile_cache()

SCORE_POLICY_ABBR = {
    "Simon": "Simon",
    "RandomScore": "Random",
    "DotProductScore": "DotProd",
    "GpuClusteringScore": "GpuClustering",
    "GpuPackingScore": "GpuPacking",
    "BestFitScore": "BestFit",
    "FGDScore": "FGD",
    "PWRScore": "PWR",
}


def get_args(argv=None):
    p = argparse.ArgumentParser(description="run one simulator experiment")
    p.add_argument("-d", "--experiment-dir", type=str, default="./")
    p.add_argument(
        "-f",
        "--trace",
        type=str,
        default="data/csv/openb_pod_list_default.csv",
        help="pod-trace CSV (or a name like openb_pod_list_default)",
    )
    p.add_argument(
        "--node-trace",
        type=str,
        default="data/csv/openb_node_list_gpu_node.csv",
        help="node-trace CSV",
    )
    p.add_argument("-r", "--deschedule-ratio", type=float, default=0.0)
    p.add_argument("-p", "--deschedule-policy", type=str, default="")
    p.add_argument("-y", "--export-pod-snapshot-yaml-file-prefix", default=None)
    p.add_argument("-z", "--export-node-snapshot-csv-file-prefix", default=None)
    p.add_argument("--is-involved-cpu-pods", type=str, default="true")
    p.add_argument("--pod-popularity-threshold", type=int, default=95)
    p.add_argument("--pod-increase-step", type=int, default=1)
    p.add_argument("--gpu-res-weight", type=float, default=0)
    p.add_argument("--shuffle-pod", type=str, default="false")
    p.add_argument("--workload-inflation-ratio", type=float, default=1)
    p.add_argument("-seed", "--workload-inflation-seed", type=int, default=233)
    p.add_argument("-tune", "--workload-tuning-ratio", type=float, default=0)
    p.add_argument("-tuneseed", "--workload-tuning-seed", type=int, default=233)
    for abbr in SCORE_POLICY_ABBR.values():
        p.add_argument(f"-{abbr}", type=int, default=0, help="score weight")
    p.add_argument("-gpusel", "--gpu-sel-method", type=str, default="best")
    p.add_argument("-dimext", "--dim-ext-method", type=str, default="share")
    p.add_argument("-norm", "--norm-method", type=str, default="max")
    p.add_argument(
        "--use-timestamps",
        action="store_true",
        help="annotation-driven create+delete replay: expand each pod into "
        "creation (+deletion, when deletion_time is set) events stable-"
        "sorted by timestamp (ref: simulator.go:672-717)",
    )
    p.add_argument(
        "--no-per-event-report",
        action="store_true",
        help="skip per-event [Report]/[Alloc]/[Power] lines (faster, "
        "summary analysis only)",
    )
    p.add_argument(
        "--emit-configs",
        action="store_true",
        help="also write the equivalent cluster/scheduler YAML configs",
    )
    p.add_argument(
        "--engine", type=str, default="auto",
        help="replay engine: auto | sequential | table | pallas (ENGINES.md)",
    )
    p.add_argument(
        "--mesh", type=int, default=0,
        help="shard the node axis over an N-device mesh (shard_map "
        "engine, MULTICHIP.md); placements and merged CSVs are identical "
        "to single-device runs",
    )
    p.add_argument(
        "--analysis-from-log",
        action="store_true",
        help="build the analysis CSVs by re-parsing simon.log (the "
        "reference's log_to_csv lane) instead of directly from the "
        "driver's arrays; outputs are byte-identical either way "
        "(tests/test_experiments.py pins it)",
    )
    return p.parse_args(argv)


def resolve_trace(path_or_name: str, default_dir: Path) -> str:
    if os.path.isfile(path_or_name):
        return path_or_name
    name = os.path.basename(path_or_name).replace(".csv", "")
    cand = default_dir / f"{name}.csv"
    if cand.is_file():
        return str(cand)
    raise FileNotFoundError(f"trace not found: {path_or_name}")


def selected_policies(args):
    pol = []
    for name, abbr in SCORE_POLICY_ABBR.items():
        w = getattr(args, abbr, 0)
        if w > 0:
            pol.append((name, w))
    return pol or [("FGDScore", 1000)]


def emit_configs(args, policies, outdir: Path):
    """Write the reference-shape YAML pair with md5-suffixed names
    (generate_config_and_run.py cc_/sc_ naming)."""
    import yaml

    cc = {
        "apiVersion": "simon/v1alpha1",
        "kind": "Config",
        "metadata": {"name": "tpusim-experiment"},
        "spec": {
            "cluster": {"customConfig": str(args.trace)},
            "customConfig": {
                "shufflePod": args.shuffle_pod.lower() == "true",
                "useTimestamps": args.use_timestamps,
                "workloadInflationConfig": {
                    "ratio": args.workload_inflation_ratio,
                    "seed": args.workload_inflation_seed,
                },
                "workloadTuningConfig": {
                    "ratio": args.workload_tuning_ratio,
                    "seed": args.workload_tuning_seed,
                },
                "descheduleConfig": {
                    "ratio": args.deschedule_ratio,
                    "policy": args.deschedule_policy,
                },
                "typicalPodsConfig": {
                    "isInvolvedCpuPods": args.is_involved_cpu_pods.lower()
                    == "true",
                    "podPopularityThreshold": args.pod_popularity_threshold,
                    "podIncreaseStep": args.pod_increase_step,
                    "gpuResWeight": args.gpu_res_weight,
                },
            },
        },
    }
    sc = {
        "apiVersion": "kubescheduler.config.k8s.io/v1beta1",
        "kind": "KubeSchedulerConfiguration",
        "percentageOfNodesToScore": 100,
        "profiles": [
            {
                "schedulerName": "simon-scheduler",
                "plugins": {
                    "score": {
                        "enabled": [
                            {"name": n, "weight": w} for n, w in policies
                        ]
                    }
                },
                "pluginConfig": [
                    {
                        "name": "Open-Gpu-Share",
                        "args": {
                            "dimExtMethod": args.dim_ext_method,
                            "normMethod": args.norm_method,
                            "gpuSelMethod": args.gpu_sel_method,
                        },
                    }
                ],
            }
        ],
    }
    for prefix, doc in (("cc", cc), ("sc", sc)):
        content = yaml.dump(doc)
        suffix = md5(content.encode()).hexdigest()[:4]
        (outdir / f"{prefix}_md{suffix}.yaml").write_text(content)


_TRACE_CACHE = {}


def _load_trace_cached(path: str, loader):
    """Trace CSVs are immutable inputs shared by every experiment of a
    sweep (rows are never mutated — clones go through dataclasses.replace);
    one parse per (path, mtime) saves ~0.15 s × 2100 experiments."""
    key = (loader.__name__, path, os.path.getmtime(path))
    if key not in _TRACE_CACHE:
        _TRACE_CACHE[key] = loader(path)
    return list(_TRACE_CACHE[key])


def _build_sim(args):
    """Construct the configured Simulator + outdir/paths for one experiment
    (the setup half of run_experiment)."""
    if getattr(args, "mesh", 0) and args.mesh > 1:
        # single-chip tunnel + --mesh N: emulate the mesh on N virtual CPU
        # devices (a no-op on real multi-device platforms); must come from
        # the leaf module BEFORE anything initializes the backend
        from tpusim.virtual_mesh import force_virtual_cpu_devices

        force_virtual_cpu_devices(args.mesh)
    from tpusim.io.trace import load_node_csv, load_pod_csv
    from tpusim.sim.driver import Simulator, SimulatorConfig
    from tpusim.sim.typical import TypicalPodsConfig

    outdir = Path(args.experiment_dir)
    outdir.mkdir(parents=True, exist_ok=True)
    pod_csv = resolve_trace(args.trace, REPO / "data/csv")
    node_csv = resolve_trace(args.node_trace, REPO / "data/csv")
    policies = selected_policies(args)
    if args.emit_configs:
        emit_configs(args, policies, outdir)

    cfg = SimulatorConfig(
        policies=tuple(policies),
        gpu_sel_method=args.gpu_sel_method,
        dim_ext_method=args.dim_ext_method,
        norm_method=args.norm_method,
        shuffle_pod=args.shuffle_pod.lower() == "true",
        tuning_ratio=args.workload_tuning_ratio,
        tuning_seed=args.workload_tuning_seed,
        inflation_ratio=args.workload_inflation_ratio,
        inflation_seed=args.workload_inflation_seed,
        deschedule_ratio=args.deschedule_ratio,
        deschedule_policy=args.deschedule_policy,
        seed=args.workload_tuning_seed,
        report_per_event=not args.no_per_event_report,
        use_timestamps=args.use_timestamps,
        engine=args.engine,
        mesh=args.mesh,
        typical_pods=TypicalPodsConfig(
            is_involved_cpu_pods=args.is_involved_cpu_pods.lower() == "true",
            pod_popularity_threshold=args.pod_popularity_threshold,
            pod_increase_step=args.pod_increase_step,
            gpu_res_weight=args.gpu_res_weight,
        ),
    )
    sim = Simulator(_load_trace_cached(node_csv, load_node_csv), cfg)
    sim.set_workload_pods(_load_trace_cached(pod_csv, load_pod_csv))
    return sim, outdir, pod_csv, policies


def _post_run(sim, args, outdir, pod_csv, policies, t0) -> dict:
    """Everything after the main schedule: inflation/deschedule stages,
    exports, log write, analysis CSVs (the tail half of run_experiment)."""
    if args.workload_inflation_ratio > 1:
        sim.run_workload_inflation_evaluation("ScheduleInflation")
    if args.deschedule_ratio > 0 and args.deschedule_policy:
        sim.deschedule_cluster()
        sim.cluster_analysis("PostDeschedule")
        if args.workload_inflation_ratio > 1:
            sim.run_workload_inflation_evaluation("DescheduleInflation")
    if args.export_pod_snapshot_yaml_file_prefix:
        path = f"{args.export_pod_snapshot_yaml_file_prefix}.yaml"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        sim.export_pod_snapshot_yaml(path)
    if args.export_node_snapshot_csv_file_prefix:
        path = f"{args.export_node_snapshot_csv_file_prefix}.csv"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        sim.export_node_snapshot_csv(path)
    sim.finish()
    wall = time.perf_counter() - t0

    log_path = outdir / "simon.log"
    with open(log_path, "w") as f:
        f.write(sim.log.dump())
    print(f"[run] {log_path} ({wall:.1f}s, {sim.last_result.events} events)")

    sys.path.insert(0, str(Path(__file__).parent))
    from analysis import analyze_dir, analyze_sim

    meta = {
        "workload": Path(pod_csv).stem,
        "policy": "_".join(f"{SCORE_POLICY_ABBR[n]}{w}" for n, w in policies),
        "tune": args.workload_tuning_ratio,
        "tune_seed": args.workload_tuning_seed,
        "de": args.dim_ext_method,
        "gs": args.gpu_sel_method,
        "dr": args.deschedule_ratio,
        "dp": args.deschedule_policy,
    }
    if args.analysis_from_log:
        return analyze_dir(str(outdir), meta)
    return analyze_sim(sim, str(outdir), meta)


def run_experiment(args) -> dict:
    sim, outdir, pod_csv, policies = _build_sim(args)
    t0 = time.perf_counter()
    sim.run()
    return _post_run(sim, args, outdir, pod_csv, policies, t0)


def dispatch_experiment_batch(args_list) -> dict:
    """Host prep + async device dispatch of a seed group (same trace/
    policy/knobs, different seeds → ONE vmapped replay). The device work
    runs while the caller processes other groups' host tails — the sweep
    pipelines finish_experiment_batch(group i) under group i+1's replay."""
    from tpusim.sim.driver import dispatch_run_batch

    t0 = time.perf_counter()
    built = [_build_sim(a) for a in args_list]
    handle = dispatch_run_batch([b[0] for b in built])
    return {
        "args_list": args_list,
        "built": built,
        "handle": handle,
        # dispatch-phase host wall: the pipelined sweep interleaves other
        # groups' work before finish, so per-experiment wall attribution
        # sums the two phases instead of spanning them
        "prep_s": time.perf_counter() - t0,
    }


def finish_experiment_batch(st: dict) -> list:
    """Block on a dispatch_experiment_batch handle and write every
    per-experiment output (simon.log + analysis CSVs)."""
    from tpusim.sim.driver import finish_run_batch

    t_fin = time.perf_counter()
    finish_run_batch(st["handle"])
    batch_s = st["prep_s"] + (time.perf_counter() - t_fin)
    shared = batch_s / len(st["built"])
    results = []
    for args, (sim, outdir, pod_csv, policies) in zip(
        st["args_list"], st["built"]
    ):
        # report each experiment's fair share of the batched phase plus its
        # own post-run stages, not the whole batch's elapsed time
        results.append(
            _post_run(
                sim, args, outdir, pod_csv, policies,
                time.perf_counter() - shared,
            )
        )
    return results


def run_experiment_batch(args_list) -> list:
    """Run a seed group through ONE vmapped device replay. Produces
    per-experiment outputs identical to run_experiment — the batch only
    changes how the main schedules execute on the chip."""
    return finish_experiment_batch(dispatch_experiment_batch(args_list))


if __name__ == "__main__":
    run_experiment(get_args())
