#!/usr/bin/env python
"""Compare merged sweep results against the reference's cached
expected-results matrix (the E2E validation step of the reference's
artifact workflow, experiments/README.md step 3).

    python experiments/compare.py --merged <dir-with-analysis_*_discrete.csv>
    python experiments/compare.py --merged /tmp/cmp10/merged --metric frag_ratio --at 90

Prints one table per requested metric: mean per (workload, policy) for both
sides plus the delta. Reference CSVs default to the read-only tree at
/root/reference; point --expected elsewhere if the artifact lives elsewhere.
"""

from __future__ import annotations

import argparse
import csv
from pathlib import Path

METRIC_FILES = {
    "alloc": "analysis_allo_discrete.csv",
    "frag": "analysis_frag_discrete.csv",
    "frag_ratio": "analysis_frag_ratio_discrete.csv",
}


def load(path: Path):
    with open(path, newline="") as f:
        return list(csv.DictReader(f))


def mean(rows, wl, pol, col, tune=None):
    vals = [
        float(r[col])
        for r in rows
        if r["workload"] == wl
        and r["sc_policy"] == pol
        and (tune is None or r.get("tune") == tune)
        and r.get(col)
    ]
    return sum(vals) / len(vals) if vals else None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--merged", required=True, help="dir with analysis_*_discrete.csv")
    ap.add_argument(
        "--expected",
        default="/root/reference/experiments/analysis/expected_results",
        help="reference expected-results dir",
    )
    ap.add_argument("--metric", choices=sorted(METRIC_FILES), default="alloc")
    ap.add_argument("--at", default="130", help="arrived-load percent column")
    ap.add_argument(
        "--tune", default=None,
        help="restrict to one tuning ratio (required if the merged dir "
        "holds several)",
    )
    args = ap.parse_args()

    fname = METRIC_FILES[args.metric]
    merged_path = Path(args.merged) / fname
    if not merged_path.exists():
        ap.error(f"no {fname} under {args.merged} (run experiments/merge.py first)")
    ours = load(merged_path)
    tunes = sorted({r.get("tune", "") for r in ours})
    tune = args.tune
    if tune is None:
        if len(tunes) > 1:
            ap.error(
                f"merged dir mixes tuning ratios {tunes}; pass --tune to "
                "pick one (averaging across tunes is meaningless)"
            )
        tune = tunes[0] if tunes else None
    ref_path = Path(args.expected) / fname
    ref = load(ref_path) if ref_path.exists() else []

    workloads = sorted({r["workload"] for r in ours})
    policies = sorted({r["sc_policy"] for r in ours})
    print(f"== {args.metric} @ {args.at}% arrived load (ref | ours | delta) ==")
    width = 27
    print(
        f"{'workload':28s}"
        + "".join(f"{p.split('-', 1)[-1]:>{width}s}" for p in policies)
    )
    worst, compared = 0.0, 0
    for wl in workloads:
        cells = []
        for pol in policies:
            r = mean(ref, wl, pol, args.at, tune)
            o = mean(ours, wl, pol, args.at, tune)
            if o is None:
                cells.append(f"{'-':>{width}s}")
            elif r is None:
                cells.append(f"{'- |':>12s}{o:8.2f}{'':7s}")
            else:
                d = o - r
                worst = max(worst, abs(d))
                compared += 1
                cells.append(f"{r:9.2f} |{o:8.2f} ({d:+5.2f})")
        print(f"{wl:28s}" + "".join(cells))
    if compared:
        print(
            f"\nmax |delta| over {compared} cells with reference data: "
            f"{worst:.2f}"
        )
    else:
        print("\n(no overlapping reference cells)")


if __name__ == "__main__":
    main()
