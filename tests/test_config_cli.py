"""Config ingestion + applier/CLI layer tests (ref surfaces: pkg/apply,
pkg/api/v1alpha1, pkg/algo, cmd/)."""

import io
import os

import numpy as np
import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- k8s quantity / manifest parsing ----


def test_parse_quantities():
    from tpusim.io.k8s_yaml import parse_cpu_milli, parse_mem_mib

    assert parse_cpu_milli("4") == 4000
    assert parse_cpu_milli("250m") == 250
    assert parse_cpu_milli(64) == 64000
    assert parse_mem_mib("256000Mi") == 256000
    assert parse_mem_mib("2Gi") == 2048
    assert parse_mem_mib("1048576Ki") == 1024
    assert parse_mem_mib(str(512 * 1024 * 1024)) == 512


def test_node_pod_from_k8s():
    from tpusim.io.k8s_yaml import load_cluster_from_dir

    res = load_cluster_from_dir(os.path.join(REPO, "example/test-cluster"))
    assert [n.name for n in res.nodes] == ["gpu-node-a", "gpu-node-b"]
    a = res.nodes[0]
    assert (a.cpu_milli, a.memory_mib, a.gpu, a.model) == (
        48000,
        196608,
        4,
        "V100M16",
    )
    pods = {p.name: p for p in res.pods}
    t1 = pods["demo/train-pod-1"]
    assert (t1.cpu_milli, t1.num_gpu, t1.gpu_milli, t1.gpu_spec) == (
        16000,
        2,
        1000,
        "A100",
    )
    cpu = pods["demo/cpu-pod-0"]
    assert (cpu.num_gpu, cpu.gpu_milli) == (0, 0)


def test_workload_expansion():
    from tpusim.io.k8s_yaml import load_cluster_from_objects

    deploy = {
        "kind": "Deployment",
        "metadata": {"name": "web", "namespace": "d"},
        "spec": {
            "replicas": 3,
            "template": {
                "spec": {
                    "containers": [
                        {"resources": {"requests": {"cpu": "1", "memory": "1Gi"}}}
                    ]
                }
            },
        },
    }
    job = {
        "kind": "Job",
        "metadata": {"name": "batch"},
        "spec": {
            "completions": 2,
            "template": {
                "metadata": {
                    "annotations": {
                        "alibabacloud.com/gpu-count": "1",
                        "alibabacloud.com/gpu-milli": "300",
                    }
                },
                "spec": {
                    "containers": [{"resources": {"requests": {"cpu": "500m"}}}]
                },
            },
        },
    }
    node = {
        "kind": "Node",
        "metadata": {"name": "n0"},
        "status": {"allocatable": {"cpu": "8", "memory": "16Gi"}},
    }
    ds = {
        "kind": "DaemonSet",
        "metadata": {"name": "agent"},
        "spec": {
            "template": {
                "spec": {
                    "containers": [{"resources": {"requests": {"cpu": "100m"}}}]
                }
            }
        },
    }
    res = load_cluster_from_objects([deploy, job, node, ds])
    names = sorted(p.name for p in res.workload_pods())
    assert names == ["batch-0", "batch-1", "d/web-0", "d/web-1", "d/web-2"]
    assert all(p.cpu_milli == 1000 for p in res.pods if "web" in p.name)
    jobs = [p for p in res.pods if p.workload_kind == "Job"]
    assert all((p.num_gpu, p.gpu_milli) == (1, 300) for p in jobs)
    ds_pods = res.daemonset_pods()
    assert len(ds_pods) == 1 and ds_pods[0].pinned_node == "n0"
    assert ds_pods[0].workload_kind == "DaemonSet"


# ---- Simon CR + scheduler config ----


def test_simon_cr_parse_and_validate(tmp_path):
    from tpusim.config import load_simon_cr
    from tpusim.config.simon import ConfigError

    cr = load_simon_cr(
        os.path.join(REPO, "example/test-cluster-config.yaml"), REPO
    )
    assert cr.custom_cluster == os.path.join(REPO, "example/test-cluster")
    assert cr.custom_config.typical_pods.pod_popularity_threshold == 95
    assert cr.custom_config.tuning.ratio == 0.0

    assert cr.custom_config.engine == "auto"  # default

    # the engine knob flows customConfig.engine -> SimulatorConfig.engine
    doc = {
        "apiVersion": "simon/v1alpha1",
        "kind": "Config",
        "spec": {
            "cluster": {"customConfig": "example/test-cluster"},
            "customConfig": {"engine": "table"},
        },
    }
    p = tmp_path / "engine.yaml"
    p.write_text(yaml.dump(doc))
    cr2 = load_simon_cr(str(p), REPO)
    assert cr2.custom_config.engine == "table"

    bad = {
        "apiVersion": "simon/v1alpha1",
        "kind": "Config",
        "spec": {"cluster": {}},
    }
    p = tmp_path / "bad.yaml"
    p.write_text(yaml.dump(bad))
    with pytest.raises(ConfigError):
        load_simon_cr(str(p))


def test_scheduler_config_parse():
    from tpusim.config import load_scheduler_config

    cfg = load_scheduler_config(
        os.path.join(REPO, "example/test-scheduler-config.yaml")
    )
    assert cfg.policies == [("FGDScore", 1000)]
    assert cfg.gpu_sel_method == "FGDScore"
    assert cfg.dim_ext_method == "share"
    assert cfg.percentage_of_nodes_to_score == 100
    default = load_scheduler_config("")
    assert ("FGDScore", 1) in default.policies


def test_scheduler_config_enabled_wins_over_disabled(tmp_path):
    """The reference's example configs use disable-everything boilerplate
    and then re-enable the chosen policy; k8s merge semantics make the
    enabled entry win (disabled strips DEFAULT plugins only), so the parse
    must yield exactly the enabled policy — not fall back to the default
    profile (ref: example/original/test-scheduler-config.yaml)."""
    from tpusim.config.scheduler import load_scheduler_config

    doc = {
        "apiVersion": "kubescheduler.config.k8s.io/v1beta1",
        "kind": "KubeSchedulerConfiguration",
        "percentageOfNodesToScore": 100,
        "profiles": [
            {
                "schedulerName": "simon-scheduler",
                "plugins": {
                    "score": {
                        "disabled": [
                            {"name": n}
                            for n in (
                                "RandomScore", "DotProductScore",
                                "GpuClusteringScore", "GpuPackingScore",
                                "BestFitScore", "FGDScore", "ImageLocality",
                                "NodeAffinity", "TaintToleration",
                            )
                        ],
                        "enabled": [{"name": "FGDScore", "weight": 1000}],
                    }
                },
            }
        ],
    }
    p = tmp_path / "sc.yaml"
    p.write_text(yaml.dump(doc))
    cfg = load_scheduler_config(str(p))
    assert cfg.policies == [("FGDScore", 1000)]


def test_scheduler_config_rejects_unknown(tmp_path):
    from tpusim.config.scheduler import SchedulerConfigError, load_scheduler_config

    doc = {
        "apiVersion": "kubescheduler.config.k8s.io/v1beta1",
        "kind": "KubeSchedulerConfiguration",
        "profiles": [
            {"plugins": {"score": {"enabled": [{"name": "NotAPlugin"}]}}}
        ],
    }
    p = tmp_path / "sc.yaml"
    p.write_text(yaml.dump(doc))
    with pytest.raises(SchedulerConfigError):
        load_scheduler_config(str(p))


def test_scheduler_config_rejects_extenders_and_pct(tmp_path):
    """Partial node scoring fails loudly (the reference forces
    percentageOfNodesToScore=100, utils.go:234); extenders parse into the
    host-loop protocol since round 5 (tests/test_extender.py covers the
    live contract)."""
    from tpusim.config.scheduler import SchedulerConfigError, load_scheduler_config

    base = {
        "apiVersion": "kubescheduler.config.k8s.io/v1beta1",
        "kind": "KubeSchedulerConfiguration",
    }
    p = tmp_path / "sc.yaml"

    p.write_text(yaml.dump({**base, "percentageOfNodesToScore": 50}))
    with pytest.raises(SchedulerConfigError, match="percentageOfNodesToScore"):
        load_scheduler_config(str(p))
    p.write_text(yaml.dump({**base, "percentageOfNodesToScore": 100}))
    load_scheduler_config(str(p))  # explicit 100 is fine

    # non-numeric YAML must surface as the typed error, not bare ValueError
    p.write_text(yaml.dump({**base, "percentageOfNodesToScore": "most"}))
    with pytest.raises(SchedulerConfigError, match="not an integer"):
        load_scheduler_config(str(p))

    p.write_text(
        yaml.dump({**base, "extenders": [{"urlPrefix": "http://x/"}]})
    )
    cfg = load_scheduler_config(str(p))  # round 5: extenders parse
    assert cfg.extenders[0].url_prefix == "http://x/"

    # k8s validation parity: an explicit weight: 0 with prioritizeVerb set
    # must be rejected, not silently coerced to 1
    p.write_text(yaml.dump({**base, "extenders": [
        {"urlPrefix": "http://x/", "prioritizeVerb": "prioritize",
         "weight": 0}
    ]}))
    with pytest.raises(SchedulerConfigError, match="weight"):
        load_scheduler_config(str(p))
    # weight 0 without a prioritize verb keeps the lenient default
    p.write_text(yaml.dump({**base, "extenders": [
        {"urlPrefix": "http://x/", "filterVerb": "filter", "weight": 0}
    ]}))
    assert load_scheduler_config(str(p)).extenders[0].weight == 1


# ---- queue sorts (pkg/algo) ----


def test_queue_sorts():
    from tpusim.io.trace import NodeRow, PodRow
    from tpusim.sim.queues import app_queue, greed_sort

    nodes = [NodeRow("n0", 10000, 10000, 0)]
    small = PodRow("small", 1000, 100, 0, 0)
    big = PodRow("big", 8000, 100, 0, 0)
    pinned = PodRow("pinned", 500, 100, 0, 0, pinned_node="n0")
    sel = PodRow("sel", 500, 100, 0, 0, node_selector={"disk": "ssd"})
    tol = PodRow("tol", 500, 100, 0, 0, tolerations=True)

    out = greed_sort([small, big, pinned], nodes)
    assert [p.name for p in out] == ["pinned", "big", "small"]

    out = app_queue([small, sel, tol], nodes)
    # toleration partition is the outermost sort; affinity breaks ties
    assert out[0].name == "tol"
    assert [p.name for p in out[1:]] == ["sel", "small"]


# ---- helm chart rendering ----


def test_chart_render(tmp_path):
    from tpusim.io.chart import ChartError, chart_objects

    chart = tmp_path / "mychart"
    (chart / "templates").mkdir(parents=True)
    (chart / "Chart.yaml").write_text("name: mychart\nversion: 1.0.0\n")
    (chart / "values.yaml").write_text("replicas: 2\ncpu: 500m\n")
    (chart / "templates" / "deploy.yaml").write_text(
        """kind: Deployment
metadata:
  name: {{ .Release.Name }}-web
spec:
  replicas: {{ .Values.replicas }}
  template:
    spec:
      containers:
      - resources:
          requests:
            cpu: {{ .Values.cpu | quote }}
"""
    )
    objs = chart_objects("demo", str(chart))
    assert objs[0]["metadata"]["name"] == "demo-web"
    assert objs[0]["spec"]["replicas"] == 2

    # genuinely unsupported directives still fail loudly with the file name
    (chart / "templates" / "loop.yaml").write_text(
        '{{ lookup "v1" "Pod" "ns" "x" }}\n'
    )
    with pytest.raises(ChartError, match="loop.yaml"):
        chart_objects("demo", str(chart))


def test_chart_render_full_engine(tmp_path):
    """helm-create-style chart: helpers, include, if/with/range, variables,
    nindent/toYaml pipelines — rendered to the same docs `helm template`
    produces (ref engine: pkg/chart/chart.go:40-140)."""
    from tpusim.io.chart import chart_objects, render_chart

    chart = tmp_path / "web"
    (chart / "templates").mkdir(parents=True)
    (chart / "Chart.yaml").write_text(
        "name: web\nversion: 0.1.0\nappVersion: '2.4'\n"
    )
    (chart / "values.yaml").write_text(
        """nameOverride: ""
replicaCount: 3
autoscaling:
  enabled: false
image:
  repository: nginx
  tag: ""
resources:
  requests:
    cpu: 250m
    memory: 64Mi
nodeSelector:
  disktype: ssd
service:
  enabled: true
  ports: [80, 443]
"""
    )
    (chart / "templates" / "_helpers.tpl").write_text(
        """{{/* boilerplate comment */}}
{{- define "web.name" -}}
{{- default .Chart.Name .Values.nameOverride | trunc 63 | trimSuffix "-" -}}
{{- end -}}
{{- define "web.fullname" -}}
{{- printf "%s-%s" .Release.Name (include "web.name" .) | trunc 63 | trimSuffix "-" -}}
{{- end -}}
{{- define "web.labels" -}}
app: {{ include "web.name" . }}
release: {{ .Release.Name }}
{{- end -}}
"""
    )
    (chart / "templates" / "deployment.yaml").write_text(
        """apiVersion: apps/v1
kind: Deployment
metadata:
  name: {{ include "web.fullname" . }}
  labels:
    {{- include "web.labels" . | nindent 4 }}
spec:
  {{- if not .Values.autoscaling.enabled }}
  replicas: {{ .Values.replicaCount }}
  {{- end }}
  template:
    spec:
      containers:
        - name: {{ .Chart.Name }}
          image: "{{ .Values.image.repository }}:{{ .Values.image.tag | default .Chart.AppVersion }}"
          resources:
            {{- toYaml .Values.resources | nindent 12 }}
      {{- with .Values.nodeSelector }}
      nodeSelector:
        {{- toYaml . | nindent 8 }}
      {{- end }}
"""
    )
    (chart / "templates" / "service.yaml").write_text(
        """{{- if .Values.service.enabled }}
apiVersion: v1
kind: Service
metadata:
  name: {{ include "web.fullname" . }}
spec:
  ports:
    {{- range $i, $port := .Values.service.ports }}
    - name: {{ printf "port-%d" $i | quote }}
      port: {{ $port }}
    {{- end }}
{{- end }}
"""
    )
    (chart / "templates" / "NOTES.txt").write_text(
        "Visit {{ include \"web.fullname\" . }}!\n"
    )

    objs = {o["kind"]: o for o in chart_objects("rel", str(chart))}
    dep = objs["Deployment"]
    assert dep["metadata"]["name"] == "rel-web"
    assert dep["metadata"]["labels"] == {"app": "web", "release": "rel"}
    assert dep["spec"]["replicas"] == 3
    c = dep["spec"]["template"]["spec"]["containers"][0]
    assert c["image"] == "nginx:2.4"
    assert c["resources"] == {"requests": {"cpu": "250m", "memory": "64Mi"}}
    assert dep["spec"]["template"]["spec"]["nodeSelector"] == {
        "disktype": "ssd"
    }
    svc = objs["Service"]
    assert svc["spec"]["ports"] == [
        {"name": "port-0", "port": 80},
        {"name": "port-1", "port": 443},
    ]
    # NOTES.txt excluded from manifests (chart.go:116-130)
    assert len(render_chart("rel", str(chart))) == 2

    # flipping the if guard drops the service manifest entirely
    (chart / "values.yaml").write_text(
        (chart / "values.yaml").read_text().replace(
            "service:\n  enabled: true", "service:\n  enabled: false"
        )
    )
    assert "Service" not in {
        o["kind"] for o in chart_objects("rel", str(chart))
    }


def test_chart_tpl_and_semver(tmp_path):
    """`tpl` re-parses its string argument against the given dot, and
    `semverCompare` evaluates single constraints (raising on range syntax
    outside the subset) instead of silently passing through."""
    import pytest

    from tpusim.io.chart import ChartError, chart_objects

    chart = tmp_path / "t"
    (chart / "templates").mkdir(parents=True)
    (chart / "Chart.yaml").write_text("name: t\nversion: 1.0.0\n")
    (chart / "values.yaml").write_text(
        'greeting: "hi {{ .Release.Name }}"\n'
    )
    (chart / "templates" / "cm.yaml").write_text(
        """kind: ConfigMap
metadata:
  name: cm
data:
  msg: {{ tpl .Values.greeting . | quote }}
  new: {{ if semverCompare ">=1.19" .Capabilities.KubeVersion.Version }}"yes"{{ else }}"no"{{ end }}
"""
    )
    (cm,) = chart_objects("rel", str(chart))
    assert cm["data"]["msg"] == "hi rel"
    assert cm["data"]["new"] == "yes"

    (chart / "templates" / "cm.yaml").write_text(
        """kind: ConfigMap
metadata:
  name: cm
data:
  bad: {{ semverCompare "^1.19.x" "1.20" }}
"""
    )
    with pytest.raises(ChartError, match="semverCompare"):
        chart_objects("rel", str(chart))


# ---- applier end-to-end on the example cluster ----


def test_applier_end_to_end():
    from tpusim.apply import Applier, ApplyOptions

    out = io.StringIO()
    applier = Applier(
        ApplyOptions(
            simon_config=os.path.join(REPO, "example/test-cluster-config.yaml"),
            default_scheduler_config=os.path.join(
                REPO, "example/test-scheduler-config.yaml"
            ),
            base_dir=REPO,
            report_tables=True,
        )
    )
    result = applier.run(out=out)
    text = out.getvalue()
    assert not result.unscheduled_pods, text
    assert "Success!" in text
    assert "Pod Info" in text and "Node Info" in text
    # the 2-GPU A100-constrained pod must land on the A100 node
    pods = {p.name: i for i, p in enumerate(result.pods)}
    i = pods["demo/train-pod-1"]
    assert result.node_names[result.placed_node[i]] == "gpu-node-b"
    assert result.dev_mask[i].sum() == 2


@pytest.mark.parametrize("bundle", ["new1", "new2"])
def test_applier_sample_bundles(bundle):
    """The new1/new2 sample bundles (mirroring /root/reference/example/
    {new1,new2}: a PWR heterogeneous-cluster quick start and a typed-GPU-
    request FGD one) run end-to-end with every pod placed."""
    from tpusim.apply import Applier, ApplyOptions

    out = io.StringIO()
    applier = Applier(
        ApplyOptions(
            simon_config=os.path.join(
                REPO, f"example/{bundle}/test-cluster-config.yaml"
            ),
            default_scheduler_config=os.path.join(
                REPO, f"example/{bundle}/test-scheduler-config.yaml"
            ),
            base_dir=REPO,
        )
    )
    result = applier.run(out=out)
    assert not result.unscheduled_pods, out.getvalue()
    assert "Success!" in out.getvalue()
    if bundle == "new2":
        # the typed requests must land on matching GPU models
        pods = {p.name: i for i, p in enumerate(result.pods)}
        names = result.node_names
        assert names[result.placed_node[pods["pai-gpu/gpu-pod-00"]]] == "pai-node-00"
        assert names[result.placed_node[pods["pai-gpu/gpu-pod-01"]]] == "pai-node-02"


def test_cli_version_and_gen_doc(tmp_path, capsys):
    from tpusim.cli import main

    assert main(["version"]) == 0
    assert "tpusim version" in capsys.readouterr().out
    assert main(["gen-doc", "-d", str(tmp_path)]) == 0
    assert (tmp_path / "tpusim.md").exists()
    assert main(["debug"]) == 0


# ---- real-cluster snapshot (kubeConfig dump) ingestion ----


def _dump_doc():
    """A `kubectl get nodes,pods,deployments -A -o yaml` style List dump."""
    node = lambda name, gpus, model: {
        "kind": "Node",
        "apiVersion": "v1",
        "metadata": {
            "name": name,
            "labels": (
                {"alibabacloud.com/gpu-card-model": model} if model else {}
            ),
        },
        "status": {
            "allocatable": {
                "cpu": "64",
                "memory": "256Gi",
                "alibabacloud.com/gpu-count": str(gpus),
            }
        },
    }
    return {
        "kind": "List",
        "apiVersion": "v1",
        "items": [
            node("real-a", 0, ""),
            node("real-b", 4, "V100M16"),
            {  # API-sourced pod: dropped, its Deployment re-expands it
                "kind": "Pod",
                "apiVersion": "v1",
                "metadata": {"name": "web-abc12", "namespace": "prod"},
                "spec": {
                    "nodeName": "real-a",
                    "containers": [
                        {"resources": {"requests": {"cpu": "2"}}}
                    ],
                },
            },
            {  # static pod: survives ingestion (IsStaticPod semantics)
                "kind": "Pod",
                "apiVersion": "v1",
                "metadata": {
                    "name": "kube-proxy-real-a",
                    "namespace": "kube-system",
                    "annotations": {"kubernetes.io/config.source": "file"},
                },
                "spec": {
                    "nodeName": "real-a",
                    "containers": [
                        {"resources": {"requests": {"cpu": "250m"}}}
                    ],
                },
            },
            {
                "kind": "Deployment",
                "apiVersion": "apps/v1",
                "metadata": {"name": "web", "namespace": "prod"},
                "spec": {
                    "replicas": 2,
                    "template": {
                        "spec": {
                            "containers": [
                                {"resources": {"requests": {"cpu": "2"}}}
                            ]
                        }
                    },
                },
            },
        ],
    }


def test_cluster_dump_ingestion(tmp_path):
    from tpusim.io.k8s_yaml import load_cluster_from_dump

    dump = tmp_path / "dump.yaml"
    dump.write_text(yaml.dump(_dump_doc()))
    res = load_cluster_from_dump(str(dump))
    assert res.node_names == ["real-a", "real-b"]
    names = [p.name for p in res.pods]
    # API-sourced pod dropped; static pod kept; deployment re-expanded
    assert "prod/web-abc12" not in names
    assert "kube-system/kube-proxy-real-a" in names
    assert "prod/web-0" in names and "prod/web-1" in names


def test_cluster_dump_rejects_kubeconfig(tmp_path):
    from tpusim.io.k8s_yaml import load_cluster_from_dump

    kc = tmp_path / "kubeconfig"
    kc.write_text(
        yaml.dump(
            {
                "apiVersion": "v1",
                "kind": "Config",
                "clusters": [{"name": "c", "cluster": {"server": "https://x"}}],
                "users": [],
                "contexts": [],
            }
        )
    )
    with pytest.raises(ValueError, match="kubeconfig credential"):
        load_cluster_from_dump(str(kc))


def test_applier_kube_config_dump_end_to_end(tmp_path):
    """spec.cluster.kubeConfig pointing at a dump simulates the snapshot
    (capability parity with CreateClusterResourceFromClient)."""
    from tpusim.apply import Applier, ApplyOptions

    dump = tmp_path / "dump.yaml"
    dump.write_text(yaml.dump(_dump_doc()))
    cr = tmp_path / "cc.yaml"
    cr.write_text(
        yaml.dump(
            {
                "apiVersion": "simon/v1alpha1",
                "kind": "Config",
                "metadata": {"name": "dump-sim"},
                "spec": {"cluster": {"kubeConfig": str(dump)}},
            }
        )
    )
    out = io.StringIO()
    applier = Applier(ApplyOptions(simon_config=str(cr)))
    result = applier.run(out=out)
    assert not result.unscheduled_pods, out.getvalue()
    assert "Success!" in out.getvalue()
    names = {p.name: i for i, p in enumerate(result.pods)}
    # static pod pinned to its node
    i = names["kube-system/kube-proxy-real-a"]
    assert result.node_names[result.placed_node[i]] == "real-a"


def test_reference_example_config_compat(tmp_path):
    """The reference's shipped example configs use tab-indented YAML
    comments (Go's yaml lib accepts them) and a GPU model ("V100") outside
    the trace's 14-model table; both must work drop-in (ref:
    example/original/*). Unknown models register dynamically with zeroed
    energy/memory tables."""
    from tpusim.apply import Applier, ApplyOptions
    from tpusim.constants import GPU_MODEL_IDS

    base = tmp_path / "example" / "original"
    cluster = base / "test-cluster"
    (cluster / "node").mkdir(parents=True)
    (cluster / "pod").mkdir(parents=True)
    (base / "cc.yaml").write_text(
        "apiVersion: simon/v1alpha1 \t# tab-indented comment\n"
        "kind: Config\n"
        "metadata:\n  name: tab-config\n"
        "spec:\t\t\t# more tabs\n"
        "  cluster:\n"
        f"    customConfig: {cluster}\n"
    )
    (cluster / "node" / "n0.yaml").write_text(
        yaml.dump(
            {
                "apiVersion": "v1",
                "kind": "Node",
                "metadata": {
                    "name": "pai-n0",
                    "labels": {"alibabacloud.com/gpu-card-model": "V100"},
                },
                "status": {
                    "allocatable": {
                        "cpu": "64",
                        "memory": "256Gi",
                        "alibabacloud.com/gpu-count": "8",
                    }
                },
            }
        )
    )
    (cluster / "pod" / "p0.yaml").write_text(
        yaml.dump(
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": "gpu-pod-00",
                    "annotations": {
                        "alibabacloud.com/gpu-count": "1",
                        "alibabacloud.com/gpu-milli": "500",
                        "alibabacloud.com/gpu-card-model": "V100",
                    },
                },
                "spec": {
                    "containers": [
                        {"resources": {"requests": {"cpu": "4"}}}
                    ]
                },
            }
        )
    )
    out = io.StringIO()
    result = Applier(ApplyOptions(simon_config=str(base / "cc.yaml"))).run(out=out)
    assert not result.unscheduled_pods, out.getvalue()
    assert "V100" in GPU_MODEL_IDS  # dynamically registered
    assert result.placed_node[0] == 0
