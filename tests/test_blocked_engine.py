"""Blocked table engine (tpusim.sim.table_engine, block_size > 0) must be
bit-identical to the flat table engine — and transitively to the sequential
oracle, whose equality tests/test_table_engine.py pins — for every policy,
normalizer, and per-event-random config, across block sizes. The blocked
path only changes the select-phase data layout (block aggregates + two-level
packed_argmax), never the kernels, so placements, device masks, telemetry,
and final state must match exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.fixtures import random_cluster, random_pods
from tpusim.policies import make_policy
from tpusim.sim.engine import EV_CREATE, EV_DELETE
from tpusim.sim.table_engine import (
    BLOCKED_MIN_NODES,
    build_pod_types,
    make_table_replay,
    resolve_block_size,
)

NUM_NODES = 140


def _events_with_deletes(num_pods, rng):
    kinds, idxs = [], []
    seen = set()
    for i in range(num_pods):
        kinds.append(EV_CREATE)
        idxs.append(i)
        if rng.random() < 0.34 and i > 0:
            victim = int(rng.integers(0, i + 1))
            if victim not in seen:
                seen.add(victim)
                kinds.append(EV_DELETE)
                idxs.append(victim)
    return jnp.asarray(kinds, jnp.int32), jnp.asarray(idxs, jnp.int32)


def _assert_equal(r0, r1):
    """Full equality contract: placements, device masks, failure flags,
    telemetry (event_node/event_dev — what the metric post-pass consumes),
    and final cluster state."""
    assert np.array_equal(np.asarray(r0.placed_node), np.asarray(r1.placed_node))
    assert np.array_equal(np.asarray(r0.dev_mask), np.asarray(r1.dev_mask))
    assert np.array_equal(np.asarray(r0.ever_failed), np.asarray(r1.ever_failed))
    assert np.array_equal(np.asarray(r0.event_node), np.asarray(r1.event_node))
    assert np.array_equal(np.asarray(r0.event_dev), np.asarray(r1.event_dev))
    for a, b in zip(jax.tree.leaves(r0.state), jax.tree.leaves(r1.state)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize(
    "policies,gpu_sel,blocks",
    [
        # normalize: none — {8, 128, N} dedup'd to the boundary sizes
        # (tier-1 trim, ISSUE 14: each block size is its own compile;
        # 128 is exercised by the BestFit minmax row below and the
        # openb-prefix acceptance in resume-smoke)
        ([("FGDScore", 1000)], "FGDScore", (8, NUM_NODES)),
        # tier-1 trim, ISSUE 16: the single-policy variants below pin the
        # same blocked==flat contract through per-policy kernels that the
        # FGD row and the weighted mix already exercise structurally —
        # they ride resume-smoke instead
        pytest.param([("BestFitScore", 1000)], "best", (128,),
                     marks=pytest.mark.slow),  # minmax
        pytest.param([("PWRScore", 1000)], "PWRScore", (8,),
                     marks=pytest.mark.slow),  # pwr
        # weighted mix with per-policy normalization (the reference's
        # PWR+FGD rows): totals combine a stored-extrema normalized plane
        # with a raw plane
        ([("PWRScore", 500), ("FGDScore", 500)], "FGDScore", (8,)),
        # per-event randomness: the blocked maker must keep the oracle's
        # key-split discipline bit-for-bit (it runs the flat body for
        # RandomScore configs; gpu_sel=random stays blocked with the same
        # k_sel draw)
        pytest.param([("RandomScore", 1000)], "random", (8,),
                     marks=pytest.mark.slow),
        pytest.param([("FGDScore", 1000)], "random", (8,),
                     marks=pytest.mark.slow),
    ],
    ids=lambda p: "+".join(n for n, _ in p) if isinstance(p, list) else str(p),
)
def test_blocked_matches_flat(policies, gpu_sel, blocks):
    rng = np.random.default_rng(7)
    state, tp = random_cluster(rng, num_nodes=NUM_NODES)
    pods = random_pods(rng, num_pods=60)
    ev_kind, ev_pod = _events_with_deletes(60, rng)
    pol = [(make_policy(name), w) for name, w in policies]
    key = jax.random.PRNGKey(3)
    rank = jnp.asarray(rng.permutation(NUM_NODES).astype(np.int32))
    types = build_pod_types(pods)

    flat = make_table_replay(pol, gpu_sel=gpu_sel, block_size=-1)
    r0 = flat(state, pods, types, ev_kind, ev_pod, tp, key, rank)
    for block in blocks:
        blocked = make_table_replay(pol, gpu_sel=gpu_sel, block_size=block)
        r1 = blocked(state, pods, types, ev_kind, ev_pod, tp, key, rank)
        _assert_equal(r0, r1)


@pytest.mark.slow
def test_blocked_matches_flat_openb_prefix():
    """The pinned cross-engine equality contract on real trace data: an
    openb cluster prefix replay must come out bit-identical between the
    flat and blocked layouts (block not dividing N exercises the sentinel
    padding columns)."""
    import os

    from tpusim.io.trace import (
        build_events,
        load_node_csv,
        load_pod_csv,
        nodes_to_state,
        pods_to_specs,
        tiebreak_rank,
    )
    from tpusim.sim.typical import TypicalPodsConfig, get_typical_pods, pad_typical_pods

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    nodes = load_node_csv(
        os.path.join(repo, "data/csv/openb_node_list_gpu_node.csv")
    )
    pods = load_pod_csv(
        os.path.join(repo, "data/csv/openb_pod_list_default.csv")
    )[:250]
    state = nodes_to_state(nodes)
    tp, _ = get_typical_pods(pods, TypicalPodsConfig())
    tp = pad_typical_pods(tp)
    specs = pods_to_specs(pods)
    ev_kind, ev_pod = build_events(pods)
    ev_kind, ev_pod = jnp.asarray(ev_kind), jnp.asarray(ev_pod)
    rank = jnp.asarray(tiebreak_rank(len(nodes), 42))
    key = jax.random.PRNGKey(42)
    types = build_pod_types(specs)
    pol = [(make_policy("FGDScore"), 1000)]

    flat = make_table_replay(pol, gpu_sel="FGDScore", block_size=-1)
    r0 = flat(state, specs, types, ev_kind, ev_pod, tp, key, rank)
    for block in (8, 128, len(nodes)):
        blocked = make_table_replay(pol, gpu_sel="FGDScore", block_size=block)
        r1 = blocked(state, specs, types, ev_kind, ev_pod, tp, key, rank)
        _assert_equal(r0, r1)


def test_blocked_pinned_pods():
    """nodeSelector-pinned pods bypass the block summaries (single
    candidate) and must still match the flat feasibility-mask semantics."""
    rng = np.random.default_rng(13)
    state, tp = random_cluster(rng, num_nodes=16)
    pods = random_pods(rng, num_pods=20)
    pinned = np.full(20, -1, np.int32)
    pinned[3] = 5
    pinned[7] = 2
    pinned[11] = 15
    # unknown nodeSelector name: pods_to_specs pins to index N (out of
    # range) — must FAIL, not land on a clipped node (review round 6)
    pinned[13] = 16
    pods = pods._replace(pinned=jnp.asarray(pinned))
    ev_kind = jnp.zeros(20, jnp.int32)
    ev_pod = jnp.arange(20, dtype=jnp.int32)
    pol = [(make_policy("FGDScore"), 1000)]
    key = jax.random.PRNGKey(1)
    types = build_pod_types(pods)

    flat = make_table_replay(pol, gpu_sel="FGDScore", block_size=-1)
    r0 = flat(state, pods, types, ev_kind, ev_pod, tp, key)
    blocked = make_table_replay(pol, gpu_sel="FGDScore", block_size=4)
    r1 = blocked(state, pods, types, ev_kind, ev_pod, tp, key)
    _assert_equal(r0, r1)
    assert int(np.asarray(r1.placed_node)[13]) == -1  # out-of-range pin fails


def test_resolve_block_size_heuristic():
    """Auto keeps small clusters (openb N=1523) on the flat path, turns on
    ~sqrt(N/K) power-of-two blocks at scale, honors explicit overrides,
    and clamps forced sizes to N."""
    assert resolve_block_size(0, 1523, 151) == 0  # openb stays flat
    assert resolve_block_size(0, BLOCKED_MIN_NODES - 1, 10) == 0
    b = resolve_block_size(0, 100_000, 151)
    assert b > 0 and (b & (b - 1)) == 0  # power of two
    assert 16 <= b <= 1024
    big = resolve_block_size(0, 100_000, 1)
    assert big >= b  # fewer types -> cheaper refresh -> larger blocks
    assert resolve_block_size(64, 100, 151) == 64
    assert resolve_block_size(7, 100, 151) == 7
    assert resolve_block_size(512, 40, 151) == 40  # clamped to N
    assert resolve_block_size(-1, 100_000, 151) == 0  # forced flat


def test_driver_block_size_knob():
    """SimulatorConfig.block_size routes through run_events with results
    (including the metric post-pass) unchanged vs the flat layout."""
    from tpusim.io.trace import NodeRow, PodRow, pods_to_specs
    from tpusim.sim.driver import Simulator, SimulatorConfig

    rng = np.random.default_rng(31)
    nodes = [
        NodeRow(f"n{i}", 32000, 131072, int(g), "V100M16" if g else "")
        for i, g in enumerate(rng.choice([0, 2, 4, 8], 12))
    ]
    pods = [
        PodRow(f"p{i}", int(rng.choice([1000, 4000])), 1024,
               int(rng.choice([0, 1])), 500)
        for i in range(25)
    ]
    results = []
    for bs in (-1, 5):
        sim = Simulator(nodes, SimulatorConfig(
            policies=(("FGDScore", 1000),), gpu_sel_method="FGDScore",
            report_per_event=True, block_size=bs,
        ))
        sim.set_workload_pods(pods)
        sim.set_typical_pods()
        specs = pods_to_specs(pods)
        ev_kind = jnp.zeros(25, jnp.int32)
        ev_pod = jnp.arange(25, dtype=jnp.int32)
        results.append(sim.run_events(
            sim.init_state, specs, ev_kind, ev_pod, jax.random.PRNGKey(2)
        ))
    r0, r1 = results
    _assert_equal(r0, r1)
    for a, b in zip(r0.metrics, r1.metrics):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
