"""Fused whole-replay Pallas engine (tpusim.sim.pallas_engine) vs the
incremental table engine: identical placements, device masks, failure flags
and final state on randomized create/delete mixes.

The CPU lane runs the kernel in Pallas interpreter mode (the Mosaic path
needs real TPU hardware — tests/test_tpu.py pins the on-chip equality on the
full openb trace). Interpreter steps are slow, so traces here are small; the
semantics exercised (share + whole + CPU-only pods, deletions, infeasible
pods, pinned pods, tie-breaking) are the same."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.fixtures import random_cluster, random_pods
from tests.test_table_engine import _assert_equal, _events_with_deletes
from tpusim.policies import make_policy
from tpusim.sim.engine import EV_CREATE
from tpusim.sim.pallas_engine import make_pallas_replay, supports
from tpusim.sim.table_engine import build_pod_types, make_table_replay
from tpusim.types import PodSpec


def _run_both(policy, gpu_sel, state, tp, pods, ev_kind, ev_pod, rank):
    policies = [(make_policy(policy), 1000)]
    key = jax.random.PRNGKey(3)
    types = build_pod_types(pods)
    tab = make_table_replay(policies, gpu_sel=gpu_sel)
    r0 = tab(state, pods, types, ev_kind, ev_pod, tp, key, rank)
    pal = make_pallas_replay(policies, gpu_sel=gpu_sel, interpret=True)
    r1 = pal(state, pods, types, ev_kind, ev_pod, tp, key, rank)
    return r0, r1


@pytest.mark.parametrize(
    "policy,gpu_sel",
    [
        ("FGDScore", "FGDScore"),
        ("BestFitScore", "best"),
        # tier-1 trim, ISSUE 16: these three ride resume-smoke
        pytest.param("GpuPackingScore", "worst", marks=pytest.mark.slow),
        ("GpuClusteringScore", "best"),
        pytest.param("PWRScore", "PWRScore", marks=pytest.mark.slow),
        pytest.param("DotProductScore", "DotProductScore",
                     marks=pytest.mark.slow),
    ],
    ids=lambda p: str(p),
)
def test_pallas_matches_table_engine(policy, gpu_sel):
    rng = np.random.default_rng(11)
    state, tp = random_cluster(rng, num_nodes=24)
    pods = random_pods(rng, num_pods=40)
    ev_kind, ev_pod = _events_with_deletes(40, rng)
    rank = jnp.asarray(rng.permutation(24).astype(np.int32))
    r0, r1 = _run_both(policy, gpu_sel, state, tp, pods, ev_kind, ev_pod, rank)
    _assert_equal(r0, r1)
    assert np.array_equal(np.asarray(r0.event_node), np.asarray(r1.event_node))
    assert np.array_equal(np.asarray(r0.event_dev), np.asarray(r1.event_dev))


# interpreter-mode sweeps are minutes of tier-1 wall for variant coverage
# the core per-policy equality tests already give; the full sweep still
# runs under plain pytest / `make test` and on-chip in the TPU lane
@pytest.mark.slow
@pytest.mark.parametrize("norm", ["max", "node", "pod"])
@pytest.mark.parametrize("dim_ext", ["merge", "share", "divide", "extend"])
def test_pallas_dotprod_dim_ext(dim_ext, norm):
    """Every DotProduct (dim-extension × norm) config has a Pallas column
    (the reference's 4 virtual-expansion modes, resource.go:246-381, and
    3 norm methods, dot_product_score.go:76-83)."""
    from tpusim.policies import make_policy as mk

    rng = np.random.default_rng(31)
    state, tp = random_cluster(rng, num_nodes=16)
    pods = random_pods(rng, num_pods=30)
    ev_kind, ev_pod = _events_with_deletes(30, rng)
    rank = jnp.asarray(rng.permutation(16).astype(np.int32))
    policies = [
        (mk("DotProductScore", dim_ext_method=dim_ext, norm_method=norm), 1000)
    ]
    key = jax.random.PRNGKey(3)
    types = build_pod_types(pods)
    r0 = make_table_replay(policies, gpu_sel="DotProductScore")(
        state, pods, types, ev_kind, ev_pod, tp, key, rank
    )
    r1 = make_pallas_replay(
        policies, gpu_sel="DotProductScore", interpret=True
    )(state, pods, types, ev_kind, ev_pod, tp, key, rank)
    _assert_equal(r0, r1)


@pytest.mark.slow  # see test_pallas_dotprod_dim_ext
@pytest.mark.parametrize(
    "weights", [(500, 500), (100, 900), (50, 950)], ids=lambda w: f"{w[0]}"
)
def test_pallas_weighted_multi_policy(weights):
    """The reference's PWR+FGD weighted mixes (generate_run_scripts.py
    AllMethodList rows 08/11/12) run fused: Σ wᵢ·normalizeᵢ(colᵢ) in i32,
    placements bit-identical to the table engine."""
    rng = np.random.default_rng(47)
    state, tp = random_cluster(rng, num_nodes=24)
    pods = random_pods(rng, num_pods=40)
    ev_kind, ev_pod = _events_with_deletes(40, rng)
    rank = jnp.asarray(rng.permutation(24).astype(np.int32))
    policies = [
        (make_policy("PWRScore"), weights[0]),
        (make_policy("FGDScore"), weights[1]),
    ]
    key = jax.random.PRNGKey(3)
    types = build_pod_types(pods)
    r0 = make_table_replay(policies, gpu_sel="FGDScore")(
        state, pods, types, ev_kind, ev_pod, tp, key, rank
    )
    r1 = make_pallas_replay(policies, gpu_sel="FGDScore", interpret=True)(
        state, pods, types, ev_kind, ev_pod, tp, key, rank
    )
    _assert_equal(r0, r1)
    assert np.array_equal(np.asarray(r0.event_node), np.asarray(r1.event_node))
    assert np.array_equal(np.asarray(r0.event_dev), np.asarray(r1.event_dev))


def test_pallas_fgd_gpu_sel_best():
    """gpuSelMethod=best routes Reserve through the best-fit device pick
    instead of FGD's own (open_gpu_share.go:285-304)."""
    rng = np.random.default_rng(13)
    state, tp = random_cluster(rng, num_nodes=16)
    pods = random_pods(rng, num_pods=30)
    ev_kind, ev_pod = _events_with_deletes(30, rng)
    rank = jnp.asarray(rng.permutation(16).astype(np.int32))
    r0, r1 = _run_both("FGDScore", "best", state, tp, pods, ev_kind, ev_pod, rank)
    _assert_equal(r0, r1)


def test_pallas_pinned_and_infeasible():
    """Pinned pods (snapshot re-bind) bind only to their node; pods no node
    can host are recorded failed — identically to the table engine."""
    rng = np.random.default_rng(17)
    state, tp = random_cluster(rng, num_nodes=12)
    pods = random_pods(rng, num_pods=20)
    # pin pod 0 to node 3; make pod 1 infeasible everywhere
    pods = PodSpec(
        cpu=pods.cpu.at[1].set(2**28),
        mem=pods.mem,
        gpu_milli=pods.gpu_milli,
        gpu_num=pods.gpu_num,
        gpu_mask=pods.gpu_mask,
        pinned=pods.pinned.at[0].set(3),
    )
    ev_kind = jnp.full(20, EV_CREATE, jnp.int32)
    ev_pod = jnp.arange(20, dtype=jnp.int32)
    rank = jnp.asarray(rng.permutation(12).astype(np.int32))
    r0, r1 = _run_both("FGDScore", "FGDScore", state, tp, pods, ev_kind, ev_pod, rank)
    _assert_equal(r0, r1)
    assert bool(np.asarray(r1.ever_failed)[1])


def test_driver_engine_knob():
    """SimulatorConfig.engine routes run_events: forced `pallas` (CPU ->
    interpreter mode) must reproduce forced `table` exactly through the
    full driver path; bad/unsupported knobs raise at construction."""
    from tests.test_batch import _mk_cluster, _mk_pods
    from tpusim.sim.driver import Simulator, SimulatorConfig
    from tpusim.sim.typical import TypicalPodsConfig

    rng = np.random.default_rng(23)
    nodes = _mk_cluster(rng)
    pods = _mk_pods(rng, n=24)

    def run(engine):
        cfg = SimulatorConfig(
            policies=(("FGDScore", 1000),),
            gpu_sel_method="FGDScore",
            shuffle_pod=True,
            seed=42,
            report_per_event=False,
            engine=engine,
            typical_pods=TypicalPodsConfig(pod_popularity_threshold=95),
        )
        sim = Simulator(nodes, cfg)
        sim.set_workload_pods(pods)
        return sim.run()

    r_tab = run("table")
    r_pal = run("pallas")
    assert np.array_equal(r_tab.placed_node, r_pal.placed_node)
    assert np.array_equal(r_tab.dev_mask, r_pal.dev_mask)
    assert [u.pod.name for u in r_tab.unscheduled_pods] == [
        u.pod.name for u in r_pal.unscheduled_pods
    ]

    from tpusim.sim.driver import Simulator as S, SimulatorConfig as C

    with pytest.raises(ValueError, match="unknown engine"):
        S(nodes, C(engine="warp"))
    with pytest.raises(ValueError, match="pallas"):
        S(nodes, C(policies=(("RandomScore", 1000),), gpu_sel_method="random",
                   engine="pallas", report_per_event=False))
    # round 5: the table engine replays per-event-random configs (bit-
    # identical to the oracle), and report mode is no pallas blocker (the
    # shared post-pass reconstructs the series from telemetry)
    S(nodes, C(policies=(("RandomScore", 1000),), gpu_sel_method="random",
               engine="table", report_per_event=False))
    S(nodes, C(engine="pallas", report_per_event=True))


def test_supports_gating():
    fgd = make_policy("FGDScore")
    rand = make_policy("RandomScore")
    bestfit = make_policy("BestFitScore")
    simon = make_policy("Simon")
    assert supports([(fgd, 1000)], "FGDScore")
    assert supports([(fgd, 1000)], "best")
    assert supports([(bestfit, 1000)], "best")
    assert not supports([(fgd, 1000)], "random")
    # weighted mixes run fused since round 5 when every policy has a column
    assert supports([(fgd, 1000), (bestfit, 1)], "best")
    assert supports([(make_policy("PWRScore"), 500), (fgd, 500)], "FGDScore")
    assert not supports([(simon, 1000)], "best")  # no column
    assert not supports([(fgd, 1000), (simon, 1)], "best")  # one lacks a column
    assert not supports([(fgd, 1000)], "PWRScore")
    with pytest.raises(ValueError):
        make_pallas_replay([(rand, 1000)], gpu_sel="best")
